"""Stage-completion ledger + battery runner tests (ISSUE 5 harness).

The contract under test: a tunnel window that dies mid-battery leaves a
ledger (``window_*/done.json``) from which the NEXT window re-fires only
the missing stages — the battery is multi-window and resumable, and the
probe loop around it re-arms until the ledger says complete."""

import importlib.util
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


battery = _load("battery")


def fake_stage(name, tmp, ok=True, extra=""):
    """A stage that appends one line to a per-stage count file (so a test
    can prove how many times it fired) and optionally fails."""
    count = os.path.join(str(tmp), f"count_{name}")
    cmd = f"echo fired >> {count}{extra}" + ("" if ok else "; exit 1")
    return battery.stage(name, 30, None, ["sh", "-c", cmd])


def fired(tmp, name):
    count = os.path.join(str(tmp), f"count_{name}")
    if not os.path.exists(count):
        return 0
    with open(count) as f:
        return len(f.readlines())


def quiet(msg):
    pass


# --- default battery shape (the ordering contract) ---------------------

def test_default_stage_order_and_headline_budget():
    """Four-phase bench JSON must land within the first ~10 minutes of
    the FIRST window (VERDICT r5 item 1): stage 1 is the no-sweep bench
    with a 600 s inner budget; attribution + lever A/B + 1024-readiness
    stages exist and precede the optional sweep."""
    stages = battery.default_stages()
    names = [s["name"] for s in stages]
    assert len(names) == len(set(names))
    assert names[0] == "bench_phases"
    first = stages[0]
    assert first["env"]["GRAFT_BENCH_SWEEP"] == ""      # no sweep up front
    assert float(first["env"]["GRAFT_BENCH_TPU_TIMEOUT"]) <= 600
    assert first["budget_s"] <= 780
    for required in ("components", "ab_levers", "readiness_1024",
                     "graftcomms", "bench_scaling"):
        assert required in names
        assert names.index(required) < names.index("bench_sweep")
    # every {win} placeholder stays inside the window dir (each
    # occurrence is a path component: "{win}/...")
    import re

    for s in stages:
        for a in s["argv"]:
            for m in re.finditer(r"\{win\}", a):
                assert a[m.end():m.end() + 1] == "/", a


def test_graftcomms_stage_captures_tpu_comms_table():
    """ISSUE 6 satellite: the battery records the comms attribution as
    a stage artifact — native backend (TPU-compiled HLO), full trace
    profile, repo-root artifact copied into the window ledger so
    bench.py's expected_scaling finds it on later stages/windows."""
    stages = {s["name"]: s for s in battery.default_stages()}
    st = stages["graftcomms"]
    argv = " ".join(st["argv"])
    assert "gansformer_tpu.analysis.cli" in argv
    assert "--trace-native" in argv and "--trace-profile full" in argv
    assert "--json-out .comms_attribution.json" in argv
    assert (".comms_attribution.json", "comms_attribution.json") \
        in [tuple(c) for c in st["copies"]]
    # capture beats verdict: lint exit 1 (new findings) still completes
    # the stage when the artifact exists — else it re-fires forever
    assert "[ $rc -le 1 ]" in argv
    assert "[ -s .comms_attribution.json ]" in argv
    # ISSUE 7 satellite: the capture is diffed against the checked-in
    # expectation, verdict recorded in the window ledger (not gating)
    assert "scripts/diff_comms.py" in argv
    assert "--json-out {win}/comms_diff.json" in argv


def test_serve_loadtest_stage_banks_slo_artifact():
    """ISSUE 10 satellite: the battery load-tests the generation
    service on TPU — Zipfian mix on the flagship architecture
    (random-init: serving PERFORMANCE needs the model, not trained
    weights), artifact into the window ledger, submit window bounded
    under the stage budget."""
    stages = {s["name"]: s for s in battery.default_stages()}
    st = stages["serve_loadtest"]
    argv = " ".join(st["argv"])
    assert "scripts/loadtest_serve.py" in argv
    assert "--json-out {win}/serve_loadtest.json" in argv
    assert "--init random" in argv and "--preset" in argv
    assert "--duration-s 600" in argv          # + compile headroom
    assert st["budget_s"] >= 600 + 150
    # persistent manifest: only the FIRST window pays flagship
    # compiles; a per-window tempdir would bust the budget every time
    assert "--manifest-dir .serve_manifest" in argv
    names = [s["name"] for s in battery.default_stages()]
    assert names.index("serve_loadtest") < names.index("bench_sweep")


def test_serve_chaos_stage_banks_overload_artifact():
    """ISSUE 13 satellite: the battery runs the overload/chaos drill —
    burst past the admission bound with one injected dispatcher crash —
    and archives {win}/serve_chaos.json (capture beats verdict: the
    script exits 0 whenever the artifact lands; the doctor's serving
    section grades hung tickets / recovery)."""
    stages = {s["name"]: s for s in battery.default_stages()}
    st = stages["serve_chaos"]
    argv = " ".join(st["argv"])
    assert "scripts/loadtest_serve.py" in argv and "--chaos" in argv
    assert "--json-out {win}/serve_chaos.json" in argv
    assert "--queue-depth" in argv and "--crash-at-batch" in argv
    # rides the SAME persistent manifest as the SLO loadtest, so the
    # flagship compiles are paid once across both stages
    assert "--manifest-dir .serve_manifest" in argv
    # the chaos prom must not clobber 6b's {win}/telemetry.prom
    assert "--prom-out {win}/serve_chaos.prom" in argv
    # doctor grades the window (serve_chaos section) without gating
    # completion: the stage exit is the loadtest's rc
    assert "telemetry doctor {win}/" in argv
    assert "--json-out {win}/serve_doctor.json" in argv
    assert "exit $rc" in argv
    names = [s["name"] for s in battery.default_stages()]
    assert names.index("serve_loadtest") < names.index("serve_chaos")
    assert names.index("serve_chaos") < names.index("bench_sweep")


def test_scaling_stage_runs_bench_scaling():
    """ISSUE 7: the battery measures scaling efficiency on real chips —
    bench.py --scaling before the optional sweep, stable artifact copy
    preserved into the window ledger."""
    stages = {s["name"]: s for s in battery.default_stages()}
    st = stages["bench_scaling"]
    assert "--scaling" in st["argv"]
    assert "bench.py" in " ".join(st["argv"])
    assert (".scaling_bench.json", "scaling_bench.json") \
        in [tuple(c) for c in st["copies"]]
    # inner budget leaves probe/shutdown headroom under the stage
    # budget — else an over-budget window re-fires the stage forever
    assert float(st["env"]["GRAFT_SCALING_TIMEOUT"]) <= \
        st["budget_s"] - 150


def test_train_ticks_stage_runs_under_supervisor():
    """ISSUE 12 satellite: every tunnel window that trains also PROVES
    recovery — the train stage runs under gansformer-supervise with one
    injected SIGKILL mid-checkpoint (one-shot via the fault ledger) and
    the doctor's JSON (availability section included) is archived into
    the window."""
    stages = {s["name"]: s for s in battery.default_stages()}
    st = stages["train_ticks"]
    argv = " ".join(st["argv"])
    assert "gansformer_tpu.cli.supervise" in argv
    assert "--run-dir {win}/train_tpu/run" in argv
    assert "--fault sigkill@ckpt_mid_write:step=4000" in argv
    assert "--max-restarts" in argv
    assert "gansformer_tpu.cli.telemetry doctor" in argv
    assert "--json-out {win}/doctor.json" in argv
    # the unattended-stage discipline survives the rewrite: device-time
    # sampler off (a killed trace can wedge the tunnel's claim)
    assert "--device-time-ticks 0" in argv
    # ISSUE 15: the stage trains from a TFRECORD source (converted up
    # front) with one injected transient read error, so every tunnel
    # window also proves the bounded-backoff IO retry path
    assert "gansformer_tpu.cli.prepare_data" in argv
    assert "--to tfrecord" in argv
    assert "--data-source tfrecord" in argv
    assert "--data-path {win}/train_tpu/data" in argv
    assert "--fault raise@data_read_error:n=64" in argv


def test_default_probe_cmd_env_override(monkeypatch):
    monkeypatch.setenv("GRAFT_PROBE_CMD", "true")
    assert battery.default_probe_argv() == ["sh", "-c", "true"]
    assert battery.probe_ok()
    monkeypatch.setenv("GRAFT_PROBE_CMD", "false")
    assert not battery.probe_ok()


# --- ledger resume logic -----------------------------------------------

def test_window_dies_then_only_missing_stages_refire(tmp_path):
    """The acceptance contract: window 1 completes s1, fails s2 (tunnel
    blip; re-probe still OK so s3 runs); window 2 re-fires ONLY s2."""
    out = tmp_path / "probe"
    stages = [fake_stage("s1", tmp_path), fake_stage("s2", tmp_path,
                                                     ok=False),
              fake_stage("s3", tmp_path)]
    r1 = battery.run_battery(str(out), stages, probe_argv=["true"],
                             log=quiet)
    assert r1["ran"] == ["s1", "s3"] and r1["failed"] == ["s2"]
    assert r1["remaining"] == ["s2"] and not r1["complete"]
    assert (fired(tmp_path, "s1"), fired(tmp_path, "s2"),
            fired(tmp_path, "s3")) == (1, 1, 1)
    # ledger on disk: s1/s3 exit 0, s2 nonzero
    wins = battery.window_dirs(str(out))
    assert len(wins) == 1
    done = battery.load_done(wins[0])
    assert done["s1"]["exit"] == 0 and done["s2"]["exit"] == 1
    assert set(battery.completed_stages(str(out))) == {"s1", "s3"}

    # next window: s2 now succeeds; s1/s3 must NOT re-fire
    stages2 = [fake_stage("s1", tmp_path), fake_stage("s2", tmp_path),
               fake_stage("s3", tmp_path)]
    r2 = battery.run_battery(str(out), stages2, probe_argv=["true"],
                             log=quiet)
    assert r2["ran"] == ["s2"] and r2["complete"]
    assert (fired(tmp_path, "s1"), fired(tmp_path, "s2"),
            fired(tmp_path, "s3")) == (1, 2, 1)
    assert len(battery.window_dirs(str(out))) == 2

    # fully complete: a further run opens NO new window, fires nothing
    r3 = battery.run_battery(str(out), stages2, probe_argv=["true"],
                             log=quiet)
    assert r3["complete"] and r3["window"] is None and r3["ran"] == []
    assert fired(tmp_path, "s2") == 2


def test_dead_tunnel_aborts_window_immediately(tmp_path):
    """A failed stage + failed re-probe = the window is dead: remaining
    stages are NOT attempted (their budgets would burn against a wedged
    claim loop) and stay missing for the next window."""
    out = tmp_path / "probe"
    stages = [fake_stage("s1", tmp_path, ok=False),
              fake_stage("s2", tmp_path)]
    r = battery.run_battery(str(out), stages, probe_argv=["false"],
                            log=quiet)
    assert r["aborted"] and r["failed"] == ["s1"] and r["ran"] == []
    assert fired(tmp_path, "s2") == 0          # never attempted
    assert r["remaining"] == ["s1", "s2"]


def test_marker_exists_during_and_not_after(tmp_path):
    out = tmp_path / "probe"
    marker = os.path.join(str(out), battery.MARKER)
    st = battery.stage("s1", 30, None,
                       ["sh", "-c", f"test -f {marker}"])
    r = battery.run_battery(str(out), [st], probe_argv=["true"], log=quiet)
    assert r["complete"]                        # stage saw the marker
    assert not os.path.exists(marker)           # removed on exit


def test_stage_timeout_counts_as_missing(tmp_path):
    out = tmp_path / "probe"
    st = battery.stage("slow", 1, None, ["sleep", "5"])
    r = battery.run_battery(str(out), [st], probe_argv=["true"], log=quiet)
    assert r["failed"] == ["slow"] and not r["complete"]
    done = battery.load_done(battery.window_dirs(str(out))[0])
    assert done["slow"]["exit"] == "timeout"


def test_torn_done_json_is_tolerated(tmp_path):
    out = tmp_path / "probe"
    win = out / "window_20260801T000000Z"
    win.mkdir(parents=True)
    (win / "done.json").write_text('{"s1": {"exit":')   # torn write
    assert battery.load_done(str(win)) == {}
    assert battery.completed_stages(str(out)) == {}
    # and a fresh battery still runs
    r = battery.run_battery(str(out), [fake_stage("s1", tmp_path)],
                            probe_argv=["true"], log=quiet)
    assert r["complete"]


def test_artifact_capture_and_win_substitution(tmp_path):
    out = tmp_path / "probe"
    st = battery.stage("art", 30, "art.json",
                       ["sh", "-c", "echo '{\"ok\": 1}'; "
                                    "echo side > {win}/side.txt"])
    r = battery.run_battery(str(out), [st], probe_argv=["true"], log=quiet)
    win = battery.window_dirs(str(out))[0]
    assert r["complete"]
    assert json.load(open(os.path.join(win, "art.json"))) == {"ok": 1}
    assert open(os.path.join(win, "side.txt")).read().strip() == "side"


# --- CLI + shell loop ---------------------------------------------------

def test_battery_cli_status_exit_codes(tmp_path):
    out = str(tmp_path / "probe")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "battery.py"),
         "status", "--out", out], capture_output=True, text=True)
    assert r.returncode == 3                    # everything remaining
    payload = json.loads(r.stdout)
    assert payload["remaining"][0] == "bench_phases"
    # pre-complete the ledger → status flips to 0
    win = os.path.join(out, "window_20260801T000000Z")
    os.makedirs(win)
    names = [s["name"] for s in battery.default_stages()]
    with open(os.path.join(win, "done.json"), "w") as f:
        json.dump({n: {"exit": 0} for n in names}, f)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "battery.py"),
         "status", "--out", out], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout


def _run_sh(out_dir, env_extra, timeout=60):
    env = {**os.environ, "PROBE_OUT": str(out_dir), "PROBE_INTERVAL": "0",
           **env_extra}
    return subprocess.run(
        ["bash", os.path.join(ROOT, "scripts", "probe_and_bench.sh")],
        env=env, capture_output=True, text=True, timeout=timeout)


def test_probe_loop_sh_gives_up_at_max_probes(tmp_path):
    r = _run_sh(tmp_path, {"GRAFT_PROBE_CMD": "false", "MAX_PROBES": "2"})
    assert r.returncode == 1
    log = open(os.path.join(str(tmp_path), "probe.log")).read()
    assert "probe 2 failed" in log and "MAX_PROBES=2" in log


def test_probe_loop_sh_exits_zero_when_ledger_complete(tmp_path):
    """Probe succeeds → shell calls battery.py run, which consults the
    (pre-completed) ledger and reports complete → loop exits 0 without
    firing anything."""
    win = tmp_path / "window_20260801T000000Z"
    win.mkdir()
    names = [s["name"] for s in battery.default_stages()]
    (win / "done.json").write_text(
        json.dumps({n: {"exit": 0} for n in names}))
    r = _run_sh(tmp_path, {"GRAFT_PROBE_CMD": "true", "MAX_PROBES": "3"})
    assert r.returncode == 0, r.stderr
    log = open(os.path.join(str(tmp_path), "probe.log")).read()
    assert "battery COMPLETE" in log


def test_side_artifact_copies_survive_stage_failure(tmp_path):
    """bench.py writes .bench_phases.json incrementally; a timed-out
    bench stage must still have its partial side artifact copied into
    the window before the next re-fire overwrites the repo-root file."""
    out = tmp_path / "probe"
    src = os.path.join(ROOT, ".bench_phases.json")
    existed = os.path.exists(src)
    backup = open(src).read() if existed else None
    try:
        st = battery.stage(
            "bench_like", 30, None,
            ["sh", "-c", f"echo '{{\"partial\": 1}}' > {src}; exit 1"],
            copies=[(".bench_phases.json", "bench_phases_tpu.json")])
        r = battery.run_battery(str(out), [st], probe_argv=["true"],
                                log=lambda m: None)
        assert r["failed"] == ["bench_like"]
        win = battery.window_dirs(str(out))[0]
        assert json.load(open(os.path.join(
            win, "bench_phases_tpu.json"))) == {"partial": 1}
    finally:
        if existed:
            open(src, "w").write(backup)
        elif os.path.exists(src):
            os.remove(src)


def test_modconv_train_ab_stage_wired():
    """ISSUE 14 satellite: the conv-backend four-program A/B rides the
    battery with zero new plumbing — same script as the attention A/B,
    flipped to the conv field, landing its own window artifact."""
    stages = {s["name"]: s for s in battery.default_stages()}
    st = stages["modconv_train_ab"]
    argv = " ".join(st["argv"])
    assert "bench_pallas_attention.py" in argv
    assert "--train-ab" in argv
    assert "--ab-backend conv" in argv or "--ab-backend', 'conv" in argv \
        or ("--ab-backend" in st["argv"] and "conv" in st["argv"])
    assert st["artifact"] == "modconv_train_ab_tpu.jsonl"
