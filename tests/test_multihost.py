"""Multi-host path exercised for REAL: 2 coordinator-connected CPU
processes, 4 virtual devices each (VERDICT r2 item 6 — previously
``jax.distributed.initialize`` / ``local_batch_size`` /
``make_array_from_process_local_data`` / run-id broadcast were dead code).
The child runs a 4×2 data×model mesh with sequence parallelism ON, so the
multi-host exercise also covers the grid-axis SP collectives (SURVEY.md
§2.4 SP row) across the process boundary.
"""

import json
import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_children(tmp_path):
    """Run the 2-process child pair to completion; returns on success.

    Bounded retries for gloo's clique-formation DEADLINE_EXCEEDED: the
    clique's key-value exchange carries a hard 30 s deadline inside XLA,
    and the 8 virtual ranks timeshare one physical core — under external
    host load the ranks' pre-collective execution skew alone can exceed
    30 s, regardless of the child's AOT compiles and pre-dispatch KV
    barrier (which remove the compile/trace component of the skew).  The
    retries are gated on that exact signature so a real failure —
    assertion, crash, lockstep divergence — still fails immediately; on
    an otherwise-idle host the first attempt passes (verified r5).
    """
    from gansformer_tpu.utils.hostenv import sanitized_cpu_env

    child = os.path.join(os.path.dirname(__file__), "multihost_child.py")
    env = sanitized_cpu_env(4)     # 4 virtual CPU devices per process
    # cross-process CPU collectives ride gloo (the CPU stand-in for ICI)
    env.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    for attempt in (0, 1, 2):
        port = _free_port()
        # Fresh out-dir per attempt: a retry after a mid-run infra failure
        # must not inherit attempt 0's stats/checkpoints (stale artifacts
        # could satisfy the callers' assertions).
        out_dir = tmp_path / f"a{attempt}"
        out_dir.mkdir(exist_ok=True)
        procs = [
            subprocess.Popen(
                [sys.executable, child, str(port), str(pid), str(out_dir)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, cwd=os.path.dirname(os.path.dirname(child)))
            for pid in (0, 1)]
        try:
            outs = [p.communicate(timeout=1500) for p in procs]
        except subprocess.TimeoutExpired:
            for p in procs:      # never leak gloo-connected children
                p.kill()
            raise
        rcs = [p.returncode for p in procs]
        if all(rc == 0 for rc in rcs):
            return out_dir
        # Two infra signatures, both gloo-transport-level: the clique
        # rendezvous 30s deadline (host-load skew) and the TCP pair's
        # preamble-size abort ("enforce fail at external/gloo ...
        # op.preamble.length <= op.nbytes") — a jaxlib-internal race
        # where concurrent collectives interleave on the shared pair.
        # Neither says anything about the program under test.
        infra = any(("DEADLINE_EXCEEDED" in err and "gloo" in (out + err))
                    or "enforce fail at external/gloo" in err
                    for out, err in outs)
        if attempt < 2 and infra:
            print("gloo transport infra failure (rendezvous deadline or "
                  "pair preamble race); retrying the child pair",
                  file=sys.stderr)
            continue
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"child failed:\n{out}\n{err[-3000:]}"


@pytest.mark.slow  # two spawned processes each running a full tick loop
def test_two_process_sharded_train_step(tmp_path):
    out_dir = _spawn_children(tmp_path)

    results = []
    for pid in (0, 1):
        with open(out_dir / f"p{pid}.json") as f:
            results.append(json.load(f))
    r0, r1 = results
    assert r0["lbs"] == r1["lbs"] == 8          # 16 global / 2 processes
    assert r0["rid"] == r1["rid"] == 42         # broadcast reached p1
    assert r0["cks"] == pytest.approx(r1["cks"], rel=1e-6)  # same update
    assert r0["loss_d"] == pytest.approx(r1["loss_d"], rel=1e-5)

    # full tick loop (VERDICT r3 item 3): params stayed in lockstep over
    # 2 ticks incl. the checkpoint barrier and image snapshot...
    assert r0["loop_cks"] == pytest.approx(r1["loop_cks"], rel=1e-6)
    run_files = set(r0["run_dir_files"])
    assert "checkpoints" in run_files
    assert any(fn.startswith("fakes") and fn.endswith(".png")
               for fn in run_files), run_files
    assert "stats.jsonl" in run_files
    # ...and the sharded metric sweep produced IDENTICAL values on both
    # processes (each host swept a disjoint real shard; features merged
    # globally).
    assert set(r0["metrics"]) == set(r1["metrics"])
    assert any(k.startswith("fid32") for k in r0["metrics"])
    assert any(k.startswith("ppl32") for k in r0["metrics"])
    for k, v in r0["metrics"].items():
        assert v == pytest.approx(r1["metrics"][k], rel=1e-4), k

    # ---- fleet aggregation over the REAL two-process run dir (ISSUE 16):
    # both children beat into the shared dir, so the roll-up must see a
    # complete roster, agree with check_heartbeats on the step skew (the
    # aggregator calls it, so disagreement means the wiring rotted), and
    # export a fleet.prom that passes its own schema lints.
    from gansformer_tpu.analysis.telemetry_schema import (
        check_fleet_metric_families, check_prom)
    from gansformer_tpu.obs.aggregate import aggregate_fleet, write_fleet
    from gansformer_tpu.obs.heartbeat import check_heartbeats

    run_dir = str(out_dir / "run")
    fleet = aggregate_fleet(run_dir, expected=2)
    assert fleet["reporting"] == [0, 1]
    assert not fleet["partial"], fleet["partial_reasons"]
    hb = check_heartbeats(run_dir, max_age_s=1e18, expected=[0, 1])
    assert fleet["step_skew"] == hb["step_skew"]
    assert fleet["steps"] == {str(k): v for k, v in hb["steps"].items()}
    # single-writer layout: process 0 owns telemetry.prom, and its
    # counters survive the merge
    assert fleet["prom_reporting"] == [0]
    assert fleet["counters"], "no counters merged from telemetry.prom"
    fleet_json, fleet_prom = write_fleet(fleet, str(out_dir / "fleet"))
    assert check_prom(fleet_prom) == []
    assert check_fleet_metric_families(fleet_prom) == []
