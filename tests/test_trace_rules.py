"""Fixture tests for the jaxpr-level trace rules (ISSUE 4): every rule
family has a FIRES case (seeded defect), a QUIET case (correct code),
and suppression + baseline handling over the same fixtures — mirroring
tests/test_analysis_rules.py for the AST half.

The fixture functions live in THIS file so findings anchor on real
source lines here (trace findings carry file:line like AST findings;
inline ``# graftlint: disable=`` on the anchored line suppresses)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gansformer_tpu.analysis.baseline import Baseline
from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, def_site, line_text)
from gansformer_tpu.analysis.trace.const_bloat import ConstBloatRule
from gansformer_tpu.analysis.trace.dtype_flow import DtypePromotionRule
from gansformer_tpu.analysis.trace.retrace import (
    RetraceHazardRule, scalar_flavor_variant)
from gansformer_tpu.analysis.trace.sharding_audit import ShardingAuditRule

VEC = jax.ShapeDtypeStruct((4,), np.float32)


def ep_for(fn, *abstract_args, jit_kwargs=None, **fields):
    jitted = jax.jit(fn, **(jit_kwargs or {}))
    path, line = def_site(jitted)
    return EntryPoint(name=f"fixture.{fn.__name__}", fn=jitted,
                      abstract_args=abstract_args, path=path, line=line,
                      **fields)


def run_one(rule_cls, ep):
    ctx = TraceContext()
    rule_cls().check(ep, ctx)
    return ctx.findings


def roundtrip_baseline(rule_cls, make_ep, tmp_path):
    """fires → write baseline → fresh run is baselined, not new.
    ``make_ep`` builds a FRESH entry point per run (the retrace probe
    leaves its variants in the jit cache — a reused fn can't re-fire)."""
    findings = run_one(rule_cls, make_ep())
    assert findings

    def text_of(f):
        return line_text(f.path, f.line)

    bl = str(tmp_path / "baseline.json")
    Baseline.write(bl, findings, text_of)
    fresh = run_one(rule_cls, make_ep())
    Baseline.load(bl).apply(fresh, text_of)
    assert all(f.baselined and not f.new for f in fresh)


# --- jaxpr-const-bloat ------------------------------------------------------

_BIG = np.zeros((220, 220), np.float32)        # ~189 KiB > 64 KiB threshold


def _const_leaker(x):
    return x + jnp.asarray(_BIG).sum()


def _const_leaker_suppressed(x):  # graftlint: disable=jaxpr-const-bloat — fixture: suppression contract
    return x + jnp.asarray(_BIG).sum()


def _const_small(x):
    return x + jnp.asarray(np.ones((8,), np.float32)).sum()


def test_const_bloat_fires():
    findings = run_one(ConstBloatRule, ep_for(_const_leaker, VEC))
    assert len(findings) == 1 and findings[0].new
    assert "KiB" in findings[0].message
    assert findings[0].path.endswith("test_trace_rules.py")


def test_const_bloat_quiet():
    assert run_one(ConstBloatRule, ep_for(_const_small, VEC)) == []


def test_const_bloat_suppressed():
    findings = run_one(ConstBloatRule,
                       ep_for(_const_leaker_suppressed, VEC))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_const_bloat_baselined(tmp_path):
    roundtrip_baseline(ConstBloatRule,
                       lambda: ep_for(_const_leaker, VEC), tmp_path)


# --- dtype-promotion --------------------------------------------------------

BVEC = jax.ShapeDtypeStruct((4,), jnp.bfloat16)


def _promotes_bf16(x):
    return x + jnp.arange(4.0)


def _promotes_bf16_suppressed(x):
    return x + jnp.arange(4.0)  # graftlint: disable=dtype-promotion — fixture: suppression contract
    # (the comment sits on the PROMOTING line — dtype findings anchor
    # there, not on the def)


def _explicit_upcast(x):
    return x.astype(jnp.float32) + jnp.arange(4.0)


def test_dtype_promotion_fires_on_silent_bf16_upcast():
    findings = run_one(DtypePromotionRule,
                       ep_for(_promotes_bf16, BVEC,
                              compute_dtype="bfloat16"))
    assert len(findings) == 1 and findings[0].new
    assert "bfloat16" in findings[0].message
    # anchored on the promoting line, not the def line
    assert "jnp.arange(4.0)" in line_text(findings[0].path,
                                          findings[0].line)


def test_dtype_promotion_quiet_when_cast_is_written():
    findings = run_one(DtypePromotionRule,
                       ep_for(_explicit_upcast, BVEC,
                              compute_dtype="bfloat16"))
    assert findings == []


def test_dtype_promotion_quiet_on_f32_model():
    # in an all-f32 model only →f64 would be a leak; bf16→f32 can't occur
    findings = run_one(DtypePromotionRule,
                       ep_for(_promotes_bf16, VEC,
                              compute_dtype="float32"))
    assert findings == []


def test_dtype_promotion_suppressed():
    findings = run_one(DtypePromotionRule,
                       ep_for(_promotes_bf16_suppressed, BVEC,
                              compute_dtype="bfloat16"))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_dtype_promotion_baselined(tmp_path):
    roundtrip_baseline(
        DtypePromotionRule,
        lambda: ep_for(_promotes_bf16, BVEC, compute_dtype="bfloat16"),
        tmp_path)


# --- retrace-hazard ---------------------------------------------------------

def _scalar_lr_step(lr, x):
    return x * lr


def _scalar_lr_step_suppressed(lr, x):  # graftlint: disable=retrace-hazard — fixture: suppression contract
    return x * lr


def _arrays_only(x):
    return x * 2.0


def _fresh_clone(fn):
    """A new function object with fn's code — jax.jit keys its tracing
    cache on the function object, so re-jitting the SAME fn reuses
    cache entries from earlier probes; each probe needs its own."""
    import functools
    import types

    clone = types.FunctionType(fn.__code__, fn.__globals__, fn.__name__,
                               fn.__defaults__, fn.__closure__)
    return functools.wraps(fn)(clone)


def _lr_ep(fn):
    # the seeded regression (ISSUE 4 acceptance): a python float from
    # enclosing state reaches the jit boundary as a traced argument —
    # the next caller passing np.float32 (same value!) pays a recompile
    lr = 0.5
    return ep_for(_fresh_clone(fn), jax.ShapeDtypeStruct((), np.float32),
                  VEC, make_args=lambda: (lr, np.ones((4,), np.float32)))


def test_retrace_catches_seeded_python_float_regression():
    findings = run_one(RetraceHazardRule, _lr_ep(_scalar_lr_step))
    assert len(findings) == 1 and findings[0].new
    assert "scalar-flavor" in findings[0].message


def test_retrace_quiet_on_array_only_signature():
    ep = ep_for(_arrays_only, VEC,
                make_args=lambda: (np.ones((4,), np.float32),))
    assert run_one(RetraceHazardRule, ep) == []


def test_retrace_suppressed():
    findings = run_one(RetraceHazardRule,
                       _lr_ep(_scalar_lr_step_suppressed))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_retrace_baselined(tmp_path):
    roundtrip_baseline(RetraceHazardRule,
                       lambda: _lr_ep(_scalar_lr_step), tmp_path)


def test_scalar_flavor_variant_builder():
    args = (0.5, 3, np.ones((2,), np.float32), None)
    flipped = scalar_flavor_variant(args)
    assert isinstance(flipped[0], np.float32)
    assert isinstance(flipped[1], np.int32)
    assert flipped[2] is args[2] and flipped[3] is None
    assert scalar_flavor_variant((np.ones((2,)),)) is None   # no scalars


# --- sharding-audit ---------------------------------------------------------

def _batch_sharding():
    from gansformer_tpu.core.config import MeshConfig
    from gansformer_tpu.parallel.mesh import make_mesh

    return make_mesh(MeshConfig(data=2, model=1),
                     devices=jax.devices()[:2]).batch()


MAT = jax.ShapeDtypeStruct((8, 4), np.float32)
# crosses the 8 MiB replicated-parameter threshold (2100*1024*4 ≈ 8.2 MiB)
GIANT = jax.ShapeDtypeStruct((2100, 1024), np.float32)

_BATCH_SH = None


def _resharding_donor(s):
    return jax.lax.with_sharding_constraint(s + 1.0, _BATCH_SH)


def _resharding_donor_suppressed(s):  # graftlint: disable=sharding-audit — fixture: suppression contract
    return jax.lax.with_sharding_constraint(s + 1.0, _BATCH_SH)


def _stable_donor(s):
    return s + 1.0


def _giant_reader(p):
    return p.sum()


@pytest.fixture(autouse=True)
def _bind_batch_sharding():
    global _BATCH_SH
    if _BATCH_SH is None and len(jax.devices()) >= 2:
        _BATCH_SH = _batch_sharding()
    yield


def _donor_ep(fn):
    return ep_for(fn, MAT, jit_kwargs={"donate_argnums": (0,)},
                  donate_argnums=(0,), arg_specs=("repl",))


def test_sharding_audit_fires_on_donation_resharding():
    findings = run_one(ShardingAuditRule, _donor_ep(_resharding_donor))
    assert len(findings) == 1 and findings[0].new
    assert "defeating donation" in findings[0].message


def test_sharding_audit_fires_on_oversize_replicated_param():
    ep = ep_for(_giant_reader, GIANT, arg_specs=("repl",))
    findings = run_one(ShardingAuditRule, ep)
    assert len(findings) == 1 and findings[0].new
    assert "fully replicated" in findings[0].message


def test_sharding_audit_quiet_on_stable_donation():
    assert run_one(ShardingAuditRule, _donor_ep(_stable_donor)) == []


def test_sharding_audit_quiet_on_batch_sharded_input():
    ep = ep_for(_giant_reader, GIANT, arg_specs=("batch",))
    assert run_one(ShardingAuditRule, ep) == []


def test_sharding_audit_suppressed():
    findings = run_one(ShardingAuditRule,
                       _donor_ep(_resharding_donor_suppressed))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_sharding_audit_baselined(tmp_path):
    roundtrip_baseline(ShardingAuditRule,
                       lambda: _donor_ep(_resharding_donor), tmp_path)
