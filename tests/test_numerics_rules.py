"""Fixture + acceptance tests for graftnum, the numerics half of
graftlint (ISSUE 19): every rule family has a FIRES case (seeded
defect), a QUIET case (correct code), and suppression + baseline
handling over the same fixtures — mirroring tests/test_trace_rules.py
for the other trace rules and tests/test_analysis_rules.py for the AST
half.  Plus the contract-resolution units, the machine-epsilon pin
against jnp.finfo, the whole-repo AST clean gate, and the headline
acceptance check: the tiny-bf16 step programs really do compute every
declared fp32 island (and the optimizer moments) in float32.

The jaxpr fixture functions live in THIS file so findings anchor on
real source lines here (inline ``# graftlint: disable=`` on the
anchored line suppresses); the eps-dtype fixtures are source STRINGS
fed to ``lint_source`` so the whole-repo AST gate below doesn't trip
over its own seeded defects.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gansformer_tpu.analysis.engine import lint_paths, lint_source
from gansformer_tpu.analysis.numerics.contracts import (
    ISLANDS, NUMERIC_CONTRACTS, Island, NumericContract,
    numeric_contract_for)
from gansformer_tpu.analysis.numerics.dtypes import (
    ACCUM_THRESHOLD, MACHINE_EPS)
from gansformer_tpu.analysis.numerics.eps_dtype import EpsDtypeMismatchRule
from gansformer_tpu.analysis.numerics.island_contract import (
    Fp32IslandContractRule)
from gansformer_tpu.analysis.numerics.reduction_accum import (
    ReductionAccumulationRule)
from gansformer_tpu.analysis.numerics.unstable_primitive import (
    UnstablePrimitiveRule)
from gansformer_tpu.analysis.trace.base import TraceContext, line_text
from tests.test_trace_rules import BVEC, VEC, ep_for, roundtrip_baseline, \
    run_one

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# past the accumulation threshold with room to spare
BVEC8K = jax.ShapeDtypeStruct((2 * ACCUM_THRESHOLD,), jnp.bfloat16)

JAXPR_RULES = (Fp32IslandContractRule, ReductionAccumulationRule,
               UnstablePrimitiveRule)


def _others_quiet(fired_rule, ep):
    """A seeded defect must fire exactly its own rule."""
    for cls in JAXPR_RULES:
        if cls is not fired_rule:
            assert run_one(cls, ep) == [], cls.id


# --- reduction-accumulation -------------------------------------------------
#
# jnp.sum on a narrow operand already inserts an f32 accumulator
# (convert → reduce_sum f32 → convert back) — exactly the fix the rule
# asks for.  Seeding the defect therefore needs lax.reduce, which
# lowers to a genuine narrow-in/narrow-out reduce_sum.

_BF0 = jnp.zeros((), jnp.bfloat16)


def _accum_narrow(x):
    return jax.lax.reduce(x, _BF0, jax.lax.add, (0,))


def _accum_narrow_suppressed(x):
    return jax.lax.reduce(x, _BF0, jax.lax.add, (0,))  # graftlint: disable=reduction-accumulation — fixture: suppression contract


def _accum_wide_out(x):
    return jnp.sum(x, dtype=jnp.float32)


def _accum_dot(x, y):
    return jnp.dot(x, y)


def test_reduction_accum_fires_on_large_narrow_sum():
    findings = run_one(ReductionAccumulationRule,
                       ep_for(_accum_narrow, BVEC8K))
    assert len(findings) == 1 and findings[0].new
    assert "reduce_sum" in findings[0].message
    assert "bfloat16 accumulator" in findings[0].message
    assert "lax.reduce(x" in line_text(findings[0].path, findings[0].line)
    _others_quiet(ReductionAccumulationRule, ep_for(_accum_narrow, BVEC8K))


def test_reduction_accum_fires_on_narrow_dot_general():
    findings = run_one(ReductionAccumulationRule,
                       ep_for(_accum_dot, BVEC8K, BVEC8K))
    assert len(findings) == 1
    assert "dot_general" in findings[0].message


def test_reduction_accum_quiet_with_explicit_accumulator():
    assert run_one(ReductionAccumulationRule,
                   ep_for(_accum_wide_out, BVEC8K)) == []


def test_reduction_accum_quiet_below_threshold():
    # the same formulation over 4 elements is fine — bf16 noise there
    # is below anything the training signal can see
    assert run_one(ReductionAccumulationRule,
                   ep_for(_accum_narrow, BVEC)) == []


def test_reduction_accum_suppressed():
    findings = run_one(ReductionAccumulationRule,
                       ep_for(_accum_narrow_suppressed, BVEC8K))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_reduction_accum_baselined(tmp_path):
    roundtrip_baseline(ReductionAccumulationRule,
                       lambda: ep_for(_accum_narrow, BVEC8K), tmp_path)


# --- unstable-primitive -----------------------------------------------------

def _rsqrt_raw(x):
    return jax.lax.rsqrt(x)


def _rsqrt_raw_suppressed(x):
    return jax.lax.rsqrt(x)  # graftlint: disable=unstable-primitive — fixture: suppression contract


def _rsqrt_guarded(x):
    return jax.lax.rsqrt(jnp.square(x).sum() + 1e-6)


def _exp_raw(x):
    return jnp.exp(x)


def _exp_shifted(x):
    return jnp.exp(x - x.max())


def _softmax_library(x):
    return jax.nn.softmax(x)


def _div_raw(x, d):
    return x / d


def _div_guarded(x, d):
    return x / (jnp.square(d).sum() + 1e-6)


def test_unstable_primitive_fires_on_unguarded_rsqrt():
    ep = ep_for(_rsqrt_raw, VEC)
    findings = run_one(UnstablePrimitiveRule, ep)
    assert len(findings) == 1 and findings[0].new
    assert "rsqrt" in findings[0].message
    _others_quiet(UnstablePrimitiveRule, ep_for(_rsqrt_raw, VEC))


def test_unstable_primitive_fires_on_unshifted_exp():
    findings = run_one(UnstablePrimitiveRule, ep_for(_exp_raw, VEC))
    assert len(findings) == 1 and "exp" in findings[0].message


def test_unstable_primitive_fires_on_unguarded_div():
    findings = run_one(UnstablePrimitiveRule, ep_for(_div_raw, VEC, VEC))
    assert len(findings) == 1 and "div" in findings[0].message


def test_unstable_primitive_quiet_on_guarded_forms():
    assert run_one(UnstablePrimitiveRule, ep_for(_rsqrt_guarded, VEC)) == []
    assert run_one(UnstablePrimitiveRule, ep_for(_exp_shifted, VEC)) == []
    assert run_one(UnstablePrimitiveRule,
                   ep_for(_div_guarded, VEC, VEC)) == []
    # the library softmax carries its own max-subtraction + exp-floored
    # denominator — the positivity/domination proofs see through it
    assert run_one(UnstablePrimitiveRule, ep_for(_softmax_library, VEC)) == []


def test_unstable_primitive_suppressed():
    findings = run_one(UnstablePrimitiveRule,
                       ep_for(_rsqrt_raw_suppressed, VEC))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_unstable_primitive_baselined(tmp_path):
    roundtrip_baseline(UnstablePrimitiveRule,
                       lambda: ep_for(_rsqrt_raw, VEC), tmp_path)


# --- fp32-island-contract ---------------------------------------------------

FIXTURE_ISLAND = Island(
    name="fixture-island",
    anchors=(("tests/test_numerics_rules.py", None),),
    primitives=frozenset({"reduce_sum"}),
    rationale="fixture reduction")


def _island_bad(x):
    xb = x.astype(jnp.bfloat16)
    return jax.lax.reduce(xb, _BF0, jax.lax.add, (0,))


def _island_bad_suppressed(x):
    xb = x.astype(jnp.bfloat16)
    return jax.lax.reduce(xb, _BF0, jax.lax.add, (0,))  # graftlint: disable=fp32-island-contract — fixture: suppression contract


def _island_good(x):
    return jnp.sum(x.astype(jnp.float32))


def _island_absent(x):
    return x * 2.0


def _moments_fn(state):
    return state["g_opt"]["mu"].sum() + state["d_opt"]["nu"].sum()


def _island_ep(monkeypatch, fn, *args, islands=("fixture-island",),
               opt_moments=False):
    """Declare a contract for a fixture entry: NUMERIC_CONTRACTS is
    keyed by short name, and short_entry_name("fixture._f") == "_f"."""
    monkeypatch.setitem(ISLANDS, "fixture-island", FIXTURE_ISLAND)
    monkeypatch.setitem(NUMERIC_CONTRACTS, fn.__name__,
                        NumericContract(islands=tuple(islands),
                                        opt_moments=opt_moments))
    return ep_for(fn, *args)


def test_island_contract_fires_on_narrow_island(monkeypatch):
    findings = run_one(Fp32IslandContractRule,
                       _island_ep(monkeypatch, _island_bad, VEC))
    assert len(findings) == 1 and findings[0].new
    assert "fixture-island island: reduce_sum computes on bfloat16" \
        in findings[0].message
    assert "lax.reduce(xb" in line_text(findings[0].path, findings[0].line)


def test_island_contract_quiet_and_audited_on_fp32_island(monkeypatch):
    ep = _island_ep(monkeypatch, _island_good, VEC)
    ctx = TraceContext()
    Fp32IslandContractRule().check(ep, ctx)
    assert ctx.findings == []
    (rec,) = ctx.numerics
    isl = rec["islands"]["fixture-island"]
    assert isl["ok"] and isl["violations"] == 0
    assert isl["dtypes"] == ["float32"]


def test_island_contract_fires_when_required_island_missing(monkeypatch):
    findings = run_one(Fp32IslandContractRule,
                       _island_ep(monkeypatch, _island_absent, VEC))
    assert len(findings) == 1 and findings[0].new
    assert "matched no equation" in findings[0].message


def test_island_contract_notes_undeclared_entries():
    # no contract (plain fixture): a note, not a finding — the rule
    # only audits declared intent
    ctx = TraceContext()
    Fp32IslandContractRule().check(ep_for(_island_absent, VEC), ctx)
    assert ctx.findings == [] and ctx.numerics == []
    assert any("no numeric contract" in n for n in ctx.notes)


def test_island_contract_suppressed(monkeypatch):
    findings = run_one(Fp32IslandContractRule,
                       _island_ep(monkeypatch, _island_bad_suppressed, VEC))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_island_contract_baselined(monkeypatch, tmp_path):
    roundtrip_baseline(
        Fp32IslandContractRule,
        lambda: _island_ep(monkeypatch, _island_bad, VEC), tmp_path)


def test_island_contract_flags_narrow_optimizer_moments(monkeypatch):
    state = {"g_opt": {"mu": BVEC}, "d_opt": {"nu": VEC}}
    ep = _island_ep(monkeypatch, _moments_fn, state,
                    islands=(), opt_moments=True)
    ctx = TraceContext()
    Fp32IslandContractRule().check(ep, ctx)
    assert len(ctx.findings) == 1
    assert "optimizer moment" in ctx.findings[0].message
    assert "bfloat16" in ctx.findings[0].message
    rec = ctx.numerics[0]["islands"]["optimizer-moments"]
    assert not rec["ok"] and rec["violations"] == 1


def test_island_contract_moments_quiet_at_fp32(monkeypatch):
    state = {"g_opt": {"mu": VEC}, "d_opt": {"nu": VEC}}
    ep = _island_ep(monkeypatch, _moments_fn, state,
                    islands=(), opt_moments=True)
    ctx = TraceContext()
    Fp32IslandContractRule().check(ep, ctx)
    assert ctx.findings == []
    rec = ctx.numerics[0]["islands"]["optimizer-moments"]
    assert rec["ok"] and rec["dtypes"] == ["float32"]


# --- eps-dtype-mismatch (AST half) ------------------------------------------

def _eps_findings(src):
    return lint_source(src, path="fixture.py",
                       rules=[EpsDtypeMismatchRule])


def test_eps_dtype_fires_on_sub_epsilon_bf16_guard():
    findings = _eps_findings(
        "def f(x, eps=1e-8):\n"
        "    xb = x.astype(jnp.bfloat16)\n"
        "    return jax.lax.rsqrt(xb + eps)\n")
    assert len(findings) == 1 and findings[0].new
    assert "1e-08" in findings[0].message
    assert "bfloat16" in findings[0].message
    assert findings[0].line == 3


def test_eps_dtype_fires_on_maximum_clamp_and_inline_literal():
    findings = _eps_findings(
        "def f(x):\n"
        "    xb = x.astype('bfloat16')\n"
        "    return x / jnp.maximum(xb, 1e-9)\n")
    assert len(findings) == 1 and "1e-09" in findings[0].message


def test_eps_dtype_uses_per_dtype_thresholds():
    # 5e-4 sits below float16's epsilon (2^-10) but above bfloat16's
    # would-be threshold only if it were wide — the fired class names
    # the dtype so the fix is obvious
    findings = _eps_findings(
        "def f(x, eps=5e-4):\n"
        "    xh = x.astype(jnp.float16)\n"
        "    return jnp.log(xh + eps)\n")
    assert len(findings) == 1 and "float16" in findings[0].message


def test_eps_dtype_quiet_on_fp32_island_and_representable_eps():
    # the _instance_norm idiom: cast to fp32 FIRST, then guard
    assert _eps_findings(
        "def f(x, eps=1e-8):\n"
        "    x32 = x.astype(jnp.float32)\n"
        "    return jax.lax.rsqrt(x32 + eps)\n") == []
    # an eps bfloat16 can actually resolve is fine where it is
    assert _eps_findings(
        "def f(x, eps=1e-2):\n"
        "    xb = x.astype(jnp.bfloat16)\n"
        "    return jax.lax.rsqrt(xb + eps)\n") == []
    # unresolved operands prove nothing — the jaxpr half owns ambient
    # dtype truth
    assert _eps_findings(
        "def f(x, eps=1e-8):\n"
        "    return jax.lax.rsqrt(x + eps)\n") == []


def test_eps_dtype_suppressed_inline():
    findings = _eps_findings(
        "def f(x, eps=1e-8):\n"
        "    xb = x.astype(jnp.bfloat16)\n"
        "    return xb + eps  # graftlint: disable=eps-dtype-mismatch — fixture\n")
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_machine_eps_matches_jnp_finfo():
    # dtypes.py promises its jax-free table equals jnp.finfo — pin it
    for name, eps in MACHINE_EPS.items():
        assert eps == float(jnp.finfo(name).eps), name


# --- contract resolution ----------------------------------------------------

def test_numeric_contracts_cover_entry_catalog():
    from gansformer_tpu.parallel.contracts import ENTRY_CONTRACTS

    assert set(NUMERIC_CONTRACTS) == set(ENTRY_CONTRACTS)


def test_contract_islands_all_declared():
    for name, contract in NUMERIC_CONTRACTS.items():
        for isl in contract.islands:
            assert isl in ISLANDS, (name, isl)


def test_numeric_contract_resolution():
    c = numeric_contract_for("steps.d_step[tiny-f32]")
    assert c is not None and c.opt_moments
    assert set(c.islands) == {"instance-norm", "attention-lse",
                              "demodulation", "loss-reductions"}
    synth = numeric_contract_for("steps.sample[tiny-bf16]")
    assert synth is not None and not synth.opt_moments
    assert "loss-reductions" not in synth.islands
    assert numeric_contract_for("serve.serve_map_seeds[tiny-f32]").islands \
        == ()
    assert numeric_contract_for("fixture._nope") is None


def test_entry_points_refuse_undeclared_numeric_contract(monkeypatch):
    from gansformer_tpu.analysis.trace.entry_points import build_entry_points

    monkeypatch.delitem(NUMERIC_CONTRACTS, "sample")
    with pytest.raises(ValueError, match="no numeric contract"):
        build_entry_points("tiny-f32", include=["sample"])


# --- whole-repo gates -------------------------------------------------------

def test_eps_dtype_clean_over_repo():
    """The AST half over everything the pre-commit hook lints, plus
    tests/ — clean with NO baseline (the repo ships an empty one)."""
    findings = lint_paths(
        [os.path.join(ROOT, "gansformer_tpu"),
         os.path.join(ROOT, "scripts"),
         os.path.join(ROOT, "tests")],
        rules=[EpsDtypeMismatchRule])
    new = [f for f in findings if f.new]
    assert new == [], "\n".join(
        f"{f.location}: {f.message}" for f in new)


def test_tiny_bf16_islands_compute_fp32():
    """The headline ISSUE 19 acceptance: in the compiled (traced)
    tiny-bf16 training programs every declared fp32 island —
    instance-norm statistics, attention lse, demodulation, the loss
    reductions — and the optimizer moments compute in float32, with no
    new numerics findings of any family and an EMPTY baseline."""
    from gansformer_tpu.analysis.trace.entry_points import build_entry_points

    entries = build_entry_points("tiny-bf16",
                                 include=["d_step_r1", "g_step_pl"])
    assert len(entries) == 2
    ctx = TraceContext()
    rules = [cls() for cls in JAXPR_RULES]
    for ep in entries:
        for rule in rules:
            rule.check(ep, ctx)
    new = [f for f in ctx.findings if f.new]
    assert new == [], "\n".join(
        f"{f.rule} {f.location}: {f.message}" for f in new)
    assert len(ctx.numerics) == 2
    for rec in ctx.numerics:
        assert rec["compute_dtype"] == "bfloat16"
        assert set(rec["islands"]) == {
            "instance-norm", "attention-lse", "demodulation",
            "loss-reductions", "optimizer-moments"}
        for name, isl in rec["islands"].items():
            assert isl["ok"], (rec["entry"], name, isl)
            assert set(isl["dtypes"]) <= {"float32"}, \
                (rec["entry"], name, isl)
