"""Parity of the XLA op layer vs the numpy oracles + differentiability.

Covers SURVEY.md §4's implied obligations: ref-vs-fast parity (the reference's
inline `'ref'` switch pattern), gradient checks, and the second-order
gradients R1/path-length regularization relies on (SURVEY.md §7.3 item 1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:  # public jax.test_util was removed in jax 0.9; fall back to private
    from jax import test_util as jtu  # type: ignore
    jtu.check_grads
except (ImportError, AttributeError):
    from jax._src import test_util as jtu

from gansformer_tpu import ops
from tests import reference_ops as refs


# ---------------------------------------------------------------- upfirdn2d

@pytest.mark.parametrize("up,down,pad", [
    (1, 1, (0, 0, 0, 0)),
    (1, 1, (2, 1, 1, 2)),
    (2, 1, (2, 1, 2, 1)),     # upsample_2d's padding shape
    (1, 2, (1, 1, 1, 1)),     # downsample_2d
    (2, 2, (3, 3, 3, 3)),
    (1, 1, (-1, -1, -1, -1)),  # negative pad = crop
])
def test_upfirdn2d_matches_oracle(rng, up, down, pad):
    x = rng.randn(2, 8, 8, 3).astype(np.float32)
    f = refs.setup_filter_ref([1, 3, 3, 1])
    got = ops.upfirdn2d(jnp.asarray(x), jnp.asarray(f, dtype=jnp.float32),
                        up=up, down=down, pad=pad)
    want = refs.upfirdn2d_ref(x.astype(np.float64), f, up=up, down=down, pad=pad)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_upsample_downsample_shapes(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    up = ops.upsample_2d(x, [1, 3, 3, 1])
    assert up.shape == (2, 16, 16, 3)
    down = ops.downsample_2d(x, [1, 3, 3, 1])
    assert down.shape == (2, 4, 4, 3)
    same = ops.filter_2d(x, [1, 3, 3, 1])
    assert same.shape == x.shape


def test_upsample_preserves_mean(rng):
    # gain factor**2 on the filter keeps total energy: mean of the upsampled
    # image equals mean of the input (interior; use constant input to avoid
    # edge effects entirely).
    x = jnp.ones((1, 8, 8, 1))
    up = ops.upsample_2d(x, [1, 3, 3, 1])
    np.testing.assert_allclose(np.asarray(up[0, 4:12, 4:12, 0]), 1.0, atol=1e-5)


def test_upfirdn2d_grad(rng):
    x = jnp.asarray(rng.randn(1, 6, 6, 2).astype(np.float32))
    f = jnp.asarray(refs.setup_filter_ref([1, 2, 1]), dtype=jnp.float32)

    def fn(v):
        return ops.upfirdn2d(v, f, up=2, down=1, pad=(2, 1, 2, 1))

    jtu.check_grads(fn, (x,), order=2, modes=("rev",), atol=1e-2, rtol=1e-2)


# ISSUE 14 satellite: the wrappers' GRADIENTS against the closed-form
# adjoint (upfirdn is linear, so grad-of-⟨r, y⟩ must equal the oracle
# upfirdn of r with the flipped filter, up↔down swapped, and the
# reference's gradient pads) — odd AND even taps, asymmetric pads.
# Previously only forward shapes were exercised.
@pytest.mark.parametrize("taps", [[1, 3, 3, 1], [1, 2, 1]],
                         ids=["even4", "odd3"])
@pytest.mark.parametrize("wrapper", ["upsample_2d", "downsample_2d",
                                     "filter_2d", "asym"])
def test_upfirdn_wrapper_grads_match_adjoint_oracle(rng, taps, wrapper):
    x = rng.randn(2, 7, 9, 3).astype(np.float32)
    f = refs.setup_filter_ref(taps)
    fh = f.shape[0]
    if wrapper == "upsample_2d":
        fn = lambda v: ops.upsample_2d(v, taps)
        up, down = 2, 1
        p = fh - 2
        pad = ((p + 1) // 2 + 1, p // 2, (p + 1) // 2 + 1, p // 2)
        f_eff = f * 4.0                       # gain = factor²
    elif wrapper == "downsample_2d":
        fn = lambda v: ops.downsample_2d(v, taps)
        up, down = 1, 2
        p = fh - 2
        pad = ((p + 1) // 2, p // 2, (p + 1) // 2, p // 2)
        f_eff = f
    elif wrapper == "filter_2d":
        fn = lambda v: ops.filter_2d(v, taps)
        up, down = 1, 1
        p = fh - 1
        pad = ((p + 1) // 2, p // 2, (p + 1) // 2, p // 2)
        f_eff = f
    else:                                     # raw op, asymmetric pads
        pad = (2, 0, 1, 3)
        up, down = 2, 2
        f_eff = f
        fn = lambda v: ops.upfirdn2d(
            v, jnp.asarray(f, jnp.float32), up=up, down=down, pad=pad)
    y = fn(jnp.asarray(x))
    r = rng.randn(*y.shape).astype(np.float32)
    got = jax.grad(lambda v: jnp.sum(fn(v) * jnp.asarray(r)))(
        jnp.asarray(x))
    # adjoint: flipped filter, up/down swapped, reference gradient pads
    oh = y.shape[1]
    ow = y.shape[2]
    gpad = (fh - pad[0] - 1, x.shape[1] * up - oh * down + pad[0] - up + 1,
            fh - pad[2] - 1, x.shape[2] * up - ow * down + pad[2] - up + 1)
    want = refs.upfirdn2d_ref(r.astype(np.float64), f_eff[::-1, ::-1],
                              up=down, down=up, pad=gpad)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


# ------------------------------------------------------------ fused_bias_act

@pytest.mark.parametrize("act", ["linear", "relu", "lrelu", "tanh", "sigmoid"])
@pytest.mark.parametrize("gain,clamp", [(None, None), (2.0, 0.5)])
def test_fused_bias_act_matches_oracle(rng, act, gain, clamp):
    x = rng.randn(4, 5, 5, 8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    got = ops.fused_bias_act(jnp.asarray(x), jnp.asarray(b), act=act,
                             gain=gain, clamp=clamp)
    want = refs.fused_bias_act_ref(x, b, act=act, gain=gain, clamp=clamp)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_fused_bias_act_second_order_grad(rng):
    # R1 needs grad-of-grad through the discriminator's activations.
    x = jnp.asarray(rng.randn(8).astype(np.float32))
    b = jnp.asarray(rng.randn(8).astype(np.float32))

    def scalar(v):
        return jnp.sum(ops.fused_bias_act(v, b, act="lrelu") ** 2)

    g = jax.grad(scalar)(x)
    h = jax.grad(lambda v: jnp.sum(jax.grad(scalar)(v) ** 2))(x)
    assert np.isfinite(np.asarray(g)).all()
    assert np.isfinite(np.asarray(h)).all()


# --------------------------------------------------------- modulated_conv2d

@pytest.mark.parametrize("demodulate", [True, False])
def test_modulated_conv_matches_oracle(rng, demodulate):
    x = rng.randn(3, 5, 5, 4).astype(np.float32)
    w = (rng.randn(3, 3, 4, 6) * 0.3).astype(np.float32)
    s = (rng.rand(3, 4) + 0.5).astype(np.float32)
    got = ops.modulated_conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(s),
                               demodulate=demodulate)
    want = refs.modulated_conv2d_ref(x.astype(np.float64), w.astype(np.float64),
                                     s.astype(np.float64), demodulate=demodulate)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-3)


def test_modulated_conv_demod_unit_norm(rng):
    # After demodulation each output channel has unit expected scale:
    # feeding unit-variance noise should give ~unit-variance output.
    x = rng.randn(8, 16, 16, 32).astype(np.float32)
    w = (rng.randn(3, 3, 32, 32) * 0.5).astype(np.float32)
    s = (rng.rand(8, 32) * 2).astype(np.float32)
    y = ops.modulated_conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(s))
    std = float(np.asarray(y).std())
    assert 0.7 < std < 1.3


def test_modulated_conv_up(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
    w = jnp.asarray((rng.randn(3, 3, 4, 6) * 0.3).astype(np.float32))
    s = jnp.asarray((rng.rand(2, 4) + 0.5).astype(np.float32))
    y = ops.modulated_conv2d(x, w, s, up=2)
    assert y.shape == (2, 16, 16, 6)


def test_modulated_conv_second_order(rng):
    # Path-length reg takes jvp-of-grad through this op.
    x = jnp.asarray(rng.randn(1, 4, 4, 3).astype(np.float32))
    w = jnp.asarray((rng.randn(3, 3, 3, 3) * 0.3).astype(np.float32))
    s = jnp.asarray((rng.rand(1, 3) + 0.5).astype(np.float32))

    def scalar(ss):
        return jnp.sum(ops.modulated_conv2d(x, w, ss) ** 2)

    h = jax.grad(lambda ss: jnp.sum(jax.grad(scalar)(ss) ** 2))(s)
    assert np.isfinite(np.asarray(h)).all()


def test_conv2d_resampling_shapes(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
    w = jnp.asarray(rng.randn(3, 3, 4, 6).astype(np.float32))
    assert ops.conv2d(x, w).shape == (2, 8, 8, 6)
    assert ops.conv2d(x, w, up=2).shape == (2, 16, 16, 6)
    assert ops.conv2d(x, w, down=2).shape == (2, 4, 4, 6)


def test_conv2d_down_1x1_decimated_blur_exact(rng):
    """The skip path's decimated blur (upfirdn down=2 computing only kept
    pixels) must equal the dense formulation — blur every pixel, then let
    the 1x1 stride-2 conv discard 3 of 4 — EXACTLY: same taps, same
    positions, just never computing the discarded ones.  Grads included
    (the skip sits inside D, under R1's second-order grads)."""
    from gansformer_tpu.ops.modulated_conv import _conv
    from gansformer_tpu.ops.upfirdn2d import setup_filter, upfirdn2d

    x = jnp.asarray(rng.randn(2, 16, 16, 4).astype(np.float32))
    w = jnp.asarray((rng.randn(1, 1, 4, 6) * 0.5).astype(np.float32))
    f = (1, 3, 3, 1)

    def dense(x, w):
        fk = setup_filter(f)
        p = (fk.shape[0] - 2) + 0
        xb = upfirdn2d(x, fk, pad=((p + 1) // 2, p // 2))
        return _conv(xb, w, stride=2, padding="VALID")

    got = ops.conv2d(x, w, down=2, resample_filter=f)
    want = dense(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)

    loss_got = lambda x, w: jnp.sum(jnp.square(
        ops.conv2d(x, w, down=2, resample_filter=f)))
    loss_want = lambda x, w: jnp.sum(jnp.square(dense(x, w)))
    for arg in (0, 1):
        g = jax.grad(loss_got, arg)(x, w)
        g_ref = jax.grad(loss_want, arg)(x, w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=1e-5, rtol=1e-5)


def test_conv_transpose_poly_exact(rng):
    # The polyphase decomposition must equal a SAME-padded correlation over
    # the zero-inserted 2x grid EXACTLY (it reads the same taps, reordered).
    from gansformer_tpu.ops.modulated_conv import _conv, _conv_transpose_poly

    x = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    w = jnp.asarray((rng.randn(3, 3, 3, 5) * 0.3).astype(np.float32))
    zi = jnp.zeros((2, 16, 16, 3), x.dtype).at[:, ::2, ::2, :].set(x)
    want = _conv(zi, w, stride=1, padding="SAME")
    got = _conv_transpose_poly(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


def test_conv2d_up_polyphase_matches_blur_first(rng):
    # conv2d(up=2) = transposed-conv-then-blur (polyphase); interior pixels
    # must equal the commuted blur-first pipeline (upsample_2d then SAME
    # conv).  Only the <=2-px border may differ (where zero padding
    # truncates the commuted support) — that border is the reference's own
    # transposed-conv boundary semantics.
    from gansformer_tpu.ops.modulated_conv import _conv
    from gansformer_tpu.ops.upfirdn2d import upsample_2d

    x = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    w = jnp.asarray((rng.randn(3, 3, 3, 5) * 0.3).astype(np.float32))
    blur_first = _conv(upsample_2d(x, (1, 3, 3, 1), factor=2), w,
                       stride=1, padding="SAME")
    got = ops.conv2d(x, w, up=2)
    assert got.shape == blur_first.shape
    np.testing.assert_allclose(
        np.asarray(got)[:, 2:-2, 2:-2, :],
        np.asarray(blur_first)[:, 2:-2, 2:-2, :], atol=1e-5, rtol=1e-5)


def test_conv2d_up_polyphase_bf16(rng):
    # The training path runs this op in bf16 on TPU; the polyphase
    # decomposition must stay close to its fp32 value under bf16 inputs.
    x32 = rng.randn(2, 8, 8, 3).astype(np.float32)
    w32 = (rng.randn(3, 3, 3, 5) * 0.3).astype(np.float32)
    y32 = ops.conv2d(jnp.asarray(x32), jnp.asarray(w32), up=2)
    y16 = ops.conv2d(jnp.asarray(x32, jnp.bfloat16),
                     jnp.asarray(w32, jnp.bfloat16), up=2)
    assert y16.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y16, np.float32), np.asarray(y32),
                               atol=0.15, rtol=0.15)


def test_modulated_conv_up_second_order(rng):
    # R1/PL need grad-of-grad THROUGH the up path (polyphase + blur).
    x = jnp.asarray(rng.randn(1, 4, 4, 3).astype(np.float32))
    w = jnp.asarray((rng.randn(3, 3, 3, 3) * 0.3).astype(np.float32))
    s = jnp.asarray((rng.rand(1, 3) + 0.5).astype(np.float32))

    def scalar(ss):
        return jnp.sum(ops.modulated_conv2d(x, w, ss, up=2) ** 2)

    h = jax.grad(lambda ss: jnp.sum(jax.grad(scalar)(ss) ** 2))(s)
    assert np.isfinite(np.asarray(h)).all()


# ----------------------------------------------------------------- attention

@pytest.mark.parametrize("heads", [1, 4])
def test_attention_matches_oracle(rng, heads):
    q = rng.randn(2, 10, 16).astype(np.float32)
    k = rng.randn(2, 7, 16).astype(np.float32)
    v = rng.randn(2, 7, 16).astype(np.float32)
    got, probs = ops.multihead_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v), num_heads=heads)
    want = refs.attention_ref(q, k, v, num_heads=heads)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)
    p = np.asarray(probs)
    assert p.shape == (2, heads, 10, 7)
    np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-5)


def test_grid_encoding_static():
    enc = ops.sinusoidal_grid_encoding(4, 4, 32)
    assert enc.shape == (16, 32)
    assert np.isfinite(enc).all()
    # distinct positions get distinct encodings
    assert len(np.unique(enc.round(5), axis=0)) == 16


# ------------------------------------------- sequence-parallel attention

def _mesh2d(data=2, model=4):
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[: data * model]).reshape(data, model)
    return Mesh(devs, ("data", "model"))


def test_sharded_attention_matches_unsharded(rng):
    """The explicit shard_map kernel (K/V length axis sharded over 'model',
    cross-shard-stable softmax; SURVEY.md §2.4 SP row) must equal the plain
    op bit-for-bit up to collective reduction order."""
    n, lq, lk, d, dv, heads = 4, 6, 64, 32, 16, 2
    q = jnp.asarray(rng.randn(n, lq, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, lk, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, lk, dv), jnp.float32)
    mesh = _mesh2d()
    ref_out, ref_probs = ops.multihead_attention(q, k, v, heads)
    out, probs = jax.jit(
        lambda q, k, v: ops.sharded_multihead_attention(q, k, v, heads, mesh)
    )(q, k, v)
    np.testing.assert_allclose(ref_out, out, atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(ref_probs, probs, atol=1e-6, rtol=1e-5)
    # global row-stochasticity survives the shard boundary
    np.testing.assert_allclose(np.asarray(probs).sum(-1), 1.0, atol=1e-5)


def test_sharded_attention_grad_parity(rng):
    """psum/pmax collectives are transposable — first-order grads through the
    sharded softmax must match the unsharded op (R1/PL rely on this)."""
    n, lq, lk, d, dv, heads = 2, 3, 32, 16, 8, 1
    q = jnp.asarray(rng.randn(n, lq, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, lk, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, lk, dv), jnp.float32)
    mesh = _mesh2d()

    def loss_ref(k):
        return (ops.multihead_attention(q, k, v, heads)[0] ** 2).sum()

    def loss_sharded(k):
        return (ops.sharded_multihead_attention(
            q, k, v, heads, mesh)[0] ** 2).sum()

    g_ref = jax.grad(loss_ref)(k)
    g_sh = jax.grad(loss_sharded)(k)
    np.testing.assert_allclose(g_ref, g_sh, atol=2e-5, rtol=1e-4)


# ------------------------------------------------- pallas attention kernels

@pytest.mark.parametrize("shape,heads,block_n", [
    ((2, 64, 16, 32, 32), 1, 16),    # grid->latent, n padded to blocks
    ((2, 16, 300, 32, 16), 2, 64),   # latent->grid, masked tail block
    ((3, 100, 8, 16, 16), 2, 512),   # block_n > n
    ((1, 5, 257, 64, 64), 1, 128),   # latent->grid, odd n
])
def test_pallas_attention_matches_jnp(rng, shape, heads, block_n):
    """Fused blockwise kernels (ops/pallas_attention.py; SURVEY.md §2.4
    blockwise row) vs the jnp composite, interpret mode on CPU.  Covers both
    directions: softmax-over-latents (grid queries) and the flash-style
    online softmax over the grid axis (latent queries)."""
    from gansformer_tpu.ops.pallas_attention import multihead_attention_pallas

    n, lq, lk, d, dv = shape
    q = jnp.asarray(rng.randn(n, lq, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, lk, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, lk, dv), jnp.float32)
    ref, _ = ops.multihead_attention(q, k, v, heads)
    out = multihead_attention_pallas(q, k, v, heads, block_n=block_n,
                                     interpret=True)
    np.testing.assert_allclose(ref, out, atol=3e-5, rtol=1e-5)


@pytest.mark.skipif(jax.default_backend() == "tpu",
                    reason="off-TPU pass-through contract; on TPU "
                           "resolve_backend runs a real native smoke compile")
def test_pallas_resolve_backend_off_tpu():
    """resolve_backend (ADVICE r3 gate): the smoke check only gates native
    TPU lowering — off-TPU the interpret-mode path is oracle-tested in CI,
    so the request passes through untouched."""
    from gansformer_tpu.ops.pallas_attention import resolve_backend

    assert resolve_backend("xla") == "xla"
    assert resolve_backend("pallas") == "pallas"


def test_pallas_generator_forward_parity(rng):
    """Same params, attention_backend 'pallas' vs 'xla': the full duplex
    generator forward must agree (the backend only changes the attention
    compute path, never the math)."""
    import dataclasses

    from gansformer_tpu.core.config import ModelConfig
    from gansformer_tpu.models.generator import Generator

    cfg = ModelConfig(resolution=16, components=3, latent_dim=16, w_dim=16,
                      mapping_dim=16, mapping_layers=2, fmap_base=128,
                      fmap_max=32, attention="duplex", attn_start_res=8,
                      attn_max_res=16)
    z = jnp.asarray(rng.randn(2, cfg.num_ws, cfg.latent_dim), jnp.float32)
    noise = jax.random.PRNGKey(3)
    G_xla = Generator(cfg)
    params = G_xla.init({"params": jax.random.PRNGKey(0), "noise": noise}, z)
    G_pl = Generator(dataclasses.replace(cfg, attention_backend="pallas"))
    img_xla = G_xla.apply(params, z, rngs={"noise": noise})
    img_pl = G_pl.apply(params, z, rngs={"noise": noise})
    np.testing.assert_allclose(img_xla, img_pl, atol=5e-5, rtol=1e-4)


def test_sequence_parallel_model_samples_without_mesh(rng):
    """A checkpoint trained with sequence_parallel=True must still run a
    plain single-device forward (generate/evaluate CLIs set no ambient
    mesh): the grid constraint is a layout hint, skipped when no mesh (or
    none with a model axis) is active."""
    from gansformer_tpu.core.config import ModelConfig
    from gansformer_tpu.models.generator import Generator

    cfg = ModelConfig(resolution=16, components=2, latent_dim=16, w_dim=16,
                      mapping_dim=16, mapping_layers=2, fmap_base=128,
                      fmap_max=32, attention="duplex", attn_start_res=8,
                      attn_max_res=16, sequence_parallel=True)
    G = Generator(cfg)
    z = jnp.asarray(rng.randn(2, cfg.num_ws, cfg.latent_dim), jnp.float32)
    noise = jax.random.PRNGKey(1)
    params = G.init({"params": jax.random.PRNGKey(0), "noise": noise}, z)
    img = jax.jit(lambda p, z: G.apply(p, z, rngs={"noise": noise}))(params, z)
    assert np.isfinite(np.asarray(img)).all()
