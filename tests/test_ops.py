"""Parity of the XLA op layer vs the numpy oracles + differentiability.

Covers SURVEY.md §4's implied obligations: ref-vs-fast parity (the reference's
inline `'ref'` switch pattern), gradient checks, and the second-order
gradients R1/path-length regularization relies on (SURVEY.md §7.3 item 1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:  # public jax.test_util was removed in jax 0.9; fall back to private
    from jax import test_util as jtu  # type: ignore
    jtu.check_grads
except (ImportError, AttributeError):
    from jax._src import test_util as jtu

from gansformer_tpu import ops
from tests import reference_ops as refs


# ---------------------------------------------------------------- upfirdn2d

@pytest.mark.parametrize("up,down,pad", [
    (1, 1, (0, 0, 0, 0)),
    (1, 1, (2, 1, 1, 2)),
    (2, 1, (2, 1, 2, 1)),     # upsample_2d's padding shape
    (1, 2, (1, 1, 1, 1)),     # downsample_2d
    (2, 2, (3, 3, 3, 3)),
    (1, 1, (-1, -1, -1, -1)),  # negative pad = crop
])
def test_upfirdn2d_matches_oracle(rng, up, down, pad):
    x = rng.randn(2, 8, 8, 3).astype(np.float32)
    f = refs.setup_filter_ref([1, 3, 3, 1])
    got = ops.upfirdn2d(jnp.asarray(x), jnp.asarray(f, dtype=jnp.float32),
                        up=up, down=down, pad=pad)
    want = refs.upfirdn2d_ref(x.astype(np.float64), f, up=up, down=down, pad=pad)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_upsample_downsample_shapes(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    up = ops.upsample_2d(x, [1, 3, 3, 1])
    assert up.shape == (2, 16, 16, 3)
    down = ops.downsample_2d(x, [1, 3, 3, 1])
    assert down.shape == (2, 4, 4, 3)
    same = ops.filter_2d(x, [1, 3, 3, 1])
    assert same.shape == x.shape


def test_upsample_preserves_mean(rng):
    # gain factor**2 on the filter keeps total energy: mean of the upsampled
    # image equals mean of the input (interior; use constant input to avoid
    # edge effects entirely).
    x = jnp.ones((1, 8, 8, 1))
    up = ops.upsample_2d(x, [1, 3, 3, 1])
    np.testing.assert_allclose(np.asarray(up[0, 4:12, 4:12, 0]), 1.0, atol=1e-5)


def test_upfirdn2d_grad(rng):
    x = jnp.asarray(rng.randn(1, 6, 6, 2).astype(np.float32))
    f = jnp.asarray(refs.setup_filter_ref([1, 2, 1]), dtype=jnp.float32)

    def fn(v):
        return ops.upfirdn2d(v, f, up=2, down=1, pad=(2, 1, 2, 1))

    jtu.check_grads(fn, (x,), order=2, modes=("rev",), atol=1e-2, rtol=1e-2)


# ------------------------------------------------------------ fused_bias_act

@pytest.mark.parametrize("act", ["linear", "relu", "lrelu", "tanh", "sigmoid"])
@pytest.mark.parametrize("gain,clamp", [(None, None), (2.0, 0.5)])
def test_fused_bias_act_matches_oracle(rng, act, gain, clamp):
    x = rng.randn(4, 5, 5, 8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    got = ops.fused_bias_act(jnp.asarray(x), jnp.asarray(b), act=act,
                             gain=gain, clamp=clamp)
    want = refs.fused_bias_act_ref(x, b, act=act, gain=gain, clamp=clamp)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_fused_bias_act_second_order_grad(rng):
    # R1 needs grad-of-grad through the discriminator's activations.
    x = jnp.asarray(rng.randn(8).astype(np.float32))
    b = jnp.asarray(rng.randn(8).astype(np.float32))

    def scalar(v):
        return jnp.sum(ops.fused_bias_act(v, b, act="lrelu") ** 2)

    g = jax.grad(scalar)(x)
    h = jax.grad(lambda v: jnp.sum(jax.grad(scalar)(v) ** 2))(x)
    assert np.isfinite(np.asarray(g)).all()
    assert np.isfinite(np.asarray(h)).all()


# --------------------------------------------------------- modulated_conv2d

@pytest.mark.parametrize("demodulate", [True, False])
def test_modulated_conv_matches_oracle(rng, demodulate):
    x = rng.randn(3, 5, 5, 4).astype(np.float32)
    w = (rng.randn(3, 3, 4, 6) * 0.3).astype(np.float32)
    s = (rng.rand(3, 4) + 0.5).astype(np.float32)
    got = ops.modulated_conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(s),
                               demodulate=demodulate)
    want = refs.modulated_conv2d_ref(x.astype(np.float64), w.astype(np.float64),
                                     s.astype(np.float64), demodulate=demodulate)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-3)


def test_modulated_conv_demod_unit_norm(rng):
    # After demodulation each output channel has unit expected scale:
    # feeding unit-variance noise should give ~unit-variance output.
    x = rng.randn(8, 16, 16, 32).astype(np.float32)
    w = (rng.randn(3, 3, 32, 32) * 0.5).astype(np.float32)
    s = (rng.rand(8, 32) * 2).astype(np.float32)
    y = ops.modulated_conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(s))
    std = float(np.asarray(y).std())
    assert 0.7 < std < 1.3


def test_modulated_conv_up(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
    w = jnp.asarray((rng.randn(3, 3, 4, 6) * 0.3).astype(np.float32))
    s = jnp.asarray((rng.rand(2, 4) + 0.5).astype(np.float32))
    y = ops.modulated_conv2d(x, w, s, up=2)
    assert y.shape == (2, 16, 16, 6)


def test_modulated_conv_second_order(rng):
    # Path-length reg takes jvp-of-grad through this op.
    x = jnp.asarray(rng.randn(1, 4, 4, 3).astype(np.float32))
    w = jnp.asarray((rng.randn(3, 3, 3, 3) * 0.3).astype(np.float32))
    s = jnp.asarray((rng.rand(1, 3) + 0.5).astype(np.float32))

    def scalar(ss):
        return jnp.sum(ops.modulated_conv2d(x, w, ss) ** 2)

    h = jax.grad(lambda ss: jnp.sum(jax.grad(scalar)(ss) ** 2))(s)
    assert np.isfinite(np.asarray(h)).all()


def test_conv2d_resampling_shapes(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
    w = jnp.asarray(rng.randn(3, 3, 4, 6).astype(np.float32))
    assert ops.conv2d(x, w).shape == (2, 8, 8, 6)
    assert ops.conv2d(x, w, up=2).shape == (2, 16, 16, 6)
    assert ops.conv2d(x, w, down=2).shape == (2, 4, 4, 6)


# ----------------------------------------------------------------- attention

@pytest.mark.parametrize("heads", [1, 4])
def test_attention_matches_oracle(rng, heads):
    q = rng.randn(2, 10, 16).astype(np.float32)
    k = rng.randn(2, 7, 16).astype(np.float32)
    v = rng.randn(2, 7, 16).astype(np.float32)
    got, probs = ops.multihead_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v), num_heads=heads)
    want = refs.attention_ref(q, k, v, num_heads=heads)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)
    p = np.asarray(probs)
    assert p.shape == (2, heads, 10, 7)
    np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-5)


def test_grid_encoding_static():
    enc = ops.sinusoidal_grid_encoding(4, 4, 32)
    assert enc.shape == (16, 32)
    assert np.isfinite(enc).all()
    # distinct positions get distinct encodings
    assert len(np.unique(enc.round(5), axis=0)) == 16
