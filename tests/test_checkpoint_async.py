"""Async checkpoint writeback (ISSUE 2): single-slot writer semantics,
crash-safe atomic writes (a failed write NEVER replaces the last good
checkpoint), error surfacing at the next tick boundary, retention, and
bit-exact npz round-trips including extension dtypes."""

import dataclasses
import glob
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.train import checkpoint as ckpt
from gansformer_tpu.train.state import TrainState
from gansformer_tpu.utils.background import (
    BackgroundWriteError, SingleSlotWriter)


def tiny_state(step=0, scale=1.0):
    """A TrainState-shaped pytree small enough for unit tests (no model
    init / compile)."""
    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        g_params={"w": jnp.arange(6, dtype=jnp.float32) * scale,
                  "b16": jnp.arange(4, dtype=jnp.bfloat16)},
        d_params={"w": jnp.full((2, 3), 2.0 * scale)},
        g_opt=(jnp.zeros(3),),
        d_opt=(jnp.zeros(3),),
        ema_params={"w": jnp.ones(5) * scale},
        w_avg=jnp.zeros(4),
        pl_mean=jnp.asarray(0.25 * scale),
    )


def assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype and x.shape == y.shape
        assert np.array_equal(x, y), (x, y)


# --- SingleSlotWriter -------------------------------------------------------

def test_single_slot_writer_runs_and_joins():
    w = SingleSlotWriter("test/ssw")
    out = []
    w.submit(lambda: out.append(1))
    w.wait()
    assert out == [1] and not w.busy


def test_single_slot_writer_is_bounded_single_slot():
    w = SingleSlotWriter("test/ssw2")
    order = []
    gate = threading.Event()

    def slow():
        gate.wait(5.0)
        order.append("first")

    w.submit(slow)
    assert w.busy
    t0 = time.perf_counter()
    gate.set()
    # second submit must JOIN the first (bounded backpressure)
    w.submit(lambda: order.append("second"))
    assert order[0] == "first"
    w.wait()
    assert order == ["first", "second"]
    assert time.perf_counter() - t0 < 5.0


def test_single_slot_writer_error_sticky_until_polled():
    w = SingleSlotWriter("test/ssw3")

    def boom():
        raise OSError("disk gone")

    w.submit(boom, label="step 42")
    w.wait(reraise=False)          # finally-path join must not raise
    with pytest.raises(BackgroundWriteError, match="disk gone"):
        w.poll()
    w.poll()                        # delivered once, then cleared
    w.submit(lambda: None)          # writer usable again after delivery
    w.wait()


# --- atomic npz write / restore --------------------------------------------

def test_checkpoint_roundtrip_bit_exact_incl_bfloat16(tmp_path):
    d = str(tmp_path / "ck")
    st = tiny_state(step=1000, scale=1.5)
    ckpt.save(d, st, block=True)
    assert ckpt.latest_step(d) == 1000
    restored = ckpt.restore(d, tiny_state())
    assert_trees_equal(st, restored)


def test_checkpoint_async_save_roundtrips(tmp_path):
    d = str(tmp_path / "ck")
    st = tiny_state(step=2000, scale=0.5)
    ckpt.save(d, st, block=False)
    ckpt.wait(d)
    assert ckpt.latest_step(d) == 2000
    assert_trees_equal(st, ckpt.restore(d, tiny_state()))


def test_checkpoint_template_mismatch_raises(tmp_path):
    d = str(tmp_path / "ck")
    ckpt.save(d, tiny_state(step=1), block=True)
    bad = dataclasses.replace(tiny_state(), w_avg=jnp.zeros(9))
    with pytest.raises(ValueError, match="does not match template"):
        ckpt.restore(d, bad)


def test_checkpoint_retention_keeps_newest(tmp_path):
    d = str(tmp_path / "ck")
    for s in range(1, 8):
        ckpt.save(d, tiny_state(step=s * 100), max_to_keep=5, block=True)
    steps = sorted(int(p) for p in os.listdir(d) if p.isdigit())
    assert steps == [300, 400, 500, 600, 700]


def test_failed_write_never_replaces_last_good(tmp_path, monkeypatch):
    d = str(tmp_path / "ck")
    good = tiny_state(step=100, scale=3.0)
    ckpt.save(d, good, block=True)

    def hook(step):
        raise OSError("injected mid-write failure")

    monkeypatch.setattr(ckpt, "_WRITE_HOOK", hook)
    with pytest.raises(OSError, match="injected"):
        ckpt.save(d, tiny_state(step=200), block=True)
    monkeypatch.setattr(ckpt, "_WRITE_HOOK", None)
    # last good survives, no temp litter, no torn step dir
    assert ckpt.latest_step(d) == 100
    assert not [p for p in os.listdir(d) if p.startswith(".tmp")]
    assert_trees_equal(good, ckpt.restore(d, tiny_state()))


def test_reset_errors_clears_undelivered_failure(tmp_path, monkeypatch):
    """A run that aborts BETWEEN a writer failure and its tick-boundary
    poll leaves an undelivered sticky error on the per-directory writer
    (cached across train() runs).  The next run's setup calls
    reset_errors — a healthy resume must not crash on the previous
    run's diagnostics."""
    d = str(tmp_path / "ck")

    def hook(step):
        raise OSError("previous run's late failure")

    monkeypatch.setattr(ckpt, "_WRITE_HOOK", hook)
    ckpt.save(d, tiny_state(step=1), block=False)
    ckpt.wait(d, reraise=False)          # the finally-path join
    monkeypatch.setattr(ckpt, "_WRITE_HOOK", None)

    ckpt.reset_errors(d)                 # next run's setup
    ckpt.check_error(d)                  # must not raise
    ckpt.save(d, tiny_state(step=2), block=False)
    ckpt.wait(d)
    assert ckpt.latest_step(d) == 2


def test_async_save_loop_cost_is_dispatch_bound(tmp_path):
    """The O(dispatch) acceptance property: the calling thread's cost of
    an async save must not pay the serialize/fsync work — with a ~64 MB
    state the submit must be far cheaper than the blocking write of the
    SAME state (the device-side copy is an async dispatch; D2H settle,
    serialize, and fsync ride the writer thread)."""
    big = dataclasses.replace(
        tiny_state(step=7),
        g_params={"w": jnp.zeros((16 << 20,), jnp.float32)})   # 64 MB
    ckpt.warm_async(big)            # the loop pre-compiles at setup too

    d_sync = str(tmp_path / "sync")
    t0 = time.perf_counter()
    ckpt.save(d_sync, big, block=True)
    t_block = time.perf_counter() - t0

    d_async = str(tmp_path / "async")
    t0 = time.perf_counter()
    ckpt.save(d_async, big, block=False)
    t_submit = time.perf_counter() - t0
    ckpt.wait(d_async)

    assert t_submit < 0.5 * t_block, (t_submit, t_block)
    assert_trees_equal(big, ckpt.restore(d_async, big))


# --- loop integration: writer crash surfaces at the next tick ---------------

def _crash_cfg(total_kimg):
    from tests.test_train import micro_cfg

    cfg = micro_cfg(attention="simplex", batch=8)
    return dataclasses.replace(
        cfg, train=dataclasses.replace(
            cfg.train, total_kimg=total_kimg, kimg_per_tick=1,
            snapshot_ticks=1, image_snapshot_ticks=0))


@pytest.mark.slow  # two extra training runs (crash + resume)
def test_loop_async_ckpt_crash_surfaces_and_resume_restores(
        tmp_path, monkeypatch):
    """ISSUE 2 satellite: inject a writer-thread exception mid-write →
    the temp file never replaces the last good checkpoint, the error
    surfaces at the next tick boundary, and --resume restores the
    pre-crash step and finishes the run."""
    from gansformer_tpu.train.loop import train

    def hook(step):
        if step >= 2000:
            raise OSError("injected disk failure")

    monkeypatch.setattr(ckpt, "_WRITE_HOOK", hook)
    d = str(tmp_path / "run")
    os.makedirs(d)
    # 3 ticks: save@1000 ok, save@2000 fails on the writer thread, the
    # failure is re-raised from the loop thread at the tick-3 boundary.
    with pytest.raises(BackgroundWriteError, match="injected disk failure"):
        train(_crash_cfg(total_kimg=3), d)
    monkeypatch.setattr(ckpt, "_WRITE_HOOK", None)

    ck = os.path.join(d, "checkpoints")
    assert ckpt.latest_step(ck) == 1000          # last good survived
    assert not [p for p in os.listdir(ck) if p.startswith(".tmp")]
    # the crash window still reached stats.jsonl (tick 2 logged before
    # the boundary check raised)
    lines = [json.loads(l) for l in open(os.path.join(d, "stats.jsonl"))]
    assert lines[-1]["Progress/kimg"] >= 3.0

    # resume: restores the pre-crash step and completes the second kimg
    state = train(_crash_cfg(total_kimg=2), d, resume=True)
    assert int(jax.device_get(state.step)) == 2000
    log = open(os.path.join(d, "log.txt")).read()
    assert "resumed from step 1000" in log
    assert ckpt.latest_step(ck) == 2000


def test_checkpoint_config_json_written_once(tmp_path):
    from tests.test_train import micro_cfg

    d = str(tmp_path / "ck")
    cfg = micro_cfg()
    ckpt.save(d, tiny_state(step=5), cfg=cfg, block=True)
    p = os.path.join(d, "config.json")
    assert os.path.exists(p)
    before = open(p).read()
    ckpt.save(d, tiny_state(step=6), cfg=cfg, block=True)
    assert open(p).read() == before
