"""Serving subsystem tests (ISSUE 10): the split AOT programs, the
bucketed-padding parity contract, the serialized-executable warm start,
the w-cache, and the continuous-batching service.

The load-bearing contracts, each pinned here:

* bucket selection picks the smallest bucket ≥ n and refuses oversize
  batches (the service chunks at max-bucket instead);
* a request batch padded up to the next bucket produces BIT-IDENTICAL
  images to the unpadded batch prefix, f32 and bf16 — held by per-row
  noise keys in ``serve_synth`` (a batch-shaped draw from one key would
  make row i depend on the bucket);
* a second process start with a populated manifest compiles ZERO
  programs (``compile/compiles_total`` delta via the existing listener)
  and corrupt/stale manifest entries fall back to recompile;
* a repeat-seed request never dispatches the mapping program
  (``serve/map_dispatch_total`` stays flat — the acceptance counter);
* a dead dispatcher surfaces at ``submit()`` (LoopWorker discipline),
  not as a hang.
"""

import dataclasses
import json
import os

import numpy as np
import pytest


def _tiny_bundle(dtype="float32"):
    from gansformer_tpu.analysis.trace.entry_points import tiny_config
    from gansformer_tpu.serve import init_generator

    return init_generator(tiny_config(dtype))


def _noisy(bundle):
    """A bundle whose noise layers CONTRIBUTE (random init zeroes
    ``noise_strength``, which would make padding parity trivially true
    regardless of how noise is drawn) and whose w_avg is a real anchor
    (zero would make truncation a pure scale)."""
    import jax
    import jax.numpy as jnp

    def bump(path, leaf):
        name = str(getattr(path[-1], "name", getattr(path[-1], "key", "")))
        return jnp.full_like(leaf, 0.1) if name == "noise_strength" \
            else leaf

    w_avg = jnp.asarray(np.random.RandomState(0).normal(
        size=bundle.w_avg.shape), jnp.float32)
    return dataclasses.replace(
        bundle,
        ema_params=jax.tree_util.tree_map_with_path(bump,
                                                    bundle.ema_params),
        w_avg=w_avg)


@pytest.fixture(scope="module")
def bundle():
    return _tiny_bundle()


@pytest.fixture(scope="module")
def programs(bundle):
    """Shared compiled programs (no manifest — warm-start behavior has
    its own tmp-dir test) so the service/w-cache tests pay the tiny
    compiles once."""
    from gansformer_tpu.serve import ServePrograms

    return ServePrograms(bundle, buckets=(1, 2, 4), manifest_dir=None)


# -- bucket selection --------------------------------------------------------

def test_bucket_selection_edges():
    """Smallest bucket ≥ n, covered at 1 / bucket / bucket+1 /
    oversize / invalid — the edges the padding path lives on."""
    from gansformer_tpu.serve import bucket_for
    from gansformer_tpu.serve.programs import sorted_buckets

    buckets = sorted_buckets([8, 1, 4, 4])
    assert buckets == (1, 4, 8)
    assert bucket_for(1, buckets) == 1
    assert bucket_for(2, buckets) == 4      # bucket-1 + 1
    assert bucket_for(4, buckets) == 4      # exactly a bucket
    assert bucket_for(5, buckets) == 8      # bucket + 1
    assert bucket_for(8, buckets) == 8
    with pytest.raises(ValueError, match="exceeds the largest"):
        bucket_for(9, buckets)
    with pytest.raises(ValueError, match="n >= 1"):
        bucket_for(0, buckets)
    with pytest.raises(ValueError, match="positive"):
        sorted_buckets([0, 2])
    with pytest.raises(ValueError, match="positive"):
        sorted_buckets([])


# -- bucketed-padding parity -------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_padding_parity_bit_identical(dtype):
    """A batch padded up to the next bucket produces BIT-identical
    images to the unpadded batch prefix — the contract that lets the
    service pad freely.  Noise strengths are forced non-zero so the
    per-row noise keys are actually exercised."""
    from gansformer_tpu.serve import ServePrograms

    b = _noisy(_tiny_bundle(dtype))
    p = ServePrograms(b, buckets=(2, 4), manifest_dir=None)
    rng = np.array([3, 9], np.uint32)

    ws2 = np.asarray(p.map_seeds(np.array([11, 12], np.int32)))
    ws4 = np.asarray(p.map_seeds(np.array([11, 12, 12, 12], np.int32)))
    assert (ws4[:2] == ws2).all(), "mapping rows depend on the bucket"

    img2 = np.asarray(p.synthesize(
        ws2, np.array([0.6, 0.9], np.float32), rng))
    img4 = np.asarray(p.synthesize(
        ws4, np.array([0.6, 0.9, 1.0, 1.0], np.float32), rng))
    assert img2.dtype == img4.dtype
    assert (img4[:2] == img2).all(), \
        f"{dtype}: padded prefix differs from the unpadded batch"


def test_programs_refuse_partial_buckets(programs):
    """The dispatch layer owns padding; the program layer refuses a
    non-bucket batch instead of silently recompiling a new shape."""
    with pytest.raises(ValueError, match="full bucket"):
        programs.map_seeds(np.array([1, 2, 3], np.int32))
    with pytest.raises(ValueError, match="full bucket"):
        programs.synthesize(
            np.zeros((3, programs.bundle.cfg.model.num_ws,
                      programs.bundle.cfg.model.w_dim), np.float32),
            np.ones((3,), np.float32), np.array([0, 1], np.uint32))


# -- warm start --------------------------------------------------------------

def test_warm_start_second_process_compiles_zero(tmp_path, bundle):
    """The ISSUE 10 acceptance pair: a fresh ``ServePrograms`` against a
    populated manifest deserializes every executable — zero program
    compiles AND zero XLA compiles by the existing registry counter —
    and still serves a correct image."""
    from gansformer_tpu import obs
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import ServePrograms

    obs.install_compile_listener()
    md = str(tmp_path / "manifest")
    cold = ServePrograms(bundle, buckets=(1,), manifest_dir=md)
    w1 = cold.warm_start()
    assert w1["compiled"] == 2 and w1["loaded"] == 0   # map + synth
    assert os.path.exists(os.path.join(md, "manifest.json"))

    imgs_cold = np.asarray(cold.synthesize(
        np.asarray(cold.map_seeds(np.array([5], np.int32))),
        np.array([0.7], np.float32), np.array([0, 1], np.uint32)))

    before = telemetry.counter("compile/compiles_total").value
    warm = ServePrograms(bundle, buckets=(1,), manifest_dir=md)
    w2 = warm.warm_start()
    imgs_warm = np.asarray(warm.synthesize(
        np.asarray(warm.map_seeds(np.array([5], np.int32))),
        np.array([0.7], np.float32), np.array([0, 1], np.uint32)))
    assert w2 == {"loaded": 2, "compiled": 0, "seconds": w2["seconds"]}
    assert telemetry.counter("compile/compiles_total").value == before, \
        "warm start triggered an XLA compile"
    assert (imgs_warm == imgs_cold).all()   # deserialized program parity


def test_warm_start_corrupt_entries_fall_back(tmp_path, bundle):
    """Corrupt/stale manifest entries recompile instead of crashing:
    torn executable bytes, a tampered fingerprint, and a garbage
    manifest.json each land on the fallback path (counted stale)."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import ServePrograms

    md = str(tmp_path / "manifest")
    ServePrograms(bundle, buckets=(1,), manifest_dir=md).warm_start()

    # torn bytes under a valid manifest entry
    victim = os.path.join(md, "map_seeds_b1.bin")
    with open(victim, "r+b") as f:
        f.write(b"\x00garbage\x00")
    stale0 = telemetry.counter("serve/manifest_stale_total").value
    p = ServePrograms(bundle, buckets=(1,), manifest_dir=md)
    w = p.warm_start()
    assert w["compiled"] == 1 and w["loaded"] == 1
    assert telemetry.counter("serve/manifest_stale_total").value > stale0

    # stale fingerprint (architecture/runtime drift)
    mpath = os.path.join(md, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["entries"]["synthesize_b1"]["fingerprint"] = "deadbeef"
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    w = ServePrograms(bundle, buckets=(1,), manifest_dir=md).warm_start()
    assert w["compiled"] == 1 and w["loaded"] == 1    # rewritten above

    # garbage manifest.json: start over, no crash
    with open(mpath, "w") as f:
        f.write("{not json")
    w = ServePrograms(bundle, buckets=(1,), manifest_dir=md).warm_start()
    assert w["compiled"] == 2 and w["loaded"] == 0


# -- w-cache -----------------------------------------------------------------

def test_wcache_lru_eviction_and_keys():
    from gansformer_tpu.serve import WCache, wcache_key

    c = WCache(capacity=2)
    k1, k2, k3 = (wcache_key(i, None) for i in (1, 2, 3))
    c.put(k1, np.zeros(1)), c.put(k2, np.ones(1))
    assert c.get(k1) is not None          # touch 1 → 2 becomes LRU
    c.put(k3, np.full(1, 3.0))
    assert len(c) == 2 and c.get(k2) is None and c.get(k3) is not None
    # labels distinguish keys; identical content hits
    la = wcache_key(7, np.array([1.0, 0.0], np.float32))
    assert la == wcache_key(7, np.array([1.0, 0.0], np.float32))
    assert la != wcache_key(7, np.array([0.0, 1.0], np.float32))
    assert WCache(0).get(k1) is None      # capacity-0 = disabled


def test_repeat_seed_skips_mapping_program(programs):
    """THE acceptance counter: on the cache-hit path the mapping program
    dispatches ZERO times — including at a different ψ (the cache is
    ψ-independent because truncation lives in the synthesis program)."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService

    with GenerationService(programs, max_fill_wait_ms=0.0) as svc:
        first = svc.submit(991, psi=0.7).result(timeout=60)
        maps = telemetry.counter("serve/map_dispatch_total").value
        hits = telemetry.counter("serve/wcache_hits_total").value
        again = svc.submit(991, psi=0.7).result(timeout=60)
        other_psi = svc.submit(991, psi=0.4).result(timeout=60)
        assert telemetry.counter("serve/map_dispatch_total").value == maps
        assert telemetry.counter("serve/wcache_hits_total").value == \
            hits + 2
    assert (again == first).all()          # same seed+ψ, same noise seed
    assert first.shape == other_psi.shape and not (other_psi == first).all()


def test_partial_miss_batch_maps_once(programs):
    """A batch mixing cache hits and misses takes the assemble-on-host
    path: exactly one mapping dispatch for the misses, every ticket
    still resolves."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService

    with GenerationService(programs, max_fill_wait_ms=200.0) as svc:
        svc.submit(700).result(timeout=60)            # cache seed 700
        maps = telemetry.counter("serve/map_dispatch_total").value
        t1, t2 = svc.submit(700), svc.submit(701)     # hit + miss
        a, b = t1.result(timeout=60), t2.result(timeout=60)
    assert np.isfinite(np.float32(a)).all()
    assert np.isfinite(np.float32(b)).all()
    assert telemetry.counter("serve/map_dispatch_total").value == maps + 1


# -- the service -------------------------------------------------------------

def test_service_serves_a_burst_with_slo_telemetry(programs, tmp_path):
    """A burst through the continuous-batching queue: every ticket
    resolves, the SLO histograms/counters land, and telemetry.prom
    passes the serve-family schema lint."""
    from gansformer_tpu.analysis.telemetry_schema import (
        check_prom, check_serve_metric_families)
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService

    reg = telemetry.get_registry()
    e2e0 = reg.histogram("serve/e2e_ms").count
    imgs0 = telemetry.counter("serve/images_total").value
    with GenerationService(programs, max_fill_wait_ms=20.0) as svc:
        tickets = [svc.submit(seed, psi=0.5 + 0.1 * (seed % 3))
                   for seed in range(30, 39)]
        images = [t.result(timeout=60) for t in tickets]
    m = programs.bundle.cfg.model
    assert all(i.shape == (m.resolution, m.resolution, m.img_channels)
               for i in images)
    assert all(np.isfinite(np.float32(i)).all() for i in images)
    assert reg.histogram("serve/e2e_ms").count == e2e0 + 9
    assert telemetry.counter("serve/images_total").value == imgs0 + 9
    assert reg.histogram("serve/queue_depth").count > 0
    fill = reg.histogram("serve/batch_fill")
    assert fill.count > 0 and 0.0 < fill.max <= 1.0
    assert all(t.latency_ms is not None and t.latency_ms > 0
               for t in tickets)

    prom = str(tmp_path / "telemetry.prom")
    reg.write_prom(prom)
    assert check_prom(prom) == []
    assert check_serve_metric_families(prom) == []


def test_dead_dispatcher_surfaces_at_submit(bundle):
    """LoopWorker discipline: a dispatcher crash fails the in-flight
    tickets AND re-raises at the next ``submit`` — never a silent
    hang."""
    from gansformer_tpu.serve import GenerationService, ServePrograms
    from gansformer_tpu.utils.background import BackgroundWriteError

    class Boom(ServePrograms):
        def map_seeds(self, seeds, label=None):
            raise RuntimeError("device on fire")

    svc = GenerationService(Boom(bundle, buckets=(1,), manifest_dir=None),
                            max_fill_wait_ms=0.0)
    t = svc.submit(1)
    with pytest.raises(RuntimeError, match="generation request failed"):
        t.result(timeout=30)
    svc._worker.join(30)
    # sticky FOREVER: a dead loop never recovers, so every later
    # submitter must see the crash — not just the first one
    for _ in range(2):
        with pytest.raises(BackgroundWriteError, match="dispatch"):
            svc.submit(2)
    svc.close()


def test_service_close_fails_queued_tickets(programs):
    """Tickets still queued at close() resolve with an error, not a
    hang."""
    from gansformer_tpu.serve import GenerationService

    svc = GenerationService(programs, max_fill_wait_ms=0.0)
    svc.close()
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit(1)


# -- the load-test harness ---------------------------------------------------

def test_run_loadtest_smoke(bundle):
    """``run_loadtest`` end-to-end on the tiny CPU proxy: the artifact
    carries the whole reporting contract — latency percentiles,
    throughput per chip, batch fill, warm-start + first-image split —
    with coherent values."""
    from scripts.loadtest_serve import run_loadtest

    r = run_loadtest(bundle, (1, 2), requests=12, rate=0.0,
                     duration_s=60.0, manifest_dir=None, wcache=64,
                     seed_universe=8, measure_cold=False)
    assert r["requests"] == 12 and r["images"] == 12
    for k in ("p50_ms", "p90_ms", "p99_ms", "img_per_s",
              "img_per_s_per_chip", "batch_fill_mean",
              "warm_first_image_total_ms", "wcache_hit_rate"):
        assert np.isfinite(r[k]), (k, r[k])
    assert r["p50_ms"] <= r["p99_ms"]
    assert 0.0 <= r["wcache_hit_rate"] <= 1.0
    assert r["synth_dispatch_total"] > 0
    # Zipf over an 8-seed universe with 12 draws must repeat seeds —
    # the w-cache sees hits
    assert r["wcache_hit_rate"] > 0.0


# -- the G-only checkpoint surface -------------------------------------------

def test_restore_selected_partial_restore(micro_run_dir):
    """``restore_selected`` against an ABSTRACT template loads exactly
    the selected leaves (== the full restore's values) and leaves the
    rest None — the discriminator and optimizer are never materialized."""
    import jax

    from gansformer_tpu.core.config import ExperimentConfig
    from gansformer_tpu.parallel.contracts import key_str
    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.train.state import create_train_state

    with open(os.path.join(micro_run_dir, "config.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    ckpt_dir = os.path.join(micro_run_dir, "checkpoints")
    template = jax.eval_shape(lambda k: create_train_state(cfg, k),
                              jax.random.PRNGKey(0))

    def is_g(path):
        return key_str(path[0]) in ("ema_params", "w_avg") if path \
            else False

    part = ckpt.restore_selected(ckpt_dir, template, is_g)

    def all_none(tree):   # unselected POSITIONS restore as None leaves
        leaves = jax.tree_util.tree_flatten(
            tree, is_leaf=lambda x: x is None)[0]
        return bool(leaves) and all(l is None for l in leaves)

    assert all_none(part.d_params) and all_none(part.g_opt) \
        and all_none(part.d_opt)
    full = ckpt.restore(ckpt_dir,
                        create_train_state(cfg, jax.random.PRNGKey(0)))
    assert (np.asarray(part.w_avg) == np.asarray(full.w_avg)).all()
    pl, fl = (jax.tree_util.tree_leaves(t.ema_params) for t in (part,
                                                                full))
    assert len(pl) == len(fl)
    assert all((np.asarray(a) == np.asarray(b)).all()
               for a, b in zip(pl, fl))


def test_load_generator_bundle_matches_checkpoint(micro_run_dir):
    """``load_generator`` (the serve/generate CLI surface) returns the
    checkpoint's EMA generator and records its restore cost."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import load_generator

    b = load_generator(micro_run_dir)
    assert b.cfg.model.resolution == 16
    assert np.asarray(b.w_avg).shape == (b.cfg.model.w_dim,)
    assert np.isfinite(np.asarray(b.w_avg)).all()
    assert telemetry.gauge("serve/restore_ms").value > 0
