"""Serving subsystem tests (ISSUE 10): the split AOT programs, the
bucketed-padding parity contract, the serialized-executable warm start,
the w-cache, and the continuous-batching service.

The load-bearing contracts, each pinned here:

* bucket selection picks the smallest bucket ≥ n and refuses oversize
  batches (the service chunks at max-bucket instead);
* a request batch padded up to the next bucket produces BIT-IDENTICAL
  images to the unpadded batch prefix, f32 and bf16 — held by per-row
  noise keys in ``serve_synth`` (a batch-shaped draw from one key would
  make row i depend on the bucket);
* a second process start with a populated manifest compiles ZERO
  programs (``compile/compiles_total`` delta via the existing listener)
  and corrupt/stale manifest entries fall back to recompile;
* a repeat-seed request never dispatches the mapping program
  (``serve/map_dispatch_total`` stays flat — the acceptance counter);
* the robustness floor (ISSUE 13): over-bound submits shed with a typed
  ``Overloaded``; expired/cancelled tickets are dropped BEFORE dispatch;
  a crashed (or hung) dispatcher is restarted by the supervisor with
  only the in-flight batch failed; restart-budget exhaustion trips the
  circuit breaker (typed ``ServiceUnhealthy`` at submit, sticky);
  ``close()`` drains gracefully and never leaves a ticket blocked.
"""

import dataclasses
import json
import os
import time

import numpy as np
import pytest


def _tiny_bundle(dtype="float32"):
    from gansformer_tpu.analysis.trace.entry_points import tiny_config
    from gansformer_tpu.serve import init_generator

    return init_generator(tiny_config(dtype))


def _noisy(bundle):
    """A bundle whose noise layers CONTRIBUTE (random init zeroes
    ``noise_strength``, which would make padding parity trivially true
    regardless of how noise is drawn) and whose w_avg is a real anchor
    (zero would make truncation a pure scale)."""
    import jax
    import jax.numpy as jnp

    def bump(path, leaf):
        name = str(getattr(path[-1], "name", getattr(path[-1], "key", "")))
        return jnp.full_like(leaf, 0.1) if name == "noise_strength" \
            else leaf

    w_avg = jnp.asarray(np.random.RandomState(0).normal(
        size=bundle.w_avg.shape), jnp.float32)
    return dataclasses.replace(
        bundle,
        ema_params=jax.tree_util.tree_map_with_path(bump,
                                                    bundle.ema_params),
        w_avg=w_avg)


@pytest.fixture(scope="module")
def bundle():
    return _tiny_bundle()


@pytest.fixture(scope="module")
def programs(bundle):
    """Shared compiled programs (no manifest — warm-start behavior has
    its own tmp-dir test) so the service/w-cache tests pay the tiny
    compiles once."""
    from gansformer_tpu.serve import ServePrograms

    return ServePrograms(bundle, buckets=(1, 2, 4), manifest_dir=None)


# -- bucket selection --------------------------------------------------------

def test_bucket_selection_edges():
    """Smallest bucket ≥ n, covered at 1 / bucket / bucket+1 /
    oversize / invalid — the edges the padding path lives on."""
    from gansformer_tpu.serve import bucket_for
    from gansformer_tpu.serve.programs import sorted_buckets

    buckets = sorted_buckets([8, 1, 4, 4])
    assert buckets == (1, 4, 8)
    assert bucket_for(1, buckets) == 1
    assert bucket_for(2, buckets) == 4      # bucket-1 + 1
    assert bucket_for(4, buckets) == 4      # exactly a bucket
    assert bucket_for(5, buckets) == 8      # bucket + 1
    assert bucket_for(8, buckets) == 8
    with pytest.raises(ValueError, match="exceeds the largest"):
        bucket_for(9, buckets)
    with pytest.raises(ValueError, match="n >= 1"):
        bucket_for(0, buckets)
    with pytest.raises(ValueError, match="positive"):
        sorted_buckets([0, 2])
    with pytest.raises(ValueError, match="positive"):
        sorted_buckets([])


# -- bucketed-padding parity -------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_padding_parity_bit_identical(dtype):
    """A batch padded up to the next bucket produces BIT-identical
    images to the unpadded batch prefix — the contract that lets the
    service pad freely.  Noise strengths are forced non-zero so the
    per-row noise keys are actually exercised."""
    from gansformer_tpu.serve import ServePrograms

    b = _noisy(_tiny_bundle(dtype))
    p = ServePrograms(b, buckets=(2, 4), manifest_dir=None)
    rng = np.array([3, 9], np.uint32)

    ws2 = np.asarray(p.map_seeds(np.array([11, 12], np.int32)))
    ws4 = np.asarray(p.map_seeds(np.array([11, 12, 12, 12], np.int32)))
    assert (ws4[:2] == ws2).all(), "mapping rows depend on the bucket"

    img2 = np.asarray(p.synthesize(
        ws2, np.array([0.6, 0.9], np.float32), rng))
    img4 = np.asarray(p.synthesize(
        ws4, np.array([0.6, 0.9, 1.0, 1.0], np.float32), rng))
    assert img2.dtype == img4.dtype
    assert (img4[:2] == img2).all(), \
        f"{dtype}: padded prefix differs from the unpadded batch"


def test_programs_refuse_partial_buckets(programs):
    """The dispatch layer owns padding; the program layer refuses a
    non-bucket batch instead of silently recompiling a new shape."""
    with pytest.raises(ValueError, match="full bucket"):
        programs.map_seeds(np.array([1, 2, 3], np.int32))
    with pytest.raises(ValueError, match="full bucket"):
        programs.synthesize(
            np.zeros((3, programs.bundle.cfg.model.num_ws,
                      programs.bundle.cfg.model.w_dim), np.float32),
            np.ones((3,), np.float32), np.array([0, 1], np.uint32))


# -- warm start --------------------------------------------------------------

def test_warm_start_second_process_compiles_zero(tmp_path, bundle):
    """The ISSUE 10 acceptance pair: a fresh ``ServePrograms`` against a
    populated manifest deserializes every executable — zero program
    compiles AND zero XLA compiles by the existing registry counter —
    and still serves a correct image."""
    from gansformer_tpu import obs
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import ServePrograms

    obs.install_compile_listener()
    md = str(tmp_path / "manifest")
    cold = ServePrograms(bundle, buckets=(1,), manifest_dir=md)
    w1 = cold.warm_start()
    assert w1["compiled"] == 2 and w1["loaded"] == 0   # map + synth
    assert os.path.exists(os.path.join(md, "manifest.json"))

    imgs_cold = np.asarray(cold.synthesize(
        np.asarray(cold.map_seeds(np.array([5], np.int32))),
        np.array([0.7], np.float32), np.array([0, 1], np.uint32)))

    before = telemetry.counter("compile/compiles_total").value
    warm = ServePrograms(bundle, buckets=(1,), manifest_dir=md)
    w2 = warm.warm_start()
    imgs_warm = np.asarray(warm.synthesize(
        np.asarray(warm.map_seeds(np.array([5], np.int32))),
        np.array([0.7], np.float32), np.array([0, 1], np.uint32)))
    assert w2 == {"loaded": 2, "compiled": 0, "seconds": w2["seconds"]}
    assert telemetry.counter("compile/compiles_total").value == before, \
        "warm start triggered an XLA compile"
    assert (imgs_warm == imgs_cold).all()   # deserialized program parity


def test_warm_start_corrupt_entries_fall_back(tmp_path, bundle):
    """Corrupt/stale manifest entries recompile instead of crashing:
    torn executable bytes, a tampered fingerprint, and a garbage
    manifest.json each land on the fallback path (counted stale)."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import ServePrograms

    md = str(tmp_path / "manifest")
    ServePrograms(bundle, buckets=(1,), manifest_dir=md).warm_start()

    # torn bytes under a valid manifest entry
    victim = os.path.join(md, "map_seeds_b1.bin")
    with open(victim, "r+b") as f:
        f.write(b"\x00garbage\x00")
    stale0 = telemetry.counter("serve/manifest_stale_total").value
    p = ServePrograms(bundle, buckets=(1,), manifest_dir=md)
    w = p.warm_start()
    assert w["compiled"] == 1 and w["loaded"] == 1
    assert telemetry.counter("serve/manifest_stale_total").value > stale0

    # stale fingerprint (architecture/runtime drift)
    mpath = os.path.join(md, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["entries"]["synthesize_b1"]["fingerprint"] = "deadbeef"
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    w = ServePrograms(bundle, buckets=(1,), manifest_dir=md).warm_start()
    assert w["compiled"] == 1 and w["loaded"] == 1    # rewritten above

    # garbage manifest.json: start over, no crash
    with open(mpath, "w") as f:
        f.write("{not json")
    w = ServePrograms(bundle, buckets=(1,), manifest_dir=md).warm_start()
    assert w["compiled"] == 2 and w["loaded"] == 0


# -- w-cache -----------------------------------------------------------------

def test_wcache_lru_eviction_and_keys():
    from gansformer_tpu.serve import WCache, wcache_key

    c = WCache(capacity=2)
    k1, k2, k3 = (wcache_key(i, None) for i in (1, 2, 3))
    c.put(k1, np.zeros(1)), c.put(k2, np.ones(1))
    assert c.get(k1) is not None          # touch 1 → 2 becomes LRU
    c.put(k3, np.full(1, 3.0))
    assert len(c) == 2 and c.get(k2) is None and c.get(k3) is not None
    # labels distinguish keys; identical content hits
    la = wcache_key(7, np.array([1.0, 0.0], np.float32))
    assert la == wcache_key(7, np.array([1.0, 0.0], np.float32))
    assert la != wcache_key(7, np.array([0.0, 1.0], np.float32))
    assert WCache(0).get(k1) is None      # capacity-0 = disabled


def test_repeat_seed_skips_mapping_program(programs):
    """THE acceptance counter: on the cache-hit path the mapping program
    dispatches ZERO times — including at a different ψ (the cache is
    ψ-independent because truncation lives in the synthesis program)."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService

    with GenerationService(programs, max_fill_wait_ms=0.0) as svc:
        first = svc.submit(991, psi=0.7).result(timeout=60)
        maps = telemetry.counter("serve/map_dispatch_total").value
        hits = telemetry.counter("serve/wcache_hits_total").value
        again = svc.submit(991, psi=0.7).result(timeout=60)
        other_psi = svc.submit(991, psi=0.4).result(timeout=60)
        assert telemetry.counter("serve/map_dispatch_total").value == maps
        assert telemetry.counter("serve/wcache_hits_total").value == \
            hits + 2
    assert (again == first).all()          # same seed+ψ, same noise seed
    assert first.shape == other_psi.shape and not (other_psi == first).all()


def test_partial_miss_batch_maps_once(programs):
    """A batch mixing cache hits and misses takes the assemble-on-host
    path: exactly one mapping dispatch for the misses, every ticket
    still resolves."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService

    with GenerationService(programs, max_fill_wait_ms=200.0) as svc:
        svc.submit(700).result(timeout=60)            # cache seed 700
        maps = telemetry.counter("serve/map_dispatch_total").value
        t1, t2 = svc.submit(700), svc.submit(701)     # hit + miss
        a, b = t1.result(timeout=60), t2.result(timeout=60)
    assert np.isfinite(np.float32(a)).all()
    assert np.isfinite(np.float32(b)).all()
    assert telemetry.counter("serve/map_dispatch_total").value == maps + 1


# -- the service -------------------------------------------------------------

def test_service_serves_a_burst_with_slo_telemetry(programs, tmp_path):
    """A burst through the continuous-batching queue: every ticket
    resolves, the SLO histograms/counters land, and telemetry.prom
    passes the serve-family schema lint."""
    from gansformer_tpu.analysis.telemetry_schema import (
        check_prom, check_serve_metric_families)
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService

    reg = telemetry.get_registry()
    e2e0 = reg.histogram("serve/e2e_ms").count
    imgs0 = telemetry.counter("serve/images_total").value
    with GenerationService(programs, max_fill_wait_ms=20.0) as svc:
        tickets = [svc.submit(seed, psi=0.5 + 0.1 * (seed % 3))
                   for seed in range(30, 39)]
        images = [t.result(timeout=60) for t in tickets]
        h = svc.health()
        assert h["state"] == "ready" and h["reasons"] == []
        assert h["dispatcher_alive"] and h["dispatcher_restarts"] == 0
    m = programs.bundle.cfg.model
    assert all(i.shape == (m.resolution, m.resolution, m.img_channels)
               for i in images)
    assert all(np.isfinite(np.float32(i)).all() for i in images)
    assert reg.histogram("serve/e2e_ms").count == e2e0 + 9
    assert telemetry.counter("serve/images_total").value == imgs0 + 9
    assert reg.histogram("serve/queue_depth").count > 0
    fill = reg.histogram("serve/batch_fill")
    assert fill.count > 0 and 0.0 < fill.max <= 1.0
    assert all(t.latency_ms is not None and t.latency_ms > 0
               for t in tickets)

    prom = str(tmp_path / "telemetry.prom")
    reg.write_prom(prom)
    assert check_prom(prom) == []
    assert check_serve_metric_families(prom) == []


def _wait_until(cond, timeout=30.0, what="condition"):
    """Poll helper for cross-thread state (dispatcher pop, monitor
    verdicts) — asserts instead of hanging the suite."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def _gated_programs(bundle, buckets=(1, 2, 4)):
    """Programs whose synthesis blocks on an Event — the deterministic
    way to hold the dispatcher busy while tests fill/shed/expire the
    queue behind it."""
    import threading

    from gansformer_tpu.serve import ServePrograms

    gate = threading.Event()

    class Gated(ServePrograms):
        def synthesize(self, ws, psi, rng, tags=None):
            gate.wait(20)
            return super().synthesize(ws, psi, rng, tags)

    return Gated(bundle, buckets=buckets, manifest_dir=None), gate


def test_dispatcher_crash_trips_breaker_and_surfaces_typed(bundle):
    """The self-healing floor's last line: with a zero restart budget a
    dispatcher crash trips the circuit breaker — the in-flight ticket
    fails (not hangs), every later ``submit`` raises a typed
    ``ServiceUnhealthy`` (sticky: a tripped breaker never silently
    recovers), and ``health()`` reports unhealthy."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import (
        GenerationService, ServePrograms, ServiceUnhealthy)

    class Boom(ServePrograms):
        def map_seeds(self, seeds, label=None):
            raise RuntimeError("device on fire")

    svc = GenerationService(Boom(bundle, buckets=(1,), manifest_dir=None),
                            max_fill_wait_ms=0.0,
                            max_dispatcher_restarts=0,
                            restart_backoff_base_s=0.01)
    t = svc.submit(1)
    with pytest.raises(RuntimeError, match="generation request failed"):
        t.result(timeout=30)
    _wait_until(lambda: svc.health()["state"] == "unhealthy",
                what="breaker trip")
    for _ in range(2):
        with pytest.raises(ServiceUnhealthy, match="circuit breaker"):
            svc.submit(2)
    assert telemetry.gauge("serve/health_state").value == 2
    assert not svc.health()["dispatcher_alive"]
    svc.close()


def test_dispatcher_self_heals_through_injected_crash(programs):
    """ISSUE 13 chaos acceptance (tier-1 shape): an injected
    ``raise@serve_dispatch`` kills the dispatcher mid-traffic; the
    supervisor restarts it under backoff, only the in-flight batch
    fails, later requests are served, and ``health()`` reports the
    restart."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService
    from gansformer_tpu.supervise import faults

    restarts0 = telemetry.counter("serve/dispatcher_restarts_total").value
    faults.arm(faults.parse_specs("raise@serve_dispatch:batch=2"))
    try:
        svc = GenerationService(programs, max_fill_wait_ms=0.0,
                                restart_backoff_base_s=0.01)
        ok1 = svc.submit(881).result(timeout=60)
        t2 = svc.submit(882)
        with pytest.raises(RuntimeError, match="generation request failed"):
            t2.result(timeout=60)
        ok3 = svc.submit(883).result(timeout=60)   # served post-restart
        assert ok1.shape == ok3.shape
        h = svc.health()
        assert h["state"] == "degraded"
        assert h["dispatcher_restarts"] == 1
        assert any("restart" in r for r in h["reasons"])
        svc.close()
        assert telemetry.counter(
            "serve/dispatcher_restarts_total").value == restarts0 + 1
    finally:
        faults.disarm()


def test_breaker_trips_on_persistent_failure_with_budget(bundle):
    """A permanently-broken device with a NONZERO restart budget must
    still trip: crashed dispatch attempts are not progress (only
    fulfilled batches reset the count), so back-to-back failures walk
    through the budget and open the breaker instead of crash-looping
    forever."""
    from gansformer_tpu.serve import (
        GenerationService, ServePrograms, ServiceUnhealthy)

    class Boom(ServePrograms):
        def map_seeds(self, seeds, label=None):
            raise RuntimeError("device on fire")

    svc = GenerationService(Boom(bundle, buckets=(1,), manifest_dir=None),
                            max_fill_wait_ms=0.0,
                            max_dispatcher_restarts=2,
                            restart_backoff_base_s=0.01)
    tickets = []
    for seed in range(1, 4):               # three consecutive deaths
        try:
            tickets.append(svc.submit(seed))
        except ServiceUnhealthy:
            break
        with pytest.raises(RuntimeError):
            tickets[-1].result(timeout=30)
    _wait_until(lambda: svc.health()["state"] == "unhealthy",
                what="breaker trip after budget walk-through")
    with pytest.raises(ServiceUnhealthy, match="circuit breaker"):
        svc.submit(9)
    svc.close()


def test_breaker_counts_back_to_back_deaths_not_lifetime(programs):
    """Progress between deaths resets the breaker count: a service that
    crashes, recovers and SERVES, then crashes again never trips a
    budget of 1 — only back-to-back no-progress deaths escalate."""
    from gansformer_tpu.serve import GenerationService
    from gansformer_tpu.supervise import faults

    faults.arm(faults.parse_specs(
        "raise@serve_dispatch:batch=2,raise@serve_dispatch:batch=4"))
    try:
        svc = GenerationService(programs, max_fill_wait_ms=0.0,
                                max_dispatcher_restarts=1,
                                restart_backoff_base_s=0.01)
        for seed in (771, 772, 773, 774, 775):   # batches 1..5
            try:
                svc.submit(seed).result(timeout=60)
            except RuntimeError:
                pass                             # the two injected crashes
        h = svc.health()
        assert h["state"] == "degraded", h      # NOT unhealthy
        assert h["dispatcher_restarts"] == 2
        assert np.isfinite(
            np.float32(svc.submit(776).result(timeout=60))).all()
        svc.close()
    finally:
        faults.disarm()


def test_hung_dispatcher_abandoned_and_replaced(programs):
    """An injected ``hang@serve_dispatch`` wedges the dispatcher on one
    batch; the hang watchdog abandons the thread, fails the in-flight
    ticket with a typed error, and a replacement serves the next
    request."""
    from gansformer_tpu.serve import GenerationService, ServiceUnhealthy
    from gansformer_tpu.supervise import faults

    faults.arm(faults.parse_specs("hang@serve_dispatch:batch=1"))
    try:
        svc = GenerationService(programs, max_fill_wait_ms=0.0,
                                restart_backoff_base_s=0.01,
                                hang_after_s=0.3,
                                hang_startup_grace_s=0.3)
        t1 = svc.submit(771)
        with pytest.raises(ServiceUnhealthy, match="hung"):
            t1.result(timeout=30)
        assert np.isfinite(
            np.float32(svc.submit(772).result(timeout=60))).all()
        assert svc.health()["dispatcher_restarts"] == 1
        svc.close()
    finally:
        faults.disarm()


def test_overload_sheds_typed_with_zero_hung_tickets(bundle):
    """ISSUE 13 overload acceptance: with the dispatcher held busy,
    submissions beyond the queue bound shed DETERMINISTICALLY with a
    typed ``Overloaded`` (counted in ``serve/shed_total``), health
    degrades with a saturation reason, and once the gate opens every
    ACCEPTED ticket still resolves — zero hung tickets."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService, Overloaded

    p, gate = _gated_programs(bundle)
    shed0 = telemetry.counter("serve/shed_total").value
    svc = GenerationService(p, max_fill_wait_ms=0.0, max_queue_depth=4)
    try:
        first = svc.submit(10)
        _wait_until(lambda: not svc._pending and svc._busy_since
                    is not None, what="first batch in flight")
        accepted = [svc.submit(11 + i) for i in range(4)]
        for i in range(12):                 # 4x the bound, beyond it
            with pytest.raises(Overloaded, match="shed"):
                svc.submit(100 + i)
        assert telemetry.counter("serve/shed_total").value == shed0 + 12
        h = svc.health()
        assert h["state"] == "degraded"
        assert any("saturated" in r for r in h["reasons"])
        gate.set()
        imgs = [t.result(timeout=60) for t in [first] + accepted]
        assert all(np.isfinite(np.float32(i)).all() for i in imgs)
        assert all(t.state == "done" for t in [first] + accepted)
    finally:
        gate.set()
        svc.close()
    assert svc.health()["queue_depth"] == 0


def test_expired_requests_dropped_before_dispatch(bundle):
    """A ticket whose deadline passes while queued resolves with a
    typed ``Expired`` at pop time — never padded into a bucket (the
    mapping program is not dispatched for it)."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import Expired, GenerationService

    p, gate = _gated_programs(bundle, buckets=(1, 2))
    exp0 = telemetry.counter("serve/expired_total").value
    maps0 = telemetry.counter("serve/map_dispatch_total").value
    svc = GenerationService(p, max_fill_wait_ms=0.0)
    try:
        t1 = svc.submit(331)
        _wait_until(lambda: not svc._pending and svc._busy_since
                    is not None, what="first batch in flight")
        t2 = svc.submit(332, deadline_s=0.02)
        time.sleep(0.1)                    # t2 expires while queued
        gate.set()
        assert np.isfinite(np.float32(t1.result(timeout=60))).all()
        with pytest.raises(Expired, match="deadline"):
            t2.result(timeout=60)
        assert telemetry.counter("serve/expired_total").value == exp0 + 1
        # only t1 was mapped: the expired ticket never reached dispatch
        assert telemetry.counter(
            "serve/map_dispatch_total").value == maps0 + 1
    finally:
        gate.set()
        svc.close()


def test_client_timeout_cancels_orphaned_work(bundle):
    """Satellite 1 (orphaned work): a client whose ``result(timeout)``
    raised marks its ticket cancelled; the dispatcher skips it at pop
    time (``serve/cancelled_total``) instead of synthesizing an image
    nobody will read."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import Cancelled, GenerationService

    p, gate = _gated_programs(bundle, buckets=(1, 2))
    can0 = telemetry.counter("serve/cancelled_total").value
    svc = GenerationService(p, max_fill_wait_ms=0.0)
    try:
        t1 = svc.submit(441)
        _wait_until(lambda: not svc._pending and svc._busy_since
                    is not None, what="first batch in flight")
        t2 = svc.submit(442)
        with pytest.raises(TimeoutError):
            t2.result(timeout=0.05)
        assert t2.state == "cancelled"
        gate.set()
        assert np.isfinite(np.float32(t1.result(timeout=60))).all()
        # a later request forces the queue past the cancelled ticket
        svc.submit(443).result(timeout=60)
        assert telemetry.counter(
            "serve/cancelled_total").value == can0 + 1
        with pytest.raises(Cancelled):
            t2.result(timeout=1)
    finally:
        gate.set()
        svc.close()


def test_cancelled_tickets_free_admission_slots(bundle):
    """Dead tickets must not shed live traffic as phantom load: with
    the dispatcher wedged and every queued client timed out (cancelled),
    a new submit compacts the dead slots and is ACCEPTED instead of
    raising Overloaded."""
    from gansformer_tpu.serve import GenerationService

    p, gate = _gated_programs(bundle, buckets=(1, 2))
    svc = GenerationService(p, max_fill_wait_ms=0.0, max_queue_depth=3)
    try:
        t1 = svc.submit(901)
        _wait_until(lambda: not svc._pending and svc._busy_since
                    is not None, what="first batch in flight")
        queued = [svc.submit(902 + i) for i in range(3)]   # at the bound
        for t in queued:
            with pytest.raises(TimeoutError):
                t.result(timeout=0.01)                     # all abandoned
        t_live = svc.submit(909)       # compaction frees the dead slots
        gate.set()
        assert np.isfinite(np.float32(t_live.result(timeout=60))).all()
        assert np.isfinite(np.float32(t1.result(timeout=60))).all()
    finally:
        gate.set()
        svc.close()


def test_bucket_quarantine_reroutes_to_next_larger(bundle):
    """Repeated synthesis failures on one bucket quarantine it; later
    batches route to the next-larger bucket and serve."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService, ServePrograms

    class FlakyBucket(ServePrograms):
        def synthesize(self, ws, psi, rng, tags=None):
            if ws.shape[0] == 1:
                raise RuntimeError("bucket-1 executable poisoned")
            return super().synthesize(ws, psi, rng, tags)

    q0 = telemetry.counter("serve/bucket_quarantined_total").value
    svc = GenerationService(
        FlakyBucket(bundle, buckets=(1, 2), manifest_dir=None),
        max_fill_wait_ms=0.0, max_dispatcher_restarts=5,
        restart_backoff_base_s=0.01, quarantine_after=2)
    try:
        for seed in (551, 552):            # two consecutive b1 failures
            with pytest.raises(RuntimeError,
                               match="generation request failed"):
                svc.submit(seed).result(timeout=60)
        img = svc.submit(553).result(timeout=60)   # rerouted to b2
        assert np.isfinite(np.float32(img)).all()
        h = svc.health()
        assert h["quarantined_buckets"] == [1]
        assert any("quarantined" in r for r in h["reasons"])
        assert telemetry.counter(
            "serve/bucket_quarantined_total").value == q0 + 1
    finally:
        svc.close()


def test_graceful_drain_serves_queue_and_leaks_no_threads(programs):
    """ISSUE 13 drain acceptance: ``close()`` during a burst serves
    every queued ticket within the grace window, ``serve/queue_depth``
    returns to 0, and no service thread (dispatcher or supervisor)
    leaks."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import GenerationService

    svc = GenerationService(programs, max_fill_wait_ms=0.0)
    tickets = [svc.submit(600 + i) for i in range(8)]
    svc.close(timeout=60)
    assert all(t.state == "done" for t in tickets)
    assert not svc._worker.alive and not svc._monitor.is_alive()
    assert telemetry.gauge("serve/queue_depth_now").value == 0
    # a CLEAN close reads as closed (3), never as unhealthy — the
    # exported gauge must not look like a tripped breaker
    assert svc.health()["state"] == "closed"
    assert telemetry.gauge("serve/health_state").value == 3
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit(1)


def test_close_past_grace_fails_leftovers_typed(bundle):
    """A drain that can't finish inside the grace window fails the
    in-flight batch AND the still-queued tickets with a typed
    ``ServiceClosed`` — nothing is left blocked."""
    from gansformer_tpu.serve import GenerationService, ServiceClosed

    p, gate = _gated_programs(bundle, buckets=(1, 2))
    svc = GenerationService(p, max_fill_wait_ms=0.0)
    try:
        t1 = svc.submit(661)
        _wait_until(lambda: not svc._pending and svc._busy_since
                    is not None, what="first batch in flight")
        queued = [svc.submit(662 + i) for i in range(3)]
        svc.close(timeout=0.3)             # gate still shut: can't drain
        for t in [t1] + queued:
            assert t.state in ("failed", "done")
        with pytest.raises(ServiceClosed):
            queued[-1].result(timeout=1)
        assert svc.health()["state"] == "unhealthy"   # drain FAILED
    finally:
        gate.set()


def test_close_fails_queued_after_dispatcher_death(bundle):
    """Satellite 2: the dispatcher died between submit and close (and
    the supervisor is still backing off) — ``close()``'s finally-path
    fails every queued ticket with a typed error instead of leaving
    them blocked forever."""
    from gansformer_tpu.serve import (
        GenerationService, ServePrograms, ServiceClosed)

    class Boom(ServePrograms):
        def map_seeds(self, seeds, label=None):
            raise RuntimeError("device on fire")

    svc = GenerationService(Boom(bundle, buckets=(1,), manifest_dir=None),
                            max_fill_wait_ms=0.0,
                            max_dispatcher_restarts=5,
                            restart_backoff_base_s=60.0)   # long backoff
    t1 = svc.submit(1)
    with pytest.raises(RuntimeError, match="generation request failed"):
        t1.result(timeout=30)
    queued = [svc.submit(2), svc.submit(3)]   # dead dispatcher: queued
    svc.close(timeout=0.5)
    for t in queued:
        with pytest.raises(ServiceClosed, match="closed"):
            t.result(timeout=1)


def test_service_close_fails_queued_tickets(programs):
    """Submitting after close() refuses with a typed error, not a
    hang."""
    from gansformer_tpu.serve import GenerationService

    svc = GenerationService(programs, max_fill_wait_ms=0.0)
    svc.close()
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit(1)


def test_serve_schema_overload_values_awareness(tmp_path):
    """The serve-family schema lint is values-aware: when the caller
    DROVE overload traffic (``expect_overload=True``, the chaos
    loadtest), a shed counter still at zero is flagged — admission
    control rotted.  Without the declaration a full-but-drained queue
    is never flagged (filling to the bound is legitimate)."""
    from gansformer_tpu.analysis.telemetry_schema import (
        check_serve_metric_families)

    base = {"serve_queue_depth_count": 4, "serve_queue_depth_max": 8,
            "serve_batch_fill_count": 4, "serve_e2e_ms_count": 4,
            "serve_requests_total": 12, "serve_images_total": 4,
            "serve_map_dispatch_total": 1, "serve_synth_dispatch_total": 4,
            "serve_wcache_hits_total": 0, "serve_wcache_misses_total": 4,
            "serve_shed_total": 0, "serve_expired_total": 0,
            "serve_cancelled_total": 0,
            "serve_dispatcher_restarts_total": 0,
            "serve_health_state": 0, "serve_dispatcher_alive": 1,
            "serve_queue_bound": 8, "serve_queue_depth_now": 0,
            # the ISSUE 16 tracing family rides every serving prom;
            # requests opened AND reached terminals (lifecycle leaks
            # are a separate values-aware error)
            "reqtrace_requests_total": 12, "reqtrace_events_total": 60,
            "reqtrace_terminal_total": 12, "reqtrace_dropped_total": 0,
            "reqtrace_ledger_rows_total": 12,
            "reqtrace_ledger_dropped_total": 0, "reqtrace_enabled": 1}

    def write(vals, name):
        path = str(tmp_path / name)
        with open(path, "w") as f:
            for k, v in vals.items():
                f.write(f"# TYPE {k} gauge\n{k} {v}\n")
        return path

    sat = write(base, "sat.prom")
    errs = check_serve_metric_families(sat, expect_overload=True)
    assert any("serve_shed_total never moved" in e for e in errs), \
        "declared overload with zero sheds must be flagged"
    # the same prom is fine when overload was not driven: a queue may
    # fill to its bound and drain without refusing anything
    assert check_serve_metric_families(sat) == []
    ok = dict(base, serve_shed_total=3)
    assert check_serve_metric_families(write(ok, "ok.prom"),
                                       expect_overload=True) == []
    missing = dict(base)
    del missing["serve_expired_total"]
    errs = check_serve_metric_families(write(missing, "miss.prom"))
    assert any("serve_expired_total" in e for e in errs)


# -- the load-test harness ---------------------------------------------------

def _chaos_asserts(r):
    """The chaos-artifact contract shared by the tier-1 smoke and the
    slow full drill."""
    assert r["hung_tickets"] == 0, "a recovery path leaked requests"
    assert r["shed"] > 0 and r["shed_rate"] > 0
    assert r["dispatcher_restarts"] >= 1, "injected crash never fired"
    assert r["recovery_wave_served"] > 0, "no post-crash service"
    assert r["served"] > 0
    # conservation: every accepted ticket reached a terminal outcome
    assert r["served"] + r["failed"] + r["expired"] + r["cancelled"] \
        == r["accepted"]
    assert r["health"]["state"] in ("ready", "degraded")


def test_run_chaos_smoke(bundle):
    """``run_chaos`` end-to-end on the tiny CPU proxy: deterministic
    typed shedding under a 4x-bound burst, the injected dispatcher
    crash self-heals, zero hung tickets, recovery measured."""
    from scripts.loadtest_serve import run_chaos

    r = run_chaos(bundle, (1, 2), queue_depth=4, burst_factor=4,
                  crash_at_batch=2, manifest_dir=None, wcache=64,
                  seed_universe=16, restart_backoff_s=0.01)
    _chaos_asserts(r)
    # burst 16 + 4-request recovery wave, both in the accounting
    assert r["burst"] == 16 and r["submitted"] == 20
    assert r["queue_bound"] == 4 and r["accepted"] <= r["submitted"]
    assert r["shed_rate"] <= 1.0
    assert np.isfinite(r["p99_ms_under_overload"])
    assert r["p50_ms_under_overload"] <= r["p99_ms_under_overload"]


@pytest.mark.slow
def test_run_chaos_full_drill(bundle):
    """The battery-shaped overload/chaos drill (larger burst, deeper
    queue, deadlines armed) — slow-marked; the tier-1 smoke above keeps
    the path always-green."""
    from scripts.loadtest_serve import run_chaos

    r = run_chaos(bundle, (1, 2, 4), queue_depth=16, burst_factor=4,
                  crash_at_batch=2, deadline_s=30.0, manifest_dir=None,
                  wcache=256, seed_universe=64,
                  restart_backoff_s=0.05)
    _chaos_asserts(r)
    assert r["burst"] == 64 and r["submitted"] == 80


def test_run_loadtest_smoke(bundle):
    """``run_loadtest`` end-to-end on the tiny CPU proxy: the artifact
    carries the whole reporting contract — latency percentiles,
    throughput per chip, batch fill, warm-start + first-image split —
    with coherent values."""
    from scripts.loadtest_serve import run_loadtest

    r = run_loadtest(bundle, (1, 2), requests=12, rate=0.0,
                     duration_s=60.0, manifest_dir=None, wcache=64,
                     seed_universe=8, measure_cold=False)
    assert r["requests"] == 12 and r["images"] == 12
    for k in ("p50_ms", "p90_ms", "p99_ms", "img_per_s",
              "img_per_s_per_chip", "batch_fill_mean",
              "warm_first_image_total_ms", "wcache_hit_rate"):
        assert np.isfinite(r[k]), (k, r[k])
    assert r["p50_ms"] <= r["p99_ms"]
    assert 0.0 <= r["wcache_hit_rate"] <= 1.0
    assert r["synth_dispatch_total"] > 0
    # Zipf over an 8-seed universe with 12 draws must repeat seeds —
    # the w-cache sees hits
    assert r["wcache_hit_rate"] > 0.0


# -- the G-only checkpoint surface -------------------------------------------

def test_restore_selected_partial_restore(micro_run_dir):
    """``restore_selected`` against an ABSTRACT template loads exactly
    the selected leaves (== the full restore's values) and leaves the
    rest None — the discriminator and optimizer are never materialized."""
    import jax

    from gansformer_tpu.core.config import ExperimentConfig
    from gansformer_tpu.parallel.contracts import key_str
    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.train.state import create_train_state

    with open(os.path.join(micro_run_dir, "config.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    ckpt_dir = os.path.join(micro_run_dir, "checkpoints")
    template = jax.eval_shape(lambda k: create_train_state(cfg, k),
                              jax.random.PRNGKey(0))

    def is_g(path):
        return key_str(path[0]) in ("ema_params", "w_avg") if path \
            else False

    part = ckpt.restore_selected(ckpt_dir, template, is_g)

    def all_none(tree):   # unselected POSITIONS restore as None leaves
        leaves = jax.tree_util.tree_flatten(
            tree, is_leaf=lambda x: x is None)[0]
        return bool(leaves) and all(l is None for l in leaves)

    assert all_none(part.d_params) and all_none(part.g_opt) \
        and all_none(part.d_opt)
    full = ckpt.restore(ckpt_dir,
                        create_train_state(cfg, jax.random.PRNGKey(0)))
    assert (np.asarray(part.w_avg) == np.asarray(full.w_avg)).all()
    pl, fl = (jax.tree_util.tree_leaves(t.ema_params) for t in (part,
                                                                full))
    assert len(pl) == len(fl)
    assert all((np.asarray(a) == np.asarray(b)).all()
               for a, b in zip(pl, fl))


def test_load_generator_bundle_matches_checkpoint(micro_run_dir):
    """``load_generator`` (the serve/generate CLI surface) returns the
    checkpoint's EMA generator and records its restore cost."""
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import load_generator

    b = load_generator(micro_run_dir)
    assert b.cfg.model.resolution == 16
    assert np.asarray(b.w_avg).shape == (b.cfg.model.w_dim,)
    assert np.isfinite(np.asarray(b.w_avg)).all()
    assert telemetry.gauge("serve/restore_ms").value > 0
