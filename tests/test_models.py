"""Golden shape/dtype/finiteness tests for the model zoo (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.core.config import ModelConfig, get_preset
from gansformer_tpu.models import (
    BipartiteAttention,
    Discriminator,
    Generator,
    MappingNetwork,
    SynthesisNetwork,
)

TINY = ModelConfig(resolution=32, components=4, latent_dim=32, w_dim=32,
                   mapping_dim=32, mapping_layers=2, fmap_base=512,
                   fmap_max=64, attention="duplex", attn_start_res=8,
                   attn_max_res=16)


def _z(cfg, n=2, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(n, cfg.num_ws, cfg.latent_dim).astype(np.float32))


def test_mapping_shapes():
    m = MappingNetwork(w_dim=32, hidden_dim=32, num_layers=3)
    z = _z(TINY)
    params = m.init(jax.random.PRNGKey(0), z)
    w = m.apply(params, z)
    assert w.shape == (2, TINY.num_ws, 32)
    assert np.isfinite(np.asarray(w)).all()


@pytest.mark.parametrize("mode", ["none", "simplex", "duplex"])
def test_synthesis_shapes(mode):
    cfg = ModelConfig(**{**TINY.__dict__, "attention": mode})
    net = SynthesisNetwork(cfg)
    ws = jnp.zeros((2, cfg.num_ws, cfg.w_dim))
    params = net.init({"params": jax.random.PRNGKey(0),
                       "noise": jax.random.PRNGKey(1)}, ws)
    img = net.apply(params, ws, rngs={"noise": jax.random.PRNGKey(2)})
    assert img.shape == (2, 32, 32, 3)
    assert img.dtype == jnp.float32
    assert np.isfinite(np.asarray(img)).all()


def test_generator_end_to_end_and_truncation():
    g = Generator(TINY)
    z = _z(TINY)
    params = g.init({"params": jax.random.PRNGKey(0),
                     "noise": jax.random.PRNGKey(1)}, z)
    img = g.apply(params, z, rngs={"noise": jax.random.PRNGKey(2)})
    assert img.shape == (2, 32, 32, 3)
    # truncation toward w_avg must change the output
    w_avg = jnp.zeros((TINY.w_dim,))
    img_t = g.apply(params, z, truncation_psi=0.5, w_avg=w_avg,
                    rngs={"noise": jax.random.PRNGKey(2)})
    assert not np.allclose(np.asarray(img), np.asarray(img_t))


@pytest.mark.parametrize("d_attention", [False, True])
def test_discriminator_shapes(d_attention):
    cfg = ModelConfig(**{**TINY.__dict__, "d_attention": d_attention,
                         "d_components": 4})
    d = Discriminator(cfg)
    img = jnp.asarray(np.random.RandomState(0)
                      .randn(4, 32, 32, 3).astype(np.float32))
    params = d.init(jax.random.PRNGKey(0), img)
    logits = d.apply(params, img)
    assert logits.shape == (4, 1)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_bipartite_attention_updates_latents_in_duplex():
    attn = BipartiteAttention(grid_dim=16, latent_dim=16, duplex=True)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 8, 16).astype(np.float32))
    y = jnp.asarray(np.random.RandomState(1).randn(2, 4, 16).astype(np.float32))
    params = attn.init(jax.random.PRNGKey(0), x, y)
    x2, y2 = attn.apply(params, x, y)
    assert x2.shape == x.shape and y2.shape == y.shape
    assert not np.allclose(np.asarray(y), np.asarray(y2))  # duplex updates Y

    simplex = BipartiteAttention(grid_dim=16, latent_dim=16, duplex=False)
    sp = simplex.init(jax.random.PRNGKey(0), x, y)
    _, y3 = simplex.apply(sp, x, y)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y3))  # simplex doesn't


def test_bf16_compute_path():
    cfg = ModelConfig(**{**TINY.__dict__, "dtype": "bfloat16"})
    g = Generator(cfg)
    z = _z(cfg)
    params = g.init({"params": jax.random.PRNGKey(0),
                     "noise": jax.random.PRNGKey(1)}, z)
    # params stay fp32
    leaves = jax.tree_util.tree_leaves(params)
    assert all(l.dtype == jnp.float32 for l in leaves)
    img = g.apply(params, z, rngs={"noise": jax.random.PRNGKey(2)})
    assert img.dtype == jnp.float32
    assert np.isfinite(np.asarray(img)).all()


def test_preset_configs_instantiable():
    for name in ["clevr64-simplex", "ffhq256-duplex"]:
        cfg = get_preset(name).model
        assert cfg.block_resolutions[-1] == cfg.resolution
        assert cfg.nf(4) <= cfg.fmap_max
        assert len(cfg.attn_resolutions()) >= 1
