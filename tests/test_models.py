"""Golden shape/dtype/finiteness tests for the model zoo (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.core.config import ModelConfig, get_preset
from gansformer_tpu.models import (
    BipartiteAttention,
    Discriminator,
    Generator,
    MappingNetwork,
    SynthesisNetwork,
)

TINY = ModelConfig(resolution=32, components=4, latent_dim=32, w_dim=32,
                   mapping_dim=32, mapping_layers=2, fmap_base=512,
                   fmap_max=64, attention="duplex", attn_start_res=8,
                   attn_max_res=16)


def _z(cfg, n=2, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(n, cfg.num_ws, cfg.latent_dim).astype(np.float32))


def test_mapping_shapes():
    m = MappingNetwork(w_dim=32, hidden_dim=32, num_layers=3)
    z = _z(TINY)
    params = m.init(jax.random.PRNGKey(0), z)
    w = m.apply(params, z)
    assert w.shape == (2, TINY.num_ws, 32)
    assert np.isfinite(np.asarray(w)).all()


@pytest.mark.parametrize("mode", ["none", "simplex", "duplex"])
def test_synthesis_shapes(mode):
    cfg = ModelConfig(**{**TINY.__dict__, "attention": mode})
    net = SynthesisNetwork(cfg)
    ws = jnp.zeros((2, cfg.num_ws, cfg.w_dim))
    params = net.init({"params": jax.random.PRNGKey(0),
                       "noise": jax.random.PRNGKey(1)}, ws)
    img = net.apply(params, ws, rngs={"noise": jax.random.PRNGKey(2)})
    assert img.shape == (2, 32, 32, 3)
    assert img.dtype == jnp.float32
    assert np.isfinite(np.asarray(img)).all()


def test_generator_end_to_end_and_truncation():
    g = Generator(TINY)
    z = _z(TINY)
    params = g.init({"params": jax.random.PRNGKey(0),
                     "noise": jax.random.PRNGKey(1)}, z)
    img = g.apply(params, z, rngs={"noise": jax.random.PRNGKey(2)})
    assert img.shape == (2, 32, 32, 3)
    # truncation toward w_avg must change the output
    w_avg = jnp.zeros((TINY.w_dim,))
    img_t = g.apply(params, z, truncation_psi=0.5, w_avg=w_avg,
                    rngs={"noise": jax.random.PRNGKey(2)})
    assert not np.allclose(np.asarray(img), np.asarray(img_t))


@pytest.mark.parametrize("d_attention", [False, True])
def test_discriminator_shapes(d_attention):
    cfg = ModelConfig(**{**TINY.__dict__, "d_attention": d_attention,
                         "d_components": 4})
    d = Discriminator(cfg)
    img = jnp.asarray(np.random.RandomState(0)
                      .randn(4, 32, 32, 3).astype(np.float32))
    params = d.init(jax.random.PRNGKey(0), img)
    logits = d.apply(params, img)
    assert logits.shape == (4, 1)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_bipartite_attention_updates_latents_in_duplex():
    attn = BipartiteAttention(grid_dim=16, latent_dim=16, duplex=True)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 8, 16).astype(np.float32))
    y = jnp.asarray(np.random.RandomState(1).randn(2, 4, 16).astype(np.float32))
    params = attn.init(jax.random.PRNGKey(0), x, y)
    x2, y2 = attn.apply(params, x, y)
    assert x2.shape == x.shape and y2.shape == y.shape
    assert not np.allclose(np.asarray(y), np.asarray(y2))  # duplex updates Y

    simplex = BipartiteAttention(grid_dim=16, latent_dim=16, duplex=False)
    sp = simplex.init(jax.random.PRNGKey(0), x, y)
    _, y3 = simplex.apply(sp, x, y)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y3))  # simplex doesn't


def test_bf16_compute_path():
    cfg = ModelConfig(**{**TINY.__dict__, "dtype": "bfloat16"})
    g = Generator(cfg)
    z = _z(cfg)
    params = g.init({"params": jax.random.PRNGKey(0),
                     "noise": jax.random.PRNGKey(1)}, z)
    # params stay fp32
    leaves = jax.tree_util.tree_leaves(params)
    assert all(l.dtype == jnp.float32 for l in leaves)
    img = g.apply(params, z, rngs={"noise": jax.random.PRNGKey(2)})
    assert img.dtype == jnp.float32
    assert np.isfinite(np.asarray(img)).all()


def test_preset_configs_instantiable():
    for name in ["clevr64-simplex", "ffhq256-duplex"]:
        cfg = get_preset(name).model
        assert cfg.block_resolutions[-1] == cfg.resolution
        assert cfg.nf(4) <= cfg.fmap_max
        assert len(cfg.attn_resolutions()) >= 1


def test_attention_style_mode():
    """style_mode='attention' routes refined latents into conv modulation
    (SURVEY.md §3.2 w_attn) and starts exactly at global styling (ReZero)."""
    import dataclasses

    cfg_g = dataclasses.replace(TINY, style_mode="global")
    cfg_a = dataclasses.replace(TINY, style_mode="attention")
    z = _z(TINY)
    ws = jnp.broadcast_to(z[:, :1], z.shape)  # any ws works; reuse z stats

    net_a = SynthesisNetwork(cfg_a)
    params_a = net_a.init(
        {"params": jax.random.PRNGKey(0), "noise": jax.random.PRNGKey(1)}, ws)
    # wattn projection + gate exist at each attention resolution
    p = params_a["params"]
    for res in cfg_a.attn_resolutions():
        assert f"b{res}_wattn" in p and f"b{res}_wattn_gate" in p

    # gate starts at 0 → output must equal the global-mode output with the
    # same shared parameters.
    net_g = SynthesisNetwork(cfg_g)
    params_g = {"params": {k: v for k, v in p.items()
                           if "wattn" not in k}}
    out_a = net_a.apply(params_a, ws, rngs={"noise": jax.random.PRNGKey(2)})
    out_g = net_g.apply(params_g, ws, rngs={"noise": jax.random.PRNGKey(2)})
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_g),
                               rtol=1e-5, atol=1e-5)

    # with a non-zero gate the attention term must change the image
    p2 = jax.tree_util.tree_map(lambda x: x, params_a)
    p2["params"] = dict(p2["params"])
    for res in cfg_a.attn_resolutions():
        p2["params"][f"b{res}_wattn_gate"] = jnp.asarray(1.0)
    out_a2 = net_a.apply(p2, ws, rngs={"noise": jax.random.PRNGKey(2)})
    assert not np.allclose(np.asarray(out_a2), np.asarray(out_a))


def test_ffhq1024_duplex_compiles():
    """The v4-32 flagship preset (BASELINE.json config #5) must trace AND
    XLA-compile end-to-end at batch 1 (VERDICT r1 item 6).  Locks the param
    count and the forward workspace.  HBM headroom for the full TRAIN step
    is measured separately (PERF.md §2: g_step_pl needs ~16.9 GiB temp at
    batch 8 → fits v4's 32 GiB with ~1.8× margin, batch 4 on v5e)."""
    from gansformer_tpu.models.generator import Generator

    cfg = get_preset("ffhq1024-duplex")
    G = Generator(cfg.model)
    z = jnp.zeros((1, cfg.model.num_ws, cfg.model.latent_dim), jnp.float32)
    params = jax.eval_shape(
        lambda k: G.init({"params": k, "noise": k}, z), jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(params))
    assert 20e6 < n_params < 80e6, f"suspicious param count {n_params}"

    def fwd(p, z):
        ws = G.apply(p, z, method=Generator.map)
        return G.apply(p, ws, rngs={"noise": jax.random.PRNGKey(1)},
                       method=Generator.synthesize)

    compiled = jax.jit(fwd).lower(params, z).compile()
    # Output aval via eval_shape (version-safe; `Compiled` has no out_avals
    # attribute in the installed JAX — VERDICT r2 item 2).
    out_shape = jax.eval_shape(fwd, params, z)
    assert tuple(out_shape.shape) == (1, 1024, 1024, 3)
    temp = compiled.memory_analysis().temp_size_in_bytes
    assert temp < 2 * 1024**3, f"fwd workspace blew up: {temp/1e9:.1f} GB"


def test_conditional_generator_and_discriminator():
    """Label path end-to-end (VERDICT r2 item 7): the label changes G's
    output and D's logit; D is a projection head over embed(label)."""
    import dataclasses

    cfg = dataclasses.replace(TINY, label_dim=5)
    g = Generator(cfg)
    z = _z(cfg)
    lab1 = jnp.eye(5)[jnp.array([0, 1])]
    lab2 = jnp.eye(5)[jnp.array([2, 3])]
    params = g.init({"params": jax.random.PRNGKey(0),
                     "noise": jax.random.PRNGKey(1)}, z, label=lab1)
    img1 = g.apply(params, z, label=lab1, rngs={"noise": jax.random.PRNGKey(2)})
    img2 = g.apply(params, z, label=lab2, rngs={"noise": jax.random.PRNGKey(2)})
    assert img1.shape == (2, 32, 32, 3)
    assert not np.allclose(np.asarray(img1), np.asarray(img2))
    # unconditional call must fail loudly, not silently ignore the label
    with pytest.raises(ValueError, match="label"):
        g.apply(params, z, rngs={"noise": jax.random.PRNGKey(2)})

    d = Discriminator(cfg)
    dp = d.init(jax.random.PRNGKey(0), img1, lab1)
    s1 = d.apply(dp, img1, lab1)
    s2 = d.apply(dp, img1, lab2)
    assert s1.shape == (2, 1)
    assert not np.allclose(np.asarray(s1), np.asarray(s2))


def test_attention_probs_intermediates_and_overlay():
    """Attention blocks sow latent→region maps (the GANsformer paper's
    visualization); maps are row-stochastic over k and the overlay util
    renders them."""
    from gansformer_tpu.utils.image import attention_overlay

    net = SynthesisNetwork(TINY)
    ws = jnp.zeros((2, TINY.num_ws, TINY.w_dim))
    params = net.init({"params": jax.random.PRNGKey(0),
                       "noise": jax.random.PRNGKey(1)}, ws)
    img, aux = net.apply(params, ws, rngs={"noise": jax.random.PRNGKey(2)},
                         mutable=["intermediates"])
    inter = aux["intermediates"]
    for res in TINY.attn_resolutions():
        probs = np.asarray(inter[f"b{res}_attn"]["attn_probs"][0])
        assert probs.shape == (2, TINY.num_heads, res, res, TINY.components)
        np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-3)

    top = max(TINY.attn_resolutions())
    probs = np.asarray(inter[f"b{top}_attn"]["attn_probs"][0]).mean(axis=1)
    overlay = attention_overlay(np.asarray(img), probs)
    assert overlay.shape == (2, 32, 32, 3) and overlay.dtype == np.uint8

    # normal apply (no mutable) is unaffected
    img2 = net.apply(params, ws, rngs={"noise": jax.random.PRNGKey(2)})
    np.testing.assert_array_equal(np.asarray(img), np.asarray(img2))
