"""Replica-per-device serving tests (ISSUE 20): placement, routing,
fleet health, the autoscaler controller, and the fleet-aware telemetry
schema + healthcheck semantics.

The load-bearing contracts, each pinned here:

* the SAME seed+ψ request stream produces BIT-IDENTICAL images through
  1 replica and through N — replica placement never enters the rng
  path (per-row noise tags carry the request seed);
* the router walks past a non-accepting replica (tripped breaker /
  draining) instead of failing the fleet, and every routed request
  lands on the per-replica dispatch-share counter;
* the autoscaler scales OUT on sustained queue saturation and IN on
  idle collapse, under hysteresis and min/max bounds — driven through
  ``_autoscale_tick`` directly so the drill is deterministic;
* ``check_serve_metric_families`` requires the fleet families
  (scale counters, per-replica gauges, per-replica traffic WITH
  latency samples) whenever ``serve_replicas`` is exported;
* the jax-free fleet-liveness helpers: any-replica-alive, and
  dead-with-work = ALL dispatchers dead while ANY queue is non-empty
  (the ``gansformer-serve --healthcheck`` semantics).

Runs on the conftest's 8 virtual CPU devices."""

import numpy as np
import pytest


def _tiny_bundle():
    from gansformer_tpu.analysis.trace.entry_points import tiny_config
    from gansformer_tpu.serve import init_generator

    return init_generator(tiny_config("float32"))


@pytest.fixture(scope="module")
def bundle():
    return _tiny_bundle()


def _stream(rs, seeds, psis):
    tickets = [rs.submit(int(s), psi=float(p))
               for s, p in zip(seeds, psis)]
    return [np.asarray(t.result(timeout=120)) for t in tickets]


# -- determinism across placement --------------------------------------------

def test_one_vs_two_replica_streams_bit_identical(bundle):
    """THE determinism contract: same request stream, 1 vs 2 replicas,
    bit-identical images per request."""
    import jax

    from gansformer_tpu.serve import ReplicaSet

    if len(jax.local_devices()) < 2:
        pytest.skip("needs >= 2 local devices")
    seeds = [11, 12, 11, 13, 14, 12, 15, 16]
    psis = [0.7, 0.5, 1.0, 0.7, 0.8, 0.5, 0.7, 1.0]
    with ReplicaSet(bundle, buckets=(1, 2), manifest_dir=None,
                    replicas=1) as one:
        imgs1 = _stream(one, seeds, psis)
    with ReplicaSet(bundle, buckets=(1, 2), manifest_dir=None,
                    replicas=2) as two:
        assert two.n_active == 2
        imgs2 = _stream(two, seeds, psis)
    for a, b in zip(imgs1, imgs2):
        assert np.array_equal(a, b), \
            "image depends on replica placement — rng path leaked"


# -- routing -----------------------------------------------------------------

def test_router_skips_tripped_replica_and_counts_dispatch(bundle):
    import jax

    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import ReplicaSet, ServiceUnhealthy

    if len(jax.local_devices()) < 2:
        pytest.skip("needs >= 2 local devices")
    with ReplicaSet(bundle, buckets=(1, 2), manifest_dir=None,
                    replicas=2) as rs:
        r0, r1 = rs.active_replicas
        c1 = telemetry.counter("serve/replica1/requests_total").value
        with r0.service._cv:
            r0.service._tripped = True     # breaker tripped on member 0
        img = rs.submit(77).result(timeout=120)
        assert img is not None
        assert telemetry.counter(
            "serve/replica1/requests_total").value == c1 + 1
        hp = rs.health()
        assert hp["state"] == "ready", \
            "fleet health must follow the HEALTHIEST member"
        with r1.service._cv:
            r1.service._tripped = True
        with pytest.raises(ServiceUnhealthy):
            rs.submit(78)
        # un-trip so close() drains cleanly
        with r0.service._cv:
            r0.service._tripped = False
        with r1.service._cv:
            r1.service._tripped = False


# -- autoscaler --------------------------------------------------------------

def test_autoscaler_scales_out_on_saturation_then_in_on_idle(bundle):
    """Deterministic controller drill through ``_autoscale_tick``:
    sustained saturation (queue pinned at the bound behind a gated
    dispatcher) scales OUT; empty-queue idleness scales back IN to
    ``min_replicas``; every transition lands in the event log and on
    the scale counters."""
    import threading

    import jax

    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import Overloaded, ReplicaSet

    if len(jax.local_devices()) < 2:
        pytest.skip("needs >= 2 local devices")
    out0 = telemetry.counter("serve/scale_out_total").value
    in0 = telemetry.counter("serve/scale_in_total").value
    rs = ReplicaSet(bundle, buckets=(1, 2), manifest_dir=None,
                    replicas=1, min_replicas=1, max_replicas=2,
                    autoscale=False,       # tick driven by hand
                    scale_out_saturation=0.8, scale_out_ticks=1,
                    scale_in_fill=0.5, scale_in_ticks=1, cooldown_s=0.0,
                    service_kwargs=dict(max_fill_wait_ms=0.0,
                                        max_queue_depth=2))
    try:
        stop = threading.Event()

        def pressure():
            i = 0
            while not stop.is_set():
                try:
                    rs.submit(100 + (i % 8))
                except Overloaded:
                    pass
                i += 1

        th = threading.Thread(target=pressure, daemon=True)
        th.start()
        import time as _t

        now, scaled = 0.0, None
        deadline = _t.monotonic() + 120.0
        while _t.monotonic() < deadline:
            # yield between ticks — a tight tick loop can starve the
            # pressure thread of the GIL and sample an eternally-empty
            # queue (the controller thread sleeps its interval too)
            _t.sleep(0.01)
            now += 1.0
            if rs._autoscale_tick(now=now) == "out":
                scaled = "out"
                break
        stop.set()
        th.join(timeout=30)
        assert scaled == "out", "sustained saturation never scaled out"
        assert rs.n_active == 2
        assert telemetry.counter("serve/scale_out_total").value == out0 + 1
        # drain, then idle ticks must scale back in to min_replicas
        for r in rs.active_replicas:
            spins = 200
            while r.service.load() and spins:
                _t.sleep(0.05)
                spins -= 1
        scaled_in = None
        deadline = _t.monotonic() + 120.0
        while _t.monotonic() < deadline:
            _t.sleep(0.01)
            now += 1.0
            if rs._autoscale_tick(now=now) == "in":
                scaled_in = "in"
                break
        assert scaled_in == "in", "idle fleet never scaled back in"
        assert rs.n_active == 1
        assert telemetry.counter("serve/scale_in_total").value == in0 + 1
        kinds = [e["kind"] for e in rs.events]
        assert kinds.count("scale_out") == 1
        assert kinds.count("scale_in") == 1
        assert kinds.index("scale_out") < kinds.index("scale_in")
        # bounds hold: at min, further idle ticks are no-ops
        for _ in range(10):
            now += 1.0
            assert rs._autoscale_tick(now=now) != "in"
        assert rs.n_active == 1
    finally:
        rs.close(timeout=60)


# -- fleet telemetry schema + healthcheck ------------------------------------

def test_fleet_prom_passes_schema_and_healthcheck(bundle, tmp_path,
                                                  capsys):
    import jax

    from gansformer_tpu.analysis.telemetry_schema import (
        check_serve_metric_families)
    from gansformer_tpu.cli.serve import healthcheck
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import ReplicaSet

    if len(jax.local_devices()) < 2:
        pytest.skip("needs >= 2 local devices")
    with ReplicaSet(bundle, buckets=(1, 2), manifest_dir=None,
                    replicas=2) as rs:
        for s in (21, 22, 23, 24):
            rs.submit(s).result(timeout=120)
        rs.health()
        prom = str(tmp_path / "telemetry.prom")
        telemetry.get_registry().write_prom(prom)
    assert check_serve_metric_families(prom) == []
    # healthcheck grades the closed-but-clean fleet prom as ok
    telemetry.get_registry().write_prom(prom)
    assert healthcheck(str(tmp_path)) == 0
    out = capsys.readouterr().out
    import json

    rep = json.loads(out.strip().splitlines()[-1])
    assert rep["ok"] and "replicas" in rep
    assert rep["scale_out_total"] is not None


def test_fleet_liveness_helpers_are_value_level():
    """Pure-dict semantics (no jax, no files): any-replica-alive, and
    dead-with-work = ALL dispatchers dead AND any queue non-empty."""
    from gansformer_tpu.analysis.telemetry_schema import (
        serve_fleet_alive, serve_fleet_dead_with_work,
        serve_replica_ordinals)

    fleet = {"serve_replicas": 2.0,
             "serve_replica0_dispatcher_alive": 0.0,
             "serve_replica0_queue_depth_now": 3.0,
             "serve_replica1_dispatcher_alive": 1.0,
             "serve_replica1_queue_depth_now": 0.0}
    assert serve_replica_ordinals(fleet) == [0, 1]
    assert serve_fleet_alive(fleet)
    # one dead member with work is quarantine's problem, NOT fleet-dead
    assert not serve_fleet_dead_with_work(fleet)
    dead = dict(fleet, serve_replica1_dispatcher_alive=0.0)
    assert not serve_fleet_alive(dead)
    assert serve_fleet_dead_with_work(dead)
    idle_dead = dict(dead, serve_replica0_queue_depth_now=0.0)
    assert not serve_fleet_dead_with_work(idle_dead)
    # no per-replica families → falls back to the global gauges
    solo = {"serve_dispatcher_alive": 0.0, "serve_queue_depth_now": 2.0}
    assert serve_replica_ordinals(solo) == []
    assert not serve_fleet_alive(solo)
    assert serve_fleet_dead_with_work(solo)
