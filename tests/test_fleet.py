"""Fleet telemetry aggregation tests (obs/aggregate, ISSUE 16): the
declared merge semantics (counters sum, gauges max/min/spread, summary
members sum/min/max), step skew agreeing with ``check_heartbeats`` by
construction, restart asymmetry, the ``fleet.prom`` export passing the
prom + fleet-family lints, and the degradation contract — missing
process, stale heartbeat, torn prom, conflicting gauge timestamps all
yield a PARTIAL view with reasons, never an exception."""

import json
import os

import pytest

from gansformer_tpu.analysis.telemetry_schema import (
    check_fleet_metric_families, check_prom)
from gansformer_tpu.obs.aggregate import (
    aggregate_fleet, fleet_prom_text, write_fleet)
from gansformer_tpu.obs.heartbeat import check_heartbeats

NOW = 1_000_000.0

PROM_P0 = """\
# TYPE serve_requests_total counter
serve_requests_total 100.0
# TYPE device_mfu gauge
device_mfu 0.30
# TYPE data_wait_ms summary
data_wait_ms_count 10.0
data_wait_ms_sum 50.0
data_wait_ms_min 1.0
data_wait_ms_max 9.0
"""

PROM_P1 = """\
# TYPE serve_requests_total counter
serve_requests_total 40.0
# TYPE device_mfu gauge
device_mfu 0.22
# TYPE data_wait_ms summary
data_wait_ms_count 4.0
data_wait_ms_sum 30.0
data_wait_ms_min 0.5
data_wait_ms_max 20.0
"""


def write_hb(d, idx, *, time=NOW - 5.0, step=4000):
    with open(os.path.join(d, f"heartbeat-p{idx}.json"), "w") as f:
        json.dump({"process": idx, "pid": 1, "host": "h", "time": time,
                   "step": step, "kimg": step / 1000}, f)


def shared_dir(tmp_path, name="run"):
    d = tmp_path / name
    d.mkdir()
    write_hb(d, 0, step=4000)
    write_hb(d, 1, step=3800)
    (d / "telemetry-p0.prom").write_text(PROM_P0)
    (d / "telemetry-p1.prom").write_text(PROM_P1)
    return str(d)


# --- merge semantics --------------------------------------------------------

def test_merge_semantics_shared_dir(tmp_path):
    d = shared_dir(tmp_path)
    fleet = aggregate_fleet(d, expected=2, now=NOW)
    assert not fleet["partial"], fleet["partial_reasons"]
    assert fleet["reporting"] == [0, 1]
    assert fleet["prom_reporting"] == [0, 1]
    # counters SUM
    assert fleet["counters"]["serve_requests_total"] == 140.0
    # gauges → max/min/spread with per-process provenance
    mfu = fleet["gauges"]["device_mfu"]
    assert mfu["max"] == 0.30 and mfu["min"] == 0.22
    assert mfu["spread"] == pytest.approx(0.08)
    assert mfu["per_process"] == {"0": 0.30, "1": 0.22}
    assert fleet["mfu_spread"] == pytest.approx(0.08)
    # summaries: count/sum SUM, min MIN, max MAX — quantiles never invented
    s = fleet["histograms"]["data_wait_ms"]
    assert s == {"count": 14.0, "sum": 80.0, "min": 0.5, "max": 20.0}
    # step skew is check_heartbeats' OWN number — same computation,
    # cannot disagree with the heartbeats CLI / doctor verdict
    hb = check_heartbeats(d, max_age_s=1e18, expected=[0, 1], now=NOW)
    assert fleet["step_skew"] == hb["step_skew"] == 200
    assert fleet["steps"] == {"0": 4000, "1": 3800}


def test_fleet_prom_passes_both_lints(tmp_path):
    d = shared_dir(tmp_path)
    fleet = aggregate_fleet(d, expected=2, now=NOW)
    _, prom_path = write_fleet(fleet, str(tmp_path / "fleet"))
    assert check_prom(prom_path) == []
    assert check_fleet_metric_families(prom_path) == []
    text = open(prom_path).read()
    # the partial marker is the FIRST sample — a reader can't miss it
    assert text.splitlines()[1] == "fleet_partial 0.0"
    assert "serve_requests_total 140.0" in text
    assert "device_mfu_spread" in text
    assert "data_wait_ms_count 14.0" in text


def test_list_of_dirs_mode_and_restart_asymmetry(tmp_path):
    dirs = []
    for i, (prom, restarts) in enumerate(((PROM_P0, 3), (PROM_P1, 0))):
        d = tmp_path / f"p{i}"
        d.mkdir()
        write_hb(d, i, step=1000 + i)
        (d / "telemetry.prom").write_text(prom)
        with open(d / "supervisor_events.jsonl", "w") as f:
            f.write(json.dumps({"kind": "start", "time": NOW,
                                "pid": 1}) + "\n")
            for _ in range(restarts):
                f.write(json.dumps({"kind": "restart", "time": NOW,
                                    "pid": 1}) + "\n")
        dirs.append(str(d))
    fleet = aggregate_fleet(dirs, expected=2, now=NOW)
    assert not fleet["partial"], fleet["partial_reasons"]
    assert fleet["counters"]["serve_requests_total"] == 140.0
    assert fleet["step_skew"] == 1
    # restarts clustered on one host: total AND asymmetry are visible
    assert fleet["restarts_total"] == 3
    assert fleet["restart_spread"] == 3
    _, prom_path = write_fleet(fleet, str(tmp_path / "fleet"))
    assert "fleet_restart_spread 3.0" in open(prom_path).read()
    assert check_prom(prom_path) == []


# --- degradation contract: partial, with reasons, never a raise -------------

def test_missing_process_degrades_to_partial(tmp_path):
    d = shared_dir(tmp_path)
    fleet = aggregate_fleet(d, expected=3, now=NOW)
    assert fleet["partial"]
    assert fleet["missing"] == [2]
    assert any("process 2 missing" in r for r in fleet["partial_reasons"])
    # merged numbers still present — partial degrades, it doesn't empty
    assert fleet["counters"]["serve_requests_total"] == 140.0
    _, prom_path = write_fleet(fleet, str(tmp_path / "fleet"))
    text = open(prom_path).read()
    assert "fleet_partial 1.0" in text
    assert "fleet_processes_missing 1.0" in text
    assert check_fleet_metric_families(prom_path) == []


def test_stale_heartbeat_degrades_to_partial(tmp_path):
    d = shared_dir(tmp_path)
    write_hb(d, 1, time=NOW - 500.0, step=3800)
    fleet = aggregate_fleet(d, expected=2, max_age_s=120.0, now=NOW)
    assert fleet["partial"]
    assert fleet["stale"] == [1]
    assert any("stale" in r for r in fleet["partial_reasons"])
    assert fleet["heartbeat_age_max_s"] == pytest.approx(500.0)


def test_torn_prom_degrades_but_still_merges(tmp_path):
    d = shared_dir(tmp_path)
    with open(os.path.join(d, "telemetry-p1.prom"), "w") as f:
        f.write("# TYPE serve_requests_total counter\n"
                "serve_requests_total 40.0\n"
                "device_mfu 0.22 extra garbage tokens\n")   # torn line
    fleet = aggregate_fleet(d, expected=2, now=NOW)
    assert fleet["partial"]
    assert any("partially-written prom" in r
               for r in fleet["partial_reasons"])
    assert fleet["processes"]["1"]["prom_issues"] == 1
    # the parsable lines of the torn file still contribute
    assert fleet["counters"]["serve_requests_total"] == 140.0


def test_conflicting_gauge_timestamps_flag_the_merge(tmp_path):
    d = shared_dir(tmp_path)
    write_hb(d, 1, time=NOW - 400.0, step=3800)   # artifacts 395s apart
    fleet = aggregate_fleet(d, expected=2, now=NOW, gauge_skew_s=300.0)
    assert fleet["partial"] and fleet["gauge_ts_conflict"]
    assert any("not simultaneous" in r for r in fleet["partial_reasons"])
    _, prom_path = write_fleet(fleet, str(tmp_path / "fleet"))
    assert "fleet_gauge_ts_conflict 1.0" in open(prom_path).read()
    # within the skew bound the same layout is NOT flagged
    write_hb(d, 1, time=NOW - 100.0, step=3800)
    ok = aggregate_fleet(d, expected=2, now=NOW, gauge_skew_s=300.0)
    assert not ok["gauge_ts_conflict"]


def test_empty_dir_never_raises(tmp_path):
    d = tmp_path / "empty"
    d.mkdir()
    fleet = aggregate_fleet(str(d), now=NOW)
    assert fleet["partial"]
    assert any("no heartbeat" in r for r in fleet["partial_reasons"])
    # the export is still a valid, lintable artifact
    _, prom_path = write_fleet(fleet, str(tmp_path / "fleet"))
    assert check_prom(prom_path) == []
    assert check_fleet_metric_families(prom_path) == []


def test_single_writer_layout_attributes_prom_to_p0(tmp_path):
    """The train loop's layout: one telemetry.prom (process 0 owns it),
    per-process heartbeats.  p1 having no prom is the DESIGN, not a
    partial view."""
    d = tmp_path / "run"
    d.mkdir()
    write_hb(d, 0)
    write_hb(d, 1)
    (d / "telemetry.prom").write_text(PROM_P0)
    fleet = aggregate_fleet(str(d), expected=2, now=NOW)
    assert not fleet["partial"], fleet["partial_reasons"]
    assert fleet["prom_reporting"] == [0]
    assert fleet["counters"]["serve_requests_total"] == 100.0
    assert fleet["processes"]["0"]["prom"] == "telemetry.prom"
    assert fleet["processes"]["1"]["prom"] is None


def test_cli_fleet_writes_artifacts(tmp_path, capsys):
    from gansformer_tpu.cli.telemetry import main as cli_main

    d = shared_dir(tmp_path)
    out = tmp_path / "out"
    cli_main(["fleet", d, "--expected", "2", "--out-dir", str(out)])
    assert "wrote" in capsys.readouterr().out
    assert (out / "fleet.json").exists() and (out / "fleet.prom").exists()
    fleet = json.load(open(out / "fleet.json"))
    assert fleet["counters"]["serve_requests_total"] == 140.0
