"""Fixture-driven tests for each graftlint rule (ISSUE 3): every rule
has at least one case proving it FIRES on broken code and one proving
it stays QUIET on correct code, plus suppression and baseline handling
exercised over the same fixtures."""

import ast
import os

import pytest

from gansformer_tpu.analysis import get_rule, lint_source
from gansformer_tpu.analysis.baseline import Baseline
from gansformer_tpu.analysis.jit_regions import JitIndex

# --- fixtures: (rule id, fires-source, quiet-source) ------------------------

HOST_SYNC_BAD = """
import jax

@jax.jit
def f(x):
    y = x + 1
    v = float(y)
    print("tracing", v)
    return jax.device_get(y)
"""

HOST_SYNC_OK = """
import jax

LR = "0.1"

@jax.jit
def f(x):
    n = int(x.shape[0])          # static shape: legal under a trace
    return x * float(LR) / n     # trace-time constant, not a tracer

def host_side(x):
    # not a jit region: syncs are this function's job
    print(float(jax.device_get(x).sum()))
"""

DONATION_BAD = """
import jax

def _step(s, b):
    return s + b, s

step = jax.jit(_step, donate_argnums=(0,))

def run(state, batch):
    new, aux = step(state, batch)
    return state.sum() + new      # read of the donated buffer
"""

DONATION_OK = """
import jax

def _step(s, b):
    return s + b, s

step = jax.jit(_step, donate_argnums=(0,))

def run(state, batch):
    state, aux = step(state, batch)   # rebinds over the donated name
    return state.sum()
"""

RNG_BAD = """
import jax

def f(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))
    return a + b
"""

RNG_OK = """
import jax

def f(seed):
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (2,))
    b = jax.random.uniform(k2, (2,))
    return a + b
"""

HOT_LOOP_BAD = """
def _train(x):
    while x < 10:
        jax.block_until_ready(x)
        y = jax.device_get(x)
        with span("tick_fetch"):
            z = jax.device_get(x)      # sanctioned
        x += 1
"""

HOT_LOOP_OK = """
def _train(x):
    while x < 10:
        with span("tick_fetch"):
            jax.block_until_ready(x)
            v = float(jax.device_get(x))
        x += 1
"""

THREAD_BAD = """
import threading

_CACHE = {}

class Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run)

    def _run(self):
        _CACHE["latest"] = 1
"""

THREAD_OK = """
import threading

_CACHE = {}

class Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            _CACHE["latest"] = 1
"""

TELEMETRY_BAD = """
from gansformer_tpu.obs import registry as telemetry

c = telemetry.counter("BadName")
"""

TELEMETRY_OK = """
from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.registry import gauge

c = telemetry.counter("data/batches_total")
g = gauge("ckpt/write_ms")

def per_metric(name):
    return telemetry.gauge(f"metric/{name}/duration_s")
"""

RETRACE_STATIC_BAD = """
import jax

_SCHEDULE = {"lr": 0.1}

def _apply(x, lr):
    return x * lr * _SCHEDULE["lr"]

step = jax.jit(_apply, static_argnums=(1,))

def tick(x, i):
    _SCHEDULE["lr"] = 0.1 / (i + 1)       # mutated after trace
    return step(x, [0.1, 0.2])            # unhashable static arg
"""

RETRACE_STATIC_OK = """
import jax

_ACTIVATIONS = {"relu": 1}     # never mutated: a de-facto constant

def _apply(x, lr):
    return x * lr * _ACTIVATIONS["relu"]

step = jax.jit(_apply, static_argnums=(1,))

def tick(x):
    return step(x, 0.1)        # hashable scalar static
"""

CASES = [
    ("host-sync-in-jit", HOST_SYNC_BAD, HOST_SYNC_OK),
    ("donation-after-use", DONATION_BAD, DONATION_OK),
    ("rng-key-reuse", RNG_BAD, RNG_OK),
    ("hot-loop-sync", HOT_LOOP_BAD, HOT_LOOP_OK),
    ("unguarded-shared-attribute", THREAD_BAD, THREAD_OK),
    ("telemetry-name-convention", TELEMETRY_BAD, TELEMETRY_OK),
    ("retrace-static", RETRACE_STATIC_BAD, RETRACE_STATIC_OK),
]


def run_rule(rule_id, source):
    return lint_source(source, path="fixture.py", rules=[get_rule(rule_id)])


# --- positive / negative ----------------------------------------------------

@pytest.mark.parametrize("rule_id,bad,ok", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_fires_on_bad_code(rule_id, bad, ok):
    findings = run_rule(rule_id, bad)
    assert findings, f"{rule_id} produced no findings on its bad fixture"
    assert all(f.rule == rule_id for f in findings)
    assert all(f.new for f in findings)
    assert all(f.line > 0 for f in findings)


@pytest.mark.parametrize("rule_id,bad,ok", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_quiet_on_good_code(rule_id, bad, ok):
    findings = run_rule(rule_id, ok)
    assert findings == [], \
        f"{rule_id} false-positived: {[f.message for f in findings]}"


# --- suppression ------------------------------------------------------------

@pytest.mark.parametrize("rule_id,bad,ok", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_suppressed_inline(rule_id, bad, ok):
    findings = run_rule(rule_id, bad)
    lines = bad.splitlines()
    for f in findings:
        lines[f.line - 1] += f"  # graftlint: disable={rule_id} — test"
    suppressed = run_rule(rule_id, "\n".join(lines))
    assert len(suppressed) == len(findings)
    assert all(f.suppressed and not f.new for f in suppressed)


def test_suppress_file_level_and_all():
    src = RNG_BAD + "\n# graftlint: disable-file=rng-key-reuse\n"
    assert all(f.suppressed for f in run_rule("rng-key-reuse", src))
    lines = RNG_BAD.splitlines()
    bad = run_rule("rng-key-reuse", RNG_BAD)
    lines[bad[0].line - 1] += "  # graftlint: disable=all"
    assert all(f.suppressed
               for f in run_rule("rng-key-reuse", "\n".join(lines)))


# --- baseline ---------------------------------------------------------------

@pytest.mark.parametrize("rule_id,bad,ok", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_baselined(rule_id, bad, ok, tmp_path):
    src_path = tmp_path / "fixture.py"
    src_path.write_text(bad)
    findings = lint_source(bad, path=str(src_path),
                           rules=[get_rule(rule_id)])
    assert findings
    lines = bad.splitlines()

    def line_text(f):
        return lines[f.line - 1]

    bl_path = tmp_path / "baseline.json"
    Baseline.write(str(bl_path), findings, line_text)
    fresh = lint_source(bad, path=str(src_path), rules=[get_rule(rule_id)])
    Baseline.load(str(bl_path)).apply(fresh, line_text)
    assert all(f.baselined and not f.new for f in fresh)


# --- rule-specific edge cases ----------------------------------------------

def test_hot_loop_covers_serve_dispatch_loop():
    """ISSUE 10: the serving dispatch loop joins the hot-loop-sync
    discipline — its sanctioned span is ``serve_fetch`` (NOT the train
    loop's ``tick_fetch``), and syncs outside it are findings."""
    bad = """
def _serve_dispatch(self):
    while True:
        ws = jax.device_get(dev)
        with span("serve_fetch"):
            imgs = jax.device_get(out)      # sanctioned
        with span("tick_fetch"):
            other = jax.device_get(out)     # WRONG loop's span
"""
    findings = run_rule("hot-loop-sync", bad)
    assert len(findings) == 2
    assert all("serve_fetch" in f.message for f in findings)
    ok = """
def _serve_dispatch(self):
    while True:
        with span("serve_fetch"):
            ws = jax.device_get(dev)
"""
    assert run_rule("hot-loop-sync", ok) == []


def test_hot_loop_covers_trace_emitter_bodies():
    """ISSUE 16: the request-trace emitters run per ticket inside the
    serve dispatch loop but live outside its ``while`` body — the rule
    scans their FULL bodies (no loop required, no span sanctioned),
    gated on the reqtrace module path so an unrelated ``begin``
    elsewhere stays out of scope."""
    bad = """
def event(self, rid, kind, **attrs):
    snap = jax.device_get(dev)
    with span("serve_fetch"):
        more = jax.block_until_ready(out)   # no span sanctions an emitter
"""
    findings = lint_source(bad, path="gansformer_tpu/obs/reqtrace.py",
                           rules=[get_rule("hot-loop-sync")])
    assert len(findings) == 2
    assert all("trace emitter" in f.message for f in findings)
    # the same source OUTSIDE the reqtrace module is not an emitter
    assert lint_source(bad, path="gansformer_tpu/serve/cache.py",
                       rules=[get_rule("hot-loop-sync")]) == []
    # non-emitter functions in the reqtrace module stay unscanned
    # (read-side helpers may legitimately block on IO, not the device)
    other = """
def read_requests(path):
    rows = jax.device_get(dev)
"""
    assert lint_source(other, path="gansformer_tpu/obs/reqtrace.py",
                       rules=[get_rule("hot-loop-sync")]) == []
    # and the REAL emitter bodies are clean — the acceptance property
    real = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "gansformer_tpu", "obs", "reqtrace.py")
    with open(real) as f:
        assert lint_source(f.read(), path=real,
                           rules=[get_rule("hot-loop-sync")]) == []


def test_host_sync_item_and_np_asarray_taint():
    src = """
import jax
import numpy as np

@jax.jit
def f(x):
    m = x.mean()
    a = m.item()
    b = np.asarray(x)
    return a, b
"""
    msgs = [f.message for f in run_rule("host-sync-in-jit", src)]
    assert any(".item()" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)


def test_host_sync_untainted_conversions_pass():
    # float()/int() on config values at trace time are legal
    src = """
import jax

LR = "0.1"

@jax.jit
def f(x):
    return x * float(LR) + int("2")
"""
    assert run_rule("host-sync-in-jit", src) == []


def test_jit_region_transitive_propagation():
    src = """
import jax
import functools

def helper(x):
    return float(x)          # reached from the jitted fn

def _step(x):
    return helper(x) + 1

step = jax.jit(functools.partial(_step, ), donate_argnums=(0,))
"""
    findings = run_rule("host-sync-in-jit", src)
    assert any(f.line == 6 for f in findings), findings


def test_jit_region_lambda_wrap():
    """Regression (ISSUE 4 satellite): ``step = jax.jit(lambda s, b:
    _step(s, b))`` must pull ``_step`` into the jit region — the
    resolver previously only covered decorator/call-wrap/partial."""
    src = """
import jax

def _step(s, b):
    print("silent")          # host sync — must be flagged
    return s + b

step = jax.jit(lambda s, b: _step(s, b), donate_argnums=(0,))
"""
    findings = run_rule("host-sync-in-jit", src)
    assert any(f.line == 5 for f in findings), findings
    # donation through the lambda wrap resolves to the assigned name too
    tree = ast.parse(src)
    assert JitIndex(tree).donating.get("step") == (0,)
    # lambda parameters don't leak as region references
    src_shadow = """
import jax

def helper(x):
    return float(x)

step = jax.jit(lambda helper: helper + 1)   # param shadows the def
"""
    assert run_rule("host-sync-in-jit", src_shadow) == []


def test_jit_index_resolves_real_steps_module():
    # the shared resolver marks the real train-step functions in-region
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "gansformer_tpu", "train", "steps.py")
    with open(path) as f:
        tree = ast.parse(f.read())
    idx = JitIndex(tree)
    names = {n.name for n in ast.walk(tree)
             if isinstance(n, ast.FunctionDef) and idx.is_jit(n)}
    for expected in ("_d_step", "_g_step", "_cycle", "_sample",
                     "_ppl_pairs", "g_forward", "d_loss_fn", "g_loss_fn"):
        assert expected in names, f"{expected} not resolved as jit region"
    # the host-side orchestrators must NOT be in-region
    assert "make_train_steps" not in names
    assert "make_metric_samplers" not in names


def test_rng_reuse_in_loop_and_exclusive_branches():
    loop_src = """
import jax

def g(key, n):
    out = []
    for _ in range(n):
        out.append(jax.random.normal(key, (2,)))
    return out
"""
    assert run_rule("rng-key-reuse", loop_src), \
        "cross-iteration reuse not caught"
    branch_src = """
import jax

def h(key, flag):
    if flag:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key, (2,))
"""
    assert run_rule("rng-key-reuse", branch_src) == [], \
        "exclusive branches wrongly flagged"


def test_rng_reuse_counts_condition_expressions():
    # a consumption inside an if/while TEST is a consumption like any other
    if_src = """
import jax

def f(key):
    if jax.random.bernoulli(key):
        pass
    return jax.random.normal(key, (2,))
"""
    assert run_rule("rng-key-reuse", if_src), \
        "consumption in an if-test not counted"
    while_src = """
import jax

def g(key):
    while jax.random.bernoulli(key):
        pass
"""
    assert run_rule("rng-key-reuse", while_src), \
        "cross-iteration consumption in a while-test not counted"


def test_rng_reuse_ignores_stateful_numpy_and_str_split():
    src = """
import jax
import numpy as np

def f(line):
    rng = np.random.RandomState(0)
    a = rng.randn(2)
    b = rng.randn(2)
    parts = line.split()
    name, value = parts
    return a, b, float(value), name
"""
    assert run_rule("rng-key-reuse", src) == []


def test_donation_dict_splat_resolution():
    src = """
import jax

def _step(s):
    return s

donate_state = dict(donate_argnums=(0,))
step = jax.jit(_step, **donate_state)

def run(state):
    out = step(state)
    return state + out
"""
    findings = run_rule("donation-after-use", src)
    assert len(findings) == 1 and "state" in findings[0].message


def test_shared_state_bare_function_target():
    # run through the RETIRED alias on purpose: thread-shared-state
    # must keep resolving to unguarded-shared-attribute (ISSUE 18)
    src = """
import threading

_LOG = []

def _worker():
    _LOG.append("x")

t = threading.Thread(target=_worker)
"""
    findings = run_rule("thread-shared-state", src)
    assert len(findings) == 1 and "_LOG" in findings[0].message
    assert findings[0].rule == "unguarded-shared-attribute"


def test_telemetry_fstring_fragments_checked():
    src = """
from gansformer_tpu.obs import registry as telemetry

def f(name):
    return telemetry.gauge(f"Metric-{name}/Duration")
"""
    findings = run_rule("telemetry-name-convention", src)
    assert len(findings) == 1
