"""Shared numeric tolerance classes for the parity tests (ISSUE 19).

One home for the constants test_pallas_conv.py and
test_device_prefetch.py used to repeat inline, keyed by the dtype the
compared pipelines compute in.  The classes import the machine-epsilon
table from ``analysis/numerics/dtypes`` so the graftnum lint and the
tests can never disagree about what a dtype can resolve — the asserts
at the bottom pin each class sensibly above its dtype's epsilon.

* ``FWD``            — two same-dtype pipelines of the SAME math
  (Pallas kernel vs XLA composite, both accumulating fp32): near-bit,
  a few ulps of headroom.
* ``GRAD``           — one order looser: backward passes chain more
  rounding steps, and a float64 oracle comparison lands in the same
  band (the fp32 side carries ~eps_f32 of per-op rounding either way).
* ``TRAIN_REORDER``  — first-tick loss means across backends, same
  seed: only chained-update fp reorder separates the runs, but a full
  tick of D+G updates amplifies it (the ISSUE 9/14 twin-test class).
* ``SCALAR_REPLAY_ABS`` — host-replayed tick scalars of the SAME
  program/seed under a different overlap schedule: equal up to the
  fp32 printing round-trip.
"""

from gansformer_tpu.analysis.numerics.dtypes import MACHINE_EPS

FWD = {"float32": dict(atol=1e-6, rtol=1e-6)}

GRAD = {"float32": dict(atol=1e-5, rtol=1e-5)}

TRAIN_REORDER = {"float32": dict(atol=5e-2, rtol=5e-2),
                 "bfloat16": dict(atol=0.2, rtol=0.2)}

SCALAR_REPLAY_ABS = 1e-6

# The classes must sit above the machine epsilon of the dtype they
# grade — a tolerance below it would be asking for agreement the
# arithmetic cannot express (exactly the eps-dtype-mismatch rule's
# complaint about sub-epsilon guards).
assert FWD["float32"]["atol"] > MACHINE_EPS["float32"]
assert GRAD["float32"]["atol"] > MACHINE_EPS["float32"]
assert TRAIN_REORDER["float32"]["atol"] > MACHINE_EPS["float32"]
assert TRAIN_REORDER["bfloat16"]["atol"] > MACHINE_EPS["bfloat16"]
assert SCALAR_REPLAY_ABS > MACHINE_EPS["float32"]
