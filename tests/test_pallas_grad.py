"""ISSUE 9 acceptance: the Pallas bipartite-attention kernels are
differentiable — to second order — and training-grade.

Interpret-mode parity on CPU against the jnp oracle
(``ops.attention.multihead_attention``) for both directions: forward,
first-order grads (dq/dk/dv), and R1/PL-shaped double-backwards, in f32
and bf16; plus the wiring contracts (bwd kernels actually on the reverse
path, forward-mode rejection, generator-level grad parity) and the
training-path parity of all four step programs under
``attention_backend='pallas'``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu import ops
from gansformer_tpu.ops.pallas_attention import (
    grid_to_latent_attention,
    latent_to_grid_attention,
    multihead_attention_pallas,
)

# (batch, Lq, Lk, D, Dv, block_n): covers both directions, the padded
# n-block tail (g2l) and the masked flash tail (l2g — n=100 over
# block_n=32 is already multi-block with a masked tail).  The "-odd"
# member (a second non-divisible-n geometry) rides the slow sweep:
# interpret-mode grad traces cost seconds per case and the main l2g
# member already exercises the mask in tier-1.
CASES = {
    "grid_to_latent": (2, 100, 9, 16, 24, 32),
    "latent_to_grid": (2, 9, 100, 16, 24, 32),
    "latent_to_grid-odd": (1, 5, 257, 8, 8, 64),
}
ODD_SLOW = [
    "grid_to_latent", "latent_to_grid",
    pytest.param("latent_to_grid-odd", marks=pytest.mark.slow),
]


def _inputs(rng, case, dtype=jnp.float32):
    b, lq, lk, d, dv, bn = CASES[case]
    q = jnp.asarray(rng.randn(b, lq, d), dtype)
    k = jnp.asarray(rng.randn(b, lk, d), dtype)
    v = jnp.asarray(rng.randn(b, lk, dv), dtype)
    fn = (grid_to_latent_attention if lq >= lk else latent_to_grid_attention)
    att = lambda q, k, v: fn(q, k, v, block_n=bn, interpret=True)
    oracle = lambda q, k, v: ops.multihead_attention(q, k, v, 1)[0]
    return q, k, v, att, oracle


@pytest.mark.parametrize("case", ODD_SLOW)
def test_first_order_grads_match_oracle(rng, case):
    """dq/dk/dv from the backward kernels vs the differentiated jnp
    composite (f32, per-dtype tolerance)."""
    q, k, v, att, oracle = _inputs(rng, case)

    def loss(f):
        def fn(q, k, v):
            o = f(q, k, v)     # nonlinear in o, so dL/do varies per row
            return jnp.sum(o * jnp.cos(o))
        return fn

    got = jax.grad(loss(att), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "dq dk dv".split()):
        assert g.dtype == w.dtype, name
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=5e-4, rtol=1e-4, err_msg=name)


@pytest.mark.parametrize("case", ["grid_to_latent", "latent_to_grid"])
def test_first_order_grads_bf16(rng, case):
    """bf16 in/out: cotangents keep the primal dtypes and stay within
    bf16 round-off of the oracle (stats are fp32 in both paths)."""
    q, k, v, att, oracle = _inputs(rng, case, jnp.bfloat16)

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32) ** 2)

    got = jax.grad(loss(att), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "dq dk dv".split()):
        assert g.dtype == jnp.bfloat16, name
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w, np.float32),
            atol=0.2, rtol=0.1, err_msg=name)


@pytest.mark.parametrize("case", ODD_SLOW)
def test_r1_shaped_double_backward(rng, case):
    """The R1 transform shape (losses/gan.py r1_penalty): grad w.r.t. a
    parameter of ‖grad w.r.t. the INPUT‖² — reverse-over-reverse through
    the kernels must match the oracle."""
    q, k, v, att, oracle = _inputs(rng, case)

    def r1(w, f):
        gq = jax.grad(lambda q: jnp.sum(f(q * w, k, v)))(q)
        return jnp.sum(gq ** 2)

    got = jax.grad(lambda w: r1(w, att))(1.1)
    want = jax.grad(lambda w: r1(w, oracle))(1.1)
    np.testing.assert_allclose(float(got), float(want), atol=1e-3,
                               rtol=1e-3)


@pytest.mark.slow  # the R1 sweep above is the tier-1 second-order gate
@pytest.mark.parametrize("case", ["grid_to_latent", "latent_to_grid"])
def test_pl_shaped_hvp(rng, case):
    """The PL transform shape (losses/gan.py path_length_penalty): the
    params scale the k/v projections and the HVP flows through the inner
    input-grad — jitted, like the real g_step_pl program."""
    q, k, v, att, oracle = _inputs(rng, case)

    def pl(w, f):
        gq = jax.grad(lambda q: jnp.sum(f(q, k * w, v * w)))(q)
        return jnp.sum(gq ** 2)

    got = jax.jit(jax.grad(lambda w: pl(w, att)))(0.9)
    want = jax.grad(lambda w: pl(w, oracle))(0.9)
    np.testing.assert_allclose(float(got), float(want), atol=1e-3,
                               rtol=1e-3)


def test_bwd_kernels_are_on_the_reverse_path(rng):
    """The first-order reverse path must RUN the backward kernels, not a
    transposed jnp tangent: the grad jaxpr carries ≥ 2 pallas_call sites
    (forward-stats + backward), where a glue-transposed rule would carry
    exactly the forward one."""
    q, k, v, att, _ = _inputs(rng, "grid_to_latent")
    jaxpr = str(jax.make_jaxpr(
        jax.grad(lambda q: jnp.sum(att(q, k, v))))(q))
    assert jaxpr.count("pallas_call") >= 2, jaxpr[:2000]


def test_forward_mode_is_rejected(rng):
    """Direct jax.jvp through the op is NOT supported (custom_vjp outer
    layer) — pinned so a future jvp-based loss reformulation fails loudly
    here instead of deep inside a trace.  R1/PL are reverse-mode
    formulations (losses/gan.py) and never hit this."""
    q, k, v, att, _ = _inputs(rng, "grid_to_latent")
    with pytest.raises(TypeError, match="custom_vjp"):
        jax.jvp(lambda q: att(q, k, v), (q,), (q,))


@pytest.mark.slow  # ~26 s: whole-generator trace + interpret execution
def test_generator_pallas_param_grads_match_xla(rng):
    """End-to-end first-order check: grads of a duplex generator loss
    w.r.t. EVERY parameter agree between the backends (head folding, both
    kernel directions, flax integration).  Slow: the op-level parity
    tests above are the tier-1 gate; this and the step-program tests
    below are the (slow) integration layer over the same kernels."""
    from gansformer_tpu.core.config import ModelConfig
    from gansformer_tpu.models.generator import Generator

    cfg = ModelConfig(resolution=16, components=2, latent_dim=16, w_dim=16,
                      mapping_dim=16, mapping_layers=2, fmap_base=64,
                      fmap_max=16, attention="duplex", attn_start_res=8,
                      attn_max_res=8)
    z = jnp.asarray(rng.randn(2, cfg.num_ws, cfg.latent_dim), jnp.float32)
    noise = jax.random.PRNGKey(3)
    G_xla = Generator(cfg)
    params = G_xla.init({"params": jax.random.PRNGKey(0), "noise": noise}, z)
    G_pl = Generator(dataclasses.replace(cfg, attention_backend="pallas"))

    def loss(G):
        return lambda p: jnp.mean(
            G.apply(p, z, rngs={"noise": noise}) ** 2)

    g_xla = jax.grad(loss(G_xla))(params)
    g_pl = jax.grad(loss(G_pl))(params)
    leaves_x = jax.tree_util.tree_leaves(g_xla)
    leaves_p = jax.tree_util.tree_leaves(g_pl)
    assert len(leaves_x) == len(leaves_p)
    for x, p in zip(leaves_x, leaves_p):
        np.testing.assert_allclose(np.asarray(x), np.asarray(p),
                                   atol=2e-5, rtol=2e-3)


# --------------------------------------------------------------------------
# Training path: all four step programs on the pallas backend
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def reg_step_pair():
    """The second-order SUPERSET step programs (d_step_r1, g_step_pl —
    each contains its plain sibling's whole graph plus the reg term) on
    both backends, same inputs/rng — compiled once, shared by the
    assertions below (slow-marked: ~25 s of second-order compiles).  The
    full four-program cadence (d, g, d_r1, g_pl through real ticks)
    rides the slow micro-train test; tracing all eight programs here
    would double the bill for the two branches the supersets already
    contain."""
    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps
    from tests.test_train import micro_cfg

    imgs_np = np.random.RandomState(0).randint(
        0, 255, (8, 16, 16, 3), dtype=np.uint8)
    rng = jax.random.PRNGKey(11)
    out = {}
    for backend in ("xla", "pallas"):
        cfg = micro_cfg(attention="duplex")
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, attention_backend=backend))
        cfg.validate()       # the relaxed rule: pallas is training-grade
        env = make_mesh(cfg.mesh)
        state = jax.device_put(create_train_state(cfg, jax.random.PRNGKey(0)),
                               env.replicated())
        fns = make_train_steps(cfg, env, batch_size=cfg.train.batch_size)
        imgs = jax.device_put(imgs_np, env.batch())
        with env.activate():
            r = jax.random.fold_in(rng, 0)
            state, d_aux = fns.d_step_r1(state, imgs,
                                         jax.random.fold_in(r, 0))
            state, g_aux = fns.g_step_pl(state, jax.random.fold_in(r, 1))
            jax.block_until_ready(state.step)
        out[backend] = {k: float(jax.device_get(v))
                        for k, v in {**d_aux, **g_aux}.items()}
    return out


@pytest.mark.slow  # the fixture compiles 4 second-order programs (~25 s)
def test_pallas_training_reg_steps_finite(reg_step_pair):
    """The lifted core/config.py restriction, exercised: the REAL
    second-order step programs (R1 grad-of-grad, PL HVP through
    synthesis) compile and produce finite losses on the pallas backend."""
    aux = reg_step_pair["pallas"]
    assert "Loss/D/r1" in aux and "Loss/G/pl" in aux
    for k, v in aux.items():
        assert np.isfinite(v), (k, v)


@pytest.mark.slow  # shares the reg_step_pair fixture
def test_pallas_training_losses_match_xla(reg_step_pair):
    """Losses of the second-order step programs agree across backends
    within fp-reorder tolerance — the backend changes the attention
    compute path, never the math."""
    ax, ap = reg_step_pair["xla"], reg_step_pair["pallas"]
    assert set(ax) == set(ap)
    for k in ax:
        np.testing.assert_allclose(ap[k], ax[k], atol=5e-3, rtol=5e-3,
                                   err_msg=k)


@pytest.mark.slow  # two micro train() runs (fresh second-order compiles)
def test_micro_train_run_pallas_vs_xla(tmp_path):
    """ISSUE 9 acceptance: a micro ``train()`` run with
    ``attention_backend='pallas'`` (interpret mode on CPU) completes with
    finite losses through full lazy-reg cadences, and its per-tick loss
    means agree with the xla backend within tolerance (25 iterations of
    chained updates amplify fp-reorder noise, hence the loose band)."""
    import json
    import os

    from gansformer_tpu.train.loop import train
    from tests.test_train import micro_cfg

    ticks = {}
    for backend in ("xla", "pallas"):
        cfg = micro_cfg(attention="duplex", batch=40)
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, attention_backend=backend))
        cfg.validate()
        d = str(tmp_path / backend)
        os.makedirs(d)
        train(cfg, d)
        with open(os.path.join(d, "stats.jsonl")) as f:
            rows = [json.loads(line) for line in f]
        assert rows, backend
        ticks[backend] = rows[-1]
    for key in ("Loss/D", "Loss/G", "Loss/D/r1", "Loss/G/pl",
                "Loss/scores/real", "Loss/scores/fake"):
        a, b = ticks["xla"][key], ticks["pallas"][key]
        assert np.isfinite(a) and np.isfinite(b), (key, a, b)
        np.testing.assert_allclose(b, a, atol=0.2, rtol=0.2, err_msg=key)
