"""Golden tests for the Inception checkpoint converter.

The FID north star (BASELINE.json:2) is only meaningful with calibrated
Inception weights (VERDICT round 1, missing item #1).  These tests prove the
converter + our Flax architecture reproduce a *published implementation*
(keras.applications.InceptionV3 — the same TF-slim architecture family as
the reference's pickled TF1 graph) numerically, using randomly-initialized
weights so they run airgapped: any pairing/transpose/BN-role mistake in the
converter produces order-1 errors, far outside the tolerance.
"""

import numpy as np
import pytest

from gansformer_tpu.metrics.convert_inception import (
    expected_keys, from_keras, from_torch_state_dict, ordered_convbn_paths,
    save_npz)
from gansformer_tpu.metrics.inception import (
    FeatureExtractor, load_params_npz, tree_from_flat)

keras = pytest.importorskip("keras")


@pytest.fixture(scope="module")
def keras_model():
    model = keras.applications.InceptionV3(
        weights=None, classifier_activation=None)
    # Randomize BN stats/offsets so a mean<->var<->beta role mix-up in the
    # converter cannot hide behind the zeros/ones defaults.
    rng = np.random.RandomState(0)
    for layer in model.layers:
        if isinstance(layer, keras.layers.BatchNormalization):
            beta, mean, var = layer.get_weights()
            layer.set_weights([
                rng.randn(*beta.shape).astype(np.float32) * 0.1,
                rng.randn(*mean.shape).astype(np.float32) * 0.1,
                rng.rand(*var.shape).astype(np.float32) * 0.5 + 0.75,
            ])
    return model


@pytest.fixture(scope="module")
def flat(keras_model):
    return from_keras(keras_model)


def test_conversion_is_complete(flat):
    assert set(flat) == set(expected_keys())


def test_forward_parity_vs_keras(keras_model, flat):
    """pool3 features and logits match keras on a fixed input."""
    rng = np.random.RandomState(1)
    x = (rng.rand(2, 299, 299, 3).astype(np.float32) * 2.0) - 1.0

    ref_model = keras.Model(
        keras_model.input,
        [keras_model.get_layer("avg_pool").output, keras_model.output])
    ref_pool, ref_logits = [np.asarray(t) for t in
                            ref_model(x, training=False)]

    ours = FeatureExtractor(tree_from_flat(flat))
    assert ours.calibrated
    pool, logits = ours(x)
    np.testing.assert_allclose(np.asarray(pool), ref_pool,
                               rtol=1e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(logits), ref_logits,
                               rtol=1e-3, atol=5e-3)


def test_npz_round_trip(flat, tmp_path):
    path = str(tmp_path / "inception.npz")
    save_npz(flat, path)
    tree = load_params_npz(path)
    ext = FeatureExtractor(tree)
    assert ext.calibrated
    flat_back = {}

    def walk(node, prefix):
        for k, v in node.items():
            if isinstance(v, dict):
                walk(v, prefix + k + "/")
            else:
                flat_back[prefix + k] = np.asarray(v)

    walk(tree, "")
    assert set(flat_back) == set(flat)
    for k in flat:
        np.testing.assert_array_equal(flat_back[k], flat[k])


def _torch_module_name(path: str) -> str:
    """Our module path → torchvision module path (inverse of converter)."""
    first, _, branch = path.partition("/")
    if not branch:
        return {"Conv2d_1a": "Conv2d_1a_3x3", "Conv2d_2a": "Conv2d_2a_3x3",
                "Conv2d_2b": "Conv2d_2b_3x3", "Conv2d_3b": "Conv2d_3b_1x1",
                "Conv2d_4a": "Conv2d_4a_3x3"}[first]
    torch_branch = ("branch_pool" if branch == "bpool"
                    else branch.replace("b", "branch", 1))
    return f"{first}.{torch_branch}"


def test_torch_layout_matches_keras_layout(flat):
    """A torchvision-named state_dict built from the keras weights converts
    to the identical flat dict (validates the structural name mapping and
    the OIHW->HWIO transpose without needing torchvision).  Affine BN scale
    gamma (torchvision's BasicConv2d) must fold exactly into kernel+mean."""
    rng = np.random.RandomState(2)
    sd, gammas = {}, {}
    for path in ordered_convbn_paths():
        mod = _torch_module_name(path)
        gamma = (rng.rand(flat[f"{path}/beta"].shape[0]).astype(np.float32)
                 * 0.5 + 0.75)
        gammas[path] = gamma
        sd[f"{mod}.conv.weight"] = flat[f"{path}/conv/kernel"].transpose(
            3, 2, 0, 1)
        sd[f"{mod}.bn.weight"] = gamma
        sd[f"{mod}.bn.bias"] = flat[f"{path}/beta"]
        sd[f"{mod}.bn.running_mean"] = flat[f"{path}/mean"]
        sd[f"{mod}.bn.running_var"] = flat[f"{path}/var"]
        sd[f"{mod}.bn.num_batches_tracked"] = np.zeros((), np.int64)
    sd["fc.weight"] = flat["fc/kernel"].T
    sd["fc.bias"] = flat["fc/bias"]
    sd["AuxLogits.conv0.conv.weight"] = np.zeros((1,), np.float32)  # skipped

    flat2 = from_torch_state_dict(sd)
    assert set(flat2) == set(flat)
    for path in ordered_convbn_paths():
        g = gammas[path]
        np.testing.assert_allclose(flat2[f"{path}/conv/kernel"],
                                   flat[f"{path}/conv/kernel"] * g, rtol=1e-6)
        np.testing.assert_allclose(flat2[f"{path}/mean"],
                                   flat[f"{path}/mean"] * g, rtol=1e-6)
        np.testing.assert_array_equal(flat2[f"{path}/var"],
                                      flat[f"{path}/var"])
        np.testing.assert_array_equal(flat2[f"{path}/beta"],
                                      flat[f"{path}/beta"])
    np.testing.assert_array_equal(flat2["fc/kernel"], flat["fc/kernel"])


def test_uncalibrated_metric_renamed():
    """Random-weight extractor must label its FID as _uncal."""
    from gansformer_tpu.metrics.metric_base import FIDMetric

    class FakeDataset:
        num_images = 8

        def cache_tag(self):
            return "fake"

        def batches(self, batch_size, seed=0):
            rng = np.random.RandomState(seed)
            while True:
                yield {"image": rng.randint(
                    0, 255, (batch_size, 32, 32, 3), np.uint8)}

    ext = FeatureExtractor(None)
    assert not ext.calibrated
    rng = np.random.RandomState(0)
    fakes = rng.rand(4, 32, 32, 3).astype(np.float32) * 2 - 1
    out = FIDMetric(num_images=4, batch_size=4).run(
        lambda n: fakes[:n], FakeDataset(), ext, cache_dir=None)
    assert list(out) == ["fid4_uncal"]
