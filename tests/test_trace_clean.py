"""Tier-1 gate: the repo's REAL jitted entry points are trace-clean
(ISSUE 4), mirroring test_lint_clean.py for the jaxpr-level half.

Zero non-baselined findings from the trace rules over the real
``train/steps.py`` entry points — that is what makes the rules
enforceable rather than advisory.  The gate splits by cost:

* structural rules (const bloat, dtype promotion) trace only — run over
  a 5-entry subset of the real matrix here (≥ the 4-entry acceptance
  floor);
* the retrace probe compiles — run on the real plain train step
  (acceptance: it must compile exactly ONCE across the equivalence
  matrix);
* the sharding audit + the full matrix × all rules are ``slow`` (>30s).

Also pins the migration/CLI contracts this PR added: the
check_learning_trend shim, ``--trace`` flag plumbing, and the
``--selfcheck`` artifact."""

import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(ROOT, "graftlint-baseline.json")


def _apply_baseline(findings):
    from gansformer_tpu.analysis.baseline import Baseline, line_text_lookup

    Baseline.load(BASELINE).apply(findings, line_text_lookup())
    return findings


def _assert_no_new(findings):
    new = [f for f in findings if f.new]
    assert new == [], "new trace findings — fix, suppress with a " \
        "justification comment, or baseline:\n" + "\n".join(
            f"{f.location}: {f.rule}: {f.message}" for f in new)


# --- the gate ---------------------------------------------------------------

def test_structural_trace_clean_on_real_entry_points():
    """const-bloat + dtype-promotion over real entry points of both
    matrix configs: zero non-baselined findings."""
    from gansformer_tpu.analysis.trace.const_bloat import ConstBloatRule
    from gansformer_tpu.analysis.trace.dtype_flow import DtypePromotionRule
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.analysis.trace.harness import run_trace

    eps = (build_entry_points("tiny-f32",
                              include=["d_step", "sample", "ppl_pairs"])
           + build_entry_points("tiny-bf16", include=["d_step_r1"]))
    assert len(eps) == 4          # ≥ the 4-entry acceptance floor
    findings, ctx = run_trace(
        "structural", rules=[ConstBloatRule, DtypePromotionRule],
        entries=eps)
    _assert_no_new(_apply_baseline(findings))


def test_real_train_step_compiles_exactly_once():
    """ISSUE 4 acceptance: the repo's real train step compiles exactly
    once across the retrace equivalence matrix (rebuilt arrays, flipped
    scalar flavors)."""
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.analysis.trace.harness import run_trace
    from gansformer_tpu.analysis.trace.retrace import RetraceHazardRule

    eps = build_entry_points("tiny-f32", include=["d_step"])
    findings, ctx = run_trace("fast", rules=[RetraceHazardRule],
                              entries=eps)
    _assert_no_new(_apply_baseline(findings))
    assert not ctx.notes, ctx.notes   # the probe ran, it didn't skip


def test_fast_matrix_covers_at_least_four_entry_points():
    """``gansformer-lint --trace`` traces ≥ 4 real entry points
    (acceptance floor) — the fused cycle program is among them, and
    since ISSUE 10 so is the serving split (map + synth)."""
    from gansformer_tpu.analysis.trace.entry_points import build_matrix

    eps = build_matrix("fast")
    shorts = {ep.name.split(".")[1].split("[")[0] for ep in eps}
    assert len(eps) >= 4
    assert {"d_step", "g_step", "cycle", "sample",
            "serve_map_seeds", "serve_synth"} <= shorts
    assert all(ep.path.endswith(("train/steps.py", "serve/programs.py"))
               for ep in eps)


def test_cycle_it0_flavor_pinned_at_jit_boundary():
    """Regression pin for the PR's marquee retrace fix WITHOUT paying
    the cycle compile: the real wrapper factory (`steps._wrap_cycle`,
    the one `make_train_steps` installs) must hand the underlying jit
    the SAME python-int it0 whether the caller passed a python int or
    an np scalar — one trace key, one compile.  (End-to-end coverage of
    the compiled cycle lives in the slow full-matrix test.)"""
    import numpy as np

    from gansformer_tpu.train import steps

    received = []

    def fake_jit(state, imgs_k, rng, it0, label_k=None):
        received.append(it0)
        return state

    fake_jit.lower = lambda *a, **k: None
    fake_jit._cache_size = lambda: len({type(x) for x in received})
    wrapper = steps._wrap_cycle(fake_jit, fake_jit)
    for flavor in (7, np.int32(7), np.int64(7)):
        wrapper("state", "imgs", "rng", flavor)
    assert [type(x) for x in received] == [int, int, int]
    assert [x for x in received] == [7, 7, 7]
    assert wrapper._cache_size() == 1     # one trace-key flavor
    # the installed fns.cycle really is this wrapper (not a raw jit)
    from gansformer_tpu.analysis.trace.entry_points import tiny_config

    fns = steps.make_train_steps(tiny_config(), None, batch_size=2)
    assert fns.cycle is not None
    assert fns.cycle.__wrapped__.__name__ == "_cycle"
    assert callable(fns.cycle.lower)


def test_g_step_all_reduces_on_two_device_mesh():
    """ISSUE 7 acceptance, promoted from a PR-6 documented observation
    into a tier-1 gate: the real ``g_step`` compiled on a 2-device data
    mesh MUST contain a gradient all-reduce — zero collectives there
    means the latent path regressed to replicated compute (N chips, N
    copies of the same work), which the collective-flow rule now also
    flags as a finding (checked clean here)."""
    from gansformer_tpu.analysis.trace.collective_flow import (
        CollectiveFlowRule)
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.analysis.trace.harness import run_trace

    eps = build_entry_points("tiny-f32", include=["g_step"])
    findings, ctx = run_trace("fast", rules=[CollectiveFlowRule],
                              entries=eps, mesh_sizes=(2,))
    _assert_no_new(_apply_baseline(findings))
    assert not ctx.notes, ctx.notes
    rec = ctx.comms[0]
    assert rec["entry"] == "steps.g_step[tiny-f32]"
    assert rec["collectives"].get("all-reduce", {}).get("count", 0) >= 1, \
        "g_step compiled to zero all-reduces — replicated compute"


def test_serve_entries_graftcomms_clean():
    """ISSUE 10 satellite: partition-contract + collective-flow stay
    CLEAN (zero non-baselined findings, zero skip-notes) over the
    serving split programs on the simulated 2-device mesh — the AOT
    executables the service dispatches must honor the declared layout
    (params replicated, request rows on ``data``)."""
    from gansformer_tpu.analysis.trace.collective_flow import (
        CollectiveFlowRule)
    from gansformer_tpu.analysis.trace.entry_points import (
        build_serve_entry_points)
    from gansformer_tpu.analysis.trace.harness import run_trace
    from gansformer_tpu.analysis.trace.partition_contract import (
        PartitionContractRule)

    eps = build_serve_entry_points(
        include=["serve_map_seeds", "serve_synth"])
    assert [ep.name for ep in eps] == [
        "serve.serve_map_seeds[tiny-f32]", "serve.serve_synth[tiny-f32]"]
    findings, ctx = run_trace(
        "fast", rules=[PartitionContractRule, CollectiveFlowRule],
        entries=eps, mesh_sizes=(2,))
    _assert_no_new(_apply_baseline(findings))
    assert not ctx.notes, ctx.notes     # compiled, audited, not skipped
    assert {c["entry"] for c in ctx.comms} == {ep.name for ep in eps}


def test_fast_matrix_has_pallas_backend_member():
    """ISSUE 9 satellite: the traced-entry catalog carries the pallas
    training backend (interpret mode off-TPU) via its second-order
    superset programs, on the DUPLEX model so both kernel directions
    (and both backward kernels) sit inside the traced jaxprs."""
    from gansformer_tpu.analysis.trace.entry_points import (
        build_matrix, trace_configs)

    cfg = trace_configs()["tiny-pallas"]
    assert cfg.model.attention_backend == "pallas"
    assert cfg.model.attention == "duplex"
    cfg.validate()          # the relaxed training rule covers the member
    pallas_eps = [ep for ep in build_matrix("fast")
                  if ep.config_name == "tiny-pallas"]
    assert {ep.name.split(".")[1].split("[")[0] for ep in pallas_eps} \
        == {"d_step_r1", "g_step_pl"}


@pytest.mark.slow
def test_pallas_backend_entries_graftcomms_clean():
    """ISSUE 9 satellite: partition-contract + collective-flow stay CLEAN
    (zero non-baselined findings, zero skip-notes) over the pallas-backend
    member's second-order programs on the simulated 2-device mesh — the
    kernels must not break the declared layouts or the gradient
    all-reduce."""
    from gansformer_tpu.analysis.trace.collective_flow import (
        CollectiveFlowRule)
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.analysis.trace.harness import run_trace
    from gansformer_tpu.analysis.trace.partition_contract import (
        PartitionContractRule)

    eps = build_entry_points("tiny-pallas",
                             include=["d_step_r1", "g_step_pl"])
    assert len(eps) == 2
    findings, ctx = run_trace(
        "fast", rules=[PartitionContractRule, CollectiveFlowRule],
        entries=eps, mesh_sizes=(2,))
    _assert_no_new(_apply_baseline(findings))
    assert not ctx.notes, ctx.notes
    assert {(r["entry"], r["devices"]) for r in ctx.comms} \
        == {(ep.name, 2) for ep in eps}
    # the gradient all-reduce survives the backend swap
    for rec in ctx.comms:
        assert rec["collectives"].get("all-reduce", {}).get("count", 0) \
            >= 1, rec


@pytest.mark.slow
def test_g_step_per_device_flops_halve_on_two_device_mesh():
    """ISSUE 7 acceptance: at a FIXED global batch, the 2-device
    compile's per-device cost-analysis FLOPs drop to ~1/2 of the
    1-device value — the compute genuinely shards (the pre-change
    ratio was 1.0: N chips, N copies)."""
    from gansformer_tpu.analysis.trace.base import TraceContext
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.utils.benchcheck import flops_of

    eps = build_entry_points("tiny-f32", include=["g_step"])
    ctx = TraceContext(mesh_sizes=(1, 2))
    c1, _ = ctx.compiled(eps[0], 1)
    c2, _ = ctx.compiled(eps[0], 2)
    f1, f2 = flops_of(c1), flops_of(c2)
    assert f1 and f2
    # not exactly 0.5: the optimizer update and the (non-divisible)
    # PL-free replicated tails stay whole-per-device
    assert 0.40 <= f2 / f1 <= 0.75, (f1, f2)


@pytest.mark.slow
def test_sharding_audit_clean_on_real_train_step():
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.analysis.trace.harness import run_trace
    from gansformer_tpu.analysis.trace.sharding_audit import (
        ShardingAuditRule)

    eps = build_entry_points("tiny-f32", include=["d_step"])
    findings, ctx = run_trace("fast", rules=[ShardingAuditRule],
                              entries=eps)
    _assert_no_new(_apply_baseline(findings))
    assert not ctx.notes, ctx.notes


@pytest.mark.slow
def test_graftcomms_clean_on_real_entries_2_and_4_device_meshes():
    """ISSUE 6 acceptance: partition-contract AND collective-flow are
    clean (zero non-baselined findings, zero skip-notes) over EVERY
    real entry point on the simulated 2- and 4-device meshes, and the
    comms table covers every entry×mesh pair."""
    from gansformer_tpu.analysis.trace.collective_flow import (
        CollectiveFlowRule, ranked_comms_table, scaling_report)
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.analysis.trace.harness import run_trace
    from gansformer_tpu.analysis.trace.partition_contract import (
        PartitionContractRule)

    eps = build_entry_points("tiny-f32")
    findings, ctx = run_trace(
        "fast", rules=[PartitionContractRule, CollectiveFlowRule],
        entries=eps, mesh_sizes=(2, 4))
    _assert_no_new(_apply_baseline(findings))
    assert not ctx.notes, ctx.notes
    assert {(r["entry"], r["devices"]) for r in ctx.comms} \
        == {(ep.name, n) for ep in eps for n in (2, 4)}
    # the train steps move real bytes; the ranked table reflects it
    table = ranked_comms_table(ctx.comms)
    by_entry = {r["entry"]: r for r in table}
    assert by_entry["steps.d_step[tiny-f32]"][
        "total_wire_bytes_per_device"] > 0
    # the fused cycle tops the ranking (largest program, most traffic)
    assert table[0]["entry"] == "steps.cycle[tiny-f32]"
    # and the scaling prediction is monotone in chip count per entry
    for entry, per_chip in scaling_report(ctx.comms).items():
        seq = [per_chip[c] for c in sorted(per_chip, key=int)]
        assert seq == sorted(seq), (entry, seq)


@pytest.mark.slow
def test_full_matrix_trace_clean():
    """Everything: all four rule families over every entry point of
    every matrix config — the exhaustive version of the gate."""
    from gansformer_tpu.analysis.trace.harness import run_trace

    findings, ctx = run_trace("full")
    _assert_no_new(_apply_baseline(findings))


# --- migration contract: learning-trend shim --------------------------------

def test_check_learning_trend_shim_api(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_learning_trend",
        os.path.join(ROOT, "scripts", "check_learning_trend.py"))
    clt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(clt)
    # legacy API surface intact
    for fn in ("check", "read_metric_series", "fit_line", "main"):
        assert callable(getattr(clt, fn))
    out = clt.check(str(tmp_path), None, 3, 0.10)
    assert not out["ok"] and "metric points" in out["error"]
    # framework rule: same verdict as Findings
    findings = clt.lint_learning_trend(str(tmp_path))
    assert len(findings) == 1 and findings[0].rule == "learning-trend"


def test_learning_trend_rule_quiet_on_learning_run(tmp_path):
    from gansformer_tpu.analysis.learning_trend import lint_learning_trend

    d = tmp_path / "run"
    d.mkdir()
    with open(d / "metric-fid8_test.txt", "w") as f:
        for i, v in enumerate([300.0, 220.0, 170.0, 140.0]):
            f.write(f"kimg {2.0 * (i + 1):<10.1f} fid8_test {v:.4f}\n")
    assert lint_learning_trend(str(d)) == []


# --- CLI plumbing -----------------------------------------------------------

def test_cli_trace_flags_and_rule_selection(capsys):
    from gansformer_tpu.analysis import cli

    # unknown rule ids error out across BOTH registries
    assert cli.main(["--select", "no-such-rule", "x.py"]) == 2
    # selecting a trace-only rule WITHOUT --trace would run zero rules
    # and report a false clean pass — it must be a usage error instead
    assert cli.main(["--select", "retrace-hazard",
                     os.path.join(ROOT, "gansformer_tpu",
                                  "analysis")]) == 2
    # with --trace the same selection is valid (structural keeps it
    # cheap: retrace is dynamic, so no entries run under this profile)
    out = cli.main(["--trace", "--trace-profile", "structural",
                    "--select", "retrace-hazard",
                    os.path.join(ROOT, "gansformer_tpu", "analysis",
                                 "findings.py")])
    assert out == 0
    # --learning-trend requires --run-dir
    assert cli.main(["--learning-trend", "x.py"]) == 2
    # the comms artifact / native backend only exist with --trace
    assert cli.main(["--json-out", "x.json", "x.py"]) == 2
    assert cli.main(["--trace-native", "x.py"]) == 2
    capsys.readouterr()


def test_cli_trace_json_emits_comms_table(tmp_path, capsys):
    """``gansformer-lint --trace --format json`` carries the graftcomms
    sections, and ``--json-out`` writes the standalone attribution
    artifact (structural profile: plumbing only — the slow gate covers
    real content; the new rule ids are selectable and listed)."""
    from gansformer_tpu.analysis import cli

    art = tmp_path / "comms.json"
    rc = cli.main(["--trace", "--trace-profile", "structural",
                   "--select", "partition-contract,collective-flow",
                   "--format", "json", "--json-out", str(art),
                   os.path.join(ROOT, "gansformer_tpu", "analysis",
                                "findings.py")])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["comms"] == []                 # structural: no compiles
    assert payload["scaling_bytes_per_device"] == {}
    assert payload["trace_profile"] == "structural"
    saved = json.loads(art.read_text())
    assert saved["version"] == 1 and saved["comms"] == []
    # --list-rules names the graftcomms pair
    assert cli.main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    assert "partition-contract" in listed and "collective-flow" in listed


def test_harness_profiles_target_graftcomms_surface():
    """Profile wiring: ``contracts`` runs ONLY partition-contract (on
    the four train steps); ``fast`` gives the mesh rules all four train
    steps (the _FAST_SHARDING satellite — no more d_step-only audits);
    ``full`` uses the 1/2/4 mesh matrix, everything else the 2-device
    mesh."""
    from gansformer_tpu.analysis.trace import harness

    class _EP:
        def __init__(self, name, config_name="tiny-f32"):
            self.name = name
            self.arg_specs = ("state",)
            self.config_name = config_name

    eps = [_EP(f"steps.{s}[tiny-f32]") for s in
           ("d_step", "d_step_r1", "g_step", "g_step_pl", "cycle",
            "sample", "ppl_pairs")]
    four = {f"steps.{s}[tiny-f32]" for s in
            ("d_step", "d_step_r1", "g_step", "g_step_pl")}
    for rule in ("sharding-audit", "partition-contract",
                 "collective-flow"):
        got = {e.name for e in
               harness._dynamic_entries(rule, "fast", eps)}
        assert got == four, rule
    assert {e.name for e in harness._dynamic_entries(
        "partition-contract", "contracts", eps)} == four
    assert harness._dynamic_entries("collective-flow", "contracts",
                                    eps) == []
    assert harness._dynamic_entries("retrace-hazard", "contracts",
                                    eps) == []
    assert len(harness._dynamic_entries("collective-flow", "full",
                                        eps)) == len(eps)
    # the bf16 matrix member is a dtype-flow fixture, not a layout one:
    # the mesh-compiling rules skip it even under full
    mixed = eps + [_EP("steps.d_step[tiny-bf16]",
                       config_name="tiny-bf16")]
    assert {e.name for e in harness._dynamic_entries(
        "partition-contract", "full", mixed)} == {e.name for e in eps}
    assert len(harness._dynamic_entries("retrace-hazard", "full",
                                        mixed)) == len(mixed)
    assert harness.mesh_sizes_for("full") == (1, 2, 4)
    assert harness.mesh_sizes_for("fast") == (2,)
    assert harness.mesh_sizes_for("contracts") == (2,)


def test_entry_points_reject_incomplete_coverage(monkeypatch):
    """The loud-coverage guard (ISSUE 6 satellite): every real entry
    carries complete per-arg placement tags AND a declared contract —
    and removing a contract makes the build RAISE instead of riding
    the audits' silent skip-note path (which once exempted the
    inference programs the serving path will reuse)."""
    import pytest as _pytest

    from gansformer_tpu.analysis.trace import entry_points
    from gansformer_tpu.parallel import contracts

    # one build covers both halves (the inference programs prove the
    # old exemption path is closed; full-catalog spec/contract
    # completeness is pinned by test_comms_rules + the structural gate)
    eps = entry_points.build_entry_points(
        "tiny-f32", include=["sample", "ppl_pairs"])
    assert {ep.name.split(".")[1].split("[")[0] for ep in eps} \
        == {"sample", "ppl_pairs"}
    for ep in eps:
        assert len(ep.arg_specs) == len(ep.abstract_args), ep.name
        assert contracts.contract_for(ep.name) is not None, ep.name

    monkeypatch.delitem(contracts.ENTRY_CONTRACTS, "ppl_pairs")
    with _pytest.raises(ValueError, match="no sharding contract"):
        entry_points.build_entry_points("tiny-f32",
                                        include=["ppl_pairs"])


def test_cli_run_dir_learning_trend(tmp_path, capsys):
    from gansformer_tpu.analysis import cli

    d = tmp_path / "run"
    d.mkdir()
    rc = cli.main(["--run-dir", str(d), "--learning-trend",
                   "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    rules = {f["rule"] for f in payload["findings"]}
    assert "learning-trend" in rules       # no metric series
    assert "telemetry-schema" in rules     # no artifacts either


def test_selfcheck_writes_artifact(tmp_path, monkeypatch):
    """cli/train.py --selfcheck contract: one command = AST + trace,
    JSON artifact in the run dir, count of new findings returned.  The
    trace half is stubbed here (its real run is covered above — no
    need to re-trace the matrix inside a unit test)."""
    from gansformer_tpu.analysis import cli

    seen = {}

    def fake_trace(profile, rules, native=False):
        seen["profile"], seen["native"] = profile, native
        return [], {"comms": [], "scaling_bytes_per_device": {},
                    "trace_profile": profile,
                    "mesh_sizes_requested": [2],
                    "mesh_sizes_compiled": [2], "notes": []}

    monkeypatch.setattr(cli, "run_trace_findings", fake_trace)
    n_new = cli.run_selfcheck(str(tmp_path))
    assert n_new == 0
    # ISSUE 6 satellite: selfcheck runs structural + the contract check
    # (the "contracts" profile) on the ambient backend
    assert seen == {"profile": "contracts", "native": True}
    artifact = tmp_path / "graftlint.json"
    assert artifact.exists()
    payload = json.loads(artifact.read_text())
    assert payload["ok"] and payload["files_checked"] > 0
    assert payload["trace_profile"] == "contracts"   # comms extra rides


def test_train_cli_exposes_selfcheck():
    from gansformer_tpu.cli.train import build_parser

    args = build_parser().parse_args(["--selfcheck"])
    assert args.selfcheck is True
    assert build_parser().parse_args([]).selfcheck is False


def test_precommit_config_invokes_ast_plus_contracts():
    """The hook runs the AST rules plus the cheap trace end: structural
    tracing + the PartitionSpec-contract check (``--trace-profile
    contracts``) — never the expensive retrace/full-matrix profiles."""
    with open(os.path.join(ROOT, ".pre-commit-config.yaml")) as f:
        content = f.read()
    entry = [ln for ln in content.splitlines() if "entry:" in ln]
    assert entry and "gansformer_tpu.analysis.cli" in entry[0]
    assert "--trace-profile contracts" in entry[0]
    assert "full" not in entry[0] and "fast" not in entry[0]
