"""utils/profparse.py — the device-time witness: xplane parsing, the
no-TensorFlow Chrome-trace fallback, program attribution, and the
``unavailable`` sentinel (ISSUE 8)."""

import gzip
import json
import os

import numpy as np
import pytest

from gansformer_tpu.utils import profparse
from gansformer_tpu.utils.profparse import (
    _merge_busy, attribute_programs, device_busy_span, device_time_report,
    parse_planes, parse_trace_events, program_name)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "chrome_trace")


def test_merge_busy_overlaps_and_gaps():
    # overlapping + nested + disjoint: covered = [0,10] ∪ [20,25] = 15
    iv = [(0, 6), (4, 10), (5, 7), (20, 25)]
    assert _merge_busy(iv) == 15
    assert _merge_busy([]) == 0
    assert _merge_busy([(3, 3)]) == 0          # zero-length event


def test_parse_live_cpu_trace(tmp_path):
    """End-to-end: trace a jitted loop on the CPU backend, parse the
    xplane, and get a plausible busy time from the executor plane."""
    import jax
    import jax.numpy as jnp

    pytest.importorskip("tensorflow.tsl.profiler.protobuf")

    f = jax.jit(lambda x: x @ x + 1.0)
    x = jnp.ones((256, 256))
    f(x).block_until_ready()          # compile outside the trace
    with jax.profiler.trace(str(tmp_path)):
        for _ in range(4):
            x = f(x)
        jax.block_until_ready(x)

    planes = parse_planes(str(tmp_path))
    assert planes, "no planes parsed from a real trace"
    got = device_busy_span(str(tmp_path))
    assert got is not None
    busy, span, plane = got
    # CPU backend: executor events land on the host plane
    assert plane.startswith(("/device:", "/host:CPU"))
    assert 0 < busy <= span < 60.0
    assert np.isfinite(busy)


def test_missing_trace_degrades_to_none(tmp_path):
    assert parse_planes(str(tmp_path)) is None
    assert device_busy_span(str(tmp_path)) is None


def test_multi_line_events_rebased_to_line_timestamps(tmp_path):
    """XEvent.offset_ps is relative to ITS LINE's timestamp_ns: two lines
    whose events are back-to-back in absolute time must merge to the SUM
    of their busy times, not collapse onto a shared zero."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")

    xs = xplane_pb2.XSpace()
    p = xs.planes.add()
    p.name = "/device:TPU:0"
    # line A at t=0ns: one event [0, 1s); line B at t=1s: one event
    # [1s, 2s) in absolute time but offset 0 in line-relative time.
    a = p.lines.add()
    a.timestamp_ns = 0
    ea = a.events.add()
    ea.offset_ps, ea.duration_ps = 0, int(1e12)
    b = p.lines.add()
    b.timestamp_ns = int(1e9)
    eb = b.events.add()
    eb.offset_ps, eb.duration_ps = 0, int(1e12)

    d = tmp_path / "plugins" / "profile" / "run"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(xs.SerializeToString())

    busy, span, plane = device_busy_span(str(tmp_path))
    assert plane == "/device:TPU:0"
    assert busy == pytest.approx(2.0)     # naive offset-merge would say 1.0
    assert span == pytest.approx(2.0)


def test_program_name_extraction():
    assert program_name("PjitFunction(d_step)") == "d_step"
    assert program_name("jit_d_step_r1.42") == "d_step_r1"
    assert program_name("jit_g_step_pl") == "g_step_pl"
    assert program_name("jit__wrap_cycle(args)") == "wrap_cycle"
    assert program_name("PjitFunction(<unnamed function>)") == \
        "unnamed_function"
    # per-op / executor events are NOT programs
    assert program_name("dot.4") is None
    assert program_name("TfrtCpuExecutable::Execute") is None
    assert program_name("broadcast_add_fusion") is None


# --- the checked-in Chrome-trace fixture (no-TensorFlow fallback) -----------

def test_chrome_fixture_parses_without_xplane():
    """The fixture dir has ONLY a *.trace.json.gz — the xplane path finds
    nothing and the Chrome fallback must carry the parse."""
    events, source = parse_trace_events(FIXTURE)
    assert source == "chrome-trace"
    assert set(events) == {"/device:TPU:0", "/host:CPU"}
    got = device_busy_span(FIXTURE)
    assert got is not None
    busy, span, plane = got
    assert plane == "/device:TPU:0"       # device plane preferred
    # merged intervals: overlapping core lines don't double-count the
    # duplicated first d_step → 10+10+17+12 ms
    assert busy == pytest.approx(0.049)
    assert span == pytest.approx(0.071)


def test_chrome_fixture_program_attribution_prefers_device_plane():
    events, _ = parse_trace_events(FIXTURE)
    progs = attribute_programs(events)
    # device-plane jit_* module events win over the host PjitFunction
    # dispatch events (which would report sub-ms dispatch times)
    assert progs["d_step"] == pytest.approx(0.020)
    assert progs["d_step_r1"] == pytest.approx(0.017)
    assert progs["g_step_pl"] == pytest.approx(0.012)


def test_chrome_fixture_report_and_python_tracer_frames_ignored():
    rep = device_time_report(FIXTURE)
    assert rep["status"] == "ok"
    assert rep["source"] == "chrome-trace"
    assert rep["plane"] == "/device:TPU:0"
    # the fixture's "$loop.py:1 _train" frame spans 6s starting before
    # the window; counting it would make busy/span ~100x larger
    assert rep["span_s"] < 1.0
    assert set(rep["program_busy_s"]) == {"d_step", "d_step_r1",
                                          "g_step_pl"}


def test_broken_xplane_import_falls_back_to_chrome(tmp_path, monkeypatch):
    """The xplane proto being unimportable (no-TensorFlow container) must
    be non-fatal: the same dir parses through the Chrome fallback."""
    import shutil

    d = tmp_path / "trace"
    shutil.copytree(FIXTURE, d)
    # a decoy .pb next to the chrome trace + a broken xplane parser
    (d / "plugins" / "profile" / "run1" / "host.xplane.pb").write_bytes(
        b"\x00")
    monkeypatch.setattr(
        profparse, "_xplane_events",
        lambda trace_dir: (_ for _ in ()).throw(
            ImportError("No module named 'tensorflow'")))
    events, source = parse_trace_events(str(d))
    assert source == "chrome-trace"
    assert device_busy_span(str(d)) is not None


def test_unavailable_sentinel_instead_of_raising(tmp_path, monkeypatch):
    """When NEITHER parser can run, device_time_report returns the
    explicit unavailable sentinel (never raises)."""
    rep = device_time_report(str(tmp_path))       # empty dir
    assert rep["status"] == "unavailable"
    assert "no parseable trace" in rep["reason"]
    # both parsers broken: still a sentinel, with the failure recorded
    monkeypatch.setattr(
        profparse, "_xplane_events",
        lambda trace_dir: (_ for _ in ()).throw(ImportError("no tf")))
    rep = device_time_report(FIXTURE)
    assert rep["status"] == "ok"                  # chrome still carries it
    monkeypatch.setattr(
        profparse, "_chrome_events",
        lambda trace_dir: (_ for _ in ()).throw(ValueError("torn gz")))
    rep = device_time_report(FIXTURE)
    assert rep["status"] == "unavailable"
    assert "chrome-trace parse failed" in rep["reason"]


def test_chrome_trace_uncompressed_and_trailing_torn_json(tmp_path):
    """A plain .trace.json (no gz) parses too; an unreadable file yields
    the sentinel rather than an exception."""
    d = tmp_path / "plugins" / "profile" / "run"
    d.mkdir(parents=True)
    doc = {"traceEvents": [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 1000.0,
         "name": "jit_d_step"}]}
    (d / "host.trace.json").write_text(json.dumps(doc))
    rep = device_time_report(str(tmp_path))
    assert rep["status"] == "ok" and rep["busy_s"] == pytest.approx(1e-3)
    (d / "host.trace.json").write_text("{not json")
    rep = device_time_report(str(tmp_path))
    assert rep["status"] == "unavailable"


def test_live_trace_report_attributes_named_programs(tmp_path):
    """End-to-end on a REAL trace: named jitted programs show up in the
    attribution regardless of which parser carried the parse."""
    import jax
    import jax.numpy as jnp

    def d_step(x):
        return x @ x + 1.0

    f = jax.jit(d_step)
    x = jnp.ones((128, 128))
    f(x).block_until_ready()
    with jax.profiler.trace(str(tmp_path)):
        for _ in range(3):
            x = f(x)
        jax.block_until_ready(x)
    rep = device_time_report(str(tmp_path))
    assert rep["status"] == "ok"
    assert rep["source"] in ("xplane", "chrome-trace")
    assert 0 < rep["busy_s"] <= rep["span_s"] < 60.0
    assert "d_step" in rep["program_busy_s"]


def test_trace_suspect_thresholds():
    from gansformer_tpu.utils.benchcheck import trace_suspect

    # honest: device busy ≈ wall
    assert trace_suspect(0.035, 0.036, 10, 0.0035) is None
    # lying wall clock: device executed 10x the claimed window
    msg = trace_suspect(3.5, 0.35, 10, 0.0035)
    assert msg and "not covering device execution" in msg
    # no device events → no verdict either way
    assert trace_suspect(0.0, 0.1, 10, 0.01) is None
