"""utils/profparse.py — the bench's xplane device-time witness."""

import numpy as np
import pytest

from gansformer_tpu.utils.profparse import (
    _merge_busy, device_busy_span, parse_planes)


def test_merge_busy_overlaps_and_gaps():
    # overlapping + nested + disjoint: covered = [0,10] ∪ [20,25] = 15
    iv = [(0, 6), (4, 10), (5, 7), (20, 25)]
    assert _merge_busy(iv) == 15
    assert _merge_busy([]) == 0
    assert _merge_busy([(3, 3)]) == 0          # zero-length event


def test_parse_live_cpu_trace(tmp_path):
    """End-to-end: trace a jitted loop on the CPU backend, parse the
    xplane, and get a plausible busy time from the executor plane."""
    import jax
    import jax.numpy as jnp

    pytest.importorskip("tensorflow.tsl.profiler.protobuf")

    f = jax.jit(lambda x: x @ x + 1.0)
    x = jnp.ones((256, 256))
    f(x).block_until_ready()          # compile outside the trace
    with jax.profiler.trace(str(tmp_path)):
        for _ in range(4):
            x = f(x)
        jax.block_until_ready(x)

    planes = parse_planes(str(tmp_path))
    assert planes, "no planes parsed from a real trace"
    got = device_busy_span(str(tmp_path))
    assert got is not None
    busy, span, plane = got
    # CPU backend: executor events land on the host plane
    assert plane.startswith(("/device:", "/host:CPU"))
    assert 0 < busy <= span < 60.0
    assert np.isfinite(busy)


def test_missing_trace_degrades_to_none(tmp_path):
    assert parse_planes(str(tmp_path)) is None
    assert device_busy_span(str(tmp_path)) is None


def test_multi_line_events_rebased_to_line_timestamps(tmp_path):
    """XEvent.offset_ps is relative to ITS LINE's timestamp_ns: two lines
    whose events are back-to-back in absolute time must merge to the SUM
    of their busy times, not collapse onto a shared zero."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")

    xs = xplane_pb2.XSpace()
    p = xs.planes.add()
    p.name = "/device:TPU:0"
    # line A at t=0ns: one event [0, 1s); line B at t=1s: one event
    # [1s, 2s) in absolute time but offset 0 in line-relative time.
    a = p.lines.add()
    a.timestamp_ns = 0
    ea = a.events.add()
    ea.offset_ps, ea.duration_ps = 0, int(1e12)
    b = p.lines.add()
    b.timestamp_ns = int(1e9)
    eb = b.events.add()
    eb.offset_ps, eb.duration_ps = 0, int(1e12)

    d = tmp_path / "plugins" / "profile" / "run"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(xs.SerializeToString())

    busy, span, plane = device_busy_span(str(tmp_path))
    assert plane == "/device:TPU:0"
    assert busy == pytest.approx(2.0)     # naive offset-merge would say 1.0
    assert span == pytest.approx(2.0)


def test_trace_suspect_thresholds():
    from gansformer_tpu.utils.benchcheck import trace_suspect

    # honest: device busy ≈ wall
    assert trace_suspect(0.035, 0.036, 10, 0.0035) is None
    # lying wall clock: device executed 10x the claimed window
    msg = trace_suspect(3.5, 0.35, 10, 0.0035)
    assert msg and "not covering device execution" in msg
    # no device events → no verdict either way
    assert trace_suspect(0.0, 0.1, 10, 0.01) is None
