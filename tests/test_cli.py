"""CLI surface tests — config construction only (no training)."""

import json
import os
import time

import numpy as np
import pytest

from gansformer_tpu.cli.train import build_parser, config_from_args
from gansformer_tpu.core.config import ExperimentConfig, get_preset, PRESETS


def test_presets_cover_driver_configs():
    # the five driver benchmark configs (BASELINE.json:7-11)
    assert set(PRESETS) == {
        "clevr64-simplex", "ffhq256-duplex", "bedroom256-duplex",
        "cityscapes256-duplex", "ffhq1024-duplex"}
    assert PRESETS["clevr64-simplex"].model.components == 8
    assert PRESETS["ffhq256-duplex"].model.components == 16
    assert PRESETS["cityscapes256-duplex"].model.components == 32
    assert PRESETS["ffhq1024-duplex"].model.resolution == 1024


def test_config_json_roundtrip():
    cfg = get_preset("ffhq256-duplex")
    back = ExperimentConfig.from_json(cfg.to_json())
    assert back == cfg


def test_cli_overrides():
    args = build_parser().parse_args([
        "--preset", "ffhq256-duplex", "--batch-size", "64",
        "--attention", "simplex", "--components", "8",
        "--total-kimg", "5", "--data-source", "synthetic"])
    cfg = config_from_args(args)
    assert cfg.train.batch_size == 64
    assert cfg.model.attention == "simplex"
    assert cfg.model.components == 8
    assert cfg.train.total_kimg == 5
    assert cfg.data.source == "synthetic"
    # untouched fields keep preset values
    assert cfg.model.resolution == 256
    # device-truth sampling (ISSUE 8): default cadence inherited, 0 = off
    assert cfg.train.device_time_ticks == 8
    args = build_parser().parse_args([
        "--preset", "ffhq256-duplex", "--device-time-ticks", "0"])
    assert config_from_args(args).train.device_time_ticks == 0


def test_cli_data_plane_flags():
    """ISSUE 15: the corruption budget, IO retry count, and stall
    watchdog are flag-overridable; defaults inherit the config."""
    args = build_parser().parse_args(["--preset", "clevr64-simplex"])
    cfg = config_from_args(args)
    assert cfg.data.max_corrupt_frac == 0.01
    assert cfg.data.io_retries == 3
    assert cfg.data.stall_after_s == 120.0
    args = build_parser().parse_args([
        "--preset", "clevr64-simplex", "--max-corrupt-frac", "0.1",
        "--io-retries", "5", "--stall-after-s", "0"])
    cfg = config_from_args(args)
    assert cfg.data.max_corrupt_frac == 0.1
    assert cfg.data.io_retries == 5
    assert cfg.data.stall_after_s == 0.0
    import pytest as _pytest

    args = build_parser().parse_args([
        "--preset", "clevr64-simplex", "--max-corrupt-frac", "1.5"])
    with _pytest.raises(ValueError, match="max_corrupt_frac"):
        config_from_args(args)


def test_cli_defaults_valid():
    for name in PRESETS:
        args = build_parser().parse_args(["--preset", name])
        cfg = config_from_args(args)
        assert cfg.model.resolution == PRESETS[name].model.resolution


def test_prepare_data_synthetic(tmp_path):
    from gansformer_tpu.cli.prepare_data import main
    import numpy as np

    out = tmp_path / "toy.npz"
    main(["--synthetic", "--out", str(out), "--resolution", "16",
          "--max-images", "12"])
    with np.load(out) as z:
        assert z["images"].shape == (12, 16, 16, 3)


def test_debug_nans_flag_and_finite_check():
    """--debug-nans plumbs to TrainConfig (VERDICT r2 item 9) and the tick
    guard raises on the first non-finite scalar."""
    import pytest

    from gansformer_tpu.cli.train import build_parser, config_from_args
    from gansformer_tpu.utils.debug import check_finite_stats

    args = build_parser().parse_args(["--debug-nans"])
    assert config_from_args(args).train.debug_nans is True
    args = build_parser().parse_args([])
    assert config_from_args(args).train.debug_nans is False

    check_finite_stats({"Loss/G": 1.0, "note": "str ok"})  # no raise
    with pytest.raises(FloatingPointError, match="Loss/D"):
        check_finite_stats({"Loss/G": 1.0, "Loss/D": float("nan")},
                           where="kimg 3.0")


@pytest.mark.slow  # trains two experiment arms end-to-end
def test_experiment_matrix(tmp_path):
    """Repro-study harness (SURVEY.md §2.2 "Repro-study harness"): the
    arXiv 2303.08577 matrix — baseline vs GANsformer arms under one budget —
    runs end-to-end and writes the comparison report."""
    import dataclasses
    import os

    from gansformer_tpu.cli.experiment import run_experiment
    from tests.test_train import micro_cfg

    base = micro_cfg(attention="simplex", batch=8)
    # reg intervals beyond the run length: each arm compiles only the two
    # steady-state step variants (R1/PL phases are covered in test_train).
    base = dataclasses.replace(
        base, train=dataclasses.replace(
            base.train, total_kimg=1, kimg_per_tick=1, snapshot_ticks=0,
            image_snapshot_ticks=0, d_reg_interval=10_000,
            g_reg_interval=10_000))
    out = str(tmp_path / "exp")
    summary = run_experiment(base, ["none", "simplex"], out)
    assert set(summary["arms"]) == {"none", "simplex"}
    for arch, arm in summary["arms"].items():
        assert arm["kimg"] and arm["kimg"] >= 1.0, arm
        assert np.isfinite(arm["loss_g"]) and np.isfinite(arm["loss_d"])
    # the baseline arm really is attention-free: fewer params
    assert summary["arms"]["none"]["g_params"] < \
        summary["arms"]["simplex"]["g_params"]
    assert os.path.exists(os.path.join(out, "experiment.json"))
    report = open(os.path.join(out, "report.md")).read()
    assert "| none |" in report and "| simplex |" in report


def test_tensorboard_event_file(tmp_path):
    """utils/tensorboard.py writes real TensorBoard event files (the
    reference's autosummary surface, SURVEY.md §5) — verified with
    TensorFlow's own record reader + Event proto when TF is available."""
    tf = pytest.importorskip("tensorflow")

    from gansformer_tpu.utils.logging import RunLogger

    log = RunLogger(str(tmp_path))
    log.log_tick({"Progress/kimg": 1.0, "Loss/G": 2.5, "Loss/D": -0.5,
                  "note": "strings are skipped"})
    log.metric("fid1k_uncal", 42.0, kimg=1.0)
    log.close()

    tb_dir = tmp_path / "tensorboard"
    files = list(tb_dir.glob("events.out.tfevents.*"))
    assert len(files) == 1
    events = []
    for rec in tf.data.TFRecordDataset(str(files[0])):
        ev = tf.compat.v1.Event()
        ev.ParseFromString(rec.numpy())
        events.append(ev)
    assert events[0].file_version == "brain.Event:2"
    scalars = {v.tag: (v.simple_value, ev.step)
               for ev in events[1:] for v in ev.summary.value}
    assert scalars["Loss/G"] == (2.5, 1000)
    assert scalars["Loss/D"] == (-0.5, 1000)
    assert scalars["Metrics/fid1k_uncal"][0] == 42.0
    assert "note" not in scalars


def test_pack_run_and_load_from_archive_and_url(tmp_path, micro_run_dir):
    """pack_run → tar.gz → resolve_run_dir from a local archive AND an
    http URL (the reference's pretrained-model distribution surface,
    SURVEY.md §2.2 loader/pretrained_networks row)."""
    import os

    import jax

    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.utils.runarchive import pack_run, resolve_run_dir
    from tests.test_data import _serve_dir

    run = micro_run_dir  # shared session-scoped training run
    archive = pack_run(run, out_path=str(tmp_path / "model.tar.gz"))
    cache1 = str(tmp_path / "cache1")
    resolved = resolve_run_dir(archive, cache_dir=cache1)
    assert os.path.exists(os.path.join(resolved, "config.json"))
    template = None  # restore proves the checkpoint inside is loadable
    from gansformer_tpu.core.config import ExperimentConfig
    from gansformer_tpu.train.state import create_train_state

    with open(os.path.join(resolved, "config.json")) as f:
        cfg2 = ExperimentConfig.from_json(f.read())
    template = create_train_state(cfg2, jax.random.PRNGKey(0))
    state = ckpt.restore(os.path.join(resolved, "checkpoints"), template)
    assert int(jax.device_get(state.step)) > 0

    # URL path through the loopback server
    srv, base = _serve_dir(str(tmp_path))
    try:
        cache2 = str(tmp_path / "cache2")
        resolved_url = resolve_run_dir(f"{base}/model.tar.gz",
                                       cache_dir=cache2)
        assert os.path.exists(os.path.join(resolved_url, "config.json"))
        # second resolve hits the cache (no re-download, same dir)
        assert resolve_run_dir(f"{base}/model.tar.gz",
                               cache_dir=cache2) == resolved_url
    finally:
        srv.shutdown()

    # re-packing to the SAME path must invalidate the cached extraction
    pack_run(run, out_path=archive)
    # force a distinct mtime: gzip output size may be identical and some
    # filesystems have 1s timestamp granularity
    st = os.stat(archive)
    os.utime(archive, ns=(st.st_atime_ns, st.st_mtime_ns + 2_000_000_000))
    resolved2 = resolve_run_dir(archive, cache_dir=cache1)
    assert resolved2 != resolved
    assert os.path.exists(os.path.join(resolved2, "config.json"))


@pytest.mark.slow  # full metric sweep (~minutes on CPU)
def test_evaluate_cli_end_to_end(tmp_path, micro_run_dir, capsys):
    """evaluate CLI main() on a real run dir: restore → sharded sweep →
    metric-<name>.txt + JSON line (reference §3.3 surface).  Uses the tiny
    uncalibrated extractor, so names carry the honest _uncal suffix."""
    import glob
    import os

    from gansformer_tpu.cli.evaluate import main as evaluate

    evaluate(["--run-dir", micro_run_dir, "--metrics", "fid,is",
              "--num-images", "32", "--batch-size", "16"])
    out = capsys.readouterr().out.strip().splitlines()
    payload = json.loads(out[-1])
    assert any(k.startswith("fid32_uncal") for k in payload)
    assert any(k.startswith("is32_uncal") for k in payload)
    assert all(np.isfinite(v) for k, v in payload.items()
               if isinstance(v, float))
    files = glob.glob(os.path.join(micro_run_dir, "metric-*.txt"))
    assert any("fid32_uncal" in f for f in files)
    # flags are state, not series (VERDICT r5 weak #4/item 7): the
    # calibrated regime lands in flag-calibrated.txt, never metric-*.txt
    assert not any(f.endswith("metric-calibrated.txt") for f in files)
    with open(os.path.join(micro_run_dir, "flag-calibrated.txt")) as f:
        # the run dir is session-shared: a calibrated sweep elsewhere may
        # have overwritten the state file — either state, one line
        assert f.read() in ("calibrated 0\n", "calibrated 1\n")


@pytest.mark.slow  # full metric sweep (~minutes on CPU)
def test_evaluate_cli_psi_sweep(micro_run_dir, capsys):
    """--psi-sweep: one metric table row per truncation value, appended to
    metric-psi-sweep.txt (the lineage's FID-vs-truncation practice; real
    stats are cached so extra psis only pay the fake-side sweep)."""
    import os

    from gansformer_tpu.cli.evaluate import main as evaluate

    evaluate(["--run-dir", micro_run_dir, "--metrics", "fid",
              "--num-images", "32", "--batch-size", "16",
              "--psi-sweep", "0.5,1.0"])
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    rows = payload["psi_sweep"]
    assert [r["psi"] for r in rows] == [0.5, 1.0]
    assert all(np.isfinite(v) for r in rows for v in r.values())
    sweep_txt = os.path.join(micro_run_dir, "metric-psi-sweep.txt")
    with open(sweep_txt) as f:
        tail = f.readlines()[-2:]          # file is append-only and the run
    assert "psi 0.50" in tail[0]           # dir is session-shared — check
    assert "psi 1.00" in tail[1]           # the rows THIS invocation wrote


def test_evaluate_cli_calibrated_npz_roundtrip(tmp_path, micro_run_dir,
                                               capsys):
    """evaluate --inception-npz with a synthetically CONVERTED checkpoint
    (VERDICT r3 item 5): the calibrated code path — converter output →
    load_params_npz → calibrated extractor → un-suffixed metric names —
    is exercised without any network access."""
    import os

    from gansformer_tpu.cli.evaluate import main as evaluate
    from gansformer_tpu.metrics.convert_inception import (
        from_torch_state_dict, save_npz)
    from tests.test_metrics import synthetic_torch_checkpoint

    npz = str(tmp_path / "cal.npz")
    save_npz(from_torch_state_dict(synthetic_torch_checkpoint()), npz)

    evaluate(["--run-dir", micro_run_dir, "--metrics", "fid",
              "--num-images", "16", "--batch-size", "16",
              "--inception-npz", npz,
              "--cache-dir", str(tmp_path / "cache")])
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "fid16" in payload, payload          # NOT fid16_uncal
    assert payload["calibrated"] == 1.0
    assert np.isfinite(payload["fid16"])
    assert os.path.exists(os.path.join(micro_run_dir, "metric-fid16.txt"))
    # flag routing under the CALIBRATED regime: state file flips to 1
    with open(os.path.join(micro_run_dir, "flag-calibrated.txt")) as f:
        assert f.read() == "calibrated 1\n"
    assert not os.path.exists(
        os.path.join(micro_run_dir, "metric-calibrated.txt"))


def test_generate_cli_grid_and_interpolation(tmp_path, micro_run_dir):
    """generate CLI: grid + latent-interpolation strips (the replication
    paper's smoothness figure) from a real checkpoint."""
    import os

    from PIL import Image

    from gansformer_tpu.cli.generate import main as generate

    out = str(tmp_path / "gen")
    generate(["--run-dir", micro_run_dir, "--grid", "--images-num", "8",
              "--batch-size", "8", "--interpolate", "2", "5",
              "--style-mix", "2", "3", "--out", out])
    grid = np.asarray(Image.open(os.path.join(out, "grid.png")))
    interp = np.asarray(Image.open(os.path.join(out, "interp.png")))
    mix = np.asarray(Image.open(os.path.join(out, "mix.png")))
    res = 16  # micro config resolution
    assert interp.shape == (2 * res, 5 * res, 3)  # rows x steps tiles
    assert mix.shape == (2 * res, 3 * res, 3)     # rows x cols tiles
    assert grid.size and interp.std() > 0 and mix.std() > 0


def test_serve_cli_warm_start_zero_compiles(tmp_path, micro_run_dir,
                                            capsys):
    """ISSUE 10 acceptance (CPU proxy): ``gansformer-serve`` against a
    real checkpoint — G-only restore, AOT programs, demo traffic — and
    a SECOND invocation against the populated manifest reaches first
    image with ZERO new program compiles.  Its telemetry.prom passes
    the serve-family schema lint."""
    import os

    from gansformer_tpu.analysis.telemetry_schema import (
        check_serve_metric_families)
    from gansformer_tpu.cli.serve import main as serve

    md = str(tmp_path / "manifest")
    out = str(tmp_path / "served")
    args = ["--run-dir", micro_run_dir, "--buckets", "1,2",
            "--images", "3", "--manifest-dir", md, "--out", out]
    serve(args)
    first = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert first["warm_start"]["compiled"] == 4          # 2 kinds × 2
    assert first["first_image_ms"] > 0

    serve(args)
    second = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert second["warm_start"] == {"compiled": 0, "loaded": 4,
                                    "seconds": second["warm_start"]
                                    ["seconds"]}
    assert second["first_image_ms"] > 0
    assert os.path.exists(os.path.join(out, "served_grid.png"))
    prom = os.path.join(out, "telemetry.prom")
    assert check_serve_metric_families(prom) == []


def test_serve_cli_healthcheck_grades_prom(tmp_path, capsys):
    """ISSUE 13: ``gansformer-serve --healthcheck`` grades an exported
    telemetry.prom without touching the accelerator — exit 0 for
    ready/degraded, 1 for unhealthy / dead-with-work / missing."""
    from gansformer_tpu.cli.serve import main as serve

    def write_prom(name, **vals):
        path = str(tmp_path / name)
        with open(path, "w") as f:
            for k, v in vals.items():
                f.write(f"# TYPE {k} gauge\n{k} {v}\n")
        return path

    ready = write_prom("ready.prom", serve_health_state=0,
                       serve_dispatcher_alive=1, serve_queue_depth_now=2,
                       serve_queue_bound=256, serve_shed_total=0)
    assert serve(["--healthcheck", ready]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["state"] == "ready" and out["ok"]

    tripped = write_prom("tripped.prom", serve_health_state=2,
                         serve_dispatcher_alive=0,
                         serve_queue_depth_now=0)
    assert serve(["--healthcheck", tripped]) == 1
    assert json.loads(capsys.readouterr().out)["state"] == "unhealthy"

    # degraded but alive-with-empty-queue is still serviceable
    degraded = write_prom("degraded.prom", serve_health_state=1,
                          serve_dispatcher_alive=1,
                          serve_queue_depth_now=1)
    assert serve(["--healthcheck", degraded]) == 0
    capsys.readouterr()

    # a CLEANLY closed service's final prom is ok, not an alarm
    closed = write_prom("closed.prom", serve_health_state=3,
                        serve_dispatcher_alive=0,
                        serve_queue_depth_now=0)
    assert serve(["--healthcheck", closed]) == 0
    assert json.loads(capsys.readouterr().out)["state"] == "closed"

    # dead dispatcher with queued work: probes must flag it
    dead = write_prom("dead.prom", serve_health_state=1,
                      serve_dispatcher_alive=0, serve_queue_depth_now=3)
    assert serve(["--healthcheck", dead]) == 1
    capsys.readouterr()

    assert serve(["--healthcheck", str(tmp_path / "absent.prom")]) == 1
    # a non-serving prom (no health gauge) is unknown, not ready
    blank = write_prom("blank.prom", device_sampler_off=1)
    assert serve(["--healthcheck", blank]) == 1
    capsys.readouterr()

    # staleness: a frozen last-good snapshot must not pass a liveness
    # probe forever — but stays gradeable without the age bound
    old = time.time() - 3600
    os.utime(ready, (old, old))
    assert serve(["--healthcheck", ready]) == 0       # age reported only
    assert json.loads(capsys.readouterr().out)["snapshot_age_s"] > 3000
    assert serve(["--healthcheck", ready,
                  "--health-max-age", "300"]) == 1
    assert json.loads(capsys.readouterr().out)["state"] == "stale"
    assert serve(["--healthcheck", ready,
                  "--health-max-age", "7200"]) == 0
    capsys.readouterr()


def test_config_validate_messages():
    """ExperimentConfig.validate fails fast with named errors instead of
    deep trace-time asserts (SURVEY.md §5 config row)."""
    from gansformer_tpu.core.config import (
        ExperimentConfig, MeshConfig, ModelConfig, TrainConfig)

    ok = ExperimentConfig()
    assert ok.validate() is ok

    bad = ExperimentConfig(
        model=ModelConfig(resolution=100, attention="quadplex",
                          attn_start_res=64, attn_max_res=8),
        train=TrainConfig(batch_size=9, pl_batch_shrink=2),
        mesh=MeshConfig(data=2))
    with pytest.raises(ValueError) as e:
        bad.validate()
    msg = str(e.value)
    for frag in ("power of two", "quadplex", "attn_start_res",
                 "pl_batch_shrink", "mesh.data", "mbstd_group_size"):
        assert frag in msg, msg

    # pallas is training-grade since ISSUE 9 (backward kernels + second-
    # order rule) — training configs must ACCEPT it; only unknown
    # backends are rejected, with both valid names in the message
    ExperimentConfig(model=ModelConfig(
        attention_backend="pallas")).validate()
    with pytest.raises(ValueError, match="xla|pallas"):
        ExperimentConfig(model=ModelConfig(
            attention_backend="mosaic")).validate()

    # sequence-parallel / mesh.model consistency both ways
    with pytest.raises(ValueError, match="sequence_parallel"):
        ExperimentConfig(mesh=MeshConfig(model=2)).validate()
    with pytest.raises(ValueError, match="mesh.model"):
        ExperimentConfig(model=ModelConfig(sequence_parallel=True)).validate()

    # pallas has no sharded kernel path: combined with sequence_parallel
    # the opaque pallas_call would make GSPMD all-gather the full n axis
    # per device — reject instead of silently un-sharding
    with pytest.raises(ValueError, match="sequence-parallel"):
        ExperimentConfig(
            model=ModelConfig(attention_backend="pallas",
                              sequence_parallel=True),
            mesh=MeshConfig(model=2)).validate()

    # every shipped preset is valid
    for name, preset in PRESETS.items():
        preset.validate()


def test_train_cli_attention_backend_tristate(tmp_path):
    """--attention-backend on the TRAIN CLI (ISSUE 9): tri-state like the
    other model flags — None inherits the loaded config (a resumed pallas
    run keeps its backend), an explicit flag overrides it, and the value
    passes the relaxed validate() rule."""
    from gansformer_tpu.core.config import ModelConfig

    saved = ExperimentConfig(model=ModelConfig(attention_backend="pallas"))
    path = tmp_path / "config.json"
    path.write_text(saved.to_json())

    args = build_parser().parse_args(["--config", str(path)])
    assert config_from_args(args).model.attention_backend == "pallas"

    args = build_parser().parse_args(
        ["--config", str(path), "--attention-backend", "xla"])
    assert config_from_args(args).model.attention_backend == "xla"

    args = build_parser().parse_args(["--attention-backend", "pallas"])
    cfg = config_from_args(args)       # validate() runs inside
    assert cfg.model.attention_backend == "pallas"

    # unknown values are an argparse error (matching the config rule)
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--attention-backend", "mosaic"])


def test_resume_inherits_mesh_layout(tmp_path):
    """--resume of a sequence-parallel run must keep the saved mesh layout
    without re-passing --mesh-model/--sequence-parallel (the mesh flags
    default to the loaded config's mesh)."""
    import dataclasses

    from gansformer_tpu.core.config import MeshConfig, ModelConfig

    saved = ExperimentConfig(
        model=ModelConfig(sequence_parallel=True),
        mesh=MeshConfig(data=4, model=2))
    path = tmp_path / "config.json"
    path.write_text(saved.to_json())

    args = build_parser().parse_args(["--config", str(path)])
    cfg = config_from_args(args)           # validate() runs inside
    assert cfg.mesh.model == 2 and cfg.mesh.data == 4
    assert cfg.model.sequence_parallel

    # explicit flags still override the saved layout
    args = build_parser().parse_args(
        ["--config", str(path), "--mesh-model", "4", "--mesh-data", "2"])
    cfg = config_from_args(args)
    assert cfg.mesh.model == 4 and cfg.mesh.data == 2

    # tri-state --sequence-parallel (ADVICE r3): the OFF direction must be
    # expressible on top of a loaded config that enabled it.
    args = build_parser().parse_args(
        ["--config", str(path), "--no-sequence-parallel", "--mesh-model", "1"])
    cfg = config_from_args(args)
    assert not cfg.model.sequence_parallel and cfg.mesh.model == 1
