"""Tests for the telemetry subsystem (gansformer_tpu/obs): span
nesting/accumulation on a fake clock, counter/gauge/histogram export
round-trips, heartbeat staleness on a monkeypatched clock, the
check_telemetry schema lint, and the loop-integration property that the
per-tick ``timing/phase/*`` breakdown actually accounts for the tick."""

import importlib.util
import json
import os

import pytest

from gansformer_tpu.obs.heartbeat import (
    Heartbeat, check_heartbeats, read_heartbeats)
from gansformer_tpu.obs.registry import Registry, prom_name
from gansformer_tpu.obs.spans import Tracer

_spec = importlib.util.spec_from_file_location(
    "check_telemetry",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts", "check_telemetry.py"))
ctl = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ctl)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --- spans -----------------------------------------------------------------

def test_span_nesting_self_vs_total():
    clk = FakeClock()
    tr = Tracer(time_fn=clk)
    with tr.span("outer"):
        clk.advance(1.0)
        with tr.span("inner"):
            clk.advance(2.0)
        clk.advance(0.5)
    totals = tr.drain()
    # self time excludes children; total is inclusive
    assert totals["outer"]["self_s"] == pytest.approx(1.5)
    assert totals["outer"]["total_s"] == pytest.approx(3.5)
    assert totals["inner"]["self_s"] == pytest.approx(2.0)
    # self times partition covered wall time — the invariant the loop's
    # timing/phase/* sum rests on
    assert sum(v["self_s"] for v in totals.values()) == pytest.approx(3.5)


def test_span_accumulates_across_entries_and_drain_resets():
    clk = FakeClock()
    tr = Tracer(time_fn=clk)
    for _ in range(3):
        with tr.span("phase"):
            clk.advance(1.0)
    totals = tr.drain()
    assert totals["phase"]["self_s"] == pytest.approx(3.0)
    assert totals["phase"]["count"] == 3
    assert tr.drain() == {}   # drained


def test_span_events_jsonl_schema(tmp_path):
    clk = FakeClock()
    tr = Tracer(time_fn=clk)
    events_path = str(tmp_path / "events.jsonl")
    tr.configure(events_path, process_index=3)
    with tr.span("a"):
        clk.advance(0.25)
        with tr.span("b"):
            clk.advance(0.5)
    tr.flush()
    lines = [json.loads(l) for l in open(events_path)]
    assert [e["name"] for e in lines] == ["b", "a"]   # children close first
    assert all(e["ph"] == "X" and e["pid"] == 3 for e in lines)
    assert lines[1]["dur"] == pytest.approx(0.75e6)   # microseconds
    assert ctl.check_events(events_path) == []


def test_tracer_configure_truncates_and_reset_discards(tmp_path):
    clk = FakeClock()
    tr = Tracer(time_fn=clk)
    path = str(tmp_path / "events.jsonl")
    tr.configure(path)
    with tr.span("old"):
        clk.advance(1.0)
    tr.flush()
    tr.configure(path)           # new run: truncate
    assert open(path).read() == ""
    with tr.span("x"):
        clk.advance(1.0)
    tr.reset()                   # run start discards stale totals
    assert tr.drain() == {}


def test_tracer_configure_resume_appends(tmp_path):
    """truncate=False (the loop's --resume path) preserves the crash-window
    events the aborted process flushed."""
    clk = FakeClock()
    tr = Tracer(time_fn=clk)
    path = str(tmp_path / "events.jsonl")
    tr.configure(path)
    with tr.span("crash_window"):
        clk.advance(1.0)
    tr.flush()
    tr.configure(path, truncate=False)   # resumed run appends
    with tr.span("resumed"):
        clk.advance(1.0)
    tr.flush()
    names = [json.loads(l)["name"] for l in open(path)]
    assert names == ["crash_window", "resumed"]
    # truncate=False with no pre-existing file still creates it
    tr2 = Tracer(time_fn=clk)
    fresh = str(tmp_path / "sub" / "events.jsonl")
    tr2.configure(fresh, truncate=False)
    with tr2.span("a"):
        clk.advance(0.5)
    tr2.flush()
    assert len(open(fresh).readlines()) == 1


# --- registry --------------------------------------------------------------

def test_registry_roundtrip_and_prom_export(tmp_path):
    reg = Registry()
    reg.counter("data/starved_total").inc()
    reg.counter("data/starved_total").inc(2)
    reg.gauge("data/prefetch_queue_depth").set(5)
    reg.gauge("device/mem_peak_bytes").max(100)
    reg.gauge("device/mem_peak_bytes").max(50)   # high-water keeps 100
    for v in (1.0, 3.0):
        reg.histogram("data/wait_ms").observe(v)

    snap = reg.snapshot()
    assert snap["counters"]["data/starved_total"] == 3
    assert snap["gauges"]["data/prefetch_queue_depth"] == 5
    assert snap["gauges"]["device/mem_peak_bytes"] == 100
    assert snap["histograms"]["data/wait_ms"] == {
        "count": 2, "sum": 4.0, "mean": 2.0, "min": 1.0, "max": 3.0}

    prom = str(tmp_path / "telemetry.prom")
    reg.write_prom(prom)
    text = open(prom).read()
    assert "data_starved_total 3" in text
    assert "# TYPE data_prefetch_queue_depth gauge" in text
    assert "data_wait_ms_count 2" in text and "data_wait_ms_sum 4" in text
    assert ctl.check_prom(prom) == []


def test_registry_same_name_same_instrument_and_type_conflict():
    reg = Registry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    with pytest.raises(ValueError):
        reg.counter("x").inc(-1)


def test_prom_name_sanitization():
    assert prom_name("data/wait_ms") == "data_wait_ms"
    assert prom_name("timing/phase/step") == "timing_phase_step"
    assert prom_name("0bad") == "_0bad"


# --- heartbeats ------------------------------------------------------------

def test_heartbeat_write_and_staleness(tmp_path):
    clk = FakeClock()
    d = str(tmp_path)
    hb0 = Heartbeat(d, 0, time_fn=clk)
    hb1 = Heartbeat(d, 1, time_fn=clk)
    hb0.beat(step=1000, kimg=1.0)
    clk.advance(10.0)
    hb1.beat(step=1000, kimg=1.0)

    beats = read_heartbeats(d)
    assert set(beats) == {0, 1} and beats[0]["step"] == 1000

    # both fresh at now=+5s from hb1's beat
    res = check_heartbeats(d, max_age_s=30.0, now=clk.t + 5.0)
    assert res["ok"] and res["stale"] == [] and res["missing"] == []
    # p0 beat 10 s before p1: at max_age 12 only p0 is stale
    res = check_heartbeats(d, max_age_s=12.0, now=clk.t + 5.0)
    assert not res["ok"] and res["stale"] == [0]
    # a dead peer that NEVER wrote is only visible with a roster
    res = check_heartbeats(d, max_age_s=30.0, expected=[0, 1, 2],
                           now=clk.t + 5.0)
    assert not res["ok"] and res["missing"] == [2]
    for p in sorted(os.listdir(d)):
        errs = ctl.check_heartbeat(os.path.join(d, p))
        assert errs == [], errs


def test_heartbeat_step_skew_straggler(tmp_path):
    """ISSUE 8 satellite: the probe reports max inter-process step skew
    and folds it into ``ok`` only when a threshold is given."""
    clk = FakeClock()
    d = str(tmp_path)
    Heartbeat(d, 0, time_fn=clk).beat(step=4000, kimg=4.0)
    Heartbeat(d, 1, time_fn=clk).beat(step=2400, kimg=2.4)

    res = check_heartbeats(d, max_age_s=30.0, now=clk.t)
    assert res["steps"] == {0: 4000, 1: 2400}
    assert res["step_skew"] == 1600
    assert res["ok"] and not res["skew_exceeded"]   # no threshold: report

    res = check_heartbeats(d, max_age_s=30.0, now=clk.t,
                           max_step_skew=1000)
    assert not res["ok"] and res["skew_exceeded"]
    assert check_heartbeats(d, max_age_s=30.0, now=clk.t,
                            max_step_skew=1600)["ok"]   # boundary: not >
    # single process: zero skew by definition
    solo = check_heartbeats(d + "/nope", max_age_s=30.0, now=clk.t)
    assert solo["step_skew"] == 0


# --- loop integration ------------------------------------------------------

def test_loop_telemetry_artifacts(micro_run_dir):
    """The acceptance property: a smoke train run produces events.jsonl,
    telemetry.prom, heartbeat-p0.json, and per-tick timing/phase/* stats
    whose sum accounts for sec_per_tick (within 20%)."""
    d = micro_run_dir
    lines = [json.loads(l) for l in open(os.path.join(d, "stats.jsonl"))]
    assert lines
    for rec in lines:
        phases = {k: v for k, v in rec.items()
                  if k.startswith("timing/phase/")}
        assert phases, f"tick {rec.get('Progress/tick')} has no phases"
        assert "timing/phase/step" in phases
        assert "timing/phase/data_wait" in phases
        ratio = sum(phases.values()) / rec["timing/sec_per_tick"]
        assert 0.8 <= ratio <= 1.2, (ratio, phases)
        assert 0.0 <= rec["timing/data_wait_frac"] <= 1.0
        # absolute wait on the record (VERDICT r5 item 8): seconds spent
        # blocked in next(batches), consistent with the frac view
        assert rec["timing/data_wait_s"] >= 0.0
        assert rec["timing/data_wait_s"] == pytest.approx(
            rec["timing/data_wait_frac"] * rec["timing/sec_per_tick"],
            abs=1e-3)
        assert rec["timing/data_wait_s"] <= rec["timing/sec_per_tick"]
        # the registry snapshot rides along in the jsonl record
        assert "telemetry" in rec
        assert rec["telemetry"]["counters"]["data/batches_total"] > 0

    result = ctl.check_run_dir(d)
    assert result["ok"], result["errors"]
    res = check_heartbeats(d, max_age_s=24 * 3600.0, expected=[0])
    assert res["ok"], res
    # Retrace cross-check (ISSUE 4 satellite): the watch armed at tick
    # 0's boundary; every later tick's record must carry the counter —
    # and a clean run must show ZERO post-warm-up compiles, the runtime
    # confirmation of the static retrace-hazard rule's prediction.
    later = [rec for rec in lines if rec.get("Progress/tick", 0) >= 1]
    assert later
    for rec in later:
        assert rec["telemetry"]["counters"]["compile/retraces_total"] == 0.0
    prom = open(os.path.join(d, "telemetry.prom")).read()
    assert "compile_retraces_total 0.0" in prom


def test_loop_device_truth_gauges(micro_run_dir):
    """ISSUE 8 acceptance: the micro run's telemetry.prom carries the
    device/* family (the periodic sampler fires at tick 1 under the
    default cadence), hbm/* (the explicit CPU-unavailable marker), and
    compile/compiles_total — and the wall-vs-device divergence gauge is
    populated because a sample landed."""
    from gansformer_tpu.cli.telemetry import read_prom_values

    vals = read_prom_values(micro_run_dir)
    # sampler on (default cadence), ≥1 sample landed on the 3-tick run
    assert vals["device_sampler_off"] == 0.0
    assert vals["device_samples_total"] >= 1.0
    assert vals["device_busy_ms"] > 0.0
    # divergence gauge populated whenever a sample lands; after the
    # python-tracer-frame filter busy can never exceed the synced wall
    # by more than scheduling noise
    assert 0.0 < vals["device_wall_busy_ratio"] < 1.1
    # per-program attribution names the REAL step programs (the named
    # partials in train/steps.py)
    assert any(k.startswith("device_phase_ms_d_step") for k in vals)
    # hbm family: CPU backend reports no memory stats → explicit marker
    assert vals["hbm_unavailable"] == 1.0
    # compile family (renamed from xla/* in ISSUE 8)
    assert vals["compile_compiles_total"] >= 0.0
    assert "xla_compile_count" not in vals
    # the registry snapshot in stats.jsonl carries the same gauges
    lines = [json.loads(l)
             for l in open(os.path.join(micro_run_dir, "stats.jsonl"))]
    last_g = lines[-1]["telemetry"]["gauges"]
    assert "device/wall_busy_ratio" in last_g
    assert last_g["hbm/unavailable"] == 1.0


def test_doctor_exits_zero_on_micro_run(micro_run_dir, capsys):
    """ISSUE 8 acceptance: ``gansformer-telemetry doctor <run_dir>``
    exits 0 with a rendered report on the CPU micro run."""
    from gansformer_tpu.cli.telemetry import main as cli_main

    cli_main(["doctor", micro_run_dir])       # SystemExit(1) would raise
    out = capsys.readouterr().out
    assert "run doctor:" in out and "verdict: OK" in out
    assert "device_truth" in out and "hbm" in out and "compiles" in out


def test_read_events_skips_torn_final_line(tmp_path):
    """A SIGKILL mid-append leaves a torn last line; the trace CLI must
    still read the crash-window events before it."""
    from gansformer_tpu.cli.telemetry import read_events

    with open(tmp_path / "events.jsonl", "w") as f:
        f.write(json.dumps({"name": "a", "ph": "X", "ts": 0.0, "dur": 1.0,
                            "pid": 0, "tid": 1}) + "\n")
        f.write('{"name": "torn", "ph"')
    assert [e["name"] for e in read_events(str(tmp_path))] == ["a"]


def test_loop_events_convert_to_chrome_trace(micro_run_dir, tmp_path):
    from gansformer_tpu.cli.telemetry import (
        summarize_events, read_events, write_chrome_trace)

    out = write_chrome_trace(micro_run_dir, str(tmp_path / "trace.json"))
    trace = json.load(open(out))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"data_wait", "step", "tick_fetch", "snapshot"} <= names
    rows = summarize_events(read_events(micro_run_dir))
    assert rows and rows[0]["total_ms"] >= rows[-1]["total_ms"]


# --- device-time sampler units (ISSUE 8) ------------------------------------

def test_device_sampler_off_marker_and_cadence():
    from gansformer_tpu import obs

    reg = obs.get_registry()
    reg.reset()
    s = obs.DeviceTimeSampler(every_ticks=0)
    assert not s.enabled
    assert reg.snapshot()["gauges"]["device/sampler_off"] == 1.0

    reg.reset()
    s = obs.DeviceTimeSampler(every_ticks=4)
    snap = reg.snapshot()
    assert snap["gauges"]["device/sampler_off"] == 0.0
    assert snap["counters"]["device/samples_total"] == 0.0   # explicit 0
    # cadence: only tick % every == 1 starts (and enabled=False never)
    assert not s.maybe_start(2) and not s.maybe_start(4)
    assert not obs.DeviceTimeSampler(every_ticks=4,
                                     enabled=False).maybe_start(1)
    # every=1 means EVERY boundary (tick % 1 is 0, never 1 — the naive
    # cadence check would make the maximum-sampling setting sample never)
    s1 = obs.DeviceTimeSampler(every_ticks=1)
    try:
        assert s1.maybe_start(3) and s1.sampling
    finally:
        s1.close()
    reg.reset()


def test_device_sampler_folds_real_trace():
    """Start → run a jitted op → stop_and_fold populates the device/*
    gauges from a REAL profiler trace."""
    import jax
    import jax.numpy as jnp

    from gansformer_tpu import obs

    reg = obs.get_registry()
    reg.reset()
    s = obs.DeviceTimeSampler(every_ticks=2, flops_per_it=1e9,
                              peak_tflops=1.0)
    assert s.maybe_start(1) and s.sampling

    def d_step(x):
        return x @ x

    f = jax.jit(d_step)
    x = jnp.ones((64, 64))
    for _ in range(3):
        x = f(x)
    jax.block_until_ready(x)
    rep = s.stop_and_fold(wall_s=0.5, iters=10)
    assert rep["status"] == "ok" and not s.sampling
    g = reg.snapshot()["gauges"]
    assert g["device/wall_ms"] == pytest.approx(500.0)
    assert g["device/busy_ms"] > 0.0
    assert g["device/wall_busy_ratio"] == pytest.approx(
        g["device/busy_ms"] / 500.0)
    assert g["device/unavailable"] == 0.0
    # device-time MFU: flops_per_it × iters / busy / peak
    assert g["device/mfu"] == pytest.approx(
        1e9 * 10 / (g["device/busy_ms"] / 1e3) / 1e12)
    assert reg.snapshot()["counters"]["device/samples_total"] == 1.0
    # stop without an active trace is a no-op
    assert s.stop_and_fold() is None
    reg.reset()


def test_device_sampler_unavailable_sentinel(monkeypatch):
    """A trace neither parser can read folds as the unavailable marker,
    not an exception."""
    import jax
    import jax.numpy as jnp

    from gansformer_tpu import obs
    from gansformer_tpu.utils import profparse

    reg = obs.get_registry()
    reg.reset()
    monkeypatch.setattr(
        profparse, "parse_trace_events",
        lambda trace_dir: (None, "no parseable trace (forced)"))
    s = obs.DeviceTimeSampler(every_ticks=2)
    assert s.maybe_start(1)
    jax.block_until_ready(jnp.ones(4) + 1)
    rep = s.stop_and_fold(wall_s=0.1)
    assert rep["status"] == "unavailable"
    snap = reg.snapshot()
    assert snap["gauges"]["device/unavailable"] == 1.0
    assert snap["counters"]["device/sample_failed_total"] == 1.0
    assert snap["counters"]["device/samples_total"] == 0.0
    reg.reset()


# --- ReZero attention-gate observability (ISSUE 5 satellite) ----------------

def test_wattn_gate_stats_duplex_attention_style():
    """A duplex style_mode='attention' generator exposes its ReZero gates
    as gates/wattn_* stats — exactly 0.0 at init (the ReZero contract),
    so a run where they never move is visible in stats.jsonl."""
    import dataclasses

    import jax

    from gansformer_tpu.train.loop import wattn_gate_stats
    from gansformer_tpu.train.state import create_train_state
    from tests.test_train import micro_cfg

    cfg = micro_cfg(attention="duplex")
    cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, style_mode="attention"))
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    stats = wattn_gate_stats(state.g_params)
    assert stats == {"gates/wattn_max": 0.0, "gates/wattn_mean": 0.0}

    # after a parameter nudge the magnitude registers
    import jax.numpy as jnp

    bumped = jax.tree_util.tree_map_with_path(
        lambda path, v: (v + 0.25 if any(
            "wattn_gate" in str(getattr(k, "key", k)) for k in path)
            else v),
        state.g_params)
    stats = wattn_gate_stats(bumped)
    assert stats["gates/wattn_max"] == pytest.approx(0.25)
    assert stats["gates/wattn_mean"] == pytest.approx(0.25)


def test_wattn_gate_stats_absent_without_gates():
    import jax

    from gansformer_tpu.train.loop import wattn_gate_stats
    from gansformer_tpu.train.state import create_train_state
    from tests.test_train import micro_cfg

    state = create_train_state(micro_cfg(), jax.random.PRNGKey(0))
    assert wattn_gate_stats(state.g_params) is None   # style_mode=global


def test_micro_run_stats_have_no_gate_keys(micro_run_dir):
    """The simplex/global micro run must not emit gates/* keys (absence
    is the signal that the config has no attention-styling path)."""
    lines = [json.loads(l)
             for l in open(os.path.join(micro_run_dir, "stats.jsonl"))]
    for rec in lines:
        assert not any(k.startswith("gates/") for k in rec)
