"""Loss & regularizer tests — analytic toy cases (SURVEY.md §7.1 item 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.losses.gan import (
    d_logistic_loss,
    g_nonsaturating_loss,
    path_length_penalty,
    r1_penalty,
)


def test_g_ns_loss_values():
    # softplus(-x): large positive logits → ~0 loss; zero logits → log 2
    assert float(g_nonsaturating_loss(jnp.array([100.0]))) < 1e-6
    np.testing.assert_allclose(
        float(g_nonsaturating_loss(jnp.zeros(4))), np.log(2), rtol=1e-6)


def test_d_logistic_loss_values():
    # perfect D: real +inf, fake -inf → 0
    v = d_logistic_loss(jnp.array([50.0]), jnp.array([-50.0]))
    assert float(v) < 1e-6
    # chance: both zero → 2 log 2
    v = d_logistic_loss(jnp.zeros(3), jnp.zeros(3))
    np.testing.assert_allclose(float(v), 2 * np.log(2), rtol=1e-6)


def test_r1_penalty_analytic():
    # D(x) = <a, x> → grad = a everywhere → penalty = ||a||²
    a = jnp.array([[1.0, 2.0], [3.0, 4.0]])  # [H,W] single-channel-ish

    def d_score(x):  # x: [N,2,2]
        return jnp.sum(x * a[None], axis=(1, 2))

    reals = jnp.ones((5, 2, 2))
    np.testing.assert_allclose(
        float(r1_penalty(d_score, reals)), float(jnp.sum(a * a)), rtol=1e-6)


def test_r1_penalty_second_order_differentiable():
    # d(R1)/d(theta) must exist: D(x) = theta * ||x||² → grad_x = 2 theta x
    # → R1 = 4 theta² E||x||² → dR1/dtheta = 8 theta E||x||²
    reals = jnp.array([[1.0, 0.0], [0.0, 2.0]])  # [N=2, D=2]

    def r1_of_theta(theta):
        return r1_penalty(lambda x: theta * jnp.sum(x * x, axis=1), reals)

    theta = 0.7
    got = float(jax.grad(r1_of_theta)(theta))
    expect = 8 * theta * float(jnp.mean(jnp.sum(reals * reals, axis=1)))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_path_length_penalty_linear_map():
    # synthesize(w) = W @ w for orthogonal-ish W → path lengths are
    # deterministic-ish; just check shapes, finiteness, EMA update direction.
    rng = jax.random.PRNGKey(0)
    w_mat = jax.random.normal(rng, (2 * 2 * 1, 3 * 4))  # img 2x2x1 from ws 3x4

    def synth(ws):  # ws [N,3,4] → img [N,2,2,1]
        flat = ws.reshape(ws.shape[0], -1) @ w_mat.T
        return flat.reshape(-1, 2, 2, 1)

    ws = jax.random.normal(jax.random.fold_in(rng, 1), (4, 3, 4))
    pl_mean = jnp.zeros(())
    pen, new_mean = path_length_penalty(synth, ws, pl_mean,
                                        jax.random.fold_in(rng, 2))
    assert np.isfinite(float(pen)) and float(pen) >= 0
    assert float(new_mean) > 0  # EMA moved toward observed lengths

    # differentiable w.r.t. the map (i.e. G's params)
    def pen_of_scale(s):
        p, _ = path_length_penalty(lambda w: s * synth(w), ws, pl_mean,
                                   jax.random.fold_in(rng, 2))
        return p

    g = float(jax.grad(pen_of_scale)(1.0))
    assert np.isfinite(g)
