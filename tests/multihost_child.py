"""Child process for the 2-process multi-host test (tests/test_multihost.py).

Each process: joins the coordinator (jax.distributed.initialize), exposes 4
virtual CPU devices (8 global), builds the global mesh, produces only its
LOCAL shard of the batch, assembles the global array, runs one sharded
d_step, and participates in the run-id broadcast — i.e. every multi-host
code path of parallel/mesh.py + train/loop.py that single-process tests
cannot reach (VERDICT r2 item 6).

Not named test_*.py: pytest must not collect it.
"""

import json
import os
import sys

# sanitized child env has no PYTHONPATH; make the repo root importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    port, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax

    # jax's enum flags never read env vars (0.4.37: config.enum_flag has
    # no getenv), so the spawner's JAX_CPU_COLLECTIVES_IMPLEMENTATION must
    # be forwarded into the config by hand — without it the CPU client has
    # no cross-process collectives and the first sharded dispatch dies with
    # "Multiprocess computations aren't implemented on the CPU backend".
    jax.config.update(
        "jax_cpu_collectives_implementation",
        os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo"))

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2, process_id=pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    import numpy as np

    from gansformer_tpu.core.config import (
        DataConfig, ExperimentConfig, MeshConfig, ModelConfig, TrainConfig)
    from gansformer_tpu.parallel.mesh import local_batch_size, make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    # 2D mesh: 4-way data parallel x 2-way sequence/context parallel —
    # multi-host AND the grid-axis sharding of every attention block
    # (ModelConfig.sequence_parallel) in one exercise.
    cfg = ExperimentConfig(
        model=ModelConfig(resolution=16, components=2, latent_dim=16,
                          w_dim=16, mapping_dim=16, mapping_layers=2,
                          fmap_base=64, fmap_max=32, attention="duplex",
                          attn_start_res=8, attn_max_res=8,
                          mbstd_group_size=2, sequence_parallel=True),
        train=TrainConfig(batch_size=16),
        data=DataConfig(resolution=16, source="synthetic"),
        mesh=MeshConfig(data=4, model=2))
    env = make_mesh(cfg.mesh)
    assert env.mesh.size == 8 and env.model_size == 2

    global_batch = 16
    lbs = local_batch_size(global_batch, env)          # 8 per process
    # Each process contributes a DIFFERENT local shard (seeded by pid) —
    # the loop's per-host shard model (train/loop.py put_batch).
    imgs_local = np.random.RandomState(pid).randint(
        0, 255, (lbs, 16, 16, 3), dtype=np.uint8)
    batch = jax.make_array_from_process_local_data(env.batch(), imgs_local)
    assert batch.shape[0] == global_batch

    with env.activate():   # ambient mesh for the SP grid constraints
        state = create_train_state(cfg, jax.random.PRNGKey(0))
        state = jax.device_put(state, env.replicated())
        fns = make_train_steps(cfg, env, batch_size=global_batch)
        # AOT-compile the first collective programs, THEN rendezvous, THEN
        # dispatch: the first dispatch forms the gloo clique, whose
        # key-value exchange carries a hard 30 s deadline inside XLA — far
        # less than the import/trace/COMPILE skew two children can
        # accumulate on a loaded single-core host (observed r5: DEADLINE_
        # EXCEEDED flakes whenever a background run shares the box).  With
        # the compiles paid up front and the coordinator's KV barrier
        # (configurable timeout) crossed after them, both processes reach
        # the clique formation within milliseconds of each other.
        # Only the FIRST program is AOT'd: its dispatch forms the clique;
        # g_step's jit call happens after the clique exists, and AOT-ing
        # it too would require matching the d-output's propagated
        # shardings exactly (AOT calls don't auto-reshard).
        d_exec = fns.d_step.lower(
            state, batch, jax.random.PRNGKey(1)).compile()

        from jax._src import distributed

        distributed.global_state.client.wait_at_barrier(
            "child_precompiled", timeout_in_ms=600_000)

        state, aux = d_exec(state, batch, jax.random.PRNGKey(1))
        state, g_aux = fns.g_step(state, jax.random.PRNGKey(2))
        jax.block_until_ready(state.step)

    # run-dir id broadcast (cli/train.py multi-host run-dir agreement)
    from jax.experimental import multihost_utils

    rid = multihost_utils.broadcast_one_to_all(
        np.int32(42 if pid == 0 else 0))

    leaves = jax.tree_util.tree_leaves(jax.device_get(state.d_params))
    cks = float(sum(np.float64(np.abs(l).sum()) for l in leaves))

    # ---- full tick loop on 2 processes (VERDICT r3 item 3): 2 ticks with
    # checkpoint save, image snapshot, then a tiny metric sweep whose
    # values must come out IDENTICAL on both processes.
    import dataclasses

    from gansformer_tpu.data.dataset import make_dataset
    from gansformer_tpu.metrics.inception import make_extractor
    from gansformer_tpu.metrics.metric_base import (
        MetricGroup, parse_metric_names)
    from gansformer_tpu.train.loop import train
    from gansformer_tpu.train.steps import make_metric_samplers
    from gansformer_tpu.utils.logging import RunLogger

    # fused_cycle=True: the tick loop dispatches one jitted program per
    # lazy-reg cycle, exercising the STACKED multi-host input path
    # (put_stack → make_array_from_process_local_data on [K, B, ...]).
    # d_reg=4/g_reg=2 keeps the cycle program small while still covering
    # the nested block scan.
    loop_cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(
            cfg.train, total_kimg=2, kimg_per_tick=1, snapshot_ticks=2,
            image_snapshot_ticks=1, metric_ticks=0, seed=5,
            d_reg_interval=4, g_reg_interval=2, fused_cycle=True),
    )
    run_dir = os.path.join(outdir, "run")
    os.makedirs(run_dir, exist_ok=True)
    state2 = train(loop_cfg, run_dir, env=env,
                   logger=RunLogger(run_dir, active=(pid == 0)))
    assert int(jax.device_get(state2.step)) >= 2000

    dataset2 = make_dataset(loop_cfg.data)
    fns2 = make_train_steps(loop_cfg, env, batch_size=16)
    with env.activate():
        group = MetricGroup(
            parse_metric_names("fid32,ppl32", batch_size=16),
            extractor=make_extractor(env=env), cache_dir=None)
        sample_fn, mpair_fn = make_metric_samplers(
            fns2, state2, loop_cfg, env, dataset2, seed=11)
        metric_res = group.run(sample_fn, dataset2, pair_fn=mpair_fn)

    leaves2 = jax.tree_util.tree_leaves(jax.device_get(state2.g_params))
    cks2 = float(sum(np.float64(np.abs(l).sum()) for l in leaves2))
    with open(os.path.join(outdir, f"p{pid}.json"), "w") as f:
        json.dump({"rid": int(rid), "lbs": lbs, "cks": cks,
                   "loss_d": float(jax.device_get(aux["Loss/D"])),
                   "loss_g": float(jax.device_get(g_aux["Loss/G"])),
                   "loop_cks": cks2,
                   "metrics": {k: float(v) for k, v in metric_res.items()},
                   "run_dir_files": sorted(
                       fn for fn in os.listdir(run_dir)
                       if not fn.startswith("."))}, f)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
