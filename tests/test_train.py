"""Training-engine tests on the 8-device CPU mesh (SURVEY.md §4): real
sharded steps, all four lazy-reg phase variants, EMA, checkpoint round-trip.
Shapes are micro to bound compile time."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.core.config import (
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, TrainConfig)
from gansformer_tpu.parallel.mesh import make_mesh
from gansformer_tpu.train.state import create_train_state, param_count
from gansformer_tpu.train.steps import make_train_steps


def micro_cfg(attention="simplex", batch=8):
    return ExperimentConfig(
        name="micro",
        model=ModelConfig(resolution=16, components=2, latent_dim=16,
                          w_dim=16, mapping_dim=16, mapping_layers=2,
                          fmap_base=64, fmap_max=32, attention=attention,
                          attn_start_res=8, attn_max_res=8, mbstd_group_size=4),
        # device_time_ticks=0: the suite runs MANY short train()s — the
        # sampler's profiler warm-up + traced tick would cost ~15 s per
        # fresh process for nothing; the session-scoped micro_run_dir
        # fixture (tests/conftest.py) re-enables it so the device-truth
        # path is exercised exactly once.
        train=TrainConfig(batch_size=batch, total_kimg=1, d_reg_interval=2,
                          g_reg_interval=2, pl_batch_shrink=2,
                          ema_kimg=0.01, style_mixing_prob=0.5,
                          device_time_ticks=0),
        data=DataConfig(resolution=16, source="synthetic"),
        mesh=MeshConfig(),
    )


@pytest.fixture(scope="module")
def trained():
    """Run 4 full iterations (covers all 4 phase variants) once; reuse."""
    cfg = micro_cfg()
    env = make_mesh(cfg.mesh)
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    state = jax.device_put(state, env.replicated())
    fns = make_train_steps(cfg, env, batch_size=cfg.train.batch_size)
    imgs = jax.device_put(
        np.random.RandomState(0).randint(
            0, 255, (cfg.train.batch_size, 16, 16, 3), dtype=np.uint8),
        env.batch())
    rng = jax.random.PRNGKey(1)
    auxes = []
    for it in range(4):
        d_fn = fns.d_step_r1 if it % 2 == 0 else fns.d_step
        g_fn = fns.g_step_pl if it % 2 == 0 else fns.g_step
        state, d_aux = d_fn(state, imgs, jax.random.fold_in(rng, 2 * it))
        state, g_aux = g_fn(state, jax.random.fold_in(rng, 2 * it + 1))
        auxes.append({**d_aux, **g_aux})
    jax.block_until_ready(state.step)
    return cfg, env, fns, state, auxes


def test_losses_finite_all_variants(trained):
    _, _, _, _, auxes = trained
    for aux in auxes:
        for k, v in aux.items():
            assert np.isfinite(float(jax.device_get(v))), k
    assert "Loss/D/r1" in auxes[0] and "Loss/G/pl" in auxes[0]
    assert "Loss/D/r1" not in auxes[1]


def test_step_counts_images(trained):
    cfg, _, _, state, _ = trained
    assert int(jax.device_get(state.step)) == 4 * cfg.train.batch_size


def test_ema_and_pl_mean_updated(trained):
    _, _, _, state, _ = trained
    assert float(jax.device_get(state.pl_mean)) > 0
    diff = jax.tree_util.tree_map(
        lambda e, p: float(jnp.max(jnp.abs(e - p))),
        state.ema_params, state.g_params)
    assert max(jax.tree_util.tree_leaves(diff)) > 0  # EMA lags G


def test_params_changed_and_finite(trained):
    cfg, _, _, state, _ = trained
    fresh = create_train_state(cfg, jax.random.PRNGKey(0))
    moved = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - jnp.asarray(b)))),
        jax.device_get(state.g_params), fresh.g_params)
    assert max(jax.tree_util.tree_leaves(moved)) > 1e-6
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.g_params)):
        assert np.all(np.isfinite(leaf))
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.d_params)):
        assert np.all(np.isfinite(leaf))


def test_sampler_truncation(trained):
    cfg, _, fns, state, _ = trained
    z = jax.random.normal(jax.random.PRNGKey(5),
                          (4, cfg.model.num_ws, cfg.model.latent_dim))
    k = jax.random.PRNGKey(6)
    full = fns.sample(state.ema_params, state.w_avg, z, k, truncation_psi=1.0)
    trunc = fns.sample(state.ema_params, state.w_avg, z, k, truncation_psi=0.5)
    assert full.shape == (4, 16, 16, 3)
    assert not np.allclose(np.asarray(full), np.asarray(trunc))


def test_checkpoint_roundtrip(trained, tmp_path):
    cfg, _, _, state, _ = trained
    from gansformer_tpu.train import checkpoint as ckpt

    host_state = jax.device_get(state)
    ckpt.save(str(tmp_path / "ck"), host_state, cfg)
    assert ckpt.latest_step(str(tmp_path / "ck")) == int(host_state.step)
    template = create_train_state(cfg, jax.random.PRNGKey(0))
    restored = ckpt.restore(str(tmp_path / "ck"), template)
    np.testing.assert_array_equal(np.asarray(restored.step),
                                  np.asarray(host_state.step))
    a = jax.tree_util.tree_leaves(restored.g_params)
    b = jax.tree_util.tree_leaves(host_state.g_params)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # optimizer state round-trips too (deliberate improvement over the
    # reference, which resets Adam moments — SURVEY.md §7.4)
    a = jax.tree_util.tree_leaves(restored.d_opt)
    b = jax.tree_util.tree_leaves(host_state.d_opt)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_gradients_identical_across_mesh_sizes():
    """DP invariance: same global batch on 1-device vs 8-device mesh gives
    the same updated params (XLA psum == single-device mean)."""
    cfg = micro_cfg(batch=8)
    imgs = np.random.RandomState(0).randint(
        0, 255, (8, 16, 16, 3), dtype=np.uint8)
    rng = jax.random.PRNGKey(3)
    results = []
    for devs in (jax.devices()[:1], jax.devices()[:8]):
        env = make_mesh(cfg.mesh, devices=devs)
        state = create_train_state(cfg, jax.random.PRNGKey(0))
        state = jax.device_put(state, env.replicated())
        fns = make_train_steps(cfg, env, batch_size=8)
        sharded = jax.device_put(imgs, env.batch())
        state, _ = fns.d_step(state, sharded, rng)
        results.append(jax.device_get(state.d_params))
    a = jax.tree_util.tree_leaves(results[0])
    b = jax.tree_util.tree_leaves(results[1])
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-4, atol=2e-5)


def test_conditional_train_steps_and_sampler():
    """All four phase variants + sampler run with labels (VERDICT r2
    item 7); conditional params exist and receive gradients."""
    cfg = micro_cfg()
    cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, label_dim=6))
    env = make_mesh(cfg.mesh)
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    assert "label_embed" in state.g_params["mapping"]
    assert "label_embed" in state.d_params
    state = jax.device_put(state, env.replicated())
    fns = make_train_steps(cfg, env, batch_size=8)
    imgs = jax.device_put(
        np.random.RandomState(0).randint(0, 255, (8, 16, 16, 3), np.uint8),
        env.batch())
    labels = jax.device_put(
        np.eye(6, dtype=np.float32)[np.arange(8) % 6], env.batch())
    rng = jax.random.PRNGKey(1)
    for it in range(2):
        d_fn = fns.d_step_r1 if it == 0 else fns.d_step
        g_fn = fns.g_step_pl if it == 0 else fns.g_step
        state, d_aux = d_fn(state, imgs, jax.random.fold_in(rng, it), labels)
        state, g_aux = g_fn(state, jax.random.fold_in(rng, it + 9), labels)
        for v in {**d_aux, **g_aux}.values():
            assert np.isfinite(float(jax.device_get(v)))
    # conditional embeds moved (got gradients)
    fresh = create_train_state(cfg, jax.random.PRNGKey(0))
    moved = np.max(np.abs(
        np.asarray(jax.device_get(
            state.d_params["label_embed"]["w"]))
        - np.asarray(fresh.d_params["label_embed"]["w"])))
    assert moved > 0
    z = jax.random.normal(jax.random.PRNGKey(5),
                          (4, cfg.model.num_ws, cfg.model.latent_dim))
    out = fns.sample(state.ema_params, state.w_avg, z, rng,
                     truncation_psi=0.7, label=jax.device_get(labels)[:4])
    assert out.shape == (4, 16, 16, 3)


def test_mbstd_sharding_collectives():
    """Verify (not just assert in a comment — VERDICT r2 weak #8) what
    GSPMD does with minibatch_stddev's consecutive-group reshape under a
    sharded batch: group-aligned shards (the flagship batch-8/chip, group-4
    case) compile with ZERO collectives; straddling groups insert small
    all-reduces over the group stats — never an activation all-gather."""
    import re
    from collections import Counter

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from gansformer_tpu.models.layers import minibatch_stddev

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8, 1), ("data", "model"))
    sh = NamedSharding(mesh, P("data"))

    def compiled_collectives(batch):
        x = jax.device_put(jnp.ones((batch, 4, 4, 8)), sh)
        jf = jax.jit(lambda x: minibatch_stddev(x, 4, 1), out_shardings=sh)
        hlo = jf.lower(x).compile().as_text()
        return Counter(re.findall(
            r"\b(all-gather|all-reduce|collective-permute|all-to-all"
            r"|reduce-scatter)\b", hlo))

    aligned = compiled_collectives(32)      # 4/shard == group size
    assert not aligned, f"aligned groups must be shard-local: {aligned}"
    straddle = compiled_collectives(16)     # 2/shard, groups straddle
    assert "all-gather" not in straddle     # stats-only comm is acceptable


@pytest.mark.slow  # jits the full step twice (sharded + unsharded)
def test_sequence_parallel_grid_sharding_parity():
    """ModelConfig.sequence_parallel shards every attention block's n = H*W
    grid axis over the mesh's model axis via GSPMD constraints
    (models/attention.py _constrain).  Same params, 4x2 data-x-model mesh:
    the full d_step_r1 + g_step_pl pair must reproduce the 1D-mesh run
    (GSPMD is held to parity with the hand-written collective kernel, which
    tests/test_ops.py verifies against the plain op)."""
    results = {}
    for sp in (False, True):
        cfg = micro_cfg(attention="duplex")
        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(cfg.model, sequence_parallel=sp),
            mesh=MeshConfig(data=4, model=2) if sp else MeshConfig(data=8),
        )
        env = make_mesh(cfg.mesh)
        with env.activate():
            state = create_train_state(cfg, jax.random.PRNGKey(0))
            state = jax.device_put(state, env.replicated())
            fns = make_train_steps(cfg, env, batch_size=cfg.train.batch_size)
            imgs = jax.device_put(
                np.random.RandomState(0).randint(
                    0, 255, (cfg.train.batch_size, 16, 16, 3), dtype=np.uint8),
                env.batch())
            rng = jax.random.PRNGKey(1)
            # Both phases from the SAME initial state: after an Adam update a
            # near-zero grad component whose sign flips under collective
            # reduction order moves a param by a full lr, so sequential-step
            # scalars are not comparable across mesh layouts.  d_step
            # (first-order, full batch) + g_step_pl (second-order grads AND
            # the pl_batch_shrink sub-batch that exercises the UNCONSTRAINED
            # batch dim) cover both autodiff regimes at half the compile
            # cost of the d_r1+g_pl pair.
            state_copy = jax.tree.map(jnp.copy, state)  # steps donate buffers
            st_d, d_aux = fns.d_step(state, imgs, jax.random.fold_in(rng, 0))
            st_g, g_aux = fns.g_step_pl(state_copy, jax.random.fold_in(rng, 1))
            jax.block_until_ready((st_d.step, st_g.step))
        results[sp] = {**d_aux, **g_aux}
    for key in results[False]:
        a = float(jax.device_get(results[False][key]))
        b = float(jax.device_get(results[True][key]))
        assert np.isfinite(a) and np.isfinite(b), (key, a, b)
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3, err_msg=key)


@pytest.mark.slow  # compiles the cycle (largest program in the repo) AND
# the four unfused steps; the tier-1 870 s budget was killing this test
# (and everything after it) mid-compile, so it runs in the slow tier where
# it actually executes
def test_fused_cycle_matches_unfused_loop():
    """TrainStepFns.cycle — one jitted program per full lazy-reg cycle —
    must follow the EXACT random stream and update sequence of the
    unfused per-step dispatch loop: same phase selection, same per-
    iteration rng derivation, matching aux sums/counts, and matching
    parameters after the cycle."""
    cfg = micro_cfg()
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, d_reg_interval=4, g_reg_interval=2))
    env = make_mesh(cfg.mesh)
    fns = make_train_steps(cfg, env, batch_size=cfg.train.batch_size)
    assert fns.cycle is not None and fns.cycle_len == 4

    k = fns.cycle_len
    imgs_k = np.random.RandomState(0).randint(
        0, 255, (k, cfg.train.batch_size, 16, 16, 3), dtype=np.uint8)
    base_rng = jax.random.PRNGKey(42)

    # unfused: the loop's dispatch pattern (train/loop.py)
    state_u = jax.device_put(create_train_state(cfg, jax.random.PRNGKey(0)),
                             env.replicated())
    acc, cnt = {}, {}
    for it in range(k):
        step_rng = jax.random.fold_in(base_rng, it)
        imgs = jax.device_put(imgs_k[it], env.batch())
        d_fn = fns.d_step_r1 if it % 4 == 0 else fns.d_step
        state_u, d_aux = d_fn(state_u, imgs, jax.random.fold_in(step_rng, 0))
        g_fn = fns.g_step_pl if it % 2 == 0 else fns.g_step
        state_u, g_aux = g_fn(state_u, jax.random.fold_in(step_rng, 1))
        for key, v in {**d_aux, **g_aux}.items():
            acc[key] = acc.get(key, 0.0) + float(jax.device_get(v))
            cnt[key] = cnt.get(key, 0) + 1

    # fused: one dispatch
    state_f = jax.device_put(create_train_state(cfg, jax.random.PRNGKey(0)),
                             env.replicated())
    state_f, sums = fns.cycle(
        state_f, jax.device_put(imgs_k, env.batch_stack()), base_rng, 0)

    # the STATIC count table must match counts observed from the real
    # unfused loop — a new aux key cannot silently drift past it
    assert fns.cycle_counts == cnt
    assert set(sums) == set(cnt)
    # Loss sums at fp-noise tolerance: a wrong rng derivation or phase
    # order anywhere in the cycle would shift these at O(1), not O(1e-7).
    for key in acc:
        assert float(jax.device_get(sums[key])) == pytest.approx(
            acc[key], rel=1e-4, abs=1e-4), key
    assert int(jax.device_get(state_f.step)) == \
        int(jax.device_get(state_u.step))
    assert float(jax.device_get(state_f.pl_mean)) == pytest.approx(
        float(jax.device_get(state_u.pl_mean)), abs=1e-6)
    # D params stay tight (first-order grads are fp-stable across program
    # variants).  G/EMA are compared loosely ON PURPOSE: with adam_beta1=0
    # the update is ~sign(g)·lr, so a near-zero second-order PL gradient
    # component whose sign flips under different XLA fusion moves a param
    # by a full lr (see test_sequence_parallel_grid_sharding_parity's
    # comment for the same effect across mesh layouts) — the loss-sum
    # check above is the stream-parity guarantee.
    np.testing.assert_allclose(
        np.concatenate([np.ravel(x) for x in jax.tree_util.tree_leaves(
            jax.device_get(state_u.d_params))]),
        np.concatenate([np.ravel(x) for x in jax.tree_util.tree_leaves(
            jax.device_get(state_f.d_params))]),
        rtol=1e-4, atol=1e-5)
    lr = cfg.train.g_lr
    for lu, lf in zip(jax.tree_util.tree_leaves(jax.device_get(state_u.g_params)),
                      jax.tree_util.tree_leaves(jax.device_get(state_f.g_params))):
        assert np.max(np.abs(lu - lf)) <= 4 * lr + 1e-6


@pytest.mark.slow  # same cycle-vs-loop compile pair as above, conditional
# variant — see the slow rationale there
def test_fused_cycle_conditional_labels():
    """The fused cycle's label path: label_k is indexed with TRACED
    iteration indices inside the scans — a conditional cycle must follow
    the unfused conditional loop exactly (loss sums at fp noise)."""
    cfg = micro_cfg()
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, label_dim=10),
        train=dataclasses.replace(cfg.train, d_reg_interval=4,
                                  g_reg_interval=2))
    env = make_mesh(cfg.mesh)
    fns = make_train_steps(cfg, env, batch_size=cfg.train.batch_size)
    k = fns.cycle_len
    rs = np.random.RandomState(3)
    imgs_k = rs.randint(0, 255, (k, cfg.train.batch_size, 16, 16, 3),
                        dtype=np.uint8)
    label_k = np.eye(10, dtype=np.float32)[
        rs.randint(0, 10, (k, cfg.train.batch_size))]
    base_rng = jax.random.PRNGKey(9)

    state_u = jax.device_put(create_train_state(cfg, jax.random.PRNGKey(0)),
                             env.replicated())
    acc = {}
    for it in range(k):
        step_rng = jax.random.fold_in(base_rng, it)
        imgs = jax.device_put(imgs_k[it], env.batch())
        lab = jax.device_put(label_k[it], env.batch())
        d_fn = fns.d_step_r1 if it % 4 == 0 else fns.d_step
        state_u, d_aux = d_fn(state_u, imgs, jax.random.fold_in(step_rng, 0),
                              lab)
        g_fn = fns.g_step_pl if it % 2 == 0 else fns.g_step
        state_u, g_aux = g_fn(state_u, jax.random.fold_in(step_rng, 1), lab)
        for key, v in {**d_aux, **g_aux}.items():
            acc[key] = acc.get(key, 0.0) + float(jax.device_get(v))

    state_f = jax.device_put(create_train_state(cfg, jax.random.PRNGKey(0)),
                             env.replicated())
    state_f, sums = fns.cycle(
        state_f, jax.device_put(imgs_k, env.batch_stack()), base_rng, 0,
        jax.device_put(label_k, env.batch_stack()))
    for key in acc:
        assert float(jax.device_get(sums[key])) == pytest.approx(
            acc[key], rel=1e-4, abs=1e-4), key
    assert int(jax.device_get(state_f.step)) == \
        int(jax.device_get(state_u.step))


@pytest.mark.slow  # compiles the (d, g) pair on two mesh layouts
def test_sharded_latents_data2_matches_data1():
    """ISSUE 7 acceptance: with the in-step latent draws sharded onto
    the data axis (steps._sample_z under an ambient mesh), a data=2 run
    reproduces the data=1 run's losses and updated params to float-
    reduction-order tolerance — the sharding is a layout change, not a
    math change.  (Bit-identity at data=1 is structural: the constraint
    is skipped entirely without a multi-device data axis.)"""
    imgs_np = np.random.RandomState(0).randint(
        0, 255, (8, 16, 16, 3), dtype=np.uint8)
    rng = jax.random.PRNGKey(7)
    results = {}
    for n in (1, 2):
        cfg = micro_cfg(batch=8)
        cfg = dataclasses.replace(cfg, mesh=MeshConfig(data=n))
        env = make_mesh(cfg.mesh, devices=jax.devices()[:n])
        state = jax.device_put(create_train_state(cfg, jax.random.PRNGKey(0)),
                               env.replicated())
        fns = make_train_steps(cfg, env, batch_size=8)
        imgs = jax.device_put(imgs_np, env.batch())
        aux_all = {}
        with env.activate():
            for it in range(2):
                r = jax.random.fold_in(rng, it)
                state, d_aux = fns.d_step(state, imgs,
                                          jax.random.fold_in(r, 0))
                state, g_aux = fns.g_step(state, jax.random.fold_in(r, 1))
                for key, v in {**d_aux, **g_aux}.items():
                    aux_all[f"{it}/{key}"] = float(jax.device_get(v))
            jax.block_until_ready(state.step)
        results[n] = (jax.device_get(state.g_params), aux_all)
    p1, a1 = results[1]
    p2, a2 = results[2]
    # The loss trajectory is THE parity signal: iteration 1's losses
    # already reflect iteration 0's updated params on both meshes.
    for key in a1:
        assert a1[key] == pytest.approx(a2[key], rel=2e-4, abs=1e-5), key
    # Params get a loose gate only: Adam's first steps are ~sign(g)·lr,
    # so float-reduction-order noise on near-zero gradients legitimately
    # moves single elements by a fraction of one update (lr·c ≈ 2e-3);
    # what this must catch is WRONG math (order-of-magnitude divergence).
    for x, y in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-2, atol=1e-3)


def test_metric_sampler_outputs_shard_on_data_axis():
    """ISSUE 7 satellite (steps.py make_metric_samplers): the metric
    sweep's generator half must actually shard at 2+ devices — z lands
    via env.put_global on the data axis and the sampled images come
    back data-sharded (2 devices hold disjoint shards), so a 50k sweep
    is batch-parallel, not replicated."""
    from gansformer_tpu.data.dataset import make_dataset
    from gansformer_tpu.train.steps import make_metric_samplers

    cfg = micro_cfg(batch=4)
    cfg = dataclasses.replace(cfg, mesh=MeshConfig(data=2))
    env = make_mesh(cfg.mesh, devices=jax.devices()[:2])
    state = jax.device_put(create_train_state(cfg, jax.random.PRNGKey(0)),
                           env.replicated())
    fns = make_train_steps(cfg, env, batch_size=4)
    dataset = make_dataset(cfg.data)
    with env.activate():
        sample_fn, pair_fn = make_metric_samplers(
            fns, state, cfg, env, dataset, truncation_psi=1.0, seed=11)
        out = sample_fn(4)
        jax.block_until_ready(out)
    assert out.shape == (4, 16, 16, 3)
    assert not out.sharding.is_fully_replicated
    assert len(out.sharding.device_set) == 2
    # each device holds a half-batch shard, not a full copy
    assert {s.data.shape[0] for s in out.addressable_shards} == {2}
