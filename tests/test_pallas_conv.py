"""ISSUE 14 acceptance: the Pallas modulated-conv/upfirdn kernel family
(``conv_backend='pallas'``) is correct, differentiable to second order,
and training-grade.

Interpret-mode parity on CPU against the XLA composites
(``ops/modulated_conv.py`` / ``ops/upfirdn2d.py``) and the numpy oracle:
forward, first-order grads (dx/dw/dstyles/dbias), the fused bias/act
epilogue, R1/PL-shaped second-order transforms, plus the wiring
contracts (backward kernels actually on the reverse path, config
validation, serve-manifest fingerprint separation) and the slow
integration layer (model grads, the four step programs, a micro train
run) over the same kernels — the same harness shape as
tests/test_pallas_grad.py (ISSUE 9).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.ops.fused_bias_act import fused_bias_act
from gansformer_tpu.ops.modulated_conv import modulated_conv2d
from gansformer_tpu.ops.pallas_modconv import (modconv_fits,
                                               modulated_conv2d_pallas)
from gansformer_tpu.ops.pallas_upfirdn import grad_pad4, upfirdn2d_pallas
from gansformer_tpu.ops.upfirdn2d import setup_filter, upfirdn2d
from tests.reference_ops import upfirdn2d_ref

# (up, down, pad): even 4-tap and odd 3-tap filters below run each of
# these — covering zero-insertion, decimation, negative-crop and
# asymmetric pads in one sweep.
UFD_CASES = [
    (1, 1, 1),
    (2, 1, (2, 1)),
    (1, 2, (1, 1)),
    (2, 2, (2, 1, 0, 3)),
    (1, 1, (-1, 2, 1, -1)),
]
FILTERS = {"even4": (1, 3, 3, 1), "odd3": (1, 2, 1)}


# --------------------------------------------------------------------------
# upfirdn kernel
# --------------------------------------------------------------------------


@pytest.mark.parametrize("ftaps", sorted(FILTERS))
@pytest.mark.parametrize("case", UFD_CASES,
                         ids=[f"u{u}d{d}p{p}" for u, d, p in UFD_CASES])
def test_upfirdn_kernel_matches_xla_and_oracle(rng, case, ftaps):
    """Fused pad→FIR→resample kernel vs the XLA lowering AND the numpy
    oracle at fp32 — near-bit parity (both accumulate fp32)."""
    up, down, pad = case
    f = setup_filter(FILTERS[ftaps])
    x = jnp.asarray(rng.randn(2, 9, 11, 6), jnp.float32)
    ref = upfirdn2d(x, f, up=up, down=down, pad=pad)
    got = upfirdn2d_pallas(x, f, up=up, down=down, pad=pad, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)
    from gansformer_tpu.ops.upfirdn2d import _pad4

    oracle = upfirdn2d_ref(np.asarray(x, np.float64), np.asarray(f),
                           up=up, down=down, pad=_pad4(pad))
    np.testing.assert_allclose(np.asarray(got), oracle, atol=1e-5,
                               rtol=1e-5)


@pytest.mark.parametrize("case", UFD_CASES[:4],
                         ids=[f"u{u}d{d}p{p}" for u, d, p in UFD_CASES[:4]])
def test_upfirdn_kernel_grads_match_xla(rng, case):
    """The hand-written adjoint (same kernel, flipped filter, up↔down
    swapped, the reference's gradient pads) vs autodiff of the XLA op."""
    up, down, pad = case
    f = setup_filter((1, 3, 3, 1))
    x = jnp.asarray(rng.randn(2, 9, 11, 4), jnp.float32)

    def loss(fn):
        return lambda x_: jnp.sum(jnp.sin(fn(x_)))

    g_ref = jax.grad(loss(lambda x_: upfirdn2d(x_, f, up=up, down=down,
                                               pad=pad)))(x)
    g_got = jax.grad(loss(lambda x_: upfirdn2d_pallas(
        x_, f, up=up, down=down, pad=pad, interpret=True)))(x)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               atol=1e-6, rtol=1e-6)


def test_grad_pad_algebra_inverts_output_shape():
    """The adjoint pad formula must map the output geometry back to the
    input geometry for every supported case — the algebra the backward
    kernel's shapes stand on."""
    from gansformer_tpu.ops.upfirdn2d import _pad4

    for up, down, pad in UFD_CASES:
        for taps in FILTERS.values():
            f = setup_filter(taps)
            p4 = _pad4(pad)
            h, w = 9, 11
            oh = (h * up + p4[0] + p4[1] - f.shape[0]) // down + 1
            ow = (w * up + p4[2] + p4[3] - f.shape[1]) // down + 1
            g4 = grad_pad4(h, w, f.shape[0], f.shape[1], up, down, p4)
            bh = (oh * down + g4[0] + g4[1] - f.shape[0]) // up + 1
            bw = (ow * down + g4[2] + g4[3] - f.shape[1]) // up + 1
            assert (bh, bw) == (h, w), (up, down, pad, taps)


def test_upfirdn_kernel_fused_epilogue(rng):
    """bias + lrelu fused into the resample kernel: forward and grads
    (dx AND dbias via the saved-output activation recovery) match the
    upfirdn → fused_bias_act composite."""
    f = setup_filter((1, 3, 3, 1))
    x = jnp.asarray(rng.randn(2, 8, 8, 5), jnp.float32)
    b = jnp.asarray(rng.randn(5), jnp.float32)

    def ref(x_, b_):
        return fused_bias_act(upfirdn2d(x_, f, up=2, pad=(2, 1)), b_,
                              act="lrelu")

    def got(x_, b_):
        return upfirdn2d_pallas(x_, f, up=2, pad=(2, 1), bias=b_,
                                act="lrelu", interpret=True)

    np.testing.assert_allclose(np.asarray(got(x, b)),
                               np.asarray(ref(x, b)), atol=1e-6, rtol=1e-6)
    gr = jax.grad(lambda x_, b_: jnp.sum(jnp.sin(ref(x_, b_))),
                  argnums=(0, 1))(x, b)
    gg = jax.grad(lambda x_, b_: jnp.sum(jnp.sin(got(x_, b_))),
                  argnums=(0, 1))(x, b)
    for a, g, name in zip(gr, gg, ("dx", "dbias")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   atol=1e-5, rtol=1e-5, err_msg=name)


# --------------------------------------------------------------------------
# modconv kernels
# --------------------------------------------------------------------------

MC_CASES = {
    "same3": (3, 1, True),
    "same1": (1, 1, True),
    "same3-nodemod": (3, 1, False),
    "poly": (3, 2, True),
    "poly-nodemod": (3, 2, False),
}


def _mc_inputs(rng, case, dtype=jnp.float32):
    k, up, demod = MC_CASES[case]
    x = jnp.asarray(rng.randn(2, 8, 8, 6), dtype)
    w = jnp.asarray(rng.randn(k, k, 6, 10) * 0.2, dtype)
    s = jnp.asarray(rng.randn(2, 6) * 0.3 + 1.0, jnp.float32)
    ref = lambda x_, w_, s_: modulated_conv2d(x_, w_, s_, demodulate=demod,
                                              up=up)
    got = lambda x_, w_, s_: modulated_conv2d_pallas(
        x_, w_, s_, demodulate=demod, up=up, interpret=True)
    return x, w, s, ref, got


@pytest.mark.parametrize("case", sorted(MC_CASES))
def test_modconv_forward_matches_xla(rng, case):
    x, w, s, ref, got = _mc_inputs(rng, case)
    np.testing.assert_allclose(np.asarray(got(x, w, s)),
                               np.asarray(ref(x, w, s)),
                               atol=5e-6, rtol=1e-5)


@pytest.mark.parametrize("case", ["same3", "same1", "poly"])
def test_modconv_first_order_grads_match_xla(rng, case):
    """dx/dw/dstyles from the backward kernels (incl. the demod-chain
    terms routed through the outside einsum) vs XLA autodiff."""
    x, w, s, ref, got = _mc_inputs(rng, case)

    def loss(fn):
        return lambda x_, w_, s_: jnp.sum(jnp.sin(fn(x_, w_, s_)))

    gr = jax.grad(loss(ref), argnums=(0, 1, 2))(x, w, s)
    gg = jax.grad(loss(got), argnums=(0, 1, 2))(x, w, s)
    for a, g, name in zip(gr, gg, "dx dw dstyles".split()):
        assert a.dtype == g.dtype, name
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   atol=5e-5, rtol=1e-4, err_msg=name)


@pytest.mark.parametrize("up", [1, 2])
def test_modconv_fused_epilogue(rng, up):
    """The fused bias/act epilogue (in the conv kernel at up=1, riding
    the blur kernel at up=2 — completing the `_conv_transpose_poly →
    reshape → fused_bias_act` chain as kernels): forward + all four
    grads vs the XLA composite."""
    x, w, s, _, _ = _mc_inputs(rng, "same3")
    b = jnp.asarray(rng.randn(10) * 0.1, jnp.float32)

    def ref(x_, w_, s_, b_):
        return fused_bias_act(modulated_conv2d(x_, w_, s_, up=up), b_,
                              act="lrelu")

    def got(x_, w_, s_, b_):
        return modulated_conv2d_pallas(x_, w_, s_, up=up, bias=b_,
                                       act="lrelu", interpret=True)

    np.testing.assert_allclose(np.asarray(got(x, w, s, b)),
                               np.asarray(ref(x, w, s, b)),
                               atol=5e-6, rtol=1e-5)
    gr = jax.grad(lambda *a: jnp.sum(jnp.sin(ref(*a))),
                  argnums=(0, 1, 2, 3))(x, w, s, b)
    gg = jax.grad(lambda *a: jnp.sum(jnp.sin(got(*a))),
                  argnums=(0, 1, 2, 3))(x, w, s, b)
    for a, g, name in zip(gr, gg, "dx dw dstyles dbias".split()):
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   atol=1e-4, rtol=1e-4, err_msg=name)


@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_first_order_grads_bf16(rng, case):
    """bf16 in/out: cotangents keep the primal dtypes and stay within
    bf16 round-off (internals are fp32 in both paths)."""
    x, w, s, _, _ = _mc_inputs(rng, case, jnp.bfloat16)
    up = MC_CASES[case][1]

    def loss(fn):
        return lambda x_, w_: jnp.sum(fn(x_, w_, s).astype(jnp.float32)**2)

    gr = jax.grad(loss(lambda x_, w_, s_: modulated_conv2d(
        x_, w_, s_, up=up)), argnums=(0, 1))(x, w)
    gg = jax.grad(loss(lambda x_, w_, s_: modulated_conv2d_pallas(
        x_, w_, s_, up=up, interpret=True)), argnums=(0, 1))(x, w)
    for a, g, name in zip(gr, gg, "dx dw".split()):
        assert g.dtype == jnp.bfloat16, name
        # Scale-aware band: both sides round to bf16 at different points
        # (XLA per-conv, kernels per-tap), so batch+space-summed weight
        # grads carry a few % of the tensor's dynamic range as noise.
        ref32, got32 = np.asarray(a, np.float32), np.asarray(g, np.float32)
        tol = 0.08 * max(np.abs(ref32).max(), 1.0)
        np.testing.assert_allclose(got32, ref32, atol=tol, err_msg=name)


@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_r1_shaped_double_backward(rng, case):
    """The R1 transform shape: grad w.r.t. a parameter scale of
    ‖grad-w.r.t.-input‖² — reverse-over-reverse through the kernels must
    match XLA (the custom_jvp tangent layer closing, docs/pallas.md)."""
    x, w, s, ref, got = _mc_inputs(rng, case)

    def r1(wm, fn):
        gq = jax.grad(lambda x_: jnp.sum(fn(x_ * wm, w, s) ** 2))(x)
        return jnp.sum(gq ** 2)

    g_ref = jax.grad(lambda wm: r1(wm, ref))(1.1)
    g_got = jax.grad(lambda wm: r1(wm, got))(1.1)
    np.testing.assert_allclose(float(g_got), float(g_ref), rtol=1e-4)


@pytest.mark.slow  # the R1 sweep above is the tier-1 second-order gate
@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_pl_shaped_hvp(rng, case):
    """The PL transform shape, jitted like the real g_step_pl: the
    scalar moves weights AND styles along fixed random directions and
    the HVP flows through the inner input-grad.  (Additive directions,
    not a multiplicative scale: demodulation makes the op exactly
    scale-invariant in (w, s), which would leave only fp noise to
    compare.)"""
    x, w, s, ref, got = _mc_inputs(rng, case)
    dw0 = jnp.asarray(rng.randn(*w.shape) * 0.2, jnp.float32)
    ds0 = jnp.asarray(rng.randn(*s.shape) * 0.3, jnp.float32)

    def pl(wm, fn):
        gq = jax.grad(lambda x_: jnp.sum(
            fn(x_, w + wm * dw0, s + wm * ds0) ** 2))(x)
        return jnp.sum(gq ** 2)

    g_got = jax.jit(jax.grad(lambda wm: pl(wm, got)))(0.1)
    g_ref = jax.grad(lambda wm: pl(wm, ref))(0.1)
    np.testing.assert_allclose(float(g_got), float(g_ref), rtol=1e-4)


def test_bwd_kernels_are_on_the_reverse_path(rng):
    """First-order reverse must RUN the backward kernels: the grad jaxpr
    carries ≥ 3 pallas_call sites (forward + dx/ds + dw), where a
    glue-transposed rule would carry exactly the forward one."""
    x, w, s, _, got = _mc_inputs(rng, "same3")
    jaxpr = str(jax.make_jaxpr(
        lambda x_: jax.grad(lambda x2: jnp.sum(got(x2, w, s)))(x_))(x))
    assert jaxpr.count("pallas_call") >= 3, jaxpr[:2000]


def test_forward_mode_is_rejected(rng):
    """Direct jax.jvp through the op is NOT supported (custom_vjp outer
    layer) — same contract as the attention kernels; R1/PL are
    reverse-mode formulations and never hit this."""
    x, w, s, _, got = _mc_inputs(rng, "same3")
    with pytest.raises(TypeError, match="custom_vjp"):
        jax.jvp(lambda x_: got(x_, w, s), (x,), (x,))


def test_oversize_and_unsupported_fall_back_to_xla(rng):
    """The VMEM gate and geometry gate return the XLA composite instead
    of a broken kernel: a 5×5 kernel (unsupported) and a down=2 call
    both produce XLA-exact results, and ``modconv_fits`` rejects a grid
    far beyond any VMEM."""
    x = jnp.asarray(rng.randn(1, 8, 8, 4), jnp.float32)
    w5 = jnp.asarray(rng.randn(5, 5, 4, 4) * 0.2, jnp.float32)
    s = jnp.asarray(rng.randn(1, 4) + 1.0, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(modulated_conv2d_pallas(x, w5, s, interpret=True)),
        np.asarray(modulated_conv2d(x, w5, s)), atol=1e-6, rtol=1e-6)
    w3 = jnp.asarray(rng.randn(3, 3, 4, 4) * 0.2, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(modulated_conv2d_pallas(x, w3, s, down=2,
                                           interpret=True)),
        np.asarray(modulated_conv2d(x, w3, s, down=2)), atol=1e-6,
        rtol=1e-6)
    assert not modconv_fits((1, 4096, 4096, 64), (3, 3, 64, 64), up=1)
    assert modconv_fits(x.shape, w3.shape, up=1)


# --------------------------------------------------------------------------
# config / serve wiring contracts
# --------------------------------------------------------------------------


def test_config_validates_conv_backend():
    """A typo fails fast with the allowed set — mirroring
    attention_backend exactly (ISSUE 14 satellite)."""
    from gansformer_tpu.core.config import ExperimentConfig, ModelConfig

    cfg = ExperimentConfig(model=ModelConfig(conv_backend="palas"))
    with pytest.raises(ValueError, match="conv_backend must be xla|pallas"):
        cfg.validate()


def test_config_rejects_conv_pallas_with_sequence_parallel():
    """pallas_call has no sharding rule: the combination would silently
    all-gather the model-sharded grid — rejected in words instead."""
    import dataclasses as dc

    from gansformer_tpu.core.config import (ExperimentConfig, MeshConfig,
                                            ModelConfig)

    cfg = ExperimentConfig(
        model=ModelConfig(conv_backend="pallas", sequence_parallel=True),
        mesh=MeshConfig(model=2, data=1))
    with pytest.raises(ValueError, match="conv_backend='pallas' does not"):
        cfg.validate()
    ok = dc.replace(cfg, model=dc.replace(
        cfg.model, conv_backend="xla"))
    ok.validate()


def test_conv_backend_roundtrips_through_config_json():
    from gansformer_tpu.core.config import ExperimentConfig, get_preset

    import dataclasses as dc

    cfg = get_preset("clevr64-simplex")
    cfg = dc.replace(cfg, model=dc.replace(cfg.model,
                                           conv_backend="pallas"))
    back = ExperimentConfig.from_json(cfg.to_json())
    assert back.model.conv_backend == "pallas"


def test_train_cli_conv_backend_flag():
    from gansformer_tpu.cli.train import build_parser, config_from_args

    args = build_parser().parse_args(
        ["--preset", "clevr64-simplex", "--conv-backend", "pallas"])
    assert config_from_args(args).model.conv_backend == "pallas"
    # tri-state: no flag inherits the loaded config's value
    args = build_parser().parse_args(["--preset", "clevr64-simplex"])
    assert config_from_args(args).model.conv_backend == "xla"


def test_serve_fingerprint_separates_conv_backends():
    """A warm-start manifest entry written under one conv backend can
    never be served under the other: the fingerprint hashes the full
    ModelConfig, conv_backend included (ISSUE 14 — AOT executables
    record the conv backend)."""
    import dataclasses as dc
    import json as _json

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.serve.warmstart import fingerprint

    cfg = get_preset("clevr64-simplex")
    m_xla = _json.dumps(dc.asdict(cfg.model))
    m_pl = _json.dumps(dc.asdict(
        dc.replace(cfg.model, conv_backend="pallas")))
    assert fingerprint(m_xla, "synthesize", 4) != \
        fingerprint(m_pl, "synthesize", 4)


def test_resolve_conv_backend_off_tpu():
    """Off-TPU, 'pallas' resolves to itself (interpret mode is the CI
    story) and 'xla' passes through untouched."""
    from gansformer_tpu.ops.pallas_modconv import resolve_conv_backend

    assert resolve_conv_backend("pallas") == "pallas"
    assert resolve_conv_backend("xla") == "xla"


# --------------------------------------------------------------------------
# model / training-path integration (slow tier)
# --------------------------------------------------------------------------


@pytest.mark.slow  # whole-generator + whole-D traces in interpret mode
def test_model_grads_match_xla_conv_backend(rng):
    """Grads of a duplex generator loss w.r.t. EVERY parameter agree
    between conv backends (kernel dispatch inside ModulatedConv, the
    fused tRGB epilogue, the rgb-skip pallas upsample, flax
    integration); same for the discriminator's blur-pool path."""
    from gansformer_tpu.core.config import ModelConfig
    from gansformer_tpu.models.discriminator import Discriminator
    from gansformer_tpu.models.generator import Generator

    cfg = ModelConfig(resolution=16, components=2, latent_dim=16, w_dim=16,
                      mapping_dim=16, mapping_layers=2, fmap_base=64,
                      fmap_max=16, attention="duplex", attn_start_res=8,
                      attn_max_res=8)
    cfg_pl = dataclasses.replace(cfg, conv_backend="pallas")
    z = jnp.asarray(rng.randn(2, cfg.num_ws, cfg.latent_dim), jnp.float32)
    noise = jax.random.PRNGKey(3)
    G = Generator(cfg)
    params = G.init({"params": jax.random.PRNGKey(0), "noise": noise}, z)
    G_pl = Generator(cfg_pl)

    def loss(g):
        return lambda p: jnp.mean(g.apply(p, z, rngs={"noise": noise})**2)

    gx = jax.tree_util.tree_leaves(jax.grad(loss(G))(params))
    gp = jax.tree_util.tree_leaves(jax.grad(loss(G_pl))(params))
    assert len(gx) == len(gp)
    for a, b in zip(gx, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-3)

    imgs = jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32)
    D = Discriminator(cfg)
    dvars = D.init(jax.random.PRNGKey(1), imgs)
    D_pl = Discriminator(cfg_pl)
    dx = jax.tree_util.tree_leaves(
        jax.grad(lambda p: jnp.mean(D.apply(p, imgs)**2))(dvars))
    dp = jax.tree_util.tree_leaves(
        jax.grad(lambda p: jnp.mean(D_pl.apply(p, imgs)**2))(dvars))
    for a, b in zip(dx, dp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-3)


@pytest.fixture(scope="module")
def conv_reg_step_pair():
    """The second-order SUPERSET step programs (d_step_r1, g_step_pl) on
    both conv backends, same inputs/rng — the ISSUE 14 acceptance that
    R1 grad-of-grad and PL HVPs re-enter the conv kernels' rules inside
    the REAL programs (same fixture shape as ISSUE 9's)."""
    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps
    from tests.test_train import micro_cfg

    imgs_np = np.random.RandomState(0).randint(
        0, 255, (8, 16, 16, 3), dtype=np.uint8)
    rng = jax.random.PRNGKey(11)
    out = {}
    for backend in ("xla", "pallas"):
        cfg = micro_cfg(attention="duplex")
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, conv_backend=backend))
        cfg.validate()
        env = make_mesh(cfg.mesh)
        state = jax.device_put(
            create_train_state(cfg, jax.random.PRNGKey(0)),
            env.replicated())
        fns = make_train_steps(cfg, env, batch_size=cfg.train.batch_size)
        imgs = jax.device_put(imgs_np, env.batch())
        with env.activate():
            r = jax.random.fold_in(rng, 0)
            state, d_aux = fns.d_step_r1(state, imgs,
                                         jax.random.fold_in(r, 0))
            state, g_aux = fns.g_step_pl(state, jax.random.fold_in(r, 1))
            jax.block_until_ready(state.step)
        out[backend] = {k: float(jax.device_get(v))
                        for k, v in {**d_aux, **g_aux}.items()}
    return out


@pytest.mark.slow  # 4 second-order step compiles through interpret kernels
def test_conv_pallas_training_reg_steps_finite(conv_reg_step_pair):
    aux = conv_reg_step_pair["pallas"]
    assert "Loss/D/r1" in aux and "Loss/G/pl" in aux
    for k, v in aux.items():
        assert np.isfinite(v), (k, v)


@pytest.mark.slow  # shares the conv_reg_step_pair fixture
def test_conv_pallas_training_losses_match_xla(conv_reg_step_pair):
    ax, ap = conv_reg_step_pair["xla"], conv_reg_step_pair["pallas"]
    assert set(ax) == set(ap)
    for k in ax:
        np.testing.assert_allclose(ap[k], ax[k], atol=5e-3, rtol=5e-3,
                                   err_msg=k)


@pytest.mark.slow  # two micro train() runs (fresh second-order compiles)
def test_micro_train_run_conv_pallas_vs_xla(tmp_path):
    """ISSUE 14 acceptance: a micro ``train()`` with
    ``conv_backend='pallas'`` AND the fused 16-cycle completes with
    finite losses through full lazy-reg cadences, per-tick loss means
    within tolerance of the xla backend (chained-update fp-reorder
    band, as in ISSUE 9's twin test)."""
    import json
    import os

    from gansformer_tpu.train.loop import train
    from tests.test_train import micro_cfg

    ticks = {}
    for backend in ("xla", "pallas"):
        cfg = micro_cfg(attention="duplex", batch=40)
        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(cfg.model, conv_backend=backend),
            train=dataclasses.replace(cfg.train, fused_cycle=True))
        cfg.validate()
        d = str(tmp_path / backend)
        os.makedirs(d)
        train(cfg, d)
        with open(os.path.join(d, "stats.jsonl")) as f:
            rows = [json.loads(line) for line in f]
        assert rows, backend
        ticks[backend] = rows[-1]
    for key in ("Loss/D", "Loss/G", "Loss/D/r1", "Loss/G/pl",
                "Loss/scores/real", "Loss/scores/fake"):
        a, b = ticks["xla"][key], ticks["pallas"][key]
        assert np.isfinite(a) and np.isfinite(b), (key, a, b)
        np.testing.assert_allclose(b, a, atol=0.2, rtol=0.2, err_msg=key)
