"""ISSUE 14 acceptance: the Pallas modulated-conv/upfirdn kernel family
(``conv_backend='pallas'``) is correct, differentiable to second order,
and training-grade.

Interpret-mode parity on CPU against the XLA composites
(``ops/modulated_conv.py`` / ``ops/upfirdn2d.py``) and the numpy oracle:
forward, first-order grads (dx/dw/dstyles/dbias), the fused bias/act
epilogue, R1/PL-shaped second-order transforms, plus the wiring
contracts (backward kernels actually on the reverse path, config
validation, serve-manifest fingerprint separation) and the slow
integration layer (model grads, the four step programs, a micro train
run) over the same kernels — the same harness shape as
tests/test_pallas_grad.py (ISSUE 9).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.ops.fused_bias_act import fused_bias_act
from gansformer_tpu.ops.modulated_conv import modulated_conv2d
from gansformer_tpu.ops.pallas_modconv import (modconv_fits, modconv_plan,
                                               modulated_conv2d_pallas)
from gansformer_tpu.ops.pallas_upfirdn import (grad_pad4, upfirdn2d_pallas,
                                               upfirdn_fits, upfirdn_plan)
from gansformer_tpu.ops.upfirdn2d import setup_filter, upfirdn2d
from tests.tolerances import FWD, GRAD, TRAIN_REORDER
from tests.reference_ops import upfirdn2d_ref

# (up, down, pad): even 4-tap and odd 3-tap filters below run each of
# these — covering zero-insertion, decimation, negative-crop and
# asymmetric pads in one sweep.
UFD_CASES = [
    (1, 1, 1),
    (2, 1, (2, 1)),
    (1, 2, (1, 1)),
    (2, 2, (2, 1, 0, 3)),
    (1, 1, (-1, 2, 1, -1)),
]
FILTERS = {"even4": (1, 3, 3, 1), "odd3": (1, 2, 1)}


# --------------------------------------------------------------------------
# upfirdn kernel
# --------------------------------------------------------------------------


@pytest.mark.parametrize("ftaps", sorted(FILTERS))
@pytest.mark.parametrize("case", UFD_CASES,
                         ids=[f"u{u}d{d}p{p}" for u, d, p in UFD_CASES])
def test_upfirdn_kernel_matches_xla_and_oracle(rng, case, ftaps):
    """Fused pad→FIR→resample kernel vs the XLA lowering AND the numpy
    oracle at fp32 — near-bit parity (both accumulate fp32)."""
    up, down, pad = case
    f = setup_filter(FILTERS[ftaps])
    x = jnp.asarray(rng.randn(2, 9, 11, 6), jnp.float32)
    ref = upfirdn2d(x, f, up=up, down=down, pad=pad)
    got = upfirdn2d_pallas(x, f, up=up, down=down, pad=pad, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               **FWD["float32"])
    from gansformer_tpu.ops.upfirdn2d import _pad4

    oracle = upfirdn2d_ref(np.asarray(x, np.float64), np.asarray(f),
                           up=up, down=down, pad=_pad4(pad))
    np.testing.assert_allclose(np.asarray(got), oracle,
                               **GRAD["float32"])


@pytest.mark.parametrize("case", UFD_CASES[:4],
                         ids=[f"u{u}d{d}p{p}" for u, d, p in UFD_CASES[:4]])
def test_upfirdn_kernel_grads_match_xla(rng, case):
    """The hand-written adjoint (same kernel, flipped filter, up↔down
    swapped, the reference's gradient pads) vs autodiff of the XLA op."""
    up, down, pad = case
    f = setup_filter((1, 3, 3, 1))
    x = jnp.asarray(rng.randn(2, 9, 11, 4), jnp.float32)

    def loss(fn):
        return lambda x_: jnp.sum(jnp.sin(fn(x_)))

    g_ref = jax.grad(loss(lambda x_: upfirdn2d(x_, f, up=up, down=down,
                                               pad=pad)))(x)
    g_got = jax.grad(loss(lambda x_: upfirdn2d_pallas(
        x_, f, up=up, down=down, pad=pad, interpret=True)))(x)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               **FWD["float32"])


def test_grad_pad_algebra_inverts_output_shape():
    """The adjoint pad formula must map the output geometry back to the
    input geometry for every supported case — the algebra the backward
    kernel's shapes stand on."""
    from gansformer_tpu.ops.upfirdn2d import _pad4

    for up, down, pad in UFD_CASES:
        for taps in FILTERS.values():
            f = setup_filter(taps)
            p4 = _pad4(pad)
            h, w = 9, 11
            oh = (h * up + p4[0] + p4[1] - f.shape[0]) // down + 1
            ow = (w * up + p4[2] + p4[3] - f.shape[1]) // down + 1
            g4 = grad_pad4(h, w, f.shape[0], f.shape[1], up, down, p4)
            bh = (oh * down + g4[0] + g4[1] - f.shape[0]) // up + 1
            bw = (ow * down + g4[2] + g4[3] - f.shape[1]) // up + 1
            assert (bh, bw) == (h, w), (up, down, pad, taps)


def test_upfirdn_kernel_fused_epilogue(rng):
    """bias + lrelu fused into the resample kernel: forward and grads
    (dx AND dbias via the saved-output activation recovery) match the
    upfirdn → fused_bias_act composite."""
    f = setup_filter((1, 3, 3, 1))
    x = jnp.asarray(rng.randn(2, 8, 8, 5), jnp.float32)
    b = jnp.asarray(rng.randn(5), jnp.float32)

    def ref(x_, b_):
        return fused_bias_act(upfirdn2d(x_, f, up=2, pad=(2, 1)), b_,
                              act="lrelu")

    def got(x_, b_):
        return upfirdn2d_pallas(x_, f, up=2, pad=(2, 1), bias=b_,
                                act="lrelu", interpret=True)

    np.testing.assert_allclose(np.asarray(got(x, b)),
                               np.asarray(ref(x, b)), **FWD["float32"])
    gr = jax.grad(lambda x_, b_: jnp.sum(jnp.sin(ref(x_, b_))),
                  argnums=(0, 1))(x, b)
    gg = jax.grad(lambda x_, b_: jnp.sum(jnp.sin(got(x_, b_))),
                  argnums=(0, 1))(x, b)
    for a, g, name in zip(gr, gg, ("dx", "dbias")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   err_msg=name, **GRAD["float32"])


# --------------------------------------------------------------------------
# modconv kernels
# --------------------------------------------------------------------------

MC_CASES = {
    "same3": (3, 1, True),
    "same1": (1, 1, True),
    "same3-nodemod": (3, 1, False),
    "poly": (3, 2, True),
    "poly-nodemod": (3, 2, False),
}


def _mc_inputs(rng, case, dtype=jnp.float32):
    k, up, demod = MC_CASES[case]
    x = jnp.asarray(rng.randn(2, 8, 8, 6), dtype)
    w = jnp.asarray(rng.randn(k, k, 6, 10) * 0.2, dtype)
    s = jnp.asarray(rng.randn(2, 6) * 0.3 + 1.0, jnp.float32)
    ref = lambda x_, w_, s_: modulated_conv2d(x_, w_, s_, demodulate=demod,
                                              up=up)
    got = lambda x_, w_, s_: modulated_conv2d_pallas(
        x_, w_, s_, demodulate=demod, up=up, interpret=True)
    return x, w, s, ref, got


@pytest.mark.parametrize("case", sorted(MC_CASES))
def test_modconv_forward_matches_xla(rng, case):
    x, w, s, ref, got = _mc_inputs(rng, case)
    np.testing.assert_allclose(np.asarray(got(x, w, s)),
                               np.asarray(ref(x, w, s)),
                               atol=5e-6, rtol=1e-5)


@pytest.mark.parametrize("case", ["same3", "same1", "poly"])
def test_modconv_first_order_grads_match_xla(rng, case):
    """dx/dw/dstyles from the backward kernels (incl. the demod-chain
    terms routed through the outside einsum) vs XLA autodiff."""
    x, w, s, ref, got = _mc_inputs(rng, case)

    def loss(fn):
        return lambda x_, w_, s_: jnp.sum(jnp.sin(fn(x_, w_, s_)))

    gr = jax.grad(loss(ref), argnums=(0, 1, 2))(x, w, s)
    gg = jax.grad(loss(got), argnums=(0, 1, 2))(x, w, s)
    for a, g, name in zip(gr, gg, "dx dw dstyles".split()):
        assert a.dtype == g.dtype, name
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   atol=5e-5, rtol=1e-4, err_msg=name)


@pytest.mark.parametrize("up", [1, 2])
def test_modconv_fused_epilogue(rng, up):
    """The fused bias/act epilogue (in the conv kernel at up=1, riding
    the blur kernel at up=2 — completing the `_conv_transpose_poly →
    reshape → fused_bias_act` chain as kernels): forward + all four
    grads vs the XLA composite."""
    x, w, s, _, _ = _mc_inputs(rng, "same3")
    b = jnp.asarray(rng.randn(10) * 0.1, jnp.float32)

    def ref(x_, w_, s_, b_):
        return fused_bias_act(modulated_conv2d(x_, w_, s_, up=up), b_,
                              act="lrelu")

    def got(x_, w_, s_, b_):
        return modulated_conv2d_pallas(x_, w_, s_, up=up, bias=b_,
                                       act="lrelu", interpret=True)

    np.testing.assert_allclose(np.asarray(got(x, w, s, b)),
                               np.asarray(ref(x, w, s, b)),
                               atol=5e-6, rtol=1e-5)
    gr = jax.grad(lambda *a: jnp.sum(jnp.sin(ref(*a))),
                  argnums=(0, 1, 2, 3))(x, w, s, b)
    gg = jax.grad(lambda *a: jnp.sum(jnp.sin(got(*a))),
                  argnums=(0, 1, 2, 3))(x, w, s, b)
    for a, g, name in zip(gr, gg, "dx dw dstyles dbias".split()):
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   atol=1e-4, rtol=1e-4, err_msg=name)


@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_first_order_grads_bf16(rng, case):
    """bf16 in/out: cotangents keep the primal dtypes and stay within
    bf16 round-off (internals are fp32 in both paths)."""
    x, w, s, _, _ = _mc_inputs(rng, case, jnp.bfloat16)
    up = MC_CASES[case][1]

    def loss(fn):
        return lambda x_, w_: jnp.sum(fn(x_, w_, s).astype(jnp.float32)**2)

    gr = jax.grad(loss(lambda x_, w_, s_: modulated_conv2d(
        x_, w_, s_, up=up)), argnums=(0, 1))(x, w)
    gg = jax.grad(loss(lambda x_, w_, s_: modulated_conv2d_pallas(
        x_, w_, s_, up=up, interpret=True)), argnums=(0, 1))(x, w)
    for a, g, name in zip(gr, gg, "dx dw".split()):
        assert g.dtype == jnp.bfloat16, name
        # Scale-aware band: both sides round to bf16 at different points
        # (XLA per-conv, kernels per-tap), so batch+space-summed weight
        # grads carry a few % of the tensor's dynamic range as noise.
        ref32, got32 = np.asarray(a, np.float32), np.asarray(g, np.float32)
        tol = 0.08 * max(np.abs(ref32).max(), 1.0)
        np.testing.assert_allclose(got32, ref32, atol=tol, err_msg=name)


@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_r1_shaped_double_backward(rng, case):
    """The R1 transform shape: grad w.r.t. a parameter scale of
    ‖grad-w.r.t.-input‖² — reverse-over-reverse through the kernels must
    match XLA (the custom_jvp tangent layer closing, docs/pallas.md)."""
    x, w, s, ref, got = _mc_inputs(rng, case)

    def r1(wm, fn):
        gq = jax.grad(lambda x_: jnp.sum(fn(x_ * wm, w, s) ** 2))(x)
        return jnp.sum(gq ** 2)

    g_ref = jax.grad(lambda wm: r1(wm, ref))(1.1)
    g_got = jax.grad(lambda wm: r1(wm, got))(1.1)
    np.testing.assert_allclose(float(g_got), float(g_ref), rtol=1e-4)


@pytest.mark.slow  # the R1 sweep above is the tier-1 second-order gate
@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_pl_shaped_hvp(rng, case):
    """The PL transform shape, jitted like the real g_step_pl: the
    scalar moves weights AND styles along fixed random directions and
    the HVP flows through the inner input-grad.  (Additive directions,
    not a multiplicative scale: demodulation makes the op exactly
    scale-invariant in (w, s), which would leave only fp noise to
    compare.)"""
    x, w, s, ref, got = _mc_inputs(rng, case)
    dw0 = jnp.asarray(rng.randn(*w.shape) * 0.2, jnp.float32)
    ds0 = jnp.asarray(rng.randn(*s.shape) * 0.3, jnp.float32)

    def pl(wm, fn):
        gq = jax.grad(lambda x_: jnp.sum(
            fn(x_, w + wm * dw0, s + wm * ds0) ** 2))(x)
        return jnp.sum(gq ** 2)

    g_got = jax.jit(jax.grad(lambda wm: pl(wm, got)))(0.1)
    g_ref = jax.grad(lambda wm: pl(wm, ref))(0.1)
    np.testing.assert_allclose(float(g_got), float(g_ref), rtol=1e-4)


def test_bwd_kernels_are_on_the_reverse_path(rng):
    """First-order reverse must RUN the backward kernels: the grad jaxpr
    carries ≥ 3 pallas_call sites (forward + dx/ds + dw), where a
    glue-transposed rule would carry exactly the forward one."""
    x, w, s, _, got = _mc_inputs(rng, "same3")
    jaxpr = str(jax.make_jaxpr(
        lambda x_: jax.grad(lambda x2: jnp.sum(got(x2, w, s)))(x_))(x))
    assert jaxpr.count("pallas_call") >= 3, jaxpr[:2000]


def test_forward_mode_is_rejected(rng):
    """Direct jax.jvp through the op is NOT supported (custom_vjp outer
    layer) — same contract as the attention kernels; R1/PL are
    reverse-mode formulations and never hit this."""
    x, w, s, _, got = _mc_inputs(rng, "same3")
    with pytest.raises(TypeError, match="custom_vjp"):
        jax.jvp(lambda x_: got(x_, w, s), (x,), (x,))


def test_oversize_and_unsupported_fall_back_to_xla(rng):
    """The geometry gate returns the XLA composite instead of a broken
    kernel: a 5×5 kernel (unsupported) and a down=2 call both produce
    XLA-exact results, and each denial is COUNTED at the dispatch seam
    (``ops/modconv_fallback_total`` by cause — the ISSUE 17 telemetry
    that turns a silent coverage regression into a prom line)."""
    from gansformer_tpu.obs import registry as telemetry

    reg = telemetry.get_registry()
    before = {c: reg.counter(f"ops/modconv_fallback{c}_total").value
              for c in ("", "_shape", "_vmem")}
    x = jnp.asarray(rng.randn(1, 8, 8, 4), jnp.float32)
    w5 = jnp.asarray(rng.randn(5, 5, 4, 4) * 0.2, jnp.float32)
    s = jnp.asarray(rng.randn(1, 4) + 1.0, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(modulated_conv2d_pallas(x, w5, s, interpret=True)),
        np.asarray(modulated_conv2d(x, w5, s)), **FWD["float32"])
    w3 = jnp.asarray(rng.randn(3, 3, 4, 4) * 0.2, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(modulated_conv2d_pallas(x, w3, s, down=2,
                                           interpret=True)),
        np.asarray(modulated_conv2d(x, w3, s, down=2)),
        **FWD["float32"])
    assert reg.counter("ops/modconv_fallback_total").value == \
        before[""] + 2
    assert reg.counter("ops/modconv_fallback_shape_total").value == \
        before["_shape"] + 2
    assert reg.counter("ops/modconv_fallback_vmem_total").value == \
        before["_vmem"]
    assert modconv_fits(x.shape, w3.shape, up=1)


def test_modconv_plan_semantics(rng):
    """The typed planner verdicts (ISSUE 17): whole when the image
    double-buffers in the budget, the largest dividing row block when
    only strips do (the pre-row-blocking ``modconv_fits`` rejected this
    4096² grid outright), 'shape' for unimplemented geometry, and a
    'vmem' fallback ONLY when even a single-row strip overflows — plus
    the ``modconv_fits`` shim staying consistent with ``.ok``."""
    assert modconv_plan((1, 8, 8, 4), (3, 3, 4, 4)).mode == "whole"
    big = modconv_plan((1, 4096, 4096, 64), (3, 3, 64, 64), up=1)
    assert big.mode == "rows" and big.rows is not None
    assert 4096 % big.rows == 0 and big.rows < 4096
    assert modconv_fits((1, 4096, 4096, 64), (3, 3, 64, 64), up=1)
    for shape_case in (
            modconv_plan((1, 8, 8, 4), (5, 5, 4, 4)),          # 5×5
            modconv_plan((1, 8, 8, 4), (3, 3, 4, 4), down=2),  # down
            modconv_plan((1, 8, 8, 4), (3, 3, 4, 4), up=4)):   # up∉{1,2}
        assert shape_case.mode == "fallback" and not shape_case.ok
        assert shape_case.cause == "shape"
    # A single-row strip of a 2²⁰-wide grid overflows any budget: the
    # one geometry row blocking cannot save.
    wide = modconv_plan((1, 8, 1 << 20, 64), (3, 3, 64, 64), up=1)
    assert wide.mode == "fallback" and wide.cause == "vmem"
    assert not modconv_fits((1, 8, 1 << 20, 64), (3, 3, 64, 64), up=1)


# --------------------------------------------------------------------------
# halo row blocking (ISSUE 17): blocked vs whole-image parity
# --------------------------------------------------------------------------


def _mc_blocked(rng, case, dtype=jnp.float32, h=8):
    k, up, demod = MC_CASES[case]
    x = jnp.asarray(rng.randn(2, h, 8, 6), dtype)
    w = jnp.asarray(rng.randn(k, k, 6, 10) * 0.2, dtype)
    s = jnp.asarray(rng.randn(2, 6) * 0.3 + 1.0, jnp.float32)

    def run(block_rows):
        return lambda x_, w_, s_: modulated_conv2d_pallas(
            x_, w_, s_, demodulate=demod, up=up, block_rows=block_rows,
            interpret=True)

    return x, w, s, run


@pytest.mark.parametrize("case", ["same3", "same1", "poly"])
@pytest.mark.parametrize("h,bh", [(8, 4), (9, 3), (8, 2)],
                         ids=["h8b4", "h9b3-odd", "h8b2"])
def test_modconv_row_blocked_forward_bit_parity(rng, case, h, bh):
    """Row-blocked forward vs the whole-image launch, BIT-identical:
    each output pixel's tap accumulation happens entirely inside one
    strip in the same order, so tiling must not move a single ulp —
    including odd row counts where the halo crosses block boundaries
    asymmetrically (h=9, bh=3)."""
    x, w, s, run = _mc_blocked(rng, case, h=h)
    y_whole = run(None)(x, w, s)     # tiny grid → the plan is 'whole'
    y_rows = run(bh)(x, w, s)
    assert y_rows.shape == y_whole.shape
    np.testing.assert_array_equal(np.asarray(y_rows), np.asarray(y_whole))


@pytest.mark.parametrize("case", ["same3", "same1", "poly"])
@pytest.mark.parametrize("h,bh", [(8, 2), (9, 3)], ids=["h8b2", "h9b3-odd"])
def test_modconv_row_blocked_grads_match_whole(rng, case, h, bh):
    """dx/dw/dstyles through the row-blocked backward kernels vs the
    whole-image launch: dx is strip-local (bit parity); dw and ds
    accumulate ACROSS strips (the revisited-output ds and the dw grid
    scratch), so they carry only fp32 reassociation noise."""
    x, w, s, run = _mc_blocked(rng, case, h=h)

    def loss(fn):
        return lambda x_, w_, s_: jnp.sum(jnp.sin(fn(x_, w_, s_)))

    g_whole = jax.grad(loss(run(None)), argnums=(0, 1, 2))(x, w, s)
    g_rows = jax.grad(loss(run(bh)), argnums=(0, 1, 2))(x, w, s)
    np.testing.assert_array_equal(np.asarray(g_rows[0]),
                                  np.asarray(g_whole[0]), err_msg="dx")
    for a, g, name in zip(g_whole[1:], g_rows[1:], ("dw", "dstyles")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   atol=1e-4, rtol=1e-5, err_msg=name)


@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_row_blocked_bf16(rng, case):
    """bf16 blocked vs whole: the strips accumulate in fp32 and round
    once at the output write, so the forward AND both first-order grads
    stay bit-identical across tilings (the ISSUE 17 'bf16 round-off'
    acceptance, met at zero ulps)."""
    x, w, s, run = _mc_blocked(rng, case, jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(run(4)(x, w, s), np.float32),
        np.asarray(run(None)(x, w, s), np.float32))

    def loss(fn):
        return lambda x_, w_: jnp.sum(fn(x_, w_, s).astype(jnp.float32)**2)

    g_whole = jax.grad(loss(run(None)), argnums=(0, 1))(x, w)
    g_rows = jax.grad(loss(run(4)), argnums=(0, 1))(x, w)
    for a, g, name in zip(g_whole, g_rows, ("dx", "dw")):
        assert g.dtype == jnp.bfloat16, name
        np.testing.assert_array_equal(np.asarray(g, np.float32),
                                      np.asarray(a, np.float32),
                                      err_msg=name)


@pytest.mark.parametrize(
    "case", [(1, 1, (2, 1), 6), (2, 1, (2, 1), 6), (1, 2, (1, 1), 3),
             (2, 2, (2, 1, 0, 3), 4)],
    ids=["blur-b6", "up2-b6", "down2-b3", "updown-b4"])
def test_upfirdn_row_blocked_matches_whole(rng, case):
    """upfirdn row strips (the wrapper's pre-pad/crop + per-strip tap
    offset algebra) vs the whole-image launch — bit parity on the
    forward AND on grads (the adjoint is the same kernel on its own
    plan, so the forward's tiling must be invisible to it)."""
    up, down, pad, bh = case
    f = setup_filter((1, 3, 3, 1))
    x = jnp.asarray(rng.randn(2, 12, 11, 4), jnp.float32)

    def run(block_rows):
        return lambda x_: upfirdn2d_pallas(x_, f, up=up, down=down,
                                           pad=pad, block_rows=block_rows,
                                           interpret=True)

    np.testing.assert_array_equal(np.asarray(run(bh)(x)),
                                  np.asarray(run(None)(x)))
    gw = jax.grad(lambda x_: jnp.sum(jnp.sin(run(None)(x_))))(x)
    gr = jax.grad(lambda x_: jnp.sum(jnp.sin(run(bh)(x_))))(x)
    np.testing.assert_array_equal(np.asarray(gr), np.asarray(gw))


def test_upfirdn_plan_semantics(monkeypatch):
    """The upfirdn planner's typed verdicts under a shrunken budget:
    whole → rows → vmem, with the row block dividing the OUTPUT rows and
    honoring the phase-alignment constraint, and ``upfirdn_fits``
    demanding an ok plan for the adjoint too."""
    from gansformer_tpu.ops import pallas_upfirdn

    f_shape = (4, 4)
    xs = (2, 16, 16, 4)
    pad4 = (2, 1, 2, 1)
    assert upfirdn_plan(xs, f_shape, 1, 1, pad4).mode == "whole"
    monkeypatch.setattr(pallas_upfirdn, "_VMEM_BUDGET", 3 * 1024)
    p = upfirdn_plan(xs, f_shape, 1, 1, pad4)
    assert p.mode == "rows" and 16 % p.rows == 0 and p.rows < 16
    assert upfirdn_fits(xs, f_shape, 1, 1, pad4)
    monkeypatch.setattr(pallas_upfirdn, "_VMEM_BUDGET", 64)
    tiny = upfirdn_plan(xs, f_shape, 1, 1, pad4)
    assert tiny.mode == "fallback" and tiny.cause == "vmem"
    assert not upfirdn_fits(xs, f_shape, 1, 1, pad4)


# --------------------------------------------------------------------------
# flagship grid coverage gate (ISSUE 17)
# --------------------------------------------------------------------------


def _flagship_conv_calls(mcfg, batch=8):
    """Enumerate every kernel launch one generator + one discriminator
    forward emit at this ModelConfig — mirrored layer-by-layer from
    models/synthesis.py and models/discriminator.py (the D dense convs
    are plain MXU contractions by design and carry no Pallas launch).
    Returns (filter_shape, modconv_calls, upfirdn_calls)."""
    from gansformer_tpu.ops.upfirdn2d import _pad4

    f = np.asarray(setup_filter(mcfg.blur_filter))
    fh = f.shape[0]
    ch = mcfg.img_channels
    mc, fir = [], []
    for res in mcfg.block_resolutions:
        nf = mcfg.nf(res)
        if res > 4:
            nf_in = mcfg.nf(res // 2)
            mc.append((f"G/b{res}_conv_up",
                       (batch, res // 2, res // 2, nf_in),
                       (3, 3, nf_in, nf), 2))
            p = fh - 1  # the up-conv's fused blur leg (filter_2d pads)
            fir.append((f"G/b{res}_conv_up/blur", (batch, res, res, nf),
                        1, 1, _pad4(((p + 1) // 2, p // 2))))
            p = fh - 2  # rgb-skip upsample_2d, factor 2
            fir.append((f"G/b{res}_rgb_up",
                        (batch, res // 2, res // 2, ch), 2, 1,
                        _pad4(((p + 1) // 2 + 1, p // 2))))
        mc.append((f"G/b{res}_conv", (batch, res, res, nf),
                   (3, 3, nf, nf), 1))
        mc.append((f"G/b{res}_trgb", (batch, res, res, nf),
                   (1, 1, nf, ch), 1))
    for res in reversed(mcfg.block_resolutions[1:]):
        nf_in = mcfg.nf(res)
        p = (fh - 2) + 2  # blur-pool with the VALID 3×3's pad folded in
        fir.append((f"D/b{res}_conv1/blur", (batch, res, res, nf_in),
                    1, 1, _pad4(((p + 1) // 2, p // 2))))
        p = fh - 2        # decimated 1×1-skip blur (fused stride)
        fir.append((f"D/b{res}_skip/blur", (batch, res, res, nf_in),
                    1, 2, _pad4(((p + 1) // 2, p // 2))))
    return f.shape, mc, fir


@pytest.mark.parametrize("preset", ["ffhq256-duplex", "ffhq1024-duplex"])
def test_flagship_grids_all_route_to_pallas(preset):
    """ISSUE 17 acceptance gate: EVERY conv/FIR shape the flagship
    synthesis + discriminator emit gets an ok plan — no 'shape' and no
    'vmem' fallback — at fp32 AND bf16 item sizes, and the big grids
    actually exercise row blocking (before ISSUE 17 every grid from
    128² up was a silent XLA fallback).  Planner-level, so the tier-1
    gate prices the full 1024² coverage matrix without tracing a
    flagship model."""
    from gansformer_tpu.core.config import get_preset

    mcfg = get_preset(preset).model
    f_shape, mc, fir = _flagship_conv_calls(mcfg)
    assert len(mc) >= 3 * len(mcfg.block_resolutions) - 1
    modes = set()
    for itemsize in (4, 2):
        for name, xs, ws, up in mc:
            plan = modconv_plan(xs, ws, up=up, itemsize=itemsize)
            assert plan.ok, (preset, itemsize, name, xs, ws, plan)
            modes.add(plan.mode)
            if plan.mode == "rows":
                assert xs[1] % plan.rows == 0, (name, xs, plan)
    for name, xs, up, down, pad4 in fir:
        plan = upfirdn_plan(xs, f_shape, up, down, pad4)
        assert plan.ok, (preset, name, xs, plan)
        # the dispatch gate itself (fwd AND adjoint plans)
        assert upfirdn_fits(xs, f_shape, up, down, pad4), (preset, name)
        modes.add(plan.mode)
    # Both launch modes occur on every flagship: small grids stay
    # whole-image, the flagship-resolution grids row-block.
    assert modes == {"whole", "rows"}, (preset, modes)


# --------------------------------------------------------------------------
# config / serve wiring contracts
# --------------------------------------------------------------------------


def test_config_validates_conv_backend():
    """A typo fails fast with the allowed set — mirroring
    attention_backend exactly (ISSUE 14 satellite)."""
    from gansformer_tpu.core.config import ExperimentConfig, ModelConfig

    cfg = ExperimentConfig(model=ModelConfig(conv_backend="palas"))
    with pytest.raises(ValueError, match="conv_backend must be xla|pallas"):
        cfg.validate()


def test_config_rejects_conv_pallas_with_sequence_parallel():
    """pallas_call has no sharding rule: the combination would silently
    all-gather the model-sharded grid — rejected in words instead."""
    import dataclasses as dc

    from gansformer_tpu.core.config import (ExperimentConfig, MeshConfig,
                                            ModelConfig)

    cfg = ExperimentConfig(
        model=ModelConfig(conv_backend="pallas", sequence_parallel=True),
        mesh=MeshConfig(model=2, data=1))
    with pytest.raises(ValueError, match="conv_backend='pallas' does not"):
        cfg.validate()
    ok = dc.replace(cfg, model=dc.replace(
        cfg.model, conv_backend="xla"))
    ok.validate()


def test_conv_backend_roundtrips_through_config_json():
    from gansformer_tpu.core.config import ExperimentConfig, get_preset

    import dataclasses as dc

    cfg = get_preset("clevr64-simplex")
    cfg = dc.replace(cfg, model=dc.replace(cfg.model,
                                           conv_backend="pallas"))
    back = ExperimentConfig.from_json(cfg.to_json())
    assert back.model.conv_backend == "pallas"


def test_train_cli_conv_backend_flag():
    from gansformer_tpu.cli.train import build_parser, config_from_args

    args = build_parser().parse_args(
        ["--preset", "clevr64-simplex", "--conv-backend", "pallas"])
    assert config_from_args(args).model.conv_backend == "pallas"
    # tri-state: no flag inherits the loaded config's value
    args = build_parser().parse_args(["--preset", "clevr64-simplex"])
    assert config_from_args(args).model.conv_backend == "xla"


def test_serve_fingerprint_separates_conv_backends():
    """A warm-start manifest entry written under one conv backend can
    never be served under the other: the fingerprint hashes the full
    ModelConfig, conv_backend included (ISSUE 14 — AOT executables
    record the conv backend)."""
    import dataclasses as dc
    import json as _json

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.serve.warmstart import fingerprint

    cfg = get_preset("clevr64-simplex")
    m_xla = _json.dumps(dc.asdict(cfg.model))
    m_pl = _json.dumps(dc.asdict(
        dc.replace(cfg.model, conv_backend="pallas")))
    assert fingerprint(m_xla, "synthesize", 4) != \
        fingerprint(m_pl, "synthesize", 4)


def test_resolve_conv_backend_off_tpu():
    """Off-TPU, 'pallas' resolves to itself (interpret mode is the CI
    story) and 'xla' passes through untouched."""
    from gansformer_tpu.ops.pallas_modconv import resolve_conv_backend

    assert resolve_conv_backend("pallas") == "pallas"
    assert resolve_conv_backend("xla") == "xla"


# --------------------------------------------------------------------------
# model / training-path integration (slow tier)
# --------------------------------------------------------------------------


@pytest.mark.slow  # whole-generator + whole-D traces in interpret mode
def test_model_grads_match_xla_conv_backend(rng):
    """Grads of a duplex generator loss w.r.t. EVERY parameter agree
    between conv backends (kernel dispatch inside ModulatedConv, the
    fused tRGB epilogue, the rgb-skip pallas upsample, flax
    integration); same for the discriminator's blur-pool path."""
    from gansformer_tpu.core.config import ModelConfig
    from gansformer_tpu.models.discriminator import Discriminator
    from gansformer_tpu.models.generator import Generator

    cfg = ModelConfig(resolution=16, components=2, latent_dim=16, w_dim=16,
                      mapping_dim=16, mapping_layers=2, fmap_base=64,
                      fmap_max=16, attention="duplex", attn_start_res=8,
                      attn_max_res=8)
    cfg_pl = dataclasses.replace(cfg, conv_backend="pallas")
    z = jnp.asarray(rng.randn(2, cfg.num_ws, cfg.latent_dim), jnp.float32)
    noise = jax.random.PRNGKey(3)
    G = Generator(cfg)
    params = G.init({"params": jax.random.PRNGKey(0), "noise": noise}, z)
    G_pl = Generator(cfg_pl)

    def loss(g):
        return lambda p: jnp.mean(g.apply(p, z, rngs={"noise": noise})**2)

    gx = jax.tree_util.tree_leaves(jax.grad(loss(G))(params))
    gp = jax.tree_util.tree_leaves(jax.grad(loss(G_pl))(params))
    assert len(gx) == len(gp)
    for a, b in zip(gx, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-3)

    imgs = jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32)
    D = Discriminator(cfg)
    dvars = D.init(jax.random.PRNGKey(1), imgs)
    D_pl = Discriminator(cfg_pl)
    dx = jax.tree_util.tree_leaves(
        jax.grad(lambda p: jnp.mean(D.apply(p, imgs)**2))(dvars))
    dp = jax.tree_util.tree_leaves(
        jax.grad(lambda p: jnp.mean(D_pl.apply(p, imgs)**2))(dvars))
    for a, b in zip(dx, dp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-3)


@pytest.fixture(scope="module")
def conv_reg_step_pair():
    """The second-order SUPERSET step programs (d_step_r1, g_step_pl) on
    both conv backends, same inputs/rng — the ISSUE 14 acceptance that
    R1 grad-of-grad and PL HVPs re-enter the conv kernels' rules inside
    the REAL programs (same fixture shape as ISSUE 9's)."""
    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps
    from tests.test_train import micro_cfg

    imgs_np = np.random.RandomState(0).randint(
        0, 255, (8, 16, 16, 3), dtype=np.uint8)
    rng = jax.random.PRNGKey(11)
    out = {}
    for backend in ("xla", "pallas"):
        cfg = micro_cfg(attention="duplex")
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, conv_backend=backend))
        cfg.validate()
        env = make_mesh(cfg.mesh)
        state = jax.device_put(
            create_train_state(cfg, jax.random.PRNGKey(0)),
            env.replicated())
        fns = make_train_steps(cfg, env, batch_size=cfg.train.batch_size)
        imgs = jax.device_put(imgs_np, env.batch())
        with env.activate():
            r = jax.random.fold_in(rng, 0)
            state, d_aux = fns.d_step_r1(state, imgs,
                                         jax.random.fold_in(r, 0))
            state, g_aux = fns.g_step_pl(state, jax.random.fold_in(r, 1))
            jax.block_until_ready(state.step)
        out[backend] = {k: float(jax.device_get(v))
                        for k, v in {**d_aux, **g_aux}.items()}
    return out


@pytest.mark.slow  # 4 second-order step compiles through interpret kernels
def test_conv_pallas_training_reg_steps_finite(conv_reg_step_pair):
    aux = conv_reg_step_pair["pallas"]
    assert "Loss/D/r1" in aux and "Loss/G/pl" in aux
    for k, v in aux.items():
        assert np.isfinite(v), (k, v)


@pytest.mark.slow  # shares the conv_reg_step_pair fixture
def test_conv_pallas_training_losses_match_xla(conv_reg_step_pair):
    ax, ap = conv_reg_step_pair["xla"], conv_reg_step_pair["pallas"]
    assert set(ax) == set(ap)
    for k in ax:
        np.testing.assert_allclose(ap[k], ax[k], atol=5e-3, rtol=5e-3,
                                   err_msg=k)


@pytest.mark.slow  # two micro train() runs (fresh second-order compiles)
def test_micro_train_run_conv_pallas_vs_xla(tmp_path):
    """ISSUE 14 acceptance: a micro ``train()`` with
    ``conv_backend='pallas'`` AND the fused 16-cycle completes with
    finite losses through full lazy-reg cadences, per-tick loss means
    within tolerance of the xla backend (chained-update fp-reorder
    band, as in ISSUE 9's twin test)."""
    import json
    import os

    from gansformer_tpu.train.loop import train
    from tests.test_train import micro_cfg

    ticks = {}
    for backend in ("xla", "pallas"):
        cfg = micro_cfg(attention="duplex", batch=40)
        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(cfg.model, conv_backend=backend),
            train=dataclasses.replace(cfg.train, fused_cycle=True))
        cfg.validate()
        d = str(tmp_path / backend)
        os.makedirs(d)
        train(cfg, d)
        with open(os.path.join(d, "stats.jsonl")) as f:
            rows = [json.loads(line) for line in f]
        assert rows, backend
        ticks[backend] = rows[-1]
    for key in ("Loss/D", "Loss/G", "Loss/D/r1", "Loss/G/pl",
                "Loss/scores/real", "Loss/scores/fake"):
        a, b = ticks["xla"][key], ticks["pallas"][key]
        assert np.isfinite(a) and np.isfinite(b), (key, a, b)


@pytest.mark.slow  # second-order sweeps at a flagship-class row count
@pytest.mark.parametrize("case", ["same3", "poly"])
def test_modconv_row_blocked_second_order_flagship_rows(rng, case):
    """R1-shaped grad-of-grad and a jitted PL-shaped HVP THROUGH the
    row-blocked kernels at a 256-row grid — the strip count the
    flagship plans pick (rows=64 at 256², so 4+ strips with live halo
    overlap on both the primal and tangent re-entries).  Channels cut
    for interpret-mode time; the row/halo algebra under test is
    channel-independent."""
    k, up, demod = MC_CASES[case]
    h = 256 // up
    x = jnp.asarray(rng.randn(1, h, h, 4), jnp.float32)
    w = jnp.asarray(rng.randn(k, k, 4, 4) * 0.2, jnp.float32)
    s = jnp.asarray(rng.randn(1, 4) * 0.3 + 1.0, jnp.float32)

    def run(block_rows):
        return lambda x_, w_, s_: modulated_conv2d_pallas(
            x_, w_, s_, demodulate=demod, up=up, block_rows=block_rows,
            interpret=True)

    def r1(wm, fn):
        gq = jax.grad(lambda x_: jnp.sum(fn(x_ * wm, w, s) ** 2))(x)
        return jnp.sum(gq ** 2)

    r1_whole = jax.grad(lambda wm: r1(wm, run(None)))(1.1)
    r1_rows = jax.grad(lambda wm: r1(wm, run(h // 4)))(1.1)
    np.testing.assert_allclose(float(r1_rows), float(r1_whole), rtol=1e-5)

    dw0 = jnp.asarray(rng.randn(*w.shape) * 0.2, jnp.float32)
    ds0 = jnp.asarray(rng.randn(*s.shape) * 0.3, jnp.float32)

    def pl(wm, fn):
        gq = jax.grad(lambda x_: jnp.sum(
            fn(x_, w + wm * dw0, s + wm * ds0) ** 2))(x)
        return jnp.sum(gq ** 2)

    pl_whole = jax.grad(lambda wm: pl(wm, run(None)))(0.1)
    pl_rows = jax.jit(jax.grad(lambda wm: pl(wm, run(h // 4))))(0.1)
    np.testing.assert_allclose(float(pl_rows), float(pl_whole), rtol=1e-5)


@pytest.mark.slow  # two micro train() runs under shrunken VMEM budgets
def test_micro_train_row_blocked_no_fallbacks(tmp_path, monkeypatch):
    """ISSUE 17 acceptance: shrink both VMEM budgets until the micro
    model's 16² grids can no longer launch whole-image — the geometry
    that fell back to XLA before row blocking — then run a full micro
    ``train()`` on conv_backend='pallas'.  The run's own telemetry must
    pin the coverage claim (``ops_modconv_fallback_total 0`` — every
    conv and FIR leg rode a Pallas kernel, several of them row-blocked)
    and the losses must stay finite and within the cross-backend
    reorder band of the xla twin."""
    import json
    import os

    from gansformer_tpu.obs.registry import parse_prom_values
    from gansformer_tpu.ops import pallas_modconv, pallas_upfirdn
    from gansformer_tpu.train.loop import train
    from tests.test_train import micro_cfg

    monkeypatch.setattr(pallas_modconv, "_VMEM_BUDGET", 8 * 1024)
    monkeypatch.setattr(pallas_upfirdn, "_VMEM_BUDGET", 4 * 1024)
    # The planners must agree BEFORE we pay for training: the micro
    # model's largest grids now row-block (no whole-image launch fits)
    # and nothing degrades to a fallback.
    mp = modconv_plan((8, 16, 16, 4), (3, 3, 4, 4))
    assert mp.mode == "rows", mp
    up_ = upfirdn_plan((8, 16, 16, 4), (4, 4), 1, 1, (2, 2, 2, 2))
    assert up_.mode == "rows", up_
    assert upfirdn_fits((8, 16, 16, 4), (4, 4), 1, 1, (2, 2, 2, 2))

    ticks = {}
    for backend in ("xla", "pallas"):
        cfg = micro_cfg(attention="duplex")
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model,
                                           conv_backend=backend))
        cfg.validate()
        d = str(tmp_path / backend)
        os.makedirs(d)
        train(cfg, d)
        with open(os.path.join(d, "stats.jsonl")) as f:
            rows = [json.loads(line) for line in f]
        assert rows, backend
        ticks[backend] = rows
        prom = parse_prom_values(os.path.join(d, "telemetry.prom"))
        assert prom.get("ops_modconv_fallback_total") == 0.0, (backend,
                                                               prom)
        assert prom.get("ops_modconv_fallback_shape_total") == 0.0
        assert prom.get("ops_modconv_fallback_vmem_total") == 0.0
    for key in ("Loss/D", "Loss/G", "Loss/scores/real",
                "Loss/scores/fake"):
        a, b = ticks["xla"][0][key], ticks["pallas"][0][key]
        assert np.isfinite(a) and np.isfinite(b), (key, a, b)
        # First-tick means, same seed: the kernels are near-bit vs the
        # composite, so only chained-update fp reorder separates the
        # backends (the ISSUE 9/14 twin tests' tolerance class).
        np.testing.assert_allclose(b, a, err_msg=key,
                                   **TRAIN_REORDER["float32"])
        np.testing.assert_allclose(b, a, err_msg=key,
                                   **TRAIN_REORDER["bfloat16"])
