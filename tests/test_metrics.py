"""Metric pipeline tests — closed-form Fréchet distance on synthetic
Gaussians (SURVEY.md §4 'Implication for the TPU build')."""

import jax.numpy as jnp
import numpy as np

from gansformer_tpu.metrics.fid import (
    compute_activation_stats,
    fid_from_features,
    frechet_distance,
    sqrtm_newton_schulz,
)
from gansformer_tpu.metrics.inception_score import inception_score


def test_frechet_distance_identical_is_zero():
    mu = np.zeros(8)
    sigma = np.eye(8)
    assert abs(frechet_distance(mu, sigma, mu, sigma)) < 1e-8


def test_frechet_distance_closed_form_means():
    # equal covariances → d² = ||μ₁-μ₂||²
    sigma = np.eye(4) * 2.0
    mu1, mu2 = np.zeros(4), np.array([1.0, 2.0, 0.0, 0.0])
    np.testing.assert_allclose(
        frechet_distance(mu1, sigma, mu2, sigma), 5.0, rtol=1e-6)


def test_frechet_distance_closed_form_diag():
    # diagonal Σ → d² = Σᵢ (√σ1ᵢ - √σ2ᵢ)²  (means equal)
    s1 = np.diag([1.0, 4.0])
    s2 = np.diag([9.0, 16.0])
    expect = (1 - 3) ** 2 + (2 - 4) ** 2
    np.testing.assert_allclose(
        frechet_distance(np.zeros(2), s1, np.zeros(2), s2), expect, rtol=1e-6)


def test_sqrtm_newton_schulz_matches_eig():
    rs = np.random.RandomState(0)
    a = rs.randn(16, 16)
    psd = (a @ a.T + 16 * np.eye(16)).astype(np.float32)
    got = np.asarray(sqrtm_newton_schulz(jnp.asarray(psd)))
    np.testing.assert_allclose(got @ got, psd, rtol=2e-3, atol=2e-3)


def test_fid_from_samples_statistical():
    rs = np.random.RandomState(1)
    a = rs.randn(4000, 16)
    b = rs.randn(4000, 16) + 1.0  # shifted → d² ≈ 16
    same = fid_from_features(a, rs.randn(4000, 16))
    diff = fid_from_features(a, b)
    assert same < 1.0
    assert abs(diff - 16.0) < 2.0


def test_inception_score_bounds():
    rs = np.random.RandomState(2)
    n, c = 1000, 10
    # one-hot-confident uniform-over-classes logits → IS ≈ num classes
    classes = rs.randint(0, c, n)
    logits = np.full((n, c), -20.0)
    logits[np.arange(n), classes] = 20.0
    mean, _ = inception_score(logits, splits=5)
    assert mean > c * 0.8
    # constant logits → IS = 1
    mean, _ = inception_score(np.zeros((n, c)), splits=5)
    np.testing.assert_allclose(mean, 1.0, rtol=1e-6)


def test_metric_group_on_tiny_extractor():
    """End-to-end FID/IS machinery with the uncalibrated extractor on tiny
    images — pipeline correctness, not FID values."""
    from gansformer_tpu.data.dataset import SyntheticDataset
    from gansformer_tpu.metrics.inception import FeatureExtractor
    from gansformer_tpu.metrics.metric_base import FIDMetric, ISMetric, MetricGroup

    ds = SyntheticDataset(resolution=32, num_images=64)
    ex = FeatureExtractor(None)  # deterministic random init
    group = MetricGroup([FIDMetric(num_images=16, batch_size=8),
                         ISMetric(num_images=16, batch_size=8, splits=2)],
                        extractor=ex)

    rs = np.random.RandomState(3)

    def sample_fn(n):
        return jnp.asarray(rs.rand(n, 32, 32, 3).astype(np.float32) * 2 - 1)

    out = group.run(sample_fn, ds)
    assert np.isfinite(out["fid16_uncal"]) and out["fid16_uncal"] >= 0
    assert out["is16_uncal_mean"] >= 1.0
    assert out["calibrated"] == 0.0
