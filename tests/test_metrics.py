"""Metric pipeline tests — closed-form Fréchet distance on synthetic
Gaussians (SURVEY.md §4 'Implication for the TPU build')."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.metrics.fid import (
    compute_activation_stats,
    fid_from_features,
    frechet_distance,
    sqrtm_newton_schulz,
)
from gansformer_tpu.metrics.inception_score import inception_score


def test_frechet_distance_identical_is_zero():
    mu = np.zeros(8)
    sigma = np.eye(8)
    assert abs(frechet_distance(mu, sigma, mu, sigma)) < 1e-8


def test_frechet_distance_closed_form_means():
    # equal covariances → d² = ||μ₁-μ₂||²
    sigma = np.eye(4) * 2.0
    mu1, mu2 = np.zeros(4), np.array([1.0, 2.0, 0.0, 0.0])
    np.testing.assert_allclose(
        frechet_distance(mu1, sigma, mu2, sigma), 5.0, rtol=1e-6)


def test_frechet_distance_closed_form_diag():
    # diagonal Σ → d² = Σᵢ (√σ1ᵢ - √σ2ᵢ)²  (means equal)
    s1 = np.diag([1.0, 4.0])
    s2 = np.diag([9.0, 16.0])
    expect = (1 - 3) ** 2 + (2 - 4) ** 2
    np.testing.assert_allclose(
        frechet_distance(np.zeros(2), s1, np.zeros(2), s2), expect, rtol=1e-6)


def test_sqrtm_newton_schulz_matches_eig():
    rs = np.random.RandomState(0)
    a = rs.randn(16, 16)
    psd = (a @ a.T + 16 * np.eye(16)).astype(np.float32)
    got = np.asarray(sqrtm_newton_schulz(jnp.asarray(psd)))
    np.testing.assert_allclose(got @ got, psd, rtol=2e-3, atol=2e-3)


def test_fid_from_samples_statistical():
    rs = np.random.RandomState(1)
    a = rs.randn(4000, 16)
    b = rs.randn(4000, 16) + 1.0  # shifted → d² ≈ 16
    same = fid_from_features(a, rs.randn(4000, 16))
    diff = fid_from_features(a, b)
    assert same < 1.0
    assert abs(diff - 16.0) < 2.0


def test_inception_score_bounds():
    rs = np.random.RandomState(2)
    n, c = 1000, 10
    # one-hot-confident uniform-over-classes logits → IS ≈ num classes
    classes = rs.randint(0, c, n)
    logits = np.full((n, c), -20.0)
    logits[np.arange(n), classes] = 20.0
    mean, _ = inception_score(logits, splits=5)
    assert mean > c * 0.8
    # constant logits → IS = 1
    mean, _ = inception_score(np.zeros((n, c)), splits=5)
    np.testing.assert_allclose(mean, 1.0, rtol=1e-6)


def test_metric_group_on_tiny_extractor():
    """End-to-end FID/IS machinery with the uncalibrated extractor on tiny
    images — pipeline correctness, not FID values."""
    from gansformer_tpu.data.dataset import SyntheticDataset
    from gansformer_tpu.metrics.inception import FeatureExtractor
    from gansformer_tpu.metrics.metric_base import FIDMetric, ISMetric, MetricGroup

    ds = SyntheticDataset(resolution=32, num_images=64)
    ex = FeatureExtractor(None)  # deterministic random init
    group = MetricGroup([FIDMetric(num_images=16, batch_size=8),
                         ISMetric(num_images=16, batch_size=8, splits=2)],
                        extractor=ex)

    rs = np.random.RandomState(3)

    def sample_fn(n):
        return jnp.asarray(rs.rand(n, 32, 32, 3).astype(np.float32) * 2 - 1)

    out = group.run(sample_fn, ds)
    assert np.isfinite(out["fid16_uncal"]) and out["fid16_uncal"] >= 0
    assert out["is16_uncal_mean"] >= 1.0
    assert out["calibrated"] == 0.0


def test_feature_extractor_sharded_over_mesh():
    """The FID sweep runs data-parallel over the mesh (VERDICT r2 item 4):
    input batches land sharded on the data axis, params replicated, and a
    non-divisible batch is padded+trimmed.  Results match the unsharded
    extractor exactly."""
    import jax

    from gansformer_tpu.metrics.inception import FeatureExtractor
    from gansformer_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from gansformer_tpu.core.config import MeshConfig

    env = make_mesh(MeshConfig())
    assert env.data_size == 8  # conftest forces the 8-device CPU mesh
    ex_mesh = FeatureExtractor(None, env=env)
    ex_solo = FeatureExtractor(None)

    rs = np.random.RandomState(0)
    imgs = jnp.asarray(rs.rand(8, 32, 32, 3).astype(np.float32) * 2 - 1)
    sharded = jax.device_put(imgs, env.batch())
    spec = sharded.sharding.spec
    assert spec and spec[0] == DATA_AXIS  # batch axis rides the mesh

    f_mesh, l_mesh = ex_mesh(imgs)
    f_solo, l_solo = ex_solo(imgs)
    np.testing.assert_allclose(np.asarray(f_mesh), np.asarray(f_solo),
                               rtol=2e-4, atol=2e-4)

    # batch=5 doesn't divide the 8-device mesh → pad+trim path
    f5, l5 = ex_mesh(imgs[:5])
    assert f5.shape[0] == 5 and l5.shape[0] == 5
    np.testing.assert_allclose(np.asarray(f5), np.asarray(f_solo)[:5],
                               rtol=2e-4, atol=2e-4)


# --- PPL + precision/recall (VERDICT r2 item 8) ------------------------------

def test_precision_recall_identical_and_disjoint():
    from gansformer_tpu.metrics.precision_recall import precision_recall

    rs = np.random.RandomState(0)
    a = rs.randn(256, 16).astype(np.float32)
    p, r = precision_recall(a, a.copy(), k=3, block=64)
    assert p == 1.0 and r == 1.0  # identical sets cover each other

    far = a + 1000.0
    p, r = precision_recall(a, far, k=3, block=64)
    assert p == 0.0 and r == 0.0  # disjoint manifolds

    # mode-dropping fake set: high precision (fakes sit on the real
    # manifold), low recall (half the real modes uncovered)
    reals = np.concatenate([rs.randn(200, 8), rs.randn(200, 8) + 50.0]
                           ).astype(np.float32)
    fakes = (rs.randn(400, 8) * 0.5).astype(np.float32)  # first mode only
    p, r = precision_recall(reals, fakes, k=3)
    assert p > 0.8 and r < 0.6


def test_ppl_distance_filtering():
    from gansformer_tpu.metrics.ppl import ppl_from_distances

    d = np.ones(1000)
    d[0] = 1e9   # outlier must be filtered by the 1%-tails rule
    assert abs(ppl_from_distances(d) - 1.0) < 1e-6


def test_ppl_end_to_end_tiny_generator():
    """ppl_pairs probe + PPL metric on a micro generator: smaller ε-steps
    through a smooth G give finite, positive path lengths."""
    import jax

    from gansformer_tpu.core.config import (
        DataConfig, ExperimentConfig, ModelConfig, TrainConfig)
    from gansformer_tpu.metrics.inception import FeatureExtractor
    from gansformer_tpu.metrics.metric_base import PPLMetric
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    cfg = ExperimentConfig(
        model=ModelConfig(resolution=16, components=2, latent_dim=16,
                          w_dim=16, mapping_dim=16, mapping_layers=2,
                          fmap_base=64, fmap_max=32, attention="simplex",
                          attn_start_res=8, attn_max_res=8),
        train=TrainConfig(batch_size=8),
        data=DataConfig(resolution=16, source="synthetic"))
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    fns = make_train_steps(cfg, batch_size=8)
    ex = FeatureExtractor(None)

    def pair_fn(n, ts, seed, epsilon):
        k0, k1, kn = jax.random.split(jax.random.PRNGKey(seed), 3)
        shape = (n, cfg.model.num_ws, cfg.model.latent_dim)
        return fns.ppl_pairs(state.ema_params, jax.random.normal(k0, shape),
                             jax.random.normal(k1, shape),
                             np.asarray(ts, np.float32), kn, epsilon)

    m = PPLMetric(num_samples=16, batch_size=8, epsilon=1e-2)
    out = m.run(None, None, ex, None, pair_fn=pair_fn)
    (name, val), = out.items()
    assert name == "ppl16_wfull_uncal"
    assert np.isfinite(val) and val >= 0


def test_parse_metric_names_ppl_pr():
    from gansformer_tpu.metrics.metric_base import (
        PPLMetric, PRMetric, parse_metric_names)

    ms = parse_metric_names("fid1k,ppl2k,pr500", batch_size=8)
    assert isinstance(ms[1], PPLMetric) and ms[1].num_samples == 2000
    assert isinstance(ms[2], PRMetric) and ms[2].num_images == 500


def test_calibrated_fetch_attempt_is_one_shot(tmp_path, monkeypatch):
    """try_fetch_calibrated records its outcome and never re-attempts
    (VERDICT r2 item 2: attempt the download path once, record it)."""
    import json

    from gansformer_tpu.metrics import inception as inc

    monkeypatch.setattr(inc, "_WEIGHTS_DIR", str(tmp_path))
    monkeypatch.setattr(inc, "_CAL_NPZ", str(tmp_path / "w.npz"))
    monkeypatch.setattr(inc, "_FETCH_OUTCOME", str(tmp_path / "o.json"))
    # this test is about the one-shot NETWORK attempt; local cache probes
    # (tested separately below) depend on the host's ~/.cache contents
    monkeypatch.setattr(inc, "_local_checkpoint_candidates", lambda: [])

    calls = []

    class FakeProc:
        returncode = 1
        stderr = "URL fetch failure: no network"

    import subprocess as sp
    monkeypatch.setattr(sp, "run", lambda *a, **k: calls.append(1) or FakeProc())
    assert inc.try_fetch_calibrated() is None
    assert json.load(open(tmp_path / "o.json"))["result"] == "failed"
    assert inc.try_fetch_calibrated() is None   # marker short-circuits
    assert len(calls) == 1

    # a corrupt/truncated weights file must NOT be trusted (partial
    # download from a killed converter)
    (tmp_path / "w.npz").write_bytes(b"x")
    assert inc.try_fetch_calibrated() is None

    # a loadable weights file wins without any attempt
    np.savez(tmp_path / "w.npz", a=np.zeros(1))
    assert inc.try_fetch_calibrated() == str(tmp_path / "w.npz")
    assert len(calls) == 1


def _flat_from_net_params(params) -> dict:
    """Our InceptionV3 param tree → flat {'a/b/c': np.ndarray}."""
    flat = {}

    def walk(node, prefix):
        for k, v in node.items():
            if isinstance(v, dict):
                walk(v, prefix + k + "/")
            else:
                flat[prefix + k] = np.asarray(v)

    walk(params, "")
    return flat


def synthetic_torch_checkpoint(seed: int = 0) -> dict:
    """A torchvision-named Inception state_dict with our net's shapes and
    random values — the airgapped stand-in for pt_inception-2015-12-05."""
    from gansformer_tpu.metrics.convert_inception import (
        _TORCH_CONV_RENAME, ordered_convbn_paths)
    from gansformer_tpu.metrics.inception import FeatureExtractor

    flat = _flat_from_net_params(FeatureExtractor(None, seed=seed).params)
    inv = {v: k for k, v in _TORCH_CONV_RENAME.items()}
    sd = {}
    for path in ordered_convbn_paths():
        block, _, branch = path.partition("/")
        mod = (inv[block] if not branch else
               f"{block}." + ("branch_pool" if branch == "bpool"
                              else branch.replace("b", "branch", 1)))
        sd[f"{mod}.conv.weight"] = flat[f"{path}/conv/kernel"].transpose(
            3, 2, 0, 1)
        sd[f"{mod}.bn.weight"] = np.ones_like(flat[f"{path}/beta"])
        sd[f"{mod}.bn.bias"] = flat[f"{path}/beta"]
        sd[f"{mod}.bn.running_mean"] = flat[f"{path}/mean"]
        sd[f"{mod}.bn.running_var"] = flat[f"{path}/var"]
        sd[f"{mod}.bn.num_batches_tracked"] = np.zeros((), np.int64)
    sd["fc.weight"] = flat["fc/kernel"].T
    sd["fc.bias"] = flat["fc/bias"]
    return sd


def test_local_torch_cache_probe_converts_and_calibrates(tmp_path,
                                                         monkeypatch):
    """try_fetch_calibrated (VERDICT r3 item 5): a torch checkpoint already
    sitting in the torch-hub download cache is found, converted through the
    REAL converter subprocess, and yields a calibrated extractor — no
    network involved."""
    torch = pytest.importorskip("torch")

    from gansformer_tpu.metrics import inception as inc

    hub = tmp_path / "torch_home" / "hub" / "checkpoints"
    hub.mkdir(parents=True)
    torch.save(synthetic_torch_checkpoint(),
               str(hub / "inception_v3_google-test.pth"))

    monkeypatch.setenv("TORCH_HOME", str(tmp_path / "torch_home"))
    monkeypatch.setattr(inc, "_WEIGHTS_DIR", str(tmp_path / "w"))
    monkeypatch.setattr(inc, "_CAL_NPZ", str(tmp_path / "w" / "cal.npz"))
    monkeypatch.setattr(inc, "_FETCH_OUTCOME",
                        str(tmp_path / "w" / "outcome.json"))

    got = inc.try_fetch_calibrated(timeout=180.0)
    assert got == str(tmp_path / "w" / "cal.npz"), got
    import json
    outcome = json.load(open(tmp_path / "w" / "outcome.json"))
    assert outcome["result"] == "success"
    assert outcome["local_probes"][0]["kind"] == "torch"

    ext = inc.FeatureExtractor(inc.load_params_npz(got))
    assert ext.calibrated
    # converted weights are numerically usable end to end
    x = np.random.RandomState(3).rand(2, 64, 64, 3).astype(np.float32) * 2 - 1
    f, l = ext(x)
    assert np.isfinite(np.asarray(f)).all() and np.asarray(f).shape == (2, 2048)


def test_failed_local_probe_is_memoized(tmp_path, monkeypatch):
    """A corrupt checkpoint in the cache must cost ONE converter attempt,
    not one per metric tick (code-review r4): failed probes are skipped by
    (path, mtime) until the file changes."""
    from gansformer_tpu.metrics import inception as inc

    hub = tmp_path / "torch_home" / "hub" / "checkpoints"
    hub.mkdir(parents=True)
    bad = hub / "inception_corrupt.pth"
    bad.write_bytes(b"not a checkpoint")

    monkeypatch.setenv("TORCH_HOME", str(tmp_path / "torch_home"))
    monkeypatch.setattr(inc, "_WEIGHTS_DIR", str(tmp_path / "w"))
    monkeypatch.setattr(inc, "_CAL_NPZ", str(tmp_path / "w" / "cal.npz"))
    monkeypatch.setattr(inc, "_FETCH_OUTCOME",
                        str(tmp_path / "w" / "outcome.json"))
    monkeypatch.setattr(inc, "_FAILED_PROBES", {})

    calls = []

    def fake_converter(args, timeout):
        calls.append(list(args))
        return 1, "conversion failed"

    monkeypatch.setattr(inc, "_run_converter", fake_converter)
    assert inc.try_fetch_calibrated() is None
    n_first = len(calls)
    assert n_first >= 2          # the bad probe + the network attempt
    assert inc.try_fetch_calibrated() is None
    assert len(calls) == n_first       # probe memoized, network one-shot

    # cross-process memo: a fresh in-process dict still skips via the file
    monkeypatch.setattr(inc, "_FAILED_PROBES", {})
    assert inc.try_fetch_calibrated() is None
    assert len(calls) == n_first

    # a CHANGED file is probed again
    bad.write_bytes(b"different bytes")
    os.utime(bad, (1e9, 2e9))
    assert inc.try_fetch_calibrated() is None
    assert len(calls) == n_first + 1


def test_eval_mesh_falls_back_when_run_mesh_too_big():
    """A checkpoint trained on a bigger mesh (e.g. --mesh-model 2 on a pod)
    must still evaluate on this host: metrics/sweep.py falls back to an
    all-devices DP mesh when the saved layout doesn't fit."""
    import jax

    from gansformer_tpu.core.config import (
        DataConfig, ExperimentConfig, MeshConfig, ModelConfig, TrainConfig)
    from gansformer_tpu.metrics.sweep import make_eval_mesh

    cfg = ExperimentConfig(
        name="podrun",
        model=ModelConfig(resolution=16, sequence_parallel=True),
        train=TrainConfig(batch_size=8),
        data=DataConfig(resolution=16, source="synthetic"),
        mesh=MeshConfig(data=8, model=2),  # needs 16 devices; host has 8
    )
    env = make_eval_mesh(cfg)
    assert env.mesh.size == len(jax.devices())
    assert env.model_size == 1
    # and when the saved mesh does fit, it is honored
    cfg_fit = ExperimentConfig(
        name="fits", model=cfg.model, train=cfg.train, data=cfg.data,
        mesh=MeshConfig(data=4, model=2))
    assert make_eval_mesh(cfg_fit).model_size == 2


def test_preprocess_resize_matches_tf_golden():
    """Pin the FID-comparability-critical resize semantics (VERDICT r4
    weak #5): ``preprocess()`` claims jax.image.resize(antialias=True)
    matches TF's tf.image.resize(antialias=True) — the op the reference's
    Inception graph applies before feature extraction, and the op FID is
    notoriously sensitive to.  The golden fixture was computed ONCE with
    TF 2.21 (tests/data/resize_golden_tf.npz: deterministic RandomState(42)
    inputs at 64**2/256**2 -> bilinear+antialias 299**2, sampled on a 23x23
    probe grid + full-output mean/std), measured agreement 3.5e-6 max.
    A drift in jax.image.resize, in preprocess()'s method/antialias
    arguments, or in its clip/scale contract fails this test."""
    from gansformer_tpu.metrics.inception import preprocess

    golden = np.load(os.path.join(os.path.dirname(__file__), "data",
                                  "resize_golden_tf.npz"))
    rng = np.random.RandomState(42)   # must match the fixture generator
    for res in (64, 256):
        x = (rng.rand(2, res, res, 3).astype(np.float32) * 2 - 1)
        got = np.asarray(preprocess(jnp.asarray(x)))
        assert got.shape == (2, 299, 299, 3)
        np.testing.assert_allclose(
            got[:, ::13, ::13, :], golden[f"sample_{res}"],
            atol=1e-4, rtol=0,
            err_msg=f"resize semantics drifted vs TF golden at {res}^2")
        assert abs(got.mean() - golden[f"mean_{res}"]) < 1e-5
        assert abs(got.std() - golden[f"std_{res}"]) < 1e-5


def test_uncalibrated_extractor_discriminates():
    """Regression guard for the r5 uncalibrated-regime fix: random
    lecun-init features had collapsed to ~1e-4 scale (FID_uncal ~1e-4 for
    ANY pair of distributions — 'FID fell' was unobservable).  With the
    He rescale + probe standardization, features must have O(1) per-dim
    spread and the Frechet distance between clearly different
    distributions must dwarf the same-distribution sampling floor."""
    from gansformer_tpu.metrics.fid import (compute_activation_stats,
                                            frechet_distance)
    from gansformer_tpu.metrics.inception import FeatureExtractor

    ex = FeatureExtractor(None)
    rs = np.random.RandomState(0)
    noise_a = jnp.asarray(rs.rand(16, 64, 64, 3) * 2 - 1, jnp.float32)
    noise_b = jnp.asarray(rs.rand(16, 64, 64, 3) * 2 - 1, jnp.float32)
    yy, xx = np.mgrid[0:64, 0:64] / 64.0
    grads = jnp.asarray(np.stack(
        [np.stack([yy * s, xx, yy * xx], -1)
         for s in np.linspace(0.2, 1.0, 16)]) * 2 - 1, jnp.float32)

    fa, _ = ex(noise_a)
    fb, _ = ex(noise_b)
    fc, _ = ex(grads)
    fa, fb, fc = map(np.asarray, (fa, fb, fc))
    assert fa.std(0).mean() > 0.01, "features collapsed again"
    fid_same = frechet_distance(*compute_activation_stats(fa),
                                *compute_activation_stats(fb))
    fid_diff = frechet_distance(*compute_activation_stats(fa),
                                *compute_activation_stats(fc))
    assert fid_diff > 20 * fid_same, (fid_same, fid_diff)
