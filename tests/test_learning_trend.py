"""Tests for the learning-trend checker (VERDICT r4 item 4): the tool
that turns 'FID went down' from prose into an assertable property of a
run dir's recorded artifacts."""

import importlib.util
import json
import os
import sys

_spec = importlib.util.spec_from_file_location(
    "check_learning_trend",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts", "check_learning_trend.py"))
clt = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(clt)


def write_run(tmp_path, values, losses=None, name="fid512_uncal"):
    d = str(tmp_path / "run")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"metric-{name}.txt"), "w") as f:
        for i, v in enumerate(values):
            f.write(f"kimg {2.0 * (i + 1):<10.1f} {name} {v:.6f}\n")
    with open(os.path.join(d, "stats.jsonl"), "w") as f:
        for i, l in enumerate(losses or [1.0] * len(values)):
            f.write(json.dumps({"Progress/tick": i, "Loss/D": l,
                                "Loss/G": 0.5}) + "\n")
    return d

def test_decreasing_fid_passes(tmp_path):
    d = write_run(tmp_path, [320.0, 260.0, 210.0, 190.0])
    out = clt.check(d, None, min_points=3, min_drop=0.10)
    assert out["ok"], out
    assert out["metric"] == "fid512_uncal"
    assert out["points"] == 4 and out["fit_drop_rel"] > 0.3


def test_flat_fid_fails(tmp_path):
    d = write_run(tmp_path, [300.0, 298.0, 301.0, 299.0])
    out = clt.check(d, None, min_points=3, min_drop=0.10)
    assert not out["ok"] and "no learning evidence" in out["error"]


def test_noisy_last_tick_cannot_fake_trend(tmp_path):
    # rising overall; a lucky final dip must not pass the fitted check
    d = write_run(tmp_path, [200.0, 240.0, 280.0, 180.0])
    out = clt.check(d, None, min_points=3, min_drop=0.10)
    assert not out["ok"]


def test_too_few_points_fails(tmp_path):
    d = write_run(tmp_path, [300.0, 200.0])
    out = clt.check(d, None, min_points=3, min_drop=0.10)
    assert not out["ok"] and "metric points" in out["error"]


def test_nonfinite_loss_fails(tmp_path):
    d = write_run(tmp_path, [320.0, 260.0, 210.0],
                  losses=[1.0, float("nan"), 1.0])
    out = clt.check(d, None, min_points=3, min_drop=0.10)
    assert not out["ok"] and "non-finite" in out["error"]


def test_cli_exit_codes(tmp_path):
    import subprocess

    d = write_run(tmp_path, [320.0, 260.0, 210.0, 190.0])
    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "check_learning_trend.py")
    r = subprocess.run([sys.executable, script, d], capture_output=True,
                       text=True)
    assert r.returncode == 0 and json.loads(r.stdout)["ok"]


# --- harvest flag routing (VERDICT r5 weak #4 / item 7) ---------------------

def test_harvest_copies_flags_not_pseudo_metrics(tmp_path):
    """The harvester must copy real metric series + flag state files, and
    must NEVER copy a legacy metric-<flag>.txt pseudo-metric."""
    import importlib.util
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    scripts = os.path.join(root, "scripts")
    if scripts not in sys.path:       # harvest imports its sibling script
        sys.path.insert(0, scripts)
    spec = importlib.util.spec_from_file_location(
        "harvest_learning_run",
        os.path.join(scripts, "harvest_learning_run.py"))
    harvest = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(harvest)

    run = tmp_path / "run"
    out = tmp_path / "out"
    run.mkdir(), out.mkdir()
    (run / "stats.jsonl").write_text("{}\n")
    (run / "metric-fid512_uncal.txt").write_text(
        "kimg 2.0        fid512_uncal 100.0\n")
    (run / "metric-calibrated.txt").write_text(     # legacy pseudo-metric
        "kimg 2.0        calibrated 0.000000\n")
    (run / "flag-calibrated.txt").write_text("calibrated 0\n")

    copied = harvest.copy_artifacts(str(run), str(out))
    assert "metric-fid512_uncal.txt" in copied
    assert "flag-calibrated.txt" in copied
    assert "metric-calibrated.txt" not in copied
    assert sorted(os.listdir(out)) == [
        "flag-calibrated.txt", "metric-fid512_uncal.txt", "stats.jsonl"]


def test_committed_evidence_has_no_pseudo_metric_flags():
    """The committed r05 learning evidence carries the flag under its
    honest name (renamed this round); no metric-calibrated.txt remains."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ev = os.path.join(root, "docs", "learning_evidence_r05")
    names = os.listdir(ev)
    assert "metric-calibrated.txt" not in names
    assert "flag-calibrated.txt" in names


def test_write_flag_is_state_not_series(tmp_path):
    """write_flag overwrites in place — two writes leave ONE line — and
    RunLogger.flag routes through it without touching metric files."""
    from gansformer_tpu.utils.logging import RunLogger, write_flag

    write_flag(str(tmp_path), "calibrated", 0.0)
    write_flag(str(tmp_path), "calibrated", 1.0)
    assert open(tmp_path / "flag-calibrated.txt").read() == "calibrated 1\n"

    log = RunLogger(str(tmp_path / "run"))
    log.flag("calibrated", False)
    log.close()
    assert open(tmp_path / "run" / "flag-calibrated.txt").read() == \
        "calibrated 0\n"
    assert not any(n.startswith("metric-")
                   for n in os.listdir(tmp_path / "run"))
