"""The bench's self-validation is itself tested: these are the checks that
must reject the round-3 class of impossible throughput numbers
(VERDICT r3 weak #1) and accept honest ones."""

import pytest

from gansformer_tpu.utils.benchcheck import (
    cadence_weighted, find_suspects, mfu, peak_tflops)

# The REAL r3 artifact: v5e phase times (s) and the XLA-cost-analysis
# per-phase FLOPs the judge computed for the exact bench config.
R3_TIMINGS = {"d": 3.47e-3, "g": 3.88e-3, "d_r1": 3.69e-3, "g_pl": 5.76e-3}
R3_FLOPS = {"d": 2.013e12, "g": 2.118e12, "d_r1": 3.481e12, "g_pl": 3.748e12}


def test_peak_lookup_order():
    assert peak_tflops("TPU v5 lite") == 197.0
    assert peak_tflops("TPU v5e") == 197.0
    assert peak_tflops("TPU v5p") == 459.0
    assert peak_tflops("TPU v4") == 275.0
    assert peak_tflops("TPU v6 lite") == 918.0
    assert peak_tflops("cpu") is None


def test_cadence_weighting_matches_hand_calc():
    w = cadence_weighted(R3_TIMINGS, 16, 4)
    hand = (3.47e-3 * 15 / 16 + 3.69e-3 / 16
            + 3.88e-3 * 3 / 4 + 5.76e-3 / 4)
    assert w == pytest.approx(hand)


def test_r3_artifact_is_rejected():
    """The 1021.9 img/s/chip measurement MUST trip at least the MFU and
    the FLOPs-ratio checks — this is the exact failure the harness
    previously reported as a 5.1x win."""
    sus = find_suspects(R3_TIMINGS, R3_FLOPS, d_reg_interval=16,
                        g_reg_interval=4, peak=197.0,
                        device_kind="TPU v5 lite")
    assert any("mfu" in s and ">= 1.0" in s for s in sus), sus
    assert any("FLOPs ratio" in s for s in sus), sus
    # and the implied MFU really is ~3x peak
    m = mfu(cadence_weighted(R3_FLOPS, 16, 4),
            cadence_weighted(R3_TIMINGS, 16, 4), 197.0)
    assert 2.5 < m < 3.5


def test_honest_measurement_passes():
    """Times scaled to ~55% MFU with time/FLOPs ratios consistent: no
    objections."""
    peak = 197.0
    target_mfu = 0.55
    timings = {k: v / (peak * 1e12 * target_mfu) for k, v in R3_FLOPS.items()}
    sus = find_suspects(timings, R3_FLOPS, d_reg_interval=16,
                        g_reg_interval=4, peak=peak,
                        device_kind="TPU v5 lite", iters=20,
                        fetch_tails={k: 0.4 for k in timings},
                        linearity={"d": (timings["d"], timings["d"] * 1.05)})
    assert sus == []


def test_linearity_violation_flagged():
    timings = {"d": 0.1, "g": 0.1}
    # per-it time halves at 2N iters → acks, not execution
    sus = find_suspects(timings, {}, d_reg_interval=16, g_reg_interval=4,
                        linearity={"d": (0.1, 0.05)})
    assert any("linearity" in s for s in sus), sus


def test_sync_tail_flags_early_acks():
    timings = {"d": 0.005, "g": 0.005}   # 20 iters → 0.1 s loops
    sus = find_suspects(timings, {}, d_reg_interval=16, g_reg_interval=4,
                        iters=20, fetch_tails={"d": 8.0, "g": 0.2})
    assert len([s for s in sus if "sync tail" in s]) == 1, sus
    # a plain 1-RTT tail on a slow tunnel is NOT flagged
    sus2 = find_suspects({"d": 0.1, "g": 0.1}, {}, d_reg_interval=16,
                         g_reg_interval=4, iters=20,
                         fetch_tails={"d": 0.9, "g": 0.9})
    assert sus2 == []


def test_partial_phases_use_plain_approximation():
    # only (d, g): reg phases approximated by the plain ones
    w = cadence_weighted({"d": 2.0, "g": 3.0}, 16, 4)
    assert w == pytest.approx(5.0)


def test_flops_of_compiled_and_garbage():
    # The shared cost-analysis extractor (bench.py, bench_components.py,
    # and the loop's MFU bookkeeping all route through it).
    import jax
    import jax.numpy as jnp

    from gansformer_tpu.utils.benchcheck import flops_of

    compiled = jax.jit(lambda a, b: a @ b).lower(
        jnp.zeros((64, 64)), jnp.zeros((64, 64))).compile()
    # XLA:CPU reliably reports flops for a matmul (2*n^3); a None here
    # means the extractor itself regressed.
    assert flops_of(compiled) == pytest.approx(2 * 64**3, rel=0.5)

    class Garbage:
        def cost_analysis(self):
            raise RuntimeError("nope")

    assert flops_of(Garbage()) is None
    assert flops_of(object()) is None


# --- roofline classification (ISSUE 14 satellite) -----------------------

def test_roofline_classifies_memory_vs_compute_bound():
    from gansformer_tpu.utils.benchcheck import roofline

    # v5e-ish machine: 197 TFLOP/s, 819 GB/s → ridge ≈ 240.5 FLOP/byte.
    # A 4-tap depthwise blur (~0.1 FLOP/byte) is memory-bound; a dense
    # 512² matmul chain (~1000 FLOP/byte) is compute-bound.
    mem = roofline(flops=1e9, bytes_accessed=1e10,
                   peak_tflops_per_chip=197.0, hbm_gbps=819.0)
    assert mem["bound"] == "memory"
    assert mem["intensity_flops_per_byte"] == pytest.approx(0.1)
    assert mem["ridge_flops_per_byte"] == pytest.approx(240.54, rel=1e-3)
    comp = roofline(flops=1e12, bytes_accessed=1e9,
                    peak_tflops_per_chip=197.0, hbm_gbps=819.0)
    assert comp["bound"] == "compute"


def test_roofline_pct_of_binding_roof():
    from gansformer_tpu.utils.benchcheck import roofline

    # memory-bound op: roof = intensity * BW = 0.1 * 819e9 = 81.9 GFLOP/s
    # → 1 GFLOP takes 12.21 ms at the roof; measured 24.42 ms = 50%.
    r = roofline(flops=1e9, bytes_accessed=1e10,
                 peak_tflops_per_chip=197.0, hbm_gbps=819.0,
                 ms=2 * 1e9 / 81.9e9 * 1e3)
    assert r["pct_of_roof"] == pytest.approx(0.5, rel=1e-3)
    assert r["roof_ms"] == pytest.approx(1e9 / 81.9e9 * 1e3, rel=1e-3)
    # compute-bound op at exactly peak = 1.0
    r2 = roofline(flops=1e12, bytes_accessed=1e9,
                  peak_tflops_per_chip=197.0, hbm_gbps=819.0,
                  ms=1e12 / 197e12 * 1e3)
    assert r2["pct_of_roof"] == pytest.approx(1.0, rel=1e-3)


def test_roofline_degrades_to_empty_without_inputs():
    from gansformer_tpu.utils.benchcheck import (peak_hbm_gbps, roofline)

    assert roofline(None, 1e9, 197.0, 819.0) == {}
    assert roofline(1e9, None, 197.0, 819.0) == {}
    assert roofline(1e9, 1e9, None, 819.0) == {}
    assert roofline(1e9, 1e9, 197.0, None) == {}
    # the HBM lookup mirrors peak_tflops' substring discipline
    assert peak_hbm_gbps("TPU v5e chip") == 819.0
    assert peak_hbm_gbps("TPU v5p") == 2765.0
    assert peak_hbm_gbps("Quantum QPU") is None
