"""Integration test of the tick loop (SURVEY.md §3.1 parity): a short
training run must produce decreasing-ish finite losses, image grids,
stats.jsonl, and a resumable checkpoint."""

import glob
import json
import os

import numpy as np
import pytest

from tests.test_train import micro_cfg


@pytest.fixture(scope="module")
def run_dir(micro_run_dir):
    # the shared session-scoped training run (tests/conftest.py)
    return micro_run_dir


def test_loop_artifacts(run_dir):
    assert glob.glob(os.path.join(run_dir, "fakes*.png"))
    assert os.path.exists(os.path.join(run_dir, "log.txt"))
    stats_path = os.path.join(run_dir, "stats.jsonl")
    lines = [json.loads(l) for l in open(stats_path)]
    assert lines, "no ticks logged"
    last = lines[-1]
    assert last["Progress/kimg"] >= 1.0
    assert np.isfinite(last["Loss/G"]) and np.isfinite(last["Loss/D"])
    assert last["timing/img_per_sec_per_chip"] > 0


def test_loop_checkpoint_resumes(run_dir):
    import jax

    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.train.state import create_train_state

    ck = os.path.join(run_dir, "checkpoints")
    step = ckpt.latest_step(ck)
    assert step is not None and step >= 1000
    cfg = micro_cfg(attention="simplex", batch=8)
    template = create_train_state(cfg, jax.random.PRNGKey(0))
    restored = ckpt.restore(ck, template)
    assert int(np.asarray(restored.step)) == step
    # config was dumped alongside
    assert os.path.exists(os.path.join(ck, "config.json"))
