"""Integration test of the tick loop (SURVEY.md §3.1 parity): a short
training run must produce decreasing-ish finite losses, image grids,
stats.jsonl, and a resumable checkpoint."""

import glob
import json
import os

import numpy as np
import pytest

from tests.test_train import micro_cfg


@pytest.fixture(scope="module")
def run_dir(micro_run_dir):
    # the shared session-scoped training run (tests/conftest.py)
    return micro_run_dir


def test_loop_artifacts(run_dir):
    assert glob.glob(os.path.join(run_dir, "fakes*.png"))
    assert os.path.exists(os.path.join(run_dir, "log.txt"))
    stats_path = os.path.join(run_dir, "stats.jsonl")
    lines = [json.loads(l) for l in open(stats_path)]
    assert lines, "no ticks logged"
    last = lines[-1]
    assert last["Progress/kimg"] >= 1.0
    assert np.isfinite(last["Loss/G"]) and np.isfinite(last["Loss/D"])
    assert last["timing/img_per_sec_per_chip"] > 0


def test_loop_checkpoint_resumes(run_dir):
    import jax

    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.train.state import create_train_state

    ck = os.path.join(run_dir, "checkpoints")
    step = ckpt.latest_step(ck)
    assert step is not None and step >= 1000
    cfg = micro_cfg(attention="simplex", batch=8)
    template = create_train_state(cfg, jax.random.PRNGKey(0))
    restored = ckpt.restore(ck, template)
    assert int(np.asarray(restored.step)) == step
    # config was dumped alongside
    assert os.path.exists(os.path.join(ck, "config.json"))


@pytest.mark.slow  # a full extra training run (~minutes on virtual-CPU mesh)
def test_loop_fused_cycle_tick(tmp_path, monkeypatch):
    """train() with TrainConfig.fused_cycle: one dispatch per lazy-reg
    cycle must still produce ticks, correctly-averaged stats (device-side
    counts), snapshots, a checkpoint — and per-tick MFU (VERDICT r4
    weak #3: the flagship mode must self-report its physics; the env hook
    supplies the synthetic CPU 'peak' the TPU gate otherwise reads from
    the device table)."""
    import dataclasses

    import jax

    from gansformer_tpu.train.loop import train

    monkeypatch.setenv("GANSFORMER_TPU_FORCE_MFU", "1.0")
    cfg = micro_cfg(attention="simplex", batch=8)
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, total_kimg=1, kimg_per_tick=1, snapshot_ticks=1,
        image_snapshot_ticks=1, fused_cycle=True))
    d = str(tmp_path / "run")
    os.makedirs(d)
    state = train(cfg, d)
    assert int(jax.device_get(state.step)) >= 1000
    lines = [json.loads(l) for l in open(os.path.join(d, "stats.jsonl"))]
    assert lines
    last = lines[-1]
    # tick-averaged means, not sums: a GAN loss mean is O(1), a 63-iter
    # sum would be O(50) — this catches count mishandling outright
    assert 0 < abs(last["Loss/D"]) < 20 and 0 < abs(last["Loss/G"]) < 20
    assert np.isfinite(last["Loss/D/r1"]) and np.isfinite(last["Loss/G/pl"])
    assert glob.glob(os.path.join(d, "fakes*.png"))
    assert os.path.isdir(os.path.join(d, "checkpoints"))
    # the log records the fused dispatch mode
    assert "fused cycle" in open(os.path.join(d, "log.txt")).read()
    # MFU bookkeeping must survive the fused dispatch mode: cost analysis
    # comes from the four phase lowerings, not the cycle program (whose
    # scan bodies count once, not × trip count).
    assert "timing/mfu" in last and np.isfinite(last["timing/mfu"]) \
        and last["timing/mfu"] > 0


@pytest.mark.slow  # two back-to-back training runs
def test_loop_fused_cycle_resume_realigns(tmp_path):
    """Resuming a fused-cycle run at an iteration index that is NOT a
    cycle boundary (1 kimg / batch 8 = 125 iters, 125 % 2 != 0) must fall
    back to single-step dispatch until aligned, then continue fused —
    and actually finish the second kimg."""
    import dataclasses

    import jax

    from gansformer_tpu.train.loop import train

    # first segment UNFUSED: 125 iterations → a cycle-misaligned resume
    # point (a fused segment always stops on a cycle boundary)
    cfg = micro_cfg(attention="simplex", batch=8)
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, total_kimg=1, kimg_per_tick=1, snapshot_ticks=1,
        image_snapshot_ticks=0, fused_cycle=False))
    d = str(tmp_path / "run")
    os.makedirs(d)
    state = train(cfg, d)
    first = int(jax.device_get(state.step))
    assert first >= 1000 and (first // 8) % 2 != 0, \
        f"precondition: resume point must be cycle-misaligned, got {first}"

    cfg2 = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, total_kimg=2, kimg_per_tick=1, snapshot_ticks=1,
        image_snapshot_ticks=0, fused_cycle=True))
    state2 = train(cfg2, d, resume=True)
    assert int(jax.device_get(state2.step)) >= 2000
    lines = [json.loads(l) for l in open(os.path.join(d, "stats.jsonl"))]
    assert lines[-1]["Progress/kimg"] >= 2.0
    assert np.isfinite(lines[-1]["Loss/G"])
