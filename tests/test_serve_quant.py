"""Quantized synthesis tests (ISSUE 20): the ``serve_precision`` axis.

The load-bearing contracts, each pinned here:

* the quantization predicate hits exactly the equalized-LR kernels
  (``"w"`` leaves with ndim 2/4); biases, tables, const, gates and
  ``noise_strength`` stay fp32;
* per-output-channel dequantization reconstructs every weight within
  half a quantization step — and two quantize passes over the same
  checkpoint agree bit-for-bit (the replica-determinism precondition);
* the int8w synth executable's PARAMETER bytes per image are >= 3x
  lower than f32's (the weight-only headline) and its output stays
  inside the declared fidelity tolerance against the f32 reference;
* the warm-start fingerprint separates precisions and device ordinals
  — an int8w manifest entry can never warm-start a f32 service and
  replica 3's executables can never warm-start replica 0 — while int8w
  executables themselves round-trip through the manifest.
"""

import numpy as np
import pytest


def _tiny_bundle():
    from gansformer_tpu.analysis.trace.entry_points import tiny_config
    from gansformer_tpu.serve import init_generator

    return init_generator(tiny_config("float32"))


@pytest.fixture(scope="module")
def bundle():
    return _tiny_bundle()


# -- quantization scheme -----------------------------------------------------

def test_quantize_predicate_hits_only_kernels(bundle):
    """Every ``"w"`` (ndim 2/4) becomes a QuantizedWeight; every other
    leaf survives untouched at its original dtype."""
    import jax

    from gansformer_tpu.ops import QuantizedWeight
    from gansformer_tpu.serve import quantize_params

    qtree = quantize_params(bundle.ema_params)

    def name_of(path):
        last = path[-1]
        return str(getattr(last, "key", getattr(last, "name", last)))

    flat_q = jax.tree_util.tree_leaves_with_path(
        qtree, is_leaf=lambda x: isinstance(x, QuantizedWeight))
    n_quant = 0
    for path, leaf in flat_q:
        if isinstance(leaf, QuantizedWeight):
            n_quant += 1
            assert name_of(path) == "w"
            assert leaf.q.dtype == np.int8
            assert leaf.scale.dtype == np.float32
            # per-output-channel over the LAST axis, keepdims
            assert leaf.scale.shape == \
                (1,) * (leaf.q.ndim - 1) + (leaf.q.shape[-1],)
        else:
            assert name_of(path) != "w" or leaf.ndim not in (2, 4)
    assert n_quant > 0, "no kernel was quantized — predicate rotted"


def test_dequant_roundtrip_within_half_step_and_deterministic(bundle):
    """|w - q*scale| <= scale/2 per element (rounding only), and two
    quantize passes agree bit-for-bit."""
    import jax

    from gansformer_tpu.ops import QuantizedWeight
    from gansformer_tpu.serve import quantize_params

    q1 = quantize_params(bundle.ema_params)
    q2 = quantize_params(bundle.ema_params)
    orig = jax.tree_util.tree_leaves(bundle.ema_params)
    l1 = jax.tree_util.tree_leaves(
        q1, is_leaf=lambda x: isinstance(x, QuantizedWeight))
    l2 = jax.tree_util.tree_leaves(
        q2, is_leaf=lambda x: isinstance(x, QuantizedWeight))
    checked = 0
    for w, a, b in zip(orig, l1, l2):
        if not isinstance(a, QuantizedWeight):
            continue
        checked += 1
        assert (np.asarray(a.q) == np.asarray(b.q)).all()
        assert (np.asarray(a.scale) == np.asarray(b.scale)).all()
        deq = np.asarray(a.q, np.float32) * np.asarray(a.scale)
        err = np.abs(np.asarray(w, np.float32) - deq)
        # clipping at ±127 only triggers for |w| > amax — impossible by
        # construction, so rounding is the whole error budget
        assert (err <= np.asarray(a.scale) * 0.5 + 1e-7).all()
    assert checked > 0


# -- A/B: cost + fidelity ----------------------------------------------------

@pytest.fixture(scope="module")
def cost(bundle):
    from gansformer_tpu.serve import cost_report

    return cost_report(bundle, bucket=2)


def test_int8w_param_bytes_at_least_3x_lower(cost):
    """The acceptance headline: int8w's per-image parameter bytes (and
    the host params tree) are >= 3x smaller than f32's.  4x is the
    ideal; per-channel fp32 scales and the unquantized fp32 leaves
    (biases, tables, const) eat part of it."""
    rec = cost["per_precision"]["int8w"]
    assert rec["param_bytes_ratio_vs_f32"] is not None
    assert rec["param_bytes_ratio_vs_f32"] >= 3.0
    assert rec["tree_bytes_ratio_vs_f32"] >= 3.0
    # sanity: bf16 weights stay fp32 (weight-only means int8w is the
    # only precision that touches parameter bytes)
    bf = cost["per_precision"]["bf16"]
    assert bf["params_tree_bytes"] == \
        cost["per_precision"]["f32"]["params_tree_bytes"]


def test_fidelity_within_declared_tolerance(bundle):
    from gansformer_tpu.serve import FIDELITY_TOLERANCES, fidelity_report

    for prec in ("bf16", "int8w"):
        rep = fidelity_report(bundle, prec, bucket=2)
        assert rep["ok"], (
            f"{prec} rel_err {rep['rel_err']:.4f} exceeds declared "
            f"tolerance {FIDELITY_TOLERANCES[prec]}")
        # the A/B must be non-trivial: a zero error would mean the
        # precision axis is not actually wired into the synth program
        assert rep["rel_err"] > 0.0


# -- warm-start fingerprinting ----------------------------------------------

def test_fingerprint_separates_precision_and_ordinal(bundle):
    import dataclasses
    import json

    from gansformer_tpu.serve.warmstart import fingerprint

    cfg = json.dumps(dataclasses.asdict(bundle.cfg.model), sort_keys=True)
    base = fingerprint(cfg, "synthesize", 2)
    assert fingerprint(cfg, "synthesize", 2) == base
    assert fingerprint(cfg, "synthesize", 2,
                       serve_precision="int8w") != base
    assert fingerprint(cfg, "synthesize", 2,
                       serve_precision="bf16") != base
    assert fingerprint(cfg, "synthesize", 2, device_ordinal=3) != base
    assert fingerprint(cfg, "synthesize", 2, serve_precision="int8w",
                       device_ordinal=3) != \
        fingerprint(cfg, "synthesize", 2, serve_precision="int8w")


def test_int8w_warm_start_roundtrip_no_cross_precision_hit(bundle,
                                                           tmp_path):
    """int8w executables (quantized-params signature and all) ride the
    manifest: a second int8w process compiles ZERO programs, while a
    f32 process against the SAME manifest dir gets no warm hits."""
    from gansformer_tpu.serve import ServePrograms

    mdir = str(tmp_path / "manifest")
    first = ServePrograms(bundle, buckets=(1,), manifest_dir=mdir,
                          serve_precision="int8w").warm_start()
    assert first["compiled"] == 2 and first["loaded"] == 0   # map+synth
    second = ServePrograms(bundle, buckets=(1,), manifest_dir=mdir,
                           serve_precision="int8w").warm_start()
    assert second["compiled"] == 0 and second["loaded"] == 2
    f32 = ServePrograms(bundle, buckets=(1,), manifest_dir=mdir,
                        serve_precision="f32").warm_start()
    # precision is a SYNTH-only axis: the mapping program is identical
    # (always f32) so its executable legitimately warm-starts across
    # precisions — but the int8w SYNTH entry must never hit
    assert f32["loaded"] == 1 and f32["compiled"] == 1, \
        "a f32 synth program warm-started from an int8w executable"
