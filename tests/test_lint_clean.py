"""Tier-1 gate: the whole repo lints clean under graftlint (ISSUE 3).

Runs the full rule set over ``gansformer_tpu/`` and ``scripts/`` with
the checked-in baseline — any NEW finding (not inline-suppressed, not
baselined) fails the suite, which is what makes the rules enforceable
rather than advisory.  Also pins the migration contract: the script
shims keep their legacy module APIs, every shimmed script imports
without side effects, and the console entry point is registered."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(ROOT, "graftlint-baseline.json")
LINT_PATHS = [os.path.join(ROOT, "gansformer_tpu"),
              os.path.join(ROOT, "scripts")]


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- the gate ---------------------------------------------------------------

def test_whole_repo_zero_new_findings():
    from gansformer_tpu.analysis import lint_paths
    from gansformer_tpu.analysis.baseline import Baseline, line_text_lookup

    findings = lint_paths(LINT_PATHS)
    Baseline.load(BASELINE).apply(findings, line_text_lookup())
    new = [f for f in findings if f.new]
    assert new == [], "new graftlint findings — fix, suppress with a " \
        "justification comment, or run gansformer-lint --fix-baseline:\n" \
        + "\n".join(f"{f.location}: {f.rule}: {f.message}" for f in new)


def test_baseline_file_is_deterministic_and_relative():
    with open(BASELINE) as f:
        data = json.load(f)
    assert data["version"] == 1
    entries = data["entries"]
    assert entries == sorted(
        entries, key=lambda e: (e["path"], e["rule"], e["line"], e["key"]))
    assert all(not os.path.isabs(e["path"]) for e in entries)


# --- migration contract: shims keep working ---------------------------------

def test_check_hot_loop_shim_api():
    chl = _load_script("check_hot_loop")
    result = chl.check_file(chl._DEFAULT_TARGET)
    assert result["ok"], result["violations"]
    assert result["checked"] >= 1
    bad = ("def _train(x):\n"
           "    while x:\n"
           "        jax.device_get(x)\n")
    res = chl.check_source(bad)
    assert not res["ok"] and res["violations"][0]["call"] == "device_get"


def test_check_telemetry_shim_api(tmp_path):
    ctl = _load_script("check_telemetry")
    result = ctl.check_run_dir(str(tmp_path))   # empty dir: all missing
    assert not result["ok"] and result["errors"]
    assert callable(ctl.check_events) and callable(ctl.check_prom)
    assert callable(ctl.check_heartbeat)


@pytest.mark.parametrize("name", ["check_hot_loop", "check_telemetry",
                                  "check_learning_trend"])
def test_shimmed_scripts_import_without_side_effects(name):
    # importing must not parse argv or exit — ISSUE 3 satellite
    mod = _load_script(name)
    assert callable(mod.main)


def test_script_entrypoints_still_run(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "check_hot_loop.py")],
        capture_output=True, text=True, env=env, cwd=str(tmp_path))
    assert out.returncode == 0, out.stdout + out.stderr
    assert json.loads(out.stdout.strip())["ok"]


def test_console_script_registered():
    with open(os.path.join(ROOT, "pyproject.toml")) as f:
        content = f.read()
    assert 'gansformer-lint = "gansformer_tpu.analysis.cli:main"' in content


def test_row_blocked_kernel_modules_lint_clean_without_baseline():
    """The halo row-blocked kernel family (ISSUE 17) must stay clean
    the strong way: zero raw findings over the two kernel modules —
    nothing baselined, nothing suppressed — and the shared baseline
    must carry no entries under them, so a future edit can't quietly
    grandfather a finding into the hottest code in the repo."""
    from gansformer_tpu.analysis import lint_paths

    kernel_paths = [
        os.path.join(ROOT, "gansformer_tpu", "ops", "pallas_modconv.py"),
        os.path.join(ROOT, "gansformer_tpu", "ops", "pallas_upfirdn.py"),
    ]
    findings = lint_paths(kernel_paths)
    # No baseline applied on purpose: every finding counts as new here.
    assert findings == [], "row-blocked kernel modules must lint clean " \
        "with NO baseline entries and NO suppressions:\n" + "\n".join(
            f"{f.location}: {f.rule}: {f.message}" for f in findings)

    with open(BASELINE) as f:
        entries = json.load(f)["entries"]
    kernel_rel = {os.path.relpath(p, ROOT) for p in kernel_paths}
    leaked = [e for e in entries
              if e["path"].replace("\\", "/") in kernel_rel]
    assert leaked == [], f"baseline entries leaked under the kernel " \
        f"modules: {leaked}"

    # And zero inline suppressions at all — the kernels carry none
    # today, and the justification escape hatch (the audit below) is
    # deliberately not available to this pair.
    for path in kernel_paths:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        assert "graftlint: disable" not in src, (
            f"{path}: inline suppression in a row-blocked kernel "
            f"module — fix the finding instead")


def test_suppressions_carry_justifications():
    """Every inline suppression in the production tree must carry a
    justification: prose after the rule id, or a comment on the line
    above (the ISSUE 3 'intentionally kept' contract)."""
    import re

    pat = re.compile(r"#\s*graftlint:\s*disable=[A-Za-z0-9_,\s-]+(.*)")
    for base in LINT_PATHS:
        for dirpath, dirnames, files in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
                for i, line in enumerate(lines):
                    m = pat.search(line)
                    if not m:
                        continue
                    justified = bool(m.group(1).strip()) or (
                        i > 0 and lines[i - 1].strip().startswith("#"))
                    assert justified, (
                        f"{path}:{i + 1}: suppression without a "
                        f"justification comment")
