"""Pure-numpy oracle implementations of the native ops.

These play the role of the reference's inline `'ref'` implementations
(`impl='ref'` switch in `src/dnnlib/tflib/ops/*.py`, SURVEY.md §4 item 2):
slow, obviously-correct math that the fast XLA paths must match bit-for-bit
(to fp32 tolerance).
"""

import numpy as np


def upfirdn2d_ref(x, f, up=1, down=1, pad=(0, 0, 0, 0)):
    """x: [N,H,W,C] fp64/fp32, f: [fh,fw]. pad = (pady0,pady1,padx0,padx1)."""
    n, h, w, c = x.shape
    fh, fw = f.shape
    pady0, pady1, padx0, padx1 = pad
    # 1. zero-insert upsample (zeros after every sample, incl. the last)
    z = np.zeros((n, h * up, w * up, c), dtype=x.dtype)
    z[:, ::up, ::up, :] = x
    # 2. pad (negative = crop)
    z = np.pad(z, ((0, 0),
                   (max(pady0, 0), max(pady1, 0)),
                   (max(padx0, 0), max(padx1, 0)),
                   (0, 0)))
    z = z[:,
          max(-pady0, 0): z.shape[1] - max(-pady1, 0),
          max(-padx0, 0): z.shape[2] - max(-padx1, 0), :]
    # 3. true convolution with f (flip + correlate)
    ff = f[::-1, ::-1]
    oh, ow = z.shape[1] - fh + 1, z.shape[2] - fw + 1
    out = np.zeros((n, oh, ow, c), dtype=np.float64)
    for i in range(oh):
        for j in range(ow):
            out[:, i, j, :] = np.einsum(
                "nhwc,hw->nc", z[:, i:i + fh, j:j + fw, :], ff)
    # 4. keep every down-th sample
    return out[:, ::down, ::down, :]


def setup_filter_ref(f, gain=1.0):
    f = np.asarray(f, dtype=np.float64)
    if f.ndim == 1:
        f = np.outer(f, f)
    return f / f.sum() * gain


def fused_bias_act_ref(x, b=None, act="linear", alpha=0.2, gain=None, clamp=None):
    x = np.asarray(x, dtype=np.float64)
    if b is not None:
        x = x + b.reshape((1,) * (x.ndim - 1) + (-1,))
    acts = {
        "linear": (lambda v: v, 1.0),
        "relu": (lambda v: np.maximum(v, 0), np.sqrt(2)),
        "lrelu": (lambda v: np.where(v >= 0, v, v * alpha), np.sqrt(2)),
        "tanh": (np.tanh, 1.0),
        "sigmoid": (lambda v: 1 / (1 + np.exp(-v)), 1.0),
    }
    fn, def_gain = acts[act]
    y = fn(x) * (def_gain if gain is None else gain)
    if clamp is not None:
        y = np.clip(y, -clamp, clamp)
    return y


def modulated_conv2d_ref(x, w, styles, demodulate=True, eps=1e-8):
    """Direct per-sample weight modulation (the definition, not the trick).

    x: [N,H,W,Ci], w: [kh,kw,Ci,Co], styles: [N,Ci].  SAME padding, stride 1.
    """
    n, h, w_sz, ci = x.shape
    kh, kw, _, co = w.shape
    out = np.zeros((n, h, w_sz, co), dtype=np.float64)
    ph, pw = kh // 2, kw // 2
    xp = np.pad(x, ((0, 0), (ph, kh - 1 - ph), (pw, kw - 1 - pw), (0, 0)))
    for s in range(n):
        ws = w * styles[s][None, None, :, None]          # modulate
        if demodulate:
            d = 1.0 / np.sqrt(np.sum(ws**2, axis=(0, 1, 2)) + eps)
            ws = ws * d[None, None, None, :]             # demodulate
        for i in range(h):
            for j in range(w_sz):
                patch = xp[s, i:i + kh, j:j + kw, :]
                out[s, i, j, :] = np.einsum("hwi,hwio->o", patch, ws)
    return out


def attention_ref(q, k, v, num_heads=1):
    n, lq, d = q.shape
    _, lk, dv = v.shape
    dh = d // num_heads
    out = np.zeros((n, lq, dv), dtype=np.float64)
    for s in range(n):
        for hd in range(num_heads):
            qs = q[s, :, hd * dh:(hd + 1) * dh]
            ks = k[s, :, hd * dh:(hd + 1) * dh]
            vs = v[s][:, hd * (dv // num_heads):(hd + 1) * (dv // num_heads)]
            logits = qs @ ks.T / np.sqrt(dh)
            e = np.exp(logits - logits.max(axis=-1, keepdims=True))
            p = e / e.sum(axis=-1, keepdims=True)
            out[s, :, hd * (dv // num_heads):(hd + 1) * (dv // num_heads)] = p @ vs
    return out
