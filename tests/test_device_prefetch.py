"""Device-resident input prefetch + overlap-layer loop integration
(ISSUE 2): DevicePrefetcher unit behavior, the layered close protocol,
the check_hot_loop static lint, and the acceptance properties — with
overlap enabled the loop-thread h2d/checkpoint spans collapse, while the
rng/loss/checkpoint trajectory stays IDENTICAL to the synchronous path."""

import dataclasses
import glob
import importlib.util
import json
import os
import threading

import jax
import numpy as np
import pytest

from gansformer_tpu.data.dataset import PrefetchIterator
from tests.tolerances import SCALAR_REPLAY_ABS
from gansformer_tpu.data.device_prefetch import DevicePrefetcher

_spec = importlib.util.spec_from_file_location(
    "check_hot_loop",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts", "check_hot_loop.py"))
chl = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chl)


def _put(tagged):
    kind, d = tagged
    return kind, {k: jax.device_put(v) for k, v in d.items()}


# --- DevicePrefetcher units -------------------------------------------------

def test_device_prefetcher_preserves_order_and_lands_on_device():
    items = [("single", {"i": np.full((2,), i, np.int32)}) for i in range(9)]
    dp = DevicePrefetcher(iter(items), _put, depth=2)
    got = []
    for kind, d in dp:
        assert kind == "single"
        assert isinstance(d["i"], jax.Array)       # already device-resident
        got.append(int(np.asarray(d["i"])[0]))
    assert got == list(range(9))
    with pytest.raises(StopIteration):
        dp.get()
    dp.close()
    dp.close()                                      # idempotent
    assert not dp._thread.is_alive()


def test_device_prefetcher_propagates_transfer_error():
    def bad():
        yield ("single", {"x": np.zeros(2, np.float32)})
        raise RuntimeError("h2d boom")

    dp = DevicePrefetcher(bad(), _put, depth=2)
    kind, _ = dp.get()
    assert kind == "single"
    with pytest.raises(RuntimeError, match="h2d boom"):
        dp.get()
    dp.close()


def test_device_prefetcher_telemetry_counts():
    from gansformer_tpu.obs import registry as telemetry

    reg = telemetry.get_registry()
    before = reg.counter("data/device_batches_total").value
    h_before = reg.histogram("data/h2d_ms").count
    items = [("single", {"i": np.zeros(3, np.float32)}) for _ in range(5)]
    with DevicePrefetcher(iter(items), _put, depth=2) as dp:
        n = sum(1 for _ in dp)
    assert n == 5
    assert reg.counter("data/device_batches_total").value == before + 5
    assert reg.histogram("data/h2d_ms").count >= h_before + 5


def test_layered_close_unblocks_transfer_thread():
    """The loop's teardown order: closing the host PrefetchIterator must
    wake a DevicePrefetcher thread blocked on the empty host queue, so
    the subsequent DevicePrefetcher.close() joins promptly."""
    def slow_infinite():
        i = 0
        while True:
            yield ("single", {"i": np.full((1,), i, np.int32)})
            i += 1

    host = PrefetchIterator(slow_infinite(), depth=2)
    dp = DevicePrefetcher(iter(host), _put, depth=2)
    dp.get()                           # pipeline is live
    host.close()                       # parks the wake-up sentinel
    dp.close()
    assert not dp._thread.is_alive()
    assert not host._thread.is_alive()


def test_prefetch_iterator_close_is_idempotent_and_wakes_consumers():
    src = ({"i": i} for i in iter(int, 1))      # infinite
    it = PrefetchIterator(src, depth=2)
    next(it)
    done = threading.Event()

    def consumer():
        try:
            while True:
                next(it)
        except StopIteration:
            done.set()

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    it.close()
    it.close()
    assert done.wait(5.0), "blocked consumer was not woken by close()"
    assert not it._thread.is_alive()


# --- check_hot_loop static lint ---------------------------------------------

def test_check_hot_loop_passes_on_real_loop():
    result = chl.check_file(chl._DEFAULT_TARGET)
    assert result["ok"], result["violations"]
    assert result["checked"] >= 1


def test_check_hot_loop_catches_violations():
    bad = """
def _train(x):
    while x < 10:
        jax.block_until_ready(x)
        y = jax.device_get(x)
        with span("tick_fetch"):
            z = jax.device_get(x)      # sanctioned
        x += 1
"""
    res = chl.check_source(bad)
    assert not res["ok"]
    assert sorted(v["call"] for v in res["violations"]) == \
        ["block_until_ready", "device_get"]
    ok = """
def _train(x):
    while x < 10:
        with span("tick_fetch"):
            jax.block_until_ready(x)
            v = float(jax.device_get(x))
        x += 1
"""
    assert chl.check_source(ok)["ok"]
    # a loop.py without the expected shape must fail loudly, not pass
    assert chl.check_source("def other(): pass")["checked"] == 0


# --- loop integration: overlap vs sync --------------------------------------
#
# The OVERLAP member of the pair is the shared session micro run
# (tests/conftest.py) — it trains with the default flags, i.e. device
# prefetch + async writeback ON, for 3 ticks.  Only the synchronous
# parity reference is trained here, and only for ONE tick (tier-1 time
# budget): the comparisons use the common tick prefix — with the same
# seed the trajectories are independent of total_kimg, which only
# decides when training stops.

def _sync_cfg(total_kimg=1):
    from tests.conftest import micro_overlap_cfg

    cfg = micro_overlap_cfg(total_kimg=total_kimg)
    return dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, async_checkpoint=False),
        data=dataclasses.replace(cfg.data, device_prefetch=False))


@pytest.fixture(scope="module")
def sync_run_dir(tmp_path_factory):
    from gansformer_tpu.train.loop import train

    d = str(tmp_path_factory.mktemp("sync_run"))
    train(_sync_cfg(), d)
    return d


def _ticks(run_dir):
    lines = [json.loads(l)
             for l in open(os.path.join(run_dir, "stats.jsonl"))]
    return [r for r in lines if "timing/sec_per_tick" in r]


def test_overlap_collapses_h2d_span(micro_run_dir, sync_run_dir):
    """Acceptance: with overlap enabled (≥3 ticks), per-tick loop-thread
    h2d self-time < 10% of its sync-mode value.  The overlap side uses
    steady-state ticks (the first pays compiles); the sync reference's
    single tick is usable as-is — its h2d span is pure device_put work
    (compiles land in the step span), measured in the same 200–370 ms
    band as steady sync ticks."""
    over = _ticks(micro_run_dir)
    sync = _ticks(sync_run_dir)
    assert len(over) >= 3 and len(sync) >= 1
    s = np.mean([r["timing/phase/h2d"] for r in sync])
    o = np.mean([r["timing/phase/h2d"] for r in over[1:]])
    assert s > 0
    assert o < 0.10 * s, (o, s)


def test_overlap_checkpoint_span_is_dispatch_only(
        micro_run_dir, sync_run_dir):
    """Acceptance: the loop-thread checkpoint cost must not include the
    serialize/fsync work (that rides the writer thread).  Asserted on
    span COMPOSITION, not a wall-clock race: at the ~1 MB micro scale a
    sync fsync is occasionally as fast as async staging (the seed's
    known flake), so instead of comparing durations we assert the async
    run actually routed its in-loop saves through the writer thread and
    the sync run never did.  The wall-clock size-independence property —
    the actual O(dispatch) claim — is pinned with a 64 MB state in
    tests/test_checkpoint_async.py::
    test_async_save_loop_cost_is_dispatch_bound."""
    from gansformer_tpu.obs.registry import parse_prom_values

    o = parse_prom_values(os.path.join(micro_run_dir, "telemetry.prom"))
    s = parse_prom_values(os.path.join(sync_run_dir, "telemetry.prom"))
    # Async run: the in-loop saves were SUBMITTED to the writer thread
    # (ckpt_async_total), completed off-loop (the write_ms histogram
    # landed observations; ≤ submissions because the prom snapshot may
    # precede the last drain), and none errored.  The loop thread still
    # records its own (dispatch-only) ckpt_write_ms.
    assert o.get("ckpt_async_total", 0.0) >= 1.0, o
    assert o.get("ckpt_async_write_ms_count", 0.0) >= 1.0, o
    assert o["ckpt_async_total"] >= o["ckpt_async_write_ms_count"], o
    assert o.get("ckpt_async_errors_total", 0.0) == 0.0, o
    assert o.get("ckpt_write_ms", 0.0) > 0.0, o
    # Sync run: no ckpt_async_* family at all — every save (serialize +
    # fsync) executed on the loop thread.
    assert not any(k.startswith("ckpt_async_") for k in s), sorted(
        k for k in s if k.startswith("ckpt_async_"))
    assert s.get("ckpt_write_ms", 0.0) > 0.0, s


def test_overlap_device_queue_telemetry(micro_run_dir, sync_run_dir):
    last = _ticks(micro_run_dir)[-1]
    gauges = last["telemetry"]["gauges"]
    hists = last["telemetry"]["histograms"]
    assert "data/device_queue_depth" in gauges
    assert hists["data/h2d_ms"]["count"] > 0
    assert "ckpt/async_writer_heartbeat" in gauges
    # sync mode must NOT have spun up the device ring or the writers
    sync_gauges = _ticks(sync_run_dir)[-1]["telemetry"]["gauges"]
    assert "data/device_queue_depth" not in sync_gauges
    assert "ckpt/async_inflight" not in sync_gauges


def test_overlap_parity_losses_and_checkpoint(micro_run_dir, sync_run_dir):
    """Acceptance: with overlap off vs on (same seed), the rng stream /
    loss curves / checkpoint contents / image grids are identical at fp
    noise — the overlap layer moves work, it must not change math."""
    over, sync = _ticks(micro_run_dir), _ticks(sync_run_dir)
    common = min(len(over), len(sync))
    assert common >= 1
    for rs, ro in zip(sync[:common], over[:common]):
        keys = [k for k in rs if k.startswith("Loss/")]
        assert keys
        for k in keys:
            assert ro[k] == pytest.approx(
                rs[k], abs=SCALAR_REPLAY_ABS), (k, rs[k], ro[k])

    # checkpoint contents at the last COMMON step, serialized leaves
    def leaves(run_dir, step):
        from gansformer_tpu.train.checkpoint import STATE_FILE

        p = os.path.join(run_dir, "checkpoints", str(step), STATE_FILE)
        with np.load(p, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}

    from gansformer_tpu.train.checkpoint import latest_step

    step = latest_step(os.path.join(sync_run_dir, "checkpoints"))
    s_leaves = leaves(sync_run_dir, step)
    o_leaves = leaves(micro_run_dir, step)
    assert set(s_leaves) == set(o_leaves)
    for k in s_leaves:
        assert np.array_equal(s_leaves[k], o_leaves[k]), k

    # image grids rode the async writer — bytes must match the sync ones
    pngs = sorted(glob.glob(os.path.join(sync_run_dir, "fakes*.png")))
    assert pngs
    for p in pngs:
        q = os.path.join(micro_run_dir, os.path.basename(p))
        assert os.path.exists(q)
        assert open(p, "rb").read() == open(q, "rb").read(), p
