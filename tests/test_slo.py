"""SLO error-budget tests (obs/slo, ISSUE 16): budget/burn-rate math
over synthetic ledgers, the rolling window, the lifetime-counter prom
fallback (and what it declaredly cannot see), the no-data floor, the
``slo`` CLI exit code, and the doctor's slo section — FAIL on an
exhausted budget, informational PASS when a chaos drill spent it on
purpose."""

import json
import os

import pytest

from gansformer_tpu.cli.telemetry import main as cli_main, run_doctor
from gansformer_tpu.obs.slo import (
    DEFAULT_OBJECTIVES, evaluate_slos, render_slos)

NOW = 1_000_000.0


def write_ledger(d, rows):
    with open(os.path.join(d, "requests.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def row(rid, outcome, e2e_ms=10.0, t_wall=NOW - 10.0, cause=None):
    return {"rid": rid, "outcome": outcome, "cause": cause,
            "e2e_ms": e2e_ms, "t_wall": t_wall,
            "events": [{"kind": "submitted", "t_ms": 0.0},
                       {"kind": outcome, "t_ms": e2e_ms}]}


def by_name(report):
    return {o["name"]: o for o in report["objectives"]}


# --- ledger math ------------------------------------------------------------

def test_budget_and_burn_rate_math(tmp_path):
    d = str(tmp_path)
    rows = [row(f"r1-{i}", "fulfilled") for i in range(995)]
    rows += [row(f"r1-e{i}", "expired", cause="deadline")
             for i in range(5)]
    write_ledger(d, rows)
    report = evaluate_slos(d, window_s=3600.0, now=NOW)
    assert report["source"] == "ledger" and report["rows"] == 1000
    av = by_name(report)["availability"]
    # target 0.999 over 1000 admitted → budget 1.0; 5 bad spends 5x
    assert av["total"] == 1000 and av["bad"] == 5
    assert av["budget_total"] == pytest.approx(1.0)
    assert av["budget_spent"] == 5.0
    assert av["budget_remaining"] == 0.0
    assert av["burn_rate"] == pytest.approx(5.0)
    assert av["exhausted"] and av["status"] == "exhausted"
    assert report["exhausted"] == ["availability"]
    assert report["worst_burn_rate"] == pytest.approx(5.0)
    # latency: every fulfilled row under threshold → burn 0, ok
    lat = by_name(report)["latency_p99"]
    assert lat["total"] == 995 and lat["bad"] == 0
    assert lat["burn_rate"] == 0.0 and not lat["exhausted"]
    # shed: no sheds at all → ok
    shed = by_name(report)["shed_rate"]
    assert shed["total"] == 1000 and shed["bad"] == 0


def test_latency_objective_counts_only_fulfilled(tmp_path):
    d = str(tmp_path)
    rows = [row(f"r1-{i}", "fulfilled", e2e_ms=100.0) for i in range(90)]
    rows += [row(f"r1-s{i}", "fulfilled", e2e_ms=5000.0)
             for i in range(10)]
    # a shed row's tiny e2e must NOT dilute the latency distribution
    rows += [row("r1-x", "shed", e2e_ms=0.1, cause="overloaded")]
    write_ledger(d, rows)
    report = evaluate_slos(d, window_s=3600.0, now=NOW)
    lat = by_name(report)["latency_p99"]
    assert lat["total"] == 100 and lat["bad"] == 10
    # 10% bad over a 1% budget → burn 10
    assert lat["burn_rate"] == pytest.approx(10.0)
    assert lat["exhausted"]


def test_cancelled_rows_spend_no_availability_budget(tmp_path):
    d = str(tmp_path)
    rows = [row(f"r1-{i}", "fulfilled") for i in range(50)]
    rows += [row(f"r1-c{i}", "cancelled", cause="client")
             for i in range(50)]
    write_ledger(d, rows)
    av = by_name(evaluate_slos(d, window_s=3600.0, now=NOW))["availability"]
    assert av["total"] == 50 and av["bad"] == 0   # cancels excluded
    assert not av["exhausted"]


def test_rolling_window_excludes_old_rows(tmp_path):
    d = str(tmp_path)
    rows = [row(f"r1-{i}", "expired", cause="deadline",
                t_wall=NOW - 10_000.0) for i in range(20)]
    rows += [row("r1-new", "fulfilled", t_wall=NOW - 5.0)]
    write_ledger(d, rows)
    report = evaluate_slos(d, window_s=3600.0, now=NOW)
    assert report["rows"] == 1                    # old spend aged out
    assert report["exhausted"] == []


# --- fallbacks --------------------------------------------------------------

def test_prom_fallback_grades_what_counters_can_see(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "telemetry.prom"), "w") as f:
        f.write("serve_requests_total 200.0\n"
                "serve_shed_total 50.0\n"
                "serve_expired_total 1.0\n"
                "serve_cancelled_total 0.0\n")
    report = evaluate_slos(d, window_s=3600.0, now=NOW)
    assert report["source"] == "prom"
    objs = by_name(report)
    # counters carry no per-request latency — declared, not fabricated
    assert objs["latency_p99"]["status"] == "no_data"
    av = objs["availability"]
    assert av["total"] == 200 and av["bad"] == 1
    shed = objs["shed_rate"]
    # 50 shed over 250 submissions against a 1% budget → exhausted
    assert shed["total"] == 250 and shed["bad"] == 50
    assert shed["exhausted"]
    assert "shed_rate" in report["exhausted"]


def test_no_artifacts_reports_no_data_never_invents(tmp_path):
    report = evaluate_slos(str(tmp_path), window_s=3600.0, now=NOW)
    assert report["source"] == "none"
    assert all(o["status"] == "no_data" for o in report["objectives"])
    assert report["exhausted"] == []
    assert report["worst_burn_rate"] == 0.0
    text = render_slos(report)
    assert "no data" in text


def test_render_marks_exhausted_budgets(tmp_path):
    d = str(tmp_path)
    write_ledger(d, [row(f"r1-{i}", "expired", cause="deadline")
                     for i in range(10)])
    text = render_slos(evaluate_slos(d, window_s=3600.0, now=NOW))
    assert "EXHAUSTED" in text and "availability" in text


def test_custom_objectives(tmp_path):
    d = str(tmp_path)
    write_ledger(d, [row("r1-1", "fulfilled", e2e_ms=900.0),
                     row("r1-2", "fulfilled", e2e_ms=1100.0)])
    strict = [{"name": "latency_strict", "kind": "latency",
               "target": 0.99, "threshold_ms": 1000.0}]
    report = evaluate_slos(d, objectives=strict, window_s=3600.0, now=NOW)
    lat = by_name(report)["latency_strict"]
    assert lat["bad"] == 1 and lat["exhausted"]
    assert [o["name"] for o in report["objectives"]] == ["latency_strict"]
    assert len(DEFAULT_OBJECTIVES) == 3           # defaults untouched


# --- CLI + doctor -----------------------------------------------------------

def test_cli_slo_exit_code_gates_on_exhaustion(tmp_path, capsys):
    d = tmp_path / "bad"
    d.mkdir()
    write_ledger(str(d), [row(f"r1-{i}", "expired", cause="deadline",
                              t_wall=NOW) for i in range(10)])
    with pytest.raises(SystemExit) as exc:
        cli_main(["slo", str(d), "--window", "1e18", "--json"])
    assert exc.value.code == 1
    out = json.loads(capsys.readouterr().out)
    assert out["exhausted"] == ["availability"]

    ok = tmp_path / "ok"
    ok.mkdir()
    write_ledger(str(ok), [row(f"r1-{i}", "fulfilled", t_wall=NOW)
                           for i in range(10)])
    cli_main(["slo", str(ok), "--window", "1e18"])   # no exit → code 0
    assert "EXHAUSTED" not in capsys.readouterr().out


def test_doctor_slo_section_fails_on_exhaustion(tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    rows = [row(f"r1-{i}", "fulfilled") for i in range(50)]
    rows += [row(f"r1-e{i}", "expired", cause="deadline")
             for i in range(5)]
    write_ledger(str(d), rows)
    (d / "telemetry.prom").write_text("")   # minimal "is a run dir" marker
    report = run_doctor(str(d), now=NOW)
    slo = next(c for c in report["checks"] if c["name"] == "slo")
    assert slo["level"] == "FAIL"
    assert "EXHAUSTED" in slo["detail"]
    assert not report["ok"]


def test_doctor_slo_exhaustion_informational_under_chaos(tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    rows = [row(f"r1-{i}", "fulfilled") for i in range(50)]
    rows += [row(f"r1-s{i}", "shed", cause="overloaded")
             for i in range(20)]
    write_ledger(str(d), rows)
    (d / "telemetry.prom").write_text("")
    # the drill artifact declares the spend deliberate
    with open(d / "serve_chaos.json", "w") as f:
        json.dump({"chaos": True, "hung_tickets": 0}, f)
    report = run_doctor(str(d), now=NOW)
    slo = next(c for c in report["checks"] if c["name"] == "slo")
    assert slo["level"] == "PASS"
    assert "chaos" in slo["detail"].lower()


def test_doctor_slo_section_absent_for_train_only_dirs(tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    (d / "stats.jsonl").write_text("")
    report = run_doctor(str(d), now=NOW)
    assert all(c["name"] != "slo" for c in report["checks"])
