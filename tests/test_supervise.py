"""Preemption-tolerant supervision (ISSUE 12): exit classification,
fault injection, torn-checkpoint walk-back, bounded shutdown, elastic
re-mesh, the availability ledger + doctor section — and (slow) the
scripted fault plan: kill -9 mid-checkpoint → resume → SIGTERM → resume,
asserting per-tick loss parity against an uninterrupted run, plus an
elastic 1↔2 virtual-CPU-device restart."""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from gansformer_tpu.supervise import events, faults
from gansformer_tpu.supervise.elastic import (
    ElasticMeshError, resolve_elastic_mesh)
from gansformer_tpu.supervise.supervisor import (
    SupervisorConfig, classify_exit, probe_hang, supervise)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test starts and ends with no armed faults (the module state
    is process-global and lazily env-initialized)."""
    faults.disarm()
    yield
    faults.disarm()


# --- exit classification -----------------------------------------------------

def test_classify_exit():
    assert classify_exit(0) == "clean"
    assert classify_exit(events.EXIT_PREEMPTED) == "preemption"
    assert classify_exit(-signal.SIGTERM) == "preemption"
    assert classify_exit(-signal.SIGKILL) == "crash"
    assert classify_exit(1) == "crash"
    assert classify_exit(139) == "crash"
    # the supervisor's own kill verdict outranks whatever code resulted
    assert classify_exit(0, killed_for_hang=True) == "hang"
    assert classify_exit(-signal.SIGKILL, killed_for_hang=True) == "hang"
    # ISSUE 15: the typed data-plane exits are classified, not "crash"
    assert classify_exit(events.EXIT_DATA_CORRUPT) == "data-corrupt"
    assert classify_exit(events.EXIT_DATA_STALLED) == "data-stall"
    assert "data-corrupt" in events.CAUSES
    assert "data-corrupt" in events.NON_RETRYABLE_CAUSES
    assert "data-stall" not in events.NON_RETRYABLE_CAUSES  # retryable


# --- fault specs -------------------------------------------------------------

def test_fault_spec_parsing():
    specs = faults.parse_specs(
        "sigkill@ckpt_mid_write:step=2000,sigterm@tick:tick=1,step=3")
    assert len(specs) == 2
    assert specs[0].action == "sigkill" and \
        specs[0].point == "ckpt_mid_write"
    assert specs[0].cond == (("step", 2000.0),)
    # conditions may themselves be comma-separated inside one spec
    assert specs[1].cond == (("tick", 1.0), ("step", 3.0))
    assert faults.parse_spec("hang@data_thread").cond == ()
    with pytest.raises(ValueError, match="expected"):
        faults.parse_spec("nonsense")
    with pytest.raises(ValueError, match="unknown action"):
        faults.parse_spec("explode@tick")


def test_fault_fires_once_and_ledger_survives_rearm(tmp_path):
    led = str(tmp_path / "led.jsonl")
    faults.arm(faults.parse_specs("raise@tick:step=10"), led)
    faults.fire("tick", step=5)                     # below threshold
    with pytest.raises(faults.FaultInjected):
        faults.fire("tick", step=10)
    faults.fire("tick", step=11)                    # one-shot: no re-fire
    # a restarted process (same env) re-arms and reads the ledger
    faults.arm(faults.parse_specs("raise@tick:step=10"), led)
    faults.fire("tick", step=12)
    recs = [json.loads(l) for l in open(led)]
    assert len(recs) == 1 and recs[0]["point"] == "tick"


def test_fault_torn_action_truncates(tmp_path):
    p = tmp_path / "state.npz"
    p.write_bytes(b"x" * 1000)
    faults.arm(faults.parse_specs("torn@ckpt_after_write:step=1"), None)
    faults.fire("ckpt_after_write", step=1, path=str(p))
    assert 0 < p.stat().st_size < 1000


# --- torn-latest checkpoint walk-back ---------------------------------------

def test_restore_walks_back_and_quarantines(tmp_path):
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.train import checkpoint as ckpt
    from tests.test_checkpoint_async import (
        assert_trees_equal, tiny_state)

    d = str(tmp_path / "ck")
    good = tiny_state(step=100, scale=3.0)
    ckpt.save(d, good, block=True)
    ckpt.save(d, tiny_state(step=200, scale=5.0), block=True)
    p = os.path.join(d, "200", "state.npz")
    with open(p, "r+b") as f:                 # tear the latest
        f.truncate(os.path.getsize(p) // 2)
    before = telemetry.counter("ckpt/restore_fallback_total").value
    restored = ckpt.restore(d, tiny_state())
    assert_trees_equal(good, restored)
    assert os.path.isdir(os.path.join(d, "200.corrupt"))
    assert ckpt.latest_step(d) == 100          # quarantine hid the bad dir
    assert telemetry.counter(
        "ckpt/restore_fallback_total").value == before + 1


def test_restore_explicit_step_still_hard_fails(tmp_path):
    from gansformer_tpu.train import checkpoint as ckpt
    from tests.test_checkpoint_async import tiny_state

    d = str(tmp_path / "ck")
    ckpt.save(d, tiny_state(step=300), block=True)
    with open(os.path.join(d, "300", "state.npz"), "r+b") as f:
        f.truncate(10)
    with pytest.raises(Exception):
        ckpt.restore(d, tiny_state(), step=300)
    assert os.path.isdir(os.path.join(d, "300"))   # NOT quarantined


def test_restore_all_corrupt_raises_with_words(tmp_path):
    from gansformer_tpu.train import checkpoint as ckpt
    from tests.test_checkpoint_async import tiny_state

    d = str(tmp_path / "ck")
    ckpt.save(d, tiny_state(step=10), block=True)
    with open(os.path.join(d, "10", "state.npz"), "r+b") as f:
        f.truncate(5)
    with pytest.raises(ValueError, match="decodes cleanly"):
        ckpt.restore(d, tiny_state())


def test_mismatched_template_walks_back_too(tmp_path):
    """'torn/mismatched' (the satellite's words): a latest step whose
    leaves don't fit the template walks back like a torn one."""
    import jax.numpy as jnp

    from gansformer_tpu.train import checkpoint as ckpt
    from tests.test_checkpoint_async import (
        assert_trees_equal, tiny_state)

    d = str(tmp_path / "ck")
    good = tiny_state(step=100)
    ckpt.save(d, good, block=True)
    bad = dataclasses.replace(tiny_state(step=200), w_avg=jnp.zeros(9))
    ckpt.save(d, bad, block=True)
    restored = ckpt.restore(d, tiny_state())
    assert_trees_equal(good, restored)
    assert os.path.isdir(os.path.join(d, "200.corrupt"))


# --- bounded shutdown --------------------------------------------------------

def test_single_slot_writer_wait_timeout_bounded():
    from gansformer_tpu.utils.background import SingleSlotWriter

    w = SingleSlotWriter("test/bounded")
    gate = threading.Event()
    w.submit(lambda: gate.wait(10.0))
    t0 = time.perf_counter()
    assert w.wait(timeout=0.2) is False        # wedged writer: bounded
    assert time.perf_counter() - t0 < 2.0
    assert w.close(timeout=0.05) is False      # close never raises
    gate.set()
    assert w.wait(timeout=5.0) is True


def test_single_slot_writer_timeout_preserves_sticky_error():
    from gansformer_tpu.utils.background import (
        BackgroundWriteError, SingleSlotWriter)

    w = SingleSlotWriter("test/bounded2")
    gate = threading.Event()

    def job():
        gate.wait(10.0)
        raise OSError("late failure")

    w.submit(job)
    assert w.close(timeout=0.05) is False      # timed out, no delivery
    gate.set()
    w.wait(reraise=False, timeout=5.0)
    with pytest.raises(BackgroundWriteError, match="late failure"):
        w.poll()                               # sticky error intact


def test_loop_worker_wait_and_close_timeouts():
    from gansformer_tpu.utils.background import LoopWorker

    gate = threading.Event()
    lw = LoopWorker(lambda: gate.wait(10.0), "test/lw").start()
    assert lw.wait(timeout=0.05) is False
    assert lw.close(timeout=0.05) is False
    gate.set()
    assert lw.wait(timeout=5.0) is True


# --- elastic re-mesh ---------------------------------------------------------

def _cfg(mesh=None):
    from gansformer_tpu.core.config import MeshConfig
    from tests.test_train import micro_cfg

    cfg = micro_cfg()               # batch_size 8
    return dataclasses.replace(cfg, mesh=mesh or MeshConfig())


def test_elastic_pinned_axis_respected_when_it_fits():
    from gansformer_tpu.core.config import MeshConfig

    cfg, notes = resolve_elastic_mesh(_cfg(MeshConfig(data=2)), 2)
    assert cfg.mesh.data == 2 and notes == []


def test_elastic_pinned_axis_rewritten_to_all_devices():
    from gansformer_tpu.core.config import MeshConfig

    cfg, notes = resolve_elastic_mesh(_cfg(MeshConfig(data=2)), 1)
    assert cfg.mesh.data == -1          # grows back on a wider claim
    assert any("does not fit" in n for n in notes)


def test_elastic_derived_axis_pins_largest_divisor():
    cfg, notes = resolve_elastic_mesh(_cfg(), 3)   # batch 8 % 3 != 0
    assert cfg.mesh.data == 2
    assert any("does not divide" in n for n in notes)
    cfg, notes = resolve_elastic_mesh(_cfg(), 8)
    assert cfg.mesh.data == -1 and notes == []


def test_elastic_fsdp_dropped_only_when_pinned_to_one():
    from gansformer_tpu.core.config import MeshConfig

    base = _cfg(MeshConfig(data=2, fsdp=True))
    # shrink to 1 device: data -1 derives 1, fsdp kept (degrades to
    # replicated placement per-leaf)
    cfg, notes = resolve_elastic_mesh(base, 1)
    assert cfg.mesh.data == -1 and cfg.mesh.fsdp
    # derived axis pinned to a >1 divisor: fsdp kept
    odd = dataclasses.replace(
        _cfg(MeshConfig(data=-1, fsdp=True)),
        train=dataclasses.replace(base.train, batch_size=6),
        model=dataclasses.replace(base.model, mbstd_group_size=2))
    cfg, notes = resolve_elastic_mesh(odd, 5)   # 6 % 5 != 0 → pin 3
    assert cfg.mesh.data == 3 and cfg.mesh.fsdp
    # a pin that lands on 1 (batch 7, 2 devices) must drop fsdp to
    # stay expressible
    prime = dataclasses.replace(
        odd, train=dataclasses.replace(odd.train, batch_size=7,
                                       pl_batch_shrink=1),
        model=dataclasses.replace(odd.model, mbstd_group_size=1))
    cfg, notes = resolve_elastic_mesh(prime, 2)
    assert cfg.mesh.data == 1 and not cfg.mesh.fsdp
    assert any("fsdp disabled" in n for n in notes)


def test_elastic_model_axis_refused():
    from gansformer_tpu.core.config import MeshConfig

    base = _cfg(MeshConfig(data=1, model=2))
    base = dataclasses.replace(
        base, model=dataclasses.replace(base.model,
                                        sequence_parallel=True))
    with pytest.raises(ElasticMeshError, match="model"):
        resolve_elastic_mesh(base, 1)


# --- events ledger + availability -------------------------------------------

def _ledger(run_dir, *recs):
    for kind, fields in recs:
        events.append_event(run_dir, kind, **fields)


def test_events_roundtrip_torn_tolerant(tmp_path):
    d = str(tmp_path)
    _ledger(d, ("start", {"restart_index": 0, "downtime_s": 0.0}),
            ("exit", {"cause": "crash", "exit_code": -9,
                      "uptime_s": 10.0, "step": 1000}))
    with open(events.events_path(d), "a") as f:
        f.write('{"kind": "ex')            # SIGKILL mid-append
    evs = events.read_events(d)
    assert [e["kind"] for e in evs] == ["start", "exit"]


def test_supervisor_events_schema_tolerates_and_reports_garbage(tmp_path):
    """Schema lint: a torn FINAL line is the ledger's normal ending
    (tolerated); mid-file garbage — torn lines or valid-JSON non-objects
    — is reported, never a checker crash."""
    from gansformer_tpu.analysis.telemetry_schema import (
        check_supervisor_events)

    d = str(tmp_path)
    _ledger(d, ("start", {"restart_index": 0, "downtime_s": 0.0}))
    with open(events.events_path(d), "a") as f:
        f.write("null\n")                     # valid JSON, not an object
        f.write('{"kind": "exit"\n')          # torn mid-file
        f.write(json.dumps({"schema": 1, "kind": "exit", "time": 1.0,
                            "pid": 1, "cause": "crash",
                            "exit_code": 1}) + "\n")
        f.write('{"kind": "st')               # torn FINAL line: tolerated
    errs = check_supervisor_events(events.events_path(d))
    assert any("not a JSON object" in e for e in errs)
    assert any("not JSON" in e for e in errs)
    assert not any(":5:" in e for e in errs)   # the torn tail is free


def test_availability_summary(tmp_path):
    d = str(tmp_path)
    now = 1_000_000.0
    _ledger(
        d,
        ("supervisor_start", {"max_restarts": 8, "time": now - 100}),
        ("start", {"restart_index": 0, "downtime_s": 0.0,
                   "time": now - 100}),
        ("exit", {"cause": "preemption", "exit_code": 75,
                  "uptime_s": 60.0, "step": 1000, "time": now - 40}),
        ("start", {"restart_index": 1, "downtime_s": 20.0, "resume": True,
                   "time": now - 20}),
        ("exit", {"cause": "clean", "exit_code": 0, "uptime_s": 20.0,
                  "step": 2000, "time": now}),
        ("complete", {"restarts": 1, "step": 2000, "time": now}))
    s = events.availability(events.read_events(d), now=now)
    assert s["restarts"] == 1 and s["restarts_last_hour"] == 1
    assert s["causes"] == {"preemption": 1, "clean": 1}
    assert s["completed"] and not s["gave_up"]
    assert abs(s["ratio"] - 80.0 / 100.0) < 1e-9
    assert s["last_step"] == 2000


# --- hang probe --------------------------------------------------------------

def _write_beat(run_dir, idx, t, step=0, phase=None):
    rec = {"process": idx, "pid": 1, "host": "h", "time": t,
           "step": step, "kimg": step / 1000}
    if phase:
        rec["phase"] = phase
    with open(os.path.join(run_dir, f"heartbeat-p{idx}.json"), "w") as f:
        json.dump(rec, f)


def test_probe_hang_verdicts(tmp_path):
    d = str(tmp_path)
    cfg = SupervisorConfig(heartbeat_max_age_s=10.0, startup_grace_s=30.0,
                           max_step_skew=5)
    t0 = 1000.0
    # no beat yet, inside startup grace → healthy
    assert probe_hang(d, t0, cfg, now=t0 + 20) is None
    # no beat, grace exceeded → hang
    assert "startup grace" in probe_hang(d, t0, cfg, now=t0 + 31)
    # a STALE beat from the previous attempt must not convict this one
    _write_beat(d, 0, t0 - 50)
    assert "startup grace" in probe_hang(d, t0, cfg, now=t0 + 31)
    # fresh beat → healthy; then it goes stale
    _write_beat(d, 0, t0 + 5)
    assert probe_hang(d, t0, cfg, now=t0 + 10) is None
    assert "stale" in probe_hang(d, t0, cfg, now=t0 + 16)
    # straggler: two fresh beats, step spread beyond max_step_skew
    _write_beat(d, 0, t0 + 20, step=100)
    _write_beat(d, 1, t0 + 20, step=200)
    assert "skew" in probe_hang(d, t0, cfg, now=t0 + 21)


def test_probe_hang_setup_beat_keeps_startup_grace(tmp_path):
    """The loop beats once at setup BEFORE the first-dispatch compiles;
    a supervisor judging that window against the steady-state heartbeat
    budget would kill a healthy child mid-compile — the setup-phase
    beat keeps the startup grace in force until a tick beat lands."""
    d = str(tmp_path)
    cfg = SupervisorConfig(heartbeat_max_age_s=10.0, startup_grace_s=30.0)
    t0 = 1000.0
    _write_beat(d, 0, t0 + 1, phase="setup")
    # 25s of silence: stale by heartbeat budget, fine by startup grace
    assert probe_hang(d, t0, cfg, now=t0 + 26) is None
    # past the startup grace with still no tick beat → hang
    assert "setup phase" in probe_hang(d, t0, cfg, now=t0 + 40)
    # a tick beat ends the setup regime: heartbeat budget applies again
    _write_beat(d, 0, t0 + 41)
    assert probe_hang(d, t0, cfg, now=t0 + 50) is None
    assert "stale" in probe_hang(d, t0, cfg, now=t0 + 52)
    # the finalize beat (final snapshot + sync checkpoint window)
    # restores the grace regime — an almost-finished child must not be
    # killed as a hang mid-final-save
    _write_beat(d, 0, t0 + 60, phase="finalize")
    assert probe_hang(d, t0, cfg, now=t0 + 85) is None
    assert "finalize phase" in probe_hang(d, t0, cfg, now=t0 + 95)


def test_supervise_preempted_during_backoff_does_not_respawn(tmp_path):
    """A SIGTERM landing between children (backoff sleep) must stop the
    supervisor instead of spawning into a dying allocation."""
    d = str(tmp_path / "run")
    fired = {"n": 0}

    def build_argv(resume, i):
        fired["n"] += 1
        assert i == 0, "respawned after preemption"
        return [sys.executable, "-c", "raise SystemExit(2)"]

    def log(msg):
        # the "restart #…" line is emitted right before the backoff
        # sleep — deliver the preemption notice exactly there (on the
        # supervisor thread, where its handler is installed)
        if msg.startswith("restart #"):
            signal.raise_signal(signal.SIGTERM)

    res = supervise(build_argv, d, FAST, log=log)
    assert res["cause"] == "supervisor_preempted"
    assert res["exit_code"] == events.EXIT_PREEMPTED
    assert fired["n"] == 1
    assert any(e["kind"] == "supervisor_preempted"
               for e in events.read_events(d))


def test_concurrent_same_step_saves_use_distinct_tmp_dirs(tmp_path):
    """The preemption path can sync-save the step a timed-out async
    writer is still writing: the tmp dir must be per-thread or the two
    np.savez streams interleave into one torn file."""
    from gansformer_tpu.train import checkpoint as ckpt
    from tests.test_checkpoint_async import (
        assert_trees_equal, tiny_state)

    d = str(tmp_path / "ck")
    st = tiny_state(step=500, scale=2.0)
    gate = threading.Event()
    seen = []

    def hook(step):
        seen.append(sorted(p for p in os.listdir(d)
                           if p.startswith(".tmp")))
        if len(seen) == 1:
            # first (async) writer parks mid-write; a second thread (the
            # loop thread in the preemption scenario) sync-saves the
            # SAME step concurrently
            t = threading.Thread(
                target=lambda: ckpt.save(d, st, block=True))
            t.start()
            t.join()
            gate.set()

    try:
        ckpt._WRITE_HOOK = hook
        ckpt.save(d, st, block=False)
        ckpt.wait(d)
    finally:
        ckpt._WRITE_HOOK = None
    assert gate.is_set()
    # the nested sync save saw BOTH tmp dirs, with distinct names
    assert len(seen[1]) == 2 and len(set(seen[1])) == 2, seen
    assert ckpt.latest_step(d) == 500
    assert_trees_equal(st, ckpt.restore(d, tiny_state()))


def test_quarantine_race_lost_to_peer_still_walks_back(tmp_path,
                                                       monkeypatch):
    """Multi-host resume: every process walks the same shared dir; the
    quarantine-rename losers must walk back, not crash."""
    from gansformer_tpu.train import checkpoint as ckpt
    from tests.test_checkpoint_async import (
        assert_trees_equal, tiny_state)

    d = str(tmp_path / "ck")
    good = tiny_state(step=100, scale=3.0)
    ckpt.save(d, good, block=True)
    ckpt.save(d, tiny_state(step=200), block=True)
    p = os.path.join(d, "200", "state.npz")
    with open(p, "r+b") as f:
        f.truncate(10)

    real_quarantine = ckpt._quarantine

    def peer_wins(ckpt_dir, step):
        real_quarantine(ckpt_dir, step)     # "the peer" renames first
        return real_quarantine(ckpt_dir, step)  # our rename: src gone

    monkeypatch.setattr(ckpt, "_quarantine", peer_wins)
    restored = ckpt.restore(d, tiny_state())
    assert_trees_equal(good, restored)


# --- the supervisor itself (trivial no-jax children) -------------------------

FAST = SupervisorConfig(max_restarts=3, backoff_base_s=0.05,
                        backoff_max_s=0.2, poll_interval_s=0.05,
                        startup_grace_s=60.0, hang_kill_grace_s=0.5)


def _marker_child(tmp_path, first_exit):
    """argv for a child that exits ``first_exit`` once, then 0."""
    marker = str(tmp_path / "marker")
    return [sys.executable, "-c",
            f"import os, sys\n"
            f"m = {marker!r}\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').close(); sys.exit({first_exit})\n"
            f"sys.exit(0)"]


def test_supervise_restarts_crash_to_completion(tmp_path):
    d = str(tmp_path / "run")
    argv = _marker_child(tmp_path, 2)
    res = supervise(lambda r, i: argv, d, FAST, log=lambda m: None)
    assert res["ok"] and res["exit_code"] == 0 and res["restarts"] == 1
    causes = [e["cause"] for e in events.read_events(d)
              if e["kind"] == "exit"]
    assert causes == ["crash", "clean"]
    # telemetry family present and self-consistent
    from gansformer_tpu.analysis.telemetry_schema import (
        check_prom, check_supervise_metric_families,
        check_supervisor_events)

    prom = os.path.join(d, "supervisor.prom")
    assert check_prom(prom) == []
    assert check_supervise_metric_families(prom) == []
    assert check_supervisor_events(events.events_path(d)) == []


def test_supervise_classifies_preemption_code(tmp_path):
    d = str(tmp_path / "run")
    argv = _marker_child(tmp_path, events.EXIT_PREEMPTED)
    res = supervise(lambda r, i: argv, d, FAST, log=lambda m: None)
    assert res["ok"]
    causes = [e["cause"] for e in events.read_events(d)
              if e["kind"] == "exit"]
    assert causes == ["preemption", "clean"]


def test_supervise_data_corrupt_gives_up_without_restarts(tmp_path):
    """Acceptance (c): a data-corrupt exit is NON-RETRYABLE — the
    supervisor reports the cause and gives up with ZERO restarts
    consumed instead of crash-looping on a static defect."""
    d = str(tmp_path / "run")
    argv = [sys.executable, "-c",
            f"raise SystemExit({events.EXIT_DATA_CORRUPT})"]
    res = supervise(lambda r, i: argv, d, FAST, log=lambda m: None)
    assert not res["ok"] and res["cause"] == "data-corrupt"
    assert res["restarts"] == 0 and res["exit_code"] == 1
    evs = events.read_events(d)
    gu = [e for e in evs if e["kind"] == "give_up"]
    assert gu and gu[0]["cause"] == "data-corrupt" and \
        gu[0].get("non_retryable") is True
    assert sum(1 for e in evs if e["kind"] == "exit") == 1  # no re-spawn
    # ledger + telemetry stay schema-clean with the new cause
    from gansformer_tpu.analysis.telemetry_schema import (
        check_supervise_metric_families, check_supervisor_events)
    from gansformer_tpu.obs.registry import parse_prom_values

    prom = os.path.join(d, "supervisor.prom")
    assert check_supervise_metric_families(prom) == []
    assert check_supervisor_events(events.events_path(d)) == []
    assert parse_prom_values(prom)[
        "supervise_data_corrupt_exits_total"] == 1.0
    # the doctor's availability section grades the give-up as FAIL
    from gansformer_tpu.cli.telemetry import run_doctor

    with open(os.path.join(d, "stats.jsonl"), "w") as f:
        f.write("{}\n")              # minimal artifact so the doctor runs
    rep = run_doctor(d)
    avail = next(c for c in rep["checks"] if c["name"] == "availability")
    assert avail["level"] == "FAIL" and "data-corrupt" in avail["detail"]


def test_supervise_data_stall_is_retryable(tmp_path):
    """A data-stall exit stays RETRYABLE (possibly a transient
    filesystem wedge) but lands classified in ledger + telemetry."""
    d = str(tmp_path / "run")
    argv = _marker_child(tmp_path, events.EXIT_DATA_STALLED)
    res = supervise(lambda r, i: argv, d, FAST, log=lambda m: None)
    assert res["ok"] and res["restarts"] == 1
    causes = [e["cause"] for e in events.read_events(d)
              if e["kind"] == "exit"]
    assert causes == ["data-stall", "clean"]
    from gansformer_tpu.obs.registry import parse_prom_values

    vals = parse_prom_values(os.path.join(d, "supervisor.prom"))
    assert vals["supervise_data_stall_exits_total"] == 1.0


def test_train_cli_maps_typed_data_exits(tmp_path, monkeypatch):
    """cli/train converts DataCorrupt/DataStalled into the distinct exit
    codes the supervisor classifies on."""
    from gansformer_tpu.cli.train import main as train_main
    from gansformer_tpu.data.errors import DataCorrupt, DataStalled
    from gansformer_tpu.train import loop as loop_mod

    for exc, code in ((DataCorrupt("budget"), events.EXIT_DATA_CORRUPT),
                      (DataStalled("wedged"), events.EXIT_DATA_STALLED)):
        def raising_train(*a, **k):
            raise exc

        monkeypatch.setattr(loop_mod, "train", raising_train)
        with pytest.raises(SystemExit) as e:
            train_main(["--preset", "clevr64-simplex",
                        "--run-dir", str(tmp_path / f"r{code}")])
        assert e.value.code == code


def test_supervise_gives_up_on_budget(tmp_path):
    d = str(tmp_path / "run")
    cfg = dataclasses.replace(FAST, max_restarts=1)
    res = supervise(lambda r, i: [sys.executable, "-c", "raise SystemExit(3)"],
                    d, cfg, log=lambda m: None)
    assert not res["ok"] and res["exit_code"] == 1 and res["restarts"] == 1
    evs = events.read_events(d)
    assert any(e["kind"] == "give_up" for e in evs)
    assert sum(1 for e in evs if e["kind"] == "exit") == 2


def test_supervise_kills_hung_child(tmp_path):
    d = str(tmp_path / "run")
    cfg = dataclasses.replace(FAST, max_restarts=0, startup_grace_s=0.3)
    t0 = time.time()
    res = supervise(
        lambda r, i: [sys.executable, "-c", "import time; time.sleep(60)"],
        d, cfg, log=lambda m: None)
    assert time.time() - t0 < 20.0
    assert not res["ok"] and res["cause"] == "hang"
    ex = [e for e in events.read_events(d) if e["kind"] == "exit"]
    assert ex[0]["cause"] == "hang" and "hang_reason" in ex[0]


# --- doctor availability section --------------------------------------------

def _doctor(d, **kw):
    from gansformer_tpu.cli.telemetry import run_doctor
    from tests.test_doctor import NOW

    return run_doctor(d, now=NOW, **kw)


def _levels(report):
    return {c["name"]: c["level"] for c in report["checks"]}


def _detail(report, name):
    return next(c["detail"] for c in report["checks"]
                if c["name"] == name)


def test_doctor_availability_grades(tmp_path):
    from tests.test_doctor import NOW, synth_run_dir

    # healthy supervised run → PASS with ratio
    d = synth_run_dir(tmp_path, name="ok")
    _ledger(d, ("start", {"restart_index": 0, "downtime_s": 0.0,
                          "time": NOW - 100}),
            ("exit", {"cause": "preemption", "exit_code": 75,
                      "uptime_s": 90.0, "step": 1000, "time": NOW - 10}),
            ("start", {"restart_index": 1, "downtime_s": 10.0,
                       "time": NOW}))
    rep = _doctor(d)
    assert _levels(rep)["availability"] == "PASS"
    assert "availability 90.0%" in _detail(rep, "availability")

    # give-up → FAIL
    d = synth_run_dir(tmp_path, name="gaveup")
    _ledger(d, ("exit", {"cause": "crash", "exit_code": 1,
                         "uptime_s": 5.0, "step": 0, "time": NOW}),
            ("give_up", {"restarts": 8, "cause": "crash", "time": NOW}))
    rep = _doctor(d)
    assert _levels(rep)["availability"] == "FAIL" and not rep["ok"]

    # restart storm → WARN
    d = synth_run_dir(tmp_path, name="storm")
    for i in range(8):
        _ledger(d, ("start", {"restart_index": i + 1, "downtime_s": 1.0,
                              "time": NOW - 10 * i}),
                ("exit", {"cause": "crash", "exit_code": 1,
                          "uptime_s": 1.0, "step": 0,
                          "time": NOW - 10 * i}))
    rep = _doctor(d)
    assert _levels(rep)["availability"] == "WARN"
    assert "storm" in _detail(rep, "availability")

    # unclassified cause → WARN
    d = synth_run_dir(tmp_path, name="odd")
    _ledger(d, ("exit", {"cause": "gremlins", "exit_code": 1,
                         "uptime_s": 1.0, "step": 0, "time": NOW}))
    rep = _doctor(d)
    assert _levels(rep)["availability"] == "WARN"
    assert "unclassified" in _detail(rep, "availability")

    # no ledger → no availability section (legacy runs unchanged)
    d = synth_run_dir(tmp_path, name="plain")
    rep = _doctor(d)
    assert "availability" not in _levels(rep)


# --- data-stream resume alignment -------------------------------------------

def test_synthetic_batches_start_batch_aligns():
    import numpy as np

    from gansformer_tpu.data.dataset import SyntheticDataset

    ds = SyntheticDataset(resolution=8, num_images=100)
    full = ds.batches(4, seed=7)
    ref = [next(full) for _ in range(6)]
    resumed = ds.batches(4, seed=7, start_batch=3)
    for want in ref[3:]:
        got = next(resumed)
        assert np.array_equal(want["image"], got["image"])


def test_npz_batches_start_batch_aligns(tmp_path):
    import numpy as np

    from gansformer_tpu.data.dataset import NpzDataset

    path = str(tmp_path / "d.npz")
    np.savez(path, images=np.random.RandomState(0).randint(
        0, 255, (32, 8, 8, 3), dtype=np.uint8))
    ds = NpzDataset(path)
    full = ds.batches(4, seed=3)
    ref = [next(full) for _ in range(5)]
    resumed = ds.batches(4, seed=3, start_batch=2)
    for want in ref[2:]:
        assert np.array_equal(want["image"], next(resumed)["image"])


# --- slow: the scripted fault plan + elastic restarts ------------------------

def _write_micro_config(tmp_path, total_kimg, mesh_data=None):
    from gansformer_tpu.core.config import MeshConfig
    from tests.test_train import micro_cfg

    cfg = micro_cfg(attention="simplex", batch=8)
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(
            cfg.train, total_kimg=total_kimg, kimg_per_tick=1,
            snapshot_ticks=1, image_snapshot_ticks=0,
            device_time_ticks=0),
        mesh=MeshConfig(data=mesh_data) if mesh_data else cfg.mesh)
    p = str(tmp_path / "config.json")
    with open(p, "w") as f:
        f.write(cfg.to_json())
    return cfg, p


def _child_env(devices=8):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices}")
    return env


def _loss_by_kimg(run_dir):
    out = {}
    with open(os.path.join(run_dir, "stats.jsonl")) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if "Progress/kimg" in rec and "Loss/G" in rec:
                out[round(rec["Progress/kimg"], 3)] = (
                    rec["Loss/G"], rec.get("Loss/D"))
    return out


@pytest.mark.slow  # four subprocess training runs (compile-cache warm)
def test_scripted_fault_plan_matches_uninterrupted_run(tmp_path):
    """The ISSUE 12 acceptance plan: kill -9 mid-checkpoint → auto-resume
    → SIGTERM preemption at a tick boundary → auto-resume → complete,
    all under gansformer-supervise with zero intervention — and the
    supervised run's per-tick losses equal an uninterrupted run's."""
    cfg, cfg_path = _write_micro_config(tmp_path, total_kimg=4)

    # reference: uninterrupted run, same config, plain train CLI
    ref_dir = str(tmp_path / "ref")
    r = subprocess.run(
        [sys.executable, "-m", "gansformer_tpu.cli.train",
         "--config", cfg_path, "--run-dir", ref_dir],
        env=_child_env(), cwd=ROOT, capture_output=True, text=True,
        timeout=900)
    assert r.returncode == 0, r.stderr[-3000:]

    # supervised: crash at the step-2000 checkpoint write, preemption
    # notice at the step-3000 tick boundary
    sup_dir = str(tmp_path / "sup")
    r = subprocess.run(
        [sys.executable, "-m", "gansformer_tpu.cli.supervise",
         "--run-dir", sup_dir, "--max-restarts", "4",
         "--poll-interval", "0.5", "--backoff-base", "0.1",
         "--startup-grace", "600", "--heartbeat-max-age", "600",
         "--fault", "sigkill@ckpt_mid_write:step=2000",
         "--fault", "sigterm@tick:step=3000",
         "--", "--config", cfg_path],
        env=_child_env(), cwd=ROOT, capture_output=True, text=True,
        timeout=900)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-3000:])

    # the ledger tells the story: crash, preemption, clean — in order
    causes = [e["cause"] for e in events.read_events(sup_dir)
              if e["kind"] == "exit"]
    assert causes == ["crash", "preemption", "clean"], causes
    fired = [json.loads(l) for l in
             open(os.path.join(sup_dir, "faults_fired.jsonl"))]
    assert {f["key"] for f in fired} == {
        "sigkill@ckpt_mid_write:step=2000", "sigterm@tick:step=3000"}

    # per-tick loss parity: the supervised run's trajectory is
    # tick-for-tick identical to the uninterrupted one (bit-exact
    # restore + iteration-indexed rng + start_batch data alignment)
    ref_losses = _loss_by_kimg(ref_dir)
    sup_losses = _loss_by_kimg(sup_dir)
    assert set(ref_losses) <= set(sup_losses)
    for k, v in ref_losses.items():
        assert sup_losses[k] == v, (k, v, sup_losses[k])

    # the doctor grades the whole thing PASS (availability section
    # included) with no FAILs
    from gansformer_tpu.cli.telemetry import run_doctor

    report = run_doctor(sup_dir)
    assert report["ok"], report
    lv = {c["name"]: c["level"] for c in report["checks"]}
    assert lv["availability"] == "PASS"


@pytest.mark.slow  # three subprocess training runs at 2/1/2 devices
def test_elastic_restart_across_device_counts(tmp_path):
    """2-device run → resume on 1 device (re-mesh + re-shard) → resume
    on 2 devices again (grows back) — the forced-virtual-CPU elastic
    acceptance test."""
    cfg, cfg_path = _write_micro_config(tmp_path, total_kimg=1,
                                        mesh_data=2)
    d = str(tmp_path / "run")

    def run(devices, total_kimg, resume):
        argv = [sys.executable, "-m", "gansformer_tpu.cli.train",
                "--config", cfg_path, "--run-dir", d,
                "--total-kimg", str(total_kimg)]
        if resume:
            argv.append("--resume")
        return subprocess.run(argv, env=_child_env(devices), cwd=ROOT,
                              capture_output=True, text=True,
                              timeout=900)

    r = run(2, 1, resume=False)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "'data': 2" in open(os.path.join(d, "log.txt")).read()

    r = run(1, 2, resume=True)
    assert r.returncode == 0, r.stderr[-3000:]
    log = open(os.path.join(d, "log.txt")).read()
    assert "re-meshed" in log and "resumed from step 1000" in log
    elastic = [e for e in events.read_events(d) if e["kind"] == "elastic"]
    assert elastic and elastic[0]["n_devices"] == 1

    r = run(2, 3, resume=True)
    assert r.returncode == 0, r.stderr[-3000:]
    log = open(os.path.join(d, "log.txt")).read()
    assert "resumed from step 2000" in log
    # back on 2 devices: the rewritten data=-1 mesh derives 2 again
    assert log.rstrip().rsplit("mesh: ", 1)[-1].startswith("{'data': 2")

    from gansformer_tpu.train import checkpoint as ckpt

    assert ckpt.latest_step(os.path.join(d, "checkpoints")) == 3000


@pytest.mark.slow  # two subprocess training runs (compile-cache warm)
def test_tfrecord_kill_resume_loss_parity_with_chaos(tmp_path):
    """The ISSUE 15 chaos contract, end to end on a TFRECORD source:
    (a) one injected transient read error and (b) one corrupt record
    under budget ride a supervised run that is SIGKILLed mid-checkpoint
    and auto-resumed — training completes, the retry/quarantine counters
    are populated, the doctor grades PASS/WARN (no FAIL), and the
    per-tick losses are tick-for-tick IDENTICAL to an uninterrupted run
    (the resume-exact TFRecord positioning ROADMAP item 5 asked for,
    mirroring the npz parity test above)."""
    import numpy as np

    from gansformer_tpu.data.tfrecord_writer import (
        TFRecordExporter, encode_example_image, write_record)

    # a 64-image synthetic tfrecord set at the micro resolution, plus
    # ONE corrupt record (valid framing/CRC, garbage proto) under budget
    data_dir = str(tmp_path / "data")
    rs = np.random.RandomState(0)
    with TFRecordExporter(data_dir, "toy", 16, all_lods=False) as ex:
        for _ in range(64):
            ex.add_image(rs.randint(0, 255, (16, 16, 3), np.uint8))
    rec_file = os.path.join(data_dir, "toy-r04.tfrecords")
    with open(rec_file, "ab") as f:
        write_record(f, b"\x05not-a-proto")

    cfg, _ = _write_micro_config(tmp_path, total_kimg=4)
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(
            cfg.data, source="tfrecord", path=data_dir, resolution=16,
            max_corrupt_frac=0.1))
    cfg_path = str(tmp_path / "config_tfrecord.json")
    with open(cfg_path, "w") as f:
        f.write(cfg.to_json())

    # reference: uninterrupted run, same config + data
    ref_dir = str(tmp_path / "ref")
    r = subprocess.run(
        [sys.executable, "-m", "gansformer_tpu.cli.train",
         "--config", cfg_path, "--run-dir", ref_dir],
        env=_child_env(), cwd=ROOT, capture_output=True, text=True,
        timeout=900)
    assert r.returncode == 0, r.stderr[-3000:]

    # supervised: SIGKILL mid-checkpoint + one transient read error
    sup_dir = str(tmp_path / "sup")
    r = subprocess.run(
        [sys.executable, "-m", "gansformer_tpu.cli.supervise",
         "--run-dir", sup_dir, "--max-restarts", "4",
         "--poll-interval", "0.5", "--backoff-base", "0.1",
         "--startup-grace", "600", "--heartbeat-max-age", "600",
         "--fault", "sigkill@ckpt_mid_write:step=2000",
         "--fault", "raise@data_read_error:n=700",
         "--", "--config", cfg_path],
        env=_child_env(), cwd=ROOT, capture_output=True, text=True,
        timeout=900)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-3000:])
    causes = [e["cause"] for e in events.read_events(sup_dir)
              if e["kind"] == "exit"]
    assert causes == ["crash", "clean"], causes

    # tick-for-tick loss parity across the kill→resume (the start_batch
    # fast-forward advances the RNG permutation stream only)
    ref_losses = _loss_by_kimg(ref_dir)
    sup_losses = _loss_by_kimg(sup_dir)
    assert set(ref_losses) <= set(sup_losses)
    for k, v in ref_losses.items():
        assert sup_losses[k] == v, (k, v, sup_losses[k])

    # chaos evidence: quarantine + retry counters populated, ledger
    # written, schema lint clean, doctor PASS/WARN only
    from gansformer_tpu.analysis.telemetry_schema import check_run_dir
    from gansformer_tpu.obs.registry import parse_prom_values

    vals = parse_prom_values(os.path.join(sup_dir, "telemetry.prom"))
    assert vals["data_corrupt_records_total"] >= 1.0
    assert vals["data_stalls_total"] == 0.0
    # the injected read error fired (and was absorbed) in the PRE-KILL
    # process, whose registry died with it — the retry evidence lives in
    # the append-only stats.jsonl records and the fault ledger, which is
    # exactly what the doctor's restart-spanning max reads
    fired = {json.loads(l)["key"] for l in
             open(os.path.join(sup_dir, "faults_fired.jsonl"))}
    assert "raise@data_read_error:n=700" in fired
    retries = []
    for line in open(os.path.join(sup_dir, "stats.jsonl")):
        try:
            r = json.loads(line)
        except ValueError:
            continue               # torn line: the SIGKILL's signature
        if "telemetry" in r:
            retries.append(
                r["telemetry"]["counters"]["data/read_retries_total"])
    assert max(retries) >= 1.0
    assert os.path.exists(os.path.join(sup_dir, "data_quarantine.jsonl"))
    res = check_run_dir(sup_dir)
    assert res["ok"], res["errors"]

    from gansformer_tpu.cli.telemetry import run_doctor

    report = run_doctor(sup_dir)
    assert report["ok"], report
    lv = {c["name"]: c["level"] for c in report["checks"]}
    assert lv["data_plane"] == "WARN"      # the drill's counters moved
    assert lv["availability"] == "PASS"
