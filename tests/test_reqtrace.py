"""Per-request tracing tests (obs/reqtrace, ISSUE 16): lifecycle
recording on fake clocks, terminal finalization + ledger round-trip,
bounded growth (active-table eviction, ledger cap — every bound has a
counter), Chrome async events merged into the span sink, the
``requests.jsonl`` schema checker incl. its prom cross-checks, the
``requests`` CLI subcommand, and the disabled-path no-ops."""

import json
import os

import pytest

from gansformer_tpu.analysis.telemetry_schema import (
    check_events, check_requests)
from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.reqtrace import (
    EVENT_KINDS, TERMINAL_KINDS, ReqTracer, read_requests, render_timeline)
from gansformer_tpu.obs.spans import get_tracer


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_tracer(clk, wall0=1_000_000.0):
    # wall clock rides the same fake advance so ledger rows carry
    # deterministic t_wall values for the SLO window tests' idiom
    return ReqTracer(time_fn=clk, wall_fn=lambda: wall0 + clk.t)


def counter_value(name):
    return telemetry.counter(name).value


# --- lifecycle --------------------------------------------------------------

def test_lifecycle_roundtrip_through_ledger(tmp_path):
    clk = FakeClock()
    rt = make_tracer(clk)
    path = str(tmp_path / "requests.jsonl")
    rt.configure(path, chrome_events=False)

    rid = rt.begin(seed=7, psi=0.8)
    assert rid and rid.startswith("r")
    clk.advance(0.010)
    rt.event(rid, "admitted")
    clk.advance(0.005)
    rt.event(rid, "popped")
    rt.event(rid, "batched", batch=3, bucket=4)
    rt.event(rid, "map_dispatch")
    rt.event(rid, "synth")
    clk.advance(0.020)
    rt.event(rid, "fetch")
    rt.event(rid, "fulfilled")
    rt.flush()

    rows = read_requests(path)
    assert len(rows) == 1
    row = rows[0]
    assert row["rid"] == rid
    assert row["outcome"] == "fulfilled" and row["cause"] is None
    assert row["seed"] == 7 and row["psi"] == 0.8 and row["batch"] == 3
    assert row["e2e_ms"] == pytest.approx(35.0)
    assert [e["kind"] for e in row["events"]] == [
        "submitted", "admitted", "popped", "batched", "map_dispatch",
        "synth", "fetch", "fulfilled"]
    assert row["events"][0]["t_ms"] == 0.0
    assert row["events"][3]["bucket"] == 4           # attrs ride the event
    # no private bookkeeping keys leak into the artifact
    assert not any(k.startswith("_") for k in row)
    # the artifact passes its own schema lint
    assert check_requests(path) == []
    # the trace left the active table and landed in the ring
    assert rt.active_rids() == []
    assert rt.recent()[0]["rid"] == rid


def test_terminal_cause_and_distinct_outcomes(tmp_path):
    clk = FakeClock()
    rt = make_tracer(clk)
    path = str(tmp_path / "requests.jsonl")
    rt.configure(path, chrome_events=False)
    outcomes = {}
    for kind, cause in (("shed", "overloaded"), ("expired", "deadline"),
                        ("cancelled", "client"), ("failed", "Boom")):
        rid = rt.begin(seed=1)
        clk.advance(0.001)
        rt.event(rid, kind, cause=cause)
        outcomes[rid] = (kind, cause)
    rt.flush()
    rows = {r["rid"]: r for r in read_requests(path)}
    assert len(rows) == 4
    for rid, (kind, cause) in outcomes.items():
        assert rows[rid]["outcome"] == kind
        assert rows[rid]["cause"] == cause
    assert check_requests(path) == []


def test_active_table_eviction_counts_dropped(tmp_path):
    clk = FakeClock()
    rt = make_tracer(clk)
    rt.configure(None, max_active=2, chrome_events=False)
    before = counter_value("reqtrace/dropped_total")
    r1 = rt.begin()
    r2 = rt.begin()
    r3 = rt.begin()                 # evicts r1 (oldest-first)
    assert counter_value("reqtrace/dropped_total") == before + 1
    assert rt.active_rids() == [r2, r3]
    # a late event against the evicted trace is ignored, never a crash
    rt.event(r1, "fulfilled")
    assert rt.recent() == []
    rt.event(r2, "fulfilled")
    rt.event(r3, "fulfilled")
    assert [r["rid"] for r in rt.recent()] == [r2, r3]


def test_ledger_cap_counts_dropped_rows(tmp_path):
    clk = FakeClock()
    rt = make_tracer(clk)
    path = str(tmp_path / "requests.jsonl")
    rt.configure(path, max_ledger_rows=2, chrome_events=False)
    rows_before = counter_value("reqtrace/ledger_rows_total")
    drop_before = counter_value("reqtrace/ledger_dropped_total")
    for _ in range(3):
        rid = rt.begin()
        rt.event(rid, "fulfilled")
    rt.flush()
    assert len(read_requests(path)) == 2          # bound held
    assert counter_value("reqtrace/ledger_rows_total") == rows_before + 2
    assert counter_value("reqtrace/ledger_dropped_total") == drop_before + 1
    assert len(rt.recent()) == 3                  # the ring still has all


def test_disabled_tracer_is_a_noop():
    clk = FakeClock()
    rt = make_tracer(clk)
    rt.configure(None, enabled=False)
    before = counter_value("reqtrace/requests_total")
    assert rt.begin(seed=1) is None
    rt.event(None, "fulfilled")                   # must not raise
    assert counter_value("reqtrace/requests_total") == before
    assert rt.recent() == []
    # the explicit marker: disabled is a declared state, not absence
    assert telemetry.gauge("reqtrace/enabled").value == 0.0
    rt.configure(None, enabled=True)
    assert telemetry.gauge("reqtrace/enabled").value == 1.0


# --- Chrome async events ----------------------------------------------------

def test_chrome_async_events_merge_into_span_sink(tmp_path):
    events_path = str(tmp_path / "events.jsonl")
    tracer = get_tracer()
    tracer.configure(events_path, process_index=0)
    try:
        clk = FakeClock()
        rt = make_tracer(clk)
        rt.configure(None)
        rid = rt.begin(seed=3)
        clk.advance(0.002)
        rt.event(rid, "batched", batch=1)
        clk.advance(0.004)
        rt.event(rid, "fulfilled")
        rt.batch_span(batch=1, bucket=4, rids=[rid, None], t0=clk.t,
                      dur_s=0.004)
        tracer.flush()
    finally:
        tracer.configure(None)
    events = [json.loads(l) for l in open(events_path) if l.strip()]
    req = [e for e in events if e.get("cat") == "req"]
    # begin / per-event instant / end, all correlated by the request id
    assert [e["ph"] for e in req] == ["b", "n", "e"]
    assert all(e["id"] == rid for e in req)
    assert req[1]["args"]["kind"] == "batched"
    assert req[2]["args"]["outcome"] == "fulfilled"
    batch = [e for e in events if e.get("name") == "serve_batch"]
    assert len(batch) == 1 and batch[0]["ph"] == "X"
    assert batch[0]["args"]["rids"] == [rid]      # None rids filtered
    # the merged file passes the events schema (async phases included)
    assert check_events(events_path) == []


def test_check_events_grades_async_phases(tmp_path):
    path = str(tmp_path / "events.jsonl")
    base = {"name": "request", "ts": 1.0, "pid": 0, "tid": 1}
    with open(path, "w") as f:
        f.write(json.dumps({**base, "ph": "b", "id": "r1-1"}) + "\n")
        f.write(json.dumps({**base, "ph": "b"}) + "\n")           # no id
        f.write(json.dumps({**base, "ph": "X"}) + "\n")           # no dur
        f.write(json.dumps({**base, "ph": "Z", "id": "r1-1"}) + "\n")
    errors = check_events(path)
    assert len(errors) == 3
    assert any("missing 'id'" in e for e in errors)
    assert any("missing 'dur'" in e for e in errors)
    assert any("ph='Z'" in e for e in errors)


# --- readers / renderers ----------------------------------------------------

def test_read_requests_tolerates_torn_lines(tmp_path):
    path = str(tmp_path / "requests.jsonl")
    row = {"rid": "r1-1", "outcome": "fulfilled", "cause": None,
           "e2e_ms": 5.0, "t_wall": 1.0,
           "events": [{"kind": "submitted", "t_ms": 0.0},
                      {"kind": "fulfilled", "t_ms": 5.0}]}
    with open(path, "w") as f:
        f.write(json.dumps(row) + "\n")
        f.write(json.dumps({**row, "rid": "r1-2"}) + "\n")
        f.write('{"rid": "r1-3", "outco')          # killed mid-append
    assert [r["rid"] for r in read_requests(path)] == ["r1-1", "r1-2"]
    # the schema checker tolerates ONLY the final torn line
    assert check_requests(path) == []
    with open(path, "w") as f:
        f.write('{"torn mid')
        f.write("\n" + json.dumps(row) + "\n")
    assert any("not JSON" in e for e in check_requests(path))


def test_check_requests_catches_schema_violations(tmp_path):
    path = str(tmp_path / "requests.jsonl")
    good = {"rid": "r1-1", "outcome": "fulfilled", "cause": None,
            "e2e_ms": 5.0,
            "events": [{"kind": "submitted", "t_ms": 0.0},
                       {"kind": "fulfilled", "t_ms": 5.0}]}
    bad_rows = [
        {**good, "rid": "r1-2", "outcome": "shed", "cause": None,
         "events": [{"kind": "submitted", "t_ms": 0.0},
                    {"kind": "shed", "t_ms": 1.0}]},   # shed w/o cause
        {**good, "rid": "r1-3", "outcome": "vanished"},
        {**good, "rid": "r1-4",
         "events": [{"kind": "submitted", "t_ms": 3.0},
                    {"kind": "fulfilled", "t_ms": 1.0}]},  # non-monotone
        {**good, "rid": "r1-1"},                       # duplicate rid
    ]
    with open(path, "w") as f:
        for row in [good] + bad_rows:
            f.write(json.dumps(row) + "\n")
    errors = check_requests(path)
    assert any("without a cause" in e for e in errors)
    assert any("outside" in e and "vanished" in e for e in errors)
    assert any("not monotone" in e for e in errors)
    assert any("duplicate terminal row" in e for e in errors)


def test_check_requests_prom_cross_checks(tmp_path):
    path = str(tmp_path / "requests.jsonl")
    row = {"rid": "r1-1", "outcome": "fulfilled", "cause": None,
           "e2e_ms": 5.0,
           "events": [{"kind": "submitted", "t_ms": 0.0},
                      {"kind": "fulfilled", "t_ms": 5.0}]}
    with open(path, "w") as f:
        f.write(json.dumps(row) + "\n")
    prom = str(tmp_path / "telemetry.prom")

    def write_prom(ledgered, dropped, served):
        with open(prom, "w") as f:
            f.write(f"reqtrace_ledger_rows_total {ledgered}\n"
                    f"reqtrace_ledger_dropped_total {dropped}\n"
                    f"serve_requests_total {served}\n")

    write_prom(1, 0, 1)
    assert check_requests(path, prom_path=prom) == []
    write_prom(5, 0, 1)                 # rows lost outside the bound
    assert any("rows were lost" in e
               for e in check_requests(path, prom_path=prom))
    write_prom(5, 4, 1)                 # ...but declared overflow is fine
    assert check_requests(path, prom_path=prom) == []
    write_prom(1, 0, 0)                 # ledger vs prom from different runs
    assert any("different runs" in e
               for e in check_requests(path, prom_path=prom))


def test_render_timeline_is_readable():
    row = {"rid": "r9-1", "seed": 4, "psi": 0.7, "batch": 2,
           "outcome": "failed", "cause": "Boom", "e2e_ms": 12.5,
           "events": [{"kind": "submitted", "t_ms": 0.0},
                      {"kind": "batched", "t_ms": 3.0, "bucket": 4},
                      {"kind": "failed", "t_ms": 12.5, "cause": "Boom"}]}
    text = render_timeline(row)
    assert "r9-1" in text and "cause=Boom" in text and "batch=2" in text
    lines = text.splitlines()
    assert len(lines) == 4 and "bucket=4" in lines[2]


# --- the requests CLI subcommand --------------------------------------------

def test_cli_requests_summary_and_filters(tmp_path, capsys):
    from gansformer_tpu.cli.telemetry import main as cli_main

    d = tmp_path / "run"
    d.mkdir()
    rows = []
    for i, e2e in enumerate((5.0, 50.0, 500.0), 1):
        rows.append({"rid": f"r1-{i}", "outcome": "fulfilled",
                     "cause": None, "e2e_ms": e2e, "t_wall": 1.0,
                     "events": [{"kind": "submitted", "t_ms": 0.0},
                                {"kind": "fulfilled", "t_ms": e2e}]})
    rows.append({"rid": "r1-4", "outcome": "shed", "cause": "overloaded",
                 "e2e_ms": 0.1, "t_wall": 1.0,
                 "events": [{"kind": "submitted", "t_ms": 0.0},
                            {"kind": "shed", "t_ms": 0.1}]})
    with open(d / "requests.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    # an exemplar pointing at the slowest request makes the default view
    # resolve the p99 number to a concrete timeline
    with open(d / "telemetry.prom", "w") as f:
        f.write("serve_e2e_ms_max 500.0\n"
                "# EXEMPLAR serve_e2e_ms_max r1-3\n")

    with pytest.raises(SystemExit) as exc:
        cli_main(["requests", str(d)])
    assert exc.value.code == 0
    out = capsys.readouterr().out
    assert "fulfilled" in out and "shed" in out
    assert "r1-3" in out                       # exemplar resolved

    with pytest.raises(SystemExit) as exc:
        cli_main(["requests", str(d), "--id", "r1-4"])
    assert exc.value.code == 0
    assert "cause=overloaded" in capsys.readouterr().out

    with pytest.raises(SystemExit) as exc:
        cli_main(["requests", str(d), "--worst", "1"])
    assert exc.value.code == 0
    out = capsys.readouterr().out
    assert "r1-3" in out and "r1-1" not in out

    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit) as exc:
        cli_main(["requests", str(empty)])
    assert exc.value.code == 1                 # no ledger → exit 1
