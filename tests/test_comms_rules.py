"""Fixture tests for the graftcomms rules (ISSUE 6): partition-contract
and collective-flow each get FIRES cases (seeded defects — the
deliberately mis-specced donated leaf and the deliberate full-param
all-gather from the acceptance criteria), QUIET cases, and
suppression + baseline handling — mirroring tests/test_trace_rules.py
for the ISSUE 4 rule families.  The pure helpers (HLO collective
parsing, the ring wire-bytes model, the ranked table and the scaling
prediction) are unit-tested on synthetic inputs.

Fixture functions live in THIS file so findings anchor on real source
lines here (inline ``# graftlint: disable=`` on the anchored line
suppresses)."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from gansformer_tpu.analysis.baseline import Baseline
from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, def_site, line_text)
from gansformer_tpu.analysis.trace.collective_flow import (
    CollectiveFlowRule, comms_record, parse_collectives,
    ranked_comms_table, scaling_report, scaling_efficiency, wire_bytes)
from gansformer_tpu.analysis.trace.partition_contract import (
    PartitionContractRule)
from gansformer_tpu.parallel import contracts
from gansformer_tpu.parallel.contracts import Contract

MAT = jax.ShapeDtypeStruct((8, 64), np.float32)
BIGP = jax.ShapeDtypeStruct((64, 4096), np.float32)      # 1 MiB params
BIGX = jax.ShapeDtypeStruct((8, 512, 1024), np.float32)  # 16 MiB batch
SMALLP = jax.ShapeDtypeStruct((64,), np.float32)
GIANTO = jax.ShapeDtypeStruct((2048, 1024), np.float32)  # 8 MiB opt leaf

STATE_CONTRACT = Contract(args=("state",), outs=("state",))
FSDP_CONTRACT = Contract(args=("params", "batch"), outs=("stat",),
                         role_specs={"params": P("data")})


def ep_for(fn, *abstract_args, jit_kwargs=None, **fields):
    jitted = jax.jit(fn, **(jit_kwargs or {}))
    path, line = def_site(jitted)
    return EntryPoint(name=f"fixture.{fn.__name__}", fn=jitted,
                      abstract_args=abstract_args, path=path, line=line,
                      **fields)


def run_one(rule_cls, ep, mesh_sizes=(2,)):
    ctx = TraceContext(mesh_sizes=mesh_sizes)
    rule_cls().check(ep, ctx)
    return ctx.findings, ctx


def roundtrip_baseline(rule_cls, make_ep, tmp_path):
    findings, _ = run_one(rule_cls, make_ep())
    assert findings

    def text_of(f):
        return line_text(f.path, f.line)

    bl = str(tmp_path / "baseline.json")
    Baseline.write(bl, findings, text_of)
    fresh, _ = run_one(rule_cls, make_ep())
    Baseline.load(bl).apply(fresh, text_of)
    assert all(f.baselined and not f.new for f in fresh)


# --- partition-contract -----------------------------------------------------

def _resharding_donor(s):
    # the deliberately mis-specced donated leaf: contract says
    # replicated, the program pins the donated output to the data axis
    return jax.lax.with_sharding_constraint(s + 1.0, P("data"))


def _resharding_donor_suppressed(s):  # graftlint: disable=partition-contract — fixture: suppression contract
    return jax.lax.with_sharding_constraint(s + 1.0, P("data"))


def _stable_donor(s):
    return s + 1.0


def _donor_ep(fn):
    return ep_for(fn, MAT, jit_kwargs={"donate_argnums": (0,)},
                  donate_argnums=(0,), contract=STATE_CONTRACT)


def test_partition_contract_fires_on_misspecced_donated_leaf():
    findings, ctx = run_one(PartitionContractRule,
                            _donor_ep(_resharding_donor))
    assert len(findings) == 1 and findings[0].new
    assert "donated-leaf output" in findings[0].message
    assert "contract says" in findings[0].message
    assert not ctx.notes


def test_partition_contract_quiet_on_conforming_program():
    findings, ctx = run_one(PartitionContractRule,
                            _donor_ep(_stable_donor), mesh_sizes=(2, 4))
    assert findings == [] and not ctx.notes


def test_partition_contract_flags_declared_input_conflict():
    """Contract-sharded lowering pins the inputs, so an entry whose jit
    DECLARES a conflicting in_sharding cannot silently win — the
    conflict surfaces as a lowering-failed finding."""
    from jax.sharding import NamedSharding

    env = contracts.simulated_mesh(2)
    jitted = jax.jit(lambda x: x * 2.0,
                     in_shardings=NamedSharding(env.mesh, P()))
    path, line = def_site(jitted)
    ep = EntryPoint(name="fixture.repl_pinned", fn=jitted,
                    abstract_args=(MAT,), path=path, line=line,
                    contract=Contract(args=("batch",), outs=("batch",)))
    findings, _ = run_one(PartitionContractRule, ep)
    assert len(findings) == 1 and findings[0].new
    assert "lowering failed" in findings[0].message


def test_partition_contract_no_contract_is_a_note_not_a_pass():
    ep = ep_for(_stable_donor, MAT)          # fixture name → no catalog hit
    findings, ctx = run_one(PartitionContractRule, ep)
    assert findings == []
    assert any("no sharding contract" in n for n in ctx.notes)


def test_partition_contract_needs_devices_note():
    ep = _donor_ep(_stable_donor)
    findings, ctx = run_one(PartitionContractRule, ep,
                            mesh_sizes=(64,))
    assert findings == []
    assert any("64-device mesh" in n for n in ctx.notes)


def test_partition_contract_suppressed():
    findings, _ = run_one(PartitionContractRule,
                          _donor_ep(_resharding_donor_suppressed))
    assert len(findings) == 1
    assert findings[0].suppressed and not findings[0].new


def test_partition_contract_baselined(tmp_path):
    roundtrip_baseline(PartitionContractRule,
                       lambda: _donor_ep(_resharding_donor), tmp_path)


# --- collective-flow --------------------------------------------------------

def _full_gatherer(p, x):
    # the deliberate full-param all-gather (missed-FSDP pattern):
    # params sharded over data, compute consumes them FULL every call
    full = jax.lax.with_sharding_constraint(p, P())
    return (x @ full).sum()


def _full_gatherer_suppressed(p, x):  # graftlint: disable=collective-flow — fixture: suppression contract
    full = jax.lax.with_sharding_constraint(p, P())
    return (x @ full).sum()


def _sharded_consumer(p, x):
    # consumes p SHARDED (elementwise + partial reduction): the FSDP
    # layout pays a scalar all-reduce, never a gather
    return (p * p).sum() + x.mean()


def _activation_reducer(p, x):
    # all-reduce of a 2 MiB activation (batch-mean over the sharded
    # axis) against a 256 B param tree — bigger than any gradient
    return x.mean(axis=0).sum() + p.sum()


def _opt_reader(o, x):
    return x.sum() + o.mean()


def test_collective_flow_fires_on_full_param_all_gather():
    ep = ep_for(_full_gatherer, BIGP, MAT, contract=FSDP_CONTRACT)
    findings, ctx = run_one(CollectiveFlowRule, ep)
    assert any("full-param all-gather" in f.message and f.new
               for f in findings)
    # and the comms table recorded the gather
    assert ctx.comms[0]["collectives"]["all-gather"]["count"] >= 1


def test_collective_flow_quiet_on_sharded_consumption():
    ep = ep_for(_sharded_consumer, BIGP, MAT, contract=FSDP_CONTRACT)
    findings, ctx = run_one(CollectiveFlowRule, ep)
    assert findings == []
    assert "all-gather" not in ctx.comms[0]["collectives"]


def test_collective_flow_fires_on_oversized_all_reduce():
    ep = ep_for(_activation_reducer, SMALLP, BIGX,
                contract=Contract(args=("params", "batch"),
                                  outs=("stat",)))
    findings, _ = run_one(CollectiveFlowRule, ep)
    assert any("exceeds the TOTAL params bytes" in f.message and f.new
               for f in findings)


def test_collective_flow_fires_on_replicated_opt_state():
    ep = ep_for(_opt_reader, GIANTO, MAT,
                contract=Contract(args=("opt_state", "batch"),
                                  outs=("stat",)))
    findings, _ = run_one(CollectiveFlowRule, ep)
    assert any("opt-state leaf" in f.message and "fully replicated"
               in f.message and f.new for f in findings)


def test_collective_flow_single_device_records_but_never_flags():
    ep = ep_for(_full_gatherer, BIGP, MAT, contract=FSDP_CONTRACT)
    findings, ctx = run_one(CollectiveFlowRule, ep, mesh_sizes=(1,))
    assert findings == []
    assert len(ctx.comms) == 1 and ctx.comms[0]["devices"] == 1


def test_collective_flow_suppressed():
    ep = ep_for(_full_gatherer_suppressed, BIGP, MAT,
                contract=FSDP_CONTRACT)
    findings, _ = run_one(CollectiveFlowRule, ep)
    assert findings and all(f.suppressed and not f.new for f in findings)


def test_collective_flow_baselined(tmp_path):
    roundtrip_baseline(
        CollectiveFlowRule,
        lambda: ep_for(_full_gatherer, BIGP, MAT, contract=FSDP_CONTRACT),
        tmp_path)


def _replicated_trainer(s):
    # a "train step" whose compute never touches a sharded batch: no
    # all-reduce anywhere — the ISSUE 7 replicated-compute defect
    return s * 0.99 + 1.0


def _replicated_trainer_suppressed(s):  # graftlint: disable=collective-flow — fixture: suppression contract
    return s * 0.99 + 1.0


def _reducing_trainer(p, x):
    # gradient-shaped: a mean over the sharded batch axis → all-reduce
    return p - 1e-3 * x.mean(axis=0)


def test_collective_flow_fires_on_replicated_train_step():
    """ISSUE 7: a train_step entry compiling to ZERO all-reduces on a
    multi-device data mesh is replicated compute — a finding, not a
    table row."""
    ep = ep_for(_replicated_trainer, MAT, contract=STATE_CONTRACT,
                train_step=True)
    findings, _ = run_one(CollectiveFlowRule, ep)
    assert any("ZERO all-reduces" in f.message and f.new
               for f in findings)


def test_collective_flow_replicated_compute_quiet_cases():
    """The check is train-step-scoped and presence-satisfied: inference
    programs compile collective-free legitimately, and a train step
    with a gradient all-reduce is clean."""
    ep_inf = ep_for(_replicated_trainer, MAT, contract=STATE_CONTRACT)
    findings, _ = run_one(CollectiveFlowRule, ep_inf)
    assert findings == []                      # train_step=False → quiet
    ep_ok = ep_for(_reducing_trainer, SMALLP, MAT,
                   contract=Contract(args=("params", "batch"),
                                     outs=("params",)),
                   train_step=True)
    findings, ctx = run_one(CollectiveFlowRule, ep_ok)
    assert findings == []
    assert ctx.comms[0]["collectives"]["all-reduce"]["count"] >= 1


def test_collective_flow_replicated_compute_suppressed():
    ep = ep_for(_replicated_trainer_suppressed, MAT,
                contract=STATE_CONTRACT, train_step=True)
    findings, _ = run_one(CollectiveFlowRule, ep)
    assert findings and all(f.suppressed and not f.new for f in findings)


def test_rules_share_one_compile_per_entry_mesh():
    """partition-contract and collective-flow compile the SAME
    contract-sharded program — the shared ctx cache must make the
    second rule free (one cache entry per entry×mesh)."""
    ep = ep_for(_stable_donor, MAT, jit_kwargs={"donate_argnums": (0,)},
                donate_argnums=(0,), contract=STATE_CONTRACT)
    ctx = TraceContext(mesh_sizes=(2,))
    PartitionContractRule().check(ep, ctx)
    assert len(ctx._compiled) == 1
    before = dict(ctx._compiled)
    CollectiveFlowRule().check(ep, ctx)
    assert len(ctx._compiled) == 1
    assert ctx._compiled[(ep.name, 2)][0] is before[(ep.name, 2)][0]


# --- pure helpers: HLO parsing, wire model, tables --------------------------

HLO = """
ENTRY %main {
  %ag = f32[64,64]{1,0} all-gather(f32[32,64]{1,0} %p), channel_id=1, replica_groups=[1,2]<=[2], dimensions={0}
  %ar = (f32[16]{0}, bf16[8]{0}) all-reduce(f32[16]{0} %a, bf16[8]{0} %b), replica_groups=[1,4]<=[4], to_apply=%sum
  %rs = f32[8]{0} reduce-scatter(f32[16]{0} %c), replica_groups=[2,2]<=[4], dimensions={0}
  %cp = f32[4]{0} collective-permute(f32[4]{0} %d), source_target_pairs={{0,1}}
  %ars = f32[4]{0} all-reduce-start(f32[4]{0} %e), replica_groups={{0,1},{2,3}}
  %ard = f32[4]{0} all-reduce-done(f32[4]{0} %ars)
  %ags = (f32[32,64]{1,0}, f32[64,64]{1,0}) all-gather-start(f32[32,64]{1,0} %p), replica_groups=[1,2]<=[2], dimensions={0}
  %agd = f32[64,64]{1,0} all-gather-done((f32[32,64]{1,0}, f32[64,64]{1,0}) %ags)
  %user = f32[4]{0} add(f32[4]{0} %cp, f32[4]{0} %cp)
}
"""


def test_parse_collectives_kinds_bytes_groups():
    ops = parse_collectives(HLO, default_group=2)
    kinds = [op["kind"] for op in ops]
    # -done is NOT a second transfer; plain ops and -start both count
    assert kinds == ["all-gather", "all-reduce", "reduce-scatter",
                     "collective-permute", "all-reduce", "all-gather"]
    ag, ar, rs, cp, ars, ags = ops
    assert ag["payload_bytes"] == 64 * 64 * 4 and ag["group"] == 2
    assert ar["payload_bytes"] == 16 * 4 + 8 * 2 and ar["group"] == 4
    assert rs["payload_bytes"] == 8 * 4 * 2      # shard result × group
    assert rs["group"] == 2
    assert cp["payload_bytes"] == 16
    assert ars["group"] == 2                     # {{0,1},{2,3}} groups of 2
    # async all-gather-start: the (operand, result) bundle must not be
    # summed — payload is the gathered FULL tensor only
    assert ags["payload_bytes"] == 64 * 64 * 4


def test_wire_bytes_ring_model():
    assert wire_bytes("all-reduce", 1000, 2) == 1000       # 2N(g-1)/g
    assert wire_bytes("all-reduce", 1000, 4) == 1500
    assert wire_bytes("all-gather", 1000, 4) == 750        # N(g-1)/g
    assert wire_bytes("reduce-scatter", 1000, 4) == 750
    assert wire_bytes("collective-permute", 1000, 4) == 1000
    assert wire_bytes("all-reduce", 1000, 1) == 0          # no peers


def test_comms_record_and_ranked_table():
    ops = parse_collectives(HLO, default_group=2)
    rec2 = comms_record("e1", 2, ops, {"params": 7, "opt_state": 3})
    rec4 = comms_record("e1", 4, ops, {"params": 7, "opt_state": 3})
    quiet = comms_record("e0", 4, [], {})
    assert rec2["param_bytes"] == 7 and rec2["opt_state_bytes"] == 3
    assert rec2["collectives"]["all-reduce"]["count"] == 2
    table = ranked_comms_table([rec2, quiet, rec4])
    assert [r["entry"] for r in table] == ["e1", "e0"]   # ranked by wire
    assert table[0]["devices"] == 4                      # largest mesh wins


def test_scaling_report_ring_extrapolation():
    ops = [{"kind": "all-reduce", "payload_bytes": 1000,
            "wire_bytes_per_device": 1000, "group": 2}]
    rec = comms_record("e", 2, ops, {})
    rep = scaling_report([rec], chip_counts=(1, 2, 4, 64))
    assert rep["e"]["1"] == 0
    assert rep["e"]["2"] == 1000
    assert rep["e"]["4"] == 1500
    assert rep["e"]["64"] == int(2 * 1000 * 63 / 64)  # → 2N asymptote


def test_scaling_efficiency_floor_model():
    assert scaling_efficiency(0, 0.01, 1e9) == 1.0
    eff = scaling_efficiency(10_000_000, 0.01, 1e9)   # 10ms comms, 10ms step
    assert abs(eff - 0.5) < 1e-9
    assert scaling_efficiency(1, 0.0, 1e9) == 0.0


# --- contracts (parallel/contracts.py) --------------------------------------

def test_state_leaf_roles_cover_train_state_fields():
    import jax.tree_util as jtu

    class K:         # stand-in for GetAttrKey
        def __init__(self, name):
            self.name = name

    assert contracts.state_leaf_role((K("g_params"), K("w"))) == "params"
    assert contracts.state_leaf_role((K("ema_params"),)) == "params"
    assert contracts.state_leaf_role((K("d_opt"), K("mu"))) == "opt_state"
    assert contracts.state_leaf_role((K("w_avg"),)) == "stat"
    assert contracts.state_leaf_role(()) == "stat"


def test_every_catalog_entry_has_a_contract():
    """The loud-coverage satellite: every short name the entry-point
    catalog registers resolves a contract (build_entry_points raises
    otherwise — pinned by the structural gate in test_trace_clean)."""
    for short in ("d_step", "d_step_r1", "g_step", "g_step_pl", "cycle",
                  "sample", "ppl_pairs"):
        assert contracts.contract_for(f"steps.{short}[tiny-f32]") \
            is not None
    assert contracts.contract_for("fixture.whatever") is None


def test_contract_arity_mismatch_raises():
    with pytest.raises(ValueError):
        contracts.arg_leaf_contracts(STATE_CONTRACT, (MAT, MAT))
    with pytest.raises(ValueError):
        contracts.sharded_abstract_args(
            STATE_CONTRACT, (MAT, MAT), contracts.simulated_mesh(2))


def test_sharded_abstract_args_annotates_by_role():
    env = contracts.simulated_mesh(2)
    c = Contract(args=("params", "batch", "scalar"), outs=("batch",))
    p, b, s = contracts.sharded_abstract_args(c, (SMALLP, MAT, 3), env)
    assert p.sharding.spec == P()
    assert b.sharding.spec == P("data")
    assert s == 3                                     # scalars untouched


def test_out_leaf_contracts_state_then_stat_tail():
    state = {"g_params": {"w": MAT}, "w_avg": SMALLP}
    c = Contract(args=("state",), outs=("state", "stat"))
    out = contracts.out_leaf_contracts(c, (state,), 4)
    roles = [r for _, r, _ in out]
    assert roles == ["params", "stat", "stat", "stat"]
    assert out[0][0].startswith("state:")
    assert out[-1][0] == "out[3]"


def test_unknown_role_raises():
    with pytest.raises(KeyError):
        Contract(args=("nonsense",), outs=("stat",)).spec_for("nonsense")
