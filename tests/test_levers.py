"""Acceptance contracts for the flag-gated MFU levers (ISSUE 5).

Every lever is held to its contract on the CPU mesh before it may claim
tunnel minutes: ``pl_batch_shrink`` — expectation-parity at shrink=1 and
strictly lower cost-analysis FLOPs as the shrink grows; ``r1_batch_shrink``
— slice semantics match an explicit penalty on the slice, the main D loss
is untouched, FLOPs strictly lower; ``attn_fused_kv`` — EXACT math under
weight concatenation.  The A/B pricing harness (scripts/ab_levers.py) is
covered by its pure helpers + a slow-marked end-to-end run."""

import dataclasses
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gansformer_tpu.core.config import get_preset
from gansformer_tpu.losses.gan import r1_penalty, r1_slice
from gansformer_tpu.train.state import create_train_state
from gansformer_tpu.train.steps import make_train_steps
from gansformer_tpu.utils.benchcheck import flops_of
from tests.test_train import micro_cfg

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _with_train(cfg, **kv):
    return dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, **kv))


def _phase_flops(cfg, phase):
    # the same shared lowering the measurement scripts use
    from gansformer_tpu.utils.benchcheck import lower_phase

    return flops_of(lower_phase(cfg, phase))


def _host_params(tree):
    # np.array (copy=True), NOT np.asarray: on CPU an asarray view can
    # alias the jax buffer, and the step below DONATES the state — the
    # "pre-step copy" would silently mutate under the donation.
    return jax.tree_util.tree_map(lambda x: np.array(x), tree)


# --- r1_slice / config plumbing (pure) ----------------------------------

def test_r1_slice_unit():
    x = jnp.arange(8.0)[:, None]
    assert r1_slice(x, 1) is x
    np.testing.assert_array_equal(r1_slice(x, 2), np.arange(4.0)[:, None])
    np.testing.assert_array_equal(r1_slice(x, 4), np.arange(2.0)[:, None])
    with pytest.raises(AssertionError):
        r1_slice(x, 3)                  # non-divisible must fail loudly


def test_config_validates_r1_batch_shrink():
    cfg = _with_train(micro_cfg(), r1_batch_shrink=3)   # 8 % 3 != 0
    with pytest.raises(ValueError, match="r1_batch_shrink"):
        cfg.validate()
    with pytest.raises(ValueError, match="r1_batch_shrink"):
        _with_train(micro_cfg(), r1_batch_shrink=0).validate()
    _with_train(micro_cfg(), r1_batch_shrink=2).validate()


def test_config_validates_pl_batch_shrink_range():
    """A typo'd --pl-batch-shrink 0 must fail loudly, not silently run
    the most expensive (full-probe) variant via steps.py's max(1, ·)."""
    with pytest.raises(ValueError, match="pl_batch_shrink"):
        _with_train(micro_cfg(), pl_batch_shrink=0).validate()
    with pytest.raises(ValueError, match="pl_batch_shrink"):
        _with_train(micro_cfg(), pl_batch_shrink=-2).validate()
    with pytest.raises(ValueError, match="pl_batch_shrink"):
        _with_train(micro_cfg(), pl_batch_shrink=3).validate()  # 8 % 3
    _with_train(micro_cfg(), pl_batch_shrink=4).validate()


def test_cli_lever_flags_round_trip():
    from gansformer_tpu.cli.train import build_parser, config_from_args

    args = build_parser().parse_args(
        ["--preset", "clevr64-simplex", "--batch-size", "8",
         "--pl-batch-shrink", "4", "--r1-batch-shrink", "2",
         "--attn-fused-kv"])
    cfg = config_from_args(args)
    assert cfg.train.pl_batch_shrink == 4
    assert cfg.train.r1_batch_shrink == 2
    assert cfg.model.attn_fused_kv is True
    # defaults: levers OFF / reference values, tri-state inherits
    args = build_parser().parse_args(["--preset", "clevr64-simplex"])
    cfg = config_from_args(args)
    assert cfg.train.pl_batch_shrink == 2       # reference default
    assert cfg.train.r1_batch_shrink == 1       # lever off
    assert cfg.model.attn_fused_kv is False     # lever off
    args = build_parser().parse_args(
        ["--preset", "clevr64-simplex", "--no-attn-fused-kv"])
    assert config_from_args(args).model.attn_fused_kv is False


def test_flagship_preset_defaults_keep_levers_off():
    t = get_preset("ffhq256-duplex").train
    assert t.r1_batch_shrink == 1
    assert t.pl_batch_shrink == 2               # StyleGAN2 reference value
    assert get_preset("ffhq256-duplex").model.attn_fused_kv is False


# --- attn_fused_kv: exact parity under weight concatenation -------------

def test_attn_fused_kv_parity():
    """Fused K∥V projection must be the SAME function: build both
    variants, assemble the fused weights from the unfused ones by column
    concatenation, and require matching outputs (grid AND latents)."""
    from gansformer_tpu.models.attention import BipartiteAttention

    kw = dict(grid_dim=16, latent_dim=16, duplex=True, integration="both",
              kmeans_iters=1, pos_encoding="sinusoidal")
    m0 = BipartiteAttention(fused_kv=False, **kw)
    m1 = BipartiteAttention(fused_kv=True, **kw)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 8, 8, 16), jnp.float32)
    y = jnp.asarray(rs.randn(2, 3, 16), jnp.float32)
    k = jax.random.PRNGKey(0)
    p0 = m0.init(k, x, y)["params"]
    p1 = jax.tree_util.tree_map(np.copy, m1.init(k, x, y)["params"])

    def fuse(a, b):
        return {"w": np.concatenate([p0[a]["w"], p0[b]["w"]], axis=1),
                "b": np.concatenate([p0[a]["b"], p0[b]["b"]])}

    for name in p1:
        if name == "kv_y":
            p1[name] = fuse("k_y", "v_y")
        elif name.endswith("_kv_x"):
            stem = name[:-len("_kv_x")]
            p1[name] = fuse(f"{stem}_k_x", f"{stem}_v_x")
        else:
            p1[name] = p0[name]

    g0, y0 = m0.apply({"params": p0}, x, y)
    g1, y1 = m1.apply({"params": p1}, x, y)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-5)


def test_attn_fused_kv_builds_through_model_config():
    """The config flag reaches both G and D attention blocks: the fused
    param names exist, the unfused ones are gone."""
    import dataclasses as dc

    cfg = micro_cfg(attention="duplex")
    cfg = dc.replace(cfg, model=dc.replace(cfg.model, attn_fused_kv=True))
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    flat = {"/".join(str(getattr(k, "key", k)) for k in path)
            for path, _ in jax.tree_util.tree_leaves_with_path(
                state.g_params)}
    assert any("kv_y" in p for p in flat)
    assert not any("/k_y/" in p or "/v_y/" in p for p in flat)


# --- pl_batch_shrink ----------------------------------------------------

class TestPlBatchShrink:
    def test_flops_strictly_lower_as_shrink_grows(self):
        cfg = micro_cfg()
        fl = {s: _phase_flops(_with_train(cfg, pl_batch_shrink=s), "g_pl")
              for s in (1, 2, 4)}
        assert fl[1] and fl[2] and fl[4]
        assert fl[2] < fl[1], fl
        assert fl[4] < fl[2], fl

    def test_expectation_parity_at_shrink_1(self):
        """At shrink=1 the probe is the full fresh batch and the penalty
        must equal an explicit path_length_penalty evaluated with the
        same rng derivation — no hidden rescaling from the lever."""
        from gansformer_tpu.losses.gan import path_length_penalty
        from gansformer_tpu.models.generator import Generator

        cfg = _with_train(micro_cfg(), pl_batch_shrink=1)
        fns = make_train_steps(cfg, batch_size=cfg.train.batch_size)
        state = create_train_state(cfg, jax.random.PRNGKey(0))
        g_params = _host_params(state.g_params)   # state is donated below
        pl_mean0 = float(state.pl_mean)
        rng = jax.random.PRNGKey(123)
        _, aux = fns.g_step_pl(state, rng)

        G = Generator(cfg.model)
        k_pl, k_plnoise = jax.random.split(jax.random.fold_in(rng, 3))
        z_pl = jax.random.normal(
            k_pl, (cfg.train.batch_size, cfg.model.num_ws,
                   cfg.model.latent_dim), jnp.float32)
        ws_pl = G.apply({"params": g_params}, z_pl, None,
                        method=Generator.map)

        def synth(w):
            return G.apply({"params": g_params}, w,
                           rngs={"noise": jax.random.fold_in(rng, 4)},
                           method=Generator.synthesize)

        pl, _ = path_length_penalty(synth, ws_pl, jnp.asarray(pl_mean0),
                                    k_plnoise, cfg.train.pl_decay)
        np.testing.assert_allclose(float(aux["Loss/G/pl"]), float(pl),
                                   rtol=1e-4)

    def test_main_g_loss_untouched_by_shrink(self):
        """The adversarial term must be identical across shrink settings
        (the lever only touches the PL probe)."""
        auxes = {}
        for s in (1, 2):
            cfg = _with_train(micro_cfg(), pl_batch_shrink=s)
            fns = make_train_steps(cfg, batch_size=cfg.train.batch_size)
            state = create_train_state(cfg, jax.random.PRNGKey(0))
            _, auxes[s] = fns.g_step_pl(state, jax.random.PRNGKey(123))
        np.testing.assert_allclose(float(auxes[1]["Loss/G"]),
                                   float(auxes[2]["Loss/G"]), rtol=1e-5)


# --- r1_batch_shrink ----------------------------------------------------

class TestR1BatchShrink:
    def test_flops_strictly_lower_at_shrink_2(self):
        cfg = micro_cfg()
        fl1 = _phase_flops(_with_train(cfg, r1_batch_shrink=1), "d_r1")
        fl2 = _phase_flops(_with_train(cfg, r1_batch_shrink=2), "d_r1")
        assert fl1 and fl2
        assert fl2 < fl1, (fl1, fl2)

    def test_slice_semantics_and_main_loss_parity(self):
        """With the lever armed the logged penalty equals an explicit
        r1_penalty on the first half of the normalized batch (unbiased
        slice, weight unchanged); the main D loss matches the unsliced
        step exactly (same reals/fakes/scores)."""
        from gansformer_tpu.data.dataset import normalize_images
        from gansformer_tpu.models.discriminator import Discriminator

        imgs = np.random.RandomState(1).randint(
            0, 255, (8, 16, 16, 3)).astype(np.uint8)
        rng = jax.random.PRNGKey(7)
        auxes = {}
        d_params_host = None
        for s in (1, 2):
            cfg = _with_train(micro_cfg(), r1_batch_shrink=s)
            fns = make_train_steps(cfg, batch_size=cfg.train.batch_size)
            state = create_train_state(cfg, jax.random.PRNGKey(0))
            if d_params_host is None:
                d_params_host = _host_params(state.d_params)
            _, auxes[s] = fns.d_step_r1(state, jnp.asarray(imgs), rng)
        np.testing.assert_allclose(float(auxes[1]["Loss/D"]),
                                   float(auxes[2]["Loss/D"]), rtol=1e-5)

        D = Discriminator(micro_cfg().model)
        reals = normalize_images(jnp.asarray(imgs))
        manual = r1_penalty(
            lambda x: D.apply({"params": d_params_host}, x),
            r1_slice(reals, 2))
        np.testing.assert_allclose(float(auxes[2]["Loss/D/r1"]),
                                   float(manual), rtol=1e-4)


# --- ab_levers harness --------------------------------------------------

def test_ab_levers_catalog_covers_the_wired_levers():
    ab = _load_script("ab_levers")
    catalog = {lv["name"]: lv for lv in ab.lever_catalog()}
    assert set(catalog) == {"pl_batch_shrink", "r1_batch_shrink",
                            "attn_fused_kv", "conv_fused_mod"}
    for lv in catalog.values():
        settings = [s for s, _ in lv["variants"]]
        assert lv["baseline"] in settings
        assert lv["phase"] in ("d", "d_r1", "g", "g_pl")
        assert "tests/test_levers.py" in lv["test"]
    # catalog transforms really flip the config fields
    cfg = micro_cfg()
    assert catalog["pl_batch_shrink"]["variants"][2][1](
        cfg).train.pl_batch_shrink == 4
    assert catalog["attn_fused_kv"]["variants"][1][1](
        cfg).model.attn_fused_kv is True
    assert catalog["conv_fused_mod"]["variants"][1][1](
        cfg).model.conv_backend == "pallas"


def test_conv_fused_mod_parity():
    """Acceptance anchor of the conv_fused_mod lever (ISSUE 14): the
    'on' variant is the SAME math — generator outputs agree across
    conv backends on identical params (the deep parity battery lives in
    tests/test_pallas_conv.py; this pins the lever's config contract +
    that the flipped config validates and changes only the backend)."""
    ab = _load_script("ab_levers")
    catalog = {lv["name"]: lv for lv in ab.lever_catalog()}
    on = catalog["conv_fused_mod"]["variants"][1][1](micro_cfg())
    off = catalog["conv_fused_mod"]["variants"][0][1](micro_cfg())
    on.validate(), off.validate()
    assert on.model.conv_backend == "pallas"
    assert dataclasses.replace(on.model, conv_backend="xla") == off.model

    from gansformer_tpu.models.generator import Generator

    rng = np.random.RandomState(0)
    z = jnp.asarray(rng.randn(2, on.model.num_ws, on.model.latent_dim),
                    jnp.float32)
    noise = jax.random.PRNGKey(3)
    G_off = Generator(off.model)
    params = G_off.init({"params": jax.random.PRNGKey(0), "noise": noise},
                        z)
    out_off = G_off.apply(params, z, rngs={"noise": noise})
    out_on = Generator(on.model).apply(params, z, rngs={"noise": noise})
    np.testing.assert_allclose(np.asarray(out_on), np.asarray(out_off),
                               atol=1e-4, rtol=1e-4)


def test_ab_levers_delta_attachment_pure():
    ab = _load_script("ab_levers")
    lever = {"name": "x", "baseline": "1",
             "variants": [{"setting": "1", "gflops": 10.0, "ms": 5.0,
                           "gbytes": 2.0, "temp_gib": 1.0},
                          {"setting": "2", "gflops": 7.5, "ms": 4.0,
                           "gbytes": 1.5, "temp_gib": 0.8},
                          {"setting": "err", "error": "boom"}]}
    out = ab.attach_deltas(lever)
    v1, v2, verr = out["variants"]
    assert v1["is_baseline"] and not v2["is_baseline"]
    assert v2["delta_gflops"] == -2.5 and v2["delta_ms"] == -1.0
    assert "delta_gflops" not in verr           # errors carry no deltas


@pytest.mark.slow   # compiles micro g_pl three times end-to-end
def test_ab_levers_script_end_to_end_cpu(tmp_path):
    ab = _load_script("ab_levers")
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(micro_cfg().to_json())
    out = tmp_path / "ab.json"
    rc = ab.main(["--config", str(cfg_path), "--batch", "8", "--iters",
                  "1", "--levers", "pl_batch_shrink",
                  "--json-out", str(out)])
    assert rc == 0
    art = json.load(open(out))
    (lever,) = art["levers"]
    by_setting = {v["setting"]: v for v in lever["variants"]}
    assert by_setting["2"]["is_baseline"]
    # CPU run: FLOPs deltas exact, ms null
    assert by_setting["1"]["delta_gflops"] > 0
    assert by_setting["4"]["delta_gflops"] < 0
    assert by_setting["4"]["ms"] is None


# --- ffhq1024 readiness stage (pure core) -------------------------------

def test_readiness_fit_verdict_pure():
    rd = _load_script("readiness_ffhq1024")
    v = rd.fit_verdict(state_gib=0.93, temp_gib=16.85, hbm_gib=32.0)
    assert v["fits"] is True and v["margin_gib"] == pytest.approx(14.22)
    v = rd.fit_verdict(state_gib=0.93, temp_gib=16.85, hbm_gib=16.0)
    assert v["fits"] is False
    assert rd.fit_verdict(0.93, None, 16.0)["fits"] is None
    assert rd.fit_verdict(0.93, 1.0, None)["fits"] is None


def test_readiness_hbm_table():
    rd = _load_script("readiness_ffhq1024")

    class Dev:
        device_kind = "TPU v5 lite"

        def memory_stats(self):
            return {}

    assert rd.hbm_limit_gib(Dev()) == 16.0

    class Dev4(Dev):
        device_kind = "TPU v4"

        def memory_stats(self):
            return {"bytes_limit": 34088157184}

    assert rd.hbm_limit_gib(Dev4()) == pytest.approx(31.75, abs=0.01)


@pytest.mark.slow   # compiles d_r1/g_pl twice (batch 2 and 4)
def test_readiness_script_end_to_end_cpu(tmp_path):
    rd = _load_script("readiness_ffhq1024")
    out = tmp_path / "ready.json"
    rc = rd.main(["--preset", "clevr64-simplex", "--batches", "2,4",
                  "--json-out", str(out)])
    assert rc == 0
    art = json.load(open(out))
    assert art["meta"]["regime"].startswith("cpu-lowering")
    assert [r["batch"] for r in art["batches"]] == [2, 4]
    for rec in art["batches"]:
        for ph in ("d_r1", "g_pl"):
            assert rec["phases"][ph]["temp_gib"] >= 0
