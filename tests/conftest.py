"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax imports.

This is the JAX answer to "test multi-device without a cluster"
(SURVEY.md §4): every test sees 8 CPU devices, so sharding/collective paths
are exercised for real, just slowly.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Persistent XLA compilation cache: the suite is dominated by second-order
# -grad compiles (R1/PL step variants); repeat runs and the sanitized
# subprocess children (multihost, dryrun) reuse them.  Keyed by HLO hash,
# so source edits invalidate exactly what they change.
import sys as _sys

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from gansformer_tpu.utils.hostenv import compile_cache_env  # noqa: E402

for _k, _v in compile_cache_env().items():
    os.environ.setdefault(_k, _v)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def micro_overlap_cfg(total_kimg=3):
    """The shared micro-run config: overlap layer ON (the defaults —
    device prefetch + async writeback), 1-kimg ticks, per-tick snapshots.
    test_device_prefetch trains the same config with the overlap flags
    OFF as its synchronous parity reference."""
    import dataclasses

    from tests.test_train import micro_cfg

    cfg = micro_cfg(attention="simplex", batch=8)
    # device_time_ticks=2: micro_cfg turns the device-truth sampler OFF
    # (suite cost); THIS shared run re-enables it so the ISSUE 8
    # acceptance tests see a landed sample (tick 1 traced) in
    # telemetry.prom without any other test paying for the profiler.
    return dataclasses.replace(
        cfg, train=dataclasses.replace(
            cfg.train, total_kimg=total_kimg, kimg_per_tick=1,
            snapshot_ticks=1, image_snapshot_ticks=1,
            device_time_ticks=2))


@pytest.fixture(scope="session")
def micro_run_dir(tmp_path_factory):
    """ONE short end-to-end training run shared by every test that needs a
    real run dir (tick-loop artifacts, checkpoint resume, pack/distribute,
    the ISSUE 2 overlap acceptance tests — which need ≥3 ticks): compiles
    dominate these tests, so train once per session."""
    from gansformer_tpu.train.loop import train

    cfg = micro_overlap_cfg()
    d = str(tmp_path_factory.mktemp("micro_run"))
    import os

    with open(os.path.join(d, "config.json"), "w") as f:
        f.write(cfg.to_json())
    train(cfg, d)
    return d
