"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax imports.

This is the JAX answer to "test multi-device without a cluster"
(SURVEY.md §4): every test sees 8 CPU devices, so sharding/collective paths
are exercised for real, just slowly.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)
