"""Data pipeline tests."""

import numpy as np
import pytest

from gansformer_tpu.core.config import DataConfig
from gansformer_tpu.data.dataset import (
    NpzDataset,
    SyntheticDataset,
    make_dataset,
    normalize_images,
)


def test_synthetic_batches_shape_and_determinism():
    ds = SyntheticDataset(resolution=32, num_images=100)
    b1 = next(ds.batches(4, seed=7))
    b2 = next(ds.batches(4, seed=7))
    assert b1["image"].shape == (4, 32, 32, 3)
    assert b1["image"].dtype == np.uint8
    np.testing.assert_array_equal(b1["image"], b2["image"])


def test_synthetic_shards_disjoint():
    ds = SyntheticDataset(resolution=16, num_images=100)
    a = next(ds.batches(8, seed=0, shard=(0, 2)))["image"]
    b = next(ds.batches(8, seed=0, shard=(1, 2)))["image"]
    assert not np.array_equal(a, b)


def test_npz_dataset_roundtrip(tmp_path):
    imgs = np.random.RandomState(0).randint(
        0, 255, (20, 16, 16, 3), dtype=np.uint8)
    path = tmp_path / "d.npz"
    np.savez(path, images=imgs)
    ds = NpzDataset(str(path))
    assert ds.resolution == 16 and ds.num_images == 20
    batch = next(ds.batches(5, seed=1))
    assert batch["image"].shape == (5, 16, 16, 3)


def test_npz_with_labels(tmp_path):
    imgs = np.zeros((8, 8, 8, 3), dtype=np.uint8)
    labels = np.eye(8, 4, dtype=np.float32)[np.arange(8) % 4]
    path = tmp_path / "l.npz"
    np.savez(path, images=imgs, labels=labels)
    ds = NpzDataset(str(path))
    assert ds.has_labels and ds.label_dim == 4
    batch = next(ds.batches(4, seed=0))
    assert batch["label"].shape == (4, 4)


def test_make_dataset_dispatch(tmp_path):
    assert isinstance(
        make_dataset(DataConfig(source="synthetic", resolution=16)),
        SyntheticDataset)
    with pytest.raises(ValueError):
        make_dataset(DataConfig(source="nope"))


def test_normalize_images_range():
    x = np.array([[0, 127, 255]], dtype=np.uint8)
    out = np.asarray(normalize_images(x))
    np.testing.assert_allclose(out, [[-1.0, -0.00392157, 1.0]], atol=1e-5)


def test_tfrecord_reader_roundtrip(tmp_path):
    """Write records in the reference's format via TF, read them back."""
    tf = pytest.importorskip("tensorflow")
    from gansformer_tpu.data.dataset import TFRecordDataset

    res = 8
    imgs = np.random.RandomState(0).randint(
        0, 255, (6, 3, res, res), dtype=np.uint8)  # CHW, reference layout
    path = str(tmp_path / f"toy-r{int(np.log2(res)):02d}.tfrecords")
    with tf.io.TFRecordWriter(path) as w:
        for img in imgs:
            ex = tf.train.Example(features=tf.train.Features(feature={
                "shape": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=img.shape)),
                "data": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[img.tobytes()]))}))
            w.write(ex.SerializeToString())
    ds = TFRecordDataset(str(tmp_path))
    assert ds.resolution == res and ds.channels == 3
    batch = next(ds.batches(2, seed=0))
    assert batch["image"].shape == (2, res, res, 3)
    # content round-trips (some image from the set, HWC-transposed)
    originals = {imgs[i].transpose(1, 2, 0).tobytes() for i in range(len(imgs))}
    assert batch["image"][0].tobytes() in originals
