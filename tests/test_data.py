"""Data pipeline tests."""

import os
import numpy as np
import pytest

from gansformer_tpu.core.config import DataConfig
from gansformer_tpu.data.dataset import (
    NpzDataset,
    SyntheticDataset,
    make_dataset,
    normalize_images,
)


def test_synthetic_batches_shape_and_determinism():
    ds = SyntheticDataset(resolution=32, num_images=100)
    b1 = next(ds.batches(4, seed=7))
    b2 = next(ds.batches(4, seed=7))
    assert b1["image"].shape == (4, 32, 32, 3)
    assert b1["image"].dtype == np.uint8
    np.testing.assert_array_equal(b1["image"], b2["image"])


def test_synthetic_shards_disjoint():
    ds = SyntheticDataset(resolution=16, num_images=100)
    a = next(ds.batches(8, seed=0, shard=(0, 2)))["image"]
    b = next(ds.batches(8, seed=0, shard=(1, 2)))["image"]
    assert not np.array_equal(a, b)


def test_npz_dataset_roundtrip(tmp_path):
    imgs = np.random.RandomState(0).randint(
        0, 255, (20, 16, 16, 3), dtype=np.uint8)
    path = tmp_path / "d.npz"
    np.savez(path, images=imgs)
    ds = NpzDataset(str(path))
    assert ds.resolution == 16 and ds.num_images == 20
    batch = next(ds.batches(5, seed=1))
    assert batch["image"].shape == (5, 16, 16, 3)


def test_npz_with_labels(tmp_path):
    imgs = np.zeros((8, 8, 8, 3), dtype=np.uint8)
    labels = np.eye(8, 4, dtype=np.float32)[np.arange(8) % 4]
    path = tmp_path / "l.npz"
    np.savez(path, images=imgs, labels=labels)
    ds = NpzDataset(str(path))
    assert ds.has_labels and ds.label_dim == 4
    batch = next(ds.batches(4, seed=0))
    assert batch["label"].shape == (4, 4)


def test_make_dataset_dispatch(tmp_path):
    assert isinstance(
        make_dataset(DataConfig(source="synthetic", resolution=16)),
        SyntheticDataset)
    with pytest.raises(ValueError):
        make_dataset(DataConfig(source="nope"))


def test_normalize_images_range():
    x = np.array([[0, 127, 255]], dtype=np.uint8)
    out = np.asarray(normalize_images(x))
    np.testing.assert_allclose(out, [[-1.0, -0.00392157, 1.0]], atol=1e-5)


def test_tfrecord_reader_roundtrip(tmp_path):
    """Write records in the reference's format via TF, read them back."""
    tf = pytest.importorskip("tensorflow")
    from gansformer_tpu.data.dataset import TFRecordDataset

    res = 8
    imgs = np.random.RandomState(0).randint(
        0, 255, (6, 3, res, res), dtype=np.uint8)  # CHW, reference layout
    path = str(tmp_path / f"toy-r{int(np.log2(res)):02d}.tfrecords")
    with tf.io.TFRecordWriter(path) as w:
        for img in imgs:
            ex = tf.train.Example(features=tf.train.Features(feature={
                "shape": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=img.shape)),
                "data": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[img.tobytes()]))}))
            w.write(ex.SerializeToString())
    ds = TFRecordDataset(str(tmp_path))
    assert ds.resolution == res and ds.channels == 3
    batch = next(ds.batches(2, seed=0))
    assert batch["image"].shape == (2, res, res, 3)
    # content round-trips (some image from the set, HWC-transposed)
    originals = {imgs[i].transpose(1, 2, 0).tobytes() for i in range(len(imgs))}
    assert batch["image"][0].tobytes() in originals


# --- input-pipeline performance & prefetch (VERDICT r1 item 4) --------------

def _write_toy_records(path, imgs):
    """Hand-framed TFRecords with valid masked CRCs (the native reader
    verifies them; the Python fallback skips them)."""
    from gansformer_tpu.data.tfrecord_writer import (
        encode_example_image, write_record)

    with open(path, "wb") as f:
        for img in imgs:
            write_record(f, encode_example_image(img))


def test_prefetch_iterator_order_and_stop():
    from gansformer_tpu.data.dataset import PrefetchIterator

    src = ({"i": i} for i in range(7))
    with PrefetchIterator(src, depth=2) as it:
        got = [b["i"] for b in it]
    assert got == list(range(7))
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_iterator_propagates_producer_error():
    from gansformer_tpu.data.dataset import PrefetchIterator

    def bad():
        yield {"ok": 1}
        raise RuntimeError("decode failed")

    it = PrefetchIterator(bad(), depth=2)
    assert next(it)["ok"] == 1
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)
    it.close()


def test_tfrecord_shuffle_buffer_and_coverage(tmp_path):
    from gansformer_tpu.data.dataset import TFRecordDataset

    res, n = 8, 32
    imgs = np.arange(n, dtype=np.uint8)[:, None, None, None] * np.ones(
        (n, 3, res, res), np.uint8)
    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"), imgs)

    ds = TFRecordDataset(str(tmp_path), shuffle_buffer=8)
    seen = []
    it = ds.batches(4, seed=0)
    for _ in range(n // 4):  # one epoch
        seen.extend(b[0, 0, 0] for b in next(it)["image"])
    assert sorted(seen) == list(range(n))  # every image exactly once/epoch

    order2 = []
    it2 = ds.batches(4, seed=1)
    for _ in range(n // 4):
        order2.extend(b[0, 0, 0] for b in next(it2)["image"])
    assert seen != order2  # seed changes the shuffle


def test_tfrecord_reader_throughput(tmp_path):
    """Reader floor: a v4-32 DP run at the 200 img/s/chip target needs
    6,400 img/s of 256x256 decode across 32 hosts' worth of chips; a single
    host feeding 8 chips needs 1,600 img/s.  Measured ~6.7k img/s on this
    reader — assert a 1,600 floor so regressions that would starve the mesh
    fail loudly."""
    import time

    from gansformer_tpu.data.dataset import TFRecordDataset

    res, n = 256, 128
    imgs = np.random.RandomState(0).randint(
        0, 255, (n, 3, res, res), np.uint8)
    _write_toy_records(str(tmp_path / "toy-r08.tfrecords"), imgs)

    ds = TFRecordDataset(str(tmp_path), shuffle_buffer=64)
    it = ds.batches(32, seed=0)
    next(it)  # warm OS cache / first fill
    # Best-of-3 windows: a throughput *floor* cares about what the reader can
    # sustain, not what a transiently loaded CI box happened to do once.
    rate = 0.0
    for _ in range(3):
        t0 = time.time()
        count = 0
        for _ in range(20):
            count += len(next(it)["image"])
        rate = max(rate, count / (time.time() - t0))
    # Escape hatch for known-slow machines: GANSFORMER_PERF_FLOOR=0 disables.
    floor = float(os.environ.get("GANSFORMER_PERF_FLOOR", "1600"))
    assert rate > floor, f"reader too slow: {rate:.0f} img/s @ 256x256"


# --- TFRecord writer (VERDICT r2 item 5) ------------------------------------

def test_tfrecord_writer_roundtrip_own_reader(tmp_path):
    """Writer → reader round-trip in the reference's multi-lod layout."""
    from gansformer_tpu.data.dataset import TFRecordDataset
    from gansformer_tpu.data.tfrecord_writer import TFRecordExporter

    res, n = 16, 10
    imgs = np.random.RandomState(0).randint(
        0, 255, (n, res, res, 3), dtype=np.uint8)
    labels = np.eye(n, 5, dtype=np.float32)[np.arange(n) % 5]
    with TFRecordExporter(str(tmp_path), "toy", res) as ex:
        for img in imgs:
            ex.add_image(img)
        ex.add_labels(labels)
    # full pyramid written: r02..r04
    for lod in (2, 3, 4):
        assert (tmp_path / f"toy-r{lod:02d}.tfrecords").exists()

    ds = TFRecordDataset(str(tmp_path), resolution=res)
    assert ds.resolution == res and ds.has_labels and ds.label_dim == 5
    batch = next(ds.batches(4, seed=0))
    assert batch["image"].shape == (4, res, res, 3)
    assert batch["label"].shape == (4, 5)
    originals = {imgs[i].tobytes() for i in range(n)}
    assert batch["image"][0].tobytes() in originals

    # lower lod holds box-downsampled images at the right resolution
    ds2 = TFRecordDataset(str(tmp_path), resolution=8)
    assert ds2.resolution == 8


def test_tfrecord_writer_crc_and_tf_compat(tmp_path):
    """Files must carry valid masked CRC32C framing — i.e. be readable by
    stock tf.data exactly as the reference would read them."""
    tf = pytest.importorskip("tensorflow")
    from gansformer_tpu.data.tfrecord_writer import TFRecordExporter

    res = 8
    imgs = np.random.RandomState(1).randint(
        0, 255, (4, res, res, 3), dtype=np.uint8)
    with TFRecordExporter(str(tmp_path), "toy", res,
                          all_lods=False) as ex:
        for img in imgs:
            ex.add_image(img)
    path = str(tmp_path / "toy-r03.tfrecords")
    got = []
    for rec in tf.data.TFRecordDataset([path]):  # validates framing CRCs
        ex2 = tf.train.Example.FromString(rec.numpy())
        f = ex2.features.feature
        shape = list(f["shape"].int64_list.value)
        data = f["data"].bytes_list.value[0]
        got.append(np.frombuffer(data, np.uint8).reshape(shape))
    assert len(got) == 4
    np.testing.assert_array_equal(got[0], imgs[0].transpose(2, 0, 1))


def test_crc32c_known_vectors():
    """CRC32C (Castagnoli) check against published test vectors (RFC 3720)."""
    from gansformer_tpu.data.tfrecord_writer import crc32c

    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_cifar10_loader(tmp_path):
    import pickle

    rs = np.random.RandomState(0)
    for i in range(1, 6):
        batch = {b"data": rs.randint(0, 255, (20, 3072), dtype=np.uint8)
                 .astype(np.uint8),
                 b"labels": list(rs.randint(0, 10, 20))}
        with open(tmp_path / f"data_batch_{i}", "wb") as f:
            pickle.dump(batch, f)
    from gansformer_tpu.data.tfrecord_writer import load_cifar10

    images, labels = load_cifar10(str(tmp_path))
    assert images.shape == (100, 32, 32, 3) and images.dtype == np.uint8
    assert labels.shape == (100, 10)
    np.testing.assert_allclose(labels.sum(axis=1), 1.0)


def test_prepare_data_cli_tfrecord(tmp_path):
    """CLI end-to-end: synthetic → reference-format tfrecords → trainable
    dataset (the 'convert and train from the flagship preset's native
    format' contract)."""
    from gansformer_tpu.cli.prepare_data import main as prep
    from gansformer_tpu.data.dataset import TFRecordDataset

    out = str(tmp_path / "synth")
    prep(["--synthetic", "--to", "tfrecord", "--out", out,
          "--resolution", "16", "--max-images", "12"])
    ds = TFRecordDataset(out, resolution=16)
    batch = next(ds.batches(4, seed=0))
    assert batch["image"].shape == (4, 16, 16, 3)


# --- native host-ops (gansformer_tpu/native) ---------------------------------

def test_native_host_ops_parity(tmp_path):
    """C++ scan/parse/CRC agree with the Python implementations and with
    the writer's output; reader transparently uses the native path."""
    from gansformer_tpu import native
    from gansformer_tpu.data import tfrecord_writer as w
    from gansformer_tpu.data.dataset import TFRecordDataset

    if native.get_lib() is None:
        pytest.skip("no C++ toolchain in this environment")

    # RFC 3720 vectors through the native path
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA

    imgs = np.random.RandomState(3).randint(
        0, 255, (6, 16, 16, 3), dtype=np.uint8)
    with w.TFRecordExporter(str(tmp_path), "n", 16, all_lods=False) as ex:
        for im in imgs:
            ex.add_image(im)
    buf = (tmp_path / "n-r04.tfrecords").read_bytes()
    offs, lens, consumed = native.scan_records(buf, verify_crc=True)
    assert len(offs) == 6 and consumed == len(buf)
    shape, d_off, d_len = native.parse_example(
        buf[int(offs[0]):int(offs[0]) + int(lens[0])])
    assert shape == (3, 16, 16) and d_len == 3 * 16 * 16

    # corrupt one payload byte → CRC-verified scan raises
    bad = bytearray(buf)
    bad[int(offs[0]) + 5] ^= 0xFF
    with pytest.raises(ValueError, match="corrupt"):
        native.scan_records(bytes(bad), verify_crc=True)

    # hostile u64 length field must neither hang nor read OOB (the
    # pre-fix overflow did both): it reads as a partial tail, consumed=0
    evil = (0xFFFFFFFFFFFFFFF0).to_bytes(8, "little") + b"\0" * 20
    o2, l2, c2 = native.scan_records(evil, verify_crc=False)
    assert len(o2) == 0 and c2 == 0

    # a truncated final record is detected by the streaming reader
    from gansformer_tpu.data.dataset import _iter_tfrecord_raw
    trunc = tmp_path / "trunc.tfrecords"
    trunc.write_bytes(buf[:-3])
    with pytest.raises(ValueError, match="truncated|corrupt"):
        list(_iter_tfrecord_raw(str(trunc)))

    # full reader round-trip rides the native parse
    ds = TFRecordDataset(str(tmp_path), resolution=16)
    batch = next(ds.batches(4, seed=0))
    originals = {im.tobytes() for im in imgs}
    assert batch["image"][0].tobytes() in originals


def test_reader_native_matches_python_fallback(tmp_path, monkeypatch):
    from gansformer_tpu import native as nat
    if nat.get_lib() is None:
        pytest.skip("no C++ toolchain — parity comparison would be vacuous")
    from gansformer_tpu.data import dataset as dsmod
    from gansformer_tpu.data.tfrecord_writer import TFRecordExporter

    imgs = np.random.RandomState(4).randint(
        0, 255, (4, 8, 8, 3), dtype=np.uint8)
    with TFRecordExporter(str(tmp_path), "p", 8, all_lods=False) as ex:
        for im in imgs:
            ex.add_image(im)
    path = str(tmp_path / "p-r03.tfrecords")
    payloads = list(dsmod._iter_tfrecord_raw(path))
    native_out = [dsmod._parse_example_image(p) for p in payloads]

    from gansformer_tpu import native
    monkeypatch.setattr(native, "get_lib", lambda: None)
    python_out = [dsmod._parse_example_image(p) for p in payloads]
    for a, b in zip(native_out, python_out):
        np.testing.assert_array_equal(a, b)


def test_lsun_lmdb_converter_with_stub(tmp_path, monkeypatch):
    """LSUN lmdb → tfrecord path (dataset_tool create_lsun role), driven
    through a stub lmdb module so the gated dependency isn't needed."""
    import io
    import sys
    import types

    from PIL import Image

    rs = np.random.RandomState(5)
    encoded = []
    for i in range(5):
        img = Image.fromarray(rs.randint(0, 255, (20, 30, 3), np.uint8))
        b = io.BytesIO()
        img.save(b, format="PNG")
        encoded.append((f"k{i}".encode(), b.getvalue()))
    encoded.append((b"corrupt", b"not-an-image"))  # skipped, not fatal

    class StubTxn:
        def cursor(self):
            return iter(encoded)
        def __enter__(self):
            return self
        def __exit__(self, *a):
            return False

    class StubEnv:
        def begin(self, write=False):
            return StubTxn()

    stub = types.ModuleType("lmdb")
    stub.open = lambda *a, **k: StubEnv()
    monkeypatch.setitem(sys.modules, "lmdb", stub)

    from gansformer_tpu.cli.prepare_data import main as prep
    from gansformer_tpu.data.dataset import TFRecordDataset

    out = str(tmp_path / "lsun")
    prep(["--lsun-lmdb-dir", "/fake", "--to", "tfrecord", "--out", out,
          "--resolution", "16"])
    ds = TFRecordDataset(out, resolution=16)
    batch = next(ds.batches(4, seed=0))
    assert batch["image"].shape == (4, 16, 16, 3)


def test_lsun_without_lmdb_is_a_clear_error(monkeypatch):
    import builtins
    import sys

    real_import = builtins.__import__

    def no_lmdb(name, *a, **k):
        if name == "lmdb":
            raise ImportError("No module named 'lmdb'")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_lmdb)
    monkeypatch.delitem(sys.modules, "lmdb", raising=False)
    from gansformer_tpu.data.tfrecord_writer import iter_lsun_lmdb

    with pytest.raises(ImportError, match="pip install lmdb"):
        next(iter_lsun_lmdb("/fake", 16))


# --- dataset download path (VERDICT r2 missing #3 tail: downloads) ----------

def _serve_dir(directory):
    """Loopback HTTP server with Range support (http.server has it built
    in); returns (server, base_url)."""
    import functools
    import http.server
    import threading

    handler = functools.partial(
        http.server.SimpleHTTPRequestHandler, directory=directory)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _make_cifar_tarball(path, n=8):
    """A tiny but structurally real cifar-10-python.tar.gz."""
    import pickle
    import tarfile

    rs = np.random.RandomState(0)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".dir"
    os.makedirs(os.path.join(tmp, "cifar-10-batches-py"), exist_ok=True)
    for i in range(1, 6):
        batch = {b"data": rs.randint(0, 255, (n, 3072), np.uint8),
                 b"labels": list(rs.randint(0, 10, n))}
        with open(os.path.join(tmp, "cifar-10-batches-py",
                               f"data_batch_{i}"), "wb") as f:
            pickle.dump(batch, f)
    with tarfile.open(path, "w:gz") as t:
        t.add(os.path.join(tmp, "cifar-10-batches-py"),
              arcname="cifar-10-batches-py")


def test_download_resume_and_sha(tmp_path):
    """data/download.py: stream→.part→atomic rename; Range resume picks up a
    truncated .part; sha mismatch discards the download loudly."""
    from gansformer_tpu.data.download import download, sha256_file

    src_dir = tmp_path / "srv"
    os.makedirs(src_dir)
    payload = np.random.RandomState(1).bytes(300_000)
    (src_dir / "blob.bin").write_bytes(payload)
    srv, base = _serve_dir(str(src_dir))
    try:
        dest = str(tmp_path / "dl" / "blob.bin")
        sha = sha256_file(str(src_dir / "blob.bin"))
        # interrupted: pre-seed a truncated .part, then resume
        os.makedirs(os.path.dirname(dest))
        with open(dest + ".part", "wb") as f:
            f.write(payload[:100_000])
        download(f"{base}/blob.bin", dest, sha256=sha)
        assert open(dest, "rb").read() == payload
        assert not os.path.exists(dest + ".part")
        # corrupt: wrong sha discards and raises
        dest2 = str(tmp_path / "dl" / "blob2.bin")
        with pytest.raises(IOError, match="sha256 mismatch"):
            download(f"{base}/blob.bin", dest2, sha256="0" * 64)
        assert not os.path.exists(dest2)
        assert not os.path.exists(dest2 + ".part")
    finally:
        srv.shutdown()


def test_prepare_data_download_cifar(tmp_path):
    """--download cifar10 --mirror-url <loopback> end-to-end → npz readable
    by the framework's reader (SURVEY.md §3.4 download path)."""
    from gansformer_tpu.cli.prepare_data import main as prep
    from gansformer_tpu.data.dataset import NpzDataset

    srv_dir = tmp_path / "mirror"
    _make_cifar_tarball(str(srv_dir / "cifar-10-python.tar.gz"))
    srv, base = _serve_dir(str(srv_dir))
    try:
        out = str(tmp_path / "out" / "cifar.npz")
        # The registry sha256 is enforced even against a mirror: this toy
        # tarball is not the real CIFAR archive, so without the explicit
        # opt-out the download must be rejected.
        with pytest.raises(IOError, match="sha256 mismatch"):
            prep(["--download", "cifar10", "--mirror-url", base,
                  "--out", out, "--resolution", "32"])
        prep(["--download", "cifar10", "--mirror-url", base,
              "--download-no-verify", "--out", out, "--resolution", "32"])
        ds = NpzDataset(out)
        assert ds.resolution == 32 and ds.label_dim == 10
        batch = next(ds.batches(8, seed=0))
        assert batch["image"].shape == (8, 32, 32, 3)
    finally:
        srv.shutdown()


def test_download_truncated_stream_is_not_complete(tmp_path):
    """A connection dropped mid-stream must raise, keep the .part for
    resume, and never rename to the final name (ADVICE r3: entries without
    a registry sha256 relied on nothing but luck here)."""
    import http.server
    import threading

    from gansformer_tpu.data.download import download

    payload = np.random.RandomState(2).bytes(200_000)

    class Truncating(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("ETag", '"v1"')
            self.end_headers()
            self.wfile.write(payload[:50_000])   # then drop the connection
            self.wfile.flush()
            self.connection.close()

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Truncating)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        dest = str(tmp_path / "blob.bin")
        with pytest.raises(Exception) as e:
            download(f"http://127.0.0.1:{srv.server_address[1]}/blob.bin",
                     dest)
        # either our completeness check or httplib's IncompleteRead —
        # both are loud; what matters is no silent half-file under `dest`
        assert not os.path.exists(dest), e
        assert os.path.exists(dest + ".part")
        # the resume validator was recorded at first byte
        assert open(dest + ".part.meta").read().strip() == '"v1"'
    finally:
        srv.shutdown()


def test_download_manual_datasets_refuse():
    from gansformer_tpu.data.download import fetch_dataset

    with pytest.raises(SystemExit, match="cityscapes-dataset.com"):
        fetch_dataset("cityscapes", "/tmp/nope")
    with pytest.raises(SystemExit, match="ffhq-dataset"):
        fetch_dataset("ffhq", "/tmp/nope")


def test_native_scanner_fuzz_hostile_bytes():
    """The C++ frame scanner and proto walker must never crash, hang, or
    over-read on corrupt/hostile input — random mutations of valid records
    plus adversarial length fields either parse or fail cleanly (the
    overflow-safe bounds the native layer advertises)."""
    from gansformer_tpu import native
    from gansformer_tpu.data import tfrecord_writer as w

    if native.get_lib() is None:
        pytest.skip("no C++ toolchain in this environment")

    rs = np.random.RandomState(0)
    img = rs.randint(0, 255, (3, 8, 8), np.uint8)
    payload = w.encode_example_image(img)
    import io

    buf = io.BytesIO()
    for _ in range(4):
        w.write_record(buf, payload)
    good = buf.getvalue()

    # 200 random single/multi-byte corruptions of the valid stream
    for trial in range(200):
        data = bytearray(good)
        for _ in range(rs.randint(1, 4)):
            data[rs.randint(0, len(data))] = rs.randint(0, 256)
        try:
            offs, lens, consumed = native.scan_records(
                bytes(data), verify_crc=True)
        except ValueError:
            continue  # clean rejection is fine
        assert consumed <= len(data)
        for o, l in zip(offs, lens):
            assert 0 <= o and o + l <= len(data)  # no over-read windows
            native.parse_example(bytes(data[o:o + l]))  # may be None

    # adversarial length fields: huge u64, truncations, zero-length.
    # Fuzzed under BOTH CRC modes — verify_crc=False is the mode that
    # trusts the raw length field, so it is where an over-read would live
    # (with verify_crc=True most corruptions die at the CRC check before
    # the bounds assertions run).
    import struct

    hostile = [
        struct.pack("<Q", 2**63) + b"\x00" * 32,
        struct.pack("<Q", len(good) * 10) + good[8:],
        good[: len(good) // 2],
        struct.pack("<Q", 0) + b"\x00" * 8,
        b"\x00" * 7,  # shorter than a header
    ]
    for verify in (True, False):
        for data in hostile:
            try:
                offs, lens, consumed = native.scan_records(
                    data, verify_crc=verify)
            except ValueError:
                continue
            assert consumed <= len(data)
            for o, l in zip(offs, lens):
                assert 0 <= o and o + l <= len(data)

    # random corruptions with CRC checking OFF: every returned window must
    # still be in-bounds, and the proto walker must take any window
    for trial in range(200):
        data = bytearray(good)
        for _ in range(rs.randint(1, 4)):
            data[rs.randint(0, len(data))] = rs.randint(0, 256)
        try:
            offs, lens, consumed = native.scan_records(
                bytes(data), verify_crc=False)
        except ValueError:
            continue
        assert consumed <= len(data)
        for o, l in zip(offs, lens):
            assert 0 <= o and o + l <= len(data)
            try:
                native.parse_example(bytes(data[o:o + l]))
            except ValueError:
                pass  # clean rejection of a corrupt Example is fine

    # proto walker on random garbage payloads: None or clean error only
    for _ in range(200):
        blob = bytes(rs.randint(0, 256, rs.randint(0, 200), np.uint8))
        try:
            native.parse_example(blob)
        except ValueError:
            pass


# --- ISSUE 15: indexed, fault-tolerant TFRecord plane ------------------------

def _tf_counter(name):
    from gansformer_tpu.obs import registry as telemetry

    return telemetry.counter(name).value


@pytest.fixture
def _no_faults():
    from gansformer_tpu.supervise import faults

    faults.disarm()
    yield
    faults.disarm()


def _id_imgs(ids, res=8):
    """CHW uint8 images whose every pixel encodes the image id."""
    return [np.full((3, res, res), i, np.uint8) for i in ids]


def test_tfrecord_multi_shard_reads_all_files(tmp_path):
    """Satellite 1: a sharded dataset's shard files are ONE logical
    source — the pre-fix reader kept only files[-1]."""
    from gansformer_tpu.data.dataset import TFRecordDataset

    _write_toy_records(str(tmp_path / "a-r03.tfrecords"), _id_imgs(range(16)))
    _write_toy_records(str(tmp_path / "b-r03.tfrecords"),
                       _id_imgs(range(16, 32)))
    ds = TFRecordDataset(str(tmp_path))
    assert ds.num_images == 32
    assert len(ds.files) == 2
    seen = []
    it = ds.batches(4, seed=0)
    for _ in range(8):               # one epoch
        seen.extend(int(b[0, 0, 0]) for b in next(it)["image"])
    assert sorted(seen) == list(range(32))  # both shards, exactly once


def test_tfrecord_seek_matches_scan(tmp_path):
    """Satellite 4 (non-slow half): start_batch=N reproduces the full
    stream's batch N onward exactly — across epoch boundaries — by
    advancing the RNG stream only (the resume-exact contract)."""
    from gansformer_tpu.data.dataset import TFRecordDataset

    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"),
                       _id_imgs(range(32)))
    ds = TFRecordDataset(str(tmp_path))
    ref = [next(it)["image"] for it in [ds.batches(4, seed=5)]
           for _ in range(20)]       # 20 batches = 2.5 epochs (per_epoch 8)
    for start in (3, 8, 11):         # mid-epoch, boundary, next epoch
        resumed = ds.batches(4, seed=5, start_batch=start)
        for want in ref[start:]:
            np.testing.assert_array_equal(want, next(resumed)["image"])


def test_tfrecord_index_sidecar_built_and_refreshed(tmp_path):
    """The record-offset index persists beside the file and is rebuilt
    when the file's (mtime, size) signature changes."""
    from gansformer_tpu.data.dataset import (
        TFRecordDataset, _index_path)
    from gansformer_tpu.data.tfrecord_writer import (
        encode_example_image, write_record)

    path = str(tmp_path / "toy-r03.tfrecords")
    _write_toy_records(path, _id_imgs(range(6)))
    ds = TFRecordDataset(str(tmp_path))
    assert ds.num_images == 6
    assert os.path.exists(_index_path(path))
    # grow the file: the stale sidecar must not hide the new records
    with open(path, "ab") as f:
        for img in _id_imgs(range(6, 8)):
            write_record(f, encode_example_image(img))
    ds2 = TFRecordDataset(str(tmp_path))
    assert ds2.num_images == 8


def test_tfrecord_garbage_proto_quarantined_under_budget(tmp_path):
    """A record whose framing/CRC is valid but whose proto is garbage is
    QUARANTINED (ledger line + counter), the batch slot is re-filled
    deterministically, and the stream keeps flowing."""
    import json

    from gansformer_tpu.data.dataset import TFRecordDataset
    from gansformer_tpu.data.tfrecord_writer import write_record

    path = str(tmp_path / "toy-r03.tfrecords")
    _write_toy_records(path, _id_imgs(range(16)))
    with open(path, "ab") as f:
        write_record(f, b"\x05not-a-proto")   # valid framing, bad proto
    before = _tf_counter("data/corrupt_records_total")
    ds = TFRecordDataset(str(tmp_path), max_corrupt_frac=0.2)
    ledger = str(tmp_path / "data_quarantine.jsonl")
    ds.set_quarantine_ledger(ledger)
    assert ds.num_images == 17           # CRC-valid → in the index
    seen = set()
    it = ds.batches(4, seed=0)
    for _ in range(12):                  # ~3 epochs
        seen.update(int(b[0, 0, 0]) for b in next(it)["image"])
    assert seen == set(range(16))        # every good image still flows
    assert _tf_counter("data/corrupt_records_total") == before + 1
    recs = [json.loads(l) for l in open(ledger)]
    assert len(recs) == 1 and recs[0]["file"] == path
    assert "cause" in recs[0] and "offset" in recs[0]
    # determinism: the substitute mapping is stable, so two streams with
    # the same seed agree batch for batch (resume-exact on a static defect)
    a = ds.batches(4, seed=9)
    b = TFRecordDataset(str(tmp_path), max_corrupt_frac=0.2).batches(
        4, seed=9)
    for _ in range(8):
        np.testing.assert_array_equal(next(a)["image"], next(b)["image"])


def test_tfrecord_payload_crc_quarantined_at_index_build(tmp_path):
    """Native path: a flipped payload byte fails the per-record CRC at
    index build — the record lands in the sidecar's bad list, not the
    addressable set, and the rest of the file stays readable."""
    from gansformer_tpu import native
    from gansformer_tpu.data.dataset import TFRecordDataset, build_record_index

    if native.get_lib() is None:
        pytest.skip("no C++ toolchain — CRC verification is native-only")
    path = str(tmp_path / "toy-r03.tfrecords")
    _write_toy_records(path, _id_imgs(range(8)))
    offs, lens, _ = native.scan_records(open(path, "rb").read(),
                                        verify_crc=True)
    data = bytearray(open(path, "rb").read())
    data[int(offs[3]) + 7] ^= 0xFF       # corrupt record 3's payload
    open(path, "wb").write(bytes(data))

    idx = build_record_index(path)
    assert len(idx["offsets"]) == 7
    assert [c for _, _, c in idx["bad"]] == ["payload-crc"]
    ds = TFRecordDataset(str(tmp_path), max_corrupt_frac=0.2)
    assert ds.num_images == 7
    seen = set()
    it = ds.batches(7, seed=0)
    seen.update(int(b[0, 0, 0]) for b in next(it)["image"])
    assert seen == set(range(8)) - {3}


def test_tfrecord_over_budget_raises_typed(tmp_path):
    """Acceptance (c) unit: past max_corrupt_frac the failure is TYPED
    (DataCorrupt), not a generic crash — at init when the index already
    shows the breach, at stream time when decode failures cross it."""
    from gansformer_tpu.data.dataset import TFRecordDataset
    from gansformer_tpu.data.errors import DataCorrupt
    from gansformer_tpu.data.tfrecord_writer import write_record

    path = str(tmp_path / "toy-r03.tfrecords")
    _write_toy_records(path, _id_imgs(range(16)))
    with open(path, "ab") as f:
        write_record(f, b"\x05not-a-proto")
    ds = TFRecordDataset(str(tmp_path), max_corrupt_frac=0.0)
    it = ds.batches(4, seed=0)
    with pytest.raises(DataCorrupt, match="max_corrupt_frac"):
        for _ in range(12):
            next(it)


def test_tfrecord_read_retry_via_fault(tmp_path, _no_faults):
    """A transient read error (injected at the data_read_error point)
    retries under bounded backoff: the counter moves, the stream is
    unaffected."""
    from gansformer_tpu.data.dataset import TFRecordDataset
    from gansformer_tpu.supervise import faults

    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"),
                       _id_imgs(range(16)))
    ds = TFRecordDataset(str(tmp_path), io_retry_base_s=0.01)
    ref = [next(it)["image"] for it in [ds.batches(4, seed=1)]
           for _ in range(4)]
    faults.arm(faults.parse_specs("raise@data_read_error:n=6"))
    before = _tf_counter("data/read_retries_total")
    got = [next(it)["image"] for it in [ds.batches(4, seed=1)]
           for _ in range(4)]
    assert _tf_counter("data/read_retries_total") == before + 1
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)


def test_tfrecord_read_error_exhausts_retries(tmp_path, monkeypatch):
    """A PERSISTENT read error surfaces as an OSError after the bounded
    retries (with the counter recording every attempt)."""
    from gansformer_tpu.data.dataset import TFRecordDataset

    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"),
                       _id_imgs(range(8)))
    ds = TFRecordDataset(str(tmp_path), io_retries=2, io_retry_base_s=0.01)

    def broken_pread(fd, n, off):
        raise OSError("EIO: injected")

    before = _tf_counter("data/read_retries_total")
    monkeypatch.setattr(os, "pread", broken_pread)
    with pytest.raises(OSError, match="failed after 3 attempt"):
        next(ds.batches(4, seed=0))
    assert _tf_counter("data/read_retries_total") == before + 2


def test_prefetch_stall_watchdog_raises_typed():
    """ISSUE 15 tentpole 3: a producer that stops making progress trips
    the watchdog with typed DataStalled well before any heartbeat-
    staleness kill."""
    import time as _time

    from gansformer_tpu.data.dataset import PrefetchIterator
    from gansformer_tpu.data.errors import DataStalled

    def stalling():
        yield {"i": 0}
        _time.sleep(30.0)
        yield {"i": 1}

    before = _tf_counter("data/stalls_total")
    with PrefetchIterator(stalling(), depth=1, stall_after_s=0.3) as it:
        assert next(it)["i"] == 0
        t0 = _time.monotonic()
        with pytest.raises(DataStalled, match="no progress"):
            next(it)
        assert _time.monotonic() - t0 < 10.0
    assert _tf_counter("data/stalls_total") == before + 1


def test_prefetch_no_watchdog_by_default():
    from gansformer_tpu.data.dataset import PrefetchIterator

    src = ({"i": i} for i in range(3))
    with PrefetchIterator(src, depth=1) as it:
        assert [b["i"] for b in it] == [0, 1, 2]


def test_device_prefetch_stall_watchdog():
    import time as _time

    from gansformer_tpu.data.device_prefetch import DevicePrefetcher
    from gansformer_tpu.data.errors import DataStalled

    def stalling():
        yield {"i": 0}
        _time.sleep(30.0)

    pf = DevicePrefetcher(stalling(), lambda x: x, depth=1,
                          stall_after_s=0.3)
    try:
        assert pf.get()["i"] == 0
        with pytest.raises(DataStalled, match="transfer thread"):
            pf.get()
    finally:
        pf.close()


def test_data_slow_read_hang_fault_trips_watchdog(tmp_path, _no_faults):
    """The data_slow_read fault point + the watchdog close the loop: a
    hung read thread becomes a fast typed verdict instead of a silent
    data_wait block."""
    from gansformer_tpu.data.dataset import PrefetchIterator, TFRecordDataset
    from gansformer_tpu.data.errors import DataStalled
    from gansformer_tpu.supervise import faults

    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"),
                       _id_imgs(range(16)))
    ds = TFRecordDataset(str(tmp_path))
    faults.arm(faults.parse_specs("hang@data_slow_read:n=10"))
    with PrefetchIterator(ds.batches(4, seed=0), depth=1,
                          stall_after_s=0.3) as it:
        with pytest.raises(DataStalled):
            for _ in range(8):
                next(it)


def test_crc_verified_cache_keyed_by_signature(tmp_path):
    """Satellite 2: an overwritten/regenerated file must NOT inherit the
    previous version's 'CRC verified' verdict — the cache key carries
    (mtime, size)."""
    from gansformer_tpu import native
    from gansformer_tpu.data.dataset import _iter_tfrecord_raw

    if native.get_lib() is None:
        pytest.skip("no C++ toolchain — CRC verification is native-only")
    path = str(tmp_path / "v-r03.tfrecords")
    _write_toy_records(path, _id_imgs(range(4)))
    assert len(list(_iter_tfrecord_raw(path))) == 4   # pass 1: verified
    assert len(list(_iter_tfrecord_raw(path))) == 4   # pass 2: light path

    data = bytearray(open(path, "rb").read())
    data[20] ^= 0xFF                                  # corrupt in place
    open(path, "wb").write(bytes(data))
    os.utime(path, ns=(1, 1))                         # force a new signature
    with pytest.raises(ValueError, match="corrupt|truncated"):
        list(_iter_tfrecord_raw(path))


def test_tfrecord_labels_mismatch_raises(tmp_path):
    """Satellite 3: a label array shorter than the record set used to
    wrap silently (idx % len); now it is a loud init-time error."""
    from gansformer_tpu.data.dataset import TFRecordDataset

    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"),
                       _id_imgs(range(8)))
    np.save(str(tmp_path / "toy-rxx.labels"),
            np.eye(5, 4, dtype=np.float32))
    os.rename(str(tmp_path / "toy-rxx.labels.npy"),
              str(tmp_path / "toy-rxx.labels"))
    with pytest.raises(ValueError, match="mis-align"):
        TFRecordDataset(str(tmp_path))


def test_tfrecord_labels_align_across_shards(tmp_path):
    """Labels index the ORIGINAL record order across the whole shard
    set: emitted (image, label) pairs must agree even through shuffling
    and multi-file reads."""
    from gansformer_tpu.data.dataset import TFRecordDataset

    ids = list(range(12))
    _write_toy_records(str(tmp_path / "a-r03.tfrecords"), _id_imgs(ids[:6]))
    _write_toy_records(str(tmp_path / "b-r03.tfrecords"), _id_imgs(ids[6:]))
    labels = np.zeros((12, 12), np.float32)
    labels[np.arange(12), np.arange(12)] = 1.0        # one-hot of the id
    np.save(str(tmp_path / "ab-rxx.labels"), labels)
    os.rename(str(tmp_path / "ab-rxx.labels.npy"),
              str(tmp_path / "ab-rxx.labels"))
    ds = TFRecordDataset(str(tmp_path))
    assert ds.has_labels and ds.label_dim == 12
    it = ds.batches(4, seed=2)
    for _ in range(6):
        batch = next(it)
        for img, lbl in zip(batch["image"], batch["label"]):
            assert int(np.argmax(lbl)) == int(img[0, 0, 0])


def test_tfrecord_resolution_miss_falls_back_to_one_lod_group(tmp_path):
    """A --resolution with no matching shard falls back to the highest
    single-lod group (the pre-index files[-1] spirit) — never a MIX of
    lods, which the shape check would read as mass corruption."""
    from gansformer_tpu.data.dataset import TFRecordDataset

    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"),
                       _id_imgs(range(8), res=8))
    _write_toy_records(str(tmp_path / "toy-r02.tfrecords"),
                       _id_imgs(range(8), res=4))
    ds = TFRecordDataset(str(tmp_path), resolution=64)   # no -r06 shard
    assert [os.path.basename(f) for f in ds.files] == ["toy-r03.tfrecords"]
    assert ds.resolution == 8 and ds.num_images == 8     # no quarantines
    next(ds.batches(4, seed=0))


def test_tfrecord_close_releases_fds(tmp_path):
    from gansformer_tpu.data.dataset import TFRecordDataset

    _write_toy_records(str(tmp_path / "toy-r03.tfrecords"),
                       _id_imgs(range(8)))
    ds = TFRecordDataset(str(tmp_path))
    next(ds.batches(4, seed=0))
    assert ds._fds                       # a cached fd from the reads
    fd = next(iter(ds._fds.values()))
    ds.close()
    assert not ds._fds
    with pytest.raises(OSError):
        os.fstat(fd)                     # really closed
    ds.close()                           # idempotent
    next(ds.batches(4, seed=0))          # and reopenable
