"""FSDP mode (ISSUE 7): the per-leaf placement rule, the runtime
state-sharding derivation, the contract overlay, and the acceptance
criteria — opt-state genuinely sharded through a real train step (no
replicated moment leaves, no full-param all-gather) and loss parity
between the replicated and fsdp layouts.

The cheap shape-only units run in tier-1; everything that compiles a
step program on a mesh is ``slow`` (tier-1's budget is measured in
compile time)."""

import dataclasses

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from gansformer_tpu.core.config import MeshConfig
from gansformer_tpu.parallel import contracts
from gansformer_tpu.parallel.contracts import (
    FSDP, entry_contracts, fsdp_spec, state_shardings)
from gansformer_tpu.parallel.mesh import make_mesh


# --- fsdp_spec: the per-leaf placement rule ---------------------------------

def test_fsdp_spec_shards_largest_divisible_axis():
    assert fsdp_spec((512,), 2) == P("data")
    assert fsdp_spec((3, 3, 64, 128), 2) == P(None, None, None, "data")
    assert fsdp_spec((3, 3, 256, 128), 4) == P(None, None, "data")
    # ties pick the LAST maximal axis (output channels)
    assert fsdp_spec((64, 64), 2) == P(None, "data")


def test_fsdp_spec_replicates_when_nothing_divides():
    assert fsdp_spec((), 2) == P()          # scalars (Adam count)
    assert fsdp_spec((7,), 2) == P()        # odd vector
    assert fsdp_spec((3, 3), 2) == P()
    assert fsdp_spec((512,), 1) == P()      # no data axis → no-op


def test_entry_contracts_fsdp_overlay():
    """entry_contracts(False) IS the base table (tests monkeypatch it);
    the fsdp overlay adds the opt_state sentinel to EVERY entry and the
    sentinel resolves per-leaf only with shape+data_size."""
    assert entry_contracts(False) is contracts.ENTRY_CONTRACTS
    over = entry_contracts(True)
    assert set(over) == set(contracts.ENTRY_CONTRACTS)
    for name, c in over.items():
        assert c.role_specs["opt_state"] == FSDP, name
        # shape-blind resolution: no expectation, not a crash
        assert c.spec_for("opt_state") is None
        assert c.spec_for("opt_state", (512,), 2) == P("data")
        # other roles unchanged
        assert c.spec_for("params") == P()


def test_contract_for_fsdp_flag():
    base = contracts.contract_for("steps.g_step[tiny-f32]")
    over = contracts.contract_for("steps.g_step[tiny-f32]", fsdp=True)
    assert base.role_specs is None or "opt_state" not in base.role_specs
    assert over.role_specs["opt_state"] == FSDP


def test_state_shardings_derivation():
    """The runtime placement (loop.py device_put target) shards exactly
    the divisible opt-state leaves and replicates everything else —
    derived from the same role logic the contracts assert."""
    from gansformer_tpu.analysis.trace.entry_points import (
        _abstract_state, tiny_config)

    cfg = tiny_config()
    state = _abstract_state(cfg)
    env = make_mesh(MeshConfig(data=2), devices=jax.devices()[:2])

    repl = state_shardings(state, env, fsdp=False)
    assert all(s.is_fully_replicated
               for s in jax.tree_util.tree_leaves(repl))

    sh = state_shardings(state, env, fsdp=True)
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    shards = jax.tree_util.tree_leaves(sh)
    assert len(flat) == len(shards)
    n_sharded = 0
    for (path, leaf), s in zip(flat, shards):
        role = contracts.state_leaf_role(path)
        if role != "opt_state":
            assert s.is_fully_replicated, path
        elif fsdp_spec(getattr(leaf, "shape", ()), 2) == P():
            assert s.is_fully_replicated, path   # scalars/odd leaves
        else:
            assert not s.is_fully_replicated, path
            n_sharded += 1
    assert n_sharded > 10      # the moment trees really shard


def test_mesh_config_fsdp_validation_in_words():
    from gansformer_tpu.analysis.trace.entry_points import tiny_config

    cfg = tiny_config()
    bad = dataclasses.replace(cfg, mesh=MeshConfig(data=1, fsdp=True))
    with pytest.raises(ValueError, match="no data axis to shard"):
        bad.validate()
    multi = dataclasses.replace(
        cfg, mesh=MeshConfig(data=2, fsdp=True,
                             coordinator_address="h:1",
                             num_processes=2, process_id=0))
    with pytest.raises(ValueError, match="single-host"):
        multi.validate()
    ok = dataclasses.replace(cfg, mesh=MeshConfig(data=2, fsdp=True))
    ok.validate()


def test_train_cli_fsdp_tristate():
    from gansformer_tpu.cli.train import build_parser

    pa = build_parser().parse_args
    assert pa([]).fsdp is None                 # inherit the config
    assert pa(["--fsdp"]).fsdp is True
    assert pa(["--no-fsdp"]).fsdp is False
    assert pa(["--fsdp", "--no-fsdp"]).fsdp is False


# --- acceptance: a real fsdp step on a 2-device mesh ------------------------

@pytest.fixture(scope="module")
def fsdp_vs_replicated():
    """One (d_step, g_step) iteration pair at global batch 8 on a
    2-device data mesh, run twice from identical inits: replicated
    layout vs fsdp layout.  Shared by the parity and sharding tests
    (the compiles dominate)."""
    from tests.test_train import micro_cfg

    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    imgs_np = np.random.RandomState(0).randint(
        0, 255, (8, 16, 16, 3), dtype=np.uint8)
    rng = jax.random.PRNGKey(3)
    out = {}
    for mode in ("replicated", "fsdp"):
        cfg = micro_cfg(batch=8)
        cfg = dataclasses.replace(
            cfg, mesh=MeshConfig(data=2, fsdp=(mode == "fsdp")))
        env = make_mesh(cfg.mesh, devices=jax.devices()[:2])
        state = create_train_state(cfg, jax.random.PRNGKey(0))
        state = jax.device_put(
            state, state_shardings(state, env, fsdp=(mode == "fsdp")))
        fns = make_train_steps(cfg, env, batch_size=8)
        imgs = jax.device_put(imgs_np, env.batch())
        with env.activate():
            state, d_aux = fns.d_step(state, imgs,
                                      jax.random.fold_in(rng, 0))
            state, g_aux = fns.g_step(state, jax.random.fold_in(rng, 1))
            jax.block_until_ready(state.step)
        out[mode] = (env, state, {**d_aux, **g_aux})
    return out


@pytest.mark.slow
def test_fsdp_step_keeps_opt_state_sharded(fsdp_vs_replicated):
    """ISSUE 7 acceptance: after a REAL step, every shardable optimizer
    moment leaf is still sharded over data (the layout survives the
    Adam update — no silent gather-and-stay-replicated), params/EMA
    replicated."""
    env, state, _ = fsdp_vs_replicated["fsdp"]
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    n_sharded = 0
    for path, leaf in flat:
        role = contracts.state_leaf_role(path)
        if role == "opt_state" and \
                fsdp_spec(leaf.shape, env.data_size) != P():
            assert not leaf.sharding.is_fully_replicated, path
            n_sharded += 1
        elif role == "params":
            assert leaf.sharding.is_fully_replicated, path
    assert n_sharded > 10
    # and the replicated run's opt state is, well, replicated
    _, state_r, _ = fsdp_vs_replicated["replicated"]
    for leaf in jax.tree_util.tree_leaves(state_r.g_opt):
        assert leaf.sharding.is_fully_replicated


@pytest.mark.slow
def test_fsdp_losses_match_replicated_layout(fsdp_vs_replicated):
    """Layout changes bytes, not math: the fsdp step's losses and
    updated params match the replicated layout's (float-reduction-order
    tolerance)."""
    _, state_r, aux_r = fsdp_vs_replicated["replicated"]
    _, state_f, aux_f = fsdp_vs_replicated["fsdp"]
    for k in aux_r:
        assert float(jax.device_get(aux_r[k])) == pytest.approx(
            float(jax.device_get(aux_f[k])), rel=2e-4, abs=1e-5), k
    # Loose param gate only: Adam's first steps are ~sign(g)·lr, so
    # reduction-order noise on near-zero gradients legitimately moves
    # single elements by a fraction of one update — the gate catches
    # wrong MATH, the loss agreement above is the parity signal.
    a = jax.tree_util.tree_leaves(jax.device_get(state_r.g_params))
    b = jax.tree_util.tree_leaves(jax.device_get(state_f.g_params))
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-2, atol=1e-3)


@pytest.mark.slow
def test_fsdp_contract_and_collective_acceptance():
    """ISSUE 7 acceptance via the analysis stack: with the fsdp contract
    overlay, partition-contract is CLEAN on a 2-device mesh (inputs AND
    donated outputs resolve the per-leaf fsdp specs) and collective-flow
    reports neither a replicated opt-state leaf (threshold lowered to
    1 KiB — the tiny config has no 4 MiB leaves) nor a full-param
    all-gather; the same lowered threshold DOES fire on the replicated
    layout, proving the check has teeth."""
    from gansformer_tpu.analysis.trace.base import TraceContext
    from gansformer_tpu.analysis.trace.collective_flow import (
        CollectiveFlowRule)
    from gansformer_tpu.analysis.trace.entry_points import (
        build_entry_points)
    from gansformer_tpu.analysis.trace.partition_contract import (
        PartitionContractRule)

    class TinyOptThreshold(CollectiveFlowRule):
        opt_replicated_threshold = 1024

    eps = build_entry_points("tiny-f32", include=["g_step"], fsdp=True)
    ctx = TraceContext(mesh_sizes=(2,))
    for ep in eps:
        PartitionContractRule().check(ep, ctx)
        TinyOptThreshold().check(ep, ctx)
    assert ctx.findings == [], [f.message for f in ctx.findings]
    assert not ctx.notes
    # the fsdp step still all-reduces gradients
    assert ctx.comms[0]["collectives"]["all-reduce"]["count"] >= 1

    eps_repl = build_entry_points("tiny-f32", include=["g_step"])
    ctx2 = TraceContext(mesh_sizes=(2,))
    TinyOptThreshold().check(eps_repl[0], ctx2)
    assert any("fully replicated" in f.message for f in ctx2.findings)
