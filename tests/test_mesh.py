"""Unit tests for parallel/mesh.py — the sharding layer the contracts
(parallel/contracts.py) and the graftcomms analyses build on.  Direct
coverage for the MeshEnv sharding constructors (``batch`` /
``replicated`` / ``batch_stack``), the bare-PartitionSpec constraint
path (``activate()``), and ``simulated_mesh``'s shape matrix — on 1-
and 2-device meshes (conftest forces 8 virtual CPU devices)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gansformer_tpu.core.config import MeshConfig
from gansformer_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, local_batch_size, make_mesh)


def env_of(n_data, n_model=1):
    return make_mesh(MeshConfig(data=n_data, model=n_model),
                     devices=jax.devices()[: n_data * n_model])


@pytest.mark.parametrize("n", [1, 2])
def test_batch_sharding_spec_and_placement(n):
    env = env_of(n)
    sh = env.batch()
    assert sh.spec == P(DATA_AXIS)
    x = np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
    arr = jax.device_put(x, sh)
    # leading axis split over the data axis; content round-trips
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(4 // n, 3)}
    np.testing.assert_array_equal(np.asarray(arr), x)


@pytest.mark.parametrize("n", [1, 2])
def test_replicated_sharding_full_copy_per_device(n):
    env = env_of(n)
    sh = env.replicated()
    assert sh.spec == P()
    assert sh.is_fully_replicated
    arr = jax.device_put(np.ones((5,), np.float32), sh)
    assert all(s.data.shape == (5,) for s in arr.addressable_shards)
    assert len(arr.sharding.device_set) == n


@pytest.mark.parametrize("n", [1, 2])
def test_batch_stack_shards_axis1_replicates_axis0(n):
    env = env_of(n)
    sh = env.batch_stack()
    assert sh.spec == P(None, DATA_AXIS)
    x = np.arange(3 * 4 * 2, dtype=np.float32).reshape(3, 4, 2)
    arr = jax.device_put(x, sh)   # [K, B, ...]: K replicated, B split
    assert {s.data.shape for s in arr.addressable_shards} \
        == {(3, 4 // n, 2)}
    np.testing.assert_array_equal(np.asarray(arr), x)


@pytest.mark.parametrize("n", [1, 2])
def test_activate_resolves_bare_partition_spec(n):
    """``MeshEnv.activate()`` installs the ambient mesh, so a bare-
    PartitionSpec ``with_sharding_constraint`` (the sequence-parallel
    idiom in models/attention.py) resolves inside jit — on a 1-device
    mesh too (the degenerate axis must not error)."""
    env = env_of(n)

    @jax.jit
    def f(x):
        return jax.lax.with_sharding_constraint(x * 2.0, P(DATA_AXIS))

    x = np.ones((4, 3), np.float32)
    with env.activate():
        out = f(jax.device_put(x, env.batch()))
    np.testing.assert_array_equal(np.asarray(out), x * 2.0)
    assert {s.data.shape for s in out.addressable_shards} == {(4 // n, 3)}


def test_bare_spec_without_mesh_raises():
    # the contract the activate() helper exists to satisfy
    @jax.jit
    def f(x):
        return jax.lax.with_sharding_constraint(x, P(DATA_AXIS))

    with pytest.raises(Exception):
        f(jnp.ones((4,)))


def test_shard_batch_puts_tree_on_data_axis():
    env = env_of(2)
    tree = {"a": np.zeros((4, 2), np.float32),
            "b": np.zeros((4,), np.float32)}
    out = env.shard_batch(tree)
    for leaf in jax.tree_util.tree_leaves(out):
        assert leaf.sharding.spec == P(DATA_AXIS)


def test_local_batch_size_single_process():
    env = env_of(2)
    assert local_batch_size(8, env) == 8      # one process owns both rows
    with pytest.raises(ValueError):
        local_batch_size(5, env)              # not divisible


def test_mesh_env_axis_sizes():
    env = env_of(2, 2)
    assert env.data_size == 2 and env.model_size == 2
    assert env.mesh.axis_names == (DATA_AXIS, MODEL_AXIS)


def test_simulated_mesh_shape_matrix():
    """contracts.simulated_mesh: 1→1×1, 2→2×1, 4→2×2 (the 4-device
    member exercises the reserved model axis; the tiny trace batch
    bounds the data axis at 2)."""
    from gansformer_tpu.parallel.contracts import simulated_mesh

    assert simulated_mesh(1).mesh.devices.shape == (1, 1)
    assert simulated_mesh(2).mesh.devices.shape == (2, 1)
    env4 = simulated_mesh(4)
    assert env4.mesh.devices.shape == (2, 2)
    assert env4.data_size == 2 and env4.model_size == 2
    with pytest.raises(ValueError):
        simulated_mesh(64)                    # more than the 8 virtual


# --- ISSUE 7 satellite: local_batch_size / host-plan agreement ---------------

class _FakeDevice:
    """Stand-in device with a process_index (the only attribute the
    per-process row math reads) — lets one test process simulate the
    2-process ownership layout without a real coordinator."""

    def __init__(self, process_index):
        self.process_index = process_index


def _fake_two_process_env():
    """4-device 4x1 data mesh, devices 0-1 on process 0, 2-3 on
    process 1 (the contiguous layout jax.distributed produces)."""
    import types

    from gansformer_tpu.parallel.mesh import MeshEnv

    devs = np.array([_FakeDevice(0), _FakeDevice(0),
                     _FakeDevice(1), _FakeDevice(1)]).reshape(4, 1)
    mesh = types.SimpleNamespace(
        devices=devs, shape={DATA_AXIS: 4, MODEL_AXIS: 1},
        axis_names=(DATA_AXIS, MODEL_AXIS))
    return MeshEnv(mesh=mesh)


def test_local_batch_size_matches_local_data_rows_two_process(monkeypatch):
    """The prefetch plan's per-process share (loop.py feeds
    ``local_batch_size`` rows per process) must equal
    per-row-batch x ``MeshEnv.local_data_rows`` for EVERY process, and
    the shares must partition the global batch."""
    env = _fake_two_process_env()
    shares = {}
    for pid in (0, 1):
        monkeypatch.setattr(jax, "process_index", lambda p=pid: p)
        rows = env.local_data_rows
        assert rows == 2, pid                  # 2 of the 4 data rows each
        shares[pid] = local_batch_size(8, env)
        assert shares[pid] == (8 // env.data_size) * rows
    assert sum(shares.values()) == 8


def test_global_batch_reassembles_bit_exact_from_process_shards():
    """The addressing contract ``make_array_from_process_local_data``
    relies on, held bit-exact on a REAL 4-device mesh: each (simulated)
    process's local_batch_size rows, split per-data-row onto ITS
    devices in mesh order, reassemble the exact global batch."""
    env = env_of(4)
    global_batch = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    per_row = 8 // env.data_size               # 2 rows per device
    # simulated ownership: process p owns devices 2p, 2p+1 → its host
    # shard is local_batch_size(8) = 4 contiguous rows
    host = {0: global_batch[0:4], 1: global_batch[4:8]}
    pieces = []
    for d_idx, dev in enumerate(env.mesh.devices.flat):
        pid, local_row = divmod(d_idx, 2)
        piece = host[pid][local_row * per_row:(local_row + 1) * per_row]
        pieces.append(jax.device_put(piece, dev))
    arr = jax.make_array_from_single_device_arrays(
        (8, 3), env.batch(), pieces)
    np.testing.assert_array_equal(np.asarray(arr), global_batch)
    # and the callback-assembly path (MeshEnv.put_global's multi-process
    # branch) produces the same array from a full host copy
    cb = jax.make_array_from_callback((8, 3), env.batch(),
                                      lambda idx: global_batch[idx])
    np.testing.assert_array_equal(np.asarray(cb), global_batch)
