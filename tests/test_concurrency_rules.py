"""Concurrency static analysis (analysis/concurrency, ISSUE 18):
per-rule fires/quiet/suppressed/baselined fixtures, the retired-alias
plumbing (thread-shared-state → unguarded-shared-attribute), resolver
pins against the real threaded runtime, the whole-repo clean gate for
the five rules, and the --format json thread-model summary.
"""

import ast
import json
import os

import pytest

from gansformer_tpu.analysis import all_rules, lint_paths, lint_source
from gansformer_tpu.analysis.baseline import Baseline, line_text_lookup
from gansformer_tpu.analysis.concurrency.thread_model import (
    ThreadModel,
    summarize_paths,
)
from gansformer_tpu.analysis.engine import get_rule, legacy_ids, rule_aliases

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONCURRENCY_RULES = (
    "lock-order-inversion",
    "unguarded-shared-attribute",
    "thread-lifecycle",
    "signal-handler-safety",
    "condition-protocol",
)


def run_rule(rule_id, source):
    return lint_source(source, path="fixture.py",
                       rules=[get_rule(rule_id)])


def model_of(path):
    with open(path, encoding="utf-8") as f:
        return ThreadModel(ast.parse(f.read()))


# --- fixtures: lock-order-inversion ----------------------------------------

LOCK_ORDER_BAD = """
import threading

_a = threading.Lock()
_b = threading.Lock()

def forward():
    with _a:
        with _b:
            pass

def backward():
    with _b:
        with _a:
            pass
"""

LOCK_ORDER_OK = """
import threading

_a = threading.Lock()
_b = threading.Lock()

def forward():
    with _a:
        with _b:
            pass

def also_forward():
    with _a:
        with _b:
            pass
"""

SELF_DEADLOCK_BAD = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""

SELF_DEADLOCK_OK_RLOCK = """
import threading

class C:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""

# --- fixtures: unguarded-shared-attribute ----------------------------------

SHARED_ATTR_BAD = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        self._n += 1

    def read(self):
        with self._lock:
            return self._n
"""

SHARED_ATTR_OK = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._n += 1

    def read(self):
        with self._lock:
            return self._n
"""

SINGLE_WRITER_PUBLISH_OK = """
import threading

class C:
    def __init__(self):
        self._done = False
        self._t = threading.Thread(target=self._run)

    def _run(self):
        self._done = True        # plain single-writer publish

    def poll(self):
        return self._done        # unlocked read: sanctioned
"""

# --- fixtures: thread-lifecycle --------------------------------------------

LIFECYCLE_BAD_NEVER_JOINED = """
import threading

class C:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
"""

LIFECYCLE_BAD_FIRE_AND_FORGET = """
import threading

def _run():
    pass

def kick():
    threading.Thread(target=_run).start()
"""

LIFECYCLE_BAD_HAPPY_PATH_JOIN = """
import threading

def _run():
    pass

def wait_for_it(work):
    t = threading.Thread(target=_run)
    t.start()
    work()
    t.join()
"""

LIFECYCLE_OK = """
import threading

class C:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        pass

    def close(self):
        t = self._t
        t.join(timeout=5.0)
"""

LIFECYCLE_OK_FINALLY = """
import threading

def _run():
    pass

def wait_for_it(work):
    t = threading.Thread(target=_run)
    t.start()
    try:
        work()
    finally:
        t.join()
"""

# --- fixtures: signal-handler-safety ---------------------------------------

SIGNAL_BAD = """
import signal
import threading

_lock = threading.Lock()

def _on_term(sig, frame):
    with _lock:
        print("terminating")

signal.signal(signal.SIGTERM, _on_term)
"""

SIGNAL_OK_FLAG_IDIOM = """
import os
import signal

_FLAG = False

def _on_term(sig, frame):
    global _FLAG
    _FLAG = True
    os.write(2, b"sigterm\\n")

signal.signal(signal.SIGTERM, _on_term)
"""

SIGNAL_OK_THREAD_DRAIN = """
import signal
import threading

class Service:
    def close(self):
        pass

    def _on_term(self, sig, frame):
        threading.Thread(target=self.close, daemon=True).start()

    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)
"""

# --- fixtures: condition-protocol ------------------------------------------

CONDITION_BAD = """
import threading

class Q:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def get(self):
        with self._cv:
            self._cv.wait()
            return self._items.pop()

    def put(self, x):
        self._items.append(x)
        self._cv.notify_all()
"""

CONDITION_OK = """
import threading

class Q:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def get(self):
        with self._cv:
            while not self._items:
                self._cv.wait()
            return self._items.pop()

    def put(self, x):
        with self._cv:
            self._items.append(x)
            self._cv.notify_all()
"""

CASES = [
    ("lock-order-inversion", LOCK_ORDER_BAD, LOCK_ORDER_OK),
    ("lock-order-inversion", SELF_DEADLOCK_BAD, SELF_DEADLOCK_OK_RLOCK),
    ("unguarded-shared-attribute", SHARED_ATTR_BAD, SHARED_ATTR_OK),
    ("unguarded-shared-attribute", SHARED_ATTR_BAD,
     SINGLE_WRITER_PUBLISH_OK),
    ("thread-lifecycle", LIFECYCLE_BAD_NEVER_JOINED, LIFECYCLE_OK),
    ("thread-lifecycle", LIFECYCLE_BAD_FIRE_AND_FORGET,
     LIFECYCLE_OK_FINALLY),
    ("thread-lifecycle", LIFECYCLE_BAD_HAPPY_PATH_JOIN,
     LIFECYCLE_OK_FINALLY),
    ("signal-handler-safety", SIGNAL_BAD, SIGNAL_OK_FLAG_IDIOM),
    ("signal-handler-safety", SIGNAL_BAD, SIGNAL_OK_THREAD_DRAIN),
    ("condition-protocol", CONDITION_BAD, CONDITION_OK),
]


# --- positive / negative ----------------------------------------------------

@pytest.mark.parametrize("rule_id,bad,ok", CASES,
                         ids=[f"{c[0]}-{i}" for i, c in enumerate(CASES)])
def test_rule_fires_and_goes_quiet(rule_id, bad, ok):
    findings = run_rule(rule_id, bad)
    assert findings, f"{rule_id} produced no findings on its bad fixture"
    assert all(f.rule == rule_id for f in findings)
    assert all(f.new and f.line > 0 for f in findings)
    assert run_rule(rule_id, ok) == []


def test_condition_bad_flags_both_sides():
    messages = [f.message for f in
                run_rule("condition-protocol", CONDITION_BAD)]
    assert any("while-predicate" in m for m in messages)
    assert any("notify" in m for m in messages)


def test_registry_has_all_five_rules():
    ids = {r.id for r in all_rules()}
    assert set(CONCURRENCY_RULES) <= ids


# --- suppression / baseline -------------------------------------------------

@pytest.mark.parametrize("rule_id,bad", [(c[0], c[1]) for c in CASES[:1]]
                         + [(c[0], c[1]) for c in CASES[2:3]])
def test_inline_suppression(rule_id, bad):
    raw = run_rule(rule_id, bad)
    assert raw
    line = raw[0].line
    lines = bad.splitlines()
    lines[line - 1] += f"  # graftlint: disable={rule_id} — fixture"
    suppressed = run_rule(rule_id, "\n".join(lines))
    hit = [f for f in suppressed if f.line == line]
    assert hit and all(f.suppressed and not f.new for f in hit)


def test_suppression_via_retired_alias_still_works():
    raw = run_rule("unguarded-shared-attribute", SHARED_ATTR_BAD)
    line = raw[0].line
    lines = SHARED_ATTR_BAD.splitlines()
    lines[line - 1] += "  # graftlint: disable=thread-shared-state — old id"
    findings = run_rule("unguarded-shared-attribute", "\n".join(lines))
    hit = [f for f in findings if f.line == line]
    assert hit and all(f.suppressed for f in hit)


def test_baseline_absolves_concurrency_finding(tmp_path):
    src = tmp_path / "m.py"
    src.write_text(SHARED_ATTR_BAD)
    rules = [get_rule("unguarded-shared-attribute")]
    findings = lint_paths([str(src)], rules=rules)
    assert findings
    bl = tmp_path / "baseline.json"
    Baseline.write(str(bl), findings, line_text_lookup())
    fresh = lint_paths([str(src)], rules=rules)
    Baseline.load(str(bl)).apply(fresh, line_text_lookup())
    assert all(f.baselined and not f.new for f in fresh)


def test_baseline_keyed_by_retired_id_absolves_successor(tmp_path):
    # a baseline written BEFORE the rename (keys start with
    # thread-shared-state::) must keep absolving the successor rule
    src = tmp_path / "m.py"
    src.write_text(SHARED_ATTR_BAD)
    rules = [get_rule("unguarded-shared-attribute")]
    findings = lint_paths([str(src)], rules=rules)
    look = line_text_lookup()
    entries = []
    for f in findings:
        key = f.baseline_key(look(f))
        old = key.replace("unguarded-shared-attribute",
                          "thread-shared-state", 1)
        entries.append({"key": old.replace(str(src), "m.py")})
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"version": 1, "entries": entries}))
    fresh = lint_paths([str(src)], rules=rules)
    Baseline.load(str(bl)).apply(fresh, line_text_lookup())
    assert all(f.baselined and not f.new for f in fresh)


def test_alias_registry_plumbing():
    assert rule_aliases() == {"thread-shared-state":
                              "unguarded-shared-attribute"}
    assert legacy_ids("unguarded-shared-attribute") == \
        ["thread-shared-state"]
    assert get_rule("thread-shared-state") is \
        get_rule("unguarded-shared-attribute")


# --- resolver pins against the real threaded runtime ------------------------

def test_resolver_maps_background_workers():
    tm = model_of(os.path.join(
        ROOT, "gansformer_tpu", "utils", "background.py"))
    resolved = {q for s in tm.thread_sites
                for q in (tm.qualname(t) for t in s.targets)}
    assert {"LoopWorker._run", "SingleSlotWriter._run"} <= resolved
    # both workers bind their thread to self._thread and are daemons
    for site in tm.thread_sites:
        if site.kind == "Thread":
            assert site.binding == ("attr", site.binding[1], "_thread")
            assert site.daemon is True


def test_resolver_maps_generation_service():
    tm = model_of(os.path.join(
        ROOT, "gansformer_tpu", "serve", "service.py"))
    by_target = {}
    for s in tm.thread_sites:
        for t in s.targets:
            by_target.setdefault(tm.qualname(t), []).append(s)
    # two LoopWorker constructions run the dispatcher
    dispatch = by_target["GenerationService._serve_dispatch"]
    assert len(dispatch) == 2
    assert all(s.kind == "LoopWorker" for s in dispatch)
    # the monitor thread and the SIGTERM drain thread
    (mon,) = by_target["GenerationService._supervise_dispatch"]
    assert mon.binding == ("attr", "GenerationService", "_monitor")
    assert by_target["GenerationService.close"][0].daemon is True
    # the Condition and the installed handler
    assert tm.lock_kind(("GenerationService", "_cv")) == "condition"
    handlers = {q for h in tm.handlers
                for q in (tm.qualname(t) for t in h.targets)}
    assert "GenerationService._on_term" in handlers


def test_resolver_maps_prefetch_closure():
    tm = model_of(os.path.join(
        ROOT, "gansformer_tpu", "data", "device_prefetch.py"))
    assert tm.thread_sites, "prefetcher thread not discovered"
    site = tm.thread_sites[0]
    assert site.target_desc == "_produce" and site.targets
    assert site.daemon is True
    assert site.binding == ("attr", site.binding[1], "_thread")
    assert all(tm.is_entry(t) for t in site.targets)


def test_resolver_maps_single_slot_writer_dispatch():
    # the checkpoint writer dispatches work onto SingleSlotWriter via
    # .submit(lambda: ...) — the lambda must resolve as the thread-side
    # entry so its body counts as thread-reachable
    tm = model_of(os.path.join(
        ROOT, "gansformer_tpu", "train", "checkpoint.py"))
    submits = [s for s in tm.thread_sites if s.kind == "submit"]
    assert submits and all(s.targets for s in submits)
    assert all(tm.is_entry(t) for s in submits for t in s.targets)


def test_resolver_maps_supervisor_handlers():
    tm = model_of(os.path.join(
        ROOT, "gansformer_tpu", "supervise", "supervisor.py"))
    assert tm.thread_sites == []     # the supervisor spawns no threads
    resolved = {q for h in tm.handlers
                for q in (tm.qualname(t) for t in h.targets)}
    assert "_on_preempt" in resolved
    # the restore path re-installs a saved handler object — recorded
    # but unresolvable by a name-based resolver (documented limit)
    assert any(not h.targets for h in tm.handlers)


# --- whole-repo gate ---------------------------------------------------------

def test_whole_repo_concurrency_clean_without_baseline():
    """The five concurrency rules must hold over the real tree with NO
    baseline — every pre-existing defect was fixed or suppressed with a
    written justification in this change."""
    rules = [get_rule(r) for r in CONCURRENCY_RULES]
    findings = lint_paths(
        [os.path.join(ROOT, "gansformer_tpu"),
         os.path.join(ROOT, "scripts")], rules=rules)
    fresh = [f for f in findings if not f.suppressed]
    assert fresh == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in fresh)


def test_concurrency_suppressions_carry_justification():
    import re

    pat = re.compile(r"#\s*graftlint:\s*disable(?:-file)?="
                     r"([A-Za-z0-9_,\s-]+)(.*)")
    ids = set(CONCURRENCY_RULES) | {"thread-shared-state"}
    for path in _py_files():
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        for i, text in enumerate(lines):
            m = pat.search(text)
            if not m:
                continue
            mentioned = {r.strip() for r in m.group(1).split(",")}
            if not (mentioned & ids):
                continue
            trailing = m.group(2).strip(" -—:")
            above = lines[i - 1].strip() if i else ""
            assert trailing or above.startswith("#"), (
                f"{path}:{i + 1}: concurrency suppression without a "
                f"written justification")


def _py_files():
    from gansformer_tpu.analysis.engine import iter_python_files

    return iter_python_files([os.path.join(ROOT, "gansformer_tpu"),
                              os.path.join(ROOT, "scripts")])


# --- thread-model JSON summary ----------------------------------------------

def test_summarize_paths_shape():
    paths = [os.path.join(ROOT, "gansformer_tpu", "utils",
                          "background.py"),
             os.path.join(ROOT, "gansformer_tpu", "utils",
                          "__init__.py")]
    out = summarize_paths(paths, root=ROOT)
    assert out["totals"]["files_with_threads"] == 1   # __init__ elided
    (entry,) = out["files"]
    assert entry["path"] == "gansformer_tpu/utils/background.py"
    assert out["totals"]["threads"] == len(entry["threads"]) >= 2
    assert {l["kind"] for l in entry["locks"]} == {"lock"}
    for t in entry["threads"]:
        assert t["resolved"], f"unresolved thread target: {t}"


def test_cli_json_carries_thread_model(tmp_path, capsys):
    from gansformer_tpu.analysis.cli import main as cli_main

    src = tmp_path / "w.py"
    src.write_text(LIFECYCLE_OK)
    rc = cli_main(["--format", "json", "--no-baseline",
                   "--select", "thread-lifecycle", str(src)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    tm = payload["thread_model"]
    assert tm["totals"]["threads"] == 1
    assert tm["files"][0]["threads"][0]["resolved"] == ["C._run"]
