"""``gansformer-telemetry doctor`` — PASS/WARN goldens over synthetic
run dirs (ISSUE 8 tentpole c), the JSON output mode, exit codes, and
the results-root descent the battery relies on."""

import json
import os

import pytest

from gansformer_tpu.cli.telemetry import (
    main as cli_main, render_doctor, resolve_run_dir, run_doctor)

NOW = 1_000_000.0


def synth_run_dir(tmp_path, *, gauges=None, counters=None, stats=None,
                  beats=None, resumes=0, name="run"):
    """A minimal healthy run dir; keyword overrides poison individual
    signals for the WARN/FAIL goldens."""
    d = tmp_path / name
    d.mkdir()
    g = {"device/sampler_off": 0.0, "device/unavailable": 0.0,
         "device/busy_ms": 900.0, "device/span_ms": 950.0,
         "device/wall_ms": 1000.0, "device/wall_busy_ratio": 0.9,
         "device/mfu": 0.33, "device/phase_ms/d_step": 400.0,
         "device/phase_ms/g_step": 300.0,
         "hbm/unavailable": 0.0, "hbm/bytes_in_use": 2e9,
         "hbm/peak_bytes": 4e9, "hbm/bytes_limit": 16e9,
         "data/prefetch_queue_depth": 2.0,
         "data/device_queue_depth": 2.0,
         "data/corrupt_frac": 0.0, "data/corrupt_budget_frac": 0.01}
    g.update(gauges or {})
    c = {"device/samples_total": 2.0, "compile/compiles_total": 12.0,
         "compile/retraces_total": 0.0, "data/starved_total": 0.0,
         "data/corrupt_records_total": 0.0, "data/read_retries_total": 0.0,
         "data/stalls_total": 0.0, "train/nonfinite_total": 0.0,
         "train/nonfinite_loss_total": 0.0,
         "train/nonfinite_grad_total": 0.0,
         "train/nonfinite_param_total": 0.0}
    c.update(counters or {})
    rec = {"Progress/tick": 3, "Progress/kimg": 4.0,
           "timing/sec_per_tick": 10.0, "timing/img_per_sec": 100.0,
           "timing/img_per_sec_per_chip": 100.0,
           "timing/data_wait_s": 0.5, "timing/data_wait_frac": 0.05,
           "timing/mfu": 0.30,
           "telemetry": {"counters": c, "gauges": g, "histograms": {}}}
    rec.update(stats or {})
    with open(d / "stats.jsonl", "w") as f:
        f.write(json.dumps(rec) + "\n")
    # prom mirrors a subset (the doctor prefers stats.jsonl; prom presence
    # satisfies the artifacts check)
    with open(d / "telemetry.prom", "w") as f:
        f.write("# TYPE device_sampler_off gauge\n"
                f"device_sampler_off {g['device/sampler_off']}\n")
    for idx, rec_hb in (beats if beats is not None else
                        {0: {"time": NOW - 5.0, "step": 4000}}).items():
        hb = {"process": idx, "pid": 1, "host": "h", "kimg": 4.0}
        hb.update(rec_hb)
        with open(d / f"heartbeat-p{idx}.json", "w") as f:
            f.write(json.dumps(hb))
    for i in range(resumes):
        with open(d / "resumes.jsonl", "a") as f:
            f.write(json.dumps({"time": NOW - 100 + i, "step": 1000 * i,
                                "pid": 1}) + "\n")
    return str(d)


def levels(report):
    return {c["name"]: c["level"] for c in report["checks"]}


def detail(report, name):
    return next(c["detail"] for c in report["checks"] if c["name"] == name)


def test_healthy_run_all_pass(tmp_path):
    d = synth_run_dir(tmp_path)
    report = run_doctor(d, now=NOW)
    assert report["ok"] and report["n_fail"] == 0
    lv = levels(report)
    for name in ("artifacts", "progress", "device_truth", "mfu",
                 "data_wait", "queues", "data_plane", "numerics",
                 "compiles", "hbm", "heartbeats", "restarts",
                 "device_phases"):
        assert lv[name] == "PASS", (name, lv)
    assert report["n_warn"] == 0
    # device phase table is ranked heaviest-first
    assert detail(report, "device_phases").index("d_step") < \
        detail(report, "device_phases").index("g_step")
    text = render_doctor(report)
    assert "verdict: OK" in text and "PASS device_truth" in text


def test_sampler_off_and_wall_divergence_warn(tmp_path):
    off = run_doctor(synth_run_dir(
        tmp_path, gauges={"device/sampler_off": 1.0}, name="off"), now=NOW)
    assert levels(off)["device_truth"] == "WARN"
    assert "sampler OFF" in detail(off, "device_truth")
    assert off["ok"]                       # WARN never fails the doctor

    lying = run_doctor(synth_run_dir(
        tmp_path, gauges={"device/wall_busy_ratio": 1.4}, name="lying"),
        now=NOW)
    assert levels(lying)["device_truth"] == "WARN"
    assert "NOT covering device execution" in detail(lying, "device_truth")

    idle = run_doctor(synth_run_dir(
        tmp_path, gauges={"device/wall_busy_ratio": 0.1}, name="idle"),
        now=NOW)
    assert "host-bound" in detail(idle, "device_truth")


def test_mfu_divergence_warns_toward_device_number(tmp_path):
    d = synth_run_dir(tmp_path, gauges={"device/mfu": 0.20},
                      stats={"timing/mfu": 0.35})
    report = run_doctor(d, now=NOW)
    assert levels(report)["mfu"] == "WARN"
    assert "trust the device number" in detail(report, "mfu")
    # agreement passes
    ok = run_doctor(synth_run_dir(tmp_path, gauges={"device/mfu": 0.31},
                                  name="ok"), now=NOW)
    assert levels(ok)["mfu"] == "PASS"


def test_input_pipeline_warnings(tmp_path):
    d = synth_run_dir(tmp_path, stats={"timing/data_wait_frac": 0.6},
                      counters={"data/starved_total": 7.0})
    report = run_doctor(d, now=NOW)
    assert levels(report)["data_wait"] == "WARN"
    assert "input-bound" in detail(report, "data_wait")
    assert levels(report)["queues"] == "WARN"
    assert "starved_total = 7" in detail(report, "queues")


def test_retraces_and_hbm_warnings(tmp_path):
    d = synth_run_dir(tmp_path,
                      counters={"compile/retraces_total": 3.0},
                      gauges={"hbm/peak_bytes": 15.5e9})
    report = run_doctor(d, now=NOW)
    assert levels(report)["compiles"] == "WARN"
    assert "3 post-warm-up compile(s)" in detail(report, "compiles")
    assert levels(report)["hbm"] == "WARN"
    assert "from OOM" in detail(report, "hbm")
    # CPU backends report no memory stats: PASS, not WARN
    cpu = run_doctor(synth_run_dir(
        tmp_path, gauges={"hbm/unavailable": 1.0}, name="cpu"), now=NOW)
    assert levels(cpu)["hbm"] == "PASS"


def test_heartbeat_staleness_fails_only_with_max_age(tmp_path):
    beats = {0: {"time": NOW - 5.0, "step": 4000},
             1: {"time": NOW - 500.0, "step": 4000}}
    d = synth_run_dir(tmp_path, beats=beats)
    dflt = run_doctor(d, now=NOW)
    assert levels(dflt)["heartbeats"] == "PASS"      # archived dirs OK
    judged = run_doctor(d, max_age_s=120.0, now=NOW)
    assert levels(judged)["heartbeats"] == "FAIL"
    assert not judged["ok"] and judged["n_fail"] == 1
    assert "verdict: NOT OK" in render_doctor(judged)


def test_all_heartbeats_missing_with_expected_fails(tmp_path):
    """A fully-dead run (zero heartbeat files, roster given) must FAIL —
    the softer 'no heartbeat files' WARN would invert severity vs a
    partially-dead run."""
    d = synth_run_dir(tmp_path, beats={})
    report = run_doctor(d, expected=2, now=NOW)
    assert levels(report)["heartbeats"] == "FAIL"
    assert "missing [0, 1]" in detail(report, "heartbeats")
    assert "max age Nones" not in detail(report, "heartbeats")
    assert not report["ok"]
    # without a roster there is nothing to judge: WARN only
    unjudged = run_doctor(d, now=NOW)
    assert levels(unjudged)["heartbeats"] == "WARN"
    assert unjudged["ok"]


def test_step_skew_straggler_detection(tmp_path):
    beats = {0: {"time": NOW - 5.0, "step": 4000},
             1: {"time": NOW - 5.0, "step": 2400}}
    d = synth_run_dir(tmp_path, beats=beats)
    report = run_doctor(d, max_step_skew=1000, now=NOW)
    assert levels(report)["step_skew"] == "WARN"
    assert "straggler" in detail(report, "step_skew")
    assert report["ok"]
    loose = run_doctor(d, max_step_skew=2000, now=NOW)
    assert levels(loose)["step_skew"] == "PASS"
    # skew is reported (not judged) without the threshold
    unjudged = run_doctor(d, now=NOW)
    assert levels(unjudged)["step_skew"] == "PASS"
    assert "1600" in detail(unjudged, "step_skew")


def test_restart_count_from_resume_records(tmp_path):
    d = synth_run_dir(tmp_path, resumes=2)
    report = run_doctor(d, now=NOW)
    assert levels(report)["restarts"] == "PASS"
    assert "2 restart(s)" in detail(report, "restarts")
    assert "step 1000" in detail(report, "restarts")


def _serve_metrics(health=0.0, alive=1.0, depth=1.0, bound=256.0,
                   requests=100.0, shed=0.0, restarts=0.0):
    return ({"serve/health_state": health, "serve/dispatcher_alive": alive,
             "serve/queue_depth_now": depth, "serve/queue_bound": bound},
            {"serve/requests_total": requests, "serve/shed_total": shed,
             "serve/dispatcher_restarts_total": restarts})


def test_serving_section_absent_without_serve_telemetry(tmp_path):
    report = run_doctor(synth_run_dir(tmp_path), now=NOW)
    assert "serving" not in levels(report)


def test_serving_section_goldens(tmp_path):
    g, c = _serve_metrics()
    ok = run_doctor(synth_run_dir(tmp_path, gauges=g, counters=c,
                                  name="s_ok"), now=NOW)
    assert levels(ok)["serving"] == "PASS"
    assert "100 request(s)" in detail(ok, "serving")

    g, c = _serve_metrics(health=2.0, alive=0.0)
    tripped = run_doctor(synth_run_dir(tmp_path, gauges=g, counters=c,
                                       name="s_trip"), now=NOW)
    assert levels(tripped)["serving"] == "FAIL"
    assert "UNHEALTHY" in detail(tripped, "serving")
    assert not tripped["ok"]

    g, c = _serve_metrics(health=1.0, alive=0.0, depth=3.0)
    dead = run_doctor(synth_run_dir(tmp_path, gauges=g, counters=c,
                                    name="s_dead"), now=NOW)
    assert levels(dead)["serving"] == "FAIL"
    assert "dispatcher dead" in detail(dead, "serving")

    g, c = _serve_metrics(requests=95.0, shed=5.0)
    shed = run_doctor(synth_run_dir(tmp_path, gauges=g, counters=c,
                                    name="s_shed"), now=NOW)
    assert levels(shed)["serving"] == "WARN"
    assert "shed rate" in detail(shed, "serving")
    assert shed["ok"]                      # WARN never fails the doctor

    g, c = _serve_metrics(depth=256.0)
    sat = run_doctor(synth_run_dir(tmp_path, gauges=g, counters=c,
                                   name="s_sat"), now=NOW)
    assert levels(sat)["serving"] == "WARN"
    assert "saturated" in detail(sat, "serving")


def test_serving_shed_warn_suppressed_by_chaos_artifact(tmp_path):
    """A serve_chaos.json beside the telemetry declares the overload
    was deliberately driven — the shed-rate WARN becomes a PASS with a
    note instead of a scale-out false alarm."""
    g, c = _serve_metrics(requests=30.0, shed=70.0, restarts=1.0)
    d = synth_run_dir(tmp_path, gauges=g, counters=c, name="s_drill")
    with open(os.path.join(d, "serve_chaos.json"), "w") as f:
        json.dump({"shed_rate": 0.7, "expired_rate": 0.0,
                   "p99_ms_under_overload": 42.0,
                   "dispatcher_restarts": 1, "recovery_ms": 55.0,
                   "crash_at_batch": 2, "hung_tickets": 0}, f)
    report = run_doctor(d, now=NOW)
    assert levels(report)["serving"] == "PASS"
    assert "deliberately driven" in detail(report, "serving")
    assert levels(report)["serve_chaos"] == "PASS"


def test_serve_chaos_artifact_grading(tmp_path):
    """serve_chaos.json beside the telemetry: hung tickets FAIL, a
    never-fired injected crash WARNs, a clean drill PASSes with the
    report-card numbers."""
    g, c = _serve_metrics(restarts=1.0)
    base = {"shed_rate": 0.6, "expired_rate": 0.0,
            "p99_ms_under_overload": 42.0, "dispatcher_restarts": 1,
            "recovery_ms": 55.0, "crash_at_batch": 2, "hung_tickets": 0}

    def with_chaos(blob, name):
        d = synth_run_dir(tmp_path, gauges=dict(g), counters=dict(c),
                          name=name)
        with open(os.path.join(d, "serve_chaos.json"), "w") as f:
            json.dump(blob, f)
        return d

    ok = run_doctor(with_chaos(base, "c_ok"), now=NOW)
    assert levels(ok)["serve_chaos"] == "PASS"
    assert "recovery 55.0 ms" in detail(ok, "serve_chaos")

    hung = run_doctor(with_chaos(dict(base, hung_tickets=2), "c_hung"),
                      now=NOW)
    assert levels(hung)["serve_chaos"] == "FAIL"
    assert not hung["ok"]

    dud = run_doctor(with_chaos(dict(base, dispatcher_restarts=0),
                                "c_dud"), now=NOW)
    assert levels(dud)["serve_chaos"] == "WARN"
    assert "never fired" in detail(dud, "serve_chaos")

    # the drill's own health snapshot (whose prom may live in a file
    # the doctor never reads) grades: breaker tripped mid-drill = FAIL
    sick = run_doctor(with_chaos(
        dict(base, health={"state": "unhealthy",
                           "reasons": ["circuit breaker open"]}),
        "c_sick"), now=NOW)
    assert levels(sick)["serve_chaos"] == "FAIL"
    assert "UNHEALTHY" in detail(sick, "serve_chaos")


def test_not_a_run_dir_fails(tmp_path):
    report = run_doctor(str(tmp_path), now=NOW)
    assert not report["ok"]
    assert levels(report)["artifacts"] == "FAIL"


def test_resolve_run_dir_descends_to_latest_numbered_run(tmp_path):
    root = tmp_path / "results"
    root.mkdir()
    for name in ("00000-a", "00001-b"):
        synth_run_dir(root, name=name)
    assert resolve_run_dir(str(root)).endswith("00001-b")
    # a real run dir resolves to itself
    d = synth_run_dir(tmp_path, name="direct")
    assert resolve_run_dir(d) == d


def test_cli_doctor_json_modes(tmp_path, capsys):
    d = synth_run_dir(tmp_path)
    out_path = str(tmp_path / "doctor.json")
    cli_main(["doctor", d, "--json", "--json-out", out_path])
    printed = json.loads(capsys.readouterr().out)
    archived = json.load(open(out_path))
    assert printed == archived
    assert printed["ok"] and printed["checks"]
    # FAIL → exit 1
    beats = {0: {"time": NOW - 500.0, "step": 1}}
    bad = synth_run_dir(tmp_path, beats=beats, name="stale")
    with pytest.raises(SystemExit) as e:
        cli_main(["doctor", bad, "--max-age", "1e-6"])
    assert e.value.code == 1


# --- data-plane section (ISSUE 15) ------------------------------------------

def test_data_plane_absent_on_pre_issue15_run_dirs(tmp_path):
    d = synth_run_dir(tmp_path, name="legacy")
    # strip the robustness family the way an old run dir would lack it
    import json as _json

    p = os.path.join(d, "stats.jsonl")
    rec = _json.loads(open(p).read())
    for k in ("data/corrupt_records_total", "data/read_retries_total",
              "data/stalls_total"):
        del rec["telemetry"]["counters"][k]
    open(p, "w").write(_json.dumps(rec) + "\n")
    assert "data_plane" not in levels(run_doctor(d, now=NOW))


def test_data_plane_warn_on_quarantines_and_retries(tmp_path):
    d = synth_run_dir(
        tmp_path,
        counters={"data/corrupt_records_total": 2.0,
                  "data/read_retries_total": 3.0},
        gauges={"data/corrupt_frac": 0.002})
    with open(os.path.join(d, "data_quarantine.jsonl"), "w") as f:
        f.write('{"file": "x", "offset": 1, "cause": "payload-crc"}\n' * 2)
    rep = run_doctor(d, now=NOW)
    assert rep["ok"]                       # WARN never fails the doctor
    assert levels(rep)["data_plane"] == "WARN"
    det = detail(rep, "data_plane")
    assert "2 quarantined" in det and "2 ledger line(s)" in det \
        and "3 read retries" in det


def test_numerics_warn_on_nonfinite_with_cause_breakdown(tmp_path):
    d = synth_run_dir(
        tmp_path,
        counters={"train/nonfinite_total": 3.0,
                  "train/nonfinite_loss_total": 2.0,
                  "train/nonfinite_grad_total": 1.0})
    rep = run_doctor(d, now=NOW)
    assert rep["ok"]                       # WARN never fails the doctor
    assert levels(rep)["numerics"] == "WARN"
    det = detail(rep, "numerics")
    assert "loss=2" in det and "grad=1" in det and "param=0" in det
    assert "fp32-island" in det


def test_numerics_absent_on_pre_issue19_run_dirs(tmp_path):
    d = synth_run_dir(tmp_path, name="legacy19")
    import json as _json

    p = os.path.join(d, "stats.jsonl")
    rec = _json.loads(open(p).read())
    for k in ("train/nonfinite_total", "train/nonfinite_loss_total",
              "train/nonfinite_grad_total", "train/nonfinite_param_total"):
        del rec["telemetry"]["counters"][k]
    open(p, "w").write(_json.dumps(rec) + "\n")
    assert "numerics" not in levels(run_doctor(d, now=NOW))


def test_data_plane_fail_on_stall_kill(tmp_path):
    d = synth_run_dir(tmp_path, counters={"data/stalls_total": 1.0})
    rep = run_doctor(d, now=NOW)
    assert not rep["ok"]
    assert levels(rep)["data_plane"] == "FAIL"
    assert "stall" in detail(rep, "data_plane")


def test_data_plane_fail_on_budget_breach(tmp_path):
    d = synth_run_dir(
        tmp_path,
        counters={"data/corrupt_records_total": 40.0},
        gauges={"data/corrupt_frac": 0.04,
                "data/corrupt_budget_frac": 0.01})
    rep = run_doctor(d, now=NOW)
    assert not rep["ok"]
    assert levels(rep)["data_plane"] == "FAIL"
    assert "budget" in detail(rep, "data_plane")
