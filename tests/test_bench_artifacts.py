"""Seam tests for bench.py's pure artifact builders (VERDICT r4 weak #4:
the logic that decides whether a number is real must be unit-testable).

``build_phase_artifact`` / ``build_cycle_artifact`` are pure functions on
plain dicts — no device, no jax — so these tests pin the exact artifact
schema (PERF.md §4) and the suspect-flagging behavior the judge reads."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (repo-root module; stdlib-only at import time)

IDENTITY = {"device_kind": "TPU v5 lite", "platform": "tpu", "n_devices": 1,
            "local_device_count": 1, "process_count": 1}

# A physically consistent v5e measurement: 4 phases whose times track
# their FLOPs at ~33% MFU (the r4 interim datapoint's regime).
PEAK = 197.0
FLOPS = {"d": 1.887e12, "g": 1.712e12, "d_r1": 3.129e12, "g_pl": 2.938e12}
TIMES = {k: v / (0.33 * PEAK * 1e12) for k, v in FLOPS.items()}


def phase_kwargs(**over):
    kw = dict(metric="train_img_per_sec_per_chip_ffhq256_duplex",
              on_tpu=True, n_chips=1, platform="tpu", bsz=8,
              timings=dict(TIMES), flops=dict(FLOPS),
              fetch_s={k: 0.001 for k in TIMES},
              compile_s={k: 10.0 for k in TIMES},
              identity=IDENTITY, peak=PEAK, d_reg_interval=16,
              g_reg_interval=4, iters=20,
              linearity={"d": (TIMES["d"], TIMES["d"] * 1.02)},
              device_kind="TPU v5 lite", partial=False)
    kw.update(over)
    return kw


def test_phase_artifact_clean_measurement():
    out = bench.build_phase_artifact(**phase_kwargs())
    assert "suspect" not in out and "partial" not in out
    assert out["unit"] == "img/sec/chip"
    # cadence-weighted throughput: batch / weighted-time; MFU ≈ the 33%
    # the synthetic times encode
    assert out["value"] == pytest.approx(
        8 / (TIMES["d"] * 15 / 16 + TIMES["d_r1"] / 16
             + TIMES["g"] * 3 / 4 + TIMES["g_pl"] / 4), rel=1e-3)
    assert out["mfu"] == pytest.approx(0.33, abs=0.005)
    assert out["vs_baseline"] == pytest.approx(out["value"] / 200.0, rel=1e-3)
    assert set(out["phase_ms"]) == set(TIMES)
    assert out["device"] is IDENTITY


def test_phase_artifact_flags_faster_than_physics():
    # 10x-too-fast times → implied MFU > 1 → must carry ``suspect``
    fast = {k: v / 10 for k, v in TIMES.items()}
    out = bench.build_phase_artifact(**phase_kwargs(
        timings=fast, linearity={"d": (fast["d"], fast["d"])}))
    assert any("mfu" in s or "peak" in s for s in out["suspect"])


def test_phase_artifact_partial_label_and_reg_approximation():
    # only the steady-state pair timed: labeled partial, no weighted mfu
    pair_t = {k: TIMES[k] for k in ("d", "g")}
    pair_f = {k: FLOPS[k] for k in ("d", "g")}
    out = bench.build_phase_artifact(**phase_kwargs(
        timings=pair_t, flops=pair_f,
        fetch_s={k: 0.001 for k in pair_t},
        compile_s={k: 10.0 for k in pair_t}, linearity={}, partial=True))
    assert out["partial"] == "reg variants not yet measured"
    # the partial estimate approximates reg phases with plain ones —
    # systematically high vs the full measurement
    full = bench.build_phase_artifact(**phase_kwargs())
    assert out["value"] > full["value"]


def test_phase_artifact_device_ms_beside_wall():
    """ISSUE 8 satellite: when a profiler capture supplied per-phase
    device time, the artifact carries it next to the wall phase_ms plus
    a device-time MFU per covered phase — and the device numbers don't
    perturb the wall-derived throughput/suspect logic."""
    dev = {"d": TIMES["d"] * 1e3 * 0.98}     # device ≈ wall (honest run)
    out = bench.build_phase_artifact(**phase_kwargs(device_ms=dev))
    assert out["phase_device_ms"] == {"d": pytest.approx(dev["d"],
                                                         rel=1e-3)}
    # device-time MFU from the same FLOPs over DEVICE ms
    assert out["phase_device_mfu"]["d"] == pytest.approx(
        FLOPS["d"] / (dev["d"] / 1e3) / (PEAK * 1e12), abs=0.005)
    assert "suspect" not in out
    base = bench.build_phase_artifact(**phase_kwargs())
    assert out["value"] == base["value"]
    # no capture → the keys are absent, not empty
    assert "phase_device_ms" not in base
    assert "phase_device_mfu" not in base


def test_phase_artifact_cpu_proxy_has_null_ratio():
    out = bench.build_phase_artifact(**phase_kwargs(
        on_tpu=False, peak=None, metric="train_img_per_sec_per_chip_cpu_proxy"))
    assert out["vs_baseline"] is None
    assert "cpu proxy" in out["vs_baseline_note"]
    assert "mfu" not in out


def test_cycle_artifact_clean_and_mfu():
    k_cyc = 16
    fl_it = sum(f * w for f, w in (
        (FLOPS["d"], 15 / 16), (FLOPS["d_r1"], 1 / 16),
        (FLOPS["g"], 3 / 4), (FLOPS["g_pl"], 1 / 4)))
    per_call = fl_it * k_cyc / (0.35 * PEAK * 1e12)
    out = bench.build_cycle_artifact(
        metric="m", n_chips=1, platform="tpu", bsz=8, k_cyc=k_cyc,
        per_call_s=per_call, tail_s=0.001, n_calls=4, compile_s=30.0,
        identity=IDENTITY, peak=PEAK, cycle_flops=fl_it * k_cyc,
        device_kind="TPU v5 lite")
    assert "suspect" not in out
    assert out["method"] == "fused_cycle_16"
    assert out["mfu"] == pytest.approx(0.35, abs=0.005)
    assert out["value"] == pytest.approx(8 * k_cyc / per_call, rel=1e-3)
    assert out["cycle_flops_source"].startswith("phase cost analysis")


def test_cycle_artifact_flags_early_ack_tail():
    # sync tail comparable to the whole timed loop = the block clock lied
    out = bench.build_cycle_artifact(
        metric="m", n_chips=1, platform="tpu", bsz=8, k_cyc=16,
        per_call_s=0.5, tail_s=2.5, n_calls=4, compile_s=30.0,
        identity=IDENTITY, peak=PEAK, cycle_flops=None,
        device_kind="TPU v5 lite")
    assert any("early acks" in s for s in out["suspect"])


def test_cycle_artifact_flags_faster_than_physics():
    out = bench.build_cycle_artifact(
        metric="m", n_chips=1, platform="tpu", bsz=8, k_cyc=16,
        per_call_s=1e-4, tail_s=0.0, n_calls=4, compile_s=30.0,
        identity=IDENTITY, peak=PEAK, cycle_flops=6.4e13,
        device_kind="TPU v5 lite")
    assert any(">= 1.0" in s for s in out["suspect"])


def test_tick_probe_extracts_overlap_evidence():
    """build_tick_probe (ISSUE 2): per-tick h2d/checkpoint self-times and
    data_wait_frac from stats.jsonl records, max over ticks (the ckpt
    phase lands on the tick after the boundary that saved)."""
    records = [
        {"note": "non-tick record ignored"},
        {"timing/sec_per_tick": 50.0, "timing/data_wait_frac": 0.001,
         "timing/img_per_sec_per_chip": 2.5,
         "timing/phase/h2d": 0.25, "timing/phase/step": 49.0},
        {"timing/sec_per_tick": 40.0, "timing/data_wait_frac": 0.002,
         "timing/img_per_sec_per_chip": 3.1,
         "timing/phase/h2d": 0.0004, "timing/phase/step": 39.0,
         "timing/phase/checkpoint": 0.002, "timing/phase/ckpt/save": 0.008},
    ]
    out = bench.build_tick_probe(records)
    assert out["ticks"] == 2
    assert out["sec_per_tick"] == 40.0
    assert out["data_wait_frac"] == 0.002
    assert out["img_per_sec_per_chip"] == 3.1
    assert out["h2d_self_ms_max"] == 250.0       # max over ticks
    assert out["checkpoint_self_ms_max"] == 2.0
    assert out["phase_self_ms"]["save"] == 8.0   # last tick's breakdown
    assert out["phase_self_ms"]["h2d"] == 0.4 / 1000 * 1000
    assert bench.build_tick_probe([{"x": 1}]) == {"error": "no tick records"}


# --- bench_components attribution table (ISSUE 5) ---------------------------

def _load_components():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_components",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "bench_components.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)   # stdlib-only at import time, like bench
    return mod


COMPONENTS = [
    {"name": "pl_double_backward", "gflops": 2900.0, "gbytes": 40.0,
     "ms": 44.0, "mfu": 0.33},
    {"name": "modconv3x3_up2_128", "gflops": 400.0, "gbytes": 8.0,
     "ms": 6.2, "mfu": 0.32},
    {"name": "blur_up2_32", "gflops": 1.0, "gbytes": 0.1, "ms": 0.05,
     "mfu": 0.1},
    {"name": "init", "s": 12.0},          # no gflops → unranked tail
]


def test_attribution_table_ranked_with_shares():
    bc = _load_components()
    step_fl = 3.97e12
    rows = bc.build_attribution(COMPONENTS, step_fl, peak_tflops=197.0,
                                assumed_mfu=0.33, on_tpu=False)
    assert [r["rank"] for r in rows] == [1, 2, 3, 4]
    assert rows[0]["name"] == "pl_double_backward"
    # expected ms @ assumed MFU: flops / (mfu * peak)
    assert rows[0]["expected_ms"] == pytest.approx(
        2900e9 / (0.33 * 197e12) * 1e3, rel=1e-3)
    # share of the cadence-weighted step
    assert rows[0]["share_of_step"] == pytest.approx(2900e9 / step_fl,
                                                     abs=1e-3)
    # CPU run: measured ms is withheld (structure only)
    assert rows[0]["ms_measured"] is None
    assert rows[-1]["name"] == "init" and rows[-1]["expected_ms"] is None


def test_attribution_table_prefers_measured_ms_on_tpu():
    bc = _load_components()
    comps = [dict(COMPONENTS[0]), dict(COMPONENTS[1])]
    comps[1]["ms"] = 99.0       # slower than its FLOPs predict (bound
    rows = bc.build_attribution(comps, None, 197.0, 0.33, on_tpu=True)
    assert rows[0]["name"] == "modconv3x3_up2_128"   # measured ms wins
    assert rows[0]["ms_measured"] == 99.0
    assert rows[0]["mfu_measured"] == 0.32
    assert rows[0]["share_of_step"] is None          # no denominator


def test_attribution_expected_ms_helper():
    bc = _load_components()
    # 1 TFLOP at 50% of a 200 TFLOP/s chip = 10 ms
    assert bc.expected_ms(1e12, 200.0, 0.5) == pytest.approx(10.0)


@pytest.mark.slow   # compiles every component + the four phase programs
def test_bench_components_end_to_end_cpu(tmp_path):
    """The attribution tentpole on a small preset: the script runs on CPU
    (structure mode), emits the artifact, and the ranked table carries the
    four-phase component set with shares against the step denominator."""
    bc = _load_components()
    out = tmp_path / "components.json"
    rc = bc.main(["--preset", "clevr64-simplex", "--batch", "4",
                  "--iters", "1", "--json-out", str(out)])
    assert rc == 0
    art = json.load(open(out))
    names = {c["name"] for c in art["components"]}
    # the four phases' expected sinks are all represented
    assert "pl_double_backward" in names
    assert any(n.startswith("d_front_") for n in names)
    assert any(n.startswith("attn_block_") for n in names)
    assert any(n.startswith("attn_einsums_") for n in names)
    assert any(n.startswith("modconv3x3_up2_vjp_") for n in names)
    # ISSUE 14: every conv kernel is timed beside its XLA counterpart —
    # the *_pallas_* twins (fwd AND vjp) land in the same artifact ...
    assert any(n.startswith("modconv3x3_pallas_") for n in names)
    assert any(n.startswith("modconv3x3_up2_pallas_") for n in names)
    assert any(n.startswith("modconv3x3_up2_vjp_pallas_") for n in names)
    assert any(n.startswith("blur_up2_pallas_") for n in names)
    # ... and the roofline classification rides every cost-bearing row
    # (memory- vs compute-bound + the binding roof), including into the
    # ranked attribution table.
    with_cost = [c for c in art["components"]
                 if c.get("gflops") and c.get("gbytes")]
    assert with_cost
    for c in with_cost:
        assert c["roofline"]["bound"] in ("memory", "compute")
        assert c["roofline"]["roof_ms"] > 0
    assert any(r.get("bound") for r in art["attribution"])
    # phase denominator + ranked shares
    assert set(art["phase_gflops"]) == {"d", "g", "d_r1", "g_pl"}
    assert art["step_gflops_per_iteration"] > 0
    rows = art["attribution"]
    assert [r["rank"] for r in rows] == list(range(1, len(rows) + 1))
    ranked = [r for r in rows if r["expected_ms"] is not None]
    assert all(a["expected_ms"] >= b["expected_ms"]
               for a, b in zip(ranked, ranked[1:]))
    for r in ranked:
        assert r["share_of_step"] is not None and r["share_of_step"] > 0
        assert r["ms_measured"] is None     # CPU: structure only
    # the double-backward must rank above any leaf blur — sanity of the
    # cost model itself
    rank = {r["name"]: r["rank"] for r in rows}
    assert rank["pl_double_backward"] < rank["blur_up2_32"]


# --- expected scaling (ISSUE 6: graftcomms → bench) -------------------------

COMMS_PAYLOAD = {
    "trace_profile": "full",
    "mesh_sizes_compiled": [1, 2, 4],
    "scaling_bytes_per_device": {
        "steps.d_step[tiny-f32]": {"1": 0, "2": 120_000, "8": 210_000},
        "steps.g_step[tiny-f32]": {"1": 0, "2": 0, "8": 0},
        "steps.sample[tiny-f32]": {"1": 0, "2": 7_000, "8": 11_000},
    },
}


def test_build_expected_scaling_per_phase_efficiency():
    """graftcomms scaling bytes + measured phase ms → per-phase DP
    efficiency: 1.0 at 1 chip, monotonically non-increasing with chip
    count, and exactly 1.0 for a collective-free phase; non-phase
    entries (sample) don't leak in."""
    phase_ms = {"d": 30.0, "g": 28.0}
    out = bench.build_expected_scaling(COMMS_PAYLOAD, phase_ms,
                                       ici_bytes_per_s=1e9)
    assert set(out["per_phase_efficiency"]) == {"d", "g"}
    d = out["per_phase_efficiency"]["d"]
    assert d["1"] == 1.0
    assert d["1"] >= d["2"] >= d["8"]
    # hand-check one point: 120 kB at 1 GB/s = 0.12 ms on a 30 ms step
    assert d["2"] == pytest.approx(0.030 / (0.030 + 120_000 / 1e9),
                                   abs=1e-4)
    assert all(v == 1.0 for v in
               out["per_phase_efficiency"]["g"].values())
    assert out["assumed_ici_bytes_per_s"] == 1e9
    assert out["comms_profile"] == "full"


def test_build_expected_scaling_absent_when_nothing_matches():
    assert bench.build_expected_scaling(COMMS_PAYLOAD, {"d_r1": 5.0}) \
        is None
    assert bench.build_expected_scaling({}, {"d": 30.0}) is None


def test_build_expected_scaling_refuses_single_device_capture():
    """A 1-chip tunnel window compiles no ≥2-device mesh and records
    zero collectives — that must NOT surface as perfect scaling."""
    starved = {**COMMS_PAYLOAD, "mesh_sizes_compiled": [1]}
    assert bench.build_expected_scaling(starved, {"d": 30.0}) is None
    absent = {k: v for k, v in COMMS_PAYLOAD.items()
              if k != "mesh_sizes_compiled"}
    assert bench.build_expected_scaling(absent, {"d": 30.0}) is None


def test_load_comms_payload_tolerates_missing_and_torn(tmp_path):
    assert bench._load_comms_payload(str(tmp_path / "nope.json")) is None
    torn = tmp_path / "torn.json"
    torn.write_text("{\"scaling")
    assert bench._load_comms_payload(str(torn)) is None
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(COMMS_PAYLOAD))
    assert bench._load_comms_payload(str(ok)) == COMMS_PAYLOAD


# --- scaling bench (ISSUE 7): pure builder + diff ---------------------------

def _mesh_rec(n, img_s_chip, phase_ms, wire=0, kinds=None):
    phases = ("d", "g", "d_r1", "g_pl")
    colls = {p: dict(kinds or {}) for p in phases}
    return {
        "devices": n, "global_batch": 4 * n, "per_chip_batch": 4,
        "phase_ms": {p: phase_ms for p in phases},
        "phase_gflops_per_device": {p: 1.0 for p in phases},
        "img_per_sec_per_chip": {p: img_s_chip for p in phases},
        "collectives": colls,
        "wire_bytes_per_device": {p: wire for p in phases},
        "comms_records": [
            {"entry": f"steps.{e}[scaling]", "devices": n,
             "collectives": dict(kinds or {}),
             "total_payload_bytes": wire,
             "total_wire_bytes_per_device": wire,
             "param_bytes": 0, "opt_state_bytes": 0, "note": ""}
            for e in ("d_step", "g_step", "d_step_r1", "g_step_pl")],
    }


AR = {"all-reduce": {"count": 3, "payload_bytes": 1_000_000,
                     "wire_bytes_per_device": 1_000_000}}


def test_build_scaling_artifact_efficiency_and_floor():
    per_mesh = [_mesh_rec(1, 100.0, 10.0),
                _mesh_rec(2, 90.0, 11.1, wire=1_000_000, kinds=AR)]
    out = bench.build_scaling_artifact(
        per_mesh, platform="tpu", device_kind="TPU v5 lite",
        config_name="ffhq256-duplex", iters=10,
        ici_bytes_per_s=1e9)
    assert out["kind"] == "scaling_bench"
    assert out["mesh_sizes"] == [1, 2]
    assert out["per_phase_efficiency"]["2"]["d"] == pytest.approx(0.9)
    # floor: t_comp = 10 ms, comms = 1 MB / 1 GB/s = 1 ms → 10/11
    assert out["ring_floor_efficiency"]["2"]["d"] == pytest.approx(
        10 / 11, abs=1e-3)
    assert "suspect" not in out and "cpu_note" not in out
    # graftcomms-payload-compatible: build_expected_scaling accepts it
    assert out["mesh_sizes_compiled"] == [1, 2]
    assert out["scaling_bytes_per_device"]
    scal = bench.build_expected_scaling(
        out, per_mesh[0]["phase_ms"], ici_bytes_per_s=1e9)
    assert scal is not None
    assert scal["per_phase_efficiency"]["d"]["2"] > 0.5


def test_build_scaling_artifact_flags_replicated_phase_and_cpu():
    per_mesh = [_mesh_rec(1, 100.0, 10.0),
                _mesh_rec(2, 99.0, 10.1)]          # NO all-reduce at n=2
    out = bench.build_scaling_artifact(
        per_mesh, platform="cpu", device_kind="cpu",
        config_name="scaling-micro", iters=2)
    assert any("zero all-reduces" in s for s in out["suspect"])
    assert "cpu_note" in out
    single = bench.build_scaling_artifact(
        [_mesh_rec(1, 100.0, 10.0)], platform="cpu", device_kind="cpu",
        config_name="scaling-micro", iters=2)
    assert any("single-device" in s for s in single["suspect"])
    assert "per_phase_efficiency" not in single


def test_build_scaling_artifact_empty_capture_is_honest():
    """A device-starved run that measured NOTHING must emit an honest
    artifact (requested vs compiled distinct, suspect note), not
    crash."""
    out = bench.build_scaling_artifact(
        [], platform="tpu", device_kind="TPU v5 lite",
        config_name="ffhq256-duplex", iters=10,
        mesh_sizes_requested=[2, 4])
    assert out["mesh_sizes_compiled"] == []
    assert out["mesh_sizes_requested"] == [2, 4]
    assert any("no mesh size" in s for s in out["suspect"])
    # and requested-vs-compiled stays distinct on partial captures too
    part = bench.build_scaling_artifact(
        [_mesh_rec(1, 100.0, 10.0)], platform="cpu", device_kind="cpu",
        config_name="m", iters=1, mesh_sizes_requested=[1, 2, 4])
    assert part["mesh_sizes_requested"] == [1, 2, 4]
    assert part["mesh_sizes_compiled"] == [1]


def test_diff_comms_verdicts():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "diff_comms", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "diff_comms.py"))
    dc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dc)
    expected = {"version": 1, "min_devices": 2,
                "entries": {"g_step": {"require_kinds": ["all-reduce"]},
                            "sample": {"forbid_kinds": ["all-gather"]}}}

    def artifact(g_kinds, s_kinds, compiled=(1, 2)):
        return {"mesh_sizes_compiled": list(compiled),
                "comms": [
                    {"entry": "steps.g_step[tiny-f32]", "devices": 2,
                     "collectives": g_kinds},
                    {"entry": "steps.sample[tiny-f32]", "devices": 2,
                     "collectives": s_kinds}]}

    ok = dc.diff_comms(artifact({"all-reduce": {"count": 1,
                                                "payload_bytes": 8}}, {}),
                       expected)
    assert ok["verdict"] == "ok" and ok["checked"] == ["g_step", "sample"]
    # the replicated-compute regression reads as a mismatch in words
    bad = dc.diff_comms(artifact({}, {}), expected)
    assert bad["verdict"] == "mismatch"
    assert any("replicated compute" in m for m in bad["mismatches"])
    # forbidden inference gather
    gather = dc.diff_comms(
        artifact({"all-reduce": {"count": 1, "payload_bytes": 8}},
                 {"all-gather": {"count": 1, "payload_bytes": 512}}),
        expected)
    assert gather["verdict"] == "mismatch"
    # a 1-chip window is INCONCLUSIVE (exit 0), never a false regression
    inc = dc.diff_comms(artifact({}, {}, compiled=(1,)), expected)
    assert inc["verdict"] == "inconclusive" and inc["mismatches"] == []


def test_checked_in_comms_expectation_covers_every_entry():
    """COMMS_EXPECTED.json names every catalog entry: the train steps +
    cycle require a gradient all-reduce, the inference programs forbid
    a param gather."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "COMMS_EXPECTED.json")) as f:
        exp = json.load(f)
    entries = exp["entries"]
    for s in ("d_step", "d_step_r1", "g_step", "g_step_pl", "cycle"):
        assert "all-reduce" in entries[s]["require_kinds"], s
    for s in ("sample", "ppl_pairs"):
        assert "all-gather" in entries[s]["forbid_kinds"], s
    assert exp["min_devices"] >= 2


@pytest.mark.slow
def test_run_scaling_end_to_end_two_device_capture(tmp_path):
    """ISSUE 7 acceptance: ``run_scaling`` (the --scaling core) on the
    micro config at mesh 1+2 emits an artifact with a >= 2-device
    capture that (a) shows the gradient all-reduce in every train
    phase, (b) ``build_expected_scaling`` accepts, and (c) carries the
    per-phase efficiency + ring-floor sections."""
    from gansformer_tpu.analysis.trace.entry_points import tiny_config

    out_path = str(tmp_path / "MULTICHIP_test.json")
    cfg = tiny_config()
    out = bench.run_scaling(cfg, (1, 2), per_chip_batch=4, iters=1,
                            out_path=out_path)
    assert out["mesh_sizes_compiled"] == [1, 2]
    for ph, kinds in out["per_mesh"]["2"]["collectives"].items():
        assert "all-reduce" in kinds, ph
    assert "suspect" not in out
    assert out["per_phase_efficiency"]["2"]
    assert out["ring_floor_efficiency"]["2"]
    saved = json.load(open(out_path))
    assert bench.build_expected_scaling(
        saved, saved["per_mesh"]["1"]["phase_ms"]) is not None
