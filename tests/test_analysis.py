"""Tests for the graftlint framework itself (gansformer_tpu/analysis):
rule registry, single-walk driver, suppression parsing, reporter golden
output, baseline determinism/consumption, and the CLI contract."""

import json
import os

from gansformer_tpu.analysis import all_rules, lint_paths, lint_source
from gansformer_tpu.analysis.baseline import Baseline, line_text_lookup
from gansformer_tpu.analysis.cli import main as cli_main
from gansformer_tpu.analysis.engine import iter_python_files
from gansformer_tpu.analysis.findings import Finding
from gansformer_tpu.analysis.reporters import render_json, render_text

EXPECTED_RULES = {
    "host-sync-in-jit", "donation-after-use", "rng-key-reuse",
    "hot-loop-sync", "telemetry-name-convention",
    # the concurrency pass (ISSUE 18) — unguarded-shared-attribute
    # absorbs the retired thread-shared-state rule
    "unguarded-shared-attribute", "lock-order-inversion",
    "thread-lifecycle", "signal-handler-safety", "condition-protocol",
}

BAD_RNG = """\
import jax

def f(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))
    return a + b
"""


# --- registry / engine ------------------------------------------------------

def test_registry_contains_the_expected_rules():
    ids = {r.id for r in all_rules()}
    assert EXPECTED_RULES <= ids
    for r in all_rules():
        assert r.description and r.hint and r.node_types


def test_syntax_error_becomes_parse_finding():
    findings = lint_source("def broken(:\n", path="x.py")
    assert len(findings) == 1 and findings[0].rule == "parse-error"


def test_findings_sorted_and_deduped():
    findings = lint_source(BAD_RNG, path="x.py")
    assert findings == sorted(findings, key=Finding.sort_key)
    assert len({(f.rule, f.line, f.col, f.message) for f in findings}) \
        == len(findings)


def test_iter_python_files_deterministic_and_skips_pycache(tmp_path):
    (tmp_path / "b.py").write_text("x = 1\n")
    (tmp_path / "a.py").write_text("x = 1\n")
    sub = tmp_path / "__pycache__"
    sub.mkdir()
    (sub / "c.py").write_text("x = 1\n")
    (tmp_path / "notes.txt").write_text("not python\n")
    got = iter_python_files([str(tmp_path)])
    assert [os.path.basename(p) for p in got] == ["a.py", "b.py"]
    assert got == iter_python_files([str(tmp_path), str(tmp_path / "a.py")])


# --- reporters --------------------------------------------------------------

def test_text_reporter_golden():
    findings = lint_source(BAD_RNG, path="pkg/x.py")
    assert len(findings) == 1
    text = render_text(findings, files_checked=1)
    lines = text.splitlines()
    assert lines[0].startswith("pkg/x.py:6:27: rng-key-reuse: PRNG key "
                               "'key' passed to a second consuming call")
    assert "(fix: split the key" in lines[0]
    assert lines[-1] == ("graftlint: 1 file(s), 1 finding(s) — 1 new, "
                         "0 suppressed, 0 baselined")


def test_text_reporter_hides_non_new_unless_verbose():
    findings = lint_source(BAD_RNG, path="x.py")
    findings[0].suppressed = True
    quiet = render_text(findings, files_checked=1)
    assert "rng-key-reuse" not in quiet.splitlines()[0] or \
        len(quiet.splitlines()) == 1
    loud = render_text(findings, files_checked=1, verbose=True)
    assert "[suppressed]" in loud


def test_json_reporter_golden():
    findings = lint_source(BAD_RNG, path="x.py")
    payload = json.loads(render_json(findings, files_checked=3))
    assert payload["version"] == 1
    assert payload["ok"] is False
    assert payload["files_checked"] == 3
    assert payload["counts"] == {"total": 1, "new": 1, "suppressed": 0,
                                 "baselined": 0}
    (f,) = payload["findings"]
    assert f["rule"] == "rng-key-reuse" and f["line"] == 6
    assert f["new"] is True and f["path"] == "x.py"


# --- baseline ---------------------------------------------------------------

def test_baseline_write_is_deterministic(tmp_path):
    src = tmp_path / "m.py"
    src.write_text(BAD_RNG)
    findings = lint_paths([str(src)])
    assert findings
    look = line_text_lookup()
    p1, p2 = tmp_path / "b1.json", tmp_path / "b2.json"
    Baseline.write(str(p1), findings, look)
    Baseline.write(str(p2), findings, look)
    assert p1.read_bytes() == p2.read_bytes()
    data = json.loads(p1.read_text())
    assert data["entries"] and data["entries"][0]["path"] == "m.py"
    assert not os.path.isabs(data["entries"][0]["path"])


def test_baseline_survives_line_drift_but_not_line_edit(tmp_path):
    src = tmp_path / "m.py"
    src.write_text(BAD_RNG)
    look = line_text_lookup()
    bl = tmp_path / "baseline.json"
    Baseline.write(str(bl), lint_paths([str(src)]), look)

    # shift the finding down two lines: still baselined
    src.write_text("# pad\n# pad\n" + BAD_RNG)
    shifted = lint_paths([str(src)])
    Baseline.load(str(bl)).apply(shifted, line_text_lookup())
    assert all(f.baselined for f in shifted)

    # edit the flagged line itself: resurfaces as new
    edited = BAD_RNG.replace("jax.random.uniform(key, (2,))",
                             "jax.random.uniform(key, (3,))")
    src.write_text(edited)
    fresh = lint_paths([str(src)])
    Baseline.load(str(bl)).apply(fresh, line_text_lookup())
    assert all(f.new for f in fresh)


def test_baseline_entry_consumed_once(tmp_path):
    # two identical violations on identical lines: one baseline entry
    # absolves exactly one of them
    double = BAD_RNG + "\n\n" + BAD_RNG.replace("def f", "def g")
    src = tmp_path / "m.py"
    src.write_text(double)
    findings = lint_paths([str(src)])
    assert len(findings) == 2
    look = line_text_lookup()
    bl = tmp_path / "baseline.json"
    Baseline.write(str(bl), findings[:1], look)
    fresh = lint_paths([str(src)])
    Baseline.load(str(bl)).apply(fresh, line_text_lookup())
    assert sum(f.baselined for f in fresh) == 1
    assert sum(f.new for f in fresh) == 1


# --- CLI --------------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_RNG)
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")

    assert cli_main([str(clean), "--no-baseline"]) == 0
    assert cli_main([str(bad), "--no-baseline"]) == 1
    assert cli_main([]) == 2
    assert cli_main(["--select", "not-a-rule", str(clean)]) == 2
    # a typo'd path must NOT read as a green lint over zero files
    assert cli_main([str(tmp_path / "no_such_dir")]) == 2
    # a scoped --fix-baseline would silently drop other rules' entries
    assert cli_main(["--fix-baseline", "--select", "rng-key-reuse",
                     "--baseline", str(tmp_path / "b.json"),
                     str(bad)]) == 2
    capsys.readouterr()


def test_cli_fix_baseline_roundtrip(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_RNG)
    bl = tmp_path / "baseline.json"
    assert cli_main(["--fix-baseline", "--baseline", str(bl),
                     str(bad)]) == 0
    first = bl.read_bytes()
    # baselined: the same tree now lints clean
    assert cli_main(["--baseline", str(bl), str(bad)]) == 0
    # deterministic: regenerating writes identical bytes
    assert cli_main(["--fix-baseline", "--baseline", str(bl),
                     str(bad)]) == 0
    assert bl.read_bytes() == first
    capsys.readouterr()


def test_cli_json_format_and_select(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_RNG)
    rc = cli_main(["--format", "json", "--no-baseline",
                   "--select", "rng-key-reuse", str(bad)])
    out = capsys.readouterr().out
    payload = json.loads(out)
    assert rc == 1 and payload["ok"] is False
    assert {f["rule"] for f in payload["findings"]} == {"rng-key-reuse"}
    rc = cli_main(["--format", "json", "--no-baseline",
                   "--select", "hot-loop-sync", str(bad)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["findings"] == []


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in EXPECTED_RULES | {"telemetry-schema"}:
        assert rule_id in out


# --- telemetry artifact lint (the non-AST rule family) ----------------------

def test_lint_run_dir_findings_and_cli(tmp_path, capsys):
    from gansformer_tpu.analysis.telemetry_schema import lint_run_dir

    # empty run dir: every artifact missing → findings, rule telemetry-schema
    findings = lint_run_dir(str(tmp_path))
    assert findings and all(f.rule == "telemetry-schema" for f in findings)
    assert all(f.new for f in findings)

    (tmp_path / "events.jsonl").write_text(
        '{"name": "step", "ph": "X", "ts": 1, "dur": 2, '
        '"pid": 0, "tid": 0}\n')
    # missing the ISSUE 8 families (device/hbm/compile markers) is itself
    # a finding — "no device numbers" must be explicit, never silent
    (tmp_path / "telemetry.prom").write_text(
        "# TYPE data_wait_ms summary\ndata_wait_ms_count 3.0\n")
    (tmp_path / "heartbeat-p0.json").write_text(json.dumps(
        {"process": 0, "pid": 1, "host": "h", "time": 1.0,
         "step": 0, "kimg": 0.0}))
    findings = lint_run_dir(str(tmp_path))
    assert findings
    msgs = " ".join(f.message for f in findings)
    assert "device_sampler_off" in msgs and "hbm_unavailable" in msgs \
        and "compile_compiles_total" in msgs
    (tmp_path / "telemetry.prom").write_text(
        "# TYPE data_wait_ms summary\ndata_wait_ms_count 3.0\n"
        "# TYPE device_sampler_off gauge\ndevice_sampler_off 1.0\n"
        "# TYPE hbm_unavailable gauge\nhbm_unavailable 1.0\n"
        "# TYPE compile_compiles_total counter\n"
        "compile_compiles_total 0.0\n"
        "# TYPE compile_retraces_total counter\n"
        "compile_retraces_total 0.0\n"
        "# TYPE data_read_retries_total counter\n"
        "data_read_retries_total 0.0\n"
        "# TYPE data_corrupt_records_total counter\n"
        "data_corrupt_records_total 0.0\n"
        "# TYPE data_stalls_total counter\n"
        "data_stalls_total 0.0\n"
        "# TYPE ops_modconv_fallback_total counter\n"
        "ops_modconv_fallback_total 0.0\n"
        "# TYPE ops_modconv_fallback_shape_total counter\n"
        "ops_modconv_fallback_shape_total 0.0\n"
        "# TYPE ops_modconv_fallback_vmem_total counter\n"
        "ops_modconv_fallback_vmem_total 0.0\n"
        "# TYPE train_nonfinite_total counter\n"
        "train_nonfinite_total 0.0\n"
        "# TYPE train_nonfinite_loss_total counter\n"
        "train_nonfinite_loss_total 0.0\n"
        "# TYPE train_nonfinite_grad_total counter\n"
        "train_nonfinite_grad_total 0.0\n"
        "# TYPE train_nonfinite_param_total counter\n"
        "train_nonfinite_param_total 0.0\n")
    assert lint_run_dir(str(tmp_path)) == []

    rc = cli_main(["--run-dir", str(tmp_path)])
    assert rc == 0
    capsys.readouterr()

    # a malformed event line carries file:line through to the Finding
    (tmp_path / "events.jsonl").write_text('{"name": "x"}\n')
    findings = lint_run_dir(str(tmp_path))
    assert any(f.line == 1 and f.path.endswith("events.jsonl")
               for f in findings)


def test_check_metric_families_value_aware(tmp_path):
    """The family check reads VALUES, not just names: a sampler that
    claims to be on with landed samples must also export the divergence
    gauges; a reporting backend must export the hbm numbers."""
    from gansformer_tpu.analysis.telemetry_schema import (
        check_metric_families)

    p = tmp_path / "telemetry.prom"
    data = ("data_read_retries_total 0.0\n"
            "data_corrupt_records_total 0.0\ndata_stalls_total 0.0\n"
            "ops_modconv_fallback_total 0.0\n"
            "ops_modconv_fallback_shape_total 0.0\n"
            "ops_modconv_fallback_vmem_total 0.0\n"
            "train_nonfinite_total 0.0\n"
            "train_nonfinite_loss_total 0.0\n"
            "train_nonfinite_grad_total 0.0\n"
            "train_nonfinite_param_total 0.0\n")
    base = ("hbm_unavailable 0.0\nhbm_bytes_in_use 1.0\n"
            "hbm_peak_bytes 2.0\ncompile_compiles_total 1.0\n"
            "compile_retraces_total 0.0\n" + data)
    p.write_text("device_sampler_off 0.0\ndevice_samples_total 2.0\n"
                 + base)
    assert any("divergence" in e for e in check_metric_families(str(p)))
    p.write_text("device_sampler_off 0.0\ndevice_samples_total 2.0\n"
                 "device_wall_busy_ratio 0.9\ndevice_busy_ms 900.0\n"
                 + base)
    assert check_metric_families(str(p)) == []
    # backend claims memory reporting but exports no numbers
    p.write_text("device_sampler_off 1.0\nhbm_unavailable 0.0\n"
                 "compile_compiles_total 1.0\n"
                 "compile_retraces_total 0.0\n" + data)
    assert any("hbm_bytes_in_use" in e
               for e in check_metric_families(str(p)))


def test_check_metric_families_data_robustness(tmp_path):
    """ISSUE 15: the data/* robustness counters are REQUIRED (the loop
    materializes them at setup — absence means rotted wiring), and a
    moved quarantine counter demands the ledger evidence beside it."""
    from gansformer_tpu.analysis.telemetry_schema import (
        check_metric_families)

    head = ("device_sampler_off 1.0\nhbm_unavailable 1.0\n"
            "compile_compiles_total 1.0\ncompile_retraces_total 0.0\n")
    ops = ("ops_modconv_fallback_total 0.0\n"
           "ops_modconv_fallback_shape_total 0.0\n"
           "ops_modconv_fallback_vmem_total 0.0\n"
           "train_nonfinite_total 0.0\n"
           "train_nonfinite_loss_total 0.0\n"
           "train_nonfinite_grad_total 0.0\n"
           "train_nonfinite_param_total 0.0\n")
    p = tmp_path / "telemetry.prom"
    # missing family members (the ISSUE-17 conv fallback counters are
    # held to the same explicit-marker discipline)
    p.write_text(head)
    errs = check_metric_families(str(p))
    for name in ("data_read_retries_total", "data_corrupt_records_total",
                 "data_stalls_total", "ops_modconv_fallback_total",
                 "ops_modconv_fallback_shape_total",
                 "ops_modconv_fallback_vmem_total",
                 "train_nonfinite_total", "train_nonfinite_loss_total",
                 "train_nonfinite_grad_total",
                 "train_nonfinite_param_total"):
        assert any(name in e for e in errs), (name, errs)
    # quarantines moved without the jsonl ledger beside the prom
    p.write_text(head + ops + "data_read_retries_total 0.0\n"
                 "data_corrupt_records_total 2.0\ndata_stalls_total 0.0\n")
    assert any("data_quarantine.jsonl" in e
               for e in check_metric_families(str(p)))
    # ledger present → clean
    (tmp_path / "data_quarantine.jsonl").write_text(
        '{"file": "x", "offset": 0, "cause": "payload-crc"}\n')
    assert check_metric_families(str(p)) == []
