"""Schema lint for a run dir's telemetry artifacts (ISSUE 1 CI task).

SHIM — the checker now lives in the graftlint framework
(``gansformer_tpu/analysis/telemetry_schema.py``, ISSUE 3); this script
keeps the original entry point and module API (``check_events`` /
``check_prom`` / ``check_heartbeat`` / ``check_run_dir``, result shape
``{ok, checked, errors}``) so existing invocations (tests/test_obs.py,
the verify recipe) keep working:

  python scripts/check_telemetry.py <run_dir>

Prefer ``gansformer-lint --run-dir <run_dir>`` for new wiring; see
docs/static-analysis.md.
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:          # direct `python scripts/…` invocation
    sys.path.insert(0, _ROOT)

from gansformer_tpu.analysis.telemetry_schema import (  # noqa: E402,F401
    EVENT_KEYS,
    HEARTBEAT_KEYS,
    PROM_NAME,
    PROM_TYPES,
    check_events,
    check_heartbeat,
    check_metric_families,
    check_prom,
    check_run_dir,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
