"""Cost-analysis probe: does XLA count/execute strided-conv backwards naively?

Context (VERDICT r4 item 2): two candidate MFU optimizations for D's
down-convs were on the table —

  * phase-split the stride-2 conv into 4 stride-1 convs on input parity
    grids so autodiff never emits an lhs-dilated (zero-inserting)
    backward-input conv;
  * fold the anti-aliasing blur's taps into the conv kernel (one 6x6
    dense conv instead of blur + 3x3).

This probe settles the first empirically: it lowers value-and-grad of a
stride-2 3x3 conv at a flagship-like shape and reads XLA's post-
optimization cost analysis.  If the backward-input conv were counted (and
executed) as the naive zero-inserted correlation, grad-x would add ~4x the
forward FLOPs; measured it adds exactly ~1x — XLA rewrites backward convs
into efficient strided forms before cost analysis, so there is nothing for
a hand-written polyphase backward to save.  (The r4 polyphase UP-conv win
was different: there the *forward* op was lhs-dilated, which XLA does NOT
rewrite.)  Recorded in PERF.md §1b''''.

  PYTHONPATH= JAX_PLATFORMS=cpu python scripts/probe_backward_conv.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    n, h, ci, co = 8, 256, 64, 128
    x = jnp.zeros((n, h, h, ci), jnp.bfloat16)
    w = jnp.zeros((3, 3, ci, co), jnp.bfloat16)

    def conv_s2(x, w):
        return lax.conv_general_dilated(
            x, w, (2, 2), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def flops(fn, *args):
        ca = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float(ca.get("flops", 0.0))

    # Squared loss: a non-trivial cotangent, so the weight-grad conv cannot
    # be algebraically simplified away (an all-ones cotangent from sum()
    # lets XLA fold it into a reduce-window and hide its FLOPs).
    def loss(x, w):
        return jnp.sum(jnp.square(conv_s2(x, w).astype(jnp.float32)))

    f_fwd = flops(conv_s2, x, w)
    f_gx = flops(jax.grad(loss, 0), x, w)
    f_gw = flops(jax.grad(loss, 1), x, w)
    f_both = flops(jax.grad(loss, (0, 1)), x, w)
    naive_gx = 2.0 * n * h * h * ci * co * 9
    out = {
        "shape": f"[{n},{h},{h},{ci}] * 3x3 s2 -> {co}",
        "fwd_gflops": round(f_fwd / 1e9, 2),
        "grad_x_gflops": round(f_gx / 1e9, 2),
        "grad_w_gflops": round(f_gw / 1e9, 2),
        "grad_both_gflops": round(f_both / 1e9, 2),
        "grad_both_over_fwd": round(f_both / f_fwd, 3),
        "naive_dilated_input_grad_gflops": round(naive_gx / 1e9, 2),
        "verdict": ("backward convs counted/executed efficiently — "
                    "polyphase backward has nothing to save"
                    if f_both < 4.0 * f_fwd else
                    "backward convs counted naively — polyphase backward "
                    "would pay; re-evaluate"),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
