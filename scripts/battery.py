"""Stage-completion ledger + battery runner for the TPU tunnel windows.

The tunnel serves minutes-long windows separated by hours of outage
(PERF.md §1c availability tally), and r5's single-shot battery burned the
round's only window on the first partial claim (VERDICT r5 item 1 /
weak #2).  This module makes the battery MULTI-WINDOW and RESUMABLE:

* Every window gets its own ``<out>/window_<ts>/`` directory with a
  ``done.json`` ledger mapping stage name → {exit, duration_s, artifact}.
  The ledger is appended atomically after EACH stage, so a window that
  dies mid-battery (tunnel drop, kill, power) keeps every completed
  stage's record.
* ``completed_stages()`` is the union of successful stages over ALL
  windows; ``run_battery()`` fires only the missing ones — the next
  window resumes where the last one died instead of repeating the head.
* After a stage fails, the (cheap) backend probe runs between stages:
  a dead tunnel aborts the window immediately instead of burning the
  remaining budgets against a wedged claim loop.

Stage order is most-important-first (VERDICT r5 item 1): the four-phase +
fused-cycle bench JSON (no sweep, 600 s inner budget) lands within the
first ~10 minutes of the FIRST window; the attribution + lever A/B +
graftcomms stages follow so one window converts into a measured decision
table (PERF.md §1d) plus a TPU-compiled comms table (ISSUE 6); the
sweep/pallas/train stages ride later windows if needed.

  python scripts/battery.py run    [--out .probe]     # exit 0=complete, 3=partial
  python scripts/battery.py status [--out .probe]     # same exits, no side effects

``scripts/probe_and_bench.sh`` is the minute-0 loop around this: probe
every PROBE_INTERVAL, re-fire on every successful claim until the ledger
says complete.  ``GRAFT_PROBE_CMD`` overrides the backend probe (tests).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_TIMEOUT_S = 120
MARKER = "BATTERY_RUNNING"


def stage(name, budget_s, artifact, argv, env=None, copies=()):
    return {"name": name, "budget_s": budget_s, "artifact": artifact,
            "argv": list(argv), "env": dict(env or {}),
            "copies": list(copies)}


def default_stages():
    py = sys.executable
    return [
        # 1. Four phases + fused cycle, NO sweep: the round's headline
        #    numbers inside ~10 minutes (inner budget 600 s; bench.py
        #    emits a partial JSON line as soon as the (D, G) pair times).
        stage("bench_phases", 780, "bench_tpu.json", [py, "bench.py"],
              env={"GRAFT_BENCH_TPU_TIMEOUT": "600",
                   "GRAFT_BENCH_SWEEP": ""},
              copies=[(".bench_phases.json", "bench_phases_tpu.json")]),
        # 2. Per-op cost attribution (profiler substitute — the tracer
        #    wedges the tunnel, PERF.md §1c).
        stage("components", 900, "components_tpu.jsonl",
              [py, "scripts/bench_components.py",
               "--json-out", "{win}/components_attribution.json"]),
        # 3. Flag-gated lever A/B — the measured decision table.
        stage("ab_levers", 1500, "ab_levers_tpu.jsonl",
              [py, "scripts/ab_levers.py",
               "--json-out", "{win}/ab_levers_tpu.json"]),
        # 4. ffhq1024 memory readiness (VERDICT r5 item 5).
        stage("readiness_1024", 900, "readiness_1024_tpu.jsonl",
              [py, "scripts/readiness_ffhq1024.py",
               "--json-out", "{win}/readiness_1024_tpu.json"]),
        # 5. graftcomms (ISSUE 6): TPU-compiled collective inventory +
        #    sharding-contract check over the full trace matrix.
        #    --trace-native keeps the ambient TPU backend (mesh sizes
        #    clamp to the window's chip count); the comms attribution
        #    lands in the repo root so later bench stages/windows embed
        #    expected_scaling, and is copied into the window ledger.
        #    The stage's job is CAPTURE, not gating: lint exit 1 (new
        #    findings — the discovery case) still counts as completed
        #    as long as the artifact was written, otherwise a real
        #    finding would re-burn 900 s in every window forever.
        #    After the capture, diff the ranked comms table against the
        #    checked-in expectation (COMMS_EXPECTED.json; ISSUE 7): the
        #    train steps MUST show a gradient all-reduce on a multi-
        #    device mesh.  The diff verdict lands in the window ledger
        #    ({win}/comms_diff.json + battery.log) but does NOT gate
        #    stage completion — capture beats verdict, same rationale
        #    as the lint exit handling.
        stage("graftcomms", 900, "graftcomms_tpu.json",
              ["sh", "-c",
               f"{py} -m gansformer_tpu.analysis.cli --trace"
               f" --trace-native --trace-profile full --format json"
               f" --json-out .comms_attribution.json; rc=$?;"
               f" {py} scripts/diff_comms.py .comms_attribution.json"
               f" --json-out {{win}}/comms_diff.json;"
               f" [ $rc -le 1 ] && [ -s .comms_attribution.json ]"],
              copies=[(".comms_attribution.json",
                       "comms_attribution.json")]),
        # 6. Scaling-efficiency bench (ISSUE 7): the four phases on
        #    data meshes of 1/2/4 chips (clamped to the window's
        #    devices) — measured per-phase img/s/chip efficiency vs the
        #    ring-model floor, collective inventory included.  Writes
        #    the numbered MULTICHIP_r* round artifact; the stable copy
        #    is preserved into the window (incrementally re-written per
        #    mesh, so a timed-out stage still leaves the partial
        #    capture).  Inner budget 700 < the 900 s stage budget —
        #    ~90 s probe + shutdown headroom, same discipline as
        #    bench_phases (600/780).
        stage("bench_scaling", 900, "bench_scaling_tpu.json",
              [py, "bench.py", "--scaling"],
              env={"GRAFT_SCALING_TIMEOUT": "700"},
              copies=[(".scaling_bench.json", "scaling_bench.json")]),
        # 6b. Serving load test (ISSUE 10): the AOT generation service
        #     under a Zipfian seed/ψ mix on the real flagship G
        #     (random-init — serving PERFORMANCE needs the architecture,
        #     not trained weights, and decoupling from the train stage
        #     keeps the ledger's stages independent across windows).
        #     Capture beats verdict: the script exits 0 whenever the
        #     JSON lands; p50/p99 + img/s/chip + cold-vs-warm
        #     first-image live in {win}/serve_loadtest.json.  Inner
        #     bound: 300 requests / 600 s submit window under the 900 s
        #     stage budget.  The manifest dir is PERSISTENT (repo root,
        #     like .jax_compile_cache) so only the FIRST window pays
        #     the flagship compiles — without it every window would
        #     mkdtemp a fresh manifest and re-pay 6 × 30–100 s cold
        #     compiles, busting the budget before the submit window.
        #     The per-request trace ledger ({win}/requests.jsonl) is
        #     archived per window so the artifact's p99 / worst-request
        #     IDs resolve to full timelines (gansformer-telemetry
        #     requests {win} --id <rid>) long after the run.
        #     --autoscale (ISSUE 20): the run rides a ReplicaSet —
        #     replica-per-chip placement with the controller free to
        #     scale across the window's devices — so the artifact
        #     carries per-replica attribution (requests / img/s /
        #     batch-fill / dispatch share per device) and the
        #     img_s_per_chip headline normalized by replicas USED, not
        #     chips present.  Works on a 1-device window too (the
        #     fleet just never scales past its only member).
        stage("serve_loadtest", 900, "serve_loadtest_tpu.json",
              [py, "scripts/loadtest_serve.py",
               "--preset", "ffhq256-duplex", "--init", "random",
               "--buckets", "1,4,8", "--requests", "300", "--rate", "8",
               "--duration-s", "600", "--autoscale",
               "--manifest-dir", ".serve_manifest",
               "--requests-out", "{win}/requests.jsonl",
               "--json-out", "{win}/serve_loadtest.json"]),
        # 6c. Serving overload/chaos drill (ISSUE 13): burst 4x the
        #     admission bound back-to-back with one injected dispatcher
        #     crash mid-burst — proves the degradation contract on real
        #     hardware: typed shedding (not unbounded queueing), the
        #     self-healing restart, p99-under-overload, recovery time,
        #     and zero hung tickets.  Capture beats verdict: the stage
        #     completes on the LOADTEST exit code (0 whenever
        #     {win}/serve_chaos.json lands); the doctor then grades the
        #     window — its serve_chaos section FAILs on hung tickets —
        #     into {win}/serve_doctor.json without gating completion.
        #     --prom-out / --requests-out keep the chaos-state prom and
        #     trace ledger out of 6b's {win}/telemetry.prom and
        #     {win}/requests.jsonl (the SLO run's artifacts must survive
        #     unclobbered); the chaos artifact's trace_coverage section
        #     asserts every hung/failed ticket reached a terminal trace
        #     event with a cause.  The shared persistent manifest means
        #     the flagship compiles were already paid by 6b.
        #     --autoscale (ISSUE 20): the drill also runs the
        #     controller's ordering contract on real hardware — the
        #     artifact's autoscale section (scale-out before any
        #     breaker trip, scale-in after recovery) is graded by the
        #     doctor's serve_autoscale check (WARN, never FAIL).
        stage("serve_chaos", 600, "serve_chaos_tpu.json",
              ["sh", "-c",
               f"{py} scripts/loadtest_serve.py --chaos"
               f" --preset ffhq256-duplex --init random"
               f" --buckets 1,4,8 --queue-depth 16"
               f" --burst-factor 4 --crash-at-batch 2 --autoscale"
               f" --manifest-dir .serve_manifest"
               f" --json-out {{win}}/serve_chaos.json"
               f" --requests-out {{win}}/serve_chaos_requests.jsonl"
               f" --prom-out {{win}}/serve_chaos.prom; rc=$?;"
               f" {py} -m gansformer_tpu.cli.telemetry doctor {{win}}/"
               f" --json-out {{win}}/serve_doctor.json"
               f" >/dev/null 2>&1; exit $rc"]),
        # 7. Batch sweep (the optional throughput upside).
        stage("bench_sweep", 1800, "bench_sweep_tpu.json", [py, "bench.py"],
              env={"GRAFT_BENCH_TPU_TIMEOUT": "1500",
                   "GRAFT_BENCH_SWEEP": "16,32"}),
        # 8. Native-kernel record (Mosaic compile + parity) — now also
        #    times grad and the R1/PL-shaped grad-of-grad per direction
        #    and records the compiled-program byte evidence (ISSUE 9).
        stage("pallas", 600, "pallas_tpu.json",
              [py, "scripts/bench_pallas_attention.py"]),
        # 8b. Training-path kernel A/B (ISSUE 9): the four REAL step
        #    programs compiled at attention_backend xla vs pallas —
        #    cost-analysis FLOPs/bytes/temp-workspace deltas plus
        #    steady-state ms per phase, unattended.  The compiles are
        #    the cost (8 second-order-ish programs, warm via the
        #    persistent cache after the first window); timing itself is
        #    10 iters/phase.
        stage("pallas_train_ab", 1500, "pallas_train_ab_tpu.jsonl",
              [py, "scripts/bench_pallas_attention.py", "--train-ab",
               "--batch", "8"]),
        # 8c. Conv-family kernel A/B (ISSUE 14): the same four-program
        #    harness with conv_backend xla vs pallas — the modulated-
        #    conv/upfirdn kernels (the 33%→51% MFU tier, ROADMAP 1)
        #    priced on the REAL step programs with zero new plumbing.
        #    Gated by the conv-family native smoke check inside the
        #    script (skip-don't-crash; xla rows still land).  The
        #    preset is pinned: with ISSUE 17's row blocking the ffhq256
        #    step programs route EVERY conv/FIR grid through the Pallas
        #    kernels (pre-17 the 128²/256² grids silently fell back to
        #    XLA, so this A/B priced only the small grids); the smoke
        #    check now also lowers a row-blocked fwd+bwd natively.
        stage("modconv_train_ab", 1500, "modconv_train_ab_tpu.jsonl",
              [py, "scripts/bench_pallas_attention.py", "--train-ab",
               "--ab-backend", "conv", "--preset", "ffhq256-duplex",
               "--batch", "8"]),
        # 9. Real loop on the chip — now run UNDER the supervisor with
        #    one injected SIGKILL mid-checkpoint (ISSUE 12), so every
        #    tunnel window that trains also PROVES crash→resume recovery
        #    on real hardware: the kill fires once (fault ledger), the
        #    supervisor classifies it and re-arms, and the run completes
        #    to 8 kimg with a supervisor_events.jsonl the doctor's
        #    availability section grades.  stats.jsonl carries
        #    timing/mfu as before.
        #    --device-time-ticks 0: the periodic device-truth sampler is
        #    OFF for this unattended stage — a client killed mid-trace
        #    was observed (r4) to wedge the tunnel's backend claim for
        #    20+ minutes, and a wedged claim here would re-burn this
        #    stage's budget every window forever.  Device truth for the
        #    battery comes from the witness/doctor instead.  After the
        #    run, the doctor's JSON report (ISSUE 8) is archived into
        #    the window ledger; capture beats verdict (same rationale as
        #    graftcomms) — the stage completes on the SUPERVISE exit
        #    code (0 = trained through the injected crash).
        #    Since ISSUE 15 the stage trains from a TFRECORD source (a
        #    synthetic set converted up front — the reference's on-disk
        #    format, read through the indexed fault-tolerant plane) and
        #    arms a second fault, one transient read error
        #    (raise@data_read_error), so every tunnel window also proves
        #    the bounded-backoff IO retry path end to end:
        #    data/read_retries_total lands in telemetry.prom and the
        #    doctor's data_plane section grades it (WARN = the drill
        #    worked; its JSON is archived either way).
        stage("train_ticks", 1200, None,
              ["sh", "-c",
               f"{py} -m gansformer_tpu.cli.prepare_data --synthetic"
               f" --to tfrecord --out {{win}}/train_tpu/data"
               f" --resolution 256 --max-images 512 &&"
               f" {py} -m gansformer_tpu.cli.supervise"
               f" --run-dir {{win}}/train_tpu/run"
               f" --max-restarts 4 --poll-interval 5"
               f" --heartbeat-max-age 300 --startup-grace 600"
               f" --fault sigkill@ckpt_mid_write:step=4000"
               f" --fault raise@data_read_error:n=64 --"
               f" --preset ffhq256-duplex --data-source tfrecord"
               f" --data-path {{win}}/train_tpu/data"
               f" --batch-size 8 --total-kimg 8 --fused-cycle"
               f" --device-time-ticks 0; rc=$?;"
               f" {py} -m gansformer_tpu.cli.telemetry doctor"
               f" {{win}}/train_tpu/run --json-out {{win}}/doctor.json;"
               f" exit $rc"]),
    ]


def default_probe_argv():
    override = os.environ.get("GRAFT_PROBE_CMD")
    if override:
        return ["sh", "-c", override]
    # PYTHONPATH stays ambient: the axon sitecustomize IS the TPU plugin.
    return [sys.executable, "-c",
            "import jax; d = jax.devices(); "
            "assert d[0].platform == 'tpu', d; print(d[0].device_kind)"]


def probe_ok(probe_argv=None, timeout=PROBE_TIMEOUT_S) -> bool:
    try:
        return subprocess.run(default_probe_argv()
                              if probe_argv is None else probe_argv,
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL,
                              timeout=timeout).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


# --- ledger ------------------------------------------------------------


def window_dirs(root):
    if not os.path.isdir(root):
        return []
    return sorted(os.path.join(root, d) for d in os.listdir(root)
                  if d.startswith("window_")
                  and os.path.isdir(os.path.join(root, d)))


def load_done(win) -> dict:
    path = os.path.join(win, "done.json")
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError):
        return {}   # torn write: treat the window's ledger as empty


def append_done(win, name, record) -> None:
    """Atomic read-modify-replace so a kill between stages never corrupts
    the records of the stages that DID complete."""
    done = load_done(win)
    done[name] = record
    tmp = os.path.join(win, "done.json.tmp")
    with open(tmp, "w") as f:
        json.dump(done, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(win, "done.json"))


def completed_stages(root) -> dict:
    """stage name → its successful record, unioned over every window
    (later windows win).  Only exit==0 counts as done — a timeout or
    crash leaves the stage missing, so the next window re-fires it."""
    out = {}
    for win in window_dirs(root):
        for name, rec in load_done(win).items():
            if rec.get("exit") == 0:
                out[name] = {**rec, "window": os.path.basename(win)}
    return out


# --- running -----------------------------------------------------------


def _utcnow():
    return datetime.datetime.now(datetime.timezone.utc)


def new_window(root) -> str:
    base = os.path.join(root, "window_" +
                        _utcnow().strftime("%Y%m%dT%H%M%SZ"))
    win, i = base, 0
    while os.path.exists(win):        # same-second re-arm (tests)
        i += 1
        win = f"{base}_{i}"
    os.makedirs(win)
    return win


def run_stage(win, st, log) -> dict:
    argv = [a.replace("{win}", win) for a in st["argv"]]
    env = {**os.environ, **st["env"]}
    log(f"stage start: {st['name']} (budget {st['budget_s']}s): "
        f"{' '.join(argv)}")
    art_path = (os.path.join(win, st["artifact"]) if st["artifact"]
                else None)
    log_path = os.path.join(win, "battery.log")
    t0 = time.time()
    try:
        with open(log_path, "a") as lf:
            if art_path:
                with open(art_path, "w") as af:
                    r = subprocess.run(argv, stdout=af, stderr=lf,
                                       cwd=_REPO, env=env,
                                       timeout=st["budget_s"])
            else:
                r = subprocess.run(argv, stdout=lf, stderr=lf,
                                   cwd=_REPO, env=env,
                                   timeout=st["budget_s"])
        exit_code = r.returncode
    except subprocess.TimeoutExpired:
        exit_code = "timeout"
    except OSError as e:
        exit_code = f"oserror: {e}"
    rec = {"exit": exit_code, "duration_s": round(time.time() - t0, 1),
           "artifact": st["artifact"],
           "completed_at": _utcnow().strftime("%Y-%m-%dT%H:%M:%SZ")}
    # Side-artifact copies run even on failure/timeout: bench.py emits
    # .bench_phases.json INCREMENTALLY, and a timed-out window's partial
    # numbers must be preserved before the next window's re-fire
    # overwrites the repo-root file (the pre-ledger script copied
    # unconditionally too).
    for src, dst in st["copies"]:
        sp = os.path.join(_REPO, src)
        if os.path.exists(sp):
            shutil.copy(sp, os.path.join(win, dst))
    log(f"stage exit={exit_code}: {st['name']} "
        f"({rec['duration_s']}s)")
    return rec


def run_battery(root, stages=None, probe_argv=None, reprobe=True,
                log=None) -> dict:
    """Fire every stage not yet completed in ANY window into a fresh
    window dir.  Returns {window, ran, failed, remaining, complete,
    aborted}; ``complete`` means the whole battery is done across all
    windows (the caller's probe loop can stop)."""
    stages = default_stages() if stages is None else stages
    log = log or (lambda msg: print(f"[battery] {msg}", flush=True))
    os.makedirs(root, exist_ok=True)
    done = completed_stages(root)
    missing = [s for s in stages if s["name"] not in done]
    if not missing:
        return {"window": None, "ran": [], "failed": [], "remaining": [],
                "complete": True, "aborted": False}
    win = new_window(root)
    log(f"window {os.path.basename(win)}: {len(missing)} missing "
        f"stage(s): {[s['name'] for s in missing]}")
    marker = os.path.join(root, MARKER)
    with open(marker, "w") as f:
        f.write(os.path.basename(win) + "\n")
    ran, failed, aborted = [], [], False
    try:
        for i, st in enumerate(missing):
            rec = run_stage(win, st, log)
            append_done(win, st["name"], rec)
            (ran if rec["exit"] == 0 else failed).append(st["name"])
            if rec["exit"] != 0 and reprobe and i + 1 < len(missing):
                # Don't burn the remaining budgets against a dead
                # tunnel: cheap re-probe decides abort-vs-continue.
                if not probe_ok(probe_argv):
                    log("window dead (stage failed AND re-probe failed); "
                        "aborting — remaining stages re-fire next window")
                    aborted = True
                    break
    finally:
        try:
            os.remove(marker)
        except OSError:
            pass
    done = completed_stages(root)
    remaining = [s["name"] for s in stages if s["name"] not in done]
    result = {"window": win, "ran": ran, "failed": failed,
              "remaining": remaining, "complete": not remaining,
              "aborted": aborted}
    log(f"battery {'complete' if result['complete'] else 'partial'}: "
        f"ran={ran} failed={failed} remaining={remaining}")
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("cmd", nargs="?", default="run",
                   choices=("run", "status"))
    p.add_argument("--out", default=os.path.join(_REPO, ".probe"))
    p.add_argument("--no-reprobe", action="store_true",
                   help="don't probe the backend between failed stages")
    args = p.parse_args(argv)
    if args.cmd == "status":
        done = completed_stages(args.out)
        names = [s["name"] for s in default_stages()]
        out = {"completed": sorted(done),
               "remaining": [n for n in names if n not in done],
               "windows": [os.path.basename(w)
                           for w in window_dirs(args.out)]}
        print(json.dumps(out, indent=1))
        return 0 if not out["remaining"] else 3
    res = run_battery(args.out, reprobe=not args.no_reprobe)
    print(json.dumps({k: v for k, v in res.items() if k != "window"}
                     | {"window": os.path.basename(res["window"])
                        if res["window"] else None}))
    return 0 if res["complete"] else 3


if __name__ == "__main__":
    sys.exit(main())
