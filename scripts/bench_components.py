"""Component-level TPU micro-bench: the "poor man's profiler" for the tunnel.

``jax.profiler`` cannot run over the axon TPU tunnel (observed r4: the
tracer hangs AND a client killed mid-trace wedges the backend claim for
subsequent processes — see bench.py ``run_witness``), so per-op time
attribution comes from here instead: each major sub-program of the flagship
ffhq256-duplex step is compiled and timed as its own jitted program, with
XLA cost-analysis FLOPs and the chip's bf16 peak giving a per-component
MFU.  A component whose MFU sits far below the full-step average is the
optimization target; one far above average is already MXU-bound.

Prints one JSON line per component: {name, ms, gflops, mfu, shapes}.

  python scripts/bench_components.py [--iters 30] [--batch 8]

Caveats: isolated-program MFU is not additive to the step MFU (XLA fuses
across component boundaries inside the real step, and backward passes are
timed as grad-of-component here), but the RANKING of time sinks transfers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--preset", default="ffhq256-duplex")
    args = p.parse_args()

    import jax

    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache(_REPO)

    import jax.numpy as jnp
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.models.discriminator import Discriminator
    from gansformer_tpu.models.generator import Generator
    from gansformer_tpu.ops.modulated_conv import (
        _conv, conv2d, modulated_conv2d)
    from gansformer_tpu.ops.upfirdn2d import downsample_2d, upsample_2d
    from gansformer_tpu.utils.benchcheck import peak_tflops

    cfg = get_preset(args.preset).model
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak = peak_tflops(dev.device_kind) if on_tpu else None
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    b = args.batch
    rs = np.random.RandomState(0)
    key = jax.random.PRNGKey(0)

    print(json.dumps({"device_kind": dev.device_kind,
                      "platform": dev.platform, "batch": b,
                      "preset": args.preset,
                      "peak_bf16_tflops": peak}), flush=True)

    from gansformer_tpu.utils.benchcheck import flops_of

    def timed(name: str, fn, *xs, **extra_info):
        """Compile fn(*xs), time it, emit one JSON line."""
        t0 = time.time()
        compiled = jax.jit(fn).lower(*xs).compile()
        c_s = time.time() - t0
        fl = flops_of(compiled)
        out = compiled(*xs)
        jax.block_until_ready(out)          # warm-up
        t0 = time.time()
        for _ in range(args.iters):
            out = compiled(*xs)
        jax.block_until_ready(out)
        ms = (time.time() - t0) / args.iters * 1e3
        line = {"name": name, "ms": round(ms, 3), "compile_s": round(c_s, 1)}
        if fl:
            line["gflops"] = round(fl / 1e9, 2)
            if peak:
                line["mfu"] = round(fl / (ms * 1e-3) / (peak * 1e12), 4)
        line.update(extra_info)
        print(json.dumps(line), flush=True)
        return out

    # ---- leaf ops at each synthesis resolution ------------------------
    for res in [r for r in (32, 64, 128, 256) if r <= cfg.resolution]:
        c = cfg.nf(res)
        x = jnp.asarray(rs.randn(b, res, res, c), dtype)
        w3 = jnp.asarray(rs.randn(3, 3, c, c) * 0.05, dtype)
        styles = jnp.asarray(rs.randn(b, c), jnp.float32)
        timed(f"modconv3x3_{res}", lambda x, w, s: modulated_conv2d(x, w, s),
              x, w3, styles, res=res, cin=c, cout=c)
        timed(f"modconv3x3_up2_{res}",
              lambda x, w, s: modulated_conv2d(x, w, s, up=2),
              x, w3, styles, res=res, cin=c, cout=c)
        # The pre-polyphase dense-at-2H formulation, timed for the on-chip
        # before/after comparison (PERF.md §1b''').
        timed(f"upconv_dense_{res}",
              lambda x, w: _conv(upsample_2d(x, (1, 3, 3, 1)), w,
                                 stride=1, padding="SAME"),
              x, w3, res=res, cin=c, cout=c)
        timed(f"blur_up2_{res}", lambda x: upsample_2d(x, (1, 3, 3, 1)),
              x, res=res, chans=c)
        timed(f"blur_down2_{res}", lambda x: downsample_2d(x, (1, 3, 3, 1)),
              x, res=res, chans=c)
        # D-skip 1x1 down-conv: decimated blur (current, PERF.md §1b'''')
        # vs the dense formulation it replaced (blur every pixel, discard
        # 3 of 4 in the strided conv) — the on-chip before/after.
        c_out = cfg.nf(res // 2)
        w1 = jnp.asarray(rs.randn(1, 1, c, c_out) * 0.1, dtype)
        timed(f"skip_down_decimated_{res}",
              lambda x, w: conv2d(x, w, down=2),
              x, w1, res=res, cin=c, cout=c_out)

        def skip_dense(x, w):
            from gansformer_tpu.ops.upfirdn2d import setup_filter, upfirdn2d
            fk = setup_filter((1, 3, 3, 1))
            xb = upfirdn2d(x, fk, pad=((fk.shape[0] - 1) // 2,
                                       (fk.shape[0] - 2) // 2))
            return _conv(xb, w, stride=2, padding="VALID")

        timed(f"skip_down_dense_{res}", skip_dense,
              x, w1, res=res, cin=c, cout=c_out)

    # ---- model-level programs ----------------------------------------
    G, D = Generator(cfg), Discriminator(cfg)
    z = jnp.asarray(rs.randn(b, cfg.num_ws, cfg.latent_dim), jnp.float32)
    imgs = jnp.asarray(rs.randn(b, cfg.resolution, cfg.resolution, 3), dtype)
    noise = {"noise": jax.random.PRNGKey(1)}

    t0 = time.time()
    kg, kd = jax.random.split(key)
    g_vars = jax.jit(lambda k: G.init({"params": k, **noise}, z))(kg)
    d_vars = jax.jit(lambda k: D.init(k, imgs))(kd)
    jax.block_until_ready((g_vars, d_vars))
    print(json.dumps({"name": "init", "s": round(time.time() - t0, 1)}),
          flush=True)

    ws = timed("mapping", lambda v, z: G.apply(v, z, method=Generator.map),
               g_vars, z)
    timed("synthesis_fwd",
          lambda v, w: G.apply(v, w, rngs=noise, method=Generator.synthesize),
          g_vars, ws)
    timed("g_fwd", lambda v, z: G.apply(v, z, rngs=noise), g_vars, z)
    timed("d_fwd", lambda v, x: D.apply(v, x), d_vars, imgs)

    # backward passes (first-order only — the reg phases' second-order
    # structure is covered by bench.py's d_r1/g_pl phase numbers)
    def g_loss(v, z):
        return jnp.mean(G.apply(v, z, rngs=noise).astype(jnp.float32) ** 2)

    def d_loss(v, x):
        return jnp.mean(D.apply(v, x).astype(jnp.float32) ** 2)

    timed("g_fwd_bwd", lambda v, z: jax.grad(g_loss)(v, z), g_vars, z)
    timed("d_fwd_bwd", lambda v, x: jax.grad(d_loss)(v, x), d_vars, imgs)


if __name__ == "__main__":
    main()
