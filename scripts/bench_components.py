"""Component-level cost attribution: the "poor man's profiler" for the tunnel.

``jax.profiler`` cannot run over the axon TPU tunnel (observed r4: the
tracer hangs AND a client killed mid-trace wedges the backend claim for
subsequent processes — see bench.py ``run_witness``), so per-op time
attribution comes from here instead.  Each major sub-program of the
flagship ffhq256-duplex step is AOT-compiled as its own jitted program and
read through XLA ``cost_analysis()`` (FLOPs + bytes accessed); on a TPU it
is also self-timed, giving a per-component MFU.  A component whose MFU
sits far below the full-step average is the optimization target; one far
above average is already MXU-bound.

The component set covers the four phases' expected time sinks (ISSUE 5 /
PERF.md §1c top-3): G's modulated up-convs at the 128²/256² grids (forward
AND first-order backward), the PL double-backward through synthesis (the
largest phase's defining cost), D's fromRGB + first two residual blocks,
and the bipartite-attention einsums (block-level and raw).

Output: one JSON line per component on stdout (incremental — a dying
tunnel window still yields the lines that ran), plus ``--json-out`` with
the full artifact INCLUDING the ranked attribution table
``{component → GFLOPs → expected ms @ the assumed MFU → share of step}``.
On CPU the structure (FLOPs/bytes/shares/ranking) is exact and the
timings are meaningless; on TPU the measured ms replaces the projection.

  python scripts/bench_components.py [--iters 30] [--batch 8] \
      [--preset ffhq256-duplex] [--json-out artifact.json] [--skip-phases]

Caveats: isolated-program MFU is not additive to the step MFU (XLA fuses
across component boundaries inside the real step, and backward passes are
timed as grad-of-component here), so ``share_of_step`` values overlap and
do NOT sum to 1 — the RANKING of time sinks is what transfers.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from types import SimpleNamespace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Default "current MFU" for the expected-ms projection: the one
# physics-valid hardware datapoint (PERF.md §1c — d phase at 33% on the
# v5e).  Overridable; the artifact records what was used.
ASSUMED_MFU = 0.33
# Projection peak when not on a TPU (PERF.md §1b: the v5e target chip).
DEFAULT_PEAK_TFLOPS = 197.0
# v5e HBM bandwidth for the off-TPU roofline projection (public spec).
DEFAULT_HBM_GBPS = 819.0


def expected_ms(flops: float, peak_tflops: float, mfu: float) -> float:
    """Time a program of ``flops`` would take at ``mfu`` of ``peak``."""
    return flops / (mfu * peak_tflops * 1e12) * 1e3


def build_attribution(components, step_flops, peak_tflops, assumed_mfu,
                      on_tpu):
    """Ranked per-component attribution table (pure — unit-tested).

    ``components``: list of dicts with at least ``name`` and optionally
    ``gflops`` / ``gbytes`` / ``ms`` (measured).  Rank key: measured ms on
    TPU (the ground truth), cost-model FLOPs otherwise.  ``share_of_step``
    is component FLOPs over the cadence-weighted per-iteration step FLOPs
    (None when phases were skipped); shares OVERLAP (a backward component
    contains its forward) — they rank, they do not partition.
    """
    rows = []
    for c in components:
        fl = c.get("gflops")
        row = {"name": c["name"],
               "gflops": fl,
               "gbytes": c.get("gbytes"),
               "ms_measured": c.get("ms") if on_tpu else None,
               "mfu_measured": c.get("mfu") if on_tpu else None,
               "expected_ms": (
                   round(expected_ms(fl * 1e9, peak_tflops, assumed_mfu), 3)
                   if fl else None),
               "share_of_step": (
                   round(fl * 1e9 / step_flops, 4)
                   if fl and step_flops else None)}
        rl = c.get("roofline") or {}
        # The attributability fields (ISSUE 14 satellite): a kernel win
        # is only a win against the roof that binds the op.
        row["bound"] = rl.get("bound")
        row["pct_of_roof"] = rl.get("pct_of_roof")
        rows.append(row)
    def key(r):
        if on_tpu and r["ms_measured"] is not None:
            return r["ms_measured"]
        return r["expected_ms"] or 0.0
    rows.sort(key=key, reverse=True)
    for rank, r in enumerate(rows):
        r["rank"] = rank + 1
    return rows


def phase_flops(cfg, batch):
    """Per-phase cost-analysis FLOPs of the four REAL step programs +
    the cadence-weighted per-iteration total (PERF.md §1b methodology;
    unsharded lowering — cost analysis is per-device under SPMD anyway;
    conditional-label handling lives in the shared ``lower_phase``)."""
    from gansformer_tpu.utils.benchcheck import (
        cadence_weighted, flops_of, lower_phase)

    ph = {}
    for name in ("d", "g", "d_r1", "g_pl"):
        fl = flops_of(lower_phase(cfg, name, batch_size=batch))
        if fl:
            ph[name] = fl
    if not all(k in ph for k in ("d", "g", "d_r1", "g_pl")):
        return ph, None
    t = cfg.train
    return ph, cadence_weighted(ph, t.d_reg_interval, t.g_reg_interval)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--preset", default="ffhq256-duplex")
    p.add_argument("--json-out", default=None,
                   help="write the full artifact (components + ranked "
                        "attribution table) here")
    p.add_argument("--skip-phases", action="store_true",
                   help="skip lowering the four real step programs (the "
                        "share-of-step denominator) — faster, shares null")
    p.add_argument("--assumed-mfu", type=float, default=ASSUMED_MFU)
    p.add_argument("--peak-tflops", type=float, default=None,
                   help="projection peak off-TPU (default: v5e 197)")
    p.add_argument("--attention-backend", default="xla",
                   choices=("xla", "pallas"),
                   help="attention compute backend for the attn_block_*/"
                        "attn_einsums_* components (ISSUE 9): re-rank the "
                        "attribution table under the fused differentiable "
                        "kernels (off-TPU they run in interpret mode)")
    p.add_argument("--conv-backend", default="both",
                   choices=("xla", "pallas", "both"),
                   help="modulated-conv/upfirdn components (ISSUE 14): "
                        "'both' (default) times every pallas conv kernel "
                        "(fwd + vjp) beside its XLA counterpart as "
                        "*_pallas_* twins so kernel wins are directly "
                        "attributable in one artifact")
    args = p.parse_args(argv)

    import jax

    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache(_REPO)

    import jax.numpy as jnp
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.losses.gan import path_length_penalty
    from gansformer_tpu.models.attention import BipartiteAttention
    from gansformer_tpu.models.discriminator import Discriminator
    from gansformer_tpu.models.generator import Generator
    from gansformer_tpu.models.layers import EqualConv
    from gansformer_tpu.ops.attention import multihead_attention
    from gansformer_tpu.ops.modulated_conv import (
        _conv, conv2d, modulated_conv2d)
    from gansformer_tpu.ops.upfirdn2d import downsample_2d, upsample_2d
    from gansformer_tpu.utils.benchcheck import (bytes_accessed_of, flops_of,
                                                 peak_hbm_gbps, peak_tflops,
                                                 roofline)

    full_cfg = get_preset(args.preset)
    cfg = full_cfg.model
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak = peak_tflops(dev.device_kind) if on_tpu else None
    proj_peak = peak or args.peak_tflops or DEFAULT_PEAK_TFLOPS
    hbm = (peak_hbm_gbps(dev.device_kind) if on_tpu
           else None) or DEFAULT_HBM_GBPS
    # Which conv backends to emit components for; on TPU the pallas side
    # is gated by the conv-family native smoke check (skip-don't-crash,
    # same policy as resolve_conv_backend).
    conv_backends = (("xla", "pallas") if args.conv_backend == "both"
                     else (args.conv_backend,))
    if "pallas" in conv_backends and on_tpu:
        from gansformer_tpu.ops.pallas_modconv import tpu_smoke_check

        ok, detail = tpu_smoke_check()
        print(json.dumps({"name": "conv_tpu_smoke_check", "ok": ok,
                          "detail": detail}), flush=True)
        if not ok:
            conv_backends = tuple(b for b in conv_backends if b != "pallas")
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    b = args.batch
    rs = np.random.RandomState(0)
    key = jax.random.PRNGKey(0)
    components: list = []

    meta = {"device_kind": dev.device_kind, "platform": dev.platform,
            "batch": b, "preset": args.preset, "peak_bf16_tflops": peak,
            "projection_peak_tflops": proj_peak,
            "projection_hbm_gbps": hbm,
            "assumed_mfu": args.assumed_mfu,
            "attention_backend": args.attention_backend,
            "conv_backends": list(conv_backends)}
    print(json.dumps(meta), flush=True)

    def bytes_of(compiled):
        return bytes_accessed_of(compiled)

    def timed(name: str, fn, *xs, **extra_info):
        """Compile fn(*xs), time it (TPU only), emit one JSON line,
        record it.  Off-TPU the timing loop is skipped entirely — the
        artifact nulls CPU timings anyway, and executing e.g. the PL
        double-backward 30× on the host would waste minutes per
        component for numbers nobody reads."""
        t0 = time.time()
        compiled = jax.jit(fn).lower(*xs).compile()
        c_s = time.time() - t0
        fl = flops_of(compiled)
        by = bytes_of(compiled)
        out = compiled(*xs)        # one execution: some outputs chain on
        jax.block_until_ready(out)
        line = {"name": name, "compile_s": round(c_s, 1)}
        ms = None
        if on_tpu:
            t0 = time.time()
            for _ in range(args.iters):
                out = compiled(*xs)
            jax.block_until_ready(out)
            ms = (time.time() - t0) / args.iters * 1e3
            line["ms"] = round(ms, 3)
        if fl:
            line["gflops"] = round(fl / 1e9, 2)
            if peak and ms:
                line["mfu"] = round(fl / (ms * 1e-3) / (peak * 1e12), 4)
        if by:
            line["gbytes"] = round(by / 1e9, 3)
        # Roofline classification (ISSUE 14 satellite): memory- vs
        # compute-bound from cost-analysis bytes/FLOPs, achieved % of
        # the BINDING roof when a measured ms exists — the field that
        # makes a kernel win attributable rather than just faster.
        rl = roofline(fl, by, proj_peak, hbm, ms)
        if rl:
            line["roofline"] = rl
        line.update(extra_info)
        print(json.dumps(line), flush=True)
        components.append(line)
        return out

    # ---- leaf ops at each synthesis resolution ------------------------
    # The modulated-conv/upfirdn family is emitted once per conv backend
    # (ISSUE 14): the pallas kernels appear as *_pallas_* twins right
    # beside their XLA counterparts (fwd AND vjp), so a kernel win in
    # the artifact is attributable — same inputs, same cost model, only
    # the lowering differs.  Off-TPU the pallas twins run in interpret
    # mode (structure only, like every other CPU number here).
    def conv_fns(backend):
        if backend == "xla":
            return SimpleNamespace(
                modconv=lambda x, w, s, **kw: modulated_conv2d(x, w, s,
                                                               **kw),
                blur_up=lambda x: upsample_2d(x, (1, 3, 3, 1)),
                blur_down=lambda x: downsample_2d(x, (1, 3, 3, 1)),
                skip_down=lambda x, w: conv2d(x, w, down=2))
        from gansformer_tpu.ops.pallas_modconv import modulated_conv2d_pallas
        interp = not on_tpu
        return SimpleNamespace(
            modconv=lambda x, w, s, **kw: modulated_conv2d_pallas(
                x, w, s, interpret=interp, **kw),
            blur_up=lambda x: upsample_2d(x, (1, 3, 3, 1),
                                          backend="pallas"),
            blur_down=lambda x: downsample_2d(x, (1, 3, 3, 1),
                                              backend="pallas"),
            skip_down=lambda x, w: conv2d(x, w, down=2, backend="pallas"))

    # 512/1024 joined the sweep with ISSUE 17's row blocking — before
    # it these grids couldn't exist as pallas twins (the VMEM gate fell
    # back), so the ffhq1024 attribution table re-ranks under full
    # coverage.  Each conv component carries its launch-plan fields
    # (plan_mode/plan_rows from the SAME planner the dispatcher uses),
    # making a kernel win attributable to whole-image vs row-blocked
    # streaming rather than just "pallas".
    from gansformer_tpu.ops.pallas_modconv import modconv_plan
    from gansformer_tpu.ops.pallas_upfirdn import upfirdn_plan

    def plan_fields(plan):
        return {"plan_mode": plan.mode, "plan_rows": plan.rows}

    itemsize = jnp.dtype(dtype).itemsize
    for res in [r for r in (32, 64, 128, 256, 512, 1024)
                if r <= cfg.resolution]:
        c = cfg.nf(res)
        c_out = cfg.nf(res // 2)
        x = jnp.asarray(rs.randn(b, res, res, c), dtype)
        w3 = jnp.asarray(rs.randn(3, 3, c, c) * 0.05, dtype)
        # ONE skip-weight draw per resolution: the xla/pallas twins and
        # the decimated-vs-dense pair must all see the same weights for
        # the attributability claim to hold.
        w1 = jnp.asarray(rs.randn(1, 1, c, c_out) * 0.1, dtype)
        styles = jnp.asarray(rs.randn(b, c), jnp.float32)
        want_vjp = res * 2 in (cfg.resolution, cfg.resolution // 2)
        plan3 = modconv_plan(x.shape, w3.shape, up=1, itemsize=itemsize)
        plan_up = modconv_plan(x.shape, w3.shape, up=2, itemsize=itemsize)
        plan_bu = upfirdn_plan(x.shape, (4, 4), 2, 1, (2, 1, 2, 1))
        plan_bd = upfirdn_plan(x.shape, (4, 4), 1, 2, (1, 1, 1, 1))
        for backend in conv_backends:
            fns = conv_fns(backend)
            tag = "" if backend == "xla" else "pallas_"
            timed(f"modconv3x3_{tag}{res}",
                  lambda x, w, s: fns.modconv(x, w, s),
                  x, w3, styles, res=res, cin=c, cout=c,
                  conv_backend=backend, **plan_fields(plan3))
            timed(f"modconv3x3_up2_{tag}{res}",
                  lambda x, w, s: fns.modconv(x, w, s, up=2),
                  x, w3, styles, res=res, cin=c, cout=c,
                  conv_backend=backend, **plan_fields(plan_up))
            if want_vjp:
                # First-order backward of the up-conv feeding the
                # 128²/256² grids — the grad-path share of the G time
                # sink (ISSUE 5); for pallas this drives the hand-written
                # backward kernels (ISSUE 14's scoreboard pair).
                def upconv_loss(x, w, s):
                    y = fns.modconv(x, w, s, up=2)
                    return jnp.mean(jnp.square(y.astype(jnp.float32)))

                timed(f"modconv3x3_up2_vjp_{tag}{res}",
                      lambda x, w, s: jax.grad(
                          upconv_loss, argnums=(0, 1, 2))(x, w, s),
                      x, w3, styles, res=res, cin=c, cout=c,
                      conv_backend=backend, **plan_fields(plan_up))
            timed(f"blur_up2_{tag}{res}", fns.blur_up,
                  x, res=res, chans=c, conv_backend=backend,
                  **plan_fields(plan_bu))
            timed(f"blur_down2_{tag}{res}", fns.blur_down,
                  x, res=res, chans=c, conv_backend=backend,
                  **plan_fields(plan_bd))
            if want_vjp:
                def blur_loss(x):
                    y = fns.blur_up(x)
                    return jnp.mean(jnp.square(y.astype(jnp.float32)))

                timed(f"blur_up2_vjp_{tag}{res}",
                      lambda x: jax.grad(blur_loss)(x),
                      x, res=res, chans=c, conv_backend=backend,
                      **plan_fields(plan_bu))
            # D-skip 1x1 down-conv: decimated blur (PERF.md §1b'''').
            timed(f"skip_down_decimated_{tag}{res}", fns.skip_down,
                  x, w1, res=res, cin=c, cout=c_out, conv_backend=backend,
                  **plan_fields(plan_bd))
        # The pre-polyphase dense-at-2H formulation, timed for the on-chip
        # before/after comparison (PERF.md §1b''') — xla-only study.
        timed(f"upconv_dense_{res}",
              lambda x, w: _conv(upsample_2d(x, (1, 3, 3, 1)), w,
                                 stride=1, padding="SAME"),
              x, w3, res=res, cin=c, cout=c)

        def skip_dense(x, w):
            from gansformer_tpu.ops.upfirdn2d import setup_filter, upfirdn2d
            fk = setup_filter((1, 3, 3, 1))
            xb = upfirdn2d(x, fk, pad=((fk.shape[0] - 1) // 2,
                                       (fk.shape[0] - 2) // 2))
            return _conv(xb, w, stride=2, padding="VALID")

        timed(f"skip_down_dense_{res}", skip_dense,
              x, w1, res=res, cin=c, cout=c_out)

    # ---- attention: block-level + raw einsums -------------------------
    # The largest attention grid is where the O(n·k) einsums earn their
    # keep (n = 16384 at attn_max_res 128); fp32 by design (PERF §1b'').
    attn_resolutions = cfg.attn_resolutions()
    for res in [r for r in attn_resolutions
                if r >= (max(attn_resolutions) // 2 if attn_resolutions
                         else 0)]:
        nf = cfg.nf(res)
        xg = jnp.asarray(rs.randn(b, res, res, nf), dtype)
        yl = jnp.asarray(rs.randn(b, cfg.components, cfg.w_dim), dtype)
        attn = BipartiteAttention(
            grid_dim=nf, latent_dim=cfg.w_dim, num_heads=cfg.num_heads,
            duplex=(cfg.attention == "duplex"), integration=cfg.integration,
            kmeans_iters=cfg.kmeans_iters, pos_encoding=cfg.pos_encoding,
            fused_kv=cfg.attn_fused_kv, backend=args.attention_backend,
            dtype=dtype)
        av = jax.jit(attn.init)(jax.random.fold_in(key, res), xg, yl)
        timed(f"attn_block_{res}",
              lambda v, x, y: attn.apply(v, x, y)[0], av, xg, yl,
              res=res, n=res * res, k=cfg.components,
              attention_backend=args.attention_backend)
        q = jnp.asarray(rs.randn(b, res * res, nf), jnp.float32)
        kv_len = cfg.components + (1 if cfg.use_global else 0)
        kk = jnp.asarray(rs.randn(b, kv_len, nf), jnp.float32)
        vv = jnp.asarray(rs.randn(b, kv_len, nf), jnp.float32)
        if args.attention_backend == "pallas":
            from gansformer_tpu.ops.pallas_attention import (
                multihead_attention_pallas)
            einsums = lambda q, k, v: multihead_attention_pallas(
                q, k, v, cfg.num_heads, interpret=not on_tpu)
        else:
            einsums = lambda q, k, v: multihead_attention(
                q, k, v, cfg.num_heads)[0]
        timed(f"attn_einsums_{res}", einsums,
              q, kk, vv, res=res, n=res * res, k=kv_len,
              attention_backend=args.attention_backend)

    # ---- model-level programs ----------------------------------------
    G, D = Generator(cfg), Discriminator(cfg)
    z = jnp.asarray(rs.randn(b, cfg.num_ws, cfg.latent_dim), jnp.float32)
    imgs = jnp.asarray(rs.randn(b, cfg.resolution, cfg.resolution, 3), dtype)
    noise = {"noise": jax.random.PRNGKey(1)}

    t0 = time.time()
    kg, kd = jax.random.split(key)
    g_vars = jax.jit(lambda k: G.init({"params": k, **noise}, z))(kg)
    d_vars = jax.jit(lambda k: D.init(k, imgs))(kd)
    jax.block_until_ready((g_vars, d_vars))
    print(json.dumps({"name": "init", "s": round(time.time() - t0, 1)}),
          flush=True)

    ws = timed("mapping", lambda v, z: G.apply(v, z, method=Generator.map),
               g_vars, z)
    timed("synthesis_fwd",
          lambda v, w: G.apply(v, w, rngs=noise, method=Generator.synthesize),
          g_vars, ws)
    timed("g_fwd", lambda v, z: G.apply(v, z, rngs=noise), g_vars, z)
    timed("d_fwd", lambda v, x: D.apply(v, x), d_vars, imgs)

    # ---- D front: fromRGB + first two residual blocks -----------------
    # PERF §1c sink #3 as its own program, applied with D's real param
    # subtrees (mirrors models/discriminator.py's block structure).
    R = cfg.resolution
    fblur = cfg.blur_filter

    def d_front(p, img):
        x = img.astype(dtype)
        x = EqualConv(cfg.nf(R), kernel=1, act="lrelu",
                      dtype=dtype).apply({"params": p["from_rgb"]}, x)
        for res in (R, R // 2):
            nf_out = cfg.nf(res // 2)
            t = EqualConv(x.shape[-1], act="lrelu", resample_filter=fblur,
                          dtype=dtype).apply(
                              {"params": p[f"b{res}_conv0"]}, x)
            t = EqualConv(nf_out, down=2, act="lrelu",
                          resample_filter=fblur, dtype=dtype).apply(
                              {"params": p[f"b{res}_conv1"]}, t)
            skip = EqualConv(nf_out, kernel=1, down=2, use_bias=False,
                             resample_filter=fblur, dtype=dtype).apply(
                                 {"params": p[f"b{res}_skip"]}, x)
            x = (t + skip) * (1.0 / math.sqrt(2.0))
        return x

    d_params = d_vars["params"]
    timed(f"d_front_{R}", d_front, d_params, imgs, res=R)

    def d_front_loss(p, img):
        return jnp.mean(jnp.square(d_front(p, img).astype(jnp.float32)))

    timed(f"d_front_fwd_bwd_{R}",
          lambda p, x: jax.grad(d_front_loss)(p, x), d_params, imgs, res=R)

    # ---- PL double-backward through synthesis -------------------------
    # The defining cost of the largest phase (g_pl, PERF §1c sink #2):
    # grad w.r.t. G's params of the path-length penalty, which itself
    # contains a grad-through-synthesis — a real second-order program at
    # the PL probe batch (batch // pl_batch_shrink, the armed lever value).
    t_cfg = full_cfg.train
    pl_b = max(1, b // max(1, t_cfg.pl_batch_shrink))
    # z_pl comes from the same numpy stream as every other bench input;
    # the jax keys only drive the probe noise and the synthesis rng.
    k_plnoise, k_plsynth = jax.random.split(jax.random.fold_in(key, 3))
    z_pl = jnp.asarray(
        rs.randn(pl_b, cfg.num_ws, cfg.latent_dim), jnp.float32)
    ws_pl = jax.jit(lambda v, z: G.apply(v, z, method=Generator.map))(
        g_vars, z_pl)

    def pl_loss(v, w, k):
        def synth(w_):
            return G.apply(v, w_, rngs={"noise": k_plsynth},
                           method=Generator.synthesize)

        pl, _ = path_length_penalty(synth, w, jnp.zeros(()), k)
        return pl

    timed("pl_double_backward",
          lambda v, w, k: jax.grad(pl_loss)(v, w, k),
          g_vars, ws_pl, k_plnoise, pl_batch=pl_b)

    # backward passes (first-order only — the reg phases' second-order
    # structure is covered by pl_double_backward above and bench.py's
    # d_r1/g_pl phase numbers)
    def g_loss(v, z):
        return jnp.mean(G.apply(v, z, rngs=noise).astype(jnp.float32) ** 2)

    def d_loss(v, x):
        return jnp.mean(D.apply(v, x).astype(jnp.float32) ** 2)

    timed("g_fwd_bwd", lambda v, z: jax.grad(g_loss)(v, z), g_vars, z)
    timed("d_fwd_bwd", lambda v, x: jax.grad(d_loss)(v, x), d_vars, imgs)

    # ---- step-share denominator + ranked attribution ------------------
    phases, step_fl = (({}, None) if args.skip_phases
                       else phase_flops(full_cfg, b))
    if phases:
        print(json.dumps({"name": "phase_flops",
                          **{k: round(v / 1e9, 2) for k, v in
                             phases.items()},
                          "step_gflops_per_it": (
                              round(step_fl / 1e9, 2) if step_fl
                              else None)}), flush=True)
    attribution = build_attribution(components, step_fl, proj_peak,
                                    args.assumed_mfu, on_tpu)
    artifact = {
        "meta": meta,
        "components": components,
        "phase_gflops": {k: round(v / 1e9, 2) for k, v in phases.items()},
        "step_gflops_per_iteration": (round(step_fl / 1e9, 2)
                                      if step_fl else None),
        "attribution": attribution,
        "note": ("shares overlap (backward components contain their "
                 "forward; phases fuse across component boundaries) — "
                 "the table ranks time sinks, it does not partition the "
                 "step" + ("" if on_tpu else
                           "; CPU run: structure only, ms not meaningful")),
    }
    if args.json_out:
        tmp = args.json_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=1)
        os.replace(tmp, args.json_out)
    print(json.dumps({"name": "attribution_top5",
                      "top": [{k: r[k] for k in
                               ("rank", "name", "gflops", "expected_ms",
                                "share_of_step")}
                              for r in attribution[:5]]}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
