"""Micro-bench: Pallas fused attention kernels vs the jnp/XLA composite —
forward, first-order grad, and an R1/PL-shaped grad-of-grad per direction
(ISSUE 9: the kernels are differentiable and wired into the training
path, so the A/B must price what training actually dispatches).

Shapes are the flagship ffhq256-duplex attention workload (PERF.md §1):
grid side n = H·W at the attended resolutions, k = 16 latents,
C = nf(res).  Run on the TPU chip (ambient backend); prints one JSON line
per (resolution, direction) with timings, cost-analysis FLOPs/bytes for
the forward and grad programs of BOTH backends, and the byte deltas —
the compiled-program evidence that the kernels remove the
probability-map round-trip.  Off-TPU the pallas path runs in interpret
mode: parity (max_abs_diff) and the xla-side cost analysis are still
real, timings are skipped (bench_components.py discipline) and the
pallas-side byte figures are labeled interpret-mode (the interpreter's
emulation loop inflates them; only native Mosaic numbers count as
traffic evidence).

Timing rides ``bench.steady_state_time`` — the SAME validated loop as
the phase bench — plus a 2× linearity re-time, so these numbers inherit
the r3-retraction early-ack defenses (``benchcheck.single_timer_
suspects``; a failed check lands in the line's ``suspect`` field instead
of being presented clean).

  python scripts/bench_pallas_attention.py [--iters 50] [--res 32 64 128]
  python scripts/bench_pallas_attention.py --train-ab [--preset ...]

``--train-ab`` is the training-path A/B (battery stage
``pallas_train_ab``): the four REAL step programs (d, g, d_r1, g_pl) are
AOT-compiled per backend via ``benchcheck.lower_phase`` and their
cost-analysis FLOPs / bytes / temp workspace recorded side by side (on
TPU also steady-state timed), one JSON line per phase.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timed(fn, args, iters, name, on_tpu):
    """(ms, suspects) via the shared validated steady-state loop + the 2×
    linearity re-time; None off-TPU (timings there are meaningless)."""
    if not on_tpu:
        return None, []
    from bench import steady_state_time
    from gansformer_tpu.utils.benchcheck import single_timer_suspects

    step = lambda carry: (carry, fn(*args))
    _, per_it, tail = steady_state_time(step, None, iters)
    _, per_it_2n, _ = steady_state_time(step, None, 2 * iters)
    sus = single_timer_suspects(name, per_it, tail, iters, per_it_2n)
    return round(per_it * 1e3, 3), sus


def bench_one(res: int, k: int, batch: int, heads: int, iters: int,
              direction: str, pallas_ok: bool = True) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.ops.attention import multihead_attention
    from gansformer_tpu.ops.pallas_attention import multihead_attention_pallas
    from gansformer_tpu.utils.benchcheck import cost_summary

    cfg = get_preset("ffhq256-duplex").model
    c = cfg.nf(res)
    n = res * res
    dtype = jnp.bfloat16
    rs = np.random.RandomState(0)
    if direction == "grid_to_latent":
        # the main simplex/duplex phase: q from the grid, k/v from latents
        q = jnp.asarray(rs.randn(batch, n, c), dtype)
        kk = jnp.asarray(rs.randn(batch, k, c), dtype)
        v = jnp.asarray(rs.randn(batch, k, c), dtype)
    else:
        # duplex back-direction: q from latents, softmax over the n-grid —
        # the blockwise flash kernel (online softmax; the 1024² VMEM case)
        q = jnp.asarray(rs.randn(batch, k, c), dtype)
        kk = jnp.asarray(rs.randn(batch, n, c), dtype)
        v = jnp.asarray(rs.randn(batch, n, c), dtype)
    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu

    fwd = {"xla": lambda q, kk, v: multihead_attention(q, kk, v, heads)[0]}
    if pallas_ok:
        fwd["pallas"] = lambda q, kk, v: multihead_attention_pallas(
            q, kk, v, heads, interpret=interpret)

    def grad_fn(f):
        # first-order training shape: dq/dk/dv of a scalar loss
        return jax.grad(
            lambda q, kk, v: jnp.sum(f(q, kk, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))

    def gg_fn(f):
        # R1/PL-shaped grad-of-grad: outer grad w.r.t. the k/v side
        # (the params side in the real programs) of the squared norm of
        # the inner input-grad — the transform g_step_pl/d_step_r1 run.
        def inner_sq(q, kk, v):
            gq = jax.grad(lambda q: jnp.sum(f(q, kk, v)))(q)
            return jnp.sum(gq.astype(jnp.float32) ** 2)

        return jax.grad(inner_sq, argnums=(1, 2))

    out = {"direction": direction, "res": res, "n": n, "c": c, "k": k,
           "batch": batch, "backend": jax.default_backend(),
           "interpret_mode": interpret}
    suspects: list = []
    ref = {}
    for name, f in fwd.items():
        jf = jax.jit(f)
        jg = jax.jit(grad_fn(f))
        jgg = jax.jit(gg_fn(f))
        for tag, jitted, args in (("", jf, (q, kk, v)),
                                  ("grad_", jg, (q, kk, v)),
                                  ("gg_", jgg, (q, kk, v))):
            compiled = jitted.lower(*args).compile()
            cost = cost_summary(compiled)
            out[f"{name}_{tag}gflops"] = cost["gflops"]
            out[f"{name}_{tag}gbytes"] = cost["gbytes"]
            r = compiled(*args)
            jax.block_until_ready(r)
            if name == "xla":
                ref[tag] = r
            elif tag in ref:
                flat = jax.tree_util.tree_leaves((ref[tag], r))
                half = len(flat) // 2
                err = max(float(jnp.max(jnp.abs(
                    a.astype(jnp.float32) - b.astype(jnp.float32))))
                    for a, b in zip(flat[:half], flat[half:]))
                out[f"{tag}max_abs_diff"] = round(err, 6)
            ms, sus = _timed(compiled, args, iters,
                             f"{direction}/{name}_{tag or 'fwd'}", on_tpu)
            if ms is not None:
                out[f"{name}_{tag}ms"] = ms
            suspects += sus
    if pallas_ok:
        for tag in ("", "grad_", "gg_"):
            a, b = out.get(f"xla_{tag}ms"), out.get(f"pallas_{tag}ms")
            if a and b:
                out[f"{tag}speedup"] = round(a / b, 3)
            xb, pb = out.get(f"xla_{tag}gbytes"), out.get(f"pallas_{tag}gbytes")
            if xb and pb:
                # The probability-map round-trip evidence (ISSUE 9
                # acceptance): meaningful under native Mosaic lowering
                # only — the interpreter's emulation loop inflates the
                # pallas side, so off-TPU this delta is labeled, not
                # claimed.
                out[f"{tag}gbytes_delta_vs_xla"] = round(pb - xb, 4)
        if interpret:
            out["bytes_note"] = ("interpret mode: pallas gbytes measure "
                                 "the emulation loop, not HBM traffic — "
                                 "native evidence comes from a TPU window")
    else:
        out["pallas_skipped"] = "native smoke check failed (see head line)"
    if suspects:
        out["suspect"] = suspects
    return out


def train_ab(preset: str, batch: int, iters: int,
             pallas_ok: bool = True,
             field: str = "attention_backend") -> None:
    """The training-path A/B: cost-analysis (and, on TPU, steady-state
    time) of the four REAL step programs per backend of ``field`` —
    ``attention_backend`` (ISSUE 9, battery stage ``pallas_train_ab``)
    or ``conv_backend`` (ISSUE 14, battery stage ``modconv_train_ab``):
    one JSON line per phase.

    Capture beats verdict: one line is FLUSHED per phase as soon as its
    backends are measured, a failed smoke check skips the pallas side
    (``pallas_skipped``, xla rows still land), and an unexpected
    pallas-side compile/run failure is recorded as ``pallas_error`` on
    the line instead of crashing the battery stage with the xla minutes
    already spent."""
    import dataclasses

    import jax
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.utils.benchcheck import (
        cost_summary, lower_phase, temp_workspace_gbytes)

    on_tpu = jax.default_backend() == "tpu"
    base = get_preset(preset)
    backends = ("xla", "pallas") if pallas_ok else ("xla",)

    def measure(backend, phase):
        cfg = dataclasses.replace(base, model=dataclasses.replace(
            base.model, **{field: backend}))
        compiled = lower_phase(cfg, phase, batch_size=batch)
        rec = {**cost_summary(compiled),
               "temp_gbytes": temp_workspace_gbytes(compiled)}
        if on_tpu:
            from bench import steady_state_time
            from gansformer_tpu.train.state import create_train_state
            from gansformer_tpu.utils.benchcheck import \
                single_timer_suspects

            state = jax.jit(lambda k: create_train_state(cfg, k))(
                jax.random.PRNGKey(0))
            imgs = jax.device_put(np.random.RandomState(0).randint(
                0, 255, (batch, cfg.model.resolution,
                         cfg.model.resolution, 3), dtype=np.uint8))
            rng = jax.random.PRNGKey(1)
            extra = ((imgs, rng, None) if phase.startswith("d")
                     else (rng, None))
            state, _ = compiled(state, *extra)   # warm-up + donation
            state, per_it, tail = steady_state_time(
                lambda carry: compiled(carry, *extra), state, iters)
            # 2× linearity re-time — the same early-ack defense pair as
            # bench_one's _timed, so the docstring's "all numbers inherit
            # the r3-retraction discipline" holds for the A/B rows too.
            state, per_it_2n, _ = steady_state_time(
                lambda carry: compiled(carry, *extra), state, 2 * iters)
            rec["ms"] = round(per_it * 1e3, 3)
            sus = single_timer_suspects(
                f"{backend}/{phase}", per_it, tail, iters, per_it_2n)
            if sus:
                rec["suspect"] = sus
        return rec

    for phase in ("d", "g", "d_r1", "g_pl"):
        line = {"name": f"train_ab_{phase}", "preset": preset,
                "field": field,
                "batch": batch, "platform": jax.default_backend()}
        for backend in backends:
            try:
                rec = measure(backend, phase)
            except Exception as e:   # Mosaic failures surface as many types
                if backend == "xla":
                    raise        # the baseline failing is a real stage error
                line["pallas_error"] = (
                    f"{type(e).__name__}: {e}"[:400])
                continue
            for key, val in rec.items():
                line[f"{backend}_{key}"] = val
        if not pallas_ok:
            line["pallas_skipped"] = "native smoke check failed (see head line)"
        xb, pb = line.get("xla_gbytes"), line.get("pallas_gbytes")
        if xb and pb:
            line["gbytes_delta_vs_xla"] = round(pb - xb, 4)
        if not on_tpu:
            line["bytes_note"] = ("interpret mode inflates the pallas "
                                  "side; native deltas come from a TPU "
                                  "window")
        print(json.dumps(line), flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--res", type=int, nargs="+", default=[32, 64, 128])
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--k", type=int, default=16)
    p.add_argument("--heads", type=int, default=1)
    p.add_argument("--train-ab", action="store_true",
                   help="A/B the four REAL step programs (xla vs pallas "
                        "backend): cost-analysis bytes/FLOPs/temp "
                        "workspace, plus steady-state ms on TPU")
    p.add_argument("--ab-backend", default="attention",
                   choices=("attention", "conv"),
                   help="which backend field --train-ab flips: the "
                        "bipartite-attention kernels (ISSUE 9) or the "
                        "modulated-conv/upfirdn kernel family (ISSUE 14)")
    p.add_argument("--preset", default="ffhq256-duplex")
    args = p.parse_args()

    import jax

    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache()

    # First line: the native-Mosaic reality record (VERDICT r4 item 4).
    # On a TPU this compiles the kernels natively at the gate's shapes —
    # now INCLUDING the backward kernels (the training path, ISSUE 9) —
    # and reports max_abs_diff vs the jnp oracle: the recorded artifact
    # the runtime ``resolve_backend`` gate otherwise produces transiently.
    dev = jax.devices()[0]
    head = {"device_kind": dev.device_kind, "platform": dev.platform}
    pallas_ok = True
    if dev.platform == "tpu":
        # The gate matching the family under test: the conv A/B must not
        # be skipped because an unrelated attention kernel regressed
        # (and vice versa).
        if args.train_ab and args.ab_backend == "conv":
            from gansformer_tpu.ops.pallas_modconv import tpu_smoke_check
        else:
            from gansformer_tpu.ops.pallas_attention import tpu_smoke_check

        ok, detail = tpu_smoke_check()
        head["tpu_smoke_check"] = {"ok": ok, "detail": detail,
                                   "family": (args.ab_backend
                                              if args.train_ab
                                              else "attention")}
        # A failed native compile must not abort the sweep: the xla
        # timings (and the failure record above) are still the artifact —
        # the same skip-don't-crash policy as ops resolve_backend.
        pallas_ok = ok
    else:
        head["note"] = ("non-TPU backend: pallas runs in interpret mode; "
                        "parity + xla cost analysis only — no native "
                        "Mosaic evidence from this run")
    print(json.dumps(head), flush=True)

    if args.train_ab:
        train_ab(args.preset, args.batch, min(args.iters, 10),
                 pallas_ok=pallas_ok,
                 field=f"{args.ab_backend}_backend")
        return

    for res in args.res:
        for direction in ("grid_to_latent", "latent_to_grid"):
            print(json.dumps(bench_one(res, args.k, args.batch, args.heads,
                                       args.iters, direction, pallas_ok)),
                  flush=True)


if __name__ == "__main__":
    main()
