"""Micro-bench: Pallas fused attention kernels vs the jnp/XLA composite.

Shapes are the flagship ffhq256-duplex attention workload (PERF.md §1):
grid side n = H·W at the attended resolutions, k = 16 latents, C = nf(res).
Run on the TPU chip (ambient backend); prints one JSON line per shape with
both timings so PERF.md §1c can cite measured numbers.

  python scripts/bench_pallas_attention.py [--iters 50] [--res 32 64 128]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_one(res: int, k: int, batch: int, heads: int, iters: int,
              direction: str, pallas_ok: bool = True) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.ops.attention import multihead_attention
    from gansformer_tpu.ops.pallas_attention import multihead_attention_pallas

    cfg = get_preset("ffhq256-duplex").model
    c = cfg.nf(res)
    n = res * res
    dtype = jnp.bfloat16
    rs = np.random.RandomState(0)
    if direction == "grid_to_latent":
        # the main simplex/duplex phase: q from the grid, k/v from latents
        q = jnp.asarray(rs.randn(batch, n, c), dtype)
        kk = jnp.asarray(rs.randn(batch, k, c), dtype)
        v = jnp.asarray(rs.randn(batch, k, c), dtype)
    else:
        # duplex back-direction: q from latents, softmax over the n-grid —
        # the blockwise flash kernel (online softmax; the 1024² VMEM case)
        q = jnp.asarray(rs.randn(batch, k, c), dtype)
        kk = jnp.asarray(rs.randn(batch, n, c), dtype)
        v = jnp.asarray(rs.randn(batch, n, c), dtype)
    interpret = jax.default_backend() != "tpu"

    fns = {
        "xla": jax.jit(lambda q, kk, v: multihead_attention(q, kk, v, heads)[0]),
    }
    if pallas_ok:
        fns["pallas"] = jax.jit(lambda q, kk, v: multihead_attention_pallas(
            q, kk, v, heads, interpret=interpret))
    out = {"direction": direction, "res": res, "n": n, "c": c, "k": k,
           "batch": batch, "backend": jax.default_backend()}
    ref = None
    for name, fn in fns.items():
        r = fn(q, kk, v)
        jax.block_until_ready(r)
        if ref is None:
            ref = r
        else:
            err = float(jnp.max(jnp.abs(ref.astype(jnp.float32)
                                        - r.astype(jnp.float32))))
            out["max_abs_diff"] = err
        t0 = time.time()
        for _ in range(iters):
            r = fn(q, kk, v)
        jax.block_until_ready(r)
        out[f"{name}_ms"] = round((time.time() - t0) / iters * 1e3, 3)
    if pallas_ok:
        out["speedup"] = round(out["xla_ms"] / out["pallas_ms"], 3)
    else:
        out["pallas_skipped"] = "native smoke check failed (see head line)"
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--res", type=int, nargs="+", default=[32, 64, 128])
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--k", type=int, default=16)
    p.add_argument("--heads", type=int, default=1)
    args = p.parse_args()

    import jax

    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache()

    # First line: the native-Mosaic reality record (VERDICT r4 item 4).
    # On a TPU this compiles BOTH kernels natively at the gate's shapes and
    # reports max_abs_diff vs the jnp oracle — the recorded artifact the
    # runtime ``resolve_backend`` gate otherwise only produces transiently.
    dev = jax.devices()[0]
    head = {"device_kind": dev.device_kind, "platform": dev.platform}
    pallas_ok = True
    if dev.platform == "tpu":
        from gansformer_tpu.ops.pallas_attention import tpu_smoke_check

        ok, detail = tpu_smoke_check()
        head["tpu_smoke_check"] = {"ok": ok, "detail": detail}
        # A failed native compile must not abort the sweep: the xla
        # timings (and the failure record above) are still the artifact —
        # the same skip-don't-crash policy as ops resolve_backend.
        pallas_ok = ok
    else:
        head["note"] = ("non-TPU backend: pallas runs in interpret mode; "
                        "no native Mosaic evidence from this run")
    print(json.dumps(head), flush=True)

    for res in args.res:
        for direction in ("grid_to_latent", "latent_to_grid"):
            print(json.dumps(bench_one(res, args.k, args.batch, args.heads,
                                       args.iters, direction, pallas_ok)),
                  flush=True)


if __name__ == "__main__":
    main()
