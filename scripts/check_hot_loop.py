"""Static lint: no host syncs inside the train loop's per-iteration body.

The throughput discipline (PERF.md §1b, ISSUE 2) allows exactly ONE host
sync in the hot loop: the tick-boundary fetch inside the
``with span("tick_fetch")`` block.  Everything else must be dispatch-only
— a stray ``jax.block_until_ready`` / ``jax.device_get`` anywhere else in
the iteration body reintroduces a serial host stall per iteration, the
exact regression the device-prefetch / async-writeback layer exists to
prevent.  (``copy_to_host_async`` is non-blocking and therefore allowed.)

Mechanically: parse ``gansformer_tpu/train/loop.py``, find the ``while``
loop inside ``_train`` (the per-iteration body), and flag any call whose
name is ``block_until_ready`` or ``device_get`` that is not lexically
inside a ``with span("tick_fetch")`` block.  Function *definitions*
nested in ``_train`` but outside the while body (``snapshot_images`` —
the sync fallback path) are exempt by construction.

Prints one JSON line ``{ok, checked, violations}``; exit 0 iff ok.
Invoked from the test suite (tests/test_device_prefetch.py) like
``check_telemetry.py``, so a hot-loop sync regression fails tier-1.

  python scripts/check_hot_loop.py [path/to/loop.py]
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import List, Optional

BANNED = {"block_until_ready", "device_get"}
SANCTIONED_SPAN = "tick_fetch"

_DEFAULT_TARGET = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "gansformer_tpu", "train", "loop.py")


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_sanctioned_with(node: ast.With) -> bool:
    """``with span("tick_fetch")`` (possibly among other items)."""
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Call) and _call_name(e) == "span" and \
                e.args and isinstance(e.args[0], ast.Constant) and \
                e.args[0].value == SANCTIONED_SPAN:
            return True
    return False


def _find_hot_loops(tree: ast.AST) -> List[ast.While]:
    """Every ``while`` statement inside a function named ``_train``."""
    loops: List[ast.While] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_train":
            for sub in ast.walk(node):
                if isinstance(sub, ast.While):
                    loops.append(sub)
    return loops


def _scan(node: ast.AST, sanctioned: bool, violations: List[dict]) -> None:
    """Recursive walk tracking whether we are under a sanctioned with."""
    for child in ast.iter_child_nodes(node):
        child_ok = sanctioned
        if isinstance(child, ast.With) and _is_sanctioned_with(child):
            child_ok = True
        if isinstance(child, ast.Call):
            name = _call_name(child)
            if name in BANNED and not sanctioned:
                violations.append({
                    "line": child.lineno,
                    "call": name,
                })
        _scan(child, child_ok, violations)


def check_source(src: str) -> dict:
    """{ok, checked, violations} for one loop.py-shaped source string."""
    tree = ast.parse(src)
    loops = _find_hot_loops(tree)
    violations: List[dict] = []
    for loop in loops:
        # scanning the While node covers its condition AND its body (a
        # device_get in the while test would sync every iteration too)
        _scan(loop, False, violations)
    return {"ok": not violations,
            "checked": len(loops),
            "violations": violations}


def check_file(path: str) -> dict:
    with open(path) as f:
        out = check_source(f.read())
    out["path"] = path
    if out["checked"] == 0:
        out["ok"] = False
        out["violations"] = [
            {"line": 0, "call": f"no while loop found inside _train in "
                                f"{path} — lint target moved?"}]
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", nargs="?", default=_DEFAULT_TARGET)
    args = p.parse_args(argv)
    result = check_file(args.path)
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
