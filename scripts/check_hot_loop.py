"""Static lint: no host syncs inside the train loop's per-iteration body.

SHIM — the checker now lives in the graftlint framework as the
``hot-loop-sync`` rule (``gansformer_tpu/analysis/rules/hot_loop.py``,
ISSUE 3); this script keeps the original entry point and module API
(``check_source`` / ``check_file`` / ``_DEFAULT_TARGET``, result shape
``{ok, checked, violations}``) so existing invocations and the verify
recipe keep working:

  python scripts/check_hot_loop.py [path/to/loop.py]

Prefer ``gansformer-lint --select hot-loop-sync gansformer_tpu`` for new
wiring; see docs/static-analysis.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:          # direct `python scripts/…` invocation
    sys.path.insert(0, _ROOT)

from gansformer_tpu.analysis.rules.hot_loop import (  # noqa: E402,F401
    BANNED,
    SANCTIONED_SPAN,
    _DEFAULT_TARGET,
    check_file,
    check_source,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", nargs="?", default=_DEFAULT_TARGET)
    args = p.parse_args(argv)
    result = check_file(args.path)
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
