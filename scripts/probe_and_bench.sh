#!/usr/bin/env bash
# Probe-and-bench loop for the axon TPU tunnel (PERF.md §1c).
#
# The tunnel serves minutes-long windows separated by hours of outage
# (measured r4: ~25 min in ~20 h, window arriving EARLY in the session),
# so a session must start this loop at minute 0 or risk losing the round's
# only measurement window to setup latency:
#
#     nohup scripts/probe_and_bench.sh >/dev/null 2>&1 &
#
# Behavior: probe the backend every PROBE_INTERVAL (default 420 s) with a
# 120 s-timeout child (the axon claim loop can hang forever — the timeout
# IS the probe's failure detector).  On the first successful probe, fire
# the full measurement battery in priority order (most important first, so
# a window that closes mid-battery still yields the top artifacts), then
# exit 0 so the launching session is notified and can commit the artifacts.
#
# Battery order (VERDICT r4 item 1):
#   1. bench.py           — 4 phases + fused cycle + batch sweep, self-
#                           validating (MFU / linearity / sync-tail checks)
#   2. bench_pallas_attention.py — native Mosaic compile + parity record
#   3. bench_components.py       — per-op MFU attribution (profiler
#                                  substitute; the tracer wedges the tunnel)
#   4. 2-tick cli.train run      — real loop on the chip, stats.jsonl with
#                                  per-tick timing/mfu
#
# While the battery runs, $OUT/BATTERY_RUNNING exists — do NOT start heavy
# CPU work (the full pytest suite) while it is present; host contention
# skews the device timings' host-side loop.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
OUT="${PROBE_OUT:-$REPO/.probe}"
mkdir -p "$OUT"
LOG="$OUT/probe.log"
PROBE_INTERVAL="${PROBE_INTERVAL:-420}"

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
log() { echo "[$(stamp)] $*" >>"$LOG"; }

probe() {
    # PYTHONPATH stays ambient: the axon sitecustomize IS the TPU plugin.
    timeout 120 python -c \
        "import jax; d = jax.devices(); assert d[0].platform == 'tpu', d; print(d[0].device_kind)" \
        >>"$LOG" 2>&1
}

run_stage() {  # run_stage <timeout_s> <artifact|-> <cmd...>
    local budget="$1" artifact="$2"; shift 2
    log "stage start: $* (budget ${budget}s)"
    if [ "$artifact" = "-" ]; then
        timeout "$budget" "$@" >>"$LOG" 2>&1
    else
        timeout "$budget" "$@" >"$artifact" 2>>"$LOG"
    fi
    log "stage exit=$?: $1"
}

battery() {
    local win="$OUT/window_$(date -u +%Y%m%dT%H%M%SZ)"
    mkdir -p "$win"
    touch "$OUT/BATTERY_RUNNING"
    log "TPU reachable — battery firing into $win"

    GRAFT_BENCH_TPU_TIMEOUT=2100 GRAFT_BENCH_SWEEP=16,32 \
        run_stage 2700 "$win/bench_tpu.json" python bench.py
    [ -f .bench_phases.json ] && cp .bench_phases.json "$win/bench_phases_tpu.json"

    run_stage 900 "$win/pallas_tpu.json" python scripts/bench_pallas_attention.py
    run_stage 900 "$win/components_tpu.json" python scripts/bench_components.py
    run_stage 1200 - python -m gansformer_tpu.cli.train \
        --preset ffhq256-duplex --data-source synthetic --batch-size 8 \
        --total-kimg 8 --fused-cycle --results-dir "$win/train_tpu"

    rm -f "$OUT/BATTERY_RUNNING"
    log "battery complete: $(ls "$win")"
}

log "probe loop started (interval ${PROBE_INTERVAL}s, pid $$)"
while true; do
    if probe; then
        battery
        log "probe loop exiting after first successful battery"
        exit 0
    fi
    log "probe failed; sleeping ${PROBE_INTERVAL}s"
    sleep "$PROBE_INTERVAL"
done
