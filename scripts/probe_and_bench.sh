#!/usr/bin/env bash
# Multi-window probe-and-bench loop for the axon TPU tunnel (PERF.md §1c).
#
# The tunnel serves minutes-long windows separated by hours of outage
# (measured r4: ~25 min in ~20 h, window arriving EARLY in the session),
# so a session must start this loop at minute 0 or risk losing the round's
# only measurement window to setup latency:
#
#     nohup scripts/probe_and_bench.sh >/dev/null 2>&1 &
#
# Behavior (ISSUE 5: multi-window + resumable): probe the backend every
# PROBE_INTERVAL (default 420 s) with a 120 s-timeout child (the axon
# claim loop can hang forever — the timeout IS the probe's failure
# detector).  On every successful probe, run scripts/battery.py: it
# consults the stage-completion ledger (.probe/window_*/done.json) and
# fires ONLY the stages no previous window completed, most-important
# first — the four-phase bench JSON lands within ~10 minutes of the first
# window; a window that dies mid-battery is resumed (missing stages only)
# at the next claim.  The loop exits 0 only when the ledger says the
# whole battery is complete, so re-arming after partial windows is
# automatic.
#
# While a battery runs, $OUT/BATTERY_RUNNING exists — do NOT start heavy
# CPU work (the full pytest suite) while it is present; host contention
# skews the device timings' host-side loop.
#
# Env knobs: PROBE_OUT (artifact root), PROBE_INTERVAL (s), MAX_PROBES
# (0 = unlimited; tests use small values), GRAFT_PROBE_CMD (override the
# backend probe, also honored by battery.py's between-stage re-probe).
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
OUT="${PROBE_OUT:-$REPO/.probe}"
mkdir -p "$OUT"
LOG="$OUT/probe.log"
PROBE_INTERVAL="${PROBE_INTERVAL:-420}"
MAX_PROBES="${MAX_PROBES:-0}"

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
log() { echo "[$(stamp)] $*" >>"$LOG"; }

probe() {
    if [ -n "${GRAFT_PROBE_CMD:-}" ]; then
        timeout 120 sh -c "$GRAFT_PROBE_CMD" >>"$LOG" 2>&1
    else
        # PYTHONPATH stays ambient: the axon sitecustomize IS the TPU plugin.
        timeout 120 python -c \
            "import jax; d = jax.devices(); assert d[0].platform == 'tpu', d; print(d[0].device_kind)" \
            >>"$LOG" 2>&1
    fi
}

log "probe loop started (interval ${PROBE_INTERVAL}s, pid $$)"
n=0
while true; do
    n=$((n + 1))
    if probe; then
        log "TPU reachable — battery resuming (probe $n)"
        python scripts/battery.py run --out "$OUT" >>"$LOG" 2>&1
        rc=$?
        if [ "$rc" -eq 0 ]; then
            log "battery COMPLETE across $(ls -d "$OUT"/window_* 2>/dev/null | wc -l) window(s); exiting"
            exit 0
        fi
        log "battery partial (rc=$rc); re-arming for the next window"
    else
        log "probe $n failed"
    fi
    if [ "$MAX_PROBES" -gt 0 ] && [ "$n" -ge "$MAX_PROBES" ]; then
        log "MAX_PROBES=$MAX_PROBES reached; exiting with battery incomplete"
        exit 1
    fi
    sleep "$PROBE_INTERVAL"
done
