"""Assert that a training run actually LEARNED (VERDICT r4 item 4).

SHIM — the checker now lives in the graftlint framework as
``gansformer_tpu/analysis/learning_trend.py`` (the ``learning-trend``
run-dir rule, ISSUE 4); this script keeps the original entry point and
module API (``check`` / ``read_metric_series`` / ``fit_line``, result
shape ``{ok, metric, first, last, fit_drop_rel, points}``) so existing
invocations and tests keep working:

  python scripts/check_learning_trend.py <run_dir> [--metric fid512_uncal]

Prefer ``gansformer-lint --run-dir <dir> --learning-trend`` for new
wiring; see docs/static-analysis.md.
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:          # direct `python scripts/…` invocation
    sys.path.insert(0, _ROOT)

from gansformer_tpu.analysis.learning_trend import (  # noqa: E402,F401
    check,
    fit_line,
    lint_learning_trend,
    main,
    read_metric_series,
)

if __name__ == "__main__":
    sys.exit(main())
