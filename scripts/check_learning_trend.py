"""Assert that a training run actually LEARNED (VERDICT r4 item 4).

The reference's verification model is golden-metric empiricism: train,
then watch FID fall (SURVEY.md §4 item 1).  This checker makes that an
assertable artifact property: given a run dir, it reads the recorded
``metric-*.txt`` series (written by the tick loop / evaluate CLI) and
``stats.jsonl``, and asserts

  * >= ``--min-points`` metric evaluations exist,
  * the metric IMPROVED: last fitted value < first fitted value by
    >= ``--min-drop`` (relative), using a least-squares line over the
    series so a noisy final tick cannot fake or hide a trend,
  * losses in stats.jsonl stayed finite throughout.

Prints one JSON line {ok, metric, first, last, fit_drop_rel, points};
exit code 0 iff ok.  Used by tests/test_learning_trend.py (synthetic
artifacts) and on the committed learning-evidence run (PERF.md §5).

  python scripts/check_learning_trend.py <run_dir> [--metric fid512_uncal]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def read_metric_series(run_dir: str, metric: str | None):
    """[(kimg, value)] from metric-<name>.txt (tick-loop format:
    'kimg <k> <name> <v>').  metric=None picks the first fid* file."""
    if metric:
        paths = [os.path.join(run_dir, f"metric-{metric}.txt")]
    else:
        paths = sorted(glob.glob(os.path.join(run_dir, "metric-fid*.txt")))
    if not paths or not os.path.exists(paths[0]):
        return None, []
    name = os.path.basename(paths[0])[len("metric-"):-len(".txt")]
    series = []
    with open(paths[0]) as f:
        for line in f:
            m = re.match(r"kimg\s+([\d.]+)\s+\S+\s+([\d.eE+-]+)", line)
            if m:
                series.append((float(m.group(1)), float(m.group(2))))
    return name, series


def fit_line(series):
    """Least-squares (intercept, slope) over (kimg, value)."""
    n = len(series)
    xs = [k for k, _ in series]
    ys = [v for _, v in series]
    mx, my = sum(xs) / n, sum(ys) / n
    var = sum((x - mx) ** 2 for x in xs) or 1e-12
    slope = sum((x - mx) * (y - my) for x, y in series) / var
    return my - slope * mx, slope


def check(run_dir: str, metric: str | None, min_points: int,
          min_drop: float) -> dict:
    name, series = read_metric_series(run_dir, metric)
    out = {"ok": False, "run_dir": run_dir, "metric": name,
           "points": len(series)}
    if len(series) < min_points:
        out["error"] = (f"only {len(series)} metric points "
                        f"(need >= {min_points})")
        return out
    b, a = fit_line(series)
    first_fit = b + a * series[0][0]
    last_fit = b + a * series[-1][0]
    drop = (first_fit - last_fit) / abs(first_fit) if first_fit else 0.0
    out.update({
        "first": round(series[0][1], 4), "last": round(series[-1][1], 4),
        "first_fit": round(first_fit, 4), "last_fit": round(last_fit, 4),
        "fit_drop_rel": round(drop, 4), "slope_per_kimg": round(a, 6),
    })
    if drop < min_drop:
        out["error"] = (f"fitted {name} fell only {drop * 100:.1f}% "
                        f"(need >= {min_drop * 100:.0f}%) — no learning "
                        f"evidence")
        return out
    stats_path = os.path.join(run_dir, "stats.jsonl")
    if os.path.exists(stats_path):
        import math

        for line in open(stats_path):
            row = json.loads(line)
            for k, v in row.items():
                if k.startswith("Loss/") and isinstance(v, float) \
                        and not math.isfinite(v):
                    out["error"] = f"non-finite {k} at tick " \
                                   f"{row.get('Progress/tick')}"
                    return out
    out["ok"] = True
    return out


def main(argv=None) -> int:
    # argv-parameterized and side-effect-free on import, so the analysis
    # test suite can import and drive every script it shims (ISSUE 3):
    # parse_args/sys.exit only run under __main__ or an explicit call.
    p = argparse.ArgumentParser()
    p.add_argument("run_dir")
    p.add_argument("--metric", default=None,
                   help="metric name (default: first metric-fid*.txt)")
    p.add_argument("--min-points", type=int, default=3)
    p.add_argument("--min-drop", type=float, default=0.10,
                   help="required relative drop of the fitted line")
    args = p.parse_args(argv)
    out = check(args.run_dir, args.metric, args.min_points, args.min_drop)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
