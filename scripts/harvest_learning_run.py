"""Harvest the learning-evidence run into a committed record.

Copies the PROOF artifacts of a training run (VERDICT r4 item 4: the
reference's golden-metric verification model — watch FID fall) into
``docs/learning_evidence_<tag>/``: stats.jsonl, every metric series, the
resolved config, first/latest image grids, a grid of REAL samples from
the same dataset for side-by-side reading, and the
``check_learning_trend`` verdict as JSON.  Exits non-zero if the trend
check fails — a harvest that can't prove learning should not look like
one that did.

  PYTHONPATH= JAX_PLATFORMS=cpu python scripts/harvest_learning_run.py \
      .learning_run/00000-learn-evidence --tag r05
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from check_learning_trend import check  # noqa: E402  (sibling script)


def copy_artifacts(run: str, out: str) -> list:
    """Copy the run's record files into the evidence dir; returns the
    copied basenames.  Flags are state, not series (VERDICT r5 weak #4):
    a legacy all-constant ``metric-<flag>.txt`` pseudo-metric is NEVER
    harvested; the ``flag-<name>.txt`` state files are copied as
    themselves."""
    from gansformer_tpu.metrics.metric_base import FLAG_KEYS

    copied = []
    for name in ["stats.jsonl", "config.json", "log.txt"]:
        src = os.path.join(run, name)
        if os.path.exists(src):
            shutil.copy(src, out)
            copied.append(name)
    for src in glob.glob(os.path.join(run, "metric-*.txt")):
        base = os.path.basename(src)
        if base[len("metric-"):-len(".txt")] in FLAG_KEYS:
            continue
        shutil.copy(src, out)
        copied.append(base)
    for src in glob.glob(os.path.join(run, "flag-*.txt")):
        shutil.copy(src, out)
        copied.append(os.path.basename(src))
    return copied


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("run_dir")
    p.add_argument("--tag", default="r05")
    p.add_argument("--min-points", type=int, default=3)
    p.add_argument("--min-drop", type=float, default=0.10)
    args = p.parse_args()
    run = args.run_dir.rstrip("/")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = os.path.join(repo, "docs", f"learning_evidence_{args.tag}")
    os.makedirs(out, exist_ok=True)

    verdict = check(run, None, args.min_points, args.min_drop)
    print(json.dumps(verdict))
    if not verdict["ok"]:
        # Do NOT touch the committed evidence dir: a failing re-harvest
        # must never clobber a passing verdict with a contradiction.
        sys.exit(1)
    with open(os.path.join(out, "trend.json"), "w") as f:
        json.dump(verdict, f, indent=1)

    copy_artifacts(run, out)
    fakes = sorted(glob.glob(os.path.join(run, "fakes*.png")))
    if fakes:
        shutil.copy(fakes[0], os.path.join(out, "grid_first.png"))
        shutil.copy(fakes[-1], os.path.join(
            out, f"grid_latest_{os.path.basename(fakes[-1])[5:11]}.png"))

    # A grid of REAL samples from the exact dataset config, for the
    # side-by-side the reference's qualitative eval relied on.
    from gansformer_tpu.core.config import ExperimentConfig
    from gansformer_tpu.data.dataset import make_dataset
    from gansformer_tpu.utils.image import save_image_grid

    with open(os.path.join(run, "config.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    ds = make_dataset(cfg.data)
    batch = next(ds.batches(16, seed=123))
    save_image_grid(batch["image"], os.path.join(out, "grid_reals.png"),
                    drange=(0, 255))

    print(f"harvested into {out}: {sorted(os.listdir(out))}")


if __name__ == "__main__":
    main()
