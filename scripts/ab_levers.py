"""A/B battery for the flag-gated step-time levers (ISSUE 5, PERF.md §1d).

Each lever is a prepared, config-flag-gated variant of one train-step
phase.  This script prices every variant against its baseline with the
same methodology bench.py applies to the phases: AOT-compile the REAL
jitted step program per variant, read ``cost_analysis()`` FLOPs + bytes
and ``memory_analysis()`` temp workspace, and — on a TPU — time the
steady-state step and report the measured Δms.  On CPU the structure
(FLOPs/bytes/workspace deltas) is exact and timings are skipped, so the
same artifact schema works for the offline cost-delta table in PERF.md
and for the on-chip decision table a tunnel window produces.

  python scripts/ab_levers.py [--preset ffhq256-duplex] [--batch 8] \
      [--iters 10] [--json-out ab_levers.json] [--levers pl_batch_shrink]
  python scripts/ab_levers.py --config run_dir/config.json   # custom cfg

Lever catalog (wired through core/config.py + cli/train.py; acceptance
contracts in tests/test_levers.py):

  pl_batch_shrink   g_pl phase — PL probe on batch/N fresh samples
                    (StyleGAN2's own trick; 2 is the reference default)
  r1_batch_shrink   d_r1 phase — R1 on an unbiased batch slice,
                    lazy-reg weight unchanged (default 1 = off)
  attn_fused_kv     every phase — one K∥V projection matmul per
                    attention direction (exact math, default off)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_cfg(cfg, **kv):
    return dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, **kv))


def _model_cfg(cfg, **kv):
    return dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, **kv))


# Lever catalog: name → (phase, CLI flag, test anchor, variants).  Each
# variant is (setting_label, cfg_transform); the entry tagged
# ``baseline`` is the Δ reference.
def lever_catalog():
    return [
        {
            "name": "pl_batch_shrink",
            "phase": "g_pl",
            "flag": "--pl-batch-shrink (TrainConfig.pl_batch_shrink)",
            "test": "tests/test_levers.py::TestPlBatchShrink",
            "baseline": "2",
            "variants": [
                ("1", lambda c: _train_cfg(c, pl_batch_shrink=1)),
                ("2", lambda c: _train_cfg(c, pl_batch_shrink=2)),
                ("4", lambda c: _train_cfg(c, pl_batch_shrink=4)),
            ],
        },
        {
            "name": "r1_batch_shrink",
            "phase": "d_r1",
            "flag": "--r1-batch-shrink (TrainConfig.r1_batch_shrink)",
            "test": "tests/test_levers.py::TestR1BatchShrink",
            "baseline": "1",
            "variants": [
                ("1", lambda c: _train_cfg(c, r1_batch_shrink=1)),
                ("2", lambda c: _train_cfg(c, r1_batch_shrink=2)),
                ("4", lambda c: _train_cfg(c, r1_batch_shrink=4)),
            ],
        },
        {
            "name": "attn_fused_kv",
            "phase": "g",
            "flag": "--attn-fused-kv (ModelConfig.attn_fused_kv)",
            "test": "tests/test_levers.py::test_attn_fused_kv_parity",
            "baseline": "off",
            "variants": [
                ("off", lambda c: _model_cfg(c, attn_fused_kv=False)),
                ("on", lambda c: _model_cfg(c, attn_fused_kv=True)),
            ],
        },
        {
            # ISSUE 14: the fused modulate→conv→demodulate / polyphase
            # up-conv / upfirdn kernel family as a steppable lever — the
            # 'on' variant compiles the REAL g step with
            # conv_backend='pallas' (interpret mode off-TPU: structure
            # only; a tunnel window prices the native ms delta).  Since
            # ISSUE 17's halo row blocking the 'on' program carries the
            # kernels at EVERY grid of the preset (256²/512²/1024²
            # row-block instead of silently falling back), so the delta
            # prices the whole family, not just the small grids.
            "name": "conv_fused_mod",
            "phase": "g",
            "flag": "--conv-backend (ModelConfig.conv_backend)",
            "test": "tests/test_levers.py::test_conv_fused_mod_parity",
            "baseline": "off",
            "variants": [
                ("off", lambda c: _model_cfg(c, conv_backend="xla")),
                ("on", lambda c: _model_cfg(c, conv_backend="pallas")),
            ],
        },
    ]


def attach_deltas(lever: dict) -> dict:
    """Fill delta_* fields vs the lever's baseline variant (pure —
    unit-tested): Δ < 0 means the variant is cheaper."""
    base = next((v for v in lever["variants"]
                 if v["setting"] == lever["baseline"]), None)
    for v in lever["variants"]:
        v["is_baseline"] = base is not None and v is base
        for key in ("gflops", "gbytes", "temp_gib", "ms"):
            if base and v.get(key) is not None and base.get(key) is not None:
                v[f"delta_{key}"] = round(v[key] - base[key], 4)
    return lever


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="ffhq256-duplex")
    p.add_argument("--config", default=None,
                   help="JSON config file overriding --preset (a run "
                        "dir's config.json or a test's micro config)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--json-out", default=None)
    p.add_argument("--levers", default=None,
                   help="comma list restricting which levers run")
    args = p.parse_args(argv)

    import jax

    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache(_REPO)

    import numpy as np

    from gansformer_tpu.core.config import ExperimentConfig, get_preset
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.utils.benchcheck import (
        flops_of, lower_phase, peak_tflops)

    if args.config:
        with open(args.config) as f:
            base_cfg = ExperimentConfig.from_json(f.read())
    else:
        base_cfg = get_preset(args.preset)
    b = args.batch
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak = peak_tflops(dev.device_kind) if on_tpu else None
    rs = np.random.RandomState(0)
    key = jax.random.PRNGKey(0)
    imgs_np = rs.randint(
        0, 255, (b, base_cfg.model.resolution, base_cfg.model.resolution,
                 base_cfg.model.img_channels)).astype(np.uint8)

    meta = {"device_kind": dev.device_kind, "platform": dev.platform,
            "batch": b, "preset": base_cfg.name,
            "peak_bf16_tflops": peak, "iters": args.iters}
    print(json.dumps(meta), flush=True)

    def measure(cfg, phase):
        """(gflops, gbytes, temp_gib, ms|None) of one phase program."""
        cfg.validate()
        label_dim = cfg.model.label_dim
        # Shared lowering (benchcheck.lower_phase): abstract state via
        # eval_shape + the conditional-label arg in one place.
        compiled = lower_phase(cfg, phase, batch_size=b)
        fl = flops_of(compiled)
        rec = {"gflops": round(fl / 1e9, 2) if fl else None}
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            by = float(ca.get("bytes accessed", 0.0))
            rec["gbytes"] = round(by / 1e9, 3) if by > 0 else None
        except Exception:
            rec["gbytes"] = None
        try:
            ma = compiled.memory_analysis()
            rec["temp_gib"] = round(ma.temp_size_in_bytes / 2**30, 3)
        except Exception:
            rec["temp_gib"] = None
        rec["ms"] = None
        if on_tpu:
            # Real steady-state timing: whole-state init as ONE jitted
            # program (the eager path dispatches hundreds of tunnel
            # round-trips — PERF.md §1c harness note).  The timing loop
            # drives the AOT ``compiled`` executable from the cost pass
            # above — calling the jit wrapper here would pay a SECOND
            # compile of the same program into the window budget
            # (bench.py's established pattern).
            state = jax.jit(lambda k: create_train_state(cfg, k))(key)
            imgs = jax.device_put(imgs_np)
            lbl = (jax.device_put(np.eye(label_dim, dtype=np.float32)[
                rs.randint(0, label_dim, b)]) if label_dim else None)
            call = ((lambda s: compiled(s, imgs, key, lbl))
                    if phase.startswith("d")
                    else (lambda s: compiled(s, key, lbl)))
            state, aux = call(state)             # warm-up (donates state)
            jax.block_until_ready(aux)
            t0 = time.time()
            for _ in range(args.iters):
                state, aux = call(state)
            jax.block_until_ready(aux)
            rec["ms"] = round((time.time() - t0) / args.iters * 1e3, 3)
            if fl and peak:
                rec["mfu"] = round(
                    fl / (rec["ms"] * 1e-3) / (peak * 1e12), 4)
        return rec

    selected = None if args.levers is None else {
        s.strip() for s in args.levers.split(",") if s.strip()}
    levers = []
    for lever in lever_catalog():
        if selected is not None and lever["name"] not in selected:
            continue
        out = {k: lever[k] for k in
               ("name", "phase", "flag", "test", "baseline")}
        out["variants"] = []
        for setting, transform in lever["variants"]:
            cfg = _train_cfg(transform(base_cfg), batch_size=b)
            t0 = time.time()
            try:
                rec = measure(cfg, lever["phase"])
            except Exception as e:   # an OOM/compile failure on one
                rec = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            rec = {"setting": setting, **rec,
                   "measure_s": round(time.time() - t0, 1)}
            print(json.dumps({"lever": lever["name"], **rec}), flush=True)
            out["variants"].append(rec)
        levers.append(attach_deltas(out))

    artifact = {"meta": meta, "levers": levers,
                "note": ("CPU run: FLOPs/bytes/workspace deltas are "
                         "exact, ms is null — only a TPU window prices "
                         "time" if not on_tpu else None)}
    if args.json_out:
        tmp = args.json_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=1)
        os.replace(tmp, args.json_out)
    print(json.dumps({"ab_levers_done": [lv["name"] for lv in levers]}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
