"""Zipfian load test for the generation service (ISSUE 10).

Models the millions-of-users request mix the ROADMAP names: seeds drawn
from a bounded Zipf distribution (a few hot seeds and a long tail — the
w-cache's natural prey), ψ from a small Zipf-weighted menu, Poisson
arrivals at ``--rate``.  Reports what a TPU serving comparison must
report (the Gemma-on-TPU paper's axes, PAPERS.md): p50/p99 end-to-end
latency, img/s and img/s/chip under load, batch fill, and cold-vs-warm
first-image time (the warm-start manifest's whole value proposition).

Capture beats verdict (the battery discipline): the script exits 0
whenever the JSON artifact is written — SLO verdicts live IN the
artifact (``prom_ok``, the latency table), never in the exit code, so a
slow window still banks its numbers.

``--chaos`` (ISSUE 13) is the overload/failure variant: a back-to-back
burst of ``--burst-factor`` × ``--queue-depth`` requests against the
bounded admission queue, with one injected dispatcher crash
(``raise@serve_dispatch`` at ``--crash-at-batch``).  The artifact
reports shed/expired rates, p50/p99 *under overload*, dispatcher
restarts, recovery time, and the hung-ticket count (must be 0).

    python scripts/loadtest_serve.py --tiny --requests 64 --json-out out.json
    python scripts/loadtest_serve.py --preset ffhq256-duplex --init random \
        --buckets 1,4,8 --requests 300 --rate 8 --duration-s 300 \
        --json-out serve_loadtest.json
    python scripts/loadtest_serve.py --tiny --chaos --queue-depth 8 \
        --json-out serve_chaos.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    i = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[i])


def zipf_choice(rng, universe, size, s: float):
    """Bounded ranked-Zipf draw: p(rank i) ∝ 1/(i+1)^s."""
    import numpy as np

    p = 1.0 / np.arange(1, len(universe) + 1, dtype=np.float64) ** s
    return rng.choice(universe, size=size, p=p / p.sum())


def trace_coverage_of(tickets):
    """Terminal trace coverage over a ticket list (ISSUE 16/20
    acceptance: 100 % of terminal requests traced).  Returns
    (coverage dict, rid→terminal-row map)."""
    from gansformer_tpu.obs import reqtrace as _reqtrace

    rt = _reqtrace.get_reqtracer()
    rids = [t.rid for t in tickets if getattr(t, "rid", None)]
    terminal_rows = {r["rid"]: r for r in rt.recent()}
    missing = [r for r in rids if r not in terminal_rows]
    return ({"enabled": rt.enabled, "tickets": len(rids),
             "terminal": sum(1 for r in rids if r in terminal_rows),
             "missing_terminal_rids": missing,
             "ok": not rt.enabled or not missing}, terminal_rows)


def per_replica_report(snap0, snap1, wall_s, ordinals):
    """Per-replica attribution (ISSUE 20 satellite): img/s, batch fill,
    batch latency, and dispatch share per device, from telemetry DELTAS
    between two registry snapshots (the registry is process-global and
    cumulative — absolute values would bleed across runs)."""
    def c_delta(name):
        return (snap1["counters"].get(name, 0.0)
                - snap0["counters"].get(name, 0.0))

    def h_delta(name):
        h1 = snap1["histograms"].get(name, {})
        h0 = snap0["histograms"].get(name, {})
        dn = (h1.get("count") or 0) - (h0.get("count") or 0)
        ds = (h1.get("sum") or 0.0) - (h0.get("sum") or 0.0)
        return (ds / dn) if dn > 0 else None

    total_req = sum(
        c_delta(f"serve/replica{i}/requests_total") for i in ordinals)
    out = {}
    for i in ordinals:
        imgs = c_delta(f"serve/replica{i}/images_total")
        req = c_delta(f"serve/replica{i}/requests_total")
        out[str(i)] = {
            "requests": req,
            "images": imgs,
            "img_per_s": round(imgs / max(wall_s, 1e-9), 2),
            "batch_fill_mean": h_delta(f"serve/replica{i}/batch_fill"),
            "batch_ms_mean": h_delta(f"serve/replica{i}/batch_ms"),
            "dispatch_share": round(req / total_req, 4) if total_req
            else 0.0,
        }
    return out


def run_chaos(bundle, buckets, queue_depth=8, burst_factor=4,
              crash_at_batch=2, deadline_s=None, zipf_s=1.1,
              seed_universe=64, manifest_dir=None, fill_wait_ms=0.0,
              wcache=4096, seed=0, restart_backoff_s=0.05,
              grace_s=60.0, replicas=1, autoscale=False,
              max_replicas=None, serve_precision="f32",
              pressure_s=0.8):
    """Overload + chaos drill (ISSUE 13): submit ``burst_factor ×
    queue_depth`` requests back-to-back (arrival far beyond capacity)
    against a service with a bounded admission queue, with ONE injected
    dispatcher crash mid-burst (``raise@serve_dispatch``).  Reports the
    degradation report card: shed/expired/cancelled rates, p50/p99
    *under overload* (served tickets only), dispatcher restarts,
    recovery time (first successful completion after the first
    failure), and the hung-ticket count — the acceptance number that
    MUST be zero.  Pure of argparse/IO so tests call it directly.

    With ``autoscale`` (ISSUE 20) the drill runs against a
    ``ReplicaSet`` under a deliberately twitchy controller config and
    the burst becomes a *sustained* pressure window (``pressure_s``) so
    the controller observes consecutive saturated ticks; the artifact's
    ``autoscale`` section carries the ordering evidence (scale-out
    BEFORE any breaker trip; scale-in after recovery)."""
    import jax
    import numpy as np

    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import (
        Cancelled, Expired, GenerationService, Overloaded, ReplicaSet,
        ServeError, ServePrograms)
    from gansformer_tpu.supervise import faults

    rng = np.random.RandomState(seed)
    fleet = replicas > 1 or autoscale
    if not fleet:
        programs = ServePrograms(bundle, buckets=buckets,
                                 manifest_dir=manifest_dir,
                                 serve_precision=serve_precision)
        warm = programs.warm_start()
    n_req = int(burst_factor * queue_depth)
    seeds = zipf_choice(rng, np.arange(1, seed_universe + 1), n_req,
                        zipf_s)
    reg = telemetry.get_registry()
    restarts0 = reg.counter("serve/dispatcher_restarts_total").value
    tickets, shed = [], 0
    outcomes = {"served": 0, "failed": 0, "expired": 0, "cancelled": 0,
                "hung": 0}

    def settle(wave):
        # ONE shared wall-clock budget per wave, not grace_s per ticket:
        # a wedged dispatcher with N hung tickets must cost ~grace_s,
        # not N x grace_s — the battery stage budget (and the artifact)
        # depend on the drill bounding itself
        deadline = time.perf_counter() + grace_s
        for t in wave:
            try:
                t.result(timeout=max(0.1,
                                     deadline - time.perf_counter()))
                outcomes["served"] += 1
            except Expired:
                outcomes["expired"] += 1
            except Cancelled:
                outcomes["cancelled"] += 1
            except TimeoutError:
                outcomes["hung"] += 1      # the zero-tolerance bucket
            except RuntimeError:
                outcomes["failed"] += 1

    t0 = time.perf_counter()
    svc = None
    try:
        # arm INSIDE the disarming try: an exception anywhere past this
        # point (service construction included) must not leak an armed
        # process-global fault spec into later callers
        if crash_at_batch:
            faults.arm(faults.parse_specs(
                f"raise@serve_dispatch:batch={int(crash_at_batch)}"))
        if fleet:
            svc = ReplicaSet(
                bundle, buckets=buckets, manifest_dir=manifest_dir,
                serve_precision=serve_precision,
                replicas=replicas, min_replicas=replicas,
                max_replicas=max_replicas, autoscale=autoscale,
                # twitchy drill config: the controller must react within
                # the sub-second pressure window, not on fleet timescales
                autoscale_interval_s=0.05, scale_out_saturation=0.6,
                scale_out_ticks=2, scale_in_fill=0.5, scale_in_ticks=4,
                cooldown_s=0.3,
                service_kwargs=dict(
                    max_fill_wait_ms=fill_wait_ms,
                    wcache_capacity=wcache,
                    max_queue_depth=queue_depth,
                    default_deadline_s=deadline_s,
                    restart_backoff_base_s=restart_backoff_s))
            warm = svc.warm_start()
        else:
            svc = GenerationService(
                programs, max_fill_wait_ms=fill_wait_ms,
                wcache_capacity=wcache, max_queue_depth=queue_depth,
                default_deadline_s=deadline_s,
                restart_backoff_base_s=restart_backoff_s)
        # Wave 1 — the overload burst: back-to-back submits far beyond
        # capacity; over-bound submissions shed typed.  Capture beats
        # verdict: a breaker tripped by real deaths on sick hardware
        # refuses typed (ServiceUnhealthy) — counted, never raised out
        # of the drill (the artifact must land EXACTLY then).
        refused = 0
        burst_submitted = 0
        if fleet and autoscale:
            # sustained pressure: a one-shot burst can drain between two
            # controller ticks on a fast host, so keep the queues
            # saturated for the whole window (resubmitting the same
            # Zipf stream); sheds pace the loop so it cannot spin
            pressure_end = time.perf_counter() + pressure_s
            i = 0
            while (time.perf_counter() < pressure_end
                   or burst_submitted < n_req):
                try:
                    tickets.append(svc.submit(int(seeds[i % n_req])))
                except Overloaded:
                    shed += 1
                    time.sleep(0.002)
                except ServeError:
                    refused += 1
                    time.sleep(0.002)
                i += 1
                burst_submitted += 1
                if burst_submitted >= n_req * 64:   # runaway bound
                    break
        else:
            for i in range(n_req):
                try:
                    tickets.append(svc.submit(int(seeds[i])))
                except Overloaded:
                    shed += 1
                except ServeError:
                    refused += 1
            burst_submitted = n_req
        settle(tickets)
        # Wave 2 — paced recovery traffic: guarantees the dispatcher
        # sees MULTIPLE batches (a small burst can fit one bucket, in
        # which case the injected crash would idle un-fired) and that
        # post-crash service is measured, not assumed.
        recovery_wave = []
        n_wave2 = max(2, int(queue_depth))
        for i in range(n_wave2):
            try:
                recovery_wave.append(
                    svc.submit(int(seeds[i % n_req]) + seed_universe))
            except Overloaded:
                shed += 1
            except ServeError:
                refused += 1
            time.sleep(0.002)
        burst_tickets = list(tickets)
        tickets += recovery_wave
        settle(recovery_wave)
        recovered = sum(1 for t in recovery_wave if t.state == "done")
        if fleet and autoscale:
            # recovery is over (queues empty, batches mostly padding):
            # wait for the controller to notice and scale back IN —
            # hysteresis (4 idle ticks @50ms + 0.3s cooldown) bounds
            # how fast this CAN happen, so poll, don't assert a sleep
            poll_end = time.perf_counter() + 6.0
            while time.perf_counter() < poll_end:
                if any(e["kind"] == "scale_in" for e in svc.events):
                    break
                time.sleep(0.05)
        health = svc.health()
        scale_events = list(svc.events) if fleet else []
    finally:
        if svc is not None:
            svc.close(timeout=grace_s)
        faults.disarm()
    wall_s = time.perf_counter() - t0
    # Terminal trace coverage (ISSUE 16 acceptance): after close(),
    # EVERY ticket the drill holds — hung and failed included — must
    # have reached a terminal trace event with a cause; a rid still
    # untraced here means a recovery path resolves tickets outside the
    # _resolve funnel (a leak the ledger would never show).
    trace_coverage, terminal_rows = trace_coverage_of(tickets)
    non_fulfilled = [
        {"rid": t.rid, "state": t.state,
         "outcome": (terminal_rows.get(t.rid) or {}).get("outcome"),
         "cause": (terminal_rows.get(t.rid) or {}).get("cause")}
        for t in tickets
        if getattr(t, "rid", None) and t.state != "done"]
    # recovery: first successful completion AFTER the first failure
    fails = [t.t_done for t in tickets
             if t.state == "failed" and t.t_done is not None]
    servs = sorted(t.t_done for t in tickets
                   if t.state == "done" and t.t_done is not None)
    recovery_ms = None
    if fails:
        after = [s for s in servs if s > min(fails)]
        if after:
            recovery_ms = round((after[0] - min(fails)) * 1000.0, 1)
    # percentiles over the BURST wave only: blending in the paced
    # recovery wave's healthy latencies would dilute "under overload";
    # None (not NaN — invalid strict JSON) when nothing was served
    lats = sorted(t.latency_ms for t in burst_tickets
                  if t.state == "done")
    result = {
        "mode": "chaos", "buckets": list(buckets),
        "queue_bound": queue_depth, "burst_factor": burst_factor,
        "crash_at_batch": crash_at_batch,
        "deadline_s": deadline_s,
        "serve_precision": serve_precision,
        "replicas": replicas,
        # submitted/shed/shed_rate span BOTH waves (burst + recovery),
        # so accepted <= submitted and shed_rate <= 1.0 always hold
        "submitted": burst_submitted + n_wave2, "burst": burst_submitted,
        "accepted": len(tickets), "shed": shed,
        "refused_unhealthy": refused,
        "shed_rate": round(shed / max(burst_submitted + n_wave2, 1), 4),
        "recovery_wave_served": recovered,
        "served": outcomes["served"], "failed": outcomes["failed"],
        "expired": outcomes["expired"],
        "expired_rate": round(outcomes["expired"]
                              / max(burst_submitted + n_wave2, 1), 4),
        "cancelled": outcomes["cancelled"],
        "hung_tickets": outcomes["hung"],
        "p50_ms_under_overload":
            round(percentile(lats, 50), 2) if lats else None,
        "p99_ms_under_overload":
            round(percentile(lats, 99), 2) if lats else None,
        "dispatcher_restarts":
            reg.counter("serve/dispatcher_restarts_total").value
            - restarts0,
        "trace_coverage": trace_coverage,
        "non_fulfilled_requests": non_fulfilled,
        "recovery_ms": recovery_ms,
        "health": health,
        "warm_start": {k: (round(v, 3) if k == "seconds" else v)
                       for k, v in warm.items()},
        "duration_s": round(wall_s, 3),
        "device": {"platform": jax.devices()[0].platform,
                   "kind": jax.devices()[0].device_kind,
                   "count": len(jax.devices())},
    }
    if fleet:
        # the ordering evidence the doctor grades: the LEADING signal
        # (queue saturation → scale-out) must fire before the TRAILING
        # one (breaker trip) ever could; scale-in must follow recovery
        outs = [e["t"] for e in scale_events if e["kind"] == "scale_out"]
        ins = [e["t"] for e in scale_events if e["kind"] == "scale_in"]
        trips = [e["t"] for e in scale_events
                 if e["kind"] == "breaker_trip"]
        result["autoscale"] = {
            "enabled": bool(autoscale),
            "scale_out_fired": len(outs),
            "scale_in_fired": len(ins),
            "breaker_trips": len(trips),
            "scale_out_before_breaker":
                bool(outs) and (not trips or min(outs) < min(trips)),
            "scaled_in_after_load": bool(ins),
            "peak_replicas": max(
                [e["n_active"] for e in scale_events
                 if e["kind"] == "scale_out"] + [replicas]),
            "events": scale_events[-16:],
        }
    return result


def run_loadtest(bundle, buckets, requests, rate, duration_s,
                 zipf_s=1.1, seed_universe=512, manifest_dir=None,
                 psis=(0.7, 0.5, 1.0, 0.8), fill_wait_ms=2.0,
                 wcache=4096, seed=0, measure_cold=True,
                 serve_precision="f32", replicas=1, autoscale=False,
                 max_replicas=None, quant_report=False):
    """Drive the serving floor; returns the result dict (pure of
    argparse/IO so tests call it directly).  ``replicas > 1`` or
    ``autoscale`` routes through ``serve.ReplicaSet`` (replica-per-
    device placement, ISSUE 20) and reports per-replica attribution;
    ``serve_precision`` selects the synthesis precision axis
    (f32 | bf16 | int8w); ``quant_report=True`` attaches the AOT
    cost/fidelity A/B against the f32 reference."""
    import jax
    import numpy as np

    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import (
        GenerationService, ReplicaSet, ServePrograms)

    rng = np.random.RandomState(seed)
    fleet = replicas > 1 or autoscale
    result = {"buckets": list(buckets), "zipf_s": zipf_s,
              "seed_universe": seed_universe, "psi_menu": list(psis),
              "rate_rps": rate, "serve_precision": serve_precision,
              "replicas": replicas, "autoscale": bool(autoscale),
              "device": {"platform": jax.devices()[0].platform,
                         "kind": jax.devices()[0].device_kind,
                         "count": len(jax.devices())}}

    def first_image_ms(programs) -> float:
        with GenerationService(programs, max_fill_wait_ms=0.0,
                               wcache_capacity=0) as svc:
            t0 = time.perf_counter()
            svc.submit(int(rng.randint(1 << 20)), psi=0.7).result(
                timeout=1200)
            return (time.perf_counter() - t0) * 1000.0

    # -- cold vs warm first image -------------------------------------------
    if measure_cold:
        cold = ServePrograms(bundle, buckets=buckets,
                             manifest_dir=manifest_dir,
                             serve_precision=serve_precision)
        t0 = time.perf_counter()
        cold_warmup = cold.warm_start()
        result["cold_build_s"] = round(time.perf_counter() - t0, 3)
        result["cold_first_image_ms"] = round(first_image_ms(cold), 1)
        result["cold_compiles"] = cold_warmup["compiled"]
    programs = ServePrograms(bundle, buckets=buckets,
                             manifest_dir=manifest_dir,
                             serve_precision=serve_precision)
    t0 = time.perf_counter()
    warm_stats = programs.warm_start()
    result["warm_build_s"] = round(time.perf_counter() - t0, 3)
    result["warm_first_image_ms"] = round(first_image_ms(programs), 1)
    result["warm_start"] = {k: (round(v, 3) if k == "seconds" else v)
                            for k, v in warm_stats.items()}
    # time-to-first-image from a bare process: build (compile vs
    # deserialize) + one dispatch — THE cold/warm headline pair
    if measure_cold:
        result["cold_first_image_total_ms"] = round(
            result["cold_build_s"] * 1000.0
            + result["cold_first_image_ms"], 1)
    result["warm_first_image_total_ms"] = round(
        result["warm_build_s"] * 1000.0 + result["warm_first_image_ms"], 1)

    # -- quantization A/B (opt-in: compiles all three precisions) -----------
    if quant_report:
        from gansformer_tpu.serve.quant import cost_report, fidelity_report

        result["quant"] = {
            "cost": cost_report(bundle, bucket=max(buckets)),
            "fidelity": {
                prec: fidelity_report(bundle, prec, bucket=max(buckets))
                for prec in ("bf16", "int8w")},
        }

    # -- the load run -------------------------------------------------------
    seeds = zipf_choice(rng, np.arange(1, seed_universe + 1), requests,
                        zipf_s)
    psi_mix = zipf_choice(rng, np.asarray(psis, np.float64), requests, 1.0)
    gaps = rng.exponential(1.0 / max(rate, 1e-9), size=requests) \
        if rate > 0 else np.zeros(requests)

    tickets = []
    snap0 = telemetry.get_registry().snapshot()
    peak_replicas = replicas
    t_start = time.perf_counter()
    # the SLO loadtest measures latency under admission, not shedding:
    # the bound sits above the whole request budget so nothing sheds
    # (the overload/chaos mode is run_chaos)
    if fleet:
        svc = ReplicaSet(
            bundle, buckets=buckets, manifest_dir=manifest_dir,
            serve_precision=serve_precision, replicas=replicas,
            max_replicas=max_replicas, autoscale=autoscale,
            service_kwargs=dict(max_fill_wait_ms=fill_wait_ms,
                                wcache_capacity=wcache,
                                max_queue_depth=requests + 8))
        svc.warm_start()
    else:
        svc = GenerationService(programs, max_fill_wait_ms=fill_wait_ms,
                                wcache_capacity=wcache,
                                max_queue_depth=requests + 8)
    try:
        for i in range(requests):
            if time.perf_counter() - t_start > duration_s:
                break
            tickets.append(svc.submit(int(seeds[i]),
                                      psi=float(psi_mix[i])))
            if fleet:
                peak_replicas = max(peak_replicas, svc.n_active)
            if rate > 0:
                time.sleep(float(gaps[i]))
        images = [t.result(timeout=max(60.0, duration_s)) for t in tickets]
        wall_s = time.perf_counter() - t_start
        if fleet:
            peak_replicas = max(peak_replicas, svc.n_active)
            ordinals = [r.ordinal for r in svc._replicas]
    finally:
        svc.close(timeout=max(60.0, duration_s))

    lats = sorted(t.latency_ms for t in tickets)
    # chips actually serving, not chips present: a 1-replica run on an
    # 8-device host used one chip — THE headline the replica-scaling
    # acceptance reads (img_s_per_chip ~constant as replicas grow)
    chips_used = peak_replicas if fleet else 1
    snap = telemetry.get_registry().snapshot()
    fill = snap["histograms"].get("serve/batch_fill", {})
    depth = snap["histograms"].get("serve/queue_depth", {})
    hits = snap["counters"].get("serve/wcache_hits_total", 0.0)
    misses = snap["counters"].get("serve/wcache_misses_total", 0.0)
    result.update({
        "requests": len(tickets),
        "images": len(images),
        "duration_s": round(wall_s, 3),
        "p50_ms": round(percentile(lats, 50), 2),
        "p90_ms": round(percentile(lats, 90), 2),
        "p99_ms": round(percentile(lats, 99), 2),
        "mean_ms": round(float(sum(lats)) / max(len(lats), 1), 2),
        "img_per_s": round(len(images) / max(wall_s, 1e-9), 2),
        "chips_used": chips_used,
        "img_s_per_chip": round(
            len(images) / max(wall_s, 1e-9) / max(chips_used, 1), 2),
        "img_per_s_per_chip": round(
            len(images) / max(wall_s, 1e-9) / len(jax.devices()), 2),
        "batch_fill_mean": round(fill.get("mean", 0.0), 4),
        "queue_depth_mean": round(depth.get("mean", 0.0), 2),
        "queue_depth_max": depth.get("max"),
        "wcache_hit_rate": round(hits / max(hits + misses, 1.0), 4),
        "map_dispatch_total": snap["counters"].get(
            "serve/map_dispatch_total", 0.0),
        "synth_dispatch_total": snap["counters"].get(
            "serve/synth_dispatch_total", 0.0),
    })
    coverage, _ = trace_coverage_of(tickets)
    result["trace_coverage"] = coverage
    if fleet:
        result["peak_replicas"] = peak_replicas
        result["per_replica"] = per_replica_report(
            snap0, snap, wall_s, ordinals)
    # request-level drill-down (ISSUE 16): the slowest requests BY ID —
    # the artifact's p99 becomes resolvable to a timeline via
    # `gansformer-telemetry requests <dir> --id <rid>` — plus every
    # non-fulfilled request's ID (an SLO loadtest expects zero)
    ranked = sorted((t for t in tickets if t.state == "done"),
                    key=lambda t: -t.latency_ms)
    result["worst_requests"] = [
        {"rid": getattr(t, "rid", None),
         "latency_ms": round(t.latency_ms, 2)} for t in ranked[:5]]
    result["non_fulfilled_rids"] = [
        getattr(t, "rid", None) for t in tickets if t.state != "done"]
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Zipfian load test for the generation service")
    p.add_argument("--run-dir", default=None,
                   help="serve a real checkpoint (G-only restore)")
    p.add_argument("--preset", default=None)
    p.add_argument("--init", default="random",
                   choices=("checkpoint", "random"))
    p.add_argument("--tiny", action="store_true",
                   help="tiny 16×16 trace-config model — the CPU proxy")
    p.add_argument("--buckets", default="1,4,8")
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--rate", type=float, default=20.0,
                   help="Poisson arrival rate, req/s (0 = back-to-back)")
    p.add_argument("--duration-s", type=float, default=300.0,
                   help="hard wall bound on the submit window")
    p.add_argument("--zipf-s", type=float, default=1.1)
    p.add_argument("--seed-universe", type=int, default=512)
    p.add_argument("--fill-wait-ms", type=float, default=2.0)
    p.add_argument("--wcache", type=int, default=4096)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replicas", type=int, default=1,
                   help="replica-per-device fleet size (>1 routes "
                        "through serve.ReplicaSet; needs that many "
                        "local devices)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscaler upper bound (default: all local "
                        "devices)")
    p.add_argument("--autoscale", action="store_true",
                   help="enable the autoscaler controller (chaos mode "
                        "runs the scale-out-before-breaker drill)")
    p.add_argument("--serve-precision", default="f32",
                   choices=("f32", "bf16", "int8w"),
                   help="synthesis precision axis: f32 | bf16 "
                        "(activations) | int8w (bf16 activations + "
                        "int8 weight-only)")
    p.add_argument("--quant-report", action="store_true",
                   help="attach the quantization cost/fidelity A/B "
                        "(compiles all three precisions — slow)")
    p.add_argument("--chaos", action="store_true",
                   help="overload/chaos drill instead of the SLO "
                        "loadtest: burst past the queue bound with one "
                        "injected dispatcher crash; reports shed/expired "
                        "rates, p99-under-overload, restarts, recovery "
                        "time, hung tickets (must be 0)")
    p.add_argument("--queue-depth", type=int, default=8,
                   help="chaos: admission queue bound")
    p.add_argument("--burst-factor", type=float, default=4.0,
                   help="chaos: submit burst-factor x queue-depth "
                        "requests back-to-back")
    p.add_argument("--crash-at-batch", type=int, default=2,
                   help="chaos: inject raise@serve_dispatch at this "
                        "batch (0 = no crash, overload only)")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="chaos: per-request deadline")
    p.add_argument("--manifest-dir", default=None,
                   help="warm-start manifest dir ('' disables; default: a "
                        "fresh temp dir so cold-vs-warm is honest)")
    p.add_argument("--json-out", default=None)
    p.add_argument("--prom-out", default=None,
                   help="also write telemetry.prom here (default: next to "
                        "--json-out)")
    p.add_argument("--requests-out", default=None,
                   help="write the per-request trace ledger here "
                        "(default: requests.jsonl next to --json-out; "
                        "'' disables the ledger, keeping in-memory "
                        "tracing only)")
    p.add_argument("--no-reqtrace", action="store_true",
                   help="disable request tracing entirely — the "
                        "overhead-A/B switch (run once with, once "
                        "without, compare p50)")
    args = p.parse_args(argv)

    from gansformer_tpu.obs import install_compile_listener
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import init_generator, load_generator
    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache()
    install_compile_listener()

    if args.tiny:
        from gansformer_tpu.analysis.trace.entry_points import tiny_config

        bundle = init_generator(tiny_config("float32"), seed=args.seed)
    elif args.init == "checkpoint":
        if not args.run_dir:
            raise SystemExit("--init checkpoint needs --run-dir")
        from gansformer_tpu.utils.runarchive import resolve_run_dir

        bundle = load_generator(resolve_run_dir(args.run_dir))
    else:
        if not args.preset:
            raise SystemExit("--init random needs --preset (or --tiny)")
        from gansformer_tpu.core.config import get_preset

        bundle = init_generator(get_preset(args.preset).validate(),
                                seed=args.seed)

    if args.manifest_dir == "":
        manifest_dir = None
    elif args.manifest_dir is None:
        import tempfile

        manifest_dir = tempfile.mkdtemp(prefix="serve_manifest_")
    else:
        manifest_dir = args.manifest_dir

    # request tracing: ledger beside the JSON artifact unless pointed
    # elsewhere ('' keeps tracing but drops the file); --no-reqtrace is
    # the overhead-A/B off switch
    from gansformer_tpu.obs import reqtrace

    if args.requests_out == "":
        requests_out = None
    elif args.requests_out is None:
        requests_out = (os.path.join(
            os.path.dirname(os.path.abspath(args.json_out)),
            "requests.jsonl") if args.json_out else None)
    else:
        requests_out = args.requests_out
    reqtrace.configure_reqtrace(requests_out,
                                enabled=not args.no_reqtrace)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    if args.chaos:
        result = run_chaos(
            bundle, buckets, queue_depth=args.queue_depth,
            burst_factor=args.burst_factor,
            crash_at_batch=args.crash_at_batch,
            deadline_s=args.deadline_s, zipf_s=args.zipf_s,
            seed_universe=args.seed_universe, manifest_dir=manifest_dir,
            fill_wait_ms=args.fill_wait_ms, wcache=args.wcache,
            seed=args.seed, replicas=args.replicas,
            autoscale=args.autoscale, max_replicas=args.max_replicas,
            serve_precision=args.serve_precision)
    else:
        result = run_loadtest(
            bundle, buckets,
            requests=args.requests, rate=args.rate,
            duration_s=args.duration_s, zipf_s=args.zipf_s,
            seed_universe=args.seed_universe, manifest_dir=manifest_dir,
            fill_wait_ms=args.fill_wait_ms, wcache=args.wcache,
            seed=args.seed, serve_precision=args.serve_precision,
            replicas=args.replicas, autoscale=args.autoscale,
            max_replicas=args.max_replicas,
            quant_report=args.quant_report)

    # telemetry.prom + the schema lint's serve-family check: the SLO
    # histograms must be PRESENT and well-formed, verdict in-artifact
    prom_path = args.prom_out or (
        os.path.join(os.path.dirname(os.path.abspath(args.json_out)),
                     "telemetry.prom") if args.json_out else None)
    reqtrace.get_reqtracer().flush()
    result["reqtrace_enabled"] = not args.no_reqtrace
    if prom_path:
        from gansformer_tpu.analysis.telemetry_schema import (
            check_prom, check_requests, check_serve_metric_families)

        telemetry.get_registry().write_prom(prom_path)
        errors = check_prom(prom_path) + \
            check_serve_metric_families(prom_path,
                                        expect_overload=args.chaos)
        if requests_out and not args.no_reqtrace:
            errors += check_requests(requests_out, prom_path=prom_path)
            result["requests_out"] = requests_out
        result["prom"] = prom_path
        result["prom_ok"] = not errors
        result["prom_errors"] = errors

    blob = json.dumps(result, indent=1, sort_keys=True)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(blob + "\n")
    print(blob)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
