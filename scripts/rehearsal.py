"""Real-dataset rehearsal (VERDICT r3 item 6): drive the ENTIRE provenance
chain the first real deployment will hit —

    archive file → loopback-mirror download → TFRecord conversion
    (reference layout + labels) → conditional training (2 ticks,
    checkpoints, snapshots) → metric evaluation

— and record it in ``<run_dir>/provenance.json`` so the run dir's history
starts at an archive file, not an in-memory synthetic.

Airgapped behavior: with no real ``cifar-10-python.tar.gz`` on disk (pass
one via ``--archive`` when you have it), a structurally-real stand-in is
generated — same tar layout, same pickle schema, random pixels — and the
provenance records exactly which regime ran.  With a real archive the
registry sha256 is verified and recorded.

Usage:
    python scripts/rehearsal.py --work /tmp/rehearsal [--archive cifar.tar.gz]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import hashlib
import http.server
import json
import os
import pickle
import sys
import tarfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gansformer_tpu.core.config import (  # noqa: E402
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, TrainConfig)
from gansformer_tpu.data.download import DATASETS, sha256_file  # noqa: E402

ARCHIVE_NAME = "cifar-10-python.tar.gz"


def build_standin_archive(path: str, n_per_batch: int = 128) -> None:
    """A structurally-real cifar-10-python.tar.gz (tar layout + pickle
    schema of the real thing; random pixels)."""
    rs = np.random.RandomState(0)
    tmp = path + ".dir"
    os.makedirs(os.path.join(tmp, "cifar-10-batches-py"), exist_ok=True)
    for i in range(1, 6):
        batch = {b"data": rs.randint(0, 255, (n_per_batch, 3072), np.uint8),
                 b"labels": [int(x) for x in rs.randint(0, 10, n_per_batch)]}
        with open(os.path.join(tmp, "cifar-10-batches-py",
                               f"data_batch_{i}"), "wb") as f:
            pickle.dump(batch, f)
    with tarfile.open(path, "w:gz") as t:
        t.add(os.path.join(tmp, "cifar-10-batches-py"),
              arcname="cifar-10-batches-py")


def serve_dir(directory: str):
    handler = functools.partial(
        http.server.SimpleHTTPRequestHandler, directory=directory)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--work", required=True, help="working directory")
    ap.add_argument("--archive", default=None,
                    help="a real cifar-10-python.tar.gz (sha-verified); "
                         "default: generate the stand-in")
    ap.add_argument("--ticks", type=int, default=2)
    ap.add_argument("--metric-images", type=int, default=64)
    args = ap.parse_args(argv)

    t0 = time.time()
    work = os.path.abspath(args.work)
    mirror = os.path.join(work, "mirror")
    os.makedirs(mirror, exist_ok=True)
    prov: dict = {"chain": []}

    # 1. the archive file the chain starts at
    if args.archive:
        archive = os.path.abspath(args.archive)
        real = sha256_file(archive) == DATASETS["cifar10"].sha256
        prov["regime"] = ("real archive (registry sha256 verified)" if real
                          else "archive provided but sha256 MISMATCH — "
                               "treated as stand-in")
        import shutil

        shutil.copy(archive, os.path.join(mirror, ARCHIVE_NAME))
    else:
        build_standin_archive(os.path.join(mirror, ARCHIVE_NAME))
        prov["regime"] = ("generated stand-in (airgapped: no real CIFAR "
                          "archive on disk); same tar/pickle structure")
    archive_path = os.path.join(mirror, ARCHIVE_NAME)
    prov["chain"].append({
        "stage": "archive", "path": archive_path,
        "bytes": os.path.getsize(archive_path),
        "sha256": sha256_file(archive_path)})

    # 2-3. loopback-mirror download + TFRecord conversion (reference layout)
    srv, base = serve_dir(mirror)
    try:
        from gansformer_tpu.cli.prepare_data import main as prepare

        tfr_dir = os.path.join(work, "tfrecords")
        verify = prov["regime"].startswith("real")
        prepare(["--download", "cifar10", "--mirror-url", base,
                 "--download-dir", os.path.join(work, "downloads"),
                 *([] if verify else ["--download-no-verify"]),
                 "--to", "tfrecord", "--out", tfr_dir, "--name", "cifar10"])
    finally:
        srv.shutdown()
    prov["chain"].append({
        "stage": "download+convert", "mirror": base,
        "sha256_verified": verify,
        "tfrecords": {fn: os.path.getsize(os.path.join(tfr_dir, fn))
                      for fn in sorted(os.listdir(tfr_dir))}})

    # 4. conditional training from the TFRecords (labels flip G/D into
    # conditional mode end-to-end — train/loop.resolve_conditional)
    from gansformer_tpu.train.loop import train

    cfg = ExperimentConfig(
        name="rehearsal-cifar32",
        model=ModelConfig(resolution=32, components=4, latent_dim=32,
                          w_dim=32, mapping_dim=32, mapping_layers=2,
                          fmap_base=1024, fmap_max=64, attention="duplex",
                          attn_start_res=8, attn_max_res=16,
                          mbstd_group_size=2),
        train=TrainConfig(batch_size=8, total_kimg=args.ticks,
                          kimg_per_tick=1, snapshot_ticks=args.ticks,
                          image_snapshot_ticks=1, metric_ticks=0,
                          r1_gamma=1.0, seed=3),
        data=DataConfig(name="cifar10", path=tfr_dir, resolution=32,
                        source="tfrecord"),
        mesh=MeshConfig())
    run_dir = os.path.join(work, "run")
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "config.json"), "w") as f:
        f.write(cfg.to_json())
    state = train(cfg, run_dir)
    import jax

    # train() re-records the RESOLVED config (the labeled dataset switched
    # the model conditional, which changes the param tree) — evaluation
    # must rebuild from it, like any later generate/evaluate would.
    with open(os.path.join(run_dir, "config.json")) as f:
        cfg_resolved = ExperimentConfig.from_json(f.read())
    kimg = int(jax.device_get(state.step)) / 1000
    prov["chain"].append({
        "stage": "train",
        "run_dir": run_dir,
        "kimg": kimg,
        "conditional_label_dim": cfg_resolved.model.label_dim,
        "artifacts": sorted(fn for fn in os.listdir(run_dir)
                            if not fn.startswith("."))})

    # 5. metric evaluation of the freshly trained checkpoint
    from gansformer_tpu.metrics.sweep import run_metric_sweep
    results = run_metric_sweep(
        cfg_resolved, state, run_dir, f"fid{args.metric_images}",
        batch_size=8, num_images=args.metric_images)
    prov["chain"].append({
        "stage": "evaluate",
        "metrics": {k: float(v) for k, v in results.items()}})

    prov["wall_seconds"] = round(time.time() - t0, 1)
    with open(os.path.join(run_dir, "provenance.json"), "w") as f:
        json.dump(prov, f, indent=2)
    print(json.dumps(prov, indent=2))


if __name__ == "__main__":
    main()
