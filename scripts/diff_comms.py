"""Diff a graftcomms attribution artifact against the checked-in
collective expectation (ISSUE 7 satellite).

PR 6 *observed* that ``g_step``/``g_step_pl`` compiled to zero
collectives (replicated compute); PR 7 fixed it and promoted the
observation into expectations: ``COMMS_EXPECTED.json`` declares, per
entry point, which collective kinds a multi-device capture MUST show
(the four train steps + the fused cycle must all-reduce gradients) and
which it must NOT (the inference programs must never all-gather params
— forward compute with replicated params and a sharded batch needs no
gather).  The battery's graftcomms stage runs this diff after every
capture so a TPU window that silently regresses to replicated compute
is called out in the window ledger, not discovered at the next
re-anchor.

Exit codes: 0 — capture matches (or is INCONCLUSIVE: a 1-chip window
cannot show collectives and must not read as a regression); 1 —
mismatch; 2 — usage/IO error.

  python scripts/diff_comms.py [.comms_attribution.json]
      [--expected COMMS_EXPECTED.json] [--json-out verdict.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_ARTIFACT = os.path.join(_REPO, ".comms_attribution.json")
DEFAULT_EXPECTED = os.path.join(_REPO, "COMMS_EXPECTED.json")


def short_name(entry: str) -> str:
    tail = entry.split(".", 1)[1] if "." in entry else entry
    return tail.split("[", 1)[0]


def diff_comms(artifact: dict, expected: dict) -> dict:
    """Pure verdict builder (unit-tested in tests/test_bench_artifacts):
    ``{verdict: ok|mismatch|inconclusive, mismatches: [...], checked:
    [...], note?}``."""
    min_dev = int(expected.get("min_devices", 2))
    compiled = [int(n) for n in artifact.get("mesh_sizes_compiled") or []]
    if not compiled or max(compiled) < min_dev:
        return {"verdict": "inconclusive", "mismatches": [], "checked": [],
                "note": f"capture never compiled a >= {min_dev}-device "
                        f"mesh (compiled: {compiled}) — a device-starved "
                        f"window shows no collectives by construction; "
                        f"re-run with devices"}
    by_short = {}
    for rec in artifact.get("comms") or []:
        s = short_name(rec.get("entry", ""))
        cur = by_short.get(s)
        if cur is None or rec.get("devices", 0) > cur.get("devices", 0):
            by_short[s] = rec
    mismatches, checked = [], []
    for name, want in (expected.get("entries") or {}).items():
        rec = by_short.get(name)
        if rec is None:
            mismatches.append(f"{name}: not in the captured comms table "
                              f"(entry skipped or renamed)")
            continue
        if rec.get("devices", 0) < min_dev:
            mismatches.append(
                f"{name}: largest captured mesh is "
                f"{rec.get('devices')} device(s) (< {min_dev})")
            continue
        kinds = set(rec.get("collectives") or {})
        for k in want.get("require_kinds", ()):
            if k not in kinds:
                mismatches.append(
                    f"{name}: expected a {k} on the "
                    f"{rec['devices']}-device mesh, captured kinds: "
                    f"{sorted(kinds) or 'NONE (replicated compute)'}")
        for k in want.get("forbid_kinds", ()):
            if k in kinds:
                mismatches.append(
                    f"{name}: captured a {k} "
                    f"({rec['collectives'][k]['payload_bytes']} B) — "
                    f"forbidden for this entry (inference must not "
                    f"gather params)")
        checked.append(name)
    return {"verdict": "mismatch" if mismatches else "ok",
            "mismatches": mismatches, "checked": checked}


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("artifact", nargs="?", default=DEFAULT_ARTIFACT)
    p.add_argument("--expected", default=DEFAULT_EXPECTED)
    p.add_argument("--json-out", default=None)
    args = p.parse_args(argv)
    try:
        with open(args.artifact) as f:
            artifact = json.load(f)
        with open(args.expected) as f:
            expected = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"diff_comms: cannot read inputs: {e}", file=sys.stderr)
        return 2
    verdict = diff_comms(artifact, expected)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(verdict, f, indent=1, sort_keys=True)
            f.write("\n")
    print(f"diff_comms: {verdict['verdict']} "
          f"({len(verdict['checked'])} entries checked)")
    for m in verdict["mismatches"]:
        print(f"  MISMATCH: {m}")
    if verdict.get("note"):
        print(f"  note: {verdict['note']}")
    return 1 if verdict["verdict"] == "mismatch" else 0


if __name__ == "__main__":
    sys.exit(main())
