"""ffhq1024 on-chip readiness probe (VERDICT r5 item 5, ISSUE 5 satellite).

PERF.md §2's memory verdict (d_r1 11.0 / g_pl 16.9 GiB temp workspace at
batch 8; "batch 4 on a v5e") comes from CPU lowering — indicative layout,
never verified on the real backend.  This battery stage AOT-compiles the
REAL ``d_step_r1`` / ``g_step_pl`` programs for the ffhq1024-duplex
preset at batch 4 AND 8 on whatever backend is present, records
``memory_analysis()`` per phase, and emits a fit verdict against the
chip's HBM (from ``memory_stats()`` when the runtime exposes it, else the
public per-chip table).  On CPU the numbers are the same indicative-layout
figures PERF.md §2 used — the artifact labels which regime it is.

  python scripts/readiness_ffhq1024.py [--preset ffhq1024-duplex] \
      [--batches 4,8] [--json-out readiness.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Public per-chip HBM when the runtime doesn't say (GiB).
HBM_GIB = [("v6", 32.0), ("v5e", 16.0), ("v5 lite", 16.0),
           ("v5litepod", 16.0), ("v5p", 95.0), ("v5", 95.0),
           ("v4", 32.0), ("v3", 16.0), ("v2", 8.0)]


def hbm_limit_gib(device) -> float | None:
    try:
        stats = device.memory_stats()
        if stats and stats.get("bytes_limit"):
            return stats["bytes_limit"] / 2**30
    except Exception:
        pass
    dk = device.device_kind.lower()
    for key, val in HBM_GIB:
        if key in dk:
            return val
    return None


def fit_verdict(state_gib, temp_gib, hbm_gib):
    """Pure fit arithmetic (unit-tested): worst phase must hold the full
    TrainState plus its temp workspace (PERF.md §2's reading)."""
    if hbm_gib is None or temp_gib is None:
        return {"fits": None, "margin_gib": None}
    need = state_gib + temp_gib
    return {"fits": bool(need <= hbm_gib),
            "margin_gib": round(hbm_gib - need, 2)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="ffhq1024-duplex")
    p.add_argument("--batches", default="4,8")
    p.add_argument("--json-out", default=None)
    args = p.parse_args(argv)

    import jax

    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache(_REPO)

    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.utils.benchcheck import lower_phase

    cfg = get_preset(args.preset)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    hbm = hbm_limit_gib(dev)
    meta = {"device_kind": dev.device_kind, "platform": dev.platform,
            "preset": args.preset, "hbm_gib": hbm,
            "regime": ("device" if on_tpu
                       else "cpu-lowering (indicative layout, PERF.md §2)")}
    print(json.dumps(meta), flush=True)

    key_s = jax.ShapeDtypeStruct((2,), np.uint32)
    state_s = jax.eval_shape(lambda k: create_train_state(cfg, k), key_s)
    state_gib = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(state_s)) / 2**30

    batches = []
    for b in [int(s) for s in args.batches.split(",") if s.strip()]:
        rec = {"batch": b, "phases": {}}
        for name in ("d_r1", "g_pl"):
            try:
                # Shared lowering (benchcheck.lower_phase) — abstract
                # state + conditional-label handling in one place.
                ma = lower_phase(cfg, name, batch_size=b).memory_analysis()
                ph = {"temp_gib": round(ma.temp_size_in_bytes / 2**30, 3),
                      "argument_gib": round(
                          ma.argument_size_in_bytes / 2**30, 3),
                      "output_gib": round(
                          ma.output_size_in_bytes / 2**30, 3)}
            except Exception as e:
                ph = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            ph.update(fit_verdict(state_gib, ph.get("temp_gib"), hbm))
            rec["phases"][name] = ph
            print(json.dumps({"batch": b, "phase": name, **ph}),
                  flush=True)
        worst = [p_.get("fits") for p_ in rec["phases"].values()]
        rec["fits"] = (None if any(f is None for f in worst)
                       else bool(all(worst)))
        batches.append(rec)

    artifact = {"meta": meta, "state_gib": round(state_gib, 3),
                "batches": batches}
    if args.json_out:
        tmp = args.json_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=1)
        os.replace(tmp, args.json_out)
    print(json.dumps({"readiness_done": [r["batch"] for r in batches],
                      "fits": {r["batch"]: r["fits"] for r in batches}}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
