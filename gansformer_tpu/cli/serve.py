"""``gansformer-serve`` — stand up the AOT-compiled generation service.

Cold-start story (ISSUE 10): enable the persistent XLA compile cache,
G-only-restore the checkpoint (no discriminator, no optimizer state),
warm-start every (program, batch-bucket) executable from the serialized
manifest, and report first-image time — seconds on a warm manifest, vs
the 30–100 s per-program compiles a cold ``cli/generate.py``-style
start used to pay.

Modes:
* default      — warm start, serve ``--images`` demo requests (Zipfian
                 seed mix), write a grid + ``telemetry.prom`` to
                 ``--out``, print a JSON summary line.
* ``--warm-only`` — populate/validate the manifest and exit (the
                 deploy-time pre-bake step).
* ``--healthcheck PATH`` — grade a service's exported telemetry.prom
                 (file, or dir containing one) WITHOUT touching the
                 accelerator: prints {state, …} JSON, exit 0 unless
                 the service is unhealthy (circuit breaker tripped /
                 dispatcher dead with work queued) — the probe a
                 liveness check or babysitter scripts against the
                 robustness floor (ISSUE 13).

The demo service runs under the full robustness floor: bounded
admission (``--queue-depth``), optional per-request deadlines
(``--deadline-s``), supervised dispatcher restart, and a SIGTERM →
graceful-drain hook (``--grace-s`` window).

No network listener here deliberately: the service core is a Python
API (``serve.GenerationService``); the transport in front of it is a
deployment choice.  ``scripts/loadtest_serve.py`` is the load driver.
"""

from __future__ import annotations

import argparse
import json
import os
import time

def healthcheck(path: str, max_age_s=None) -> int:
    """Grade an exported ``telemetry.prom``: 0 = ready/degraded (and no
    dead-dispatcher-with-work signal), 1 = unhealthy/unreadable — or
    STALE when ``max_age_s`` is given and the snapshot file is older
    (a frozen last-good export must not pass a liveness probe
    forever).  Never imports jax — safe to script from probes on the
    serving host."""
    from gansformer_tpu.analysis.telemetry_schema import (
        SERVE_HEALTH_NAMES, serve_fleet_alive, serve_fleet_dead_with_work,
        serve_replica_ordinals)
    from gansformer_tpu.obs.registry import parse_prom_values

    if os.path.isdir(path):
        path = os.path.join(path, "telemetry.prom")
    if not os.path.exists(path):
        print(json.dumps({"state": "unknown", "ok": False,
                          "error": f"{path}: missing"}))
        return 1
    vals = parse_prom_values(path)
    code = vals.get("serve_health_state")
    if code is None:
        print(json.dumps({"state": "unknown", "ok": False, "prom": path,
                          "error": "no serve_health_state gauge — not a "
                                   "serving telemetry.prom"}))
        return 1
    snapshot_age = time.time() - os.path.getmtime(path)
    # Fleet-aware liveness (ISSUE 20): any-replica-alive — a replica
    # prom grades on its member families (one dead member with queued
    # work is quarantine's problem while any dispatcher runs; dead-
    # with-work means ALL dispatchers dead with SOME queue non-empty).
    # Single-service proms take the exact pre-fleet global-gauge path.
    ords = serve_replica_ordinals(vals)
    alive = serve_fleet_alive(vals)
    dead_with_work = serve_fleet_dead_with_work(vals)
    depth = vals.get("serve_queue_depth_now", 0.0)
    state = SERVE_HEALTH_NAMES.get(int(code), "unknown")
    stale = max_age_s is not None and snapshot_age > max_age_s
    if stale:
        state = "stale"
    out = {"state": state, "prom": path,
           "snapshot_age_s": round(snapshot_age, 1), "ok":
           state in ("ready", "degraded", "closed")
           and not dead_with_work,
           "dispatcher_alive": 1.0 if alive else 0.0,
           "queue_depth": depth,
           "queue_bound": vals.get("serve_queue_bound"),
           "dispatcher_restarts":
               vals.get("serve_dispatcher_restarts_total"),
           "shed_total": vals.get("serve_shed_total"),
           "expired_total": vals.get("serve_expired_total"),
           "cancelled_total": vals.get("serve_cancelled_total")}
    if ords:
        out["replicas"] = vals.get("serve_replicas")
        out["replicas_alive"] = sum(
            1 for i in ords
            if vals.get(f"serve_replica{i}_dispatcher_alive", 0.0) > 0)
        out["scale_out_total"] = vals.get("serve_scale_out_total")
        out["scale_in_total"] = vals.get("serve_scale_in_total")
    print(json.dumps(out, sort_keys=True))
    return 0 if out["ok"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="AOT-compiled generation service (warm-startable)")
    p.add_argument("--run-dir", default=None,
                   help="run dir / packed archive / URL with checkpoints "
                        "+ config.json (G/EMA leaves only are loaded)")
    p.add_argument("--preset", default=None,
                   help="with --init random: serve a randomly-initialized "
                        "G of this preset (perf/load testing without a "
                        "checkpoint)")
    p.add_argument("--init", default="checkpoint",
                   choices=("checkpoint", "random"))
    p.add_argument("--buckets", default="1,4,8",
                   help="comma list of padded batch buckets to compile")
    p.add_argument("--psi", type=float, default=0.7)
    p.add_argument("--images", type=int, default=8,
                   help="demo requests to serve (0 = none)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="output dir (default: run dir /served or ./served)")
    p.add_argument("--manifest-dir", default=None,
                   help="warm-start manifest location (default: "
                        ".jax_compile_cache/serve/)")
    p.add_argument("--no-warm-start", action="store_true",
                   help="skip the serialized-executable manifest (always "
                        "compile; the XLA disk cache still applies)")
    p.add_argument("--warm-only", action="store_true",
                   help="populate/validate the manifest and exit")
    p.add_argument("--replicas", type=int, default=1,
                   help="serving replicas, one per local device "
                        "(replica-per-chip placement; >1 routes through "
                        "serve.ReplicaSet)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscaler ceiling (default: local device "
                        "count)")
    p.add_argument("--autoscale", action="store_true",
                   help="scale replicas out on sustained queue "
                        "saturation, in on batch-fill collapse")
    p.add_argument("--serve-precision", default="f32",
                   choices=("f32", "bf16", "int8w"),
                   help="synthesis precision: f32 reference, bf16 "
                        "activations, or int8 weight-only quantization "
                        "(mapping + w-cache always f32)")
    p.add_argument("--wcache", type=int, default=4096,
                   help="w-cache capacity (entries)")
    p.add_argument("--queue-depth", type=int, default=256,
                   help="admission queue bound (over-depth submits shed "
                        "with a typed Overloaded)")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request deadline; expired requests drop "
                        "before dispatch")
    p.add_argument("--grace-s", type=float, default=30.0,
                   help="drain grace window for close()/SIGTERM")
    p.add_argument("--healthcheck", default=None, metavar="PROM",
                   help="grade a service telemetry.prom (file or dir) "
                        "and exit — no accelerator touched")
    p.add_argument("--health-max-age", type=float, default=None,
                   help="with --healthcheck: fail when the prom "
                        "snapshot is older than this many seconds "
                        "(liveness probes; default: age reported, not "
                        "judged — archived artifacts stay gradeable)")
    args = p.parse_args(argv)

    if args.healthcheck:
        return healthcheck(args.healthcheck,
                           max_age_s=args.health_max_age)

    import jax
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.obs import install_compile_listener
    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import (
        GenerationService, ReplicaSet, ServePrograms,
        default_manifest_dir, init_generator, load_generator)
    from gansformer_tpu.utils.hostenv import enable_compile_cache
    from gansformer_tpu.utils.image import save_image_grid
    from gansformer_tpu.utils.runarchive import resolve_run_dir

    enable_compile_cache()
    install_compile_listener()
    buckets = tuple(int(b) for b in args.buckets.split(","))

    t_load0 = time.perf_counter()
    if args.init == "random":
        if not args.preset:
            raise SystemExit("--init random needs --preset")
        bundle = init_generator(get_preset(args.preset).validate(),
                                seed=args.seed)
        out_dir = args.out or "served"
    else:
        if not args.run_dir:
            raise SystemExit("--init checkpoint needs --run-dir")
        run_dir = resolve_run_dir(args.run_dir)
        bundle = load_generator(run_dir)
        out_dir = args.out or os.path.join(run_dir, "served")
    load_ms = (time.perf_counter() - t_load0) * 1000.0

    manifest_dir = None if args.no_warm_start else (
        args.manifest_dir or default_manifest_dir())
    # Fleet mode (ISSUE 20): >1 replica or the autoscaler routes through
    # ReplicaSet (replica-per-device placement + least-loaded routing).
    # The single-replica default keeps the exact pre-fleet path.
    fleet = args.replicas > 1 or args.autoscale
    rs = None
    if fleet:
        rs = ReplicaSet(
            bundle, buckets=buckets, manifest_dir=manifest_dir,
            serve_precision=args.serve_precision,
            replicas=args.replicas, max_replicas=args.max_replicas,
            autoscale=args.autoscale,
            service_kwargs=dict(
                wcache_capacity=args.wcache,
                max_queue_depth=max(args.queue_depth, args.images + 1),
                default_deadline_s=args.deadline_s))
        warm = rs.warm_start()
    else:
        programs = ServePrograms(bundle, buckets=buckets,
                                 manifest_dir=manifest_dir,
                                 serve_precision=args.serve_precision)
        warm = programs.warm_start()

    summary = {
        "buckets": list(buckets),
        "serve_precision": args.serve_precision,
        "replicas": rs.n_active if fleet else 1,
        "autoscale": bool(args.autoscale),
        "restore_ms": round(load_ms, 1),
        "warm_start": {"loaded": warm["loaded"],
                       "compiled": warm["compiled"],
                       "seconds": round(warm["seconds"], 3)},
        "manifest_dir": manifest_dir,
        "device": {"platform": jax.devices()[0].platform,
                   "kind": jax.devices()[0].device_kind,
                   "count": len(jax.devices())},
    }

    if not args.warm_only and args.images > 0:
        if bundle.cfg.model.label_dim:
            # the demo loop has no label source; crashing the
            # dispatcher on the first unlabeled request would surface
            # as an opaque "generation request failed" instead
            raise SystemExit(
                f"model has label_dim={bundle.cfg.model.label_dim}: the "
                f"demo traffic can't supply labels — use --warm-only to "
                f"pre-bake the manifest, and drive conditional requests "
                f"through serve.GenerationService.submit(label=...)")
        os.makedirs(out_dir, exist_ok=True)
        rng = np.random.RandomState(args.seed)
        # Zipfian demo mix: a few hot seeds + a long tail, so the demo
        # exercises the w-cache the way real traffic would
        universe = np.arange(1, 64)
        pz = 1.0 / universe ** 1.1
        seeds = rng.choice(universe, size=args.images, p=pz / pz.sum())
        # the demo submits its whole request list unpaced, so the
        # bound must sit above it — shedding the demo's own burst
        # would be admission control arguing with the argument parser
        svc = rs if fleet else GenerationService(
            programs, wcache_capacity=args.wcache,
            max_queue_depth=max(args.queue_depth, args.images + 1),
            default_deadline_s=args.deadline_s)
        svc.install_signal_drain(grace_s=args.grace_s)
        try:
            t0 = time.perf_counter()
            first = svc.submit(int(seeds[0]), psi=args.psi)
            first.result(timeout=600)
            summary["first_image_ms"] = round(
                (time.perf_counter() - t0) * 1000.0, 1)
            tickets = [svc.submit(int(s), psi=args.psi)
                       for s in seeds[1:]]
            imgs = [first.result()] + [t.result(timeout=600)
                                       for t in tickets]
            summary["health"] = svc.health()
        finally:
            svc.close(timeout=args.grace_s)
        save_image_grid(np.stack(imgs),
                        os.path.join(out_dir, "served_grid.png"))
        snap = telemetry.get_registry().snapshot()
        summary["counters"] = {
            k.replace("serve/", ""): v
            for k, v in snap["counters"].items() if k.startswith("serve/")}
        lat = snap["histograms"].get("serve/e2e_ms", {})
        summary["e2e_ms"] = {k: lat.get(k) for k in
                             ("count", "mean", "min", "max")}
        telemetry.get_registry().write_prom(
            os.path.join(out_dir, "telemetry.prom"))
        summary["out"] = out_dir
    elif rs is not None:
        # fleet built for warm-only pre-bake (per-ordinal manifests):
        # drain it cleanly before exiting
        rs.close(timeout=args.grace_s)

    print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
