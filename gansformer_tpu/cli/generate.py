"""Generate CLI — reference ``src/generate.py`` (SURVEY.md §3.5): load a
snapshot, sample images with truncation ψ, write PNG grids.

Since ISSUE 10 this rides the serving path: a G-only partial restore
(``serve.load_generator`` — the discriminator and both optimizer states
are never initialized or read) and the split AOT programs
(``serve.ServePrograms``: ``map_z`` + ψ-vectorized ``synthesize``,
warm-started from the serialized-executable manifest).  A second
invocation therefore compiles nothing — it deserializes.
"""

from __future__ import annotations

import argparse
import os

import jax
import numpy as np


def _pad_rows(a: np.ndarray, bucket: int) -> np.ndarray:
    """Pad a [n, ...] host batch to the compiled bucket by repeating the
    last row (rows are independent, so the prefix stays bit-identical —
    the padding-parity contract in tests/test_serve.py)."""
    if a.shape[0] == bucket:
        return a
    pad = np.broadcast_to(a[-1:], (bucket - a.shape[0],) + a.shape[1:])
    return np.concatenate([a, pad])


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Sample images from a checkpoint")
    p.add_argument("--run-dir", required=True,
                   help="run dir containing checkpoints/ + config.json, a "
                        "packed run archive (.tar.gz from pack_run), or an "
                        "http(s) URL of one (the reference's pretrained-"
                        "model loading surface)")
    p.add_argument("--out", default=None, help="output dir (default run dir)")
    p.add_argument("--images-num", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--truncation-psi", type=float, default=0.7)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--grid", action="store_true", help="one grid PNG instead of singles")
    p.add_argument("--attention-backend", default=None,
                   choices=("xla", "pallas"),
                   help="override the attention compute backend for this "
                        "forward-only run ('pallas' = fused blockwise "
                        "kernels; incompatible with --save-attention)")
    p.add_argument("--conv-backend", default=None,
                   choices=("xla", "pallas"),
                   help="override the modulated-conv/upfirdn compute "
                        "backend for this forward-only run ('pallas' = "
                        "the fused modconv/upfirdn kernel family, "
                        "ISSUE 14; incompatible with --save-attention — "
                        "the overlay re-run drives the module under the "
                        "stock XLA lowering)")
    p.add_argument("--save-attention", action="store_true",
                   help="also save latent→region attention overlays "
                        "(attn.png; needs an attention model)")
    p.add_argument("--interpolate", type=int, nargs=2, default=None,
                   metavar=("ROWS", "STEPS"),
                   help="also save latent-interpolation strips: ROWS pairs "
                        "of endpoints, STEPS z-lerp columns (interp.png)")
    p.add_argument("--style-mix", type=int, nargs=2, default=None,
                   metavar=("ROWS", "COLS"),
                   help="also save the component-mixing grid: row sources "
                        "keep the leading latent components, column sources "
                        "supply the suffix (mix.png)")
    p.add_argument("--no-warm-start", action="store_true",
                   help="skip the serialized-executable manifest")
    args = p.parse_args(argv)

    import dataclasses

    from gansformer_tpu.obs import registry as telemetry
    from gansformer_tpu.serve import (
        ServePrograms, default_manifest_dir, load_generator)
    from gansformer_tpu.utils.hostenv import enable_compile_cache
    from gansformer_tpu.utils.image import save_image_grid, to_uint8
    from gansformer_tpu.utils.runarchive import resolve_run_dir

    args.run_dir = resolve_run_dir(args.run_dir)
    enable_compile_cache()

    # G-only restore: ema_params + w_avg against an ABSTRACT template —
    # no discriminator init, no optimizer leaves read (ISSUE 10).
    bundle = load_generator(args.run_dir)
    cfg = bundle.cfg
    if args.attention_backend:
        from gansformer_tpu.ops.pallas_attention import resolve_backend

        if args.save_attention and args.attention_backend != "xla":
            raise SystemExit(
                "--save-attention needs the xla backend (pallas sows no maps)")
        # On TPU: native smoke-compile of the kernels first; fall back to
        # xla with the printed reason if Mosaic lowering fails (ADVICE r3).
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model,
            attention_backend=resolve_backend(args.attention_backend)))
        bundle = dataclasses.replace(bundle, cfg=cfg)
    if args.conv_backend:
        from gansformer_tpu.ops.pallas_modconv import resolve_conv_backend

        if args.save_attention and args.conv_backend != "xla":
            # The overlay path re-runs the module with sown
            # intermediates — an introspection path that assumes the
            # stock XLA lowering end to end; reject rather than mix
            # kernel backends under a debugging run (core/config.py's
            # conv_backend validation rationale).
            raise SystemExit(
                "--save-attention needs the xla conv backend (the "
                "attention-overlay re-run assumes the stock XLA "
                "lowering); drop --conv-backend pallas")
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model,
            conv_backend=resolve_conv_backend(args.conv_backend)))
        bundle = dataclasses.replace(bundle, cfg=cfg)

    programs = ServePrograms(
        bundle, buckets=(args.batch_size,),
        manifest_dir=None if args.no_warm_start else default_manifest_dir())
    restore_ms = telemetry.gauge("serve/restore_ms").value
    print(f"G-only restore: {restore_ms:.0f} ms "
          f"(no discriminator/optimizer init)")

    def sample_batch(z: np.ndarray, noise_key, label=None) -> np.ndarray:
        """z [n ≤ batch-size, num_ws, latent] → images [n, R, R, C]
        through the split programs, bucket-padded."""
        n = z.shape[0]
        z = _pad_rows(np.asarray(z, np.float32), args.batch_size)
        label = (None if label is None
                 else _pad_rows(np.asarray(label, np.float32),
                                args.batch_size))
        ws = programs.map_z(z, label)
        psi = np.full((args.batch_size,), args.truncation_psi, np.float32)
        imgs = programs.synthesize(ws, psi, np.asarray(noise_key))
        return np.asarray(jax.device_get(imgs))[:n]

    dataset = None
    if cfg.model.label_dim:
        # Conditional model: draw labels from the training distribution.
        from gansformer_tpu.data.dataset import make_dataset

        dataset = make_dataset(cfg.data)

    out_dir = args.out or os.path.join(args.run_dir, "generated")
    os.makedirs(out_dir, exist_ok=True)
    rng = jax.random.PRNGKey(args.seed)
    all_imgs = []
    for i in range(0, args.images_num, args.batch_size):
        n = min(args.batch_size, args.images_num - i)
        z = jax.random.normal(jax.random.fold_in(rng, i),
                              (n, cfg.model.num_ws, cfg.model.latent_dim))
        label = (dataset.random_labels(n, seed=args.seed + i)
                 if dataset is not None else None)
        all_imgs.append(sample_batch(np.asarray(z),
                                     jax.random.fold_in(rng, i + 1), label))
    imgs = np.concatenate(all_imgs)

    if args.save_attention:
        # Re-run one batch collecting the sown attention maps (SURVEY.md
        # §2.3 — the paper's latent→region visualizations).  Needs
        # mutable-intermediates capture, so it drives the module
        # directly rather than the AOT programs.
        from gansformer_tpu.models.generator import Generator
        from gansformer_tpu.train.steps import apply_truncation
        from gansformer_tpu.utils.image import save_attention_grid

        if cfg.model.attention == "none":
            raise SystemExit("--save-attention needs an attention model")
        G = Generator(cfg.model)
        n = min(args.batch_size, args.images_num)
        z = jax.random.normal(jax.random.fold_in(rng, 0),
                              (n, cfg.model.num_ws, cfg.model.latent_dim))
        label = (dataset.random_labels(n, seed=args.seed)
                 if dataset is not None else None)
        ws = G.apply({"params": bundle.ema_params}, z, label,
                     method=Generator.map)
        ws = apply_truncation(ws, bundle.w_avg, args.truncation_psi)
        att_imgs, aux = G.apply(
            {"params": bundle.ema_params}, ws,
            rngs={"noise": jax.random.fold_in(rng, 1)},
            method=Generator.synthesize, mutable=["intermediates"])
        attn = aux["intermediates"]["synthesis"]
        # highest attention resolution = finest region map
        res = max(int(name[1:].split("_")[0]) for name in attn)
        probs = np.asarray(attn[f"b{res}_attn"]["attn_probs"][0])
        probs = probs.mean(axis=1)            # average heads → [N,h,w,k]
        save_attention_grid(np.asarray(jax.device_get(att_imgs)), probs,
                            os.path.join(out_dir, "attn.png"))
        print(os.path.join(out_dir, "attn.png"))

    if args.interpolate:
        # Latent interpolation strips (the replication paper's smoothness
        # figure): each row lerps z between two endpoints; columns are the
        # interpolation steps.  Done in z-space, mapped per step — the
        # convention of the lineage's interpolation videos.
        rows, steps = args.interpolate
        za = np.asarray(jax.random.normal(
            jax.random.fold_in(rng, 101),
            (rows, cfg.model.num_ws, cfg.model.latent_dim)))
        zb = np.asarray(jax.random.normal(
            jax.random.fold_in(rng, 202),
            (rows, cfg.model.num_ws, cfg.model.latent_dim)))
        label = (dataset.random_labels(rows, seed=args.seed + 7)
                 if dataset is not None else None)
        strip = []
        rows_eff = min(rows, args.batch_size)   # one sample call per step,
        if rows_eff != rows:                    # capped by --batch-size
            raise SystemExit(f"--interpolate ROWS ({rows}) must be "
                             f"<= --batch-size ({args.batch_size})")
        # same key on purpose: interpolation frames share their synthesis
        # noise (the lineage's video convention — only the latent moves)
        key303 = jax.random.fold_in(rng, 303)
        for s in range(steps):
            t = s / max(steps - 1, 1)
            zt = (1.0 - t) * za + t * zb
            strip.append(sample_batch(zt, key303, label))  # graftlint: disable=rng-key-reuse — frames share noise by design
        # [steps, rows, H, W, C] → row-major grid: rows × steps
        inter = np.stack(strip, axis=1).reshape(rows * steps,
                                                *strip[0].shape[1:])
        save_image_grid(inter, os.path.join(out_dir, "interp.png"),
                        grid=(steps, rows))
        print(os.path.join(out_dir, "interp.png"))

    if args.style_mix:
        # Component-mixing grid (the mixing figure of the lineage, in this
        # framework's per-component semantics — SURVEY.md §7.4): cell (r,c)
        # keeps row-source r's leading latent components and takes the
        # suffix (and the global component, if present) from column-source
        # c.  Mapping runs once per source; mixing happens in w-space —
        # exactly the traffic shape the serving split exists for (the
        # mixed cells never touch the mapping network).
        from gansformer_tpu.train.steps import apply_truncation

        rows, cols = args.style_mix

        def map_ws(key, n, label_seed):
            z = np.asarray(jax.random.normal(
                key, (n, cfg.model.num_ws, cfg.model.latent_dim)))
            label = (dataset.random_labels(n, seed=label_seed)
                     if dataset is not None else None)
            label = (None if label is None
                     else _pad_rows(np.asarray(label, np.float32),
                                    args.batch_size))
            ws = programs.map_z(_pad_rows(z, args.batch_size), label)
            ws = apply_truncation(ws, bundle.w_avg, args.truncation_psi)
            return np.asarray(jax.device_get(ws))[:n]

        ws_a = map_ws(jax.random.fold_in(rng, 404), rows, args.seed + 11)
        ws_b = map_ws(jax.random.fold_in(rng, 505), cols, args.seed + 12)
        cross = max(1, cfg.model.components // 2)
        # [rows, cols, num_ws, w] — leading components from A, rest from B
        mix = np.broadcast_to(
            ws_b[None, :], (rows, cols) + ws_b.shape[1:]).copy()
        mix[:, :, :cross] = ws_a[:, None, :cross]
        flat = mix.reshape((-1,) + mix.shape[2:])
        mixed = []
        key606 = np.asarray(jax.random.fold_in(rng, 606))
        psi_one = np.ones((args.batch_size,), np.float32)  # already truncated
        for i in range(0, len(flat), args.batch_size):   # respect --batch-size
            chunk = flat[i:i + args.batch_size]
            n = chunk.shape[0]
            out = programs.synthesize(_pad_rows(chunk, args.batch_size),
                                      psi_one, key606)
            mixed.append(np.asarray(jax.device_get(out))[:n])
        save_image_grid(np.concatenate(mixed),
                        os.path.join(out_dir, "mix.png"), grid=(cols, rows))
        print(os.path.join(out_dir, "mix.png"))

    if args.grid:
        save_image_grid(imgs, os.path.join(out_dir, "grid.png"))
        print(os.path.join(out_dir, "grid.png"))
    else:
        from PIL import Image

        for i, im in enumerate(to_uint8(imgs)):
            Image.fromarray(im).save(os.path.join(out_dir, f"img{i:04d}.png"))
        print(f"{len(imgs)} images → {out_dir}")


if __name__ == "__main__":
    main()
