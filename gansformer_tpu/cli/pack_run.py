"""Pack a run dir into a distributable archive — the publishing half of the
reference's pretrained-model story (SURVEY.md §2.2: ``pretrained_networks``
consumes snapshot pickles from URLs; ``pack_run`` produces the equivalent
single-file artifact, which ``generate``/``evaluate --run-dir <url|tar>``
consume).

  python -m gansformer_tpu.cli.pack_run --run-dir results/00003-ffhq \\
      [--step 25000] [--out ffhq-duplex.tar.gz]
"""

from __future__ import annotations

import argparse


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Pack a run dir for distribution")
    p.add_argument("--run-dir", required=True)
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--out", default=None,
                   help="output .tar.gz (default: <run>-step<N>.tar.gz)")
    args = p.parse_args(argv)

    from gansformer_tpu.utils.runarchive import pack_run

    out = pack_run(args.run_dir, out_path=args.out, step=args.step)
    print(out)


if __name__ == "__main__":
    main()
