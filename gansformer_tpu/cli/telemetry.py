"""Telemetry CLI — inspect a run dir's observability artifacts.

Three subcommands over the files the train loop writes
(docs/observability.md):

  trace       events.jsonl → Chrome-trace JSON (open in chrome://tracing
              or https://ui.perfetto.dev)
  heartbeats  staleness probe over heartbeat-p*.json; exit 1 when any
              peer is stale/missing (babysitter-scriptable)
  summary     per-phase totals aggregated from events.jsonl + the
              current telemetry.prom

Examples
--------
  python -m gansformer_tpu.cli.telemetry trace results/00003-run
  python -m gansformer_tpu.cli.telemetry heartbeats results/00003-run \\
      --max-age 120 --expected 4
  python -m gansformer_tpu.cli.telemetry summary results/00003-run
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def read_events(run_dir: str) -> List[dict]:
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        raise SystemExit(f"no events.jsonl under {run_dir} — was the run "
                         f"started with this framework's train loop?")
    out: List[dict] = []
    dropped = 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                # a SIGKILL mid-append leaves a torn line; the crashed
                # runs are exactly the ones worth inspecting, so skip it
                dropped += 1
    if dropped:
        print(f"warning: skipped {dropped} torn line(s) in {path}",
              file=sys.stderr)
    return out


def write_chrome_trace(run_dir: str, out: Optional[str] = None) -> str:
    """events.jsonl lines ARE Chrome trace events; the conversion is just
    the enclosing ``{"traceEvents": [...]}`` object."""
    events = read_events(run_dir)
    out = out or os.path.join(run_dir, "trace.json")
    with open(out, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return out


def summarize_events(events: List[dict]) -> List[dict]:
    """Per-phase {name, count, total_ms, mean_ms}, heaviest first."""
    agg: dict = {}
    for ev in events:
        a = agg.setdefault(ev["name"], {"count": 0, "total_ms": 0.0})
        a["count"] += 1
        a["total_ms"] += ev.get("dur", 0.0) / 1000.0
    return sorted(
        ({"name": n, "count": a["count"],
          "total_ms": round(a["total_ms"], 3),
          "mean_ms": round(a["total_ms"] / a["count"], 3)}
         for n, a in agg.items()),
        key=lambda r: -r["total_ms"])


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("trace", help="events.jsonl → Chrome trace JSON")
    t.add_argument("run_dir")
    t.add_argument("--out", default=None,
                   help="output path (default <run_dir>/trace.json)")

    h = sub.add_parser("heartbeats", help="multi-host staleness probe")
    h.add_argument("run_dir")
    h.add_argument("--max-age", type=float, default=300.0,
                   help="seconds before a heartbeat counts as stale")
    h.add_argument("--expected", type=int, default=None,
                   help="expected process count (detects missing peers)")

    s = sub.add_parser("summary", help="phase totals + current telemetry")
    s.add_argument("run_dir")

    args = p.parse_args(argv)

    if args.cmd == "trace":
        out = write_chrome_trace(args.run_dir, args.out)
        print(f"wrote {out} — open in chrome://tracing or "
              f"https://ui.perfetto.dev")
    elif args.cmd == "heartbeats":
        from gansformer_tpu.obs.heartbeat import check_heartbeats

        expected = (list(range(args.expected))
                    if args.expected is not None else None)
        result = check_heartbeats(args.run_dir, max_age_s=args.max_age,
                                  expected=expected)
        print(json.dumps(result))
        if not result["ok"]:
            sys.exit(1)
    elif args.cmd == "summary":
        for row in summarize_events(read_events(args.run_dir)):
            print("{name:<28s} n={count:<6d} total {total_ms:>10.1f} ms  "
                  "mean {mean_ms:>8.2f} ms".format(**row))
        prom = os.path.join(args.run_dir, "telemetry.prom")
        if os.path.exists(prom):
            print("\n-- telemetry.prom --")
            sys.stdout.write(open(prom).read())


if __name__ == "__main__":
    main()
