"""Telemetry CLI — inspect a run dir's observability artifacts.

Subcommands over the files the train loop and the serving floor write
(docs/observability.md):

  trace       events.jsonl → Chrome-trace JSON (open in chrome://tracing
              or https://ui.perfetto.dev)
  heartbeats  staleness + step-skew probe over heartbeat-p*.json; exit 1
              when any peer is stale/missing/straggling
              (babysitter-scriptable)
  summary     per-phase totals aggregated from events.jsonl + the
              current telemetry.prom
  requests    the request ledger (ISSUE 16): per-outcome summary, p99
              exemplar resolution (the ``# EXEMPLAR`` line in
              telemetry.prom names the request whose timeline explains
              the worst latency), ``--id <rid>`` for one request's full
              timeline, ``--worst N`` for the N slowest
  slo         error budgets over declared objectives (p99 latency,
              availability, shed rate): compliance, budget spend, burn
              rate per objective; exit 1 when any budget is exhausted
  fleet       aggregate N processes' telemetry into fleet.json /
              fleet.prom (counters sum, gauges spread, histograms
              merge; partial-view marker on degraded inputs)
  doctor      one run-health report cross-checking ALL of it (ISSUE 8):
              device-time vs wall-clock MFU, wall-vs-device divergence,
              data-wait fraction, queue depths, retraces, HBM headroom,
              heartbeat staleness + per-process step skew, restart
              count, — when a supervisor ledger exists — the
              availability section (ISSUE 12: exit causes, restart
              storms, uptime ratio, give-up verdicts), — when serve/*
              telemetry or a serve_chaos.json artifact exists — the
              serving section (ISSUE 13: circuit breaker, dead
              dispatcher, shed rate, queue saturation, hung chaos
              tickets), and — when served traffic is visible — the slo
              section (ISSUE 16: FAIL on an exhausted error budget,
              informational under a chaos drill).  PASS/WARN/FAIL
              lines; --json for the machine-readable form; exit 0 iff
              no FAIL.

Examples
--------
  python -m gansformer_tpu.cli.telemetry trace results/00003-run
  python -m gansformer_tpu.cli.telemetry heartbeats results/00003-run \\
      --max-age 120 --expected 4
  python -m gansformer_tpu.cli.telemetry summary results/00003-run
  python -m gansformer_tpu.cli.telemetry requests results/serve --worst 3
  python -m gansformer_tpu.cli.telemetry slo results/serve --window 900
  python -m gansformer_tpu.cli.telemetry fleet results/00003-run \\
      --expected 4 --out-dir results/00003-run
  python -m gansformer_tpu.cli.telemetry doctor results/00003-run
  python -m gansformer_tpu.cli.telemetry doctor results \\
      --json-out doctor.json          # picks the latest numbered run
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def read_events(run_dir: str) -> List[dict]:
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        raise SystemExit(f"no events.jsonl under {run_dir} — was the run "
                         f"started with this framework's train loop?")
    out: List[dict] = []
    dropped = 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                # a SIGKILL mid-append leaves a torn line; the crashed
                # runs are exactly the ones worth inspecting, so skip it
                dropped += 1
    if dropped:
        print(f"warning: skipped {dropped} torn line(s) in {path}",
              file=sys.stderr)
    return out


def write_chrome_trace(run_dir: str, out: Optional[str] = None) -> str:
    """events.jsonl lines ARE Chrome trace events; the conversion is just
    the enclosing ``{"traceEvents": [...]}`` object."""
    events = read_events(run_dir)
    out = out or os.path.join(run_dir, "trace.json")
    with open(out, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return out


def summarize_events(events: List[dict]) -> List[dict]:
    """Per-phase {name, count, total_ms, mean_ms}, heaviest first."""
    agg: dict = {}
    for ev in events:
        a = agg.setdefault(ev["name"], {"count": 0, "total_ms": 0.0})
        a["count"] += 1
        a["total_ms"] += ev.get("dur", 0.0) / 1000.0
    return sorted(
        ({"name": n, "count": a["count"],
          "total_ms": round(a["total_ms"], 3),
          "mean_ms": round(a["total_ms"] / a["count"], 3)}
         for n, a in agg.items()),
        key=lambda r: -r["total_ms"])


# --- requests (ISSUE 16 tentpole a) -----------------------------------------


def run_requests(run_dir: str, rid: Optional[str] = None,
                 worst: Optional[int] = None) -> int:
    """The ``requests`` subcommand body (returns the exit code).

    Jax-free by construction: everything here reads artifacts through
    ``obs.reqtrace.read_requests`` / ``obs.registry`` parsers — the CLI
    runs on a laptop against an rsync'd run dir."""
    from gansformer_tpu.obs.registry import parse_prom_exemplars
    from gansformer_tpu.obs.reqtrace import read_requests, render_timeline

    path = os.path.join(run_dir, "requests.jsonl")
    rows = read_requests(path)
    if not rows:
        print(f"no request ledger rows under {run_dir} — was the "
              f"service started with a requests.jsonl ledger "
              f"(configure_reqtrace)?", file=sys.stderr)
        return 1
    if rid is not None:
        hits = [r for r in rows if r.get("rid") == rid]
        if not hits:
            print(f"request {rid!r} not in {path} ({len(rows)} rows) — "
                  f"evicted by the ledger bound, or a different run?",
                  file=sys.stderr)
            return 1
        for row in hits:
            print(render_timeline(row))
        return 0
    if worst is not None:
        ranked = sorted(rows, key=lambda r: -(r.get("e2e_ms") or 0.0))
        for row in ranked[:worst]:
            print(render_timeline(row))
            print()
        return 0
    # default: per-outcome summary + p99 exemplar resolution
    by_outcome: Dict[str, int] = {}
    for r in rows:
        by_outcome[r.get("outcome", "?")] = \
            by_outcome.get(r.get("outcome", "?"), 0) + 1
    done = sorted(float(r.get("e2e_ms") or 0.0) for r in rows
                  if r.get("outcome") == "fulfilled")
    print(f"{len(rows)} request(s): " + ", ".join(
        f"{k}={v}" for k, v in sorted(by_outcome.items())))
    if done:
        p50 = done[len(done) // 2]
        p99 = done[min(int(len(done) * 0.99), len(done) - 1)]
        print(f"fulfilled e2e: p50 {p50:.1f} ms, p99 {p99:.1f} ms, "
              f"max {done[-1]:.1f} ms")
    prom = os.path.join(run_dir, "telemetry.prom")
    if os.path.exists(prom):
        ex = parse_prom_exemplars(prom).get("serve_e2e_ms_max")
        if ex:
            hits = [r for r in rows if r.get("rid") == ex]
            print(f"\np99 exemplar {ex} (serve_e2e_ms_max):")
            if hits:
                print(render_timeline(hits[0]))
            else:
                print(f"  not in the ledger (evicted by the row bound "
                      f"or traced before the ledger was wired)")
    return 0


# --- doctor (ISSUE 8 tentpole c) --------------------------------------------


def read_stats_records(run_dir: str) -> List[dict]:
    """stats.jsonl tick records, torn-line-tolerant (same rationale as
    read_events: crashed runs are the interesting ones)."""
    path = os.path.join(run_dir, "stats.jsonl")
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def read_prom_values(run_dir: str) -> Dict[str, float]:
    """A run dir's telemetry.prom → {prom name: value}, empty when the
    file is absent (the parser itself lives with the format's writer,
    ``obs/registry.parse_prom_values``)."""
    path = os.path.join(run_dir, "telemetry.prom")
    if not os.path.exists(path):
        return {}
    from gansformer_tpu.obs.registry import parse_prom_values

    return parse_prom_values(path)


def resolve_run_dir(path: str) -> str:
    """Accept either a run dir or a results root: when ``path`` has no
    telemetry artifacts but contains numbered run dirs, descend to the
    latest one (the battery points the doctor at ``{win}/train_tpu``)."""
    if os.path.exists(os.path.join(path, "stats.jsonl")) or \
            os.path.exists(os.path.join(path, "telemetry.prom")):
        return path
    from gansformer_tpu.utils.logging import list_run_dirs

    runs = list_run_dirs(path)
    return runs[-1] if runs else path


class _Tele:
    """Unified accessor over the LAST tick's registry snapshot (from
    stats.jsonl, the rich source) with a telemetry.prom fallback for run
    dirs that died before a full tick record landed.  Lookups use the
    registry's slash names; the prom fallback translates through
    ``prom_name``."""

    def __init__(self, run_dir: str):
        records = read_stats_records(run_dir)
        self.last = records[-1] if records else {}
        self.n_ticks = sum(1 for r in records
                           if "timing/sec_per_tick" in r)
        snap = self.last.get("telemetry", {})
        self.counters = dict(snap.get("counters", {}))
        self.gauges = dict(snap.get("gauges", {}))
        self.histograms = dict(snap.get("histograms", {}))
        self._prom = read_prom_values(run_dir)
        self.have_any = bool(snap) or bool(self._prom)

    def _get(self, table: dict, name: str):
        if name in table:
            return table[name]
        from gansformer_tpu.obs.registry import prom_name

        return self._prom.get(prom_name(name))

    def counter(self, name: str):
        return self._get(self.counters, name)

    def gauge(self, name: str):
        return self._get(self.gauges, name)

    def stat(self, name: str):
        return self.last.get(name)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def run_doctor(run_dir: str, max_age_s: Optional[float] = None,
               expected: Optional[int] = None,
               max_step_skew: Optional[int] = None,
               now: Optional[float] = None,
               max_restarts_per_hour: float = 6.0,
               max_shed_rate: float = 0.01,
               slo_window_s: float = 3600.0) -> dict:
    """The run-health report as a pure-ish dict (rendered by
    ``render_doctor``; archived verbatim by ``--json``).

    Levels: PASS (healthy / informational), WARN (suspicious — the run
    may still be fine, a human should look), FAIL (the run dir cannot be
    trusted or a liveness contract is broken).  ``ok`` is True iff no
    FAIL — WARNs never fail the doctor, so it is safe in gates that only
    guard against hard breakage (the battery archives the JSON either
    way)."""
    checks: List[dict] = []

    def check(name: str, level: str, detail: str) -> None:
        checks.append({"name": name, "level": level, "detail": detail})

    # -- artifacts ----------------------------------------------------------
    present = [f for f in ("stats.jsonl", "telemetry.prom", "events.jsonl",
                           "config.json")
               if os.path.exists(os.path.join(run_dir, f))]
    import glob as _glob

    beats_files = _glob.glob(os.path.join(run_dir, "heartbeat-p*.json"))
    if beats_files:
        present.append(f"heartbeat-p*.json x{len(beats_files)}")
    if "stats.jsonl" not in present and "telemetry.prom" not in present:
        check("artifacts", "FAIL",
              f"neither stats.jsonl nor telemetry.prom under {run_dir} — "
              f"not a run dir this framework's loop wrote")
        return {"run_dir": run_dir, "ok": False, "n_warn": 0, "n_fail": 1,
                "checks": checks}
    check("artifacts", "PASS", "found " + ", ".join(present))

    tele = _Tele(run_dir)

    # -- progress -----------------------------------------------------------
    if tele.n_ticks:
        check("progress", "PASS",
              "{} tick(s), kimg {:.1f}, {:.1f} img/s/chip, "
              "sec/tick {:.1f}".format(
                  tele.n_ticks, tele.stat("Progress/kimg") or 0.0,
                  tele.stat("timing/img_per_sec_per_chip") or 0.0,
                  tele.stat("timing/sec_per_tick") or 0.0))
    else:
        check("progress", "WARN",
              "no tick records in stats.jsonl — the run died before its "
              "first tick boundary")

    # -- device truth (wall-vs-device divergence) ---------------------------
    sampler_off = tele.gauge("device/sampler_off")
    samples = tele.counter("device/samples_total") or 0.0
    ratio = tele.gauge("device/wall_busy_ratio")
    if sampler_off == 1.0:
        check("device_truth", "WARN",
              "device-time sampler OFF — wall-clock numbers are "
              "unverified (enable with --device-time-ticks N)")
    elif sampler_off is None and ratio is None:
        check("device_truth", "WARN",
              "no device/* telemetry — run predates the device-truth "
              "layer or never wrote a tick")
    elif not samples or ratio is None:
        unavailable = tele.gauge("device/unavailable")
        check("device_truth", "WARN",
              "sampler on but no device sample landed"
              + (" (no trace parser available)"
                 if unavailable == 1.0 else
                 " yet (run shorter than the sampling cadence?)"))
    elif ratio > 1.1:
        check("device_truth", "WARN",
              f"device busy exceeds sampled wall (ratio {ratio:.2f}) — "
              f"the wall clock is NOT covering device execution (the "
              f"retracted-r3 failure mode); distrust wall-clock numbers")
    elif ratio < 0.25:
        check("device_truth", "WARN",
              f"device busy only {ratio:.0%} of the sampled tick — the "
              f"device is mostly idle (host-bound run); check data_wait "
              f"and dispatch overhead")
    else:
        check("device_truth", "PASS",
              "device busy/wall ratio {:.2f} over {} sample(s) (busy "
              "{:.0f} ms / wall {:.0f} ms)".format(
                  ratio, int(samples), tele.gauge("device/busy_ms") or 0,
                  tele.gauge("device/wall_ms") or 0))

    # -- MFU: device-time beside wall-clock ---------------------------------
    wall_mfu = tele.stat("timing/mfu")
    dev_mfu = tele.gauge("device/mfu")
    if wall_mfu is None and dev_mfu is None:
        check("mfu", "PASS",
              "no MFU bookkeeping (off-TPU or FLOPs unavailable)")
    elif dev_mfu is None:
        check("mfu", "WARN",
              f"wall-clock MFU {wall_mfu:.3f} with NO device-time MFU to "
              f"check it against — the number of record is device-time "
              f"MFU (PERF.md measurement discipline)")
    elif wall_mfu is None:
        check("mfu", "PASS", f"device-time MFU {dev_mfu:.3f}")
    elif abs(wall_mfu - dev_mfu) > 0.25 * max(dev_mfu, 1e-9):
        check("mfu", "WARN",
              f"wall-clock MFU {wall_mfu:.3f} diverges from device-time "
              f"MFU {dev_mfu:.3f} (>25%) — trust the device number")
    else:
        check("mfu", "PASS",
              f"device-time MFU {dev_mfu:.3f} agrees with wall-clock "
              f"{wall_mfu:.3f}")

    # -- input pipeline -----------------------------------------------------
    wait_frac = tele.stat("timing/data_wait_frac")
    if wait_frac is None:
        check("data_wait", "WARN", "no timing/data_wait_frac stat")
    elif wait_frac > 0.25:
        check("data_wait", "WARN",
              f"loop blocked on input {wait_frac:.0%} of the last tick — "
              f"input-bound (decode or transfer, see queue depths)")
    else:
        check("data_wait", "PASS",
              f"data wait {wait_frac:.1%} of the last tick")
    starved = tele.counter("data/starved_total") or 0.0
    depth = tele.gauge("data/prefetch_queue_depth")
    dev_depth = tele.gauge("data/device_queue_depth")
    qdetail = "host queue depth {}, device ring depth {}".format(
        "?" if depth is None else int(depth),
        "?" if dev_depth is None else int(dev_depth))
    if starved > 0:
        check("queues", "WARN",
              f"data/starved_total = {int(starved)} (consumer beat the "
              f"producer); {qdetail}")
    else:
        check("queues", "PASS", f"no starvation; {qdetail}")

    # -- data-plane robustness (ISSUE 15) -----------------------------------
    # Graded only when the data/* robustness family is present (run dirs
    # predating the fault-tolerant data plane just skip the section):
    # FAIL on a stall-kill or a corrupt-frac budget breach, WARN on any
    # quarantines/retries (the run survived, a human should know), PASS
    # on clean counters.
    # Counters reset per PROCESS (a resumed run starts a fresh
    # registry), so the last tick's snapshot under-reports anything that
    # happened before a restart — e.g. a read retry absorbed just
    # before a crash.  The stats.jsonl records are append-only across
    # restarts: take the max over every tick's snapshot, falling back
    # to the live accessor for dirs that died before a tick landed.
    _data_records = read_stats_records(run_dir)   # one read for all three

    def _max_counter(name):
        seen = [r["telemetry"]["counters"][name]
                for r in _data_records
                if name in r.get("telemetry", {}).get("counters", {})]
        live = tele.counter(name)
        if live is not None:
            seen.append(live)
        return max(seen) if seen else None

    d_corrupt = _max_counter("data/corrupt_records_total")
    d_retries = _max_counter("data/read_retries_total")
    d_stalls = _max_counter("data/stalls_total")
    d_frac = tele.gauge("data/corrupt_frac")
    d_budget = tele.gauge("data/corrupt_budget_frac")
    if any(v is not None for v in (d_corrupt, d_retries, d_stalls)):
        n_ledger = 0
        ledger = os.path.join(run_dir, "data_quarantine.jsonl")
        if os.path.exists(ledger):
            with open(ledger) as f:
                n_ledger = sum(1 for line in f if line.strip())
        dbits = ("{} quarantined record(s) ({} ledger line(s)), {} read "
                 "retr{}, corrupt frac {}".format(
                     int(d_corrupt or 0), n_ledger, int(d_retries or 0),
                     "y" if int(d_retries or 0) == 1 else "ies",
                     "?" if d_frac is None else f"{d_frac:.2%}"
                     + ("" if d_budget is None
                        else f" of {d_budget:.2%} budget")))
        if d_stalls:
            check("data_plane", "FAIL",
                  f"data stall watchdog fired {int(d_stalls)} time(s) — "
                  f"the input pipeline wedged (DataStalled); {dbits}")
        elif d_frac is not None and d_budget is not None and \
                d_frac > d_budget:
            check("data_plane", "FAIL",
                  f"corrupt-record fraction {d_frac:.2%} exceeds the "
                  f"{d_budget:.2%} budget — the run exits typed "
                  f"data-corrupt (static defect; fix the dataset, not "
                  f"the restart count); {dbits}")
        elif (d_corrupt or 0) > 0 or (d_retries or 0) > 0:
            check("data_plane", "WARN",
                  f"data plane degraded but within budget — {dbits}")
        else:
            check("data_plane", "PASS",
                  f"no quarantines, retries, or stalls; {dbits}")

    # -- numerics cross-check (ISSUE 19) ------------------------------------
    # The runtime twin of graftnum's static fp32-island audit: the loop
    # classifies any non-finite tick stat by cause (loss/grad/param) on
    # already-fetched host values.  Graded only when the family is
    # present (older run dirs skip); nonzero is a WARN, never a FAIL —
    # the loop kept running, a human decides whether the run is dead.
    nf_total = _max_counter("train/nonfinite_total")
    if nf_total is not None:
        if nf_total > 0:
            causes = ", ".join(
                f"{c}={int(_max_counter(f'train/nonfinite_{c}_total') or 0)}"
                for c in ("loss", "grad", "param"))
            check("numerics", "WARN",
                  f"{int(nf_total)} non-finite tick stat(s) reached the "
                  f"host ({causes}) — cross-check the fp32-island audit "
                  f"(gansformer-lint --trace) and consider "
                  f"train.debug_nans for op-level localization")
        else:
            check("numerics", "PASS",
                  "no non-finite tick stats (loss/grad/param all clean)")

    # -- compiles / retraces ------------------------------------------------
    compiles = tele.counter("compile/compiles_total")
    retraces = tele.counter("compile/retraces_total")
    if retraces is None:
        check("compiles", "WARN",
              "no compile/retraces_total — the retrace watch never "
              "armed (run died before its first tick boundary?)")
    elif retraces > 0:
        check("compiles", "WARN",
              f"{int(retraces)} post-warm-up compile(s) (retraces) — "
              f"equivalent work re-entering the compiler mid-run "
              f"(caveat: the first in-loop metric sweep compiles lazily "
              f"and shows as a one-time jump)")
    else:
        check("compiles", "PASS",
              "0 retraces ({} warm-up compile(s))".format(
                  "?" if compiles is None else int(compiles)))

    # -- HBM ----------------------------------------------------------------
    hbm_unavail = tele.gauge("hbm/unavailable")
    peak = tele.gauge("hbm/peak_bytes")
    limit = tele.gauge("hbm/bytes_limit")
    if hbm_unavail == 1.0:
        check("hbm", "PASS",
              "backend reports no memory stats (CPU) — hbm/* marked "
              "unavailable")
    elif peak is None:
        check("hbm", "WARN", "no hbm/* telemetry in the run dir")
    elif limit and peak / limit > 0.92:
        check("hbm", "WARN",
              f"peak HBM {_fmt_bytes(peak)} is {peak / limit:.0%} of the "
              f"{_fmt_bytes(limit)} limit — one allocation from OOM")
    else:
        check("hbm", "PASS",
              f"peak HBM {_fmt_bytes(peak)}"
              + (f" of {_fmt_bytes(limit)} ({peak / limit:.0%})"
                 if limit else ""))

    # -- heartbeats: staleness + step skew ----------------------------------
    from gansformer_tpu.obs.heartbeat import check_heartbeats

    hb = check_heartbeats(
        run_dir, max_age_s=max_age_s if max_age_s is not None else 1e18,
        expected=list(range(expected)) if expected is not None else None,
        now=now, max_step_skew=max_step_skew)
    if hb["stale"] or hb["missing"]:
        # missing peers (via --expected) must outrank the softer
        # "no files" verdict: a fully-dead run is worse, not better,
        # than a partially-dead one
        check("heartbeats", "FAIL",
              "stale processes {}, missing {}{} — babysitter should "
              "restart".format(
                  hb["stale"], hb["missing"],
                  f" (max age {max_age_s}s)"
                  if max_age_s is not None else ""))
    elif not hb["ages"]:
        check("heartbeats", "WARN", "no heartbeat files")
    else:
        age = max(hb["ages"].values())
        check("heartbeats", "PASS",
              f"{len(hb['ages'])} process(es), last beat {age:.0f}s ago"
              + ("" if max_age_s is not None
                 else " (no --max-age given: staleness not judged)"))
    if len(hb.get("steps", {})) > 1:
        if hb["skew_exceeded"]:
            check("step_skew", "WARN",
                  f"inter-process step skew {hb['step_skew']} > "
                  f"{max_step_skew} — straggler (one process lags the "
                  f"collectives); steps: {hb['steps']}")
        else:
            check("step_skew", "PASS",
                  f"inter-process step skew {hb['step_skew']}"
                  + ("" if max_step_skew is not None
                     else " (no --max-skew given: not judged)"))

    # -- restarts / availability (supervisor ledger) ------------------------
    # supervisor_events.jsonl (supervise/events.py) supersedes the bare
    # resumes.jsonl: exit CAUSES, downtime, and restart counts.  When the
    # ledger exists the availability section grades it — restart storms,
    # unclassified exits, a give-up verdict, the availability ratio;
    # otherwise the legacy resumes.jsonl count is reported as before.
    from gansformer_tpu.supervise import events as sup_events
    from gansformer_tpu.utils.logging import read_resume_records

    sup = sup_events.read_events(run_dir)
    if sup:
        s = sup_events.availability(sup, now=now)
        ratio = ("" if s["ratio"] is None
                 else f", availability {s['ratio']:.1%} "
                      f"(up {s['uptime_s']:.0f}s / down "
                      f"{s['downtime_s']:.0f}s)")
        causes = ", ".join(f"{k}x{v}" for k, v in
                           sorted(s["causes"].items())) or "none"
        summary = (f"{s['restarts']} restart(s), exits: {causes}{ratio}")
        if s["gave_up"]:
            nr = sorted(set(s["causes"])
                        & set(sup_events.NON_RETRYABLE_CAUSES))
            check("availability", "FAIL",
                  (f"supervisor gave up on non-retryable cause(s) "
                   f"{', '.join(nr)} (static defect — fix the dataset, "
                   f"not the restart count) — {summary}" if nr else
                   f"supervisor GAVE UP (restart budget exhausted) — "
                   f"{summary}; the run needs a human"))
        elif s["unclassified"]:
            check("availability", "WARN",
                  f"unclassified exit cause(s) {s['unclassified']} in "
                  f"the ledger — the supervisor's vocabulary rotted or "
                  f"the file was hand-edited; {summary}")
        elif s["restarts_last_hour"] > max_restarts_per_hour:
            check("availability", "WARN",
                  f"restart storm: {s['restarts_last_hour']} restart(s) "
                  f"in the last hour (> {max_restarts_per_hour:g}) — "
                  f"the run is thrashing, not training; {summary}")
        else:
            check("availability", "PASS", summary)
    resumes = read_resume_records(run_dir)
    if resumes:
        check("restarts", "PASS",
              f"{len(resumes)} restart(s); last resumed at step "
              f"{resumes[-1].get('step', '?')}")
    else:
        check("restarts", "PASS", "no restarts recorded")

    # -- serving (ISSUE 13) -------------------------------------------------
    # Graded only when serve/* telemetry is present (a service's
    # telemetry.prom, or a run dir a load test wrote into): FAIL on a
    # tripped circuit breaker or a dispatcher dead with work queued,
    # WARN on shed rate > max_shed_rate or a saturated admission queue.
    from gansformer_tpu.analysis.telemetry_schema import (
        serve_dead_with_work)

    serve_health = tele.gauge("serve/health_state")
    serve_reqs = tele.counter("serve/requests_total")
    chaos_path = os.path.join(run_dir, "serve_chaos.json")
    chaos_present = os.path.exists(chaos_path)
    if serve_health is not None or serve_reqs is not None:
        alive = tele.gauge("serve/dispatcher_alive")
        depth = tele.gauge("serve/queue_depth_now") or 0.0
        bound = tele.gauge("serve/queue_bound")
        s_restarts = tele.counter("serve/dispatcher_restarts_total") or 0.0
        shed = tele.counter("serve/shed_total") or 0.0
        reqs = serve_reqs or 0.0
        shed_rate = shed / max(shed + reqs, 1.0)
        bits = ("{} request(s), shed {} ({:.1%}), {} dispatcher "
                "restart(s), queue {}/{}".format(
                    int(reqs), int(shed), shed_rate, int(s_restarts),
                    int(depth), "?" if bound is None else int(bound)))
        if serve_health == 2.0:
            check("serving", "FAIL",
                  f"service UNHEALTHY (circuit breaker tripped or "
                  f"failed drain) — needs a restart; {bits}")
        elif serve_dead_with_work(alive, depth):
            check("serving", "FAIL",
                  f"dispatcher dead with {int(depth)} request(s) still "
                  f"queued — tickets are hung; {bits}")
        elif shed_rate > max_shed_rate and not chaos_present:
            # a serve_chaos.json beside the telemetry means the
            # overload was DELIBERATELY driven — shedding is the drill
            # working, not a capacity alarm
            check("serving", "WARN",
                  f"shed rate {shed_rate:.1%} > {max_shed_rate:.0%} — "
                  f"sustained overload (scale out or raise the queue "
                  f"bound); {bits}")
        elif bound and depth >= bound:
            check("serving", "WARN",
                  f"admission queue saturated — the next submit sheds; "
                  f"{bits}")
        else:
            check("serving", "PASS",
                  bits
                  + (" (overload deliberately driven — chaos drill)"
                     if chaos_present and shed_rate > max_shed_rate
                     else "")
                  + (" (degraded)" if serve_health == 1.0 else "")
                  + (" (closed cleanly)" if serve_health == 3.0
                     else ""))

    # -- serve fleet (ISSUE 20) ---------------------------------------------
    # Graded only when the replica-per-device layer is visible
    # (serve/replicas gauge): the fleet families — per-replica health
    # gauges, scale-out/in counters — must be PRESENT (their absence
    # means replica attribution rotted while the fleet gauge survived),
    # and a replica's traffic must come with its latency samples
    # (images without batch_ms is half-wired attribution).  FAIL only
    # when the whole fleet is dead with work queued — everything else
    # is WARN: the fleet serves as long as SOME replica can.
    n_replicas = tele.gauge("serve/replicas")
    if n_replicas is not None:
        from gansformer_tpu.analysis.telemetry_schema import (
            serve_fleet_dead_with_work, serve_replica_ordinals)
        from gansformer_tpu.obs.registry import prom_name

        vals = dict(tele._prom)
        for k, v in list(tele.counters.items()) + list(tele.gauges.items()):
            vals.setdefault(prom_name(k), v)
        for k, h in tele.histograms.items():
            if isinstance(h, dict) and "count" in h:
                vals.setdefault(prom_name(k) + "_count", h["count"])
        ordinals = serve_replica_ordinals(vals)
        outs = vals.get("serve_scale_out_total")
        ins = vals.get("serve_scale_in_total")
        alive_n = sum(
            1 for i in ordinals
            if vals.get(f"serve_replica{i}_dispatcher_alive", 0.0) > 0)
        fbits = ("{} active replica(s) (ordinals {}), {} alive, "
                 "scale-out {} / scale-in {}".format(
                     int(n_replicas), ordinals or "none", alive_n,
                     "?" if outs is None else int(outs),
                     "?" if ins is None else int(ins)))
        missing = [f"serve_replica{i}_{fam}" for i in ordinals
                   for fam in ("health_state", "dispatcher_alive",
                               "queue_depth_now", "requests_total")
                   if f"serve_replica{i}_{fam}" not in vals]
        unsampled = [i for i in ordinals
                     if vals.get(f"serve_replica{i}_images_total", 0.0) > 0
                     and vals.get(f"serve_replica{i}_batch_ms_count",
                                  0.0) <= 0]
        if serve_fleet_dead_with_work(vals):
            check("serve_fleet", "FAIL",
                  f"every replica's dispatcher is dead with work still "
                  f"queued — the fleet hangs its tickets; {fbits}")
        elif not ordinals:
            check("serve_fleet", "WARN",
                  f"serve/replicas present but no serve/replica<i>/* "
                  f"member families — per-replica attribution rotted; "
                  f"{fbits}")
        elif missing or outs is None or ins is None:
            check("serve_fleet", "WARN",
                  f"fleet families incomplete — missing "
                  f"{missing or 'scale counters'}; {fbits}")
        elif unsampled:
            check("serve_fleet", "WARN",
                  f"replica(s) {unsampled} served images with ZERO "
                  f"batch_ms samples — traffic without latency "
                  f"attribution; {fbits}")
        else:
            check("serve_fleet", "PASS", fbits)

    # chaos/loadtest artifacts beside the telemetry, when present
    if chaos_present:
        try:
            with open(chaos_path) as f:
                chaos = json.load(f)
        except ValueError:
            chaos = None
        if not isinstance(chaos, dict):
            check("serve_chaos", "WARN",
                  "serve_chaos.json present but not a JSON object")
        else:
            cbits = ("shed {:.1%}, expired {:.1%}, p99-under-overload "
                     "{} ms, {} restart(s), recovery {} ms".format(
                         chaos.get("shed_rate", 0.0),
                         chaos.get("expired_rate", 0.0),
                         chaos.get("p99_ms_under_overload"),
                         int(chaos.get("dispatcher_restarts", 0)),
                         chaos.get("recovery_ms")))
            chaos_state = (chaos.get("health") or {}).get("state")
            if chaos.get("hung_tickets", 0):
                check("serve_chaos", "FAIL",
                      f"{chaos['hung_tickets']} HUNG ticket(s) in the "
                      f"chaos drill — a recovery path leaks requests; "
                      f"{cbits}")
            elif chaos_state == "unhealthy":
                # the drill's own health snapshot (its prom may live in
                # a separate file the telemetry accessor never reads)
                check("serve_chaos", "FAIL",
                      f"chaos drill left the service UNHEALTHY "
                      f"(breaker tripped / failed drain) — "
                      f"{(chaos.get('health') or {}).get('reasons')}; "
                      f"{cbits}")
            elif chaos.get("crash_at_batch") and \
                    chaos.get("dispatcher_restarts", 0) < 1:
                check("serve_chaos", "WARN",
                      f"chaos drill recorded no dispatcher restart — "
                      f"the injected crash never fired; {cbits}")
            else:
                check("serve_chaos", "PASS", cbits)
            # the autoscaler drill's ordering evidence (ISSUE 20):
            # scale-out (the LEADING saturation signal) must beat any
            # breaker trip (the trailing one), and scale-in must follow
            # recovery.  A controller that misbehaves under a DRILL is
            # a WARN, never a FAIL — the floor still served (hung
            # tickets and health already graded above).
            asc = chaos.get("autoscale")
            if isinstance(asc, dict) and asc.get("enabled"):
                abits = ("scale-out x{} / scale-in x{}, breaker "
                         "trip(s) {}, peak {} replica(s)".format(
                             asc.get("scale_out_fired", 0),
                             asc.get("scale_in_fired", 0),
                             asc.get("breaker_trips", 0),
                             asc.get("peak_replicas")))
                if not asc.get("scale_out_fired"):
                    check("serve_autoscale", "WARN",
                          f"controller never scaled out under the "
                          f"burst — saturation threshold or tick "
                          f"cadence miscalibrated for the drill; "
                          f"{abits}")
                elif not asc.get("scale_out_before_breaker"):
                    check("serve_autoscale", "WARN",
                          f"breaker tripped BEFORE the first scale-out "
                          f"— the controller reacted on the trailing "
                          f"signal, not the leading one; {abits}")
                elif not asc.get("scaled_in_after_load"):
                    check("serve_autoscale", "WARN",
                          f"no scale-in after recovery — the fleet "
                          f"stays scaled out (cost leak, not an "
                          f"outage); {abits}")
                else:
                    check("serve_autoscale", "PASS", abits)

    # -- SLO error budgets (ISSUE 16) ---------------------------------------
    # Graded only when served traffic is visible (a requests.jsonl
    # ledger or serve/* counters) — train-only run dirs skip the
    # section.  FAIL on an exhausted error budget; under a chaos drill
    # the spend is deliberate, so the section reports informationally
    # instead of failing the doctor on its own fault injection.
    from gansformer_tpu.obs.slo import evaluate_slos

    slo_rep = evaluate_slos(run_dir, window_s=slo_window_s, now=now)
    slo_graded = [o for o in slo_rep["objectives"]
                  if o["status"] != "no_data"]
    if slo_graded:
        sbits = "; ".join(
            "{}: {:.2%} of target {:.1%} (burn {:g})".format(
                o["name"], o["compliance"], o["target"], o["burn_rate"])
            for o in slo_graded)
        sbits += (f" [{slo_rep['source']}"
                  + (f", {slo_rep['rows']} row(s) in "
                     f"{slo_rep['window_s']:g}s window"
                     if slo_rep["source"] == "ledger" else "")
                  + "]")
        if slo_rep["exhausted"] and chaos_present:
            check("slo", "PASS",
                  f"budget(s) {slo_rep['exhausted']} spent under a "
                  f"DELIBERATE chaos drill — not a capacity verdict; "
                  f"{sbits}")
        elif slo_rep["exhausted"]:
            check("slo", "FAIL",
                  f"error budget EXHAUSTED for "
                  f"{', '.join(slo_rep['exhausted'])} — the service is "
                  f"out of its declared objective; {sbits}")
        else:
            check("slo", "PASS", sbits)

    # -- device phase table (informational) ---------------------------------
    phase_ms = sorted(((k.split("/", 2)[2], v)
                       for k, v in tele.gauges.items()
                       if k.startswith("device/phase_ms/")),
                      key=lambda kv: -kv[1])
    if phase_ms:
        check("device_phases", "PASS",
              "device ms (last sampled tick): " + ", ".join(
                  f"{n}={v:.0f}" for n, v in phase_ms[:8]))

    n_warn = sum(1 for c in checks if c["level"] == "WARN")
    n_fail = sum(1 for c in checks if c["level"] == "FAIL")
    return {"run_dir": run_dir, "ok": n_fail == 0,
            "n_warn": n_warn, "n_fail": n_fail, "checks": checks}


def render_doctor(report: dict) -> str:
    lines = [f"run doctor: {report['run_dir']}"]
    for c in report["checks"]:
        lines.append(f"  {c['level']:<4s} {c['name']}: {c['detail']}")
    lines.append("verdict: {} ({} warn, {} fail)".format(
        "OK" if report["ok"] else "NOT OK",
        report["n_warn"], report["n_fail"]))
    return "\n".join(lines)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("trace", help="events.jsonl → Chrome trace JSON")
    t.add_argument("run_dir")
    t.add_argument("--out", default=None,
                   help="output path (default <run_dir>/trace.json)")

    h = sub.add_parser("heartbeats", help="multi-host staleness probe")
    h.add_argument("run_dir")
    h.add_argument("--max-age", type=float, default=300.0,
                   help="seconds before a heartbeat counts as stale")
    h.add_argument("--expected", type=int, default=None,
                   help="expected process count (detects missing peers)")
    h.add_argument("--max-skew", type=int, default=None,
                   help="max inter-process step skew before the probe "
                        "fails (straggler detection)")

    s = sub.add_parser("summary", help="phase totals + current telemetry")
    s.add_argument("run_dir")

    r = sub.add_parser("requests",
                       help="request ledger: summary / timelines / p99 "
                            "exemplar resolution")
    r.add_argument("run_dir")
    r.add_argument("--id", dest="rid", default=None, metavar="RID",
                   help="render one request's full timeline")
    r.add_argument("--worst", type=int, default=None, metavar="N",
                   help="render the N slowest requests' timelines")

    o = sub.add_parser("slo", help="error budgets over the declared "
                                   "objectives (exit 1 when a budget "
                                   "is exhausted)")
    o.add_argument("run_dir")
    o.add_argument("--window", type=float, default=3600.0,
                   help="rolling window in seconds over the request "
                        "ledger (default 3600)")
    o.add_argument("--json", action="store_true",
                   help="print the machine-readable report")

    fl = sub.add_parser("fleet",
                        help="aggregate N processes' telemetry into "
                             "fleet.json / fleet.prom")
    fl.add_argument("run_dirs", nargs="+",
                    help="ONE shared run dir (heartbeat-p*.json roster) "
                         "or several per-process run dirs")
    fl.add_argument("--expected", type=int, default=None,
                    help="expected process count (missing processes "
                         "mark the view partial)")
    fl.add_argument("--max-age", type=float, default=None,
                    help="heartbeats older than this many seconds mark "
                         "the view partial")
    fl.add_argument("--out-dir", default=None, metavar="DIR",
                    help="write fleet.json + fleet.prom under DIR "
                         "(default: print the JSON to stdout only)")

    d = sub.add_parser("doctor", help="one-shot run-health report "
                                      "(PASS/WARN/FAIL; exit 0 iff no "
                                      "FAIL)")
    d.add_argument("run_dir",
                   help="run dir, or a results root (picks the latest "
                        "numbered run)")
    d.add_argument("--json", action="store_true",
                   help="print the machine-readable report instead of "
                        "the rendered one")
    d.add_argument("--json-out", default=None, metavar="PATH",
                   help="also write the JSON report to PATH (the "
                        "battery archives one per window)")
    d.add_argument("--max-age", type=float, default=None,
                   help="judge heartbeat staleness against this many "
                        "seconds (stale → FAIL); default: report only")
    d.add_argument("--expected", type=int, default=None,
                   help="expected process count (missing peers → FAIL)")
    d.add_argument("--max-skew", type=int, default=None,
                   help="judge inter-process step skew against this "
                        "threshold (exceeded → WARN); default: report "
                        "only")
    d.add_argument("--max-restarts-hour", type=float, default=6.0,
                   help="restart-storm threshold for the availability "
                        "section (supervisor ledger restarts in the "
                        "last hour above this → WARN)")
    d.add_argument("--max-shed-rate", type=float, default=0.01,
                   help="serving-section shed-rate threshold (above "
                        "this → WARN)")
    d.add_argument("--slo-window", type=float, default=3600.0,
                   help="rolling window in seconds for the slo "
                        "section's ledger-based budgets")

    args = p.parse_args(argv)

    if args.cmd == "trace":
        out = write_chrome_trace(args.run_dir, args.out)
        print(f"wrote {out} — open in chrome://tracing or "
              f"https://ui.perfetto.dev")
    elif args.cmd == "heartbeats":
        from gansformer_tpu.obs.heartbeat import check_heartbeats

        expected = (list(range(args.expected))
                    if args.expected is not None else None)
        result = check_heartbeats(args.run_dir, max_age_s=args.max_age,
                                  expected=expected,
                                  max_step_skew=args.max_skew)
        print(json.dumps(result))
        if not result["ok"]:
            sys.exit(1)
    elif args.cmd == "doctor":
        run_dir = resolve_run_dir(args.run_dir)
        report = run_doctor(run_dir, max_age_s=args.max_age,
                            expected=args.expected,
                            max_step_skew=args.max_skew,
                            max_restarts_per_hour=args.max_restarts_hour,
                            max_shed_rate=args.max_shed_rate,
                            slo_window_s=args.slo_window)
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(report, f, indent=1, sort_keys=True)
                f.write("\n")
        if args.json:
            print(json.dumps(report, indent=1, sort_keys=True))
        else:
            print(render_doctor(report))
        if not report["ok"]:
            sys.exit(1)
    elif args.cmd == "requests":
        sys.exit(run_requests(args.run_dir, rid=args.rid,
                              worst=args.worst))
    elif args.cmd == "slo":
        from gansformer_tpu.obs.slo import evaluate_slos, render_slos

        report = evaluate_slos(args.run_dir, window_s=args.window)
        if args.json:
            print(json.dumps(report, indent=1, sort_keys=True))
        else:
            print(render_slos(report))
        if report["exhausted"]:
            sys.exit(1)
    elif args.cmd == "fleet":
        from gansformer_tpu.obs.aggregate import aggregate_fleet, \
            write_fleet

        target = (args.run_dirs[0] if len(args.run_dirs) == 1
                  else args.run_dirs)
        fleet = aggregate_fleet(target, expected=args.expected,
                                max_age_s=args.max_age)
        if args.out_dir:
            json_path, prom_path = write_fleet(fleet, args.out_dir)
            print(f"wrote {json_path} and {prom_path}"
                  + (" (PARTIAL view: "
                     + "; ".join(fleet["partial_reasons"]) + ")"
                     if fleet["partial"] else ""))
        else:
            print(json.dumps(fleet, indent=1, sort_keys=True))
    elif args.cmd == "summary":
        for row in summarize_events(read_events(args.run_dir)):
            print("{name:<28s} n={count:<6d} total {total_ms:>10.1f} ms  "
                  "mean {mean_ms:>8.2f} ms".format(**row))
        prom = os.path.join(args.run_dir, "telemetry.prom")
        if os.path.exists(prom):
            print("\n-- telemetry.prom --")
            sys.stdout.write(open(prom).read())


if __name__ == "__main__":
    main()
