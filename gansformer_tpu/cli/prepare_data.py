"""Dataset preparation CLI — the role of the reference's ``dataset_tool.py``
+ ``prepare_data.py`` (SURVEY.md §3.4): convert an image folder, a CIFAR-10
extract, or the builtin synthetic source into a training archive.

Two output formats:
* ``--to npz`` — this framework's fast path (uint8 NHWC ``images`` +
  optional ``labels``);
* ``--to tfrecord`` — the reference's multi-resolution layout
  (``<name>-r{02..NN}.tfrecords`` + ``<name>-rNN.labels``), via
  ``data/tfrecord_writer.py``; files carry valid masked-CRC framing so
  they are readable by stock ``tf.data`` and the reference itself.

``--download <name>`` fetches a benchmark dataset first (resumable,
sha-verified — ``data/download.py``; ``--mirror-url`` points at an internal
mirror when the default host is unreachable, e.g. an airgapped TPU pod),
then converts it like any local source.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def _resolve_download(args) -> None:
    """--download <name>: fetch + extract, then rewrite args so the rest of
    the pipeline sees an ordinary local source."""
    import glob as _glob

    from gansformer_tpu.data.download import extracted_dir, fetch_dataset

    name = args.download
    cache = args.download_dir or os.path.join(
        os.path.dirname(args.out) or ".", ".downloads")

    def progress(done, total):
        if total:
            print(f"\r{name}: {done / 1e6:.1f}/{total / 1e6:.1f} MB",
                  end="", flush=True)

    src = fetch_dataset(name, cache, base_url=args.mirror_url,
                        progress=progress,
                        verify=not args.download_no_verify)
    print()
    root = extracted_dir(name, cache)
    if src.post == "cifar10":
        hits = _glob.glob(os.path.join(root, "**", "data_batch_1"),
                          recursive=True)
        if not hits:
            raise SystemExit(f"downloaded {name} but found no CIFAR batches "
                             f"under {root}")
        if args.resolution not in (None, 32):
            raise SystemExit("CIFAR-10 is 32×32; drop --resolution or "
                             "pass --resolution 32")
        args.cifar10_dir = os.path.dirname(hits[0])
        args.resolution = 32
    elif src.post == "lmdb":
        hits = _glob.glob(os.path.join(root, "**", "data.mdb"),
                          recursive=True)
        if not hits:
            raise SystemExit(f"downloaded {name} but found no lmdb "
                             f"(data.mdb) under {root}")
        args.lsun_lmdb_dir = os.path.dirname(hits[0])
    else:
        args.source_dir = root


def _collect(args):
    """Resolve the input source → (image chunk iterator, labels|None)."""
    if args.synthetic:
        from gansformer_tpu.data.dataset import SyntheticDataset

        n = args.max_images or 10000
        ds = SyntheticDataset(resolution=args.resolution, num_images=n)
        idx = np.arange(n)
        return (ds._make(idx[i:i + 64]) for i in range(0, n, 64)), None
    if args.cifar10_dir:
        from gansformer_tpu.data.tfrecord_writer import load_cifar10

        images, labels = load_cifar10(args.cifar10_dir)
        if args.resolution != 32:
            raise SystemExit("CIFAR-10 is 32×32; pass --resolution 32")
        if args.max_images:
            images, labels = images[: args.max_images], labels[: args.max_images]
        return (images[i:i + 64] for i in range(0, len(images), 64)), labels
    if args.lsun_lmdb_dir:
        from gansformer_tpu.data.tfrecord_writer import iter_lsun_lmdb

        def chunks():
            batch = []
            for img in iter_lsun_lmdb(args.lsun_lmdb_dir, args.resolution,
                                      args.max_images):
                batch.append(img)
                if len(batch) == 64:
                    yield np.stack(batch)
                    batch = []
            if batch:
                yield np.stack(batch)

        return chunks(), None
    if args.source_dir:
        from gansformer_tpu.data.dataset import ImageFolderDataset

        ds = ImageFolderDataset(args.source_dir, resolution=args.resolution)
        files = ds.files[: args.max_images] if args.max_images else ds.files

        def chunks():
            for i in range(0, len(files), 64):
                yield np.stack([ds._load(f) for f in files[i:i + 64]])

        return chunks(), None
    return None, None


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Prepare a training dataset")
    p.add_argument("--source-dir", default=None,
                   help="directory of images (recursively scanned)")
    p.add_argument("--cifar10-dir", default=None,
                   help="extracted cifar-10-batches-py directory")
    p.add_argument("--lsun-lmdb-dir", default=None,
                   help="LSUN lmdb export directory (needs the lmdb pkg)")
    p.add_argument("--synthetic", action="store_true",
                   help="generate the procedural smoke dataset instead")
    p.add_argument("--download", default=None,
                   help="fetch a benchmark dataset first (cifar10, clevr, "
                        "lsun-bedroom; ffhq/cityscapes print manual steps)")
    p.add_argument("--download-dir", default=None,
                   help="archive cache (default: <out dir>/.downloads)")
    p.add_argument("--mirror-url", default=None,
                   help="override the download host (internal mirror)")
    p.add_argument("--download-no-verify", action="store_true",
                   help="skip the registry sha256 check (only for mirrors "
                        "that re-packed the archive)")
    p.add_argument("--to", choices=("npz", "tfrecord"), default="npz",
                   help="output format (tfrecord = reference layout)")
    p.add_argument("--out", required=True,
                   help=".npz path (--to npz) or output directory "
                        "(--to tfrecord)")
    p.add_argument("--name", default=None,
                   help="dataset name for tfrecord filenames "
                        "(default: basename of --out)")
    p.add_argument("--resolution", type=int, default=None,
                   help="output resolution (default 256; cifar10 pins 32)")
    p.add_argument("--max-images", type=int, default=None)
    p.add_argument("--max-lod-only", action="store_true",
                   help="write only the full-resolution tfrecord file "
                        "(skip the progressive pyramid)")
    args = p.parse_args(argv)

    if args.download:
        _resolve_download(args)
    if args.resolution is None:
        args.resolution = 256
    chunks, labels = _collect(args)
    if chunks is None:
        p.error("need --source-dir, --cifar10-dir, --lsun-lmdb-dir, "
                "--download, or --synthetic")

    if args.to == "npz":
        imgs = np.concatenate(list(chunks))
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        if labels is not None:
            np.savez_compressed(args.out, images=imgs, labels=labels)
        else:
            np.savez_compressed(args.out, images=imgs)
        print(f"{len(imgs)} images @ {args.resolution}² → {args.out}")
        return

    from gansformer_tpu.data.tfrecord_writer import TFRecordExporter

    name = args.name or os.path.basename(os.path.normpath(args.out))
    with TFRecordExporter(args.out, name, args.resolution,
                          all_lods=not args.max_lod_only) as ex:
        for chunk in chunks:
            for img in chunk:
                ex.add_image(img)
        if labels is not None:
            ex.add_labels(labels)
        n = ex.num_images
    print(f"{n} images @ {args.resolution}² → {args.out}/{name}-r*.tfrecords")


if __name__ == "__main__":
    main()
