"""Dataset preparation CLI — the role of the reference's ``dataset_tool.py``
+ ``prepare_data.py`` (SURVEY.md §3.4): convert an image folder (or a
builtin synthetic source) into a packed training archive.

Output format is this framework's fast path (``.npz`` with uint8 NHWC
``images``), not TFRecords — the TFRecord *reader* exists for datasets
already prepared for the reference (data/dataset.py), so conversion is only
needed for new datasets.  Downloads are out of scope in an airgapped image;
point --source-dir at data you already have.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Prepare a training dataset")
    p.add_argument("--source-dir", default=None,
                   help="directory of images (recursively scanned)")
    p.add_argument("--synthetic", action="store_true",
                   help="generate the procedural smoke dataset instead")
    p.add_argument("--out", required=True, help="output .npz path")
    p.add_argument("--resolution", type=int, default=256)
    p.add_argument("--max-images", type=int, default=None)
    args = p.parse_args(argv)

    if args.synthetic:
        from gansformer_tpu.data.dataset import SyntheticDataset

        n = args.max_images or 10000
        ds = SyntheticDataset(resolution=args.resolution, num_images=n)
        imgs = ds._make(np.arange(n))
    elif args.source_dir:
        from gansformer_tpu.data.dataset import ImageFolderDataset

        ds = ImageFolderDataset(args.source_dir, resolution=args.resolution)
        files = ds.files[: args.max_images] if args.max_images else ds.files
        imgs = np.stack([ds._load(f) for f in files])
    else:
        p.error("need --source-dir or --synthetic")

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    np.savez_compressed(args.out, images=imgs)
    print(f"{len(imgs)} images @ {args.resolution}² → {args.out}")


if __name__ == "__main__":
    main()
