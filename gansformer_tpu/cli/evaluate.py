"""Evaluate CLI — metric-only runs against a snapshot (the role of the
reference's ``run_metrics``/generate.py metric path; SURVEY.md §3.3)."""

from __future__ import annotations

import argparse
import json
import os

import jax


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Run FID/IS on a checkpoint")
    p.add_argument("--run-dir", required=True,
                   help="run dir, packed run archive (.tar.gz), or URL")
    p.add_argument("--metrics", default="fid50k,is50k")
    p.add_argument("--num-images", type=int, default=None,
                   help="override metric sample count (e.g. 1000 for smoke)")
    p.add_argument("--batch-size", type=int, default=32)
    # None default (ADVICE r4): ANY explicit value — including 1.0 — must
    # conflict with --psi-sweep; unset falls back to 1.0 below.
    p.add_argument("--truncation-psi", type=float, default=None)
    p.add_argument("--psi-sweep", default=None,
                   help="comma-separated truncation values (e.g. "
                        "'0.5,0.7,1.0'): run the metrics once per psi and "
                        "append the table to metric-psi-sweep.txt — the "
                        "lineage's FID-vs-truncation evaluation practice. "
                        "Real-image statistics are disk-cached across "
                        "psis; the eval setup (mesh/extractor/samplers) is "
                        "rebuilt per psi.")
    p.add_argument("--attention-backend", default=None,
                   choices=("xla", "pallas"),
                   help="override the attention compute backend for the "
                        "metric sweep (forward-only)")
    p.add_argument("--conv-backend", default=None,
                   choices=("xla", "pallas"),
                   help="override the modulated-conv/upfirdn compute "
                        "backend for the metric sweep (ISSUE 14; on TPU a "
                        "failed native smoke check falls back to xla)")
    p.add_argument("--inception-npz", default=None)
    p.add_argument("--cache-dir", default=None)
    args = p.parse_args(argv)

    psis = None
    if args.psi_sweep is not None:
        # Parse/validate BEFORE the expensive run-dir resolution and
        # checkpoint restore: a typo should fail in milliseconds.
        try:
            psis = [float(s) for s in args.psi_sweep.split(",") if s.strip()]
        except ValueError:
            p.error(f"--psi-sweep: not a comma-separated float list: "
                    f"{args.psi_sweep!r}")
        if not psis:
            p.error("--psi-sweep: no values given")
        if args.truncation_psi is not None:
            p.error("--truncation-psi conflicts with --psi-sweep; put the "
                    "value in the sweep list instead")
    if args.truncation_psi is None:
        args.truncation_psi = 1.0

    from gansformer_tpu.core.config import ExperimentConfig
    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.utils.hostenv import enable_compile_cache
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.utils.runarchive import resolve_run_dir

    args.run_dir = resolve_run_dir(args.run_dir)
    enable_compile_cache()
    with open(os.path.join(args.run_dir, "config.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    template = create_train_state(cfg, jax.random.PRNGKey(0))
    state = ckpt.restore(os.path.join(args.run_dir, "checkpoints"), template)
    if args.attention_backend:
        # Forward-only sweep may use the fused pallas kernels; the template
        # above already initialized on xla (identical param tree).  On a
        # TPU, resolve_backend first smoke-compiles the kernels natively
        # and falls back to xla (with the reason) if Mosaic lowering fails.
        import dataclasses

        from gansformer_tpu.ops.pallas_attention import resolve_backend

        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model,
            attention_backend=resolve_backend(args.attention_backend)))
    if args.conv_backend:
        # Same discipline for the conv family (ISSUE 14): identical param
        # tree, native smoke check first on TPU.
        import dataclasses

        from gansformer_tpu.ops.pallas_modconv import resolve_conv_backend

        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model,
            conv_backend=resolve_conv_backend(args.conv_backend)))
    from gansformer_tpu.metrics.sweep import run_metric_sweep

    from gansformer_tpu.metrics.metric_base import FLAG_KEYS

    kimg = int(jax.device_get(state.step)) / 1000
    if psis:
        table = []
        for psi in psis:
            res = run_metric_sweep(
                cfg, state, args.run_dir, args.metrics,
                batch_size=args.batch_size, num_images=args.num_images,
                truncation_psi=psi,
                inception_npz=args.inception_npz, cache_dir=args.cache_dir)
            table.append({"psi": psi, **res})
            print(f"psi {psi:<5.2f} " + "  ".join(
                f"{k} {v:.4f}" for k, v in res.items()))
        path = os.path.join(args.run_dir, "metric-psi-sweep.txt")
        with open(path, "a") as f:
            for row in table:
                f.write(f"kimg {kimg:<10.1f} psi {row['psi']:<5.2f} "
                        + "  ".join(f"{k} {v:.6f}" for k, v in row.items()
                                    if k != "psi" and k not in FLAG_KEYS)
                        + "\n")
        # Flags are per-run state, constant across psis: persist them as
        # flag files here too (the non-sweep branch below does the same).
        from gansformer_tpu.utils.logging import write_flag

        for k in FLAG_KEYS:
            if table and k in table[-1]:
                write_flag(args.run_dir, k, table[-1][k])
        print(json.dumps({"kimg": kimg, "psi_sweep": table}))
        return

    results = run_metric_sweep(
        cfg, state, args.run_dir, args.metrics,
        batch_size=args.batch_size, num_images=args.num_images,
        truncation_psi=args.truncation_psi,
        inception_npz=args.inception_npz, cache_dir=args.cache_dir)
    from gansformer_tpu.utils.logging import write_flag

    for name, val in results.items():
        print(f"{name}: {val:.4f}")
        if name in FLAG_KEYS:
            # Flags are state, not series: flag-<name>.txt, never an
            # all-constant metric-<name>.txt (VERDICT r5 weak #4/item 7).
            # The JSON payload below still carries the value.
            write_flag(args.run_dir, name, val)
            continue
        path = os.path.join(args.run_dir, f"metric-{name}.txt")
        with open(path, "a") as f:
            f.write(f"kimg {kimg:<10.1f} {name} {val:.6f}\n")
    print(json.dumps({"kimg": kimg, **results}))


if __name__ == "__main__":
    main()
