"""Evaluate CLI — metric-only runs against a snapshot (the role of the
reference's ``run_metrics``/generate.py metric path; SURVEY.md §3.3)."""

from __future__ import annotations

import argparse
import json
import os

import jax


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Run FID/IS on a checkpoint")
    p.add_argument("--run-dir", required=True)
    p.add_argument("--metrics", default="fid50k,is50k")
    p.add_argument("--num-images", type=int, default=None,
                   help="override metric sample count (e.g. 1000 for smoke)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--truncation-psi", type=float, default=1.0)
    p.add_argument("--attention-backend", default=None,
                   choices=("xla", "pallas"),
                   help="override the attention compute backend for the "
                        "metric sweep (forward-only)")
    p.add_argument("--inception-npz", default=None)
    p.add_argument("--cache-dir", default=None)
    args = p.parse_args(argv)

    from gansformer_tpu.core.config import ExperimentConfig
    from gansformer_tpu.data.dataset import make_dataset
    from gansformer_tpu.metrics.inception import make_extractor
    from gansformer_tpu.metrics.metric_base import MetricGroup, parse_metric_names
    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    with open(os.path.join(args.run_dir, "config.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    template = create_train_state(cfg, jax.random.PRNGKey(0))
    state = ckpt.restore(os.path.join(args.run_dir, "checkpoints"), template)
    if args.attention_backend:
        # Forward-only sweep may use the fused pallas kernels; the template
        # above already initialized on xla (identical param tree).
        import dataclasses

        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, attention_backend=args.attention_backend))
    fns = make_train_steps(cfg, batch_size=args.batch_size)
    dataset = make_dataset(cfg.data)

    # --num-images overrides the sample count *at construction* so the
    # metric name (and the metric-<name>.txt it lands in) stays honest.
    from gansformer_tpu.parallel.mesh import make_mesh

    env = make_mesh(cfg.mesh)  # FID sweep runs data-parallel over the mesh
    metrics = parse_metric_names(args.metrics, batch_size=args.batch_size,
                                 num_images=args.num_images)
    group = MetricGroup(metrics, make_extractor(args.inception_npz, env=env),
                        cache_dir=args.cache_dir or
                        os.path.join(args.run_dir, "metric-cache"))

    # replicate params over the mesh; make_metric_samplers shards z/labels
    # so the generator half of the sweep is data-parallel too
    from gansformer_tpu.train.steps import make_metric_samplers

    state = jax.device_put(state, env.replicated())
    sample_fn, pair_fn = make_metric_samplers(
        fns, state, cfg, env, dataset,
        truncation_psi=args.truncation_psi, seed=7)

    results = group.run(sample_fn, dataset, pair_fn=pair_fn)
    kimg = int(jax.device_get(state.step)) / 1000
    for name, val in results.items():
        print(f"{name}: {val:.4f}")
        path = os.path.join(args.run_dir, f"metric-{name}.txt")
        with open(path, "a") as f:
            f.write(f"kimg {kimg:<10.1f} {name} {val:.6f}\n")
    print(json.dumps({"kimg": kimg, **results}))


if __name__ == "__main__":
    main()
