"""``gansformer-supervise`` — run training under the run supervisor.

Everything after ``--`` is forwarded verbatim to ``gansformer-train``;
the supervisor owns the run dir, passes it to every (re)start via
``--run-dir``, adds ``--resume`` once checkpoints exist, classifies
every exit (clean / crash / preemption / hang), and re-arms under
bounded exponential backoff until the run completes or the restart
budget runs out (docs/elasticity.md has the full model).

This process NEVER imports jax — importing it would claim the
accelerator the child needs.

Examples
--------
  # an ffhq256 run that survives preemptions and crashes:
  gansformer-supervise --results-dir results -- \\
      --preset ffhq256-duplex --data-path /data/ffhq --batch-size 32

  # prove recovery: one injected SIGKILL mid-checkpoint
  gansformer-supervise --run-dir results/r0 \\
      --fault sigkill@ckpt_mid_write:step=4000 -- \\
      --preset ffhq256-duplex --data-source synthetic --total-kimg 8

Exit codes: 0 = training completed; 75 = the supervisor itself was
preempted (re-arm later, e.g. from the battery's probe loop); 1 =
restart budget exhausted.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Supervised (auto-resuming, fault-classified) "
                    "training",
        epilog="arguments after -- are forwarded to gansformer-train")
    p.add_argument("--results-dir", default="results")
    p.add_argument("--desc", default="supervised",
                   help="run dir description suffix (numbered-dir mode)")
    p.add_argument("--run-dir", default=None,
                   help="pin the run dir (default: allocate a numbered "
                        "dir under --results-dir)")
    p.add_argument("--max-restarts", type=int, default=8,
                   help="restart budget before giving up (default 8)")
    p.add_argument("--backoff-base", type=float, default=2.0,
                   help="base of the bounded exponential restart "
                        "backoff, seconds")
    p.add_argument("--backoff-max", type=float, default=120.0)
    p.add_argument("--poll-interval", type=float, default=2.0,
                   help="child liveness poll cadence, seconds")
    p.add_argument("--heartbeat-max-age", type=float, default=300.0,
                   help="a child that stops beating for this long is "
                        "declared hung and killed")
    p.add_argument("--startup-grace", type=float, default=1800.0,
                   help="grace before the FIRST heartbeat (compiles "
                        "happen before it)")
    p.add_argument("--hang-grace", type=float, default=15.0,
                   help="SIGTERM→SIGKILL window once a hang verdict "
                        "lands")
    p.add_argument("--preempt-grace", type=float, default=30.0,
                   help="grace the child gets for its final checkpoint "
                        "on SIGTERM (exported as "
                        "GANSFORMER_TPU_PREEMPT_GRACE_S)")
    p.add_argument("--max-step-skew", type=int, default=None,
                   help="multi-process: step spread beyond this is a "
                        "hang verdict (straggler)")
    p.add_argument("--fault", action="append", default=[],
                   metavar="SPEC",
                   help="arm a fault-injection spec in the child, e.g. "
                        "sigkill@ckpt_mid_write:step=4000 (repeatable; "
                        "each fires once per run dir — see "
                        "supervise/faults.py)")
    p.add_argument("train_args", nargs=argparse.REMAINDER,
                   help="-- followed by gansformer-train arguments")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    train_args = list(args.train_args)
    if train_args and train_args[0] == "--":
        train_args = train_args[1:]

    from gansformer_tpu.supervise import faults
    from gansformer_tpu.supervise.supervisor import (
        SupervisorConfig, supervise)
    from gansformer_tpu.utils.logging import create_run_dir

    run_dir = args.run_dir or create_run_dir(args.results_dir, args.desc)
    child_env = {}
    if args.fault:
        # Validate the specs HERE (a typo must fail the launch, not
        # silently never fire in the child), then hand them over by env.
        faults.parse_specs(",".join(args.fault))
        child_env[faults.ENV_SPEC] = ",".join(args.fault)
        child_env[faults.ENV_LEDGER] = os.path.join(
            run_dir, "faults_fired.jsonl")

    def build_argv(resume: bool, restart_index: int):
        argv = [sys.executable, "-m", "gansformer_tpu.cli.train",
                *train_args, "--run-dir", run_dir]
        if resume:
            argv.append("--resume")
        return argv

    cfg = SupervisorConfig(
        max_restarts=args.max_restarts,
        backoff_base_s=args.backoff_base,
        backoff_max_s=args.backoff_max,
        poll_interval_s=args.poll_interval,
        heartbeat_max_age_s=args.heartbeat_max_age,
        startup_grace_s=args.startup_grace,
        hang_kill_grace_s=args.hang_grace,
        preempt_grace_s=args.preempt_grace,
        max_step_skew=args.max_step_skew)
    result = supervise(build_argv, run_dir, cfg, child_env=child_env)
    sys.exit(result["exit_code"])


if __name__ == "__main__":
    main()
