"""Replication-study experiment harness (SURVEY.md §2.2 "Repro-study
harness" row).

The reference repo is the code artifact of *"Investigating GANsformer"*
(arXiv 2303.08577, PAPERS.md): a fixed-budget comparison of the StyleGAN2
baseline against GANsformer-Simplex and GANsformer-Duplex.  This CLI runs
that experiment matrix — one training arm per architecture under an
otherwise identical config — and writes a comparison report, so a user of
the reference can reproduce the study's structure on TPU with one command.

Example
-------
  python -m gansformer_tpu.cli.experiment --preset clevr64-simplex \\
      --archs none,simplex,duplex --total-kimg 100 --out results/repro

Each arm lands in ``<out>/<arch>/`` as an ordinary run dir (stats.jsonl,
checkpoints, fakes grids), so every per-run tool (generate, evaluate,
--resume) works on the arms individually.  The cross-arm summary lands in
``<out>/experiment.json`` + ``<out>/report.md``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import List, Optional


ARCH_CHOICES = ("none", "simplex", "duplex")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="GANsformer replication matrix")
    p.add_argument("--preset", default="clevr64-simplex",
                   help="base config preset; arms override `attention` only")
    p.add_argument("--archs", default="none,simplex,duplex",
                   help="comma list from {none,simplex,duplex} "
                        "(none = StyleGAN2 baseline)")
    p.add_argument("--out", required=True, help="experiment root dir")
    p.add_argument("--total-kimg", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--resolution", type=int, default=None)
    p.add_argument("--components", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--data-path", default=None)
    p.add_argument("--data-source",
                   choices=["synthetic", "npz", "tfrecord", "folder"])
    p.add_argument("--metrics", default=None,
                   help="optional metric names to run per arm after "
                        "training (e.g. fid10k_uncal)")
    p.add_argument("--config", default=None,
                   help="JSON base config instead of --preset")
    return p


def _arm_config(base, arch: str):
    """One matrix arm: the base config with only the architecture swapped
    (and a per-arch style_mode — attention-driven styling is meaningless
    for the baseline)."""
    model = dataclasses.replace(
        base.model, attention=arch,
        style_mode=("global" if arch == "none" else base.model.style_mode))
    return dataclasses.replace(base, name=f"{base.name}-{arch}", model=model)


def _last_stats(run_dir: str) -> dict:
    last = {}
    path = os.path.join(run_dir, "stats.jsonl")
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    last = json.loads(line)
    return last


def run_experiment(base, archs: List[str], out: str,
                   metrics: Optional[str] = None) -> dict:
    import jax

    from gansformer_tpu.train.loop import train
    from gansformer_tpu.train.state import param_count
    from gansformer_tpu.utils.logging import RunLogger

    # Run-dir writes are process-0-only (multi-host convention of
    # cli/train.py / train/loop.py); train() itself records each arm's
    # RESOLVED config.json — writing an unresolved copy here would race it
    # and could leave a wrong param-tree recipe if training crashed early.
    is_main = jax.process_index() == 0
    if is_main:
        os.makedirs(out, exist_ok=True)
    results = {}
    for arch in archs:
        cfg = _arm_config(base, arch)
        run_dir = os.path.join(out, arch)
        if is_main:
            os.makedirs(run_dir, exist_ok=True)
        logger = RunLogger(run_dir, active=is_main)
        logger.write(f"=== arm {arch}: {cfg.name} ===")
        state = train(cfg, run_dir, logger=logger)
        stats = _last_stats(run_dir)
        arm = {
            "run_dir": run_dir,
            "g_params": param_count(state.g_params),
            "d_params": param_count(state.d_params),
            "kimg": stats.get("Progress/kimg"),
            "loss_g": stats.get("Loss/G"),
            "loss_d": stats.get("Loss/D"),
            "img_per_sec": stats.get("timing/img_per_sec"),
        }
        if metrics:
            from gansformer_tpu.metrics.sweep import run_metric_sweep

            try:
                arm["metrics"] = run_metric_sweep(cfg, state, run_dir,
                                                  metrics)
            except Exception as e:  # metric deps (weights) may be absent
                arm["metrics_error"] = f"{type(e).__name__}: {e}"
        results[arch] = arm
        logger.close()

    summary = {"base_preset": base.name, "archs": archs, "arms": results}
    if is_main:
        with open(os.path.join(out, "experiment.json"), "w") as f:
            json.dump(summary, f, indent=2)
        _write_report(out, summary)
    return summary


def _write_report(out: str, summary: dict) -> None:
    lines = [
        "# Replication-matrix report",
        "",
        f"Base preset: `{summary['base_preset']}` — one arm per architecture "
        "(the arXiv 2303.08577 study design: StyleGAN2 baseline vs "
        "GANsformer simplex vs duplex under an identical budget).",
        "",
        "| arch | G params | D params | kimg | Loss/G | Loss/D | img/s |",
        "|---|---|---|---|---|---|---|",
    ]
    for arch in summary["archs"]:
        a = summary["arms"][arch]
        fmt = lambda v, spec=".3f": (format(v, spec)
                                     if isinstance(v, (int, float)) else "—")
        lines.append(
            f"| {arch} | {a['g_params']:,} | {a['d_params']:,} "
            f"| {fmt(a.get('kimg'), '.1f')} | {fmt(a.get('loss_g'))} "
            f"| {fmt(a.get('loss_d'))} "
            f"| {fmt(a.get('img_per_sec'), '.1f')} |")
        if a.get("metrics"):
            for name, value in a["metrics"].items():
                lines.append(f"|   ↳ {name} | {value:.4f} | | | | | |")
    lines.append("")
    with open(os.path.join(out, "report.md"), "w") as f:
        f.write("\n".join(lines))


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    archs = [a.strip() for a in args.archs.split(",") if a.strip()]
    for a in archs:
        if a not in ARCH_CHOICES:
            raise SystemExit(f"unknown arch {a!r}; choose from {ARCH_CHOICES}")

    from gansformer_tpu.core.config import ExperimentConfig, get_preset
    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache()   # every sweep arm reuses the same compiles

    if args.config:
        with open(args.config) as f:
            base = ExperimentConfig.from_json(f.read())
    else:
        base = get_preset(args.preset)

    def override(obj, **kv):
        kv = {k: v for k, v in kv.items() if v is not None}
        return dataclasses.replace(obj, **kv) if kv else obj

    base = dataclasses.replace(
        base,
        model=override(base.model, resolution=args.resolution,
                       components=args.components),
        train=override(base.train, total_kimg=args.total_kimg,
                       batch_size=args.batch_size, seed=args.seed),
        data=override(base.data, path=args.data_path,
                      source=args.data_source,
                      resolution=args.resolution),
    )
    run_experiment(base, archs, args.out, metrics=args.metrics)


if __name__ == "__main__":
    main()
